"""Serving benchmark — continuous batching vs the static-batch path.

Replays a FIXED-SEED synthetic ragged workload (ragged prompt lengths,
ragged arrival steps, heavily skewed output lengths — the
short-requests-behind-a-straggler shape that motivates iteration-level
scheduling) through two paths:

* **engine** — singa_tpu.serve.InferenceEngine: requests arrive over
  the first steps of the run, slots retire and backfill per step;
* **static_batch** — the offline path: requests grouped in arrival
  order into max_slots-sized batches, each batch run through
  ``gpt2_decode.generate`` to the LONGEST row's budget (rows that
  wanted fewer tokens discard the excess — exactly what a caller
  without an engine does today), next batch only after the whole
  batch drains.

Both paths warm up on the full workload once (compiles), then run
timed.  Throughput counts USEFUL tokens only (each request's own
max_new_tokens) so the static path is not credited for straggler
padding it generates and throws away.  Token parity of the engine
against single-prompt ``generate`` is asserted for every request —
the bench is invalid if the engine is fast but wrong.

Writes BENCH_SERVE.json (schema: workload/config/engine/static_batch/
speedup/parity) so future PRs have a serving perf trajectory, and
prints the same JSON to stdout.  ``--paged`` replays the workload
through the block-paged KV engine vs the slot arena at the SAME
persistent KV byte budget (the ``paged`` section: concurrent requests
at fixed memory, tokens/s, byte parity with priority preemption
exercised mid-run, recompile pin).  ``--spec`` trains a bench-scale
target/draft pair and measures speculative serve (spec_k=4) against
the plain engine on the same target — tokens/s, acceptance,
accepted-tokens/chunk, byte parity, recompile pin (the ``spec``
section).  ``--spec-sweep`` additionally sweeps spec_k ∈ {2, 4, 8} on
the same trained pair and commits tokens/s vs MEASURED acceptance per
k (the ``spec_sweep`` section, ``chip_pending: true`` — the
acceptance-sweep characterization the ``generate_speculative``
crossover cost model cross-links).  ``--fork`` measures best-of-n
sampling as ONE copy-on-write fork family vs n independent requests
over a shared system prompt (the ``fork`` section: peak-block savings
from prompt sharing, tokens/s from the vanished prefills, greedy n=1
byte parity, 100% json.loads-valid structured outputs across
seeds/temperatures, leak + recompile pins).  ``--cache-int8`` replays the
standard workload through an int8-KV-arena engine with byte parity
against the offline int8 oracle (the ``cache_int8`` section;
CPU-measured, chip-pending — see PERF.md).  ``--fleet`` additionally replays the
workload through a 2-replica ServeFleet (same total slot count) and
embeds a ``fleet`` section — routing balance, per-stream parity
against the engine run, and the jit-cache pin proving replicas share
every executable.  ``--tp K`` replays the workload through a K-shard
TENSOR-PARALLEL paged engine (serve/tp.py: Megatron-sharded weights
under shard_map, per-shard H_kv slices of the block pool) and embeds
a ``tp`` section — per-stream parity against the single-device run,
per-shard pool occupancy, psums per step, recompile pin (throughput
is chip-pending: a 2-thread virtual CPU mesh pays the collectives
without the memory win).  The ``registry`` key embeds the
process-wide ``singa_tpu.observe`` metrics snapshot; ``--trace-out
PATH`` additionally traces the timed engine run and writes a Chrome
trace-event JSON there (open in https://ui.perfetto.dev — expect
serve/prefill, serve/decode_step and serve/retire rows).  Tracing is
off unless the flag is given, so the default throughput numbers are
untouched.  ``--request-log PATH`` enables the per-request lifecycle
ledger (``observe.requests``) for every timed run, writes one
strict-JSON line per request there, embeds a ``request_log``
self-check section (complete monotonic timelines, exact TTFT phase
attribution, recompile pin with the ledger ON) and turns on the
health report's ``why_slow`` tail-latency attribution; with
``--trace-out`` the Chrome trace additionally carries per-request
tracks with hop flow arrows.  ``--prom-out PATH`` writes the
Prometheus text exposition (bucketed histogram families) at exit.
``--step-anatomy`` replays the workload with the step profiler ON
(``observe.stepprof``) and embeds the ``step_anatomy`` section: the
per-step host/device decomposition (segment fractions summing to 1,
exact arithmetic), the baseline device-bubble fraction ROADMAP item
5's overlap work must close, parity against the unprofiled run, and
the recompile pin proving the fences never enter jitted code.
"""

import argparse
import json
import os
import time

import numpy as np

# palette of output budgets: mostly short, a long tail — E[max of a
# batch] >> E[mean], which is the static path's straggler tax.  A
# small palette also bounds how many scan lengths the offline path
# compiles.
_NEW_PALETTE = [2, 4, 6, 8, 48, 64]
_NEW_WEIGHTS = [0.22, 0.22, 0.22, 0.14, 0.10, 0.10]


def make_workload(n_requests=40, seed=0, n_positions=128):
    rng = np.random.RandomState(seed)
    reqs = []
    arrival = 0
    for i in range(n_requests):
        plen = int(rng.randint(4, 25))
        prompt = rng.randint(0, 512, plen).astype(np.int32)
        n_new = int(rng.choice(_NEW_PALETTE, p=_NEW_WEIGHTS))
        arrival += int(rng.randint(0, 2))  # ragged arrivals, ~2/step
        reqs.append(dict(prompt=prompt, n_new=n_new,
                         arrival_step=arrival))
    return reqs


def run_engine(m, workload, max_slots, close_after=False, slo=None,
               **engine_kw):
    from singa_tpu.serve import GenerationRequest

    eng = m.serve(max_slots=max_slots, slo=slo, **engine_kw)
    handles = []
    pending = list(workload)
    t0 = time.perf_counter()
    while pending or eng.pending:
        while pending and pending[0]["arrival_step"] <= eng.step_count:
            w = pending.pop(0)
            handles.append(eng.submit(GenerationRequest(
                w["prompt"], max_new_tokens=w["n_new"])))
        eng.step()
    wall = time.perf_counter() - t0
    outs = [h.result() for h in handles]
    snap = eng.stats.snapshot()
    if close_after:
        # warmup engines unregister their compile-polluted serve.*
        # metrics so the registry snapshot in the report reflects the
        # TIMED engine only
        eng.close()
    return wall, outs, snap


def make_prefix_workload(n_requests=16, seed=1, vocab=512,
                         system_tokens=160):
    """Shared-system-prompt + multi-turn traffic: every request opens
    with the same ``system_tokens``-token system prompt and a ragged
    user tail, and each completed turn is continued once through its
    pinned session (the whole turn-1 conversation re-sent as turn 2's
    prompt) — the workload shape prefix caching exists for.  Arrivals
    are spread (1-2 steps apart) so TTFT reflects admission cost, not
    queue wait."""
    rng = np.random.RandomState(seed)
    system = rng.randint(0, vocab, system_tokens).astype(np.int32)
    reqs = []
    arrival = 0
    for _ in range(n_requests):
        tail = rng.randint(0, vocab,
                           int(rng.randint(8, 25))).astype(np.int32)
        arrival += int(rng.randint(1, 3))
        reqs.append(dict(
            prompt=np.concatenate([system, tail]),
            n_new=int(rng.choice([8, 16])),
            arrival_step=arrival,
            extra=rng.randint(0, vocab,
                              int(rng.randint(4, 9))).astype(np.int32),
            extra_new=int(rng.choice([8, 16]))))
    return reqs


def run_prefix_engine(m, workload, max_slots, prefix_cfg=None,
                      close_after=False):
    """Drive the two-turn session workload through one engine (warm
    when ``prefix_cfg`` is set, cold baseline otherwise).  Returns
    (wall, turn1 results, turn2 (request, result) pairs, stats snap)."""
    from singa_tpu.serve import GenerationRequest

    eng = m.serve(max_slots=max_slots, prefix_cache=prefix_cfg)
    n = len(workload)
    pending = list(workload)
    turn1, turn2 = [], []
    continued = set()
    t0 = time.perf_counter()
    while pending or len(continued) < n or eng.pending:
        while pending and pending[0]["arrival_step"] <= eng.step_count:
            w = pending.pop(0)
            turn1.append((w, eng.submit(GenerationRequest(
                w["prompt"], max_new_tokens=w["n_new"],
                pin_session=True))))
        for i, (w, h) in enumerate(turn1):
            if i in continued or not h.done():
                continue
            req2 = h.result().session.request(
                w["extra"], max_new_tokens=w["extra_new"])
            turn2.append((req2, eng.submit(req2)))
            continued.add(i)
        eng.step()
    wall = time.perf_counter() - t0
    outs1 = [h.result() for _, h in turn1]
    outs2 = [(req, h.result()) for req, h in turn2]
    for r in outs1:
        if r.session is not None:
            r.session.release()
    snap = eng.stats.snapshot()
    if close_after:
        eng.close()
    return wall, outs1, outs2, snap


def _serve_jit_cache_size():
    """Total jit-cache entries across every executable the serve stack
    dispatches — pinned across the timed runs to prove the warm path
    introduces ZERO runtime recompiles.  The census itself lives in
    :mod:`singa_tpu.serve.jitpin` since the federation round (DistFleet
    workers report it over the telemetry op); this is the same count."""
    from singa_tpu.serve.jitpin import jit_cache_size

    return jit_cache_size()


def run_prefix_mix(max_slots):
    """The --prefix-mix measurement: the session workload warm
    (radix cache on) vs cold (cache off), with byte parity against
    the offline oracle for EVERY stream and the jit cache size pinned
    across the timed runs.  Uses its own 256-position model: a
    160-token shared system prompt against a 256-wide prefill is the
    regime the cache targets (the standard bench model's 128 window
    cannot hold two turns of real history)."""
    from singa_tpu import tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.serve import PrefixCacheConfig

    cfg_m = GPT2Config(vocab_size=512, n_positions=256, n_embd=192,
                       n_layer=4, n_head=4, n_inner=384, dropout=0.0,
                       attn_impl="fused")
    m = GPT2LMHead(cfg_m)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)

    cfg = PrefixCacheConfig(block_size=16, num_blocks=128)
    workload = make_prefix_workload()

    # warmup both paths (compiles; fresh engines per run)
    run_prefix_engine(m, workload, max_slots, cfg, close_after=True)
    run_prefix_engine(m, workload, max_slots, None, close_after=True)

    jit_before = _serve_jit_cache_size()
    wall_w, w1, w2, snap_w = run_prefix_engine(m, workload, max_slots,
                                               cfg)
    wall_c, c1, c2, snap_c = run_prefix_engine(m, workload, max_slots,
                                               None, close_after=True)
    jit_after = _serve_jit_cache_size()

    parity = True
    for (w, res) in zip(workload, w1):
        want = m.generate(w["prompt"], max_new_tokens=w["n_new"],
                          temperature=0)
        parity &= bool(np.array_equal(res.tokens, want))
    for req, res in w2:
        want = m.generate(req.prompt_ids,
                          max_new_tokens=req.max_new_tokens,
                          temperature=0)
        parity &= bool(np.array_equal(res.tokens, want))
    # warm and cold engines must agree stream-for-stream too
    parity &= all(np.array_equal(a.tokens, b.tokens)
                  for a, b in zip(w1, c1))
    parity &= all(np.array_equal(a[1].tokens, b[1].tokens)
                  for a, b in zip(w2, c2))

    useful = sum(w["n_new"] + w["extra_new"] for w in workload)
    pre = snap_w["prefix"]
    return {
        "workload": {
            "requests": len(workload), "turns": 2,
            "system_prompt_tokens": 160, "useful_tokens": useful,
            "n_positions": 256, "seed": 1,
        },
        "cache": {"block_size": cfg.block_size,
                  "num_blocks": cfg.num_blocks},
        "warm": {
            "wall_s": wall_w,
            "tokens_per_s": useful / wall_w,
            "ttft_p50_s": snap_w["latency"]["ttft"]["p50"],
            "ttft_p99_s": snap_w["latency"]["ttft"]["p99"],
        },
        "cold": {
            "wall_s": wall_c,
            "tokens_per_s": useful / wall_c,
            "ttft_p50_s": snap_c["latency"]["ttft"]["p50"],
            "ttft_p99_s": snap_c["latency"]["ttft"]["p99"],
        },
        "ttft_p50_improvement": (snap_c["latency"]["ttft"]["p50"]
                                 / snap_w["latency"]["ttft"]["p50"]),
        "speedup_tokens_per_s": wall_c / wall_w,
        "prefix_hit_rate": pre["hit_rate_tokens"],
        "hit_tokens": pre["hit_tokens"],
        "lookup_tokens": pre["lookup_tokens"],
        "cached_blocks": pre["cached_blocks"],
        "evictions": pre["evictions"],
        "recompiles": (None if jit_before is None
                       else jit_after - jit_before),
        "parity": parity,
    }


def run_paged(m, workload, engine_outs):
    """The --paged measurement: the standard ragged workload through
    the SLOT-ARENA engine and through the PAGED engine at the SAME
    persistent KV byte budget — ``max_slots * max_len`` slot positions
    vs ``num_blocks * block_size`` pool positions (512 each here; the
    pool carries one extra trash block).  The slot arena admits at
    most ``max_slots`` concurrent requests whatever their lengths; the
    paged engine admits by BLOCKS FREE, so the mostly-short workload
    packs several times more live requests into the same bytes
    (``concurrency_gain`` = peak live slots, paged / slot).

    The paged run uses the PriorityScheduler with the long-budget
    requests at LOW priority, so the pool deliberately over-commits
    and priority preemption fires DURING the timed run (the gated
    ``preemptions > 0``): token streams must stay byte-identical to
    the slot engine's (same seed, same chain — swap/resume is a byte
    copy) and the jit+AOT cache must stay pinned across both timed
    runs."""
    from singa_tpu.serve import GenerationRequest, PagedConfig

    slot_slots = 4
    pcfg = PagedConfig(block_size=16, num_blocks=32)  # == 4x128 positions
    # 20 decode lanes over a 32-block pool: slots are host bookkeeping
    # + vmap width, the PERSISTENT KV bytes are the pool — and 20
    # mostly-short requests deliberately OVER-commit 32 blocks, so the
    # growth/priority preemption path runs during the timed window
    paged_slots = 20
    paged_kw = dict(paged=pcfg, scheduler="priority")

    def drive(max_slots, **kw):
        eng = m.serve(max_slots=max_slots, **kw)
        handles = []
        pending = list(workload)
        peak = 0
        t0 = time.perf_counter()
        while pending or eng.pending:
            while pending and pending[0]["arrival_step"] <= eng.step_count:
                w = pending.pop(0)
                handles.append(eng.submit(GenerationRequest(
                    w["prompt"], max_new_tokens=w["n_new"],
                    priority=0 if w["n_new"] >= 48 else 1)))
            eng.step()
            peak = max(peak, eng.live_slots)
        wall = time.perf_counter() - t0
        outs = [h.result() for h in handles]
        snap = eng.stats.snapshot()
        eng.close()
        return wall, outs, snap, peak

    # warmup both geometries (compiles; the paged steps also populate
    # their AOT cost-table cache here)
    drive(slot_slots)
    drive(paged_slots, **paged_kw)

    jit_before = _serve_jit_cache_size()
    wall_s, outs_s, snap_s, peak_s = drive(slot_slots)
    wall_p, outs_p, snap_p, peak_p = drive(paged_slots, **paged_kw)
    jit_after = _serve_jit_cache_size()

    # engine_outs are oracle-verified by the main bench; per-stream
    # equality here is transitively oracle parity — preemption/swap
    # included, because resume restores bytes
    parity = all(np.array_equal(a.tokens, b.tokens)
                 for a, b in zip(outs_s, engine_outs))
    parity &= all(np.array_equal(a.tokens, b.tokens)
                  for a, b in zip(outs_p, engine_outs))

    useful = sum(w["n_new"] for w in workload)
    pg = snap_p["paged"]
    return {
        "kv_budget": {
            "slot_positions": slot_slots * m.cfg.n_positions,
            "paged_positions": pcfg.num_blocks * pcfg.block_size,
            "block_size": pcfg.block_size,
            "num_blocks": pcfg.num_blocks,
            "slot_max_slots": slot_slots,
            "paged_max_slots": paged_slots,
        },
        "slot_arena": {
            "wall_s": wall_s,
            "tokens_per_s": useful / wall_s,
            "peak_concurrent": peak_s,
            **_lat(snap_s),
        },
        "paged": {
            "wall_s": wall_p,
            "tokens_per_s": useful / wall_p,
            "peak_concurrent": peak_p,
            **_lat(snap_p),
        },
        "concurrency_gain": peak_p / peak_s,
        "speedup_tokens_per_s": wall_s / wall_p,
        # the block-native decode kernel (PagedConfig default since
        # the gather-tax round) — CI gates that the hot path is the
        # kernel and that its decode TPOT stays within 2x of the slot
        # arena's (the gather path priced this at ~6x)
        "kernel": pcfg.kernel,
        "tpot_p50_ratio": (snap_p["latency"]["tpot"]["p50"]
                           / snap_s["latency"]["tpot"]["p50"]),
        "preemptions": pg["preemptions"],
        "swap_in": pg["swap_in"],
        "swap_out": pg["swap_out"],
        "blocks_leaked": pg["blocks_used"],
        "recompiles": (None if jit_before is None
                       else jit_after - jit_before),
        "parity": parity,
    }


def run_fork(m):
    """The --fork measurement: best-of-n sampling as ONE CoW fork
    family vs n INDEPENDENT requests over the same prompt.

    A shared 48-token system prompt + short per-request tails (the
    best-of-n shape: one question, n candidate answers).  The family
    prefills the prompt ONCE and shares every prompt block across its
    branches copy-on-first-write, so the measured win is peak pool
    blocks — the shared prefix is resident once instead of n times —
    at no throughput regression (the n-1 vanished prefills are a
    chip-pending tokens/s win: CPU prefill on this model is too
    cheap to dominate the logprob scoring the ranked branches pay).
    Token budget and slot count are identical across both arms.

    Gated rows: greedy n=1 parity against the offline oracle (the
    fork machinery is byte-invisible until n>1), the leak invariant
    via ``check_block_accounting`` after every drain, 100%
    json.loads-valid structured outputs across seeds and
    temperatures, and the jit pin across every timed run — the mask
    and logprob inputs ride fixed-shape executables, so forking and
    constraining introduce ZERO runtime recompiles."""
    from singa_tpu.observe.registry import registry
    from singa_tpu.serve import (ForkHandle, GenerationRequest,
                                 JsonSchemaAutomaton, PagedConfig)

    pcfg = PagedConfig(block_size=16, num_blocks=96)
    max_slots = 8
    n_new = 24
    rng = np.random.RandomState(11)
    system = rng.randint(0, 512, 48).astype(np.int32)
    prompts = [np.concatenate(
        [system, rng.randint(0, 512, 8).astype(np.int32)])
        for _ in range(4)]

    def drive(reqs):
        eng = m.serve(max_slots=max_slots, paged=pcfg)
        handles = [eng.submit(r) for r in reqs]
        peak = cow = 0
        lbl = eng.stats.engine_label
        t0 = time.perf_counter()
        while eng.pending:
            eng.step()
            peak = max(peak, eng.paged_arena.blocks_used)
        wall = time.perf_counter() - t0
        outs = []
        for h in handles:
            outs.extend(h.results() if isinstance(h, ForkHandle)
                        else [h.result()])
        # the leak invariant: after the drain every used block is
        # cache-owned (no prefix cache here -> exactly zero)
        leaked = eng.check_block_accounting()
        cow = registry().snapshot()["counters"].get(
            f"serve.fork.cow_copies{{engine={lbl}}}", 0)
        eng.close()
        return wall, outs, peak, leaked, cow

    def group_reqs(n):
        return [GenerationRequest(p, max_new_tokens=n_new,
                                  temperature=0.8, seed=i, n=n)
                for i, p in enumerate(prompts)]

    def indep_reqs(n):
        return [GenerationRequest(p, max_new_tokens=n_new,
                                  temperature=0.8, seed=10 * i + j)
                for i, p in enumerate(prompts) for j in range(n)]

    schema = {"type": "object", "properties": {
        "answer": {"enum": ["yes", "no", "unknown"]},
        "confidence": {"type": "integer"},
        "refusal": {"type": "boolean"},
    }}
    vocab = [chr(c) for c in range(m.cfg.vocab_size)]
    automaton = JsonSchemaAutomaton(schema, vocab, max_digits=3)

    def structured_reqs():
        return [GenerationRequest(prompts[0], max_new_tokens=64,
                                  temperature=t, seed=s,
                                  structured=automaton)
                for s, t in enumerate((0.0, 0.9, 1.3, 0.7))] \
            + [GenerationRequest(prompts[1], max_new_tokens=64,
                                 temperature=1.0, seed=9, n=2,
                                 structured=automaton)]

    # warmup EVERY timed workload once: the dispatch signature keys
    # on (lane count, mask present, logprob present), and each arm's
    # ramp-up/ramp-down walks its own lane-count sequence — replaying
    # the exact request sets is the only warm set that provably
    # covers them all.  Then pin the jit cache across the measured
    # arms.
    for reqs in (group_reqs(2), group_reqs(4), indep_reqs(2),
                 indep_reqs(4),
                 [GenerationRequest(p, max_new_tokens=n_new,
                                    temperature=0.0)
                  for p in prompts],
                 structured_reqs()):
        drive(reqs)

    jit_before = _serve_jit_cache_size()
    rows = []
    for n in (2, 4):
        wall_g, outs_g, peak_g, leak_g, cow_g = drive(group_reqs(n))
        wall_i, outs_i, peak_i, leak_i, _ = drive(indep_reqs(n))
        useful = n * len(prompts) * n_new
        assert len(outs_g) == len(outs_i) == n * len(prompts)
        rows.append({
            "n": n,
            "group_tokens_per_s": useful / wall_g,
            "independent_tokens_per_s": useful / wall_i,
            "speedup_tokens_per_s": wall_i / wall_g,
            "group_peak_blocks": peak_g,
            "independent_peak_blocks": peak_i,
            "block_savings": 1.0 - peak_g / peak_i,
            "cow_copies": cow_g,
            "blocks_leaked": leak_g + leak_i,
        })

    # greedy n=1 through the same engine == the offline oracle: the
    # fork machinery is byte-invisible until a request asks for it
    _, outs_1, _, leak_1, _ = drive(
        [GenerationRequest(p, max_new_tokens=n_new, temperature=0.0)
         for p in prompts])
    parity_n1 = all(
        np.array_equal(r.tokens,
                       m.generate(p, max_new_tokens=n_new,
                                  temperature=0))
        for p, r in zip(prompts, outs_1))

    _, outs_c, _, leak_c, _ = drive(structured_reqs())
    valid = 0
    plen = len(prompts[0])  # both structured prompts are 56 tokens
    for r in outs_c:
        try:
            obj = json.loads(
                "".join(vocab[t] for t in r.tokens[plen:]))
            if set(obj) == set(schema["properties"]):
                valid += 1
        except ValueError:
            pass
    jit_after = _serve_jit_cache_size()

    return {
        "config": {"block_size": pcfg.block_size,
                   "num_blocks": pcfg.num_blocks,
                   "max_slots": max_slots,
                   "system_tokens": len(system),
                   "max_new_tokens": n_new},
        "best_of_n": rows,
        # the measured win on CPU is MEMORY: the shared prompt is
        # resident once, so peak blocks drop 30-45% and the freed
        # capacity admits more concurrent families.  The tokens/s win
        # (n-1 prefills vanish) is chip-pending — this model's CPU
        # prefill is too cheap to dominate the logprob-scoring cost
        # the ranked branches pay
        "throughput_chip_pending": True,
        "parity_n1": bool(parity_n1),
        "structured": {"requests": len(outs_c),
                       "schema_valid": valid,
                       "all_valid": valid == len(outs_c)},
        "blocks_leaked": leak_1 + leak_c
        + sum(r["blocks_leaked"] for r in rows),
        "recompiles": (None if jit_before is None
                       else jit_after - jit_before),
    }


def _request_log_section(led, path, recompiles=None):
    """The --request-log deliverable: write the ledger's sealed ring
    as strict JSONL at ``path`` and self-check the acceptance
    invariants — every completed request's timeline is COMPLETE
    (submit -> admission -> first token -> retire) and MONOTONIC, and
    the phase attribution (hops + ship + queue + prefill) reproduces
    each request's measured TTFT — so the CI gate reads verdicts
    instead of re-deriving them from raw timelines."""
    from singa_tpu.observe import requests as reqtrace

    n = reqtrace.write_request_log(path, ledger_=led)
    entries = led.entries()
    completed = [e for e in entries
                 if e["outcome"] in ("length", "stop")]
    complete = monotonic = True
    max_rel_err = 0.0
    for e in completed:
        # the serving hop is the entry's seal-time verdict (on a
        # hedged request the last hop BY POSITION may be the losing
        # twin) — completeness is judged on it
        final = e["hops"][e["final_hop"]]
        complete &= (e["t_retire"] is not None
                     and e["ttft_s"] is not None
                     and final["t_admit"] is not None
                     and final["t_first_token"] is not None
                     and e["tokens_out"] > 0)
        # hops run CONCURRENTLY under hedging, so monotonicity is a
        # per-hop property (submit <= admit <= first token <= steps)
        # anchored at the request's original submit; retire closes
        # the serving hop
        for h in e["hops"]:
            t = e["t_submit"]
            for tn in (h["t_submit"], h["t_admit"],
                       h["t_first_token"]):
                if tn is not None:
                    monotonic &= tn >= t
                    t = tn
            for s in h["steps"]:
                monotonic &= s[0] >= t
                t = s[0]
            if h is final:
                monotonic &= e["t_retire"] >= t
        ph = e["phases"]
        if e["ttft_s"] > 0:
            err = abs(ph["hops"] + ph.get("ship", 0.0) + ph["queue"]
                      + ph["prefill"] - e["ttft_s"]) / e["ttft_s"]
            max_rel_err = max(max_rel_err, err)
    return {
        "path": path,
        "lines": n,
        "requests": len(entries),
        "completed": len(completed),
        "rejected": sum(1 for e in entries
                        if e["outcome"] == "rejected"),
        "open_after_run": led.open_count,
        "dropped": led.dropped,
        "multi_hop_requests": sum(1 for e in entries
                                  if len(e["hops"]) > 1),
        "timelines_complete": bool(complete),
        "timestamps_monotonic": bool(monotonic),
        # attribution is arithmetic over recorded timestamps, so this
        # is ~0 by construction; the gate allows 5%
        "ttft_attribution_max_rel_err": max_rel_err,
        "recompiles": recompiles,
    }


def _lat(snap):
    """TTFT/TPOT percentile block out of an EngineStats snapshot."""
    return {
        "ttft_p50_s": snap["latency"]["ttft"]["p50"],
        "ttft_p99_s": snap["latency"]["ttft"]["p99"],
        "tpot_p50_s": snap["latency"]["tpot"]["p50"],
        "tpot_p99_s": snap["latency"]["tpot"]["p99"],
    }


def _train_spec_pair(seed=0, steps=60):
    """A trained bench-scale target (4 layers) + draft (1 layer) on
    highly-learnable motif data — the examples/gpt2/speculative.py
    recipe at the serve bench's model dims.  Acceptance is a property
    of the PAIR, so the spec measurement needs models that actually
    agree; untrained weights would measure the mechanism at its floor.
    """
    from singa_tpu import device, opt, tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead

    rng = np.random.RandomState(seed)
    common = dict(vocab_size=512, n_positions=128, n_embd=192,
                  n_head=4, n_inner=384, dropout=0.0, attn_impl="fused")
    cfg_t = GPT2Config(n_layer=4, **common)
    cfg_d = GPT2Config(n_layer=1, **common)
    motif = rng.randint(0, cfg_t.vocab_size, 8)
    ids = np.tile(motif, (4, 4)).astype(np.int32)[:, :32]
    noise = rng.randint(0, cfg_t.vocab_size, ids.shape)
    mask = rng.rand(*ids.shape) < 0.05
    ids[mask] = noise[mask]
    labels = np.roll(ids, -1, axis=1).astype(np.int32)
    models = []
    for i, cfg in enumerate((cfg_t, cfg_d)):
        device.get_default_device().SetRandSeed(seed + i)
        m = GPT2LMHead(cfg)
        m.set_optimizer(opt.AdamW(lr=1e-3, weight_decay=0.01))
        m.compile([tensor.from_numpy(ids)], is_train=True,
                  use_graph=True)
        for _ in range(steps):
            m(tensor.from_numpy(ids), tensor.from_numpy(labels))
        m.eval()
        models.append(m)
    return models[0], models[1], ids


def make_spec_workload(ids, n_requests=32, seed=4):
    """The ragged serve workload shape (make_workload), with prompts
    drawn as windows of the pair's training data so the draft has the
    agreement speculation monetizes — the serving analogue of shipping
    a draft distilled on production traffic.  The budget palette skews
    DECODE-heavy: speculation amortizes target cache reads across
    accepted tokens, which buys nothing on a 2-token
    admission-dominated request (the crossover documented in
    gpt2_decode.generate_speculative) — this workload is the shape the
    knob exists for, and the baseline runs the identical workload."""
    rng = np.random.RandomState(seed)
    R, C = ids.shape
    reqs = []
    arrival = 0
    for _ in range(n_requests):
        plen = int(rng.randint(4, 21))
        row = int(rng.randint(0, R))
        off = int(rng.randint(0, C - plen))
        prompt = np.asarray(ids[row, off:off + plen], np.int32)
        n_new = int(rng.choice([8, 16, 32, 48, 64],
                               p=[0.15, 0.2, 0.25, 0.2, 0.2]))
        arrival += int(rng.randint(0, 2))
        reqs.append(dict(prompt=prompt, n_new=n_new,
                         arrival_step=arrival))
    return reqs


def run_spec(max_slots, spec_k=4, pair=None, return_baseline=False):
    """The --spec measurement: the trained-pair workload through the
    PLAIN engine (the PR-6 serve path on the same target — the
    baseline speculation must strictly beat) and through the
    SPECULATIVE engine at ``spec_k``, with byte parity for every
    stream (spec == plain == single-prompt oracle) and the jit cache
    pinned across both timed runs.  ``pair``: a pre-trained
    (target, draft, ids) triple — main() trains ONCE and shares it
    with --spec-sweep (60 training steps are the expensive part);
    ``return_baseline`` additionally hands back (wall_p, outs_p) so
    the sweep reuses this plain-engine measurement instead of
    replaying it."""
    target, draft, ids = pair if pair is not None else \
        _train_spec_pair()
    workload = make_spec_workload(ids)
    useful = sum(w["n_new"] for w in workload)

    # warmup both engines (compiles)
    run_engine(target, workload, max_slots, close_after=True)
    run_engine(target, workload, max_slots, close_after=True,
               draft_model=draft, spec_k=spec_k)

    jit_before = _serve_jit_cache_size()
    wall_p, outs_p, snap_p = run_engine(target, workload, max_slots,
                                        close_after=True)
    wall_s, outs_s, snap_s = run_engine(target, workload, max_slots,
                                        close_after=True,
                                        draft_model=draft,
                                        spec_k=spec_k)
    jit_after = _serve_jit_cache_size()

    parity = True
    for w, a, b in zip(workload, outs_p, outs_s):
        want = target.generate(w["prompt"], max_new_tokens=w["n_new"],
                               temperature=0)
        parity &= bool(np.array_equal(a.tokens, want))
        parity &= bool(np.array_equal(b.tokens, a.tokens))

    spec = snap_s["spec"]
    section = {
        "workload": {"requests": len(workload),
                     "useful_tokens": useful, "seed": 4},
        "pair": {"target_layers": 4, "draft_layers": 1,
                 "train_steps": 60},
        "spec_k": spec_k,
        "baseline": {"wall_s": wall_p,
                     "tokens_per_s": useful / wall_p, **_lat(snap_p)},
        "spec": {"wall_s": wall_s, "tokens_per_s": useful / wall_s,
                 **_lat(snap_s)},
        "speedup_tokens_per_s": wall_p / wall_s,
        "acceptance_rate": spec["acceptance_rate"],
        "accepted_tokens_per_chunk": spec["tokens_per_chunk"],
        "recompiles": (None if jit_before is None
                       else jit_after - jit_before),
        "parity": parity,
    }
    if return_baseline:
        return section, (wall_p, outs_p)
    return section


def run_spec_sweep(max_slots, ks=(2, 4, 8), pair=None,
                   baseline=None):
    """The --spec-sweep measurement (VERDICT next-round #5):
    characterize ACCEPTANCE vs throughput across spec_k ∈ {2, 4, 8}
    on the same trained pair and the same decode-heavy workload, so
    the crossover cost model in ``generate_speculative``'s docstring
    has measured (tokens/s, acceptance, tokens/chunk) points per k
    instead of a single operating point.  Expected shape: emitted
    tokens/chunk saturate at ``1/(1 - acceptance)`` while draft cost
    grows linearly in k, so tokens/s peaks at a finite k — where it
    peaks is a property of the pair and the BACKEND's relative
    draft/verify pricing, hence ``chip_pending: true`` (CPU prices
    the k sequential draft steps differently from a chip).  Every
    row keeps byte parity against the plain engine on the same
    target.  ``pair``: share main()'s trained triple with --spec —
    the 60 training steps are the expensive part."""
    target, draft, ids = pair if pair is not None else \
        _train_spec_pair()
    workload = make_spec_workload(ids)
    useful = sum(w["n_new"] for w in workload)

    if baseline is not None:
        # --spec already measured the identical plain-engine run on
        # this pair and workload; reuse it instead of replaying
        wall_p, outs_p = baseline
    else:
        run_engine(target, workload, max_slots,
                   close_after=True)  # warmup
        wall_p, outs_p, _ = run_engine(target, workload, max_slots,
                                       close_after=True)
    rows = []
    for k in ks:
        run_engine(target, workload, max_slots, close_after=True,
                   draft_model=draft, spec_k=k)  # warmup (compiles)
        wall, outs, snap = run_engine(target, workload, max_slots,
                                      close_after=True,
                                      draft_model=draft, spec_k=k)
        parity = all(np.array_equal(a.tokens, b.tokens)
                     for a, b in zip(outs, outs_p))
        spec = snap["spec"]
        rows.append({
            "spec_k": k,
            "wall_s": wall,
            "tokens_per_s": useful / wall,
            "speedup_tokens_per_s": wall_p / wall,
            "acceptance_rate": spec["acceptance_rate"],
            "accepted_tokens_per_chunk": spec["tokens_per_chunk"],
            "parity": bool(parity),
        })
    return {
        "workload": {"requests": len(workload),
                     "useful_tokens": useful, "seed": 4},
        "pair": {"target_layers": 4, "draft_layers": 1,
                 "train_steps": 60},
        "baseline_tokens_per_s": useful / wall_p,
        "sweep": rows,
        "crossover_model":
            "gpt2_decode.generate_speculative docstring",
        "chip_pending": True,  # CPU draft/verify pricing; PERF.md §10
    }


def run_int8(m, workload, max_slots, engine_section):
    """The --cache-int8 measurement: the standard workload through an
    int8-arena engine, byte parity against the offline int8 oracle for
    every stream, jit cache pinned.  ``vs_bf16_tokens_per_s`` compares
    against the report's dense ``engine`` section (same model, same
    workload) — int8 halves cache BYTES, so the win appears where
    cache reads bound the loop (chip HBM); on CPU the dequantize
    arithmetic usually prices it at/below 1.0, which is exactly why
    the PERF.md row is marked chip-pending."""
    from singa_tpu.models import gpt2_decode

    run_engine(m, workload, max_slots, close_after=True,
               cache_dtype="int8")  # warmup
    jit_before = _serve_jit_cache_size()
    wall, outs, snap = run_engine(m, workload, max_slots,
                                  close_after=True, cache_dtype="int8")
    jit_after = _serve_jit_cache_size()

    parity = True
    for w, res in zip(workload, outs):
        want = gpt2_decode.generate(m, w["prompt"],
                                    max_new_tokens=w["n_new"],
                                    temperature=0, cache_dtype="int8")
        parity &= bool(np.array_equal(res.tokens, want))

    useful = sum(w["n_new"] for w in workload)
    return {
        "wall_s": wall,
        "tokens_per_s": useful / wall,
        **_lat(snap),
        "vs_bf16_tokens_per_s": ((useful / wall)
                                 / engine_section["tokens_per_s"]),
        "recompiles": (None if jit_before is None
                       else jit_after - jit_before),
        "parity": parity,
        "chip_pending": True,  # CPU numbers; see PERF.md §9
    }


def run_fleet(m, workload, replicas, max_slots):
    """Drive the standard ragged workload through a ServeFleet (same
    TOTAL slot count as the single-engine run: replicas x max_slots).
    Returns (wall, results, fleet) — the caller closes the fleet
    (``close()`` unregisters its ``serve.fleet.*`` metrics, so any
    registry/health snapshot the caller wants must happen first)."""
    from singa_tpu.serve import GenerationRequest, ServeFleet

    fleet = ServeFleet(m, replicas=replicas, max_slots=max_slots)
    handles = []
    pending = list(workload)
    t0 = time.perf_counter()
    while pending or fleet.pending:
        while pending and pending[0]["arrival_step"] <= fleet.step_count:
            w = pending.pop(0)
            handles.append(fleet.submit(GenerationRequest(
                w["prompt"], max_new_tokens=w["n_new"])))
        fleet.step()
    wall = time.perf_counter() - t0
    outs = [h.result() for h in handles]
    return wall, outs, fleet


def run_fleet_bench(m, workload, engine_outs, replicas=2, max_slots=4,
                    engine_snap=None):
    """The --fleet measurement: the workload through a 2-replica fleet
    with per-stream parity against the (already oracle-verified)
    single-engine results, router balance across replicas, and the jit
    cache pinned across the timed run — replicas share every
    executable, so a fleet costs ZERO extra compiles.  Returns
    ``(fleet section, registry snapshot, health report)`` — the
    latter two taken BEFORE the fleet closes, because ``close()``
    unregisters the ``serve.fleet.*`` metrics and a post-close health
    report would show an all-zero fleet section."""
    from singa_tpu import observe
    from singa_tpu.utils.metrics import percentile

    _, _, warm = run_fleet(m, workload, replicas, max_slots)  # warmup
    warm.close()
    jit_before = _serve_jit_cache_size()
    wall, outs, fleet = run_fleet(m, workload, replicas, max_slots)
    jit_after = _serve_jit_cache_size()
    snap = fleet.snapshot()
    reg_snap = observe.registry().snapshot()
    health = observe.health_report(
        engine_snapshots=([engine_snap] if engine_snap is not None
                          else ()),
        include_registry=False)
    fleet.close()

    # engine_outs are parity-checked against single-prompt generate by
    # the main bench; stream equality here is transitively oracle parity
    parity = all(np.array_equal(a.tokens, b.tokens)
                 for a, b in zip(outs, engine_outs))
    useful = sum(w["n_new"] for w in workload)
    ttfts = [r.ttft for r in outs]
    return {
        "replicas": replicas,
        "max_slots_each": max_slots,
        "wall_s": wall,
        "tokens_per_s": useful / wall,
        "ttft_p50_s": percentile(ttfts, 50),
        "ttft_p99_s": percentile(ttfts, 99),
        "routed": snap["routed"],
        "replicas_healthy": snap["replicas_healthy"],
        "failovers": snap["failovers"],
        "requeues": snap["requeues"],
        "hedges": snap["hedges"],
        "recompiles": (None if jit_before is None
                       else jit_after - jit_before),
        "parity": bool(parity),
    }, reg_snap, health


def run_tp(m, workload, engine_outs, tp, engine_section,
           max_slots=8):
    """The --tp measurement: the standard ragged workload through a
    TENSOR-PARALLEL paged engine (serve/tp.py: Megatron-sharded
    weights under shard_map, each shard owning the H_kv/tp slice of
    the block pool) with per-stream parity against the (oracle-
    verified) single-device engine run, per-shard pool occupancy
    sampled per step, and the jit+twin cache pinned across the timed
    run.  ``vs_single_device_tokens_per_s`` is the honest CPU caveat
    number: the gated claims are parity / recompiles / occupancy —
    on a 2-thread virtual CPU mesh the psums and per-shard dispatch
    overhead price TP at/below 1.0, exactly like int8's dequant; the
    knob exists for models bigger than one REAL device (chip-pending,
    ROADMAP item 5)."""
    from singa_tpu.serve import GenerationRequest, PagedConfig

    pcfg = PagedConfig(block_size=16, num_blocks=48)
    kw = dict(tp=tp, paged=pcfg)

    def drive():
        eng = m.serve(max_slots=max_slots, **kw)
        handles = []
        pending = list(workload)
        peak_blocks = 0
        t0 = time.perf_counter()
        while pending or eng.pending:
            while pending and pending[0]["arrival_step"] <= eng.step_count:
                w = pending.pop(0)
                handles.append(eng.submit(GenerationRequest(
                    w["prompt"], max_new_tokens=w["n_new"])))
            eng.step()
            peak_blocks = max(peak_blocks,
                              eng.paged_arena.blocks_used)
        wall = time.perf_counter() - t0
        outs = [h.result() for h in handles]
        snap = eng.stats.snapshot()
        eng.close()
        return wall, outs, snap, peak_blocks

    drive()  # warmup (compiles the sharded twins)
    jit_before = _serve_jit_cache_size()
    wall, outs, snap, peak_blocks = drive()
    jit_after = _serve_jit_cache_size()

    # engine_outs are oracle-verified by the main bench; per-stream
    # equality here is transitively oracle parity
    parity = all(np.array_equal(a.tokens, b.tokens)
                 for a, b in zip(outs, engine_outs))
    useful = sum(w["n_new"] for w in workload)
    tp_snap = snap["tp"]
    return {
        "shards": tp_snap["shards"],
        "devices": tp_snap["devices"],
        "paged_pool": {"block_size": pcfg.block_size,
                       "num_blocks": pcfg.num_blocks},
        "wall_s": wall,
        "tokens_per_s": useful / wall,
        **_lat(snap),
        "vs_single_device_tokens_per_s": (
            (useful / wall) / engine_section["tokens_per_s"]),
        "collectives_per_step": tp_snap["collectives_per_step"],
        "sharded_dispatches": tp_snap["sharded_dispatches"],
        "per_shard": {
            "kv_bytes": tp_snap["kv_bytes_per_shard"],
            "blocks_peak": peak_blocks,
            "occupancy_peak": peak_blocks / pcfg.num_blocks,
        },
        "blocks_leaked": snap["paged"]["blocks_used"],
        "recompiles": (None if jit_before is None
                       else jit_after - jit_before),
        "parity": parity,
        "chip_pending": True,  # CPU numbers; see docs/SERVING.md
    }


#: the dense-layer tp width the --ep bench composes with (shared with
#: main()'s virtual-mesh provisioning so the two cannot drift)
_EP_BENCH_TP = 2


def run_ep(ep, tp=_EP_BENCH_TP, max_slots=8):
    """The --ep measurement: a ragged workload through an
    EXPERT-PARALLEL paged MoE engine (serve/ep.py: experts sharded
    over the ep axis, dense layers Megatron over an orthogonal tp
    axis, capacity-bounded GShard dispatch inside the pool steps)
    against a single-device MoE engine oracle (itself verified
    against offline generate here), with per-expert routed-token
    occupancy, the dropped-token counter (0 at the drop-free default
    capacity), and the jit+twin cache pinned across the timed run.
    ``vs_single_device_tokens_per_s`` carries the same honest CPU
    caveat as --tp: the gated claims are parity / recompiles / load
    accounting — the knob exists for expert banks bigger than one
    REAL device (chip-pending, ROADMAP item 5)."""
    from singa_tpu import tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.serve import EPConfig, GenerationRequest, PagedConfig

    cfg = GPT2Config(vocab_size=512, n_positions=128, n_embd=192,
                     n_layer=4, n_head=4, n_inner=384, dropout=0.0,
                     attn_impl="fused", moe_every=2, moe_experts=4)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    workload = make_workload(n_positions=cfg.n_positions)
    pcfg = PagedConfig(block_size=16, num_blocks=48)

    def drive(kw):
        eng = m.serve(max_slots=max_slots, paged=pcfg, **kw)
        handles = []
        pending = list(workload)
        peak_blocks = 0
        t0 = time.perf_counter()
        while pending or eng.pending:
            while pending and pending[0]["arrival_step"] <= eng.step_count:
                w = pending.pop(0)
                handles.append(eng.submit(GenerationRequest(
                    w["prompt"], max_new_tokens=w["n_new"])))
            eng.step()
            peak_blocks = max(peak_blocks,
                              eng.paged_arena.blocks_used)
        wall = time.perf_counter() - t0
        outs = [h.result() for h in handles]
        snap = eng.stats.snapshot()
        eng.close()
        return wall, outs, snap, peak_blocks

    ep_kw = dict(ep=EPConfig(ep=ep, tp=tp))
    drive({})           # warmup: single-device MoE executables
    drive(ep_kw)        # warmup: the (ep, tp) sharded twins
    base_wall, base_outs, _, _ = drive({})
    jit_before = _serve_jit_cache_size()
    wall, outs, snap, peak_blocks = drive(ep_kw)
    jit_after = _serve_jit_cache_size()

    # the single-device MoE engine is oracle-verified against offline
    # generate; EP parity against it is transitively offline parity
    oracle = all(
        np.array_equal(r.tokens,
                       m.generate(w["prompt"],
                                  max_new_tokens=w["n_new"],
                                  temperature=0))
        for w, r in zip(workload, base_outs))
    parity = oracle and all(
        np.array_equal(a.tokens, b.tokens)
        for a, b in zip(outs, base_outs))
    useful = sum(w["n_new"] for w in workload)
    ep_snap = snap["ep"]
    total_toks = sum(ep_snap["expert_tokens"]) or 1
    return {
        "expert_shards": ep_snap["shards"],
        "dense_tp": ep_snap["dense_tp"],
        "experts": ep_snap["experts"],
        "capacity_factor": ep_snap["capacity_factor"],
        "devices": ep_snap["devices"],
        "paged_pool": {"block_size": pcfg.block_size,
                       "num_blocks": pcfg.num_blocks},
        "wall_s": wall,
        "tokens_per_s": useful / wall,
        **_lat(snap),
        "vs_single_device_tokens_per_s": (
            (useful / wall) / (useful / base_wall)),
        "sharded_dispatches": ep_snap["sharded_dispatches"],
        "per_expert": {
            "tokens": ep_snap["expert_tokens"],
            "occupancy": [t / total_toks
                          for t in ep_snap["expert_tokens"]],
            "load_imbalance": ep_snap["load_imbalance"],
        },
        "dropped_tokens": ep_snap["dropped_tokens"],
        "kv_bytes_per_shard": ep_snap["kv_bytes_per_shard"],
        "blocks_peak": peak_blocks,
        "blocks_leaked": snap["paged"]["blocks_used"],
        "recompiles": (None if jit_before is None
                       else jit_after - jit_before),
        "parity": bool(parity),
        "chip_pending": True,  # CPU numbers; see docs/SERVING.md
    }


def run_pp(m, workload, engine_outs, stages, engine_section,
           max_slots=8):
    """The --pp measurement: the standard ragged workload through a
    PIPELINE-PARALLEL paged engine (serve/pp.py: layers partitioned
    into stages, each owning its layer slice of the block pool,
    GPipe-microbatched decode) with per-stream parity against the
    (oracle-verified) single-device engine run, per-stage pool
    occupancy, stage-boundary hop counts, and the jit+twin cache
    pinned across the timed run.  Same honest CPU caveat as --tp:
    gated claims are parity / recompiles / occupancy — the knob
    exists for models DEEPER than one real device (chip-pending)."""
    from singa_tpu.serve import GenerationRequest, PagedConfig, PPConfig

    pcfg = PagedConfig(block_size=16, num_blocks=48)
    kw = dict(pp=PPConfig(stages=stages), paged=pcfg)

    def drive():
        eng = m.serve(max_slots=max_slots, **kw)
        handles = []
        pending = list(workload)
        peak_blocks = 0
        t0 = time.perf_counter()
        while pending or eng.pending:
            while pending and pending[0]["arrival_step"] <= eng.step_count:
                w = pending.pop(0)
                handles.append(eng.submit(GenerationRequest(
                    w["prompt"], max_new_tokens=w["n_new"])))
            eng.step()
            peak_blocks = max(peak_blocks,
                              eng.paged_arena.blocks_used)
        wall = time.perf_counter() - t0
        outs = [h.result() for h in handles]
        snap = eng.stats.snapshot()
        eng.close()
        return wall, outs, snap, peak_blocks

    drive()  # warmup (compiles the stage twins)
    jit_before = _serve_jit_cache_size()
    wall, outs, snap, peak_blocks = drive()
    jit_after = _serve_jit_cache_size()

    parity = all(np.array_equal(a.tokens, b.tokens)
                 for a, b in zip(outs, engine_outs))
    useful = sum(w["n_new"] for w in workload)
    pp_snap = snap["pp"]
    return {
        "stages": pp_snap["stages"],
        "layers_per_stage": pp_snap["layers_per_stage"],
        "microbatches": pp_snap["microbatches"],
        "devices": pp_snap["devices"],
        "paged_pool": {"block_size": pcfg.block_size,
                       "num_blocks": pcfg.num_blocks},
        "wall_s": wall,
        "tokens_per_s": useful / wall,
        **_lat(snap),
        "vs_single_device_tokens_per_s": (
            (useful / wall) / engine_section["tokens_per_s"]),
        "sharded_dispatches": pp_snap["sharded_dispatches"],
        "boundary_hops": pp_snap["boundary_hops"],
        "per_stage": {
            "kv_bytes": pp_snap["kv_bytes_per_stage"],
            "blocks_peak": peak_blocks,
            "occupancy_peak": peak_blocks / pcfg.num_blocks,
        },
        "blocks_leaked": snap["paged"]["blocks_used"],
        "recompiles": (None if jit_before is None
                       else jit_after - jit_before),
        "parity": bool(parity),
        "chip_pending": True,  # CPU numbers; see docs/SERVING.md
    }


def _longctx_mix(rng, vocab, n_chat=10, long_len=384, n_long=2):
    """Document-analysis serve mix: short chat traffic arriving every
    step, two LONG admissions (a ``long_len``-token document each)
    landing early in the burst, and two pinned continuations (a chat
    turn re-sent through its session handle).  The long prompts are
    what an unbudgeted engine stalls every decode lane behind."""
    chats = []
    for i in range(n_chat):
        chats.append(dict(
            prompt=rng.randint(0, vocab,
                               int(rng.randint(8, 17))).astype(np.int32),
            n_new=8, arrival_step=i,
            pin=(i in (1, 4))))
    longs = [dict(prompt=rng.randint(0, vocab,
                                     long_len).astype(np.int32),
                  n_new=4, arrival_step=2 + j)
             for j in range(n_long)]
    return chats, longs


def run_longctx():
    """The --longctx measurement (the long-context round): the
    document-analysis mix through three engines on a dedicated
    512-position model —

    * **baseline**: chat traffic only (no long admissions) — the
      decode TPOT reference;
    * **budgeted**: the full mix with
      ``PagedConfig(prefill_token_budget=32)`` — each 384-token
      admission splits into 16-token ``_chunk_row`` windows, two per
      step, so decode lanes keep their cadence;
    * **unbudgeted**: the full mix with whole-prompt admission — one
      384-token prefill lands inside a single step and every live
      chat lane's inter-token gap absorbs it (the stall spike).

    Gated claims (tier1 serve gate + the LONGCTX.json serve rows):
    budgeted chat decode TPOT p50 within 1.5x the baseline's while
    the unbudgeted run's worst chat inter-token gap spikes measurably
    above the budgeted run's; the ledger's stall-phase fraction of
    chat latency stays bounded under the budget; every stream (chat,
    long, continuation) byte-equal to the offline oracle; zero
    blocks leaked; zero runtime recompiles.  A second, WINDOWED
    section long-chats a sliding-window model (attn_window=64) 320
    tokens deep and gates the O(window) memory model: peak blocks
    per slot <= ceil(window/block)+1 with out-of-window drops
    observed, stream token-equal to the offline rolling-cache
    oracle."""
    from singa_tpu import observe, tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.observe import requests as reqtrace
    from singa_tpu.serve import GenerationRequest, PagedConfig
    from singa_tpu.utils.metrics import percentile

    cfg = GPT2Config(vocab_size=512, n_positions=512, n_embd=128,
                     n_layer=2, n_head=4, n_inner=256, dropout=0.0,
                     attn_impl="fused")
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    rng = np.random.RandomState(12)
    chats, longs = _longctx_mix(rng, cfg.vocab_size)
    block = 16

    own_ledger = not reqtrace._active
    led = reqtrace.enable(capacity=4096) if own_ledger \
        else reqtrace._ledger

    def drive(include_long, budget):
        pcfg = PagedConfig(block_size=block, num_blocks=96,
                           prefill_token_budget=budget)
        eng = m.serve(max_slots=8, paged=pcfg)
        work = sorted(
            [dict(w, long=False) for w in chats]
            + ([dict(w, long=True) for w in longs]
               if include_long else []),
            key=lambda w: w["arrival_step"])
        pending = list(work)
        rows = []      # (kind, request, handle)
        continued = []
        t0 = time.perf_counter()
        while pending or eng.pending or \
                any(not h.done() for _, _, h in rows):
            while pending and \
                    pending[0]["arrival_step"] <= eng.step_count:
                w = pending.pop(0)
                req = GenerationRequest(
                    w["prompt"], max_new_tokens=w["n_new"],
                    pin_session=bool(w.get("pin")))
                rows.append(("long" if w["long"] else "chat",
                             req, eng.submit(req)))
            # pinned chat turns continue once their first turn
            # retires (sessions run cold here — no prefix cache —
            # which keeps the leak pin exact: used == 0 after drain)
            for kind, req, h in list(rows):
                if kind == "chat" and getattr(req, "pin_session",
                                              False) \
                        and h.done() and id(h) not in continued:
                    continued.append(id(h))
                    req2 = h.result().session.request(
                        rng.randint(0, cfg.vocab_size,
                                    6).astype(np.int32),
                        max_new_tokens=8)
                    rows.append(("chat", req2, eng.submit(req2)))
            eng.step()
        wall = time.perf_counter() - t0
        outs = [(kind, req, h.result()) for kind, req, h in rows]
        leaked = eng.paged_arena.blocks_used
        eng.close()
        return wall, outs, leaked

    # warmup all three configurations (compiles; chunk widths, the
    # budgeted admission path, and the narrow whole-prompt width all
    # enter the jit/AOT caches here)
    for inc, bud in ((False, 32), (True, 32), (True, None)):
        drive(inc, bud)

    jit_before = _serve_jit_cache_size()
    wall_base, outs_base, leak_base = drive(False, 32)
    wall_b, outs_b, leak_b = drive(True, 32)
    wall_u, outs_u, leak_u = drive(True, None)
    jit_after = _serve_jit_cache_size()

    # parity: every stream equals its offline oracle
    parity = True
    for outs in (outs_base, outs_b, outs_u):
        for kind, req, res in outs:
            want = m.generate(req.prompt_ids,
                              max_new_tokens=req.max_new_tokens,
                              temperature=0)
            parity &= bool(np.array_equal(res.tokens, want))
    for _, req, res in outs_base:
        if res.session is not None:
            res.session.release()

    def chat_stats(outs):
        tpots = [res.tpot for kind, _, res in outs
                 if kind == "chat" and res.tpot is not None]
        return percentile(tpots, 50)

    def gap_stats(outs):
        """Worst chat inter-token gap + ledger stall fraction — the
        stall-spike evidence (exact ledger arithmetic, PR-8/13)."""
        by_rid = {e["request_id"]: e for e in led.entries()}
        worst = 0.0
        stall = total = 0.0
        for kind, req, _ in outs:
            e = by_rid.get(req.request_id)
            if kind != "chat" or e is None or not e["phases"]:
                continue
            hop = e["hops"][e["final_hop"]]
            t = hop["t_first_token"]
            for s in hop["steps"]:
                worst = max(worst, s[0] - t)
                t = s[0]
            stall += e["phases"].get("stall", 0.0)
            total += (e["t_retire"] - e["t_submit"])
        return worst, (stall / total if total else 0.0)

    tpot_base = chat_stats(outs_base)
    tpot_b = chat_stats(outs_b)
    tpot_u = chat_stats(outs_u)
    gap_b, stall_b = gap_stats(outs_b)
    gap_u, stall_u = gap_stats(outs_u)
    if own_ledger:
        reqtrace.disable()

    # -- windowed long chat: O(window) blocks, offline-oracle parity --
    wcfg = GPT2Config(vocab_size=512, n_positions=512, n_embd=128,
                      n_layer=2, n_head=4, n_inner=256, dropout=0.0,
                      attn_impl="fused", attn_window=64)
    wm = GPT2LMHead(wcfg)
    wm.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
               is_train=False, use_graph=False)
    wm.set_states(m.get_states())

    def drive_windowed():
        eng = wm.serve(max_slots=2, paged=PagedConfig(
            block_size=block, num_blocks=12))
        prompt = rng2.randint(0, cfg.vocab_size, 16).astype(np.int32)
        h = eng.submit(GenerationRequest(prompt, max_new_tokens=320))
        peak = 0
        t0 = time.perf_counter()
        while eng.pending:
            eng.step()
            s = eng._slots[0]
            if s is not None:
                peak = max(peak,
                           sum(1 for b in s.blocks
                               if b != eng.paged_arena.trash))
        wall = time.perf_counter() - t0
        drops = eng.paged_arena.window_drops
        leaked = eng.paged_arena.blocks_used
        toks = h.result().tokens
        eng.close()
        return wall, prompt, toks, peak, drops, leaked

    rng2 = np.random.RandomState(13)
    drive_windowed()                   # warmup
    w_jit_before = _serve_jit_cache_size()
    wall_w, wprompt, wtoks, peak, drops, leak_w = drive_windowed()
    w_jit_after = _serve_jit_cache_size()
    w_want = wm.generate(wprompt, max_new_tokens=320, temperature=0)
    w_parity = bool(np.array_equal(wtoks, w_want))

    recompiles = (None if jit_before is None
                  else (jit_after - jit_before)
                  + (w_jit_after - w_jit_before))
    section = {
        "model": {"n_positions": 512, "n_embd": 128, "n_layer": 2,
                  "long_prompt_tokens": 384, "chat_prompts": "8-16"},
        "pool": {"block_size": block, "num_blocks": 96},
        "prefill_token_budget": 32,
        "baseline_no_long": {
            "wall_s": wall_base, "chat_tpot_p50_s": tpot_base},
        "budgeted": {
            "wall_s": wall_b, "chat_tpot_p50_s": tpot_b,
            "worst_chat_gap_s": gap_b, "chat_stall_frac": stall_b},
        "unbudgeted": {
            "wall_s": wall_u, "chat_tpot_p50_s": tpot_u,
            "worst_chat_gap_s": gap_u, "chat_stall_frac": stall_u},
        # THE gated numbers: budget keeps chat decode cadence at the
        # no-long-traffic baseline while the unbudgeted run's worst
        # gap carries the whole 384-token prefill
        "tpot_p50_ratio_budgeted": tpot_b / tpot_base,
        "tpot_p50_ratio_unbudgeted": tpot_u / tpot_base,
        "stall_spike_ratio": (gap_u / gap_b) if gap_b else None,
        "windowed": {
            "attn_window": 64, "block_size": block,
            "generated_tokens": 320, "wall_s": wall_w,
            "peak_blocks_held": peak,
            "max_blocks_allowed": 64 // block + 1,
            "window_drops": drops,
            "blocks_leaked": leak_w,
            "parity_vs_offline_windowed": w_parity,
        },
        "blocks_leaked": leak_base + leak_b + leak_u,
        "recompiles": recompiles,
        "parity": bool(parity),
    }
    return section


def _disagg_mix(rng, vocab, n_chat=10, long_len=384, n_long=3):
    """Prefill-heavy serve mix for the disaggregation measurement:
    short chat traffic arriving every step plus ``n_long``
    ``long_len``-token document admissions landing early — the LAST
    document re-sends the FIRST one's prompt, so a fleet-level prefix
    cache can prove a cross-replica warm hit (prefilled once, never
    re-prefilled)."""
    chats = [dict(prompt=rng.randint(0, vocab, int(rng.randint(
                      8, 17))).astype(np.int32),
                  n_new=8, arrival_step=i, kind="chat")
             for i in range(n_chat)]
    longs = [dict(prompt=rng.randint(0, vocab,
                                     long_len).astype(np.int32),
                  n_new=4, arrival_step=1 + j, kind="long")
             for j in range(n_long)]
    longs[-1]["prompt"] = longs[0]["prompt"].copy()
    longs[-1]["arrival_step"] = 1 + n_long
    return sorted(chats + longs, key=lambda w: w["arrival_step"])


def run_disagg():
    """The --disagg measurement (the disaggregation round): the
    prefill-heavy mix through TWO fleets of four replicas on the
    dedicated 512-position model —

    * **symmetric**: 4 mixed replicas (the classic fleet) — every
      384-token document prefills INSIDE a replica that is also
      decoding chat traffic, so chat TPOT absorbs the interference
      DistServe/Splitwise describe;
    * **disagg**: 2 prefill specialists + 2 decode specialists —
      documents build on the specialists and SHIP their KV blocks to
      the decode side as validated host images; decode replicas never
      run a long prefill.

    Gated claims (tier1 serve gate): chat decode TPOT p50 under the
    concurrent long admissions <= the symmetric fleet's
    (``tpot_p50_ratio_disagg`` <= 1.0 — TTFT and TPOT stop
    contending), ship_count > 0, shared-prefix hit rate > 0 across
    replicas (the repeated document is prefilled ONCE fleet-wide),
    per-stream parity vs the single-engine/offline oracle, zero
    leaked blocks, zero runtime recompiles."""
    from singa_tpu import tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.serve import (GenerationRequest, PagedConfig,
                                 PrefixCacheConfig, ServeFleet)
    from singa_tpu.utils.metrics import percentile

    cfg = GPT2Config(vocab_size=512, n_positions=512, n_embd=128,
                     n_layer=2, n_head=4, n_inner=256, dropout=0.0,
                     attn_impl="fused")
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    rng = np.random.RandomState(17)
    work = _disagg_mix(rng, cfg.vocab_size)
    block = 16
    kw = dict(max_slots=2,
              paged=PagedConfig(block_size=block, num_blocks=96),
              prefix_cache=PrefixCacheConfig(block_size=block))

    def drive(roles):
        fleet = ServeFleet(m, replicas=4, roles=roles, **kw)
        pending = list(work)
        rows = []
        t0 = time.perf_counter()
        while pending or fleet.pending:
            while pending and \
                    pending[0]["arrival_step"] <= fleet.step_count:
                w = pending.pop(0)
                rows.append((w, fleet.submit(GenerationRequest(
                    w["prompt"], max_new_tokens=w["n_new"],
                    temperature=0.0))))
            fleet.step()
        wall = time.perf_counter() - t0
        outs = [(w, h.result()) for w, h in rows]
        snap = fleet.snapshot()
        leaked = sum(
            fleet.supervisor(i).engine.paged_arena.blocks_used
            - fleet.supervisor(i).engine.prefix_cache.cached_blocks
            for i in range(fleet.replicas))
        fleet.close()
        return wall, outs, snap, leaked

    roles_disagg = ("prefill", "prefill", "decode", "decode")
    for roles in (None, roles_disagg):          # warmup compiles
        drive(roles)
    jit_before = _serve_jit_cache_size()
    wall_sym, outs_sym, snap_sym, leak_sym = drive(None)
    wall_d, outs_d, snap_d, leak_d = drive(roles_disagg)
    jit_after = _serve_jit_cache_size()

    # per-stream parity vs the single-engine oracle (m.generate IS
    # the engine oracle — the engine==generate pin is the suite's)
    parity = True
    oracle = {}
    for outs in (outs_sym, outs_d):
        for w, res in outs:
            key = (w["prompt"].tobytes(), w["n_new"])
            if key not in oracle:
                oracle[key] = np.asarray(m.generate(
                    w["prompt"], max_new_tokens=w["n_new"],
                    temperature=0))
            parity &= bool(np.array_equal(res.tokens, oracle[key]))

    def chat_tpot(outs):
        return percentile([res.tpot for w, res in outs
                           if w["kind"] == "chat"
                           and res.tpot is not None], 50)

    tpot_sym = chat_tpot(outs_sym)
    tpot_d = chat_tpot(outs_d)
    return {
        "model": {"n_positions": 512, "n_embd": 128, "n_layer": 2,
                  "long_prompt_tokens": 384, "chat_prompts": "8-16"},
        "pool": {"block_size": block, "num_blocks": 96},
        "fleet": {"replicas": 4, "max_slots_each": 2,
                  "roles_disagg": list(roles_disagg)},
        "symmetric": {
            "wall_s": wall_sym, "chat_tpot_p50_s": tpot_sym,
            "ships": snap_sym["ships"],
            "routed": snap_sym["routed"]},
        "disagg": {
            "wall_s": wall_d, "chat_tpot_p50_s": tpot_d,
            "ships": snap_d["ships"],
            "ship_bytes": snap_d["ship_bytes"],
            "shared_prefix_hits": snap_d["shared_prefix_hits"],
            "ship_fallbacks": snap_d["ship_fallbacks"],
            "routed": snap_d["routed"]},
        # THE gated numbers: decode TPOT stops contending with long
        # prefill, the documents shipped, and the repeated document
        # warmed a sibling replica instead of re-prefilling
        "tpot_p50_ratio_disagg": tpot_d / tpot_sym,
        "ships": snap_d["ships"],
        "shared_prefix_hits": snap_d["shared_prefix_hits"],
        "blocks_leaked": leak_sym + leak_d,
        "recompiles": (None if jit_before is None
                       else jit_after - jit_before),
        "parity": bool(parity),
    }


def _write_longctx_rows(section):
    """Commit the serve section into LONGCTX.json NEXT TO the train
    cells (the file the long-context training crossover harness owns)
    — serve and train long-context evidence live side by side."""
    from singa_tpu.observe.export import json_sanitize

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "LONGCTX.json")
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        doc = {}
    doc["serve"] = json_sanitize(section)
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, allow_nan=False)


def run_static(m, workload, max_slots):
    """Arrival-order batches of max_slots, each to its longest row."""
    from singa_tpu.models import gpt2_decode

    t0 = time.perf_counter()
    outs, ttfts = [], []
    for i in range(0, len(workload), max_slots):
        group = workload[i:i + max_slots]
        n_max = max(w["n_new"] for w in group)
        rows = gpt2_decode.generate(
            m, [w["prompt"] for w in group], max_new_tokens=n_max,
            temperature=0)
        t_done = time.perf_counter() - t0
        for w, row in zip(group, rows):
            keep = len(w["prompt"]) + w["n_new"]
            outs.append(np.asarray(row[:keep]))
            ttfts.append(t_done)  # tokens only exist once the batch drains
    wall = time.perf_counter() - t0
    return wall, outs, ttfts


def run_step_anatomy(m, workload, max_slots, baseline_outs, useful):
    """The --step-anatomy measurement: replay the standard workload
    with the step profiler ON (``observe.stepprof``) and commit the
    baseline device-bubble fraction — the ROADMAP item-5 measuring
    stick.  Every future overlap-the-host-with-the-device PR diffs
    its bubble against this section.

    Four pins ride along, asserted by the tier1 serve gate:
    per-segment fractions sum to 1 (±1e-6 — exclusive-time exact
    arithmetic), the measured bubble is nonzero (a claim of zero
    bubble on an unoverlapped step loop means the instrument is
    broken), token parity against the unprofiled run (the profiler
    must observe, not perturb), and zero runtime recompiles (fences
    and the block_until_ready hook never enter jitted code).

    CPU-measured: the absolute bubble is chip-pending (a CPU "device"
    is the same silicon as the host, so the bubble runs high); the
    INSTRUMENT and its pins are platform-independent."""
    from singa_tpu.observe import stepprof
    from singa_tpu.serve import GenerationRequest

    prof = stepprof.enable()
    jit_before = _serve_jit_cache_size()
    eng = m.serve(max_slots=max_slots)
    handles = []
    pending = list(workload)
    t0 = time.perf_counter()
    while pending or eng.pending:
        while pending and pending[0]["arrival_step"] <= eng.step_count:
            w = pending.pop(0)
            handles.append(eng.submit(GenerationRequest(
                w["prompt"], max_new_tokens=w["n_new"])))
        eng.step()
    wall = time.perf_counter() - t0
    outs = [h.result() for h in handles]
    jit_after = _serve_jit_cache_size()
    parity = all(np.array_equal(a.tokens, b.tokens)
                 for a, b in zip(outs, baseline_outs))
    sec = prof.section()
    # overall fractions over ONE denominator across the profiled
    # run's engines (one engine here; the schema holds for more)
    seg = {}
    for a in prof._agg.values():
        for k, v in a["seg"].items():
            seg[k] = seg.get(k, 0.0) + v
    denom = sum(seg.values())
    fractions = ({k: v / denom for k, v in sorted(seg.items())}
                 if denom > 0 else {})
    why = prof.why_slow_summary()
    # fences off FIRST, series kept readable, THEN close: the
    # registry snapshot and the --prom-out exposition at exit must
    # carry the serve.step.* families this section's numbers came
    # from (a close under a live profiler would forget_engine them),
    # while the profiled engine's own serve.* stats unregister as
    # every other section's timed engine does
    stepprof.disable(unregister=False)
    eng.close()
    return {
        "steps": sec["steps"],
        "wall_s": wall,
        "tokens_per_s": useful / wall,
        "bubble_frac": why["bubble_frac"] if why else None,
        "device_frac": why["device_frac"] if why else None,
        "top_host_segment": (why["top_host_segment"] if why
                             else None),
        "fractions": fractions,
        "fractions_sum": sum(fractions.values()),
        "engines": sec["engines"],
        "parity": bool(parity),
        "recompiles": jit_after - jit_before,
        # CPU host == CPU "device": the absolute bubble is not a TPU
        # number — the instrument and its pins are what this commits
        "chip_pending": True,
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="write a Chrome trace-event JSON of the timed "
                         "engine run (Perfetto/chrome://tracing)")
    ap.add_argument("--health-out", default=None, metavar="PATH",
                    help="also write observe.health_report() (goodput, "
                         "MFU, SLO counters, watchdog state) as JSON")
    ap.add_argument("--request-log", default=None, metavar="PATH",
                    help="enable the per-request lifecycle ledger "
                         "(observe.requests) for the timed runs and "
                         "write one strict-JSON line per request "
                         "there; embeds the request_log self-check "
                         "section and turns on the health report's "
                         "why_slow attribution")
    ap.add_argument("--prom-out", default=None, metavar="PATH",
                    help="also write the Prometheus text exposition "
                         "of the live metrics registry (bucketed "
                         "histogram families) at exit")
    ap.add_argument("--step-anatomy", action="store_true",
                    help="also replay the workload with the step "
                         "profiler ON (observe.stepprof) and embed "
                         "the step_anatomy section — per-segment "
                         "host/device fractions (sum to 1), the "
                         "baseline device-bubble fraction ROADMAP "
                         "item 5 diffs against, parity vs the "
                         "unprofiled run, recompile pin")
    ap.add_argument("--paged", action="store_true",
                    help="also run the workload through the paged-KV "
                         "engine vs the slot arena at the SAME KV "
                         "byte budget and embed the paged section "
                         "(concurrency at fixed memory, tokens/s, "
                         "priority preemption exercised, parity, "
                         "recompile pin)")
    ap.add_argument("--fork", action="store_true",
                    help="also measure best-of-n CoW fork families "
                         "vs n independent requests over a shared "
                         "system prompt (n in {2,4}: block savings, "
                         "tokens/s, greedy n=1 parity, 100%% "
                         "schema-valid structured outputs, leak + "
                         "recompile pins — the fork section)")
    ap.add_argument("--prefix-mix", action="store_true",
                    help="also run the shared-system-prompt + "
                         "multi-turn session workload warm (radix "
                         "prefix cache) vs cold and embed the "
                         "prefix_mix section (hit rate, TTFT "
                         "cold-vs-warm, parity, recompile pin)")
    ap.add_argument("--fleet", action="store_true",
                    help="also run the workload through a 2-replica "
                         "ServeFleet (same total slots) and embed the "
                         "fleet section (routing balance, parity, "
                         "recompile pin)")
    ap.add_argument("--spec", action="store_true",
                    help="also train a target/draft pair and measure "
                         "speculative serve (spec_k=4) against the "
                         "plain engine on the same trained target "
                         "(tokens/s, acceptance, accepted-tokens/"
                         "chunk, parity, recompile pin)")
    ap.add_argument("--spec-sweep", action="store_true",
                    help="also sweep spec_k in {2,4,8} on the trained "
                         "pair and embed the spec_sweep section "
                         "(tokens/s vs measured acceptance per k, "
                         "parity per row; chip-pending — VERDICT "
                         "next-round #5's acceptance-sweep "
                         "characterization)")
    ap.add_argument("--cache-int8", action="store_true",
                    help="also run the standard workload through an "
                         "int8-KV-arena engine (tokens/s, TTFT/TPOT "
                         "percentiles, parity vs the offline int8 "
                         "oracle, recompile pin; chip-pending row)")
    ap.add_argument("--longctx", action="store_true",
                    help="also run the long-context document-analysis "
                         "serve mix (chunked-prefill token budget vs "
                         "unbudgeted vs no-long-traffic baseline, "
                         "plus a windowed long-chat O(window)-blocks "
                         "run) — embeds the longctx section and "
                         "commits the same rows into LONGCTX.json "
                         "next to the train cells")
    ap.add_argument("--disagg", action="store_true",
                    help="also run the prefill-heavy mix through a "
                         "2-prefill/2-decode disaggregated fleet vs "
                         "4 symmetric replicas (KV shipping, fleet "
                         "prefix index) and embed the disagg section "
                         "(chat TPOT under long admissions, ships, "
                         "cross-replica shared-prefix hits, parity, "
                         "leak + recompile pins)")
    ap.add_argument("--tp", type=int, default=None, metavar="K",
                    help="also run the standard workload through a "
                         "K-shard TENSOR-PARALLEL paged engine "
                         "(serve/tp.py) with per-stream parity "
                         "against the single-device run, per-shard "
                         "occupancy, recompile pin (the tp section)")
    ap.add_argument("--ep", type=int, default=None, metavar="K",
                    help="also run a ragged MoE workload through a "
                         "K-expert-shard EXPERT-PARALLEL paged engine "
                         "(serve/ep.py, dense layers tp=2) with "
                         "parity against the single-device MoE "
                         "oracle, per-expert routed-token occupancy, "
                         "dropped-token count, recompile pin (the ep "
                         "section)")
    ap.add_argument("--pp", type=int, default=None, metavar="K",
                    help="also run the standard workload through a "
                         "K-stage PIPELINE-PARALLEL paged engine "
                         "(serve/pp.py, GPipe-microbatched decode) "
                         "with per-stream parity against the "
                         "single-device run, per-stage occupancy, "
                         "boundary-hop counts, recompile pin (the pp "
                         "section)")
    args = ap.parse_args()

    # --tp needs a >=K-device mesh BEFORE jax initializes its backend;
    # the flag only affects the CPU platform (a real slice already has
    # its chips), mirroring tests/conftest.py's virtual topology
    if args.tp or args.ep or args.pp:
        need = max(8, args.tp or 0, _EP_BENCH_TP * (args.ep or 0),
                   args.pp or 0)
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count="
                f"{need}").strip()

    import jax

    from singa_tpu import observe, tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.utils.metrics import percentile

    if args.tp and len(jax.devices()) < args.tp:
        raise SystemExit(
            f"--tp {args.tp} needs {args.tp} devices, have "
            f"{len(jax.devices())} ({jax.devices()[0].platform})")

    # active monitoring rides the whole bench: flight recorder + hang
    # watchdog (generous timeout — a CPU compile legitimately takes
    # minutes) + crash handler, so a bench killed mid-run leaves a
    # monitor-crash-*.json bundle for CI to upload.  The report's
    # `health` key proves the run was clean.
    observe.monitor.start(watchdog_timeout_s=900.0, crash_handler=True)
    # generous CPU-scale SLO targets: a clean run reports the counters
    # at zero; tighten these to your latency budget in production
    slo = observe.SLO(ttft_p99_s=120.0, tpot_p50_s=30.0,
                      queue_depth_max=64)

    max_slots = 8
    cfg = GPT2Config(vocab_size=512, n_positions=128, n_embd=192,
                     n_layer=4, n_head=4, n_inner=384, dropout=0.0,
                     attn_impl="fused")
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    workload = make_workload(n_positions=cfg.n_positions)
    useful = sum(w["n_new"] for w in workload)

    # warmup: compile both paths on the exact workload
    run_engine(m, workload, max_slots, close_after=True)
    run_static(m, workload, max_slots)

    if args.trace_out:
        observe.clear()  # drop warmup events; trace the timed run only
        observe.enable()
    led = jit_rl_before = None
    if args.request_log:
        # ledger ON for every timed run from here (engine + the
        # optional prefix/spec/int8/fleet sections); warmup traffic
        # above never reached it.  The jit pin brackets the timed
        # engine run to prove the ledger's host-side hooks introduce
        # zero runtime recompiles
        led = observe.requests.enable(capacity=4096)
        jit_rl_before = _serve_jit_cache_size()
    wall_e, outs_e, snap = run_engine(m, workload, max_slots, slo=slo)
    jit_rl_after = (_serve_jit_cache_size() if args.request_log
                    else None)
    observe.disable()
    wall_s, outs_s, ttfts_s = run_static(m, workload, max_slots)

    # parity: every engine stream == its single-prompt generate output
    parity = True
    for w, res in zip(workload, outs_e):
        want = m.generate(w["prompt"], max_new_tokens=w["n_new"],
                          temperature=0)
        if not np.array_equal(res.tokens, want):
            parity = False
            break
    # the static rows are the same offline math — sanity-check one path
    # against the other instead of recomputing 40 more oracles
    static_parity = all(
        np.array_equal(a.tokens, b) for a, b in zip(outs_e, outs_s))

    report = {
        "bench": "serve_continuous_batching",
        "device": jax.devices()[0].device_kind,
        "config": {
            "model": {"n_embd": cfg.n_embd, "n_layer": cfg.n_layer,
                      "n_head": cfg.n_head, "vocab": cfg.vocab_size,
                      "n_positions": cfg.n_positions},
            "max_slots": max_slots,
        },
        "workload": {
            "requests": len(workload),
            "useful_tokens": useful,
            "seed": 0,
            "new_token_palette": _NEW_PALETTE,
        },
        "engine": {
            "wall_s": wall_e,
            "tokens_per_s": useful / wall_e,
            "ttft_p50_s": snap["latency"]["ttft"]["p50"],
            "ttft_p99_s": snap["latency"]["ttft"]["p99"],
            "tpot_p50_s": snap["latency"]["tpot"]["p50"],
            "decode_steps": snap["throughput"]["decode_steps"],
            "slot_occupancy_mean": snap["slots"]["occupancy_mean"],
        },
        "static_batch": {
            "wall_s": wall_s,
            "tokens_per_s": useful / wall_s,
            "ttft_p50_s": percentile(ttfts_s, 50),
            "ttft_p99_s": percentile(ttfts_s, 99),
        },
        "speedup_tokens_per_s": wall_s / wall_e,
        "ttft_p50_improvement": (percentile(ttfts_s, 50)
                                 / snap["latency"]["ttft"]["p50"]),
        "parity": bool(parity and static_parity),
        # process-wide observe registry (serve counters/gauges/latency
        # histograms across every run this process made)
        "registry": observe.registry().snapshot(),
        # active-layer summary: serve goodput + SLO violation counts,
        # watchdog hang/anomaly state (a clean run has hangs == 0),
        # flight-recorder status, MFU accounting (nan here: no train
        # step and no TPU peak on CPU).  include_registry=False: the
        # snapshot already rides the top-level `registry` key above —
        # embedding it twice would double the report and let the two
        # copies silently diverge
        "health": observe.health_report(engine_snapshots=[snap],
                                        include_registry=False),
    }
    if args.step_anatomy:
        report["step_anatomy"] = run_step_anatomy(
            m, workload, max_slots, outs_e, useful)
        report["registry"] = observe.registry().snapshot()
        report["health"] = observe.health_report(
            engine_snapshots=[snap], include_registry=False)
    if args.paged:
        report["paged"] = run_paged(m, workload, outs_e)
        report["registry"] = observe.registry().snapshot()
        report["health"] = observe.health_report(
            engine_snapshots=[snap], include_registry=False)
    if args.fork:
        report["fork"] = run_fork(m)
        report["registry"] = observe.registry().snapshot()
        report["health"] = observe.health_report(
            engine_snapshots=[snap], include_registry=False)
    if args.prefix_mix:
        report["prefix_mix"] = run_prefix_mix(max_slots)
        # the prefix engines ran after the health snapshot above;
        # refresh it so serve.prefix counters appear in the report
        report["registry"] = observe.registry().snapshot()
        report["health"] = observe.health_report(
            engine_snapshots=[snap], include_registry=False)
    if args.cache_int8:
        report["cache_int8"] = run_int8(m, workload, max_slots,
                                        report["engine"])
        report["registry"] = observe.registry().snapshot()
        report["health"] = observe.health_report(
            engine_snapshots=[snap], include_registry=False)
    spec_pair = (_train_spec_pair()
                 if (args.spec or args.spec_sweep) else None)
    spec_baseline = None
    if args.spec:
        if args.spec_sweep:
            report["spec"], spec_baseline = run_spec(
                max_slots, pair=spec_pair, return_baseline=True)
        else:
            report["spec"] = run_spec(max_slots, pair=spec_pair)
        report["registry"] = observe.registry().snapshot()
        report["health"] = observe.health_report(
            engine_snapshots=[snap], include_registry=False)
    if args.spec_sweep:
        report["spec_sweep"] = run_spec_sweep(max_slots,
                                              pair=spec_pair,
                                              baseline=spec_baseline)
        report["registry"] = observe.registry().snapshot()
        report["health"] = observe.health_report(
            engine_snapshots=[snap], include_registry=False)
    if args.tp:
        report["tp"] = run_tp(m, workload, outs_e, args.tp,
                              report["engine"], max_slots=max_slots)
        report["registry"] = observe.registry().snapshot()
        report["health"] = observe.health_report(
            engine_snapshots=[snap], include_registry=False)
    if args.ep:
        report["ep"] = run_ep(args.ep, max_slots=max_slots)
        report["registry"] = observe.registry().snapshot()
        report["health"] = observe.health_report(
            engine_snapshots=[snap], include_registry=False)
    if args.pp:
        report["pp"] = run_pp(m, workload, outs_e, args.pp,
                              report["engine"], max_slots=max_slots)
        report["registry"] = observe.registry().snapshot()
        report["health"] = observe.health_report(
            engine_snapshots=[snap], include_registry=False)
    if args.disagg:
        report["disagg"] = run_disagg()
        report["registry"] = observe.registry().snapshot()
        report["health"] = observe.health_report(
            engine_snapshots=[snap], include_registry=False)
    if args.longctx:
        report["longctx"] = run_longctx()
        _write_longctx_rows(report["longctx"])
        report["registry"] = observe.registry().snapshot()
        report["health"] = observe.health_report(
            engine_snapshots=[snap], include_registry=False)
    if args.fleet:
        # the fleet's metrics unregister at close(), so the refreshed
        # registry/health snapshots come back from INSIDE the bench
        # (taken while the fleet's counters are live — a post-close
        # health report would carry an all-zero fleet section)
        report["fleet"], report["registry"], report["health"] = \
            run_fleet_bench(m, workload, outs_e, replicas=2,
                            max_slots=max_slots // 2, engine_snap=snap)
    if args.request_log:
        report["request_log"] = _request_log_section(
            led, args.request_log,
            recompiles=(None if jit_rl_before is None
                        or jit_rl_after is None
                        else jit_rl_after - jit_rl_before))
        # every optional section above refreshed health while the
        # ledger was live, so the report's why_slow is the enabled
        # attribution; refresh only when nothing ran after the timed
        # engine run (a --fleet health snapshot must NOT be retaken —
        # the fleet's metrics unregistered at close)
        if not args.fleet:
            report["health"] = observe.health_report(
                engine_snapshots=[snap], include_registry=False)
        observe.requests.disable()
    if args.prom_out:
        observe.export.write_prometheus(args.prom_out)
        report["prometheus"] = {"path": args.prom_out}
    if args.trace_out:
        n_events = observe.export.write_chrome_trace(
            args.trace_out,
            metadata={"bench": "serve_continuous_batching"},
            requests=(led.entries() if led is not None else None))
        report["trace"] = {"path": args.trace_out,
                           "trace_events": n_events}
    # strict JSON on disk/stdout: nan (e.g. MFU on CPU) becomes null,
    # so jq and non-Python consumers of the BENCH trajectory keep
    # working
    report = observe.export.json_sanitize(report)
    if args.health_out:
        with open(args.health_out, "w") as f:
            json.dump(report["health"], f, default=str,
                      allow_nan=False)
    observe.monitor.stop()
    line = json.dumps(report, default=str, allow_nan=False)
    print(line)
    with open("BENCH_SERVE.json", "w") as f:
        f.write(line + "\n")


if __name__ == "__main__":
    main()
