"""Chaos bench — drive the resilience layer end to end and PROVE the
recovery invariants the unit tests assert piecewise:

* **checkpoint corruption** — write two manager checkpoints, truncate
  AND bit-flip the newest, and require ``restore_latest`` to fall back
  to the previous good step (``resilience.checkpoint_fallbacks``);
  a transient injected write fault must be absorbed by the retry
  layer (``resilience.retries{site=checkpoint.write}``).
* **collective retry** — a transient fault at the host-side
  ``comm.collective`` dispatch site retries under backoff and the run
  proceeds.
* **decode fault + supervised restart** — a seeded fault injected into
  ``serve.decode_step`` mid-run fails the engine TYPED; the supervisor
  rebuilds it and requeues never-started requests.  The bench asserts
  ZERO wedged/lost requests (every submitted request retires or fails
  typed), token-stream parity against an uninterrupted run for every
  completed request, and ``resilience.engine_restarts`` equal to the
  number of injected decode faults.
* **fault mid-verify (speculative engine)** — the same decode-site
  fault against a trained-pair SPECULATIVE engine: the spec step
  (draft scan + chunk verify + rejection sample) fails typed, not
  wedged; the rebuilt engine gets fresh target AND draft arenas at
  zero recompiles and requeued streams keep byte parity.
* **fault mid-swap (paged engine)** — a ``serve.paged_copy`` fault
  against a block-paged engine whose pool deliberately over-commits
  (growth swaps fire every round): the copy raises mid-preemption, the
  engine fails TYPED (swapped requests ``started=True`` — tokens
  streamed, never requeued), the supervisor rebuild gets a FRESH pool,
  and requeued never-started streams keep byte parity, preemption and
  swap/resume included post-restart.
* **fault at a TP collective (tensor-parallel engine)** — a
  ``serve.tp_collective`` fault fires at a sharded-twin dispatch
  mid-decode: the sharded engine fails typed, the supervisor rebuilds
  it on the same device group (twin-cache hit, fresh sharded arenas),
  requeued streams keep byte parity, zero wedged/lost, restarts ==
  injected.
* **replica kill + fleet failover** — the same decode fault against a
  ``ServeFleet`` replica with a ZERO restart budget kills that replica
  outright mid-decode; the fleet requeues its never-started work onto
  the survivor (stream parity), fails started work typed, keeps
  serving new requests, and the jit cache stays pinned at zero
  recompiles across the failover.
* **fault mid-branch (CoW fork family)** — a ``serve.fork_copy``
  fault fires on the copy-on-write block copy inside a best-of-n
  family: the WRITING branch rejects typed (``FaultInjected``) and
  frees its private blocks, its siblings complete with byte parity
  against the clean run, the ENGINE never fails (blast radius is one
  branch — zero restarts), zero blocks leak, and a fresh-pool rerun
  reproduces the clean streams exactly.
* **disaggregated fleet under fire** — a ``serve.kv_ship`` fault
  mid-transfer requeues the shipped request COLD with byte parity
  (nothing streams during a ship) and leaks zero blocks on either
  replica; a chunk fault with a zero restart budget KILLS a prefill
  specialist mid-build and the fleet serves everything cold on the
  decode side — zero wedged, zero lost, zero leaked.

The whole run happens under active monitoring; the report embeds
``observe.health_report()`` and the bench FAILS unless
``watchdog.hangs == 0`` — recovery that trips the hang detector is
not recovery.  Writes CHAOS.json (strict JSON) and prints it; CI runs
this on CPU and re-parses the file as its gate (tier1.yml chaos job).
"""

import argparse
import json
import os
import shutil
import tempfile

import numpy as np


def chaos_checkpoint(report):
    """Corrupt-newest fallback + retried transient write fault."""
    from singa_tpu import device, opt, tensor
    from singa_tpu.models.mlp import MLP
    from singa_tpu.observe.registry import registry
    from singa_tpu.resilience import (CheckpointManager, FailOnce,
                                      RetryPolicy, faults)
    from singa_tpu.resilience.checkpoint import STATES_NAME

    dev = device.get_default_device()
    m = MLP(data_size=10, perceptron_size=16, num_classes=4)
    m.set_optimizer(opt.SGD(lr=0.05))
    x = tensor.from_numpy(np.zeros((8, 10), np.float32), dev)
    m.compile([x], is_train=True, use_graph=False, sequential=False)
    rng = np.random.RandomState(0)

    def train(n):
        for _ in range(n):
            xb = tensor.from_numpy(
                rng.randn(8, 10).astype(np.float32), dev)
            yb = tensor.from_numpy(
                rng.randint(0, 4, (8,)).astype(np.int32), dev)
            m(xb, yb)

    root = tempfile.mkdtemp(prefix="chaos-ckpt-")
    try:
        mgr = CheckpointManager(
            root, keep=3,
            retry_policy=RetryPolicy(max_attempts=3, base_delay_s=0.01,
                                     max_delay_s=0.05))
        train(2)
        # transient write fault: FailOnce fires on the first attempt,
        # the retry layer's second attempt commits the checkpoint
        faults.inject("checkpoint.write", FailOnce())
        mgr.save(m, 100, aux_states={"tag": np.int64(100)})
        faults.clear()
        good = {k: tensor.to_numpy(v) for k, v in m.get_params().items()}
        train(2)
        mgr.save(m, 200, aux_states={"tag": np.int64(200)})

        # crash-mid-write: truncate the newest states file mid-record
        sp = os.path.join(mgr.step_dir(200), STATES_NAME)
        data = open(sp, "rb").read()
        open(sp, "wb").write(data[:len(data) // 2])

        m2 = MLP(data_size=10, perceptron_size=16, num_classes=4)
        m2.compile([x], is_train=True, use_graph=False, sequential=False)
        step, aux = mgr.restore_latest(m2)
        assert step == 100 and int(aux["tag"]) == 100, \
            f"fallback restored step {step}, wanted 100"
        for k, v in m2.get_params().items():
            np.testing.assert_array_equal(tensor.to_numpy(v), good[k])

        snap = registry().snapshot()["counters"]
        report["checkpoint"] = {
            "fallbacks": snap.get("resilience.checkpoint_fallbacks", 0),
            "write_retries": snap.get(
                "resilience.retries{site=checkpoint.write}", 0),
            "restored_step_after_corruption": step,
        }
        assert report["checkpoint"]["fallbacks"] >= 1
        assert report["checkpoint"]["write_retries"] >= 1
    finally:
        shutil.rmtree(root, ignore_errors=True)


def chaos_collective(report):
    """Transient fault at the host-side collective dispatch hook —
    retried under the communicator's fast backoff policy."""
    from singa_tpu.observe.registry import registry
    from singa_tpu.parallel.communicator import _record_collective
    from singa_tpu.resilience import FailOnce, faults

    faults.inject("comm.collective", FailOnce())
    # the trace-time dispatch hook every collective method calls
    _record_collective("all_reduce", [np.zeros((1024,), np.float32)])
    faults.clear()
    snap = registry().snapshot()["counters"]
    report["collective"] = {
        "retries": snap.get(
            "resilience.retries{site=comm.collective}", 0),
    }
    assert report["collective"]["retries"] >= 1


def chaos_serve(report):
    """Injected decode faults mid-run: zero wedged/lost requests,
    parity for everything that completed, restarts == injected."""
    from singa_tpu import tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.observe.registry import registry
    from singa_tpu.resilience import FailAfterN, faults
    from singa_tpu.serve import (EngineFailedError, EngineSupervisor,
                                 GenerationRequest)

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)

    rng = np.random.RandomState(0)
    workload = [(rng.randint(0, 256, rng.randint(3, 14)).astype(np.int32),
                 int(rng.randint(2, 9))) for _ in range(10)]
    # uninterrupted oracle, one prompt at a time
    base = [np.asarray(m.generate(p, max_new_tokens=n, temperature=0.0))
            for p, n in workload]

    injected = 0
    restarts0 = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0)
    completed = wedged = typed_failed = 0
    # two chaos rounds, each killing the engine once at a different
    # depth into the run
    for round_i, fail_after in enumerate((2, 4)):
        sup = EngineSupervisor(m, max_slots=2, restart_budget=2)
        handles = [sup.submit(GenerationRequest(
            p, max_new_tokens=n, temperature=0.0))
            for p, n in workload]
        pol = faults.inject("serve.decode_step",
                            FailAfterN(fail_after, times=1))
        sup.run_until_complete(max_steps=2000)
        faults.clear()
        injected += pol.fired
        for (p, n), h, want in zip(workload, handles, base):
            if not h.done():
                wedged += 1
                continue
            try:
                got = h.result().tokens
                assert np.array_equal(got, want), \
                    "token stream diverged after restart"
                completed += 1
            except EngineFailedError:
                typed_failed += 1  # in-flight at fault: typed, not lost
        sup.close()

    restarts = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0) - restarts0
    report["serve"] = {
        "requests": 2 * len(workload),
        "completed_with_parity": completed,
        "typed_failures": typed_failed,
        "wedged_or_lost": wedged,
        "decode_faults_injected": injected,
        "engine_restarts": restarts,
    }
    assert wedged == 0, f"{wedged} requests wedged/lost"
    assert completed + typed_failed == 2 * len(workload)
    assert completed > 0 and typed_failed > 0
    assert restarts == injected, \
        f"restarts ({restarts}) != injected decode faults ({injected})"


def chaos_prefix(report):
    """Injected prefix-cache copy faults (serve.prefix_copy fires in
    the warm-admission block copy AND the retire-time donation): the
    engine fails TYPED, the supervisor rebuilds it with an EMPTY radix
    tree, and every request either completes with parity or fails
    typed — zero wedged/lost, restarts == injected."""
    from singa_tpu import tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.observe.registry import registry
    from singa_tpu.resilience import FailAfterN, faults
    from singa_tpu.serve import (EngineFailedError, EngineSupervisor,
                                 GenerationRequest, PrefixCacheConfig)

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)

    rng = np.random.RandomState(2)
    system = rng.randint(0, 256, 24).astype(np.int32)
    workload = [(np.concatenate(
        [system,
         rng.randint(0, 256, rng.randint(3, 10)).astype(np.int32)]),
        int(rng.randint(2, 7))) for _ in range(10)]
    base = [np.asarray(m.generate(p, max_new_tokens=n, temperature=0.0))
            for p, n in workload]

    injected = 0
    restarts0 = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0)
    completed = wedged = typed_failed = 0
    for fail_after in (3, 8):
        sup = EngineSupervisor(
            m, max_slots=2, restart_budget=2,
            prefix_cache=PrefixCacheConfig(block_size=8,
                                           num_blocks=32))
        cache0 = sup.engine.prefix_cache
        handles = [sup.submit(GenerationRequest(
            p, max_new_tokens=n, temperature=0.0))
            for p, n in workload]
        pol = faults.inject("serve.prefix_copy",
                            FailAfterN(fail_after, times=1))
        sup.run_until_complete(max_steps=2000)
        faults.clear()
        injected += pol.fired
        if pol.fired:
            # the advertised restart contract: a FRESH cache object,
            # rebuilt from empty (its contents now reflect only
            # post-restart donations, never pre-fault state)
            assert sup.engine.prefix_cache is not cache0, \
                "rebuilt engine carried the old prefix cache"
        for (p, n), h, want in zip(workload, handles, base):
            if not h.done():
                wedged += 1
                continue
            try:
                got = h.result().tokens
                assert np.array_equal(got, want), \
                    "warm/restarted token stream diverged"
                completed += 1
            except EngineFailedError:
                typed_failed += 1
        sup.close()

    restarts = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0) - restarts0
    report["serve_prefix"] = {
        "requests": 2 * len(workload),
        "completed_with_parity": completed,
        "typed_failures": typed_failed,
        "wedged_or_lost": wedged,
        "copy_faults_injected": injected,
        "engine_restarts": restarts,
    }
    assert wedged == 0, f"{wedged} requests wedged/lost"
    assert completed + typed_failed == 2 * len(workload)
    assert completed > 0 and injected > 0
    assert restarts == injected, \
        f"restarts ({restarts}) != injected copy faults ({injected})"


def chaos_spec(report):
    """A fault mid-verify against a SPECULATIVE engine
    (``serve.decode_step`` gates the whole spec step: draft scan +
    chunk verify + rejection sample): the engine fails TYPED, never
    wedges, the supervisor rebuilds it — fresh target AND draft
    arenas, every executable a jit cache hit — and requeued
    never-started requests stream byte-identically to an
    uninterrupted speculative run (which itself equals the
    non-speculative oracle)."""
    from singa_tpu import device, opt, tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.observe.registry import registry
    from singa_tpu.resilience import FailAfterN, faults
    from singa_tpu.serve import (EngineFailedError, EngineSupervisor,
                                 GenerationRequest)

    def train(cfg, seed, steps=12):
        device.get_default_device().SetRandSeed(seed)
        m = GPT2LMHead(cfg)
        rng = np.random.RandomState(0)
        motif = rng.randint(0, cfg.vocab_size, 8)
        ids = np.tile(motif, (4, 4)).astype(np.int32)[:, :32]
        noise = rng.randint(0, cfg.vocab_size, ids.shape)
        mask = rng.rand(*ids.shape) < 0.05
        ids[mask] = noise[mask]
        labels = np.roll(ids, -1, axis=1).astype(np.int32)
        m.set_optimizer(opt.Adam(lr=1e-3))
        m.compile([tensor.from_numpy(ids)], is_train=True,
                  use_graph=True)
        for _ in range(steps):
            m(tensor.from_numpy(ids), tensor.from_numpy(labels))
        m.eval()
        return m, ids

    target, ids = train(GPT2Config.tiny(dropout=0.0), seed=0)
    draft, _ = train(GPT2Config.tiny(dropout=0.0, n_layer=1), seed=1,
                     steps=8)

    rng = np.random.RandomState(5)
    workload = []
    for _ in range(10):
        plen = int(rng.randint(4, 13))
        row, off = int(rng.randint(0, 4)), int(rng.randint(0, 32 - 13))
        workload.append((np.asarray(ids[row, off:off + plen], np.int32),
                         int(rng.randint(3, 9))))
    base = [np.asarray(target.generate(p, max_new_tokens=n,
                                       temperature=0.0))
            for p, n in workload]

    injected = 0
    restarts0 = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0)
    completed = wedged = typed_failed = 0
    accepted = drafted = 0
    for fail_after in (2, 4):
        sup = EngineSupervisor(target, max_slots=2, restart_budget=2,
                               draft_model=draft, spec_k=3)
        handles = [sup.submit(GenerationRequest(
            p, max_new_tokens=n, temperature=0.0))
            for p, n in workload]
        pol = faults.inject("serve.decode_step",
                            FailAfterN(fail_after, times=1))
        sup.run_until_complete(max_steps=2000)
        faults.clear()
        injected += pol.fired
        spec = sup.engine.stats.snapshot()["spec"]
        accepted += spec["accepted"]
        drafted += spec["drafted"]
        for (p, n), h, want in zip(workload, handles, base):
            if not h.done():
                wedged += 1
                continue
            try:
                got = h.result().tokens
                assert np.array_equal(got, want), \
                    "speculative stream diverged after restart"
                completed += 1
            except EngineFailedError:
                typed_failed += 1
        sup.close()

    restarts = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0) - restarts0
    report["serve_spec"] = {
        "requests": 2 * len(workload),
        "completed_with_parity": completed,
        "typed_failures": typed_failed,
        "wedged_or_lost": wedged,
        "decode_faults_injected": injected,
        "engine_restarts": restarts,
        "acceptance_rate": accepted / drafted if drafted else None,
    }
    assert wedged == 0, f"{wedged} speculative requests wedged/lost"
    assert completed + typed_failed == 2 * len(workload)
    assert completed > 0 and typed_failed > 0
    assert restarts == injected > 0, \
        f"restarts ({restarts}) != injected spec-step faults ({injected})"
    assert report["serve_spec"]["acceptance_rate"] > 0


def chaos_paged(report):
    """A fault in the paged arena's copy path (``serve.paged_copy``
    fires in the admission scatter, the swap-out gather, and the
    swap-in restore): the engine fails TYPED mid-operation — the
    first injection lands on the first SWAP-OUT gather by
    construction (two admissions check the site once each, the next
    check is the preemption gather on this workload) — never wedges;
    the supervisor rebuild gets a FRESH pool (zero blocks used), and
    every request either completes with byte parity (requeued
    never-started work, swap/resume included post-restart) or fails
    typed started=True (live + swapped).  Zero wedged/lost,
    restarts == injected."""
    from singa_tpu import tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.observe.registry import registry
    from singa_tpu.resilience import FailAfterN, faults
    from singa_tpu.serve import (EngineFailedError, EngineSupervisor,
                                 GenerationRequest, PagedConfig)

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)

    rng = np.random.RandomState(7)
    # fixed 10-token prompts + 20-token budgets against a 6-block pool
    # of 8-token blocks: two live slots grow past the pool and the
    # growth self-swap fires every round
    workload = [(rng.randint(0, 256, 10).astype(np.int32), 20)
                for _ in range(8)]
    base = [np.asarray(m.generate(p, max_new_tokens=n, temperature=0.0))
            for p, n in workload]

    injected = 0
    restarts0 = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0)
    completed = wedged = typed_failed = 0
    preempted_total = 0
    # default PagedConfig kernel: the BLOCK-NATIVE decode path (the
    # gather-tax round) — the recovery invariants below therefore
    # cover the kernel, and the serve.paged_copy fault site still
    # fires on the admission scatter and the swap gather/scatter
    # (those copies kept their fixed-shape form; swap is off the hot
    # path — docs/SERVING.md)
    pcfg = PagedConfig(block_size=8, num_blocks=6)
    assert pcfg.kernel == "block"
    for fail_after in (2, 7):
        sup = EngineSupervisor(
            m, max_slots=2, restart_budget=2, paged=pcfg)
        arena0 = sup.engine.paged_arena
        handles = [sup.submit(GenerationRequest(
            p, max_new_tokens=n, temperature=0.0))
            for p, n in workload]
        pol = faults.inject("serve.paged_copy",
                            FailAfterN(fail_after, times=1))
        sup.run_until_complete(max_steps=4000)
        faults.clear()
        injected += pol.fired
        if pol.fired:
            assert sup.engine.paged_arena is not arena0, \
                "rebuilt engine carried the old paged arena"
        pg = sup.engine.stats.snapshot()["paged"]
        preempted_total += pg["preemptions"]
        assert pg["blocks_used"] == 0, \
            f"drained paged engine leaked {pg['blocks_used']} blocks"
        for (p, n), h, want in zip(workload, handles, base):
            if not h.done():
                wedged += 1
                continue
            try:
                got = h.result().tokens
                assert np.array_equal(got, want), \
                    "paged token stream diverged after restart"
                completed += 1
            except EngineFailedError:
                typed_failed += 1
        sup.close()

    restarts = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0) - restarts0
    report["serve_paged"] = {
        "requests": 2 * len(workload),
        "completed_with_parity": completed,
        "typed_failures": typed_failed,
        "wedged_or_lost": wedged,
        "copy_faults_injected": injected,
        "engine_restarts": restarts,
        "preemptions": preempted_total,
        "blocks_leaked": 0,
        "kernel": pcfg.kernel,
    }
    assert wedged == 0, f"{wedged} paged requests wedged/lost"
    assert completed + typed_failed == 2 * len(workload)
    assert completed > 0 and typed_failed > 0
    assert preempted_total > 0, "no preemption — the swap path was " \
        "not exercised"
    assert restarts == injected > 0, \
        f"restarts ({restarts}) != injected copy faults ({injected})"


def chaos_fork(report):
    """A fault on the copy-on-write block copy (``serve.fork_copy``
    fires inside ``PagedKVArena.copy_block`` when a forked branch
    first writes a sibling-shared block): the WRITING branch rejects
    typed and its private blocks return to the pool; siblings keep
    decoding to byte parity with the clean run; the ENGINE survives —
    the blast radius of a CoW fault is ONE branch, so unlike every
    other serve scenario here there is no supervisor restart to
    count (the bench asserts restarts stayed ZERO).  A fresh-pool
    rerun of the same family reproduces the clean streams, proving
    the fault never corrupted the shared prompt blocks."""
    from singa_tpu import tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.observe.registry import registry
    from singa_tpu.resilience import FailAfterN, FaultInjected, faults
    from singa_tpu.serve import GenerationRequest, PagedConfig

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)

    rng = np.random.RandomState(15)
    prompt = rng.randint(0, 256, 12).astype(np.int32)
    pcfg = PagedConfig(block_size=8, num_blocks=32)
    assert pcfg.kernel == "block"
    n_branches = 3

    def run(inject):
        eng = m.serve(max_slots=4, paged=pcfg)
        fh = eng.submit(GenerationRequest(
            prompt, max_new_tokens=16, temperature=0.9, seed=3,
            n=n_branches))
        pol = None
        if inject:
            # the FIRST CoW copy of the family fires the fault
            pol = faults.inject("serve.fork_copy",
                                FailAfterN(0, times=1))
        try:
            eng.run_until_complete(max_steps=4000)
        finally:
            faults.clear()
        outs = {}
        typed = 0
        for b in fh.branches:
            try:
                r = b.result()
                outs[r.branch] = r.tokens
            except FaultInjected as e:
                assert e.site == "serve.fork_copy", e.site
                typed += 1
        # the leak invariant: every pool block accounted after drain
        leaked = eng.check_block_accounting()
        eng.close()
        return outs, typed, (pol.fired if pol else 0), leaked

    restarts0 = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0)
    clean, typed0, _, leak0 = run(False)
    assert typed0 == 0 and len(clean) == n_branches
    faulted, typed, fired, leak1 = run(True)
    parity = sum(1 for b, toks in faulted.items()
                 if np.array_equal(toks, clean[b]))
    fresh, typed2, _, leak2 = run(False)
    fresh_parity = (typed2 == 0 and len(fresh) == n_branches
                    and all(np.array_equal(fresh[b], clean[b])
                            for b in fresh))
    restarts = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0) - restarts0

    report["serve_fork"] = {
        "requests": n_branches,
        "completed_with_parity": parity,
        "typed_failures": typed,
        "wedged_or_lost": n_branches - len(faulted) - typed,
        "cow_faults_injected": fired,
        "engine_restarts": restarts,
        "blocks_leaked": leak0 + leak1 + leak2,
        "fresh_pool_parity": bool(fresh_parity),
        "kernel": pcfg.kernel,
    }
    sf = report["serve_fork"]
    assert sf["wedged_or_lost"] == 0, "fork branches wedged/lost"
    assert sf["cow_faults_injected"] == 1 == sf["typed_failures"]
    assert sf["completed_with_parity"] == len(faulted) \
        == n_branches - 1, "a surviving sibling diverged"
    assert sf["engine_restarts"] == 0, \
        "a CoW fault must reject one branch, not restart the engine"
    assert sf["blocks_leaked"] == 0, sf["blocks_leaked"]
    assert sf["fresh_pool_parity"] is True


def chaos_tp(report):
    """A fault at the ``serve.tp_collective`` site (every sharded-twin
    dispatch of a tensor-parallel engine checks it) fires mid-decode:
    the sharded engine fails TYPED — never wedges — and the supervisor
    rebuilds it on the SAME device group (sharded-twin cache hit,
    fresh sharded arenas).  Requeued never-started streams keep byte
    parity with the uninterrupted single-device run; started requests
    fail typed.  Zero wedged/lost, restarts == injected."""
    from singa_tpu import tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.observe.registry import registry
    from singa_tpu.resilience import FailAfterN, faults
    from singa_tpu.serve import (EngineFailedError, EngineSupervisor,
                                 GenerationRequest)

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)

    rng = np.random.RandomState(11)
    workload = [(rng.randint(0, 256, rng.randint(4, 12))
                 .astype(np.int32), int(rng.randint(4, 10)))
                for _ in range(10)]
    base = [np.asarray(m.generate(p, max_new_tokens=n,
                                  temperature=0.0))
            for p, n in workload]

    injected = 0
    restarts0 = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0)
    completed = wedged = typed_failed = 0
    for fail_after in (4, 9):
        sup = EngineSupervisor(m, max_slots=2, restart_budget=2, tp=2)
        exec0 = sup.engine.tp_exec
        handles = [sup.submit(GenerationRequest(
            p, max_new_tokens=n, temperature=0.0))
            for p, n in workload]
        pol = faults.inject("serve.tp_collective",
                            FailAfterN(fail_after, times=1))
        sup.run_until_complete(max_steps=4000)
        faults.clear()
        injected += pol.fired
        if pol.fired:
            assert sup.engine.tp_exec is not exec0, \
                "rebuilt engine carried the failed TP executor"
            assert sup.engine.tp_exec.tp == 2
        for (p, n), h, want in zip(workload, handles, base):
            if not h.done():
                wedged += 1
                continue
            try:
                got = h.result().tokens
                assert np.array_equal(got, want), \
                    "TP token stream diverged after restart"
                completed += 1
            except EngineFailedError:
                typed_failed += 1
        sup.close()

    restarts = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0) - restarts0
    report["serve_tp"] = {
        "requests": 2 * len(workload),
        "shards": 2,
        "completed_with_parity": completed,
        "typed_failures": typed_failed,
        "wedged_or_lost": wedged,
        "collective_faults_injected": injected,
        "engine_restarts": restarts,
    }
    assert wedged == 0, f"{wedged} TP requests wedged/lost"
    assert completed + typed_failed == 2 * len(workload)
    assert completed > 0 and typed_failed > 0
    assert restarts == injected > 0, \
        f"restarts ({restarts}) != injected TP faults ({injected})"


def chaos_ep(report):
    """A fault at the ``serve.ep_dispatch`` site (every sharded-twin
    dispatch of an expert-parallel MoE engine checks it) fires
    mid-decode: the sharded engine fails TYPED — never wedges — and
    the supervisor rebuilds it on the SAME (ep, tp) device group
    (twin-cache hit, fresh sharded pool).  Requeued never-started
    streams keep byte parity with the uninterrupted single-device MoE
    run; started requests fail typed; the rebuilt engine's paged pool
    drains to ZERO used blocks.  Zero wedged/lost/leaked, restarts ==
    injected."""
    from singa_tpu import tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.observe.registry import registry
    from singa_tpu.resilience import FailAfterN, faults
    from singa_tpu.serve import (EngineFailedError, EngineSupervisor,
                                 GenerationRequest, PagedConfig)

    cfg = GPT2Config.tiny(dropout=0.0, moe_every=2, moe_experts=4)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)

    rng = np.random.RandomState(13)
    workload = [(rng.randint(0, 256, rng.randint(4, 12))
                 .astype(np.int32), int(rng.randint(4, 10)))
                for _ in range(10)]
    base = [np.asarray(m.generate(p, max_new_tokens=n,
                                  temperature=0.0))
            for p, n in workload]

    injected = 0
    restarts0 = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0)
    completed = wedged = typed_failed = leaked = 0
    expert_tokens_after = 0
    for fail_after in (4, 9):
        sup = EngineSupervisor(
            m, max_slots=2, restart_budget=2, ep=2,
            paged=PagedConfig(block_size=8, num_blocks=32))
        exec0 = sup.engine.ep_exec
        handles = [sup.submit(GenerationRequest(
            p, max_new_tokens=n, temperature=0.0))
            for p, n in workload]
        pol = faults.inject("serve.ep_dispatch",
                            FailAfterN(fail_after, times=1))
        sup.run_until_complete(max_steps=4000)
        faults.clear()
        injected += pol.fired
        if pol.fired:
            assert sup.engine.ep_exec is not exec0, \
                "rebuilt engine carried the failed EP executor"
            assert sup.engine.ep_exec.ep == 2
        if pol.fired:
            # the rebuilt engine kept routing: expert load flowed
            # after the restart (an imbalanced-router signal that
            # survives chaos is a working signal) — counted only for
            # iterations whose fault actually fired, so a
            # never-restarted run cannot mask a dead-router rebuild
            expert_tokens_after += sum(
                sup.engine.ep_exec.expert_tokens)
        leaked += sup.engine.paged_arena.blocks_used
        for (p, n), h, want in zip(workload, handles, base):
            if not h.done():
                wedged += 1
                continue
            try:
                got = h.result().tokens
                assert np.array_equal(got, want), \
                    "EP token stream diverged after restart"
                completed += 1
            except EngineFailedError:
                typed_failed += 1
        sup.close()

    restarts = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0) - restarts0
    report["serve_ep"] = {
        "requests": 2 * len(workload),
        "expert_shards": 2,
        "completed_with_parity": completed,
        "typed_failures": typed_failed,
        "wedged_or_lost": wedged,
        "blocks_leaked": int(leaked),
        "dispatch_faults_injected": injected,
        "engine_restarts": restarts,
        "expert_tokens_after_restart": int(expert_tokens_after),
    }
    assert wedged == 0, f"{wedged} EP requests wedged/lost"
    assert leaked == 0, f"{leaked} EP pool blocks leaked"
    assert completed + typed_failed == 2 * len(workload)
    assert completed > 0 and typed_failed > 0
    assert expert_tokens_after > 0
    assert restarts == injected > 0, \
        f"restarts ({restarts}) != injected EP faults ({injected})"


def chaos_pp(report):
    """A fault at the ``serve.pp_boundary`` site (every sharded
    dispatch of a pipeline-parallel engine checks it — a raising
    stage-boundary hop) fires mid-decode: the pipelined engine fails
    TYPED — never wedges — and the supervisor rebuilds it on the SAME
    stage group (twin-cache hit, fresh stage-sliced pool).  Requeued
    never-started streams keep byte parity with the uninterrupted
    single-device paged run; started requests fail typed; the rebuilt
    pool drains to ZERO used blocks.  Zero wedged/lost/leaked,
    restarts == injected."""
    from singa_tpu import tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.observe.registry import registry
    from singa_tpu.resilience import FailAfterN, faults
    from singa_tpu.serve import (EngineFailedError, EngineSupervisor,
                                 GenerationRequest, PagedConfig)

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)

    rng = np.random.RandomState(17)
    workload = [(rng.randint(0, 256, rng.randint(4, 12))
                 .astype(np.int32), int(rng.randint(4, 10)))
                for _ in range(10)]
    base = [np.asarray(m.generate(p, max_new_tokens=n,
                                  temperature=0.0))
            for p, n in workload]

    injected = 0
    restarts0 = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0)
    completed = wedged = typed_failed = leaked = 0
    for fail_after in (4, 9):
        sup = EngineSupervisor(
            m, max_slots=2, restart_budget=2, pp=2,
            paged=PagedConfig(block_size=8, num_blocks=32))
        exec0 = sup.engine.pp_exec
        handles = [sup.submit(GenerationRequest(
            p, max_new_tokens=n, temperature=0.0))
            for p, n in workload]
        pol = faults.inject("serve.pp_boundary",
                            FailAfterN(fail_after, times=1))
        sup.run_until_complete(max_steps=4000)
        faults.clear()
        injected += pol.fired
        if pol.fired:
            assert sup.engine.pp_exec is not exec0, \
                "rebuilt engine carried the failed PP executor"
            assert sup.engine.pp_exec.stages == 2
        leaked += sup.engine.paged_arena.blocks_used
        for (p, n), h, want in zip(workload, handles, base):
            if not h.done():
                wedged += 1
                continue
            try:
                got = h.result().tokens
                assert np.array_equal(got, want), \
                    "PP token stream diverged after restart"
                completed += 1
            except EngineFailedError:
                typed_failed += 1
        sup.close()

    restarts = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0) - restarts0
    report["serve_pp"] = {
        "requests": 2 * len(workload),
        "stages": 2,
        "completed_with_parity": completed,
        "typed_failures": typed_failed,
        "wedged_or_lost": wedged,
        "blocks_leaked": int(leaked),
        "boundary_faults_injected": injected,
        "engine_restarts": restarts,
    }
    assert wedged == 0, f"{wedged} PP requests wedged/lost"
    assert leaked == 0, f"{leaked} PP pool blocks leaked"
    assert completed + typed_failed == 2 * len(workload)
    assert completed > 0 and typed_failed > 0
    assert restarts == injected > 0, \
        f"restarts ({restarts}) != injected PP faults ({injected})"


def chaos_longctx(report):
    """A fault BETWEEN budgeted prefill chunks (the
    ``serve.prefill_chunk`` site, armed while a 72-token admission is
    mid-split under ``prefill_token_budget``): the engine fails TYPED
    mid-prefill — the chunked request has streamed NOTHING, so it
    rejects requeue-safe and the supervisor replays it to byte parity
    on the rebuilt engine; the partial chunks' blocks return to the
    free list (zero leaked on the failed engine AND zero on the
    drained rebuild).  Zero wedged/lost, restarts == injected."""
    from singa_tpu import tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.observe.registry import registry
    from singa_tpu.resilience import FailAfterN, faults
    from singa_tpu.serve import (EngineFailedError, EngineSupervisor,
                                 GenerationRequest, PagedConfig)

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)

    rng = np.random.RandomState(9)
    # one long document + chat tails: the long admission's 9 chunks
    # (72 tokens at an 8-token budget) are where the fault lands
    workload = [(rng.randint(0, 256, 72).astype(np.int32), 3)] + \
        [(rng.randint(0, 256, rng.randint(4, 10)).astype(np.int32),
          int(rng.randint(3, 7))) for _ in range(5)]
    base = [np.asarray(m.generate(p, max_new_tokens=n,
                                  temperature=0.0))
            for p, n in workload]

    pcfg = PagedConfig(block_size=8, num_blocks=32,
                       prefill_token_budget=8)
    injected = 0
    restarts0 = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0)
    completed = wedged = typed_failed = 0
    for fail_after in (3, 6):
        sup = EngineSupervisor(m, max_slots=3, restart_budget=2,
                               paged=pcfg)
        arena0 = sup.engine.paged_arena
        handles = [sup.submit(GenerationRequest(
            p, max_new_tokens=n, temperature=0.0))
            for p, n in workload]
        pol = faults.inject("serve.prefill_chunk",
                            FailAfterN(fail_after, times=1))
        sup.run_until_complete(max_steps=4000)
        faults.clear()
        injected += pol.fired
        if pol.fired:
            assert sup.engine.paged_arena is not arena0, \
                "rebuilt engine carried the old paged arena"
            assert arena0.blocks_used == 0, \
                f"failed engine leaked {arena0.blocks_used} blocks " \
                f"behind partial prefill chunks"
        pg = sup.engine.stats.snapshot()["paged"]
        assert pg["blocks_used"] == 0, \
            f"drained longctx engine leaked {pg['blocks_used']} blocks"
        for (p, n), h, want in zip(workload, handles, base):
            if not h.done():
                wedged += 1
                continue
            try:
                got = h.result().tokens
                assert np.array_equal(got, want), \
                    "budgeted-prefill stream diverged after restart"
                completed += 1
            except EngineFailedError:
                typed_failed += 1
        sup.close()

    restarts = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0) - restarts0
    report["serve_longctx"] = {
        "requests": 2 * len(workload),
        "completed_with_parity": completed,
        "typed_failures": typed_failed,
        "wedged_or_lost": wedged,
        "chunk_faults_injected": injected,
        "engine_restarts": restarts,
        "blocks_leaked": 0,
        "prefill_token_budget": pcfg.prefill_token_budget,
    }
    assert wedged == 0, f"{wedged} longctx requests wedged/lost"
    assert completed + typed_failed == 2 * len(workload)
    assert completed > 0
    assert restarts == injected > 0, \
        f"restarts ({restarts}) != injected chunk faults ({injected})"


def chaos_fleet(report):
    """Kill one replica mid-decode (``serve.decode_step`` fault against
    a zero restart budget): the fleet marks it unhealthy, requeues its
    never-started requests onto the survivor in arrival order (token-
    stream parity vs an uninterrupted single-engine run), started
    requests fail typed, the fleet KEEPS SERVING on the survivor — and
    the jit cache stays pinned at zero runtime recompiles across the
    failover (replicas share every executable)."""
    from bench_serve import _serve_jit_cache_size
    from singa_tpu import observe, tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.resilience import FailAfterN, faults
    from singa_tpu.serve import (EngineFailedError, GenerationRequest,
                                 ServeFleet)

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)

    rng = np.random.RandomState(3)
    workload = [(rng.randint(0, 256, rng.randint(3, 12)).astype(np.int32),
                 int(rng.randint(3, 8))) for _ in range(12)]
    extra = [(rng.randint(0, 256, rng.randint(3, 10)).astype(np.int32),
              int(rng.randint(2, 6))) for _ in range(4)]
    base = [np.asarray(m.generate(p, max_new_tokens=n, temperature=0.0))
            for p, n in workload]
    base_extra = [np.asarray(m.generate(p, max_new_tokens=n,
                                        temperature=0.0))
                  for p, n in extra]

    def build():
        return ServeFleet(m, replicas=2, max_slots=2, restart_budget=0)

    # warmup: compile every executable the fleet dispatches, then pin
    # the jit cache across the whole chaos run
    fleet = build()
    for p, n in workload:
        fleet.submit(GenerationRequest(p, max_new_tokens=n))
    fleet.run_until_complete(max_steps=4000)
    fleet.close()
    jit0 = _serve_jit_cache_size()

    fleet = build()
    handles = [fleet.submit(GenerationRequest(
        p, max_new_tokens=n, temperature=0.0)) for p, n in workload]
    pol = faults.inject("serve.decode_step", FailAfterN(4, times=1))
    fleet.run_until_complete(max_steps=4000)
    faults.clear()

    completed = wedged = typed_failed = 0
    for (p, n), h, want in zip(workload, handles, base):
        if not h.done():
            wedged += 1
            continue
        try:
            got = h.result().tokens
            assert np.array_equal(got, want), \
                "token stream diverged across the failover"
            completed += 1
        except EngineFailedError:
            typed_failed += 1
    snap = fleet.snapshot()

    # service-level availability: the survivor keeps admitting and
    # completing new work after the failover
    hs2 = [fleet.submit(GenerationRequest(
        p, max_new_tokens=n, temperature=0.0)) for p, n in extra]
    fleet.run_until_complete(max_steps=2000)
    post_completed = sum(
        bool(np.array_equal(h.result().tokens, want))
        for h, want in zip(hs2, base_extra))
    jit1 = _serve_jit_cache_size()

    # the fleet health section reflects the failover BEFORE close
    # unregisters this fleet's metrics
    h_fleet = observe.health_report(
        include_registry=False)["serve"]["fleet"]
    assert h_fleet["failovers"] >= 1 and h_fleet["requeues"] >= 1
    assert h_fleet["replicas_healthy"] == 1
    fleet.close()

    report["serve_fleet"] = {
        "replicas": 2,
        "requests": len(workload),
        "completed_with_parity": completed,
        "typed_failures": typed_failed,
        "wedged_or_lost": wedged,
        "decode_faults_injected": pol.fired,
        "failovers": snap["failovers"],
        "requeues": snap["requeues"],
        "replicas_healthy_after": snap["replicas_healthy"],
        "post_failover_requests": len(extra),
        "post_failover_completed": post_completed,
        "recompiles": (None if jit0 is None else jit1 - jit0),
    }
    sf = report["serve_fleet"]
    assert wedged == 0, f"{wedged} requests wedged/lost"
    assert completed + typed_failed == len(workload)
    assert completed > 0 and typed_failed > 0
    assert sf["decode_faults_injected"] == 1 and sf["failovers"] == 1
    assert sf["requeues"] >= 1, "no never-started work moved — the " \
        "failover path was not exercised"
    assert sf["replicas_healthy_after"] == 1
    assert post_completed == len(extra), \
        "survivor stopped serving after the failover"
    assert sf["recompiles"] in (0, None), sf["recompiles"]


def chaos_disagg(report):
    """Disaggregated fleet under fire, two scenarios on a
    2-replica prefill/decode fleet:

    (a) an injected ``serve.kv_ship`` fault mid-transfer — the ship
        aborts, the request is requeued COLD onto the decode replica
        (byte parity: nothing streamed during a ship), zero leaked
        blocks on either replica, both replicas stay healthy (a ship
        fault is a transfer failure, not an engine death);
    (b) a ``serve.prefill_chunk`` fault with a ZERO restart budget
        KILLS the prefill specialist mid-build — the fleet fails it
        over, the mid-ship request (and everything queued) completes
        cold on the decode replica with parity, the dead arena holds
        zero blocks behind the partial build.

    Zero wedged/lost across both."""
    from singa_tpu import tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.resilience import FailOnce, faults
    from singa_tpu.serve import (GenerationRequest, PagedConfig,
                                 PrefixCacheConfig, ServeFleet)

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)

    rng = np.random.RandomState(15)
    workload = [(rng.randint(0, 256, 48).astype(np.int32), 3)] + \
        [(rng.randint(0, 256, rng.randint(3, 7)).astype(np.int32),
          int(rng.randint(2, 5))) for _ in range(4)]
    base = [np.asarray(m.generate(p, max_new_tokens=n,
                                  temperature=0.0))
            for p, n in workload]
    kw = dict(roles=("prefill", "decode"), max_slots=2,
              paged=PagedConfig(block_size=8, num_blocks=48),
              prefix_cache=PrefixCacheConfig(block_size=8))

    def run(site, restart_budget):
        fleet = ServeFleet(m, replicas=2, restart_budget=restart_budget,
                           **kw)
        pol = faults.inject(site, FailOnce())
        handles = [fleet.submit(GenerationRequest(
            p, max_new_tokens=n, temperature=0.0))
            for p, n in workload]
        fleet.run_until_complete(max_steps=800)
        faults.clear()
        completed = wedged = 0
        for h, want in zip(handles, base):
            if not h.done():
                wedged += 1
                continue
            got = h.result().tokens
            assert np.array_equal(got, want), \
                "disagg stream diverged across the fault"
            completed += 1
        leaked = sum(
            fleet.supervisor(i).engine.paged_arena.blocks_used
            - fleet.supervisor(i).engine.prefix_cache.cached_blocks
            for i in range(2)
            if not fleet.supervisor(i).engine._closed)
        snap = fleet.snapshot()
        arena0 = fleet.supervisor(0).engine.paged_arena
        fleet.close()
        return pol.fired, completed, wedged, leaked, snap, arena0

    # (a) mid-transfer ship fault: cold requeue, nobody dies
    ship_fired, comp_a, wedged_a, leak_a, snap_a, _ = run(
        "serve.kv_ship", restart_budget=2)
    assert snap_a["replicas_healthy"] == 2
    assert snap_a["ship_fallbacks"] >= 1
    # (b) specialist killed mid-build: failover, cold completion
    chunk_fired, comp_b, wedged_b, leak_b, snap_b, arena0 = run(
        "serve.prefill_chunk", restart_budget=0)
    assert snap_b["replicas_healthy"] == 1
    assert snap_b["failovers"] == 1
    assert arena0.blocks_used == 0, \
        f"dead specialist leaked {arena0.blocks_used} blocks"

    report["serve_disagg"] = {
        "requests": 2 * len(workload),
        "completed_with_parity": comp_a + comp_b,
        "wedged_or_lost": wedged_a + wedged_b,
        "ship_faults_injected": ship_fired,
        "chunk_faults_injected": chunk_fired,
        "failovers": snap_b["failovers"],
        "ship_fallbacks": (snap_a["ship_fallbacks"]
                           + snap_b["ship_fallbacks"]),
        "blocks_leaked": leak_a + leak_b,
    }
    sd = report["serve_disagg"]
    assert sd["wedged_or_lost"] == 0, \
        f"{sd['wedged_or_lost']} disagg requests wedged/lost"
    assert sd["completed_with_parity"] == sd["requests"]
    assert sd["ship_faults_injected"] == 1
    assert sd["chunk_faults_injected"] == 1
    assert sd["blocks_leaked"] == 0, sd["blocks_leaked"]


def _dist_model_spec():
    from singa_tpu import tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.serve import gpt2_spec

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    return m, gpt2_spec(m)


def _dist_leaks(fleet):
    """Wire-level leak count: the step reply mirrors blocks_used AND
    cached_blocks parent-side, so the invariant is checkable without
    reaching into worker engines."""
    total = 0
    for i in range(fleet.replicas):
        eng = fleet.supervisor(i).engine
        if eng._closed or eng.paged_arena is None:
            continue
        total += (eng.paged_arena.blocks_used
                  - eng.prefix_cache.cached_blocks)
    return total


def chaos_dist_partition(report):
    """A PARTITIONED peer mid-decode (the dist round): the injected
    ``serve.dist.rpc`` fault fires on a step RPC exactly where a real
    network split would — the peer is marked down through the same
    PeerGone -> failover path, never-started work requeues onto the
    survivor with byte parity (nothing had streamed), and the
    role-aware autoscaler's ``replace_dead`` heals the fleet back to
    width with a FRESH worker that then serves traffic.  Workers run
    as threads here (same wire protocol and fault sites as processes;
    the chaos matrix stays bounded-time)."""
    from singa_tpu.resilience import FailOnce, faults
    from singa_tpu.serve import DistFleet, GenerationRequest
    from singa_tpu.serve.autoscale import AutoscaleConfig, Autoscaler

    m, spec = _dist_model_spec()
    rng = np.random.RandomState(21)
    workload = [(rng.randint(0, 256, rng.randint(3, 7)).astype(np.int32),
                 int(rng.randint(2, 5))) for _ in range(5)]
    base = [np.asarray(m.generate(p, max_new_tokens=n,
                                  temperature=0.0))
            for p, n in workload]

    fleet = DistFleet(spec, replicas=2, spawn="thread", max_slots=2)
    pol = faults.inject("serve.dist.rpc", FailOnce())
    handles = [fleet.submit(GenerationRequest(
        p, max_new_tokens=n, temperature=0.0))
        for p, n in workload]
    fleet.run_until_complete(max_steps=800)
    faults.clear()
    completed = wedged = 0
    for h, want in zip(handles, base):
        if not h.done():
            wedged += 1
            continue
        assert np.array_equal(h.result().tokens, want), \
            "dist stream diverged across the partition"
        completed += 1
    snap = fleet.snapshot()
    assert snap["replicas_healthy"] == 1, snap["replicas_healthy"]
    assert snap["failovers"] >= 1

    # the autoscaler replaces the dead peer on its next check, and
    # the fresh worker serves
    sc = Autoscaler(fleet, AutoscaleConfig(
        min_replicas=2, max_replicas=2,
        scale_up_cooldown_s=0.0, scale_down_cooldown_s=0.0))
    ev = sc.check()
    assert ev is not None and ev["action"] == "replace_dead", ev
    assert fleet.healthy_replicas == 2
    post = [fleet.submit(GenerationRequest(
        p, max_new_tokens=n, temperature=0.0))
        for p, n in workload[:3]]
    fleet.run_until_complete(max_steps=400)
    post_done = sum(
        1 for h, want in zip(post, base)
        if h.done() and np.array_equal(h.result().tokens, want))
    fleet.close()

    report["serve_dist_partition"] = {
        "replicas": 2,
        "requests": len(workload),
        "completed_with_parity": completed,
        "wedged_or_lost": wedged,
        "rpc_faults_injected": pol.fired,
        "failovers": snap["failovers"],
        "requeues": snap["requeues"],
        "replaced_dead": 1,
        "replicas_healthy_after": 2,
        "post_heal_requests": len(post),
        "post_heal_completed": post_done,
    }
    d = report["serve_dist_partition"]
    assert d["wedged_or_lost"] == 0, d
    assert d["completed_with_parity"] == d["requests"], d
    assert d["rpc_faults_injected"] == 1, d
    assert d["post_heal_completed"] == d["post_heal_requests"], d


def chaos_dist_halfship(report):
    """A HALF-SHIPPED image (the dist round): the transport dies
    between layers of a streamed cross-host ship — the injected
    ``serve.dist.frame`` fault fires mid-relay, the destination's
    staging buffer is aborted (typed, never admitted), the request
    falls back to a cold serve with byte parity, neither peer is
    condemned, and a LATER ship on the same fleet still streams
    clean.  Zero leaked blocks on both sides."""
    from singa_tpu.resilience import FailOnce, faults
    from singa_tpu.serve import (DistFleet, GenerationRequest,
                                 PagedConfig, PrefixCacheConfig)

    m, spec = _dist_model_spec()
    rng = np.random.RandomState(22)
    workload = [(rng.randint(0, 256, 48).astype(np.int32), 3),
                (rng.randint(0, 256, 48).astype(np.int32), 3)] + \
        [(rng.randint(0, 256, rng.randint(3, 7)).astype(np.int32),
          int(rng.randint(2, 5))) for _ in range(2)]
    base = [np.asarray(m.generate(p, max_new_tokens=n,
                                  temperature=0.0))
            for p, n in workload]

    fleet = DistFleet(
        spec, replicas=2, spawn="thread",
        roles=("prefill", "decode"), max_slots=2,
        paged=PagedConfig(block_size=8, num_blocks=48),
        prefix_cache=PrefixCacheConfig(block_size=8))
    pol = faults.inject("serve.dist.frame", FailOnce())
    handles = [fleet.submit(GenerationRequest(
        p, max_new_tokens=n, temperature=0.0))
        for p, n in workload]
    fleet.run_until_complete(max_steps=800)
    faults.clear()
    completed = wedged = 0
    for h, want in zip(handles, base):
        if not h.done():
            wedged += 1
            continue
        assert np.array_equal(h.result().tokens, want), \
            "dist stream diverged across the half-ship"
        completed += 1
    snap = fleet.snapshot()
    leaked = _dist_leaks(fleet)
    fleet.close()

    report["serve_dist_halfship"] = {
        "replicas": 2,
        "requests": len(workload),
        "completed_with_parity": completed,
        "wedged_or_lost": wedged,
        "frame_faults_injected": pol.fired,
        "ship_fallbacks": snap["ship_fallbacks"],
        "frames_relayed": snap["dist"]["frames"],
        "replicas_healthy_after": snap["replicas_healthy"],
        "blocks_leaked": leaked,
    }
    d = report["serve_dist_halfship"]
    assert d["wedged_or_lost"] == 0, d
    assert d["completed_with_parity"] == d["requests"], d
    assert d["frame_faults_injected"] == 1, d
    assert d["ship_fallbacks"] >= 1, d
    assert d["replicas_healthy_after"] == 2, d
    assert d["frames_relayed"] > 0, \
        "the post-fault ship never streamed — the fleet stayed cold"
    assert d["blocks_leaked"] == 0, d


def chaos_dist_blip(report):
    """A transient NETWORK BLIP mid-decode (the recover round): the
    controller-side socket is severed without the worker knowing — the
    worker redials with full-jitter backoff, the session RESUMES
    inside the reconnect window (same seq space, same epoch), and the
    one in-flight step CALL replays exactly-once against the worker's
    reply cache.  The hard numbers: ZERO failovers, ZERO requeues,
    ZERO respawns — the fleet never even noticed at the routing layer
    — and every stream is byte-identical to the single-model oracle."""
    from singa_tpu.serve import DistFleet, GenerationRequest

    m, spec = _dist_model_spec()
    rng = np.random.RandomState(23)
    workload = [(rng.randint(0, 256, rng.randint(3, 7)).astype(np.int32),
                 int(rng.randint(3, 6))) for _ in range(5)]
    base = [np.asarray(m.generate(p, max_new_tokens=n,
                                  temperature=0.0))
            for p, n in workload]

    fleet = DistFleet(spec, replicas=2, spawn="thread", max_slots=2)
    handles = [fleet.submit(GenerationRequest(
        p, max_new_tokens=n, temperature=0.0))
        for p, n in workload]
    for _ in range(3):
        fleet.step()           # decode is genuinely mid-flight
    fleet.blip_worker(0)
    fleet.run_until_complete(max_steps=800)
    completed = wedged = 0
    for h, want in zip(handles, base):
        if not h.done():
            wedged += 1
            continue
        assert np.array_equal(h.result().tokens, want), \
            "dist stream diverged across the blip"
        completed += 1
    snap = fleet.snapshot()
    respawns = sum(fleet.supervisor(i).restarts
                   for i in range(fleet.replicas))
    fleet.close()

    report["serve_dist_blip"] = {
        "replicas": 2,
        "requests": len(workload),
        "completed_with_parity": completed,
        "wedged_or_lost": wedged,
        "reconnects": snap["dist"]["reconnects"],
        "resumed_calls": snap["dist"]["resumed_calls"],
        "epoch": snap["dist"]["epoch"],
        "failovers": snap["failovers"],
        "requeues": snap["requeues"],
        "respawns": respawns,
        "replicas_healthy_after": snap["replicas_healthy"],
    }
    d = report["serve_dist_blip"]
    assert d["wedged_or_lost"] == 0, d
    assert d["completed_with_parity"] == d["requests"], d
    assert d["reconnects"] >= 1, d
    assert d["resumed_calls"] >= 1, d
    assert d["epoch"] == 1, d              # a resume, not an adoption
    assert d["failovers"] == 0, d
    assert d["requeues"] == 0, d
    assert d["respawns"] == 0, d
    assert d["replicas_healthy_after"] == 2, d


def chaos_dist_controller(report):
    """CONTROLLER CRASH + fenced adoption (the recover round's
    tentpole): the controller dies mid-flight with every request still
    decoding — no shutdown RPCs, no drains.  The orphaned workers keep
    stepping, journal progress, and redial; a successor controller
    ADOPTS them at their old address — fencing epoch bumped to 2 (the
    dead controller is refused typed on every op from that moment),
    journals reconciled (live work re-attached, parked results
    claimed exactly-once, never-started work requeued in arrival
    order, nothing rejected), and routing resumes against engines that
    were NEVER rebuilt.  The hard numbers: zero lost tokens, zero
    duplicated tokens (byte parity per request), zero wedged, zero
    recompiles (the jit cache is the same size after adoption — warm
    engines survived the controller)."""
    from singa_tpu.serve import DistFleet, GenerationRequest
    from singa_tpu.serve.jitpin import jit_cache_size

    m, spec = _dist_model_spec()
    rng = np.random.RandomState(24)
    workload = [(rng.randint(0, 256, rng.randint(3, 7)).astype(np.int32),
                 int(rng.randint(4, 7))) for _ in range(5)]
    base = [np.asarray(m.generate(p, max_new_tokens=n,
                                  temperature=0.0))
            for p, n in workload]

    A = DistFleet(spec, replicas=2, spawn="thread", max_slots=2)
    port, token = A._listener.port, A._token
    handles = [A.submit(GenerationRequest(
        p, max_new_tokens=n, temperature=0.0))
        for p, n in workload]
    for _ in range(2):
        A.step()
    assert not any(h.done() for h in handles), \
        "crash must land mid-flight for the scenario to mean anything"
    jit_before = jit_cache_size()
    A.crash()

    B = DistFleet.adopt(spec, port=port, token=token, replicas=2,
                        spawn="thread", max_slots=2)
    rep = B.adoption
    assert rep["rejected"] == {}, rep["rejected"]
    adopted = dict(rep["resumed"])
    adopted.update(rep["delivered"])
    adopted.update(rep["requeued"])
    B.run_until_complete(max_steps=800)
    completed = wedged = 0
    for h, want in zip(handles, base):
        rid = h.request.request_id
        bh = adopted.get(rid)
        if bh is None or not bh.done():
            wedged += 1
            continue
        # byte parity == zero lost AND zero duplicated tokens: any
        # replayed decode step would append a duplicate, any dropped
        # parked result would truncate the stream
        assert np.array_equal(bh.result().tokens, want), \
            "dist stream diverged across the controller adoption"
        completed += 1
    snap = B.snapshot()
    recompiles = jit_cache_size() - jit_before
    B.close()

    report["serve_dist_controller"] = {
        "replicas": 2,
        "requests": len(workload),
        "completed_with_parity": completed,
        "wedged_or_lost": wedged,
        "adopted_resumed": len(rep["resumed"]),
        "adopted_delivered": len(rep["delivered"]),
        "adopted_requeued": len(rep["requeued"]),
        "adopted_rejected": len(rep["rejected"]),
        "parked_results": snap["dist"]["parked_results"],
        "epoch_after": snap["dist"]["epoch"],
        "recompiles": recompiles,
        "replicas_healthy_after": snap["replicas_healthy"],
    }
    d = report["serve_dist_controller"]
    assert d["wedged_or_lost"] == 0, d
    assert d["completed_with_parity"] == d["requests"], d
    assert (d["adopted_resumed"] + d["adopted_delivered"]
            + d["adopted_requeued"]) == d["requests"], d
    assert d["adopted_rejected"] == 0, d
    assert d["epoch_after"] == 2, d
    assert d["recompiles"] == 0, \
        f"adoption recompiled {d['recompiles']} entries — the warm " \
        f"engines were not actually adopted"
    assert d["replicas_healthy_after"] == 2, d


def chaos_autoscale(report):
    """Fault the ``serve.autoscale`` site mid-scale-up (the autoscale
    round): the scaling DECISION aborts typed — ledger records
    ``scale_up_failed``, no half-registered replica exists (replica
    count and fleet counter families unchanged), the fleet keeps
    serving on its existing replica — and the next check simply
    retries and succeeds.  After the burst drains, the autoscaler
    drains the spare replica back down and the retired engine's
    ``serve.*{engine=n}`` series leave the registry (the scale-down
    leaked-gauge audit, same hazard class as the EP/PP refusal
    audits)."""
    from singa_tpu import observe, tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.resilience import FailOnce, faults
    from singa_tpu.serve import (AutoscaleConfig, Autoscaler,
                                 GenerationRequest, ServeFleet)

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)

    rng = np.random.RandomState(21)
    workload = [(rng.randint(0, 256, rng.randint(3, 12)).astype(np.int32),
                 int(rng.randint(3, 7))) for _ in range(12)]
    base = [np.asarray(m.generate(p, max_new_tokens=n, temperature=0.0))
            for p, n in workload]

    T = [0.0]
    fleet = ServeFleet(m, replicas=1, max_slots=2,
                       clock=lambda: T[0])
    sc = Autoscaler(fleet, AutoscaleConfig(
        min_replicas=1, max_replicas=2, scale_up_cooldown_s=1.0,
        scale_down_cooldown_s=2.0, queue_high=2.0, queue_low=0.5,
        occupancy_high=0.95, occupancy_low=0.45),
        clock=lambda: T[0])
    handles = [fleet.submit(GenerationRequest(
        p, max_new_tokens=n, temperature=0.0)) for p, n in workload]

    def fleet_counter_sets():
        snap = observe.registry().snapshot()
        return sorted(
            k for k in snap["counters"]
            if k.startswith("serve.fleet.routed{")
            and f"fleet={fleet.fleet_label}" in k)

    counters_before = fleet_counter_sets()
    pol = faults.inject("serve.autoscale", FailOnce())
    ev1 = sc.check()
    assert ev1 is not None and ev1["action"] == "scale_up_failed", ev1
    assert pol.fired == 1
    # no half-registered replica: same replica count, same fleet
    # counter families, the lone replica still serving
    assert fleet.replicas == 1
    assert fleet_counter_sets() == counters_before
    for _ in range(3):
        fleet.step()
    T[0] += 0.5
    ev2 = sc.check()  # the retry: no cooldown was spent on the abort
    assert ev2 is not None and ev2["action"] == "scale_up", ev2
    faults.clear()
    assert fleet.replicas == 2

    while fleet.pending:
        fleet.step()
        T[0] += 0.5
        sc.check()
    completed = sum(
        bool(np.array_equal(h.result().tokens, want))
        for h, want in zip(handles, base))
    wedged = sum(1 for h in handles if not h.done())

    # all-quiet: the spare replica drains and retires (the decision
    # ledger is the evidence — the drain may already have completed
    # during the serving loop's checks)
    for _ in range(16):
        if any(e["action"] == "drain_done"
               for e in sc.scaling_events):
            break
        T[0] += 1.0
        sc.check()
    assert any(e["action"] == "drain_done"
               for e in sc.scaling_events), \
        [e["action"] for e in sc.scaling_events]
    retired = [r for r in fleet._replicas if r.retired]
    assert len(retired) == 1
    # leaked-gauge audit: the retired engine's label series must be
    # GONE from the registry, not frozen at their last values
    lbl = f"engine={retired[0].sup.engine.stats.engine_label}"
    snap = observe.registry().snapshot()
    leaked = [k for sec in snap.values() for k in sec if lbl in k]
    assert not leaked, leaked
    actions = [e["action"] for e in sc.scaling_events]
    sc.close()
    fleet.close()

    report["serve_autoscale"] = {
        "requests": len(workload),
        "completed_with_parity": completed,
        "wedged_or_lost": wedged,
        "autoscale_faults_injected": pol.fired,
        "decisions_failed": 1,
        "scale_ups": actions.count("scale_up"),
        "scale_downs": actions.count("drain_done"),
        "actions": actions,
        "leaked_series": len(leaked),
    }
    sa = report["serve_autoscale"]
    assert sa["wedged_or_lost"] == 0, sa
    assert sa["completed_with_parity"] == len(workload), sa
    assert sa["autoscale_faults_injected"] == 1
    assert sa["scale_ups"] == 1 and sa["scale_downs"] == 1


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="CHAOS.json", metavar="PATH",
                    help="where to write the strict-JSON chaos report")
    args = ap.parse_args()

    # chaos_tp needs a >=2-device mesh before jax initializes; the
    # flag only affects the CPU platform (tests/conftest.py topology)
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    from singa_tpu import observe

    # the whole chaos run is monitored: recovery that hangs is failure
    observe.monitor.start(watchdog_timeout_s=900.0, crash_handler=True)
    report = {"bench": "chaos_resilience", "schema": "singa_tpu.chaos/1"}
    chaos_checkpoint(report)
    chaos_collective(report)
    chaos_serve(report)
    chaos_prefix(report)
    chaos_spec(report)
    chaos_paged(report)
    chaos_fork(report)
    chaos_longctx(report)
    chaos_tp(report)
    chaos_ep(report)
    chaos_pp(report)
    chaos_fleet(report)
    chaos_disagg(report)
    chaos_dist_partition(report)
    chaos_dist_halfship(report)
    chaos_dist_blip(report)
    chaos_dist_controller(report)
    chaos_autoscale(report)

    health = observe.health_report(include_registry=False)
    report["health"] = health
    assert health["watchdog"]["hangs"] == 0, "chaos run tripped the " \
        "hang watchdog — recovery wedged somewhere"
    assert health["resilience"]["engine_restarts"] >= \
        report["serve"]["engine_restarts"]
    observe.monitor.stop()

    line = json.dumps(observe.export.json_sanitize(report),
                      default=str, allow_nan=False)
    print(line)
    with open(args.out, "w") as f:
        f.write(line + "\n")


if __name__ == "__main__":
    main()
