"""bf16 mixed-precision policy tests (singa_tpu.amp).

The reference has no compute-precision policy (fp16 exists only on the
gradient wire, SURVEY.md §2.1 Communicator row); amp is the TPU-native
extension: bf16 MXU compute, fp32 master params, fp32 statistics.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from singa_tpu import amp, autograd, device as device_module, opt, tensor
from singa_tpu.models.cnn import CNN


@pytest.fixture
def dev():
    d = device_module.get_default_device()
    d.SetRandSeed(0)
    return d


@pytest.fixture
def bf16():
    amp.enable()
    try:
        yield
    finally:
        amp.enable(False)


def test_policy_flag_roundtrip():
    assert not amp.enabled()
    amp.enable()
    assert amp.enabled() and amp.compute_dtype() == jnp.bfloat16
    amp.enable(False)
    assert not amp.enabled() and amp.compute_dtype() is None


def test_matmul_runs_bf16_params_stay_fp32(dev, bf16):
    a = tensor.from_numpy(np.ones((4, 8), np.float32), dev)
    b = tensor.from_numpy(np.ones((8, 2), np.float32), dev)
    y = autograd.matmul(a, b)
    assert y.data.dtype == jnp.bfloat16
    assert a.data.dtype == jnp.float32  # inputs untouched


@pytest.mark.slow
def test_cnn_trains_one_step_bf16(dev, bf16):
    m = CNN(num_classes=10, num_channels=1)
    sgd = opt.SGD(lr=0.01, momentum=0.9)
    m.set_optimizer(sgd)
    rng = np.random.RandomState(0)
    x = tensor.from_numpy(rng.randn(4, 1, 28, 28).astype(np.float32), dev)
    y = tensor.from_numpy(rng.randint(0, 10, (4,)).astype(np.int32), dev)
    m.compile([x], is_train=True, use_graph=True, sequential=False)
    m(x, y)
    out, loss = m(x, y)
    lv = float(loss.data)
    assert np.isfinite(lv) and 0 < lv < 3 * np.log(10)
    # loss is computed in fp32, params stay fp32 masters
    assert loss.data.dtype == jnp.float32
    for name, p in m.get_params().items():
        assert p.data.dtype == jnp.float32, name


def test_bf16_close_to_fp32_loss(dev):
    """One CNN training step under amp must track the fp32 loss to bf16
    tolerance (the policy changes precision, not math)."""
    rng = np.random.RandomState(1)
    x_np = rng.randn(4, 1, 28, 28).astype(np.float32)
    y_np = rng.randint(0, 10, (4,)).astype(np.int32)

    def one_loss():
        dev2 = device_module.get_default_device()
        dev2.SetRandSeed(0)
        m = CNN(num_classes=10, num_channels=1)
        m.set_optimizer(opt.SGD(lr=0.01))
        x = tensor.from_numpy(x_np, dev2)
        y = tensor.from_numpy(y_np, dev2)
        m.compile([x], is_train=True, use_graph=True, sequential=False)
        _, loss = m(x, y)
        return float(loss.data)

    ref = one_loss()
    amp.enable()
    try:
        got = one_loss()
    finally:
        amp.enable(False)
    assert abs(got - ref) / max(abs(ref), 1e-6) < 0.05, (got, ref)


def test_amp_toggle_after_compile_recompiles(dev):
    """Round-2 verdict repro: toggling amp AFTER graph compile must
    recompile and apply the new policy, not silently replay the stale
    executable (the cache key must include every trace-time global)."""
    from singa_tpu.models.mlp import MLP

    m = MLP(perceptron_size=16, num_classes=4)
    m.set_optimizer(opt.SGD(lr=0.01))
    rng = np.random.RandomState(3)
    x = tensor.from_numpy(rng.randn(8, 2).astype(np.float32), dev)
    y = tensor.from_numpy(np.eye(4, dtype=np.float32)[
        rng.randint(0, 4, (8,))], dev)
    m.compile([x], is_train=True, use_graph=True, sequential=False)
    out, _ = m(x, y)
    assert out.data.dtype == jnp.float32
    n_compiled = len(m._graph_runner._compiled)
    amp.enable()
    try:
        out_bf16, loss = m(x, y)
        # a NEW executable was compiled for the bf16 policy...
        assert len(m._graph_runner._compiled) == n_compiled + 1
        # ...and it actually computes in bf16 (stale fp32 replay would
        # return fp32 logits)
        assert out_bf16.data.dtype == jnp.bfloat16
        assert loss.data.dtype == jnp.float32  # loss stays fp32
    finally:
        amp.enable(False)
    # toggling back off restores the fp32 program (cache hit, no growth)
    out_fp32, _ = m(x, y)
    assert out_fp32.data.dtype == jnp.float32
    assert len(m._graph_runner._compiled) == n_compiled + 1


def test_norm_stats_fp32_under_amp(dev, bf16):
    """LayerNorm on a bf16 input keeps bf16 output but fp32-accurate
    statistics (variance of large-mean data underflows in bf16)."""
    rng = np.random.RandomState(2)
    x_np = (8.0 + rng.randn(4, 64)).astype(np.float32)
    # quantize to bf16 grid first so the comparison isolates the op's
    # internal statistics precision from input rounding
    x_np = np.asarray(jnp.asarray(x_np, jnp.bfloat16), np.float32)
    x = tensor.from_numpy(x_np, dev)
    s = tensor.from_numpy(np.ones(64, np.float32), dev)
    b = tensor.from_numpy(np.zeros(64, np.float32), dev)
    xb = tensor._wrap(x.data.astype(jnp.bfloat16), dev)
    y = autograd.layer_norm(xb, s, b)
    assert y.data.dtype == jnp.bfloat16
    got = np.asarray(y.data, dtype=np.float32)
    m = x_np.mean(axis=-1, keepdims=True)
    v = x_np.var(axis=-1, keepdims=True)
    want = (x_np - m) / np.sqrt(v + 1e-12)
    # bf16 output rounding only — stats did not collapse
    np.testing.assert_allclose(got, want, atol=0.15)
