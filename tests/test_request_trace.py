"""Per-request lifecycle ledger (observe.requests): timeline
completeness on a live engine, hop continuity across supervisor
restarts and fleet failovers, typed-rejection visibility, the
disabled-mode zero-overhead pin, tail-latency attribution arithmetic,
and the JSONL / Chrome-trace export surface.

Engine-backed tests drive the REAL serve stack (tiny model, seeded
fault injection — the test_supervisor/test_fleet idiom); attribution
tests feed the ledger hooks directly on a fake timeline so the phase
arithmetic is pinned exactly."""

import json

import numpy as np
import pytest

from singa_tpu import tensor
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from singa_tpu.observe import export, requests as reqtrace
from singa_tpu.observe.health import health_report
from singa_tpu.observe.requests import RequestLedger
from singa_tpu.resilience import FailAfterN, faults
from singa_tpu.serve import (EngineFailedError, EngineSupervisor,
                             GenerationRequest, PrefixCacheConfig,
                             QueueFullError)


@pytest.fixture(autouse=True)
def _clean_state():
    faults.clear()
    reqtrace.disable()
    yield
    faults.clear()
    reqtrace.disable()


@pytest.fixture(scope="module")
def model():
    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    return m


def _workload(n, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randint(0, 256, rng.randint(3, 10)).astype(np.int32),
             int(rng.randint(2, 7))) for _ in range(n)]


def _assert_monotonic(entry):
    """Every recorded timestamp in the entry is non-decreasing in
    causal order: submit <= admit <= chunks <= first token <= steps <=
    retire, hop over hop."""
    t = entry["t_submit"]
    for h in entry["hops"]:
        assert h["t_submit"] >= t
        t = h["t_submit"]
        if h["t_admit"] is not None:
            assert h["t_admit"] >= t
            t = h["t_admit"]
        for ct, _off in h["chunks"]:
            assert ct >= t
            t = ct
        if h["t_first_token"] is not None:
            assert h["t_first_token"] >= t
            t = h["t_first_token"]
        for s in h["steps"]:
            assert s[0] >= t
            t = s[0]
    if entry["t_retire"] is not None:
        assert entry["t_retire"] >= t


# ---------------------------------------------------------------------------
# live engine: timeline completeness
# ---------------------------------------------------------------------------

def test_engine_run_records_complete_timelines(model):
    """Every completed request gets one sealed entry: submit ->
    admission -> first token -> per-step emissions -> retire, with
    monotonic timestamps, exact phase sums, and the queue depth it
    saw at enqueue."""
    work = _workload(5, seed=0)
    led = reqtrace.enable(capacity=64)
    with model.serve(max_slots=2) as eng:
        hs = [eng.submit(GenerationRequest(p, max_new_tokens=n))
              for p, n in work]
        eng.run_until_complete(max_steps=500)
        for h in hs:
            h.result()
    entries = led.entries()
    assert len(entries) == len(work)
    assert led.open_count == 0
    by_rid = {e["request_id"]: e for e in entries}
    for (p, n), h in zip(work, hs):
        e = by_rid[h.request.request_id]
        assert e["outcome"] == "length"
        assert e["prompt_len"] == len(p)
        assert e["tokens_out"] == n
        assert len(e["hops"]) == 1
        hop = e["hops"][0]
        assert hop["via"] == "submit"
        assert hop["queue_depth_at_enqueue"] is not None
        assert hop["admit_kind"] == "cold"
        assert hop["slot"] is not None
        assert hop["tokens"] == n
        # first token at admission + one step record per decode step
        assert len(hop["steps"]) == n - 1
        _assert_monotonic(e)
        # attribution is exact arithmetic: the first three phases sum
        # to TTFT and all five to total latency
        ph = e["phases"]
        ttft = ph["hops"] + ph["queue"] + ph["prefill"]
        assert ttft == pytest.approx(e["ttft_s"], abs=1e-9)
        total = sum(ph.values())
        assert total == pytest.approx(e["t_retire"] - e["t_submit"],
                                      abs=1e-9)
    # health_report carries the attribution section while enabled
    ws = health_report(include_registry=False)["serve"]["why_slow"]
    assert ws["enabled"] is True
    assert ws["completed"] == len(work)
    assert ws["ttft_p99_s"] > 0
    att = ws["ttft_p99_attribution"]
    assert att and sum(v["frac"] for v in att.values()) \
        == pytest.approx(1.0)


def test_prefix_warm_admission_annotates_hit_tokens(model):
    """The prefix cache's hook owns the cold/warm verdict: a repeated
    prompt's second admission is marked warm with the cached-token
    count, and its warm-prefill chunks are on the timeline."""
    led = reqtrace.enable()
    p = (np.arange(40) % 256).astype(np.int32)
    cachecfg = PrefixCacheConfig(block_size=8, num_blocks=32)
    with model.serve(max_slots=1, prefix_cache=cachecfg) as eng:
        h1 = eng.submit(GenerationRequest(p, max_new_tokens=3))
        eng.run_until_complete(max_steps=200)
        h1.result()
        h2 = eng.submit(GenerationRequest(p, max_new_tokens=3))
        eng.run_until_complete(max_steps=200)
        h2.result()
    e1 = led.entry(h1.request.request_id)
    e2 = led.entry(h2.request.request_id)
    assert e1["hops"][0]["admit_kind"] == "cold"
    assert e1["hops"][0]["hit_tokens"] == 0
    assert e2["hops"][0]["admit_kind"] == "warm"
    assert e2["hops"][0]["hit_tokens"] > 0
    assert e2["hops"][0]["chunks"]  # warm path prefills by chunk
    _assert_monotonic(e2)


# ---------------------------------------------------------------------------
# disabled-mode zero overhead
# ---------------------------------------------------------------------------

def test_disabled_mode_no_entries_no_ring_growth(model):
    """With the ledger off (the default), serve traffic allocates
    nothing: no live ledger, and a previously-enabled ledger's ring
    does not grow after disable()."""
    assert reqtrace.active() is False
    assert reqtrace.ledger() is None
    led = reqtrace.enable()
    reqtrace.disable()
    assert reqtrace.active() is False
    with model.serve(max_slots=2) as eng:
        hs = [eng.submit(GenerationRequest(p, max_new_tokens=n))
              for p, n in _workload(3, seed=1)]
        eng.run_until_complete(max_steps=300)
        for h in hs:
            h.result()
    assert led.entries() == []
    assert led.open_count == 0
    assert led.dropped == 0
    # the health section stays present but honest
    ws = health_report(include_registry=False)["serve"]["why_slow"]
    assert ws == {"enabled": False}


# ---------------------------------------------------------------------------
# hop continuity: supervisor restart + fleet failover
# ---------------------------------------------------------------------------

def test_supervisor_restart_hops_share_one_timeline(model):
    """A mid-stream fault + supervised restart: each requeued request
    keeps ONE ledger entry whose second hop says via=supervisor_restart
    on the rebuilt engine; the in-flight request's entry ends in a
    terminal started=True rejection."""
    led = reqtrace.enable()
    sup = EngineSupervisor(model, max_slots=1, restart_budget=2)
    hs = [sup.submit(GenerationRequest(p, max_new_tokens=n,
                                       temperature=0.0))
          for p, n in _workload(4, seed=2)]
    faults.inject("serve.decode_step", FailAfterN(2, times=1))
    sup.run_until_complete(max_steps=500)
    faults.clear()
    requeued = typed = 0
    for h in hs:
        rid = h.request.request_id
        e = led.entry(rid)
        assert e is not None
        try:
            h.result()
        except EngineFailedError:
            typed += 1
            assert e["outcome"] == "rejected"
            assert e["started"] is True
            # the terminal hop carries the typed-rejection record
            assert e["hops"][-1]["reject"]["reason"] == "engine_failed"
            _assert_monotonic(e)
            continue
        if len(e["hops"]) > 1:
            requeued += 1
            assert e["outcome"] == "length"
            assert e["hops"][0]["reject"]["reason"] == "engine_failed"
            assert e["hops"][0]["reject"]["started"] is False
            assert e["hops"][1]["via"] == "supervisor_restart"
            # one timeline, sealed once: a single JSONL record
            assert sum(1 for ln in led.jsonl_lines()
                       if json.loads(ln)["request_id"] == rid) == 1
            _assert_monotonic(e)
    assert requeued >= 1 and typed >= 1
    sup.close()


def test_fleet_failover_timeline_shows_both_replicas(model):
    """A replica dying past its budget: the requeued request's single
    timeline shows both replicas (hop 0 on the dead one, a
    via=failover hop on the survivor) and the started request's shows
    a terminal rejection hop on the dead replica."""
    led = reqtrace.enable()
    work = _workload(6, seed=3)
    fleet = model.serve_fleet(replicas=2, max_slots=1,
                              restart_budget=0)
    hs = [fleet.submit(GenerationRequest(p, max_new_tokens=n))
          for p, n in work]
    faults.inject("serve.decode_step", FailAfterN(2, times=1))
    fleet.run_until_complete(max_steps=1000)
    faults.clear()
    failed_over = typed = 0
    for h in hs:
        e = led.entry(h.request.request_id)
        assert e is not None
        try:
            h.result()
        except EngineFailedError:
            typed += 1
            assert e["outcome"] == "rejected"
            assert e["hops"][-1]["reject"] is not None
            _assert_monotonic(e)
            continue
        if len(e["hops"]) > 1:
            failed_over += 1
            assert e["outcome"] == "length"
            h0, h1 = e["hops"][0], e["hops"][-1]
            assert h1["via"] == "failover"
            assert h0["replica"] is not None
            assert h1["replica"] is not None
            assert h0["replica"] != h1["replica"]
            assert h1["src_replica"] == h0["replica"]
            # different engines served the two hops
            assert h0["engine"] != h1["engine"]
            _assert_monotonic(e)
    assert failed_over >= 1 and typed >= 1
    # the failed-over requests burned real time on the dead replica:
    # their attribution shows a non-zero hops phase, and why_slow's
    # evidence list carries the full hop chain
    ws = led.why_slow(top_k=len(work))
    assert ws["completed"] + ws["rejected"] == len(work)
    slow_hops = [s for s in ws["slowest"] if len(s["hops"]) > 1]
    assert slow_hops and all(s["phases"]["hops"] > 0
                             for s in slow_hops)
    fleet.close()


# ---------------------------------------------------------------------------
# typed rejections stay visible
# ---------------------------------------------------------------------------

def test_queue_full_rejection_lands_in_ledger_and_trace(model):
    """The small-fix satellite: a refused request must appear in the
    ledger (terminal entry) AND as a serve/request_rejected trace
    instant instead of vanishing from observability."""
    from singa_tpu.observe import trace
    from singa_tpu.serve import FIFOScheduler

    led = reqtrace.enable()
    trace.enable()
    try:
        with model.serve(max_slots=1,
                         scheduler=FIFOScheduler(
                             max_queue_depth=2)) as eng:
            p = np.asarray([1, 2, 3], np.int32)
            h1 = eng.submit(GenerationRequest(p, max_new_tokens=2))
            h2 = eng.submit(GenerationRequest(p, max_new_tokens=2))
            with pytest.raises(QueueFullError):
                eng.submit(GenerationRequest(p, max_new_tokens=2))
            eng.run_until_complete(max_steps=200)
            h1.result(), h2.result()
        rejected = [e for e in led.entries()
                    if e["outcome"] == "rejected"]
        assert len(rejected) == 1
        e = rejected[0]
        assert e["reason"] == "queue_full"
        assert e["started"] is False
        assert e["hops"][-1]["reject"]["reason"] == "queue_full"
        evs = [ev for ev in trace.events()
               if ev.get("name") == "serve/request_rejected"]
        assert any(ev["args"]["request"] == e["request_id"]
                   and ev["args"]["reason"] == "queue_full"
                   for ev in evs)
    finally:
        trace.disable()
        trace.clear()


# ---------------------------------------------------------------------------
# attribution arithmetic on a fake timeline
# ---------------------------------------------------------------------------

def _fake_completed(led, rid, engine="0", replica=0, t0=0.0,
                    queue=1.0, prefill=0.5, steps=(0.1,) * 5):
    led.on_submit(rid, engine=engine, t=t0, prompt_len=8,
                  max_new_tokens=len(steps) + 1)
    led.annotate_hop(rid, replica=replica, queue_depth_at_enqueue=2)
    led.on_admit(rid, engine=engine, t=t0 + queue, slot=0)
    t = t0 + queue + prefill
    led.on_first_token(rid, engine=engine, t=t)
    for dt in steps:
        t += dt
        led.on_step(rid, engine=engine, t=t, tokens=1)
    led.on_retire(rid, engine=engine, t=t, finish_reason="length",
                  tokens=len(steps) + 1)
    return t - t0


def test_why_slow_attribution_decomposes_exactly():
    """Pinned arithmetic: a queue-dominated slow request on replica 1
    shows ~80% queue in the p99 attribution, fractions sum to 1, and
    the per-replica split names the right replica."""
    led = RequestLedger()
    for i in range(9):
        _fake_completed(led, f"fast-{i}", replica=0, queue=0.01,
                        prefill=0.05)
    _fake_completed(led, "slow", replica=1, queue=8.0, prefill=1.5)
    ws = led.why_slow(top_k=3)
    assert ws["completed"] == 10
    # nearest-rank p99 over 10 values = the slowest request
    assert ws["ttft_p99_s"] == pytest.approx(9.5)
    att = ws["ttft_p99_attribution"]
    assert att["queue"]["frac"] == pytest.approx(8.0 / 9.5)
    assert sum(v["frac"] for v in att.values()) == pytest.approx(1.0)
    assert set(ws["per_replica"]) == {"1"}
    assert ws["per_replica"]["1"]["requests"] == 1
    top = ws["slowest"][0]
    assert top["request_id"] == "slow"
    assert top["dominant_phase"] == "queue"
    assert top["phases"]["queue"] == pytest.approx(8.0)
    assert top["phases"]["prefill"] == pytest.approx(1.5)


def test_stall_carved_out_of_decode():
    """An inter-token gap far beyond the request's own median is
    attributed to stall, not decode — and the five phases still sum
    to total latency exactly."""
    led = RequestLedger()
    total = _fake_completed(
        led, "stalled", steps=(0.1, 0.1, 0.1, 5.0, 0.1, 0.1))
    e = led.entry("stalled")
    ph = e["phases"]
    assert ph["stall"] == pytest.approx(4.9)   # excess over the median
    assert ph["decode"] == pytest.approx(5.5 - 4.9)
    assert sum(ph.values()) == pytest.approx(total)
    ws = led.why_slow()
    assert ws["tpot_p99_attribution"]["stall"]["frac"] > 0.8


def test_tpot_uses_retire_token_count():
    """The engine emits, retires, THEN writes the step record, so the
    hop's token tally lags by the final step at seal time — tpot must
    come from on_retire's authoritative count, not the tally."""
    led = RequestLedger()
    led.on_submit("r", engine="0", t=0.0)
    led.on_admit("r", engine="0", t=0.0, slot=0)
    led.on_first_token("r", engine="0", t=0.0)
    led.on_step("r", engine="0", t=1.0, tokens=1)
    led.on_retire("r", engine="0", t=2.0, finish_reason="length",
                  tokens=3)
    led.on_step("r", engine="0", t=2.0, tokens=1)  # trailing record
    e = led.entry("r")
    assert e["final_hop"] == 0
    assert e["tokens_out"] == 3
    assert e["tpot_s"] == pytest.approx(2.0 / (3 - 1))
    assert e["hops"][0]["tokens"] == 3  # tally catches up post-seal


def test_hedge_winner_defines_latency():
    """A hedged request's ttft/tpot and replica attribution come from
    the hop whose engine RETIRED it, not the last hop by position
    (the losing twin)."""
    led = RequestLedger()
    led.on_submit("h", engine="0", t=0.0)
    led.on_admit("h", engine="0", t=0.1, slot=0)
    led.on_first_token("h", engine="0", t=0.5)
    # concurrent hedge twin on a slower engine
    led.on_submit("h", engine="1", t=1.0)
    led.annotate_hop("h", engine="1", via="hedge", replica=1)
    led.on_admit("h", engine="1", t=1.2, slot=0)
    led.on_first_token("h", engine="1", t=2.0)
    # the ORIGINAL hop wins the race
    led.on_retire("h", engine="0", t=1.5, finish_reason="length",
                  tokens=3)
    e = led.entry("h")
    assert e["final_hop"] == 0
    assert e["ttft_s"] == pytest.approx(0.5)
    assert e["tpot_s"] == pytest.approx(1.0 / 2)
    assert led._replica_key(e) == "engine:0"
    # the loser's late retire only annotates, never reopens
    led.on_retire("h", engine="1", t=2.5, finish_reason="length",
                  tokens=3)
    assert e["t_retire"] == 1.5
    assert e["hops"][1]["duplicate_retire_t"] == 2.5


def test_ring_capacity_bounds_and_drop_count():
    led = RequestLedger(capacity=2)
    for i in range(5):
        _fake_completed(led, f"r{i}")
    assert len(led.entries()) == 2
    assert led.dropped == 3
    assert [e["request_id"] for e in led.entries()] == ["r3", "r4"]
    assert led.snapshot() == {"capacity": 2, "sealed": 2, "open": 0,
                              "dropped": 3}
    with pytest.raises(ValueError):
        RequestLedger(capacity=0)


# ---------------------------------------------------------------------------
# export surface: JSONL + Chrome trace tracks
# ---------------------------------------------------------------------------

def test_request_log_is_strict_jsonl(tmp_path):
    led = reqtrace.enable()
    _fake_completed(led, "a")
    led.on_submit("b", engine="0", t=0.0)
    led.on_reject("b", t=1.0, reason="shed:slo_pressure", engine="0",
                  started=False)
    path = tmp_path / "requests.jsonl"
    n = reqtrace.write_request_log(str(path))
    assert n == 2
    raiser = (lambda c: (_ for _ in ()).throw(ValueError(c)))
    lines = path.read_text().splitlines()
    assert len(lines) == 2
    recs = [json.loads(ln, parse_constant=raiser) for ln in lines]
    assert {r["request_id"] for r in recs} == {"a", "b"}
    rej = next(r for r in recs if r["request_id"] == "b")
    assert rej["outcome"] == "rejected"
    assert rej["reason"] == "shed:slo_pressure"
    reqtrace.disable()
    with pytest.raises(RuntimeError, match="enable"):
        reqtrace.write_request_log(str(path))
    # an explicit ledger still exports after disable()
    assert reqtrace.write_request_log(str(path), ledger_=led) == 2


def test_chrome_trace_request_tracks_and_hop_flow():
    """Per-request tracks: phase spans per hop, a rejection instant,
    and a flow-arrow pair across the requeue hop boundary; merged into
    chrome_trace under its own pid."""
    led = RequestLedger()
    # two-hop requeued request: hop 0 rejected requeue-safe, hop 1
    # completes on another engine
    led.on_submit("x", engine="0", t=0.0)
    led.on_reject("x", t=1.0, reason="engine_failed", engine="0",
                  started=False)
    led.on_submit("x", engine="1", t=1.5)
    led.annotate_hop("x", via="failover", replica=1)
    led.on_admit("x", engine="1", t=2.0, slot=0)
    led.on_first_token("x", engine="1", t=2.5)
    led.on_retire("x", engine="1", t=3.0, finish_reason="length",
                  tokens=2)
    evs = export.request_trace_events(led.entries())
    names = [e["name"] for e in evs]
    assert names.count("queue") == 2      # one per hop
    assert "prefill" in names and "decode" in names
    assert "rejected" in names
    flows = [e for e in evs if e["name"] == "hop"]
    assert [f["ph"] for f in flows] == ["s", "f"]
    assert flows[0]["id"] == flows[1]["id"]
    doc = export.chrome_trace(events=[], requests=led.entries())
    assert doc["otherData"]["request_tracks"] == 1
    pids = {e["pid"] for e in doc["traceEvents"]
            if e["name"] in ("queue", "prefill", "decode")}
    assert pids == {1}
    json.dumps(doc, allow_nan=False)
