"""The `singa` drop-in alias: reference import lines work unchanged and
resolve to the same module objects as singa_tpu."""

import numpy as np


def test_reference_import_lines():
    from singa import autograd, device, layer, model, opt, tensor  # noqa

    import singa_tpu

    import singa_tpu.tensor as st_tensor

    assert tensor is st_tensor  # identity, not a copy


def test_submodule_import_form():
    import singa.sonnx as s1
    import singa_tpu.sonnx as s2

    assert s1 is s2


def test_nested_submodule_identity():
    """Any-depth imports must alias, not re-execute (module copies would
    break isinstance across the two spellings)."""
    import singa.io.onnx_pb as a
    import singa_tpu.io.onnx_pb as b

    assert a is b
    assert a.TensorProto is b.TensorProto

    import singa.models.gpt2 as g1
    import singa_tpu.models.gpt2 as g2

    assert g1 is g2


def test_convnd_scalar_defaults():
    """conv2d's scalar geometry defaults broadcast to the input rank."""
    from singa_tpu import tensor
    from singa_tpu.ops import conv as conv_ops

    rng = np.random.RandomState(0)
    x = tensor.from_numpy(rng.randn(1, 2, 10).astype(np.float32))
    w = tensor.from_numpy(rng.randn(3, 2, 3).astype(np.float32))
    y = conv_ops.conv2d(x, w)  # no geometry args at all
    assert y.shape == (1, 3, 8)


def test_reference_style_script_runs():
    """The reference MLP recipe, written with `singa` imports, trains."""
    from singa import device, layer, model, opt, tensor
    from singa import autograd

    dev = device.create_cuda_gpu()  # source-compat alias -> TPU/CPU dev
    dev.SetRandSeed(0)

    class MLP(model.Model):
        def __init__(self):
            super().__init__()
            self.fc1 = layer.Linear(16)
            self.relu = layer.ReLU()
            self.fc2 = layer.Linear(2)
            self.loss = layer.SoftMaxCrossEntropy()

        def forward(self, x):
            return self.fc2(self.relu(self.fc1(x)))

        def train_one_batch(self, x, y):
            out = self.forward(x)
            loss = self.loss(out, y)
            self.optimizer(loss)
            return out, loss

    rng = np.random.RandomState(0)
    xs = rng.randn(32, 4).astype(np.float32)
    ys = (xs.sum(1) > 0).astype(np.int32)
    m = MLP()
    m.set_optimizer(opt.SGD(lr=0.1))
    x0 = tensor.from_numpy(xs, dev)
    m.compile([x0], is_train=True, use_graph=True)
    losses = []
    for _ in range(20):
        _, loss = m(tensor.from_numpy(xs, dev),
                    tensor.from_numpy(ys, dev))
        losses.append(float(tensor.to_numpy(loss)))
    assert losses[-1] < losses[0]
