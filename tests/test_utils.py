"""utils satellites: logging channel reconfiguration, metrics guards,
Timer.seconds initialization."""

import math
import time

import pytest

from singa_tpu.utils import logging as slog
from singa_tpu.utils.metrics import LatencySeries, StepTimer, percentile
from singa_tpu.utils.timer import Timer


# ---------------------------------------------------------------------------
# logging: init_channel after get_channel must reconfigure cached loggers
# ---------------------------------------------------------------------------

@pytest.fixture
def _restore_logging():
    """Snapshot and restore the module's channel config so this test
    can't leak a file handler into other tests' channels."""
    saved = (slog._channel_dir, slog._stderr_default)
    yield
    slog.init_channel(dir=saved[0] or "", stderr=saved[1])


def test_init_channel_reconfigures_cached_channels(tmp_path,
                                                   _restore_logging):
    # pin the starting config (an earlier test in the suite may have
    # left a channel dir set)
    slog.init_channel(dir="", stderr=True)
    ch = slog.get_channel("reconfig_test")
    assert not any(hasattr(h, "baseFilename") for h in ch.handlers)

    slog.init_channel(dir=str(tmp_path), stderr=False)
    # the CACHED logger must have picked up the new config: a file
    # handler into tmp_path, no stderr stream handler
    ch2 = slog.get_channel("reconfig_test")
    assert ch2 is ch
    files = [h.baseFilename for h in ch.handlers
             if hasattr(h, "baseFilename")]
    assert files and files[0].startswith(str(tmp_path))
    import logging as _pylog
    assert not any(type(h) is _pylog.StreamHandler for h in ch.handlers)

    ch.info("hello reconfig")
    for h in ch.handlers:
        h.flush()
    log_file = tmp_path / "reconfig_test.log"
    assert "hello reconfig" in log_file.read_text()

    # flipping back to stderr-only must close + drop the file handler
    slog.init_channel(dir="", stderr=True)
    assert not any(hasattr(h, "baseFilename") for h in ch.handlers)


def test_new_channel_after_init_uses_current_config(tmp_path,
                                                    _restore_logging):
    slog.init_channel(dir=str(tmp_path), stderr=False)
    ch = slog.get_channel("fresh_after_init")
    ch.info("to file")
    for h in ch.handlers:
        h.flush()
    assert "to file" in (tmp_path / "fresh_after_init.log").read_text()


# ---------------------------------------------------------------------------
# metrics guards
# ---------------------------------------------------------------------------

def test_step_timer_no_samples_is_nan_not_raise():
    t = StepTimer()
    assert math.isnan(t.mean_step_seconds())
    assert math.isnan(t.samples_per_sec(128))
    assert math.isnan(t.samples_per_sec_per_chip(128, num_chips=4))


def test_step_timer_zero_mean_is_nan_not_zero_division():
    t = StepTimer(skip_first=0)
    t.times = [0.0, 0.0]  # zero-duration clock (fake clocks in tests)
    assert t.mean_step_seconds() == 0.0
    assert math.isnan(t.samples_per_sec(128))


def test_step_timer_normal_path_still_works():
    t = StepTimer(skip_first=1)
    t.times = [10.0, 0.5, 0.5]
    assert t.mean_step_seconds() == 0.5
    assert t.samples_per_sec(64) == 128.0


def test_empty_latency_series_is_nan_everywhere():
    s = LatencySeries()
    assert math.isnan(s.mean())
    assert math.isnan(s.percentile(50))
    assert math.isnan(s.percentile(0))
    summ = s.summary()
    assert summ["count"] == 0
    for k in ("mean", "p50", "p99", "max"):
        assert math.isnan(summ[k])


def test_percentile_empty_and_clamped():
    assert math.isnan(percentile([], 99))
    assert math.isnan(percentile([], 0))
    assert percentile([3.0, 1.0, 2.0], 150) == 3.0  # p>100 clamps to max
    assert percentile([3.0, 1.0, 2.0], -5) == 1.0


def test_latency_series_running_totals_survive_window_eviction():
    """total_sum/count are maintained independently of the retained
    ``values`` ring, so the Prometheus _sum/_count pair stays
    consistent now that the window IS bounded (the soak-memory
    satellite: default ~8k, overridable)."""
    s = LatencySeries(max_samples=3)
    for v in (1.0, 2.0, 3.0):
        s.record(v)
    assert s.total_sum == 6.0 and s.count == 3
    s.record(4.0)  # evicts 1.0 from the ring
    assert list(s.values) == [2.0, 3.0, 4.0]
    assert s.total_sum == 10.0 and s.count == 4  # totals exact


# ---------------------------------------------------------------------------
# Timer.seconds
# ---------------------------------------------------------------------------

def test_timer_seconds_is_none_before_context_exit():
    t = Timer()
    assert t.seconds is None  # used to AttributeError
    assert t.elapsed() >= 0.0
    assert t.seconds is None  # elapsed() is live, not freezing
    with t:
        time.sleep(0.001)
    assert t.seconds is not None and t.seconds > 0.0
