"""Optimizer math vs hand-computed goldens (reference: test/python/test_opt.py,
unverified)."""

import numpy as np
import pytest

from singa_tpu import autograd, opt, tensor
from singa_tpu import device as device_module
from singa_tpu.tensor import Tensor


@pytest.fixture
def dev():
    return device_module.get_default_device()


def _param(arr, dev, name=None):
    t = tensor.from_numpy(arr, dev)
    t.requires_grad = True
    t.stores_grad = True
    t.name = name
    return t


def _grad(arr, dev):
    return tensor.from_numpy(arr, dev)


def test_sgd_vanilla(dev):
    p = _param(np.array([1.0, 2.0], np.float32), dev, "p")
    g = _grad(np.array([0.5, 0.5], np.float32), dev)
    sgd = opt.SGD(lr=0.1)
    sgd.update(p, g)
    np.testing.assert_allclose(tensor.to_numpy(p), [0.95, 1.95], rtol=1e-6)


def test_sgd_momentum(dev):
    p = _param(np.array([1.0], np.float32), dev, "p")
    g = _grad(np.array([1.0], np.float32), dev)
    sgd = opt.SGD(lr=0.1, momentum=0.9)
    sgd.update(p, g)   # buf=1.0, p=1-0.1
    np.testing.assert_allclose(tensor.to_numpy(p), [0.9], rtol=1e-6)
    sgd.update(p, g)   # buf=0.9*1+1=1.9, p=0.9-0.19
    np.testing.assert_allclose(tensor.to_numpy(p), [0.71], rtol=1e-6)


def test_sgd_weight_decay(dev):
    p = _param(np.array([1.0], np.float32), dev, "p")
    g = _grad(np.array([0.0], np.float32), dev)
    sgd = opt.SGD(lr=0.1, weight_decay=0.1)
    sgd.update(p, g)
    np.testing.assert_allclose(tensor.to_numpy(p), [0.99], rtol=1e-6)


def test_adam_first_step(dev):
    p = _param(np.array([1.0], np.float32), dev, "p")
    g = _grad(np.array([0.5], np.float32), dev)
    adam = opt.Adam(lr=0.001)
    adam.update(p, g)
    # bias-corrected first step ≈ lr * sign(g)
    np.testing.assert_allclose(tensor.to_numpy(p), [1.0 - 0.001], rtol=1e-4)


def test_rmsprop_adagrad_run(dev):
    for O in (opt.RMSProp, opt.AdaGrad):
        p = _param(np.ones((3,), np.float32), dev, "p")
        g = _grad(np.full((3,), 0.1, np.float32), dev)
        o = O(lr=0.01)
        for _ in range(3):
            o.update(p, g)
            o.step()
        assert np.all(tensor.to_numpy(p) < 1.0)


def test_exponential_decay_schedule(dev):
    sched = opt.ExponentialDecay(0.1, decay_steps=10, decay_rate=0.5)
    assert abs(float(sched(0)) - 0.1) < 1e-7
    assert abs(float(sched(10)) - 0.05) < 1e-7
    stair = opt.ExponentialDecay(0.1, 10, 0.5, staircase=True)
    assert abs(float(stair(9)) - 0.1) < 1e-7


def test_backward_and_update_consumes_generator(dev):
    autograd.set_training(True)
    try:
        x = tensor.from_numpy(np.ones((4, 3), np.float32), dev)
        w = _param(np.ones((3, 2), np.float32) * 0.5, dev, "w")
        sgd = opt.SGD(lr=0.1)
        before = tensor.to_numpy(w).copy()
        y = autograd.matmul(x, w)
        loss = autograd.reduce_sum(autograd.mul(y, y))
        sgd(loss)
        after = tensor.to_numpy(w)
        assert not np.allclose(before, after)
        assert float(sgd.step_counter.data) == 1.0
    finally:
        autograd.set_training(False)


def test_optimizer_state_roundtrip(dev):
    p = _param(np.ones((2,), np.float32), dev, "p")
    g = _grad(np.ones((2,), np.float32), dev)
    sgd = opt.SGD(lr=0.1, momentum=0.9)
    sgd.update(p, g)
    sgd.step()
    states = sgd.get_states()
    sgd2 = opt.SGD(lr=0.1, momentum=0.9)
    sgd2.set_states(states)
    assert float(sgd2.step_counter.data) == 1.0
    k = [k for k in states if k.endswith(":momentum")][0]
    np.testing.assert_allclose(states[k], [1.0, 1.0])


def test_adamw_equals_adam_without_decay_and_decouples_with(dev):
    """wd=0: AdamW == Adam exactly.  wd>0 with ZERO gradient: AdamW
    shrinks the parameter by lr·wd·p immediately (decoupled), while
    Adam's coupled decay routes wd·p through m/v and moves by the
    bias-corrected sign instead — the two must differ on step 1."""
    arr = np.array([1.0, -2.0], np.float32)
    g = _grad(np.array([0.5, -0.25], np.float32), dev)
    outs = {}
    for cls in (opt.Adam, opt.AdamW):
        p = _param(arr.copy(), dev, "p")
        o = cls(lr=0.01, weight_decay=0.0)
        o.update(p, g)
        outs[cls.__name__] = tensor.to_numpy(p)
    np.testing.assert_allclose(outs["Adam"], outs["AdamW"], rtol=1e-7)

    zero = _grad(np.zeros((2,), np.float32), dev)
    got = {}
    for cls in (opt.Adam, opt.AdamW):
        p = _param(arr.copy(), dev, "p")
        o = cls(lr=0.01, weight_decay=0.1)
        o.update(p, zero)
        got[cls.__name__] = tensor.to_numpy(p)
    # decoupled: p - lr·wd·p exactly
    np.testing.assert_allclose(got["AdamW"], arr * (1 - 0.01 * 0.1),
                               rtol=1e-6)
    assert not np.allclose(got["Adam"], got["AdamW"])


def test_lion_update_is_sign_scaled(dev):
    """Every Lion update coordinate has magnitude exactly lr (sign of
    the interpolated momentum); the momentum state updates with
    beta_2."""
    arr = np.array([1.0, -2.0, 3.0], np.float32)
    p = _param(arr.copy(), dev, "p")
    g = _grad(np.array([0.5, -4.0, 1e-3], np.float32), dev)
    o = opt.Lion(lr=0.01, beta_1=0.9, beta_2=0.99)
    o.update(p, g)
    # step 1: m=0 ⇒ update = sign((1-b1)·g) = sign(g)
    np.testing.assert_allclose(
        tensor.to_numpy(p), arr - 0.01 * np.sign([0.5, -4.0, 1e-3]),
        rtol=1e-6)
    k = [k for k in o.get_states() if k.endswith(":m")][0]
    np.testing.assert_allclose(o.get_states()[k],
                               0.01 * np.asarray([0.5, -4.0, 1e-3]),
                               rtol=1e-5)


def test_adamw_lion_train_a_model(dev):
    """Both new optimizers drive real training end to end."""
    from singa_tpu.models.mlp import MLP
    from singa_tpu import model as model_mod

    rng = np.random.RandomState(0)
    x = rng.randn(32, 8).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.int32)
    for o in (opt.AdamW(lr=1e-2, weight_decay=0.01),
              opt.Lion(lr=3e-3, weight_decay=0.01)):
        dev.SetRandSeed(0)
        m = MLP(data_size=8, perceptron_size=16, num_classes=2)
        m.set_optimizer(o)
        xt = tensor.from_numpy(x, dev)
        m.compile([xt], is_train=True, use_graph=True)
        losses = []
        for _ in range(25):
            _, loss = m(tensor.from_numpy(x, dev),
                        tensor.from_numpy(y, dev))
            losses.append(float(tensor.to_numpy(loss)))
        assert losses[-1] < losses[0], (type(o).__name__, losses)


def test_clip_norm_scales_to_the_ball(dev):
    """||g||_global > clip_norm ⇒ the applied update equals SGD on
    g·(clip_norm/||g||); under the norm ⇒ untouched."""
    import singa_tpu.autograd as ag

    g1 = np.array([3.0, 0.0], np.float32)
    g2 = np.array([0.0, 4.0], np.float32)  # global norm 5

    def run(clip):
        ag.set_training(True)
        try:
            p1 = _param(np.zeros(2, np.float32), dev, "p1")
            p2 = _param(np.zeros(2, np.float32), dev, "p2")
            y = ag.add(ag.mul(p1, _grad(g1, dev)),
                       ag.mul(p2, _grad(g2, dev)))
            loss = ag.reduce_sum(y)
            o = opt.SGD(lr=1.0, clip_norm=clip)
            o.backward_and_update(loss)
            return tensor.to_numpy(p1), tensor.to_numpy(p2)
        finally:
            ag.set_training(False)

    a1, a2 = run(clip=2.5)         # norm 5 -> scale 0.5
    np.testing.assert_allclose(a1, -g1 * 0.5, rtol=1e-6)
    np.testing.assert_allclose(a2, -g2 * 0.5, rtol=1e-6)
    b1, b2 = run(clip=100.0)       # under the ball -> untouched
    np.testing.assert_allclose(b1, -g1, rtol=1e-6)
    np.testing.assert_allclose(b2, -g2, rtol=1e-6)
    with pytest.raises(ValueError):
        opt.Adam(clip_norm=0.0)


def test_clip_norm_trains_in_graph_mode(dev):
    """clip_norm works inside the jitted graph-mode step (the clip is
    pure jnp, so it traces into the step executable)."""
    from singa_tpu.models.mlp import MLP

    rng = np.random.RandomState(0)
    x = rng.randn(32, 8).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.int32)
    dev.SetRandSeed(0)
    m = MLP(data_size=8, perceptron_size=16, num_classes=2)
    m.set_optimizer(opt.AdamW(lr=1e-2, clip_norm=1.0))
    m.compile([tensor.from_numpy(x, dev)], is_train=True, use_graph=True)
    ls = []
    for _ in range(25):
        _, loss = m(tensor.from_numpy(x, dev), tensor.from_numpy(y, dev))
        ls.append(float(tensor.to_numpy(loss)))
    assert ls[-1] < ls[0], ls


def test_distopt_clipped_inner_optimizer_accepted_dense_only(dev):
    """Global-norm clipping now crosses the distributed boundary: the
    dense/fp16 sync modes clip the SYNCED grads (DistOpt._apply_all,
    equivalence vs the single-device oracle in tests/test_dist.py),
    so construction accepts a clipped inner optimizer.  The
    partial/sparse modes — which sync partial gradient information
    with no per-step global norm to clip — refuse at call time with a
    pointer at the supported modes."""
    d = opt.DistOpt(opt.SGD(lr=0.1, clip_norm=1.0), num_devices=1)
    assert d.opt.clip_norm == 1.0
    x = tensor.from_numpy(np.zeros((4, 3), np.float32), dev)
    w = tensor.from_numpy(np.ones((3, 2), np.float32), dev)
    w.requires_grad = True
    w.stores_grad = True
    from singa_tpu import autograd
    loss = autograd.reduce_mean(autograd.matmul(x, w))
    with pytest.raises(ValueError, match="clip_norm"):
        d.backward_and_partial_update(loss)
