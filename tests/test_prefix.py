"""Radix prefix cache (singa_tpu/serve/prefix.py): warm-vs-cold token
parity (greedy + seeded sampling + GQA — BYTE-identical, the
subsystem's acceptance bar), refcount pin/unpin across in-flight
requests, LRU eviction safety, session continuation (including after a
supervised engine restart), arena-pressure fallback, scheduler
interleave pricing, and the serve.prefix_copy chaos site.

Cached K/V is canonical prefill output and the chunked offset-prefill
is bitwise-identical to full prefill on this backend, so every parity
assertion here is np.array_equal, not allclose."""

import numpy as np
import pytest

from singa_tpu import tensor
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from singa_tpu.serve import (EngineFailedError, EngineSupervisor,
                             FIFOScheduler, GenerationRequest,
                             PrefixCacheConfig, SessionHandle)

BS = 8  # cache block size used throughout (n_positions=128 is a multiple)


def _model(**kw):
    kw.setdefault("dropout", 0.0)
    cfg = GPT2Config.tiny(**kw)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    return m


def _prompts(vocab=256, n_shared_blocks=3, n_tails=4, seed=0):
    """A shared system prompt of ``n_shared_blocks`` full blocks plus
    ragged per-request tails — the workload shape prefix caching
    exists for."""
    rng = np.random.RandomState(seed)
    system = rng.randint(0, vocab, n_shared_blocks * BS).astype(np.int32)
    return [np.concatenate([system,
                            rng.randint(0, vocab,
                                        rng.randint(3, 2 * BS)
                                        ).astype(np.int32)])
            for _ in range(n_tails)]


def _cache_kw(num_blocks=64):
    return dict(prefix_cache=PrefixCacheConfig(block_size=BS,
                                               num_blocks=num_blocks))


def _drain(eng, handles, prompts, news, m, check=True):
    eng.run_until_complete(max_steps=500)
    for h, p, n in zip(handles, prompts, news):
        if not check:
            continue
        want = m.generate(np.asarray(p), max_new_tokens=n,
                          temperature=0)
        np.testing.assert_array_equal(h.result().tokens, want)


def test_warm_streams_byte_identical_to_cold_greedy():
    """Round 2 over a populated cache produces streams byte-identical
    to single-prompt generate AND to the cache-disabled engine."""
    m = _model()
    prompts = _prompts()
    # two distinct budgets, not four: each distinct n_new compiles its
    # own offline-oracle scan, and the oracle compiles dominate this
    # test's wall time (fast-lane budget, VERDICT weak #3)
    news = [5, 3, 5, 3]
    eng = m.serve(max_slots=2, **_cache_kw())
    hs = [eng.submit(GenerationRequest(p, max_new_tokens=n))
          for p, n in zip(prompts, news)]
    _drain(eng, hs, prompts, news, m)
    # round 2: every admission now has cached blocks to hit
    hs2 = [eng.submit(GenerationRequest(p, max_new_tokens=n))
           for p, n in zip(prompts, news)]
    _drain(eng, hs2, prompts, news, m)
    snap = eng.stats.snapshot()["prefix"]
    assert snap["hits"] >= len(prompts), snap
    assert snap["hit_tokens"] > 0
    assert snap["lookup_tokens"] >= sum(len(p) for p in prompts)
    # cold engine oracle equality is implied by the generate oracle,
    # but assert the cache actually produced warm admissions
    assert snap["hit_rate_tokens"] > 0.3, snap


def test_warm_sampled_stream_matches_seeded_generate():
    m = _model()
    prompts = _prompts()
    eng = m.serve(max_slots=2, **_cache_kw())
    h0 = eng.submit(GenerationRequest(prompts[0], max_new_tokens=3))
    eng.run_until_complete(max_steps=200)   # populate the tree
    s = int(np.random.RandomState(11).randint(0, 2 ** 31 - 1))
    h = eng.submit(GenerationRequest(prompts[1], max_new_tokens=8,
                                     temperature=0.8, seed=s))
    eng.run_until_complete(max_steps=200)
    assert eng.stats.snapshot()["prefix"]["hits"] >= 1
    want = m.generate(np.asarray(prompts[1]), max_new_tokens=8,
                      temperature=0.8, rng=np.random.RandomState(11))
    np.testing.assert_array_equal(h.result().tokens, want)


def test_warm_gqa_stream_matches_generate():
    m = _model(n_kv_head=2)
    prompts = _prompts()
    news = [4, 4, 4]
    eng = m.serve(max_slots=1, **_cache_kw())
    hs = [eng.submit(GenerationRequest(p, max_new_tokens=n))
          for p, n in zip(prompts, news)]
    _drain(eng, hs, prompts, news, m)
    assert eng.stats.snapshot()["prefix"]["hits"] >= 2


def test_refcounts_pin_matched_path_across_flight():
    """Matched nodes hold a reference while the request is in flight
    (admission copy .. retire) and drop it at retire — the invariant
    that makes LRU eviction safe under concurrency."""
    m = _model()
    prompts = _prompts()
    eng = m.serve(max_slots=1, **_cache_kw())
    h = eng.submit(GenerationRequest(prompts[0], max_new_tokens=2))
    eng.run_until_complete(max_steps=100)   # donated at retire
    cache = eng.prefix_cache
    assert cache.cached_blocks >= 3
    h2 = eng.submit(GenerationRequest(prompts[1], max_new_tokens=4))
    eng.step()                              # admits: path pinned
    slot = next(s for s in eng._slots if s is not None)
    assert slot.prefix_nodes, "warm admission matched no blocks"
    assert all(n.refs == 1 for n in slot.prefix_nodes)
    eng.run_until_complete(max_steps=100)   # retire: unpinned
    assert all(n.refs == 0 for n in slot.prefix_nodes)
    assert h.result() is not None and h2.result() is not None


def test_lru_eviction_never_frees_referenced_blocks():
    """Under pool pressure, eviction only takes unreferenced leaves:
    a pinned session's path survives arbitrary churn, and pressure
    with nothing evictable degrades to skipped donations — never an
    error, never a freed referenced block."""
    m = _model()
    rng = np.random.RandomState(3)
    pinned_prompt = rng.randint(0, 256, 3 * BS).astype(np.int32)
    eng = m.serve(max_slots=1,
                  prefix_cache=PrefixCacheConfig(block_size=BS,
                                                 num_blocks=4))
    h = eng.submit(GenerationRequest(pinned_prompt, max_new_tokens=2,
                                     pin_session=True))
    eng.run_until_complete(max_steps=100)
    sess = h.result().session
    cache = eng.prefix_cache
    pinned_blocks = {n.block for n in sess._nodes}
    assert sess.pinned_blocks >= 3
    # churn: distinct prefixes wanting more blocks than remain
    for i in range(4):
        p = rng.randint(0, 256, 2 * BS + 3).astype(np.int32)
        hh = eng.submit(GenerationRequest(p, max_new_tokens=2))
        eng.run_until_complete(max_steps=100)
        assert hh.result().finish_reason == "length"
    snap = eng.stats.snapshot()["prefix"]
    assert snap["donate_skipped"] > 0, snap
    # the pinned path is still intact and matchable
    assert {n.block for n in sess._nodes} == pinned_blocks
    assert all(n.refs >= 1 for n in sess._nodes)
    assert len(cache.lookup(pinned_prompt)) == 3
    sess.release()
    assert all(n.refs == 0 for n in sess._nodes or []) or \
        sess.pinned_blocks == 0
    # released blocks are now evictable: more churn reuses them
    for i in range(3):
        p = rng.randint(0, 256, 2 * BS + 3).astype(np.int32)
        hh = eng.submit(GenerationRequest(p, max_new_tokens=2))
        eng.run_until_complete(max_steps=100)
    assert eng.stats.snapshot()["prefix"]["evictions"] > 0


def test_session_continuation_parity_multi_turn():
    """Turn 2 re-sends the whole turn-1 conversation: warm continuation
    through the pinned session is byte-identical to the cold oracle,
    and nearly all of its prompt comes from cached blocks."""
    m = _model()
    prompts = _prompts()
    eng = m.serve(max_slots=2, **_cache_kw())
    h = eng.submit(GenerationRequest(prompts[0], max_new_tokens=9,
                                     pin_session=True))
    eng.run_until_complete(max_steps=200)
    sess = h.result().session
    assert isinstance(sess, SessionHandle)
    np.testing.assert_array_equal(sess.tokens, h.result().tokens)
    extra = np.asarray([7, 3, 11, 2], np.int32)
    before = eng.stats.snapshot()["prefix"]["hit_tokens"]
    req2 = sess.request(extra, max_new_tokens=5, pin_session=True)
    h2 = eng.submit(req2)
    eng.run_until_complete(max_steps=200)
    want = m.generate(np.asarray(req2.prompt_ids), max_new_tokens=5,
                      temperature=0)
    np.testing.assert_array_equal(h2.result().tokens, want)
    gained = eng.stats.snapshot()["prefix"]["hit_tokens"] - before
    # the whole pinned history (all full blocks of turn 1) was a hit
    assert gained >= (len(sess.tokens) // BS - 1) * BS, gained
    # turn-3 session chains from turn 2
    sess2 = h2.result().session
    assert sess2 is not None and len(sess2.tokens) > len(sess.tokens)
    sess.release()
    sess2.release()


def test_session_continuation_parity_after_engine_restart():
    """An engine death between turns rebuilds with an EMPTY cache; the
    session handle still produces the next turn, cold, with the same
    bytes an uninterrupted conversation would have produced."""
    from singa_tpu.resilience import FailOnce, faults

    m = _model()
    prompts = _prompts()
    sup = EngineSupervisor(m, max_slots=2, restart_budget=2,
                           **_cache_kw())
    h = sup.submit(GenerationRequest(prompts[0], max_new_tokens=6,
                                     pin_session=True))
    sup.run_until_complete(max_steps=200)
    sess = h.result().session
    gen1 = sup.engine.stats.engine_label
    # kill the engine between turns (an in-flight victim absorbs it)
    victim = sup.submit(GenerationRequest(prompts[1], max_new_tokens=4))
    with faults.injected("serve.decode_step", FailOnce()):
        sup.run_until_complete(max_steps=200)
    assert sup.engine.stats.engine_label != gen1, "engine not rebuilt"
    with pytest.raises(EngineFailedError):
        victim.result()
    assert sup.engine.prefix_cache.cached_blocks == 0  # rebuilt empty
    req2 = sess.request(np.asarray([9, 9, 4], np.int32),
                        max_new_tokens=5)
    h2 = sup.submit(req2)
    sup.run_until_complete(max_steps=200)
    want = m.generate(np.asarray(req2.prompt_ids), max_new_tokens=5,
                      temperature=0)
    np.testing.assert_array_equal(h2.result().tokens, want)
    sup.close()


def test_arena_pressure_falls_back_to_cold_prefill():
    """A 1-block pool can cache almost nothing: every request still
    completes with exact parity (cold), and nothing raises."""
    m = _model()
    prompts = _prompts()
    news = [3, 3, 3, 3]
    eng = m.serve(max_slots=2,
                  prefix_cache=PrefixCacheConfig(block_size=BS,
                                                 num_blocks=1))
    hs = [eng.submit(GenerationRequest(p, max_new_tokens=n))
          for p, n in zip(prompts, news)]
    _drain(eng, hs, prompts, news, m)
    snap = eng.stats.snapshot()["prefix"]
    assert snap["donate_skipped"] > 0
    assert snap["cached_blocks"] <= 1


def test_prefix_copy_fault_fails_typed_and_supervisor_recovers():
    """An injected serve.prefix_copy fault (admission copy or retire
    donation) fails the engine TYPED — no wedged handle — and the
    supervisor rebuild serves the requeued work with parity."""
    from singa_tpu.resilience import FailOnce, faults

    m = _model()
    prompts = _prompts()
    news = [3, 4, 3, 5]
    sup = EngineSupervisor(m, max_slots=2, restart_budget=2,
                           **_cache_kw())
    # populate the cache so the fault can fire on a warm copy
    h0 = sup.submit(GenerationRequest(prompts[0], max_new_tokens=2))
    sup.run_until_complete(max_steps=200)
    handles = [sup.submit(GenerationRequest(p, max_new_tokens=n))
               for p, n in zip(prompts, news)]
    with faults.injected("serve.prefix_copy", FailOnce()):
        sup.run_until_complete(max_steps=500)
    wedged = [h for h in handles if not h.done()]
    assert not wedged, f"{len(wedged)} handles left unresolved"
    completed = typed = 0
    for h, p, n in zip(handles, prompts, news):
        try:
            got = h.result().tokens
            want = m.generate(np.asarray(p), max_new_tokens=n,
                              temperature=0)
            np.testing.assert_array_equal(got, want)
            completed += 1
        except EngineFailedError:
            typed += 1
    assert completed + typed == len(handles)
    assert completed > 0
    sup.close()


def test_warm_admissions_do_not_burn_prefill_interleave_budget():
    """max_prefills_per_step=1 throttles COLD admissions; a warm hit
    that recomputes at most one chunk is priced 0, so cached traffic
    backfills freely in the same step."""
    m = _model()
    prompts = _prompts()
    eng = m.serve(max_slots=4, **_cache_kw(),
                  scheduler=FIFOScheduler(max_prefills_per_step=1))
    # round 1 (cold): serialized one admission per step
    hs = [eng.submit(GenerationRequest(p, max_new_tokens=3))
          for p in prompts[:3]]
    eng.run_until_complete(max_steps=200)
    steps = sorted(h.result().admitted_step for h in hs)
    assert len(set(steps)) == 3
    # round 2 (warm): all three admit in ONE scheduling pass
    hs2 = [eng.submit(GenerationRequest(p, max_new_tokens=3))
           for p in prompts[:3]]
    eng.run_until_complete(max_steps=200)
    steps2 = {h.result().admitted_step for h in hs2}
    assert len(steps2) == 1, steps2


def test_scheduler_cost_semantics_unit():
    """FIFO order survives pricing: a too-expensive head blocks the
    step (no skipping ahead), zero-cost requests flow past the cap."""
    sched = FIFOScheduler(max_prefills_per_step=1)
    reqs = [GenerationRequest(np.asarray([1, 2, 3]), max_new_tokens=1,
                              request_id=f"c{i}") for i in range(4)]
    for r in reqs:
        sched.enqueue(r)
    costs = {"c0": 1, "c1": 0, "c2": 0, "c3": 1}
    admit, _ = sched.schedule(4, 0.0,
                              cost=lambda r: costs[r.request_id])
    assert [r.request_id for r in admit] == ["c0", "c1", "c2"]
    admit2, _ = sched.schedule(4, 0.0,
                               cost=lambda r: costs[r.request_id])
    assert [r.request_id for r in admit2] == ["c3"]


def test_prefix_cache_config_validation():
    m = _model()
    with pytest.raises(ValueError, match="multiple"):
        m.serve(max_slots=1,
                prefix_cache=PrefixCacheConfig(block_size=13))
    with pytest.raises(ValueError, match="block_size"):
        PrefixCacheConfig(block_size=0)
    with pytest.raises(ValueError, match="num_blocks"):
        PrefixCacheConfig(num_blocks=0)
    with pytest.raises(ValueError, match="prefix_cache"):
        m.serve(max_slots=1, prefix_cache="yes")
    # an empty kwargs dict means "enable with defaults", not "off"
    eng = m.serve(max_slots=1, prefix_cache={})
    assert eng.prefix_cache is not None
    assert eng.prefix_cache.block_size == 64
    eng.close()


def test_prefix_metrics_flow_into_health_and_prometheus():
    from singa_tpu import observe

    m = _model()
    prompts = _prompts()
    eng = m.serve(max_slots=1, **_cache_kw())
    for p in prompts[:2]:
        eng.submit(GenerationRequest(p, max_new_tokens=2))
    eng.run_until_complete(max_steps=200)
    report = observe.health_report(include_registry=False)
    sec = report["serve"]["prefix"]
    assert sec["hits"] >= 1 and sec["hit_tokens"] > 0
    assert 0.0 < sec["hit_rate_tokens"] <= 1.0
    text = observe.export.prometheus_text()
    assert "serve_prefix_hits" in text.replace(".", "_") or \
        "serve.prefix.hits" in text
    eng.close()
    # close() unregisters: the engine's prefix metrics leave the
    # registry snapshot
    snap = observe.registry().snapshot()["counters"]
    lbl = "{engine=" + eng.stats.engine_label + "}"
    assert ("serve.prefix.hits" + lbl) not in snap


# ---------------------------------------------------------------------------
# FleetPrefixIndex staleness: the residency directory vs the live tree
# ---------------------------------------------------------------------------

def test_fleet_index_stale_after_live_eviction():
    """The cross-host residency lifecycle at unit level: a hint is
    registered while the blocks are cached, per-replica LRU eviction
    silently invalidates it, the verify-against-the-live-tree step
    (what the fleet's _verified_holder does over the wire) detects
    the shortfall, and ``unregister`` prunes the lie — the next
    lookup reports no holder, so the request serves cold."""
    from singa_tpu.serve.prefix import FleetPrefixIndex

    m = _model()
    idx = FleetPrefixIndex(BS)
    eng = m.serve(max_slots=1, **_cache_kw(num_blocks=8))
    rng = np.random.RandomState(5)
    warm = rng.randint(0, 256, 3 * BS).astype(np.int32)
    h = eng.submit(GenerationRequest(warm, max_new_tokens=2))
    eng.run_until_complete(max_steps=100)
    h.result()
    n_cached = len(eng.prefix_cache.lookup(warm))
    assert n_cached >= 2
    idx.register(warm, n_cached, replica=0)
    assert idx.holders(warm, n_cached) == [0]

    # unrelated traffic floods the 8-block pool: the hinted path is
    # LRU-evicted from the LIVE tree while the directory still lies
    for i in range(4):
        p = rng.randint(0, 256, 3 * BS).astype(np.int32)
        eng.submit(GenerationRequest(p, max_new_tokens=2))
        eng.run_until_complete(max_steps=100)
    live = len(eng.prefix_cache.lookup(warm))
    assert live < n_cached                        # the hint went stale
    assert idx.holders(warm, n_cached) == [0]     # ...and still lies

    idx.unregister(warm, n_cached, replica=0)     # the verify verdict
    assert idx.holders(warm, n_cached) == []
    assert idx.snapshot()["indexed_blocks"] == 0
    eng.close()


def test_fleet_index_dead_host_drop_is_exhaustive():
    """drop_replica forgets a dead host EVERYWHERE — full spans,
    partial overlaps with a surviving host, and the node accounting —
    so a revived replica's empty tree never inherits stale claims."""
    from singa_tpu.serve.prefix import FleetPrefixIndex

    idx = FleetPrefixIndex(BS)
    rng = np.random.RandomState(9)
    a = rng.randint(0, 256, 3 * BS).astype(np.int32)
    b = rng.randint(0, 256, 2 * BS).astype(np.int32)
    idx.register(a, 3, replica=0)
    idx.register(a, 2, replica=1)                 # shared partial span
    idx.register(b, 2, replica=0)                 # replica-0 exclusive
    assert idx.holders(a, 3) == [0]
    assert idx.holders(a, 2) == [0, 1]

    idx.drop_replica(0)
    assert idx.holders(a, 3) == []                # dead host's span gone
    assert idx.holders(a, 2) == [1]               # survivor's claim kept
    assert idx.holders(b, 2) == []                # exclusive path pruned
    # only replica 1's two shared blocks remain indexed
    assert idx.snapshot()["indexed_blocks"] == 2
    idx.drop_replica(1)
    assert idx.snapshot()["indexed_blocks"] == 0
