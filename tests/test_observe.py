"""singa_tpu.observe: span tracing (deterministic clock), metrics
registry, exporters (Chrome trace / JSONL / Prometheus), EngineStats
registry adoption, and the disabled-mode overhead contract."""

import json
import threading

import pytest

from singa_tpu import observe
from singa_tpu.observe import export
from singa_tpu.observe.registry import MetricsRegistry


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


@pytest.fixture(autouse=True)
def _clean_tracer():
    """Each test starts with tracing off and an empty buffer; the
    process registry is shared (get-or-create), so tests below use
    private MetricsRegistry instances for exact-value asserts."""
    observe.disable()
    observe.clear()
    yield
    observe.disable()
    observe.clear()


# ---------------------------------------------------------------------------
# trace core
# ---------------------------------------------------------------------------

def test_span_nesting_with_deterministic_clock():
    clk = FakeClock()
    observe.enable(clock=clk)
    with observe.span("outer", cat="train", step=7) as sp:
        clk.advance(1.0)
        with observe.span("inner", cat="train"):
            clk.advance(0.5)
        sp.set(loss=0.25)
        clk.advance(2.0)
    evs = observe.events()
    assert [e["name"] for e in evs] == ["inner", "outer"]  # exit order
    inner, outer = evs
    assert inner["parent"] == "outer" and inner["depth"] == 1
    assert outer["parent"] is None and outer["depth"] == 0
    assert inner["ts"] == 1.0 and inner["dur"] == 0.5
    assert outer["ts"] == 0.0 and outer["dur"] == 3.5
    assert outer["args"] == {"step": 7, "loss": 0.25}


def test_event_instant_and_stack_attribution():
    clk = FakeClock(5.0)
    observe.enable(clock=clk)
    with observe.span("scope", cat="serve"):
        observe.event("tick", cat="serve", slot=3)
    ev = [e for e in observe.events() if e["ph"] == "i"][0]
    assert ev["name"] == "tick" and ev["parent"] == "scope"
    assert ev["ts"] == 5.0 and ev["args"] == {"slot": 3}


def test_traced_decorator_names_and_args():
    observe.enable(clock=FakeClock())

    @observe.traced
    def plain():
        return 41

    @observe.traced(name="custom/name", cat="serve")
    def named():
        return 1

    assert plain() + named() == 42
    names = {(e["name"], e["cat"]) for e in observe.events()}
    assert ("custom/name", "serve") in names
    assert any(n.endswith("plain") for n, _ in names)


def test_disabled_mode_is_noop_singleton():
    """The overhead contract: disabled span() returns ONE shared
    object (no allocation) and records nothing."""
    assert not observe.is_enabled()
    s1 = observe.span("a", cat="x", big_arg=list(range(100)))
    s2 = observe.span("b")
    assert s1 is s2  # the shared null span
    with s1 as s:
        s.set(anything=1)
    observe.event("nope")

    @observe.traced
    def f():
        return 3

    for _ in range(10_000):
        with observe.span("hot"):
            pass
        f()
    assert observe.events() == []


def test_disable_mid_span_records_nothing():
    clk = FakeClock(1000.0)
    observe.enable(clock=clk)
    with observe.span("crossing"):
        observe.disable()  # swaps the clock back to perf_counter
    # the half-open span must NOT be emitted with a garbage duration
    assert observe.events() == []


def test_buffer_cap_drops_not_grows():
    observe.enable(clock=FakeClock())
    observe.set_max_events(10)
    try:
        for i in range(25):
            observe.event(f"e{i}")
        assert len(observe.events()) == 10
        assert observe.trace.dropped() == 15
    finally:
        observe.set_max_events(1_000_000)


def test_threaded_spans_keep_separate_stacks():
    observe.enable(clock=FakeClock())
    done = threading.Event()

    def worker():
        with observe.span("w", cat="bg"):
            pass
        done.set()

    with observe.span("main", cat="fg"):
        t = threading.Thread(target=worker, name="bg-thread")
        t.start()
        t.join()
    assert done.is_set()
    w = [e for e in observe.events() if e["name"] == "w"][0]
    # the worker's span must not see the main thread's open span
    assert w["parent"] is None and w["depth"] == 0
    assert w["tid"] == "bg-thread"


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

def test_registry_get_or_create_and_kinds():
    reg = MetricsRegistry()
    c = reg.counter("x.count", op="sum")
    assert reg.counter("x.count", op="sum") is c
    c.inc().inc(4)
    assert c.value == 5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("x.level")
    g.set(3.5)
    g.dec(0.5)
    assert g.value == 3.0
    h = reg.histogram("x.lat")
    h.observe(0.1)
    h.observe(0.3)
    assert h.count == 2 and h.summary()["p50"] == 0.1
    with pytest.raises(TypeError):
        reg.gauge("x.count", op="sum")  # kind morph forbidden
    with pytest.raises(TypeError):
        # even under DIFFERENT labels: a Prometheus family shares one
        # TYPE declaration, so kind is enforced per name
        reg.gauge("x.count", op="other")


def test_registry_snapshot_schema():
    reg = MetricsRegistry()
    reg.counter("a").inc(2)
    reg.gauge("b", k="v").set(1)
    reg.histogram("c").observe(2.0)
    snap = reg.snapshot()
    assert snap["counters"] == {"a": 2}
    assert snap["gauges"] == {"b{k=v}": 1}
    assert snap["histograms"]["c"]["count"] == 1
    json.dumps(snap)  # JSON-able end to end


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def _sample_events():
    clk = FakeClock()
    observe.enable(clock=clk)
    with observe.span("train/step", cat="train", step=1):
        clk.advance(0.25)
    with observe.span("serve/decode_step", cat="serve", live=4):
        clk.advance(0.001)
    observe.event("graph/cache_miss", cat="train", key="k0")
    observe.disable()
    return observe.events()


def test_chrome_trace_schema_roundtrip(tmp_path):
    evs = _sample_events()
    path = tmp_path / "trace.json"
    n = export.write_chrome_trace(str(path), evs)
    doc = json.loads(path.read_text())
    tes = doc["traceEvents"]
    assert isinstance(tes, list) and len(tes) == n
    # one thread_name metadata row per subsystem (cat)
    meta = {e["args"]["name"]: e["tid"] for e in tes if e["ph"] == "M"}
    assert set(meta) == {"train", "serve"}
    xs = [e for e in tes if e["ph"] == "X"]
    for e in xs:
        assert {"name", "cat", "ts", "dur", "pid", "tid"} <= set(e)
        assert e["tid"] == meta[e["cat"]]  # track per subsystem
    step = next(e for e in xs if e["name"] == "train/step")
    assert step["ts"] == 0.0 and step["dur"] == 0.25 * 1e6  # µs
    assert step["args"]["step"] == 1
    inst = next(e for e in tes if e["ph"] == "i")
    assert inst["name"] == "graph/cache_miss" and inst["s"] == "t"


def test_jsonl_roundtrip(tmp_path):
    evs = _sample_events()
    path = tmp_path / "events.jsonl"
    n = export.write_jsonl(str(path), evs)
    lines = path.read_text().splitlines()
    assert len(lines) == n == len(evs)
    back = [json.loads(ln) for ln in lines]
    assert back == json.loads(json.dumps(evs))


def test_prometheus_text_format():
    reg = MetricsRegistry()
    reg.counter("graph.cache_miss", help="compiles").inc(3)
    reg.gauge("serve.queue_depth", engine="0").set(2)
    h = reg.histogram("serve.ttft", engine="0")
    h.observe(0.2)
    h.observe(0.4)
    text = export.prometheus_text(reg)
    lines = text.splitlines()
    # counter TYPE/HELP declared under the _total SAMPLE name
    # (prometheus_client classic-format convention)
    assert "# HELP singa_tpu_graph_cache_miss_total compiles" in lines
    assert "# TYPE singa_tpu_graph_cache_miss_total counter" in lines
    assert "singa_tpu_graph_cache_miss_total 3" in lines
    assert "# TYPE singa_tpu_serve_queue_depth gauge" in lines
    assert 'singa_tpu_serve_queue_depth{engine="0"} 2' in lines
    # histograms export as REAL histogram families (cumulative
    # _bucket series aggregable across a fleet of scraped replicas),
    # with the in-process nearest-rank quantiles as a sibling gauge
    # family — not as quantile samples inside the histogram family,
    # which conformant scrapers reject
    assert "# TYPE singa_tpu_serve_ttft histogram" in lines
    assert 'singa_tpu_serve_ttft_bucket{engine="0",le="0.25"} 1' \
        in lines
    assert 'singa_tpu_serve_ttft_bucket{engine="0",le="0.5"} 2' \
        in lines
    assert 'singa_tpu_serve_ttft_bucket{engine="0",le="+Inf"} 2' \
        in lines
    assert 'singa_tpu_serve_ttft_count{engine="0"} 2' in lines
    assert "# TYPE singa_tpu_serve_ttft_quantile gauge" in lines
    assert ('singa_tpu_serve_ttft_quantile{engine="0",quantile="0.5"}'
            ' 0.2' in lines)
    # exposition charset: no dots/slashes survive in metric names
    for ln in lines:
        if not ln.startswith("#"):
            assert "." not in ln.split("{")[0].split(" ")[0]


def test_prometheus_bucket_override_and_inf_invariant():
    """Per-metric bucket ladders override the default, cumulative
    counts are monotone, and le="+Inf" always equals _count."""
    reg = MetricsRegistry()
    h = reg.histogram("serve.request.queue_wait_s", engine="0",
                      buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    assert h.bucket_counts() == [(0.1, 1), (1.0, 2),
                                 (float("inf"), 3)]
    lines = export.prometheus_text(reg).splitlines()
    pfx = "singa_tpu_serve_request_queue_wait_s"
    assert f'{pfx}_bucket{{engine="0",le="0.1"}} 1' in lines
    assert f'{pfx}_bucket{{engine="0",le="1"}} 2' in lines
    assert f'{pfx}_bucket{{engine="0",le="+Inf"}} 3' in lines
    assert f'{pfx}_count{{engine="0"}} 3' in lines
    # a default-ladder histogram ends in the same invariant
    d = reg.histogram("serve.ttft", engine="0")
    d.observe(0.2)
    lines = export.prometheus_text(reg).splitlines()
    assert 'singa_tpu_serve_ttft_bucket{engine="0",le="+Inf"} 1' \
        in lines
    with pytest.raises(ValueError):
        reg.histogram("bad.buckets", buckets=(1.0, 0.5))


def test_prometheus_sum_count_stay_consistent_under_windowing():
    """_sum comes from the series' RUNNING total, not the retained
    values window — evicting values must not desync the pair."""
    from singa_tpu.utils.metrics import LatencySeries

    reg = MetricsRegistry()
    h = reg.histogram("serve.ttft", engine="0",
                      series=LatencySeries(max_samples=2))
    for v in (0.1, 0.2, 0.3):
        h.observe(v)  # the bounded ring evicts 0.1
    assert list(h.series.values) == [0.2, 0.3]
    lines = export.prometheus_text(reg).splitlines()
    assert 'singa_tpu_serve_ttft_sum{engine="0"} 0.6000000000000001' \
        in lines
    assert 'singa_tpu_serve_ttft_count{engine="0"} 3' in lines


def test_dropped_is_public_and_rides_chrome_metadata():
    """Satellite: observe.dropped() is part of the public API and a
    truncated trace is self-describing in its Chrome metadata."""
    observe.enable(clock=FakeClock())
    observe.set_max_events(5)
    try:
        for i in range(8):
            observe.event(f"e{i}")
        assert observe.dropped() == 3  # re-exported at package level
        doc = export.chrome_trace(observe.events())
        assert doc["otherData"]["dropped_events"] == 3
    finally:
        observe.set_max_events(1_000_000)


# ---------------------------------------------------------------------------
# EngineStats adoption
# ---------------------------------------------------------------------------

def test_engine_stats_registers_into_registry():
    from singa_tpu.serve.stats import EngineStats

    clk = FakeClock()
    reg = MetricsRegistry()
    st = EngineStats(max_slots=4, clock=clk, reg=reg)
    st.on_submit()
    st.on_submit()
    st.on_prefill()
    st.on_decode_step(live_slots=3)
    st.on_token()
    st.on_schedule(queue_depth=5)
    st.on_queue_full("r-1")

    lbl = dict(engine=st.engine_label)
    assert reg.counter("serve.submitted", **lbl).value == 2
    assert reg.counter("serve.prefills", **lbl).value == 1
    assert reg.counter("serve.tokens_out", **lbl).value == 1
    assert reg.counter("serve.rejected_queue_full", **lbl).value == 1
    assert reg.gauge("serve.queue_depth", **lbl).value == 5
    assert reg.gauge("serve.occupancy", **lbl).value == 0.75
    # the registry ADOPTED the TTFT series: same object, two views
    assert reg.histogram("serve.ttft", **lbl).series is st.ttft

    class R:
        ttft = 0.5
        tpot = 0.01

    st.on_complete(R())
    assert reg.histogram("serve.ttft", **lbl).count == 1
    # snapshot schema unchanged by the registry rebase
    snap = st.snapshot()
    assert snap["requests"]["submitted"] == 2
    assert snap["queue"]["max_depth"] == 5
    assert snap["slots"]["occupancy_mean"] == 0.75
    json.dumps(snap)


def test_two_engines_do_not_collide():
    from singa_tpu.serve.stats import EngineStats

    reg = MetricsRegistry()
    a = EngineStats(2, FakeClock(), reg=reg)
    b = EngineStats(2, FakeClock(), reg=reg)
    a.on_submit()
    a.on_submit()
    b.on_submit()
    assert a.submitted == 2 and b.submitted == 1


def test_engine_stats_unregister_releases_metrics():
    from singa_tpu.serve.stats import EngineStats

    reg = MetricsRegistry()
    a = EngineStats(2, FakeClock(), reg=reg)
    b = EngineStats(2, FakeClock(), reg=reg)
    a.on_submit()
    assert len(reg.metrics()) == 28  # 14 per engine (incl. the
    #   queue-wait + cold/warm admission request-phase histograms)
    a.unregister()
    remaining = reg.metrics()
    assert len(remaining) == 14
    assert all(("engine", b.engine_label) in m.labels
               for m in remaining)
    # a fully-removed NAME frees its kind reservation
    c = reg.counter("ephemeral")
    reg.remove(c)
    reg.gauge("ephemeral")  # no TypeError: the name was freed
    # the retired stats object still reads its own counters
    assert a.submitted == 1 and a.snapshot()["requests"]["submitted"] == 1


def test_engine_close_unregisters_and_requires_drain():
    import numpy as np

    from singa_tpu import tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.observe.registry import registry
    from singa_tpu.serve import GenerationRequest

    cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=16,
                     n_layer=1, n_head=2, n_inner=32, dropout=0.0,
                     attn_impl="fused")
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 4), np.int32))],
              is_train=False, use_graph=False)
    with m.serve(max_slots=2) as eng:
        lbl = dict(engine=eng.stats.engine_label)
        eng.submit(GenerationRequest(np.asarray([1, 2, 3]),
                                     max_new_tokens=2))
        with pytest.raises(RuntimeError):
            eng.close()  # work in flight
        eng.run_until_complete(max_steps=20)
    # context exit closed it: serve.* metrics for THIS engine are gone
    assert not any(dict(mm.labels).get("engine") == lbl["engine"]
                   for mm in registry().metrics())
    eng.close()  # idempotent
    with pytest.raises(RuntimeError, match="closed"):
        eng.submit(GenerationRequest(np.asarray([1]), max_new_tokens=1))
    with pytest.raises(RuntimeError, match="closed"):
        eng.step()


def test_drain_swaps_buffer():
    observe.enable(clock=FakeClock())
    observe.event("a")
    observe.event("b")
    out = observe.drain()
    assert [e["name"] for e in out] == ["a", "b"]
    assert observe.events() == []
    observe.event("c")
    assert [e["name"] for e in observe.events()] == ["c"]


def test_chrome_trace_survives_numpy_args(tmp_path):
    import numpy as np

    observe.enable(clock=FakeClock())
    with observe.span("s", cat="x", loss=np.float32(0.5)):
        pass
    observe.disable()
    path = tmp_path / "np_trace.json"
    export.write_chrome_trace(str(path), observe.events())
    doc = json.loads(path.read_text())
    ev = next(e for e in doc["traceEvents"] if e["ph"] == "X")
    assert ev["args"]["loss"] == "0.5"  # stringified, not crashed


# ---------------------------------------------------------------------------
# instrumented sites
# ---------------------------------------------------------------------------

def test_communicator_records_collective_metrics():
    import numpy as np

    from singa_tpu.observe.registry import registry
    from singa_tpu.parallel.communicator import _record_collective

    reg = registry()
    before = reg.counter("comms.collectives", op="all_reduce").value
    before_b = reg.counter("comms.bytes", op="all_reduce").value
    observe.enable(clock=FakeClock())
    _record_collective("all_reduce", [np.zeros((4, 8), np.float32)])
    assert reg.counter("comms.collectives",
                       op="all_reduce").value == before + 1
    assert reg.counter("comms.bytes",
                       op="all_reduce").value == before_b + 4 * 8 * 4
    ev = [e for e in observe.events() if e["cat"] == "comms"][-1]
    assert ev["name"] == "comms/all_reduce"
    assert ev["args"]["bytes"] == 128


def test_graph_runner_counts_compiles_and_replays():
    import numpy as np

    from singa_tpu import device, opt, tensor
    from singa_tpu.models.mlp import MLP
    from singa_tpu.observe.registry import registry

    dev = device.create_tpu_device(0)
    dev.SetRandSeed(0)
    m = MLP(data_size=8, perceptron_size=4, num_classes=3)
    m.set_optimizer(opt.SGD(lr=0.05))
    rng = np.random.RandomState(0)
    x = tensor.from_numpy(rng.randn(4, 8).astype(np.float32), dev)
    y = tensor.from_numpy(rng.randint(0, 3, (4,)).astype(np.int32), dev)
    m.compile([x], is_train=True, use_graph=True)

    reg = registry()
    h0 = reg.counter("graph.cache_hit").value
    m0 = reg.counter("graph.cache_miss").value
    s0 = reg.counter("train.steps").value
    observe.enable(clock=FakeClock())
    m(x, y)          # compile
    m(x, y)          # replay
    m(x, y)          # replay
    observe.disable()
    assert reg.counter("graph.cache_miss").value == m0 + 1
    assert reg.counter("graph.cache_hit").value == h0 + 2
    assert reg.counter("train.steps").value == s0 + 3
    names = [e["name"] for e in observe.events()]
    assert names.count("graph/compile") == 1
    assert names.count("train/step") == 3
    assert "graph/cache_miss" in names
    compile_span = next(e for e in observe.events()
                        if e["name"] == "graph/compile")
    # XLA cost-table estimates ride the span args (flops present on
    # the CPU backend too)
    assert "flops" in compile_span["args"]
