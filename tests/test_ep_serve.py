"""Expert-parallel MoE serving (serve/ep.py + the engine's ``ep=``
mode): token-stream parity against the single-device MoE engine on the
virtual CPU mesh (cold / warm / int8 / GQA / speculative /
preempt-resume, greedy AND seeded sampling mixed in one pool),
capacity-overflow determinism under a finite ``capacity_factor``,
supervisor restart under an injected ``serve.ep_dispatch`` fault,
typed config validation (fired BEFORE any registration — the
leaked-gauge audit), expert-load observability, and the
metrics/health/unregister surface.

The single-device engine is the oracle (itself parity-pinned against
single-prompt ``generate`` in tests/test_serve.py), so EP parity here
is transitively offline-oracle parity.  At the default
``capacity_factor=None`` nothing ever drops and routing is per-token
independent, so the ONE arithmetic difference is the per-MoE-layer
psum over the ``ep`` axis (plus the dense layers' tp psums when
``EPConfig(tp>1)``) — float addition order, identity on token streams
away from exact ties; every workload below is seed-pinned
deterministic."""

import numpy as np
import pytest

from singa_tpu import tensor
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from singa_tpu.observe import health_report
from singa_tpu.observe.registry import registry
from singa_tpu.resilience import FailAfterN, faults
from singa_tpu.serve import (EngineFailedError, EngineSupervisor,
                             EPConfig, GenerationRequest, PagedConfig,
                             PrefixCacheConfig, ServeFleet)


def _build(cfg):
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    return m


@pytest.fixture(scope="module")
def model():
    """2-layer GPT-MoE: every 2nd block's MLP is a 4-expert top-2
    MoEFFN (the architecture serve/tp.py refuses and this round
    serves)."""
    return _build(GPT2Config.tiny(dropout=0.0, moe_every=2,
                                  moe_experts=4))


@pytest.fixture(scope="module")
def draft():
    return _build(GPT2Config.tiny(dropout=0.0, n_layer=1))


def _workload(seed, n, p_lo=3, p_hi=14, n_lo=2, n_hi=9, sampled=True):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        out.append(dict(
            prompt=rng.randint(0, 256, rng.randint(p_lo, p_hi))
            .astype(np.int32),
            n_new=int(rng.randint(n_lo, n_hi)),
            temperature=(float(rng.choice([0.0, 0.9]))
                         if sampled else 0.0),
            seed=int(rng.randint(0, 1000))))
    return out


def _run(m, work, max_slots=2, max_steps=4000, **kw):
    eng = m.serve(max_slots=max_slots, **kw)
    hs = [eng.submit(GenerationRequest(
        w["prompt"], max_new_tokens=w["n_new"],
        temperature=w["temperature"], seed=w["seed"]))
        for w in work]
    eng.run_until_complete(max_steps=max_steps)
    outs = [h.result().tokens for h in hs]
    snap = eng.stats.snapshot()
    eng.close()
    return outs, snap


def _parity(a, b):
    return all(np.array_equal(x, y) for x, y in zip(a, b))


def test_cold_parity_ep2_tp2(model):
    """ep=2 x tp=2 on the 8-device mesh: experts sharded over ep,
    dense layers Megatron over tp — streams token-identical to the
    single-device MoE engine, and the stats snapshot carries the ep
    section with per-expert routed-token load."""
    work = _workload(0, 7, sampled=True)
    base, _ = _run(model, work)
    outs, snap = _run(model, work, ep=EPConfig(ep=2, tp=2))
    assert _parity(outs, base)
    ep = snap["ep"]
    assert ep["shards"] == 2 and ep["dense_tp"] == 2
    assert ep["experts"] == 4 and ep["experts_per_shard"] == 2
    assert ep["capacity_factor"] is None
    assert ep["sharded_dispatches"] > 0
    assert ep["kv_bytes_per_shard"] > 0
    assert sum(ep["expert_tokens"]) > 0
    assert ep["dropped_tokens"] == 0, \
        "capacity_factor=None must never drop"
    assert ep["load_imbalance"] is not None


def test_cold_parity_ep4(model):
    """The full expert axis sharded one expert per device (ep=4)."""
    work = _workload(1, 4, sampled=True)
    base, _ = _run(model, work)
    outs, snap = _run(model, work, ep=4)
    assert _parity(outs, base)
    assert snap["ep"]["shards"] == 4
    assert snap["ep"]["experts_per_shard"] == 1


def test_gqa_parity_ep2_tp2():
    """GQA MoE: the narrow H_kv cache shards over the orthogonal tp
    axis (replicated over ep), experts over ep — both at once."""
    m = _build(GPT2Config.tiny(dropout=0.0, n_kv_head=2, moe_every=2,
                               moe_experts=4))
    work = _workload(2, 5, n_lo=6, n_hi=14, p_lo=4, p_hi=16)
    base, _ = _run(m, work, max_slots=3)
    outs, _ = _run(m, work, max_slots=3, ep=EPConfig(ep=2, tp=2))
    assert _parity(outs, base)


def test_int8_parity_and_scales_sharding(model):
    """int8 arenas under EP: token parity vs the single-device int8
    MoE engine, and the (values, scales) leaves shard on the H_kv
    axis over the tp sub-axis of the (ep, tp) mesh — each of the 4
    mesh devices holds an addressable H_kv/tp slice (replicated
    across ep)."""
    work = _workload(3, 5, sampled=True)
    base, _ = _run(model, work, cache_dtype="int8")

    eng = model.serve(max_slots=2, ep=EPConfig(ep=2, tp=2),
                      cache_dtype="int8")
    try:
        vals, scales = eng._kc
        H = model.cfg.n_kv_head
        assert vals.shape[2] == H and scales.shape[2] == H
        assert vals.addressable_shards[0].data.shape[2] == H // 2
        assert scales.addressable_shards[0].data.shape[2] == H // 2
        assert len(vals.addressable_shards) == 4  # ep x tp devices
        hs = [eng.submit(GenerationRequest(
            w["prompt"], max_new_tokens=w["n_new"],
            temperature=w["temperature"], seed=w["seed"]))
            for w in work]
        eng.run_until_complete(max_steps=4000)
        outs = [h.result().tokens for h in hs]
    finally:
        eng.close(force=True)
    assert _parity(outs, base)


def test_spec_parity_ep2(model, draft):
    """Speculative decoding on an expert-sharded TARGET with a fully
    REPLICATED dense draft (greedy — the byte-parity regime): the
    draft proposes identically on every rank, the verify chunk routes
    through the capacity-bounded EP dispatch."""
    work = _workload(4, 5, n_lo=4, n_hi=12, sampled=False)
    base, _ = _run(model, work, max_slots=3)
    outs, snap = _run(model, work, max_slots=3, ep=2,
                      draft_model=draft, spec_k=3)
    assert _parity(outs, base)
    assert snap["spec"]["chunks"] > 0


def test_paged_preempt_resume_parity_ep2(model):
    """Paged pool under EP (tp sub-axis slices, replicated over ep):
    an over-committed pool forces preemption/swap mid-decode and the
    resumed streams equal the uninterrupted single-device run's —
    swap images carry the full head axis, blocks never leak."""
    work = _workload(5, 6, n_lo=12, n_hi=30, p_lo=4, p_hi=20,
                     sampled=True)
    base, _ = _run(model, work, max_slots=4)
    outs, snap = _run(model, work, max_slots=4, ep=2,
                      paged=PagedConfig(block_size=8, num_blocks=10))
    assert _parity(outs, base)
    pg = snap["paged"]
    assert pg["preemptions"] > 0 and pg["swap_in"] > 0
    assert pg["blocks_used"] == 0, "leaked blocks after drain"


def test_warm_prefix_parity_ep2(model):
    """Prefix cache on an EP engine (legal at capacity_factor=None —
    drop-free routing is per-token independent, so chunked prefill
    stays canonical): a shared system prompt goes warm and streams
    stay byte-identical to the single-device engine."""
    rng = np.random.RandomState(6)
    system = rng.randint(0, 256, 40).astype(np.int32)
    work = [dict(prompt=np.concatenate(
        [system, rng.randint(0, 256, rng.randint(3, 8))
         .astype(np.int32)]),
        n_new=6, temperature=0.0, seed=int(rng.randint(0, 1000)))
        for _ in range(5)]
    base, _ = _run(model, work)
    outs, snap = _run(model, work, ep=2,
                      prefix_cache=PrefixCacheConfig(block_size=8,
                                                     num_blocks=64))
    assert _parity(outs, base)
    assert snap["prefix"]["hits"] > 0, "workload never went warm"


def test_capacity_overflow_determinism(model):
    """A FINITE capacity_factor is the GShard capacity mode: prefill
    dispatch groups drop over-capacity assignments through the
    residual path.  The drop pattern must be DETERMINISTIC — two
    fresh engines over the same workload produce identical streams —
    and counted (``dropped_tokens`` > 0 under a factor tight enough
    to overflow)."""
    work = _workload(9, 5, p_lo=16, p_hi=30, sampled=True)
    cfg = EPConfig(ep=2, capacity_factor=0.25)
    a, snap_a = _run(model, work, ep=cfg,
                     paged=PagedConfig(block_size=8, num_blocks=48))
    b, snap_b = _run(model, work, ep=cfg,
                     paged=PagedConfig(block_size=8, num_blocks=48))
    assert _parity(a, b), "capacity drops must be deterministic"
    assert snap_a["ep"]["dropped_tokens"] > 0, \
        "factor 0.25 over 16+-token prefills must overflow"
    assert snap_a["ep"]["dropped_tokens"] == \
        snap_b["ep"]["dropped_tokens"]


def test_expert_load_observability(model):
    """The dispatch twins feed the expert-load surface everywhere it
    is promised: per-expert registry counters (labeled expert=),
    snapshot()["ep"]["expert_tokens"], and a LIVE
    health_report()["serve"]["ep"] with the imbalance ratio."""
    eng = model.serve(max_slots=2, ep=2)
    try:
        h = eng.submit(GenerationRequest(
            np.arange(9, dtype=np.int32), max_new_tokens=4))
        eng.run_until_complete(max_steps=200)
        h.result()
        lbl = eng.stats.engine_label
        counters = registry().snapshot()["counters"]
        per_expert = [
            counters.get(
                f"serve.ep.expert_tokens{{engine={lbl},expert={e}}}",
                0)
            for e in range(4)]
        assert sum(per_expert) > 0
        snap = eng.stats.snapshot()["ep"]
        assert snap["expert_tokens"] == per_expert
        rep = health_report(include_registry=False)
        ep = rep["serve"]["ep"]
        assert ep["shards"] == 2
        assert sum(ep["expert_tokens"]) >= sum(per_expert)
        assert ep["load_imbalance"] is not None
        assert ep["dropped_tokens"] == 0
    finally:
        eng.close()


def test_supervisor_restart_ep2(model):
    """An injected ``serve.ep_dispatch`` fault fails the sharded
    engine TYPED mid-decode; the supervisor rebuilds it (same device
    group, twin-cache hit) and requeued never-started streams keep
    parity.  Zero wedged handles."""
    work = _workload(7, 6, n_lo=4, n_hi=10, sampled=True)
    base, _ = _run(model, work)
    restarts0 = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0)
    sup = EngineSupervisor(model, max_slots=2, restart_budget=2, ep=2)
    hs = [sup.submit(GenerationRequest(
        w["prompt"], max_new_tokens=w["n_new"],
        temperature=w["temperature"], seed=w["seed"]))
        for w in work]
    pol = faults.inject("serve.ep_dispatch", FailAfterN(3, times=1))
    try:
        sup.run_until_complete(max_steps=4000)
    finally:
        faults.clear()
    assert pol.fired == 1
    restarts = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0) - restarts0
    assert restarts == 1
    completed = typed = 0
    for i, h in enumerate(hs):
        assert h.done(), "wedged handle after EP restart"
        try:
            got = h.result().tokens
            assert np.array_equal(got, base[i])
            completed += 1
        except EngineFailedError as e:
            assert e.started is True
            typed += 1
    assert completed + typed == len(work)
    assert completed > 0
    sup.close()


def test_fleet_of_ep_replicas(model):
    """serve_fleet(ep=EPConfig(ep=2, tp=2), replicas=2) partitions
    the 8-device mesh into disjoint 4-wide (ep x tp) groups; streams
    keep parity and both replicas carry traffic."""
    work = _workload(8, 8, sampled=True)
    base, _ = _run(model, work, max_slots=4)
    fleet = ServeFleet(model, replicas=2, max_slots=2,
                       ep=EPConfig(ep=2, tp=2))
    try:
        d0 = fleet.supervisor(0).engine.ep_exec.mesh.devices.flat
        d1 = fleet.supervisor(1).engine.ep_exec.mesh.devices.flat
        assert {d.id for d in d0}.isdisjoint({d.id for d in d1})
        hs = [fleet.submit(GenerationRequest(
            w["prompt"], max_new_tokens=w["n_new"],
            temperature=w["temperature"], seed=w["seed"]))
            for w in work]
        fleet.run_until_complete(max_steps=4000)
        outs = [h.result().tokens for h in hs]
        snap = fleet.snapshot()
    finally:
        fleet.close()
    assert _parity(outs, base)
    assert all(v > 0 for v in snap["routed"].values())


def test_config_validation(model):
    """Every incompatible ep configuration is a typed construction
    error fired BEFORE any registration (no serve.ep gauge may leak
    from a refused construction — the PR-12 hazard, audited)."""

    def ep_gauges():
        return {k for k in registry().snapshot()["gauges"]
                if k.startswith("serve.ep.")}

    before = ep_gauges()
    # ep on a dense model: no expert axis
    dense = _build(GPT2Config.tiny(dropout=0.0))
    with pytest.raises(ValueError, match="dense model"):
        dense.serve(max_slots=2, ep=2)
    # ep not dividing moe_experts (4 experts)
    with pytest.raises(ValueError, match="does not divide "
                                         "moe_experts"):
        model.serve(max_slots=2, ep=3)
    # orthogonal tp not dividing n_head (tiny: n_head=4)
    with pytest.raises(ValueError, match="does not divide n_head"):
        model.serve(max_slots=2, ep=EPConfig(ep=2, tp=3))
    # ep together with the bare tp= knob
    with pytest.raises(ValueError, match="drop the bare"):
        model.serve(max_slots=2, ep=2, tp=2)
    # ep together with pp
    with pytest.raises(ValueError, match="not both"):
        model.serve(max_slots=2, ep=2, pp=2)
    # finite capacity factor next to a prefix cache: chunk
    # canonicality cannot hold
    with pytest.raises(ValueError, match="capacity_factor"):
        model.serve(max_slots=2,
                    ep=EPConfig(ep=2, capacity_factor=1.25),
                    prefix_cache=PrefixCacheConfig(block_size=8))
    # mesh too small (8-device conftest topology)
    with pytest.raises(ValueError, match="devices"):
        model.serve(max_slots=2, ep=EPConfig(ep=4, tp=4))
    # (ep x tp) x replicas exceeding the mesh
    with pytest.raises(ValueError, match="exceeds"):
        ServeFleet(model, replicas=3, max_slots=2,
                   ep=EPConfig(ep=2, tp=2))
    # bad knob type
    with pytest.raises(ValueError, match="EPConfig"):
        model.serve(max_slots=2, ep="wide")
    # a bad capacity factor is a config-time error
    with pytest.raises(ValueError, match="capacity_factor"):
        EPConfig(ep=2, capacity_factor=0.0)
    assert ep_gauges() == before, \
        "a refused construction leaked serve.ep gauges"
    # ep=1 (x tp=1) is simply off
    eng = model.serve(max_slots=2, ep=1)
    assert eng.ep_exec is None
    eng.close()
    # explicit EPConfig passes through
    eng = model.serve(max_slots=2, ep=EPConfig(ep=2))
    assert eng.ep_exec is not None and eng.ep_exec.ep == 2
    eng.close()


def test_metrics_and_health_unregister(model):
    """serve.ep.* metrics register per engine, surface in
    health_report()["serve"]["ep"], and unregister at close; the
    health section stays present (zeroed) with no live EP engine."""
    eng = model.serve(max_slots=2, ep=2)
    lbl = eng.stats.engine_label
    try:
        h = eng.submit(GenerationRequest(
            np.arange(5, dtype=np.int32), max_new_tokens=3))
        eng.run_until_complete(max_steps=200)
        h.result()
        rep = health_report(include_registry=False)
        ep = rep["serve"]["ep"]
        assert ep["shards"] == 2
        assert ep["kv_bytes_per_shard"] > 0
        assert ep["sharded_dispatches"] > 0
    finally:
        eng.close()
    snap = registry().snapshot()
    assert f"serve.ep.shards{{engine={lbl}}}" not in snap["gauges"], \
        "ep gauges leaked past close()"
    assert not any(
        k.startswith("serve.ep.expert_tokens{")
        and f"engine={lbl}" in k
        for k in snap["counters"]), \
        "per-expert counters leaked past close()"
    rep = health_report(include_registry=False)
    assert "ep" in rep["serve"]
