"""Node-level ONNX conformance sweep — every op handler in
sonnx._ONNX_OPS gets at least one single-node graph executed against a
numpy golden (the stand-in for the reference's onnx.backend.test run,
SURVEY.md §4: no `onnx` package exists in this container, so the suite
is vendored).

A completeness guard asserts no supported op is missing from the sweep,
so newly added handlers fail CI until they get a conformance case.

Documented spec divergence (advisor r04): index-producing ops (ArgMax,
ArgMin, TopK indices, NonZero) emit int32 where ONNX mandates int64 —
this runtime disables x64, so an int64 cast would silently truncate and
warn on every call.  Type-strict downstream consumers comparing against
int64 constants must cast; values are identical for any real tensor
dimension.
"""

import numpy as np
import pytest

from singa_tpu import sonnx, tensor
from singa_tpu.io import onnx_pb
from singa_tpu.io.onnx_pb import (AttributeProto, GraphProto, ModelProto,
                                  NodeProto, TensorProto, ValueInfoProto)

rng = np.random.RandomState(0)


def _run_node(op_type, inputs, attrs=None, n_out=1, initializers=()):
    """Build a 1-node graph; feed ``inputs`` (dict name->array); return
    list of output numpy arrays."""
    in_names = list(inputs)
    node = NodeProto(op_type=op_type, name="n0",
                     input=in_names + [t.name for t in initializers],
                     output=[f"out{i}" for i in range(n_out)])
    for k, v in (attrs or {}).items():
        node.attribute.append(AttributeProto.make(k, v))
    g = GraphProto(
        name="g", node=[node], initializer=list(initializers),
        input=[ValueInfoProto(name=k, elem_type=onnx_pb.FLOAT,
                              shape=list(np.asarray(v).shape))
               for k, v in inputs.items()] +
              [ValueInfoProto(name=t.name, elem_type=t.data_type,
                              shape=list(t.dims)) for t in initializers],
        output=[ValueInfoProto(name=f"out{i}", elem_type=onnx_pb.FLOAT,
                               shape=[]) for i in range(n_out)])
    rep = sonnx.prepare(ModelProto(graph=g))
    outs = rep.run([np.asarray(v) for v in inputs.values()])
    return [tensor.to_numpy(o) for o in outs]


def _init(arr, name):
    return TensorProto.from_numpy(np.asarray(arr), name)


A = rng.randn(2, 3).astype(np.float32)
B = rng.randn(2, 3).astype(np.float32)
POS = np.abs(A) + 0.5
X4 = rng.randn(1, 2, 6, 6).astype(np.float32)


def _softmax(x, axis):
    e = np.exp(x - x.max(axis, keepdims=True))
    return e / e.sum(axis, keepdims=True)


# op -> (callable building (inputs, attrs, initializers, golden_list))
CASES = {
    "Abs": lambda: ({"x": A}, {}, (), [np.abs(A)]),
    "Add": lambda: ({"a": A, "b": B}, {}, (), [A + B]),
    "Sub": lambda: ({"a": A, "b": B}, {}, (), [A - B]),
    "Mul": lambda: ({"a": A, "b": B}, {}, (), [A * B]),
    "Div": lambda: ({"a": A, "b": POS}, {}, (), [A / POS]),
    "Pow": lambda: ({"a": POS, "b": np.float32(2.0) * np.ones_like(A)},
                    {}, (), [POS ** 2]),
    "MatMul": lambda: ({"a": A, "b": B.T.copy()}, {}, (), [A @ B.T]),
    "Max": lambda: ({"a": A, "b": B}, {}, (), [np.maximum(A, B)]),
    "Min": lambda: ({"a": A, "b": B}, {}, (), [np.minimum(A, B)]),
    "Equal": lambda: ({"a": A, "b": A.copy()}, {}, (),
                      [np.ones_like(A, bool)]),
    "Greater": lambda: ({"a": A, "b": B}, {}, (), [A > B]),
    "Less": lambda: ({"a": A, "b": B}, {}, (), [A < B]),
    "Relu": lambda: ({"x": A}, {}, (), [np.maximum(A, 0)]),
    "Sigmoid": lambda: ({"x": A}, {}, (), [1 / (1 + np.exp(-A))]),
    "Tanh": lambda: ({"x": A}, {}, (), [np.tanh(A)]),
    "Exp": lambda: ({"x": A}, {}, (), [np.exp(A)]),
    "Log": lambda: ({"x": POS}, {}, (), [np.log(POS)]),
    "Sqrt": lambda: ({"x": POS}, {}, (), [np.sqrt(POS)]),
    "Neg": lambda: ({"x": A}, {}, (), [-A]),
    "Reciprocal": lambda: ({"x": POS}, {}, (), [1.0 / POS]),
    "Identity": lambda: ({"x": A}, {}, (), [A]),
    "Floor": lambda: ({"x": A * 3}, {}, (), [np.floor(A * 3)]),
    "Ceil": lambda: ({"x": A * 3}, {}, (), [np.ceil(A * 3)]),
    "Erf": lambda: ({"x": A}, {}, (),
                    [np.vectorize(__import__("math").erf)(A)
                     .astype(np.float32)]),
    "Gelu": lambda: ({"x": A}, {}, (),
                     [(A * 0.5 * (1 + np.vectorize(
                         __import__("math").erf)(A / np.sqrt(2))))
                      .astype(np.float32)]),
    "LeakyRelu": lambda: ({"x": A}, {"alpha": 0.1}, (),
                          [np.where(A > 0, A, 0.1 * A)]),
    "Elu": lambda: ({"x": A}, {"alpha": 1.0}, (),
                    [np.where(A > 0, A, np.exp(A) - 1)]),
    "Selu": lambda: ({"x": A}, {}, (),
                     [np.where(A > 0, 1.0507009873554805 * A,
                               1.0507009873554805 * 1.6732632423543772
                               * (np.exp(A) - 1)).astype(np.float32)]),
    "Softplus": lambda: ({"x": A}, {}, (),
                         [np.log1p(np.exp(A)).astype(np.float32)]),
    "Softmax": lambda: ({"x": A}, {"axis": -1}, (), [_softmax(A, -1)]),
    "Clip": lambda: ({"x": A}, {"min": -0.5, "max": 0.5}, (),
                     [np.clip(A, -0.5, 0.5)]),
    "Cast": lambda: ({"x": A}, {"to": onnx_pb.INT32}, (),
                     [A.astype(np.int32)]),
    "Gemm": lambda: ({"a": A, "b": B.T.copy(),
                      "c": rng.randn(2, 2).astype(np.float32)},
                     {"alpha": 2.0, "beta": 0.5}, (), None),
    "Flatten": lambda: ({"x": X4}, {"axis": 1}, (),
                        [X4.reshape(1, -1)]),
    "Reshape": lambda: ({"x": A}, {}, (_init([3, 2], "shp"),),
                        [A.reshape(3, 2)]),
    "Transpose": lambda: ({"x": A}, {"perm": [1, 0]}, (), [A.T]),
    "Tan": lambda: ({"x": A}, {}, (), [np.tan(A)]),
    "Asin": lambda: ({"x": A / 4}, {}, (), [np.arcsin(A / 4)]),
    "Acos": lambda: ({"x": A / 4}, {}, (), [np.arccos(A / 4)]),
    "Atan": lambda: ({"x": A}, {}, (), [np.arctan(A)]),
    "Sinh": lambda: ({"x": A}, {}, (), [np.sinh(A)]),
    "Cosh": lambda: ({"x": A}, {}, (), [np.cosh(A)]),
    "Asinh": lambda: ({"x": A}, {}, (), [np.arcsinh(A)]),
    "Acosh": lambda: ({"x": POS + 1.0}, {}, (),
                      [np.arccosh(POS + 1.0)]),
    "Atanh": lambda: ({"x": A / 4}, {}, (), [np.arctanh(A / 4)]),
    "IsNaN": lambda: ({"x": np.asarray([[0.0, np.nan, 1.0],
                                        [np.nan, 2.0, 3.0]],
                                       np.float32)}, {}, (),
                      [np.asarray([[False, True, False],
                                   [True, False, False]])]),
    "IsInf": lambda: ({"x": np.asarray([[np.inf, -np.inf, 1.0],
                                        [0.0, np.inf, -2.0]],
                                       np.float32)},
                      {"detect_negative": 0}, (),
                      [np.asarray([[True, False, False],
                                   [False, True, False]])]),
    "ReduceLogSum": lambda: ({"x": POS}, {"axes": [1], "keepdims": 0},
                             (), [np.log(POS.sum(1))]),
    "Hardmax": lambda: ({"x": A}, {"axis": -1}, (),
                        [np.eye(3, dtype=np.float32)[A.argmax(-1)]]),
    "Sum": lambda: ({"a": A, "b": B, "c": POS}, {}, (),
                    [A + B + POS]),
    "Mean": lambda: ({"a": A, "b": B, "c": POS}, {}, (),
                     [(A + B + POS) / 3]),
    "Size": lambda: ({"x": A}, {}, (),
                     [np.asarray(A.size, np.int32)]),
    "EyeLike": lambda: ({"x": A}, {"k": 1}, (),
                        [np.eye(2, 3, k=1, dtype=np.float32)]),
    "Concat": lambda: ({"a": A, "b": B}, {"axis": 1}, (),
                       [np.concatenate([A, B], 1)]),
    "Squeeze": lambda: ({"x": A[None]}, {"axes": [0]}, (), [A]),
    "Unsqueeze": lambda: ({"x": A}, {"axes": [0]}, (), [A[None]]),
    "Gather": lambda: ({"x": A}, {"axis": 1},
                       (_init(np.asarray([2, 0], np.int64), "idx"),),
                       [A[:, [2, 0]]]),
    "Slice": lambda: ({"x": A}, {},
                      (_init([0], "st"), _init([2], "en"),
                       _init([1], "ax")),
                      [A[:, 0:2]]),
    "Split": lambda: ({"x": A}, {"axis": 1, "split": [1, 2]}, (), None),
    "Shape": lambda: ({"x": A}, {}, (),
                      [np.asarray(A.shape, np.int32)]),
    "Expand": lambda: ({"x": A[:, :1]}, {},
                       (_init(np.asarray([2, 3], np.int64), "shp"),),
                       [np.broadcast_to(A[:, :1], (2, 3))]),
    "Tile": lambda: ({"x": A}, {},
                     (_init(np.asarray([2, 1], np.int64), "reps"),),
                     [np.tile(A, (2, 1))]),
    "Pad": lambda: ({"x": A}, {},
                    (_init(np.asarray([0, 1, 0, 1], np.int64), "pads"),),
                    [np.pad(A, ((0, 0), (1, 1)))]),
    "Where": lambda: ({"c": (A > 0), "a": A, "b": B}, {}, (),
                      [np.where(A > 0, A, B)]),
    "OneHot": lambda: ({"idx": np.asarray([0, 2], np.float32)}, {},
                       (_init(np.asarray(3, np.int64), "depth"),
                        _init(np.asarray([0.0, 1.0], np.float32), "vals")),
                       [np.eye(3, dtype=np.float32)[[0, 2]]]),
    "Range": lambda: ({}, {},
                      (_init(np.asarray(0, np.float32), "st"),
                       _init(np.asarray(6, np.float32), "en"),
                       _init(np.asarray(2, np.float32), "dl")),
                      [np.arange(0, 6, 2, dtype=np.float32)]),
    "Constant": lambda: ({}, {"value": _init(A, "v")}, (), [A]),
    "ConstantOfShape": lambda: ({}, {"value": _init(
        np.asarray([7.0], np.float32), "v")},
        (_init(np.asarray([2, 2], np.int64), "shp"),),
        [np.full((2, 2), 7.0, np.float32)]),
    "ReduceMean": lambda: ({"x": A}, {"axes": [1], "keepdims": 0}, (),
                           [A.mean(1)]),
    "ReduceSum": lambda: ({"x": A}, {"axes": [1], "keepdims": 0}, (),
                          [A.sum(1)]),
    "ReduceMax": lambda: ({"x": A}, {"axes": [1], "keepdims": 0}, (),
                          [A.max(1)]),
    "ReduceMin": lambda: ({"x": A}, {"axes": [1], "keepdims": 0}, (),
                          [A.min(1)]),
    "Dropout": lambda: ({"x": A}, {"ratio": 0.5}, (), [A]),  # inference
    "Conv": lambda: ({"x": X4}, {"kernel_shape": [3, 3],
                                 "pads": [1, 1, 1, 1]},
                     (_init(rng.randn(4, 2, 3, 3).astype(np.float32),
                            "w"),), None),
    "MaxPool": lambda: ({"x": X4}, {"kernel_shape": [2, 2],
                                    "strides": [2, 2]}, (), None),
    "AveragePool": lambda: ({"x": X4}, {"kernel_shape": [2, 2],
                                        "strides": [2, 2]}, (), None),
    "GlobalAveragePool": lambda: ({"x": X4}, {}, (),
                                  [X4.mean((2, 3), keepdims=True)]),
    "BatchNormalization": lambda: (
        {"x": X4}, {"epsilon": 1e-5},
        (_init(np.ones(2, np.float32), "s"),
         _init(np.zeros(2, np.float32), "b"),
         _init(np.zeros(2, np.float32), "m"),
         _init(np.ones(2, np.float32), "v")),
        [X4 / np.sqrt(1 + 1e-5)]),
    "LayerNormalization": lambda: (
        {"x": A}, {"epsilon": 1e-5, "axis": -1},
        (_init(np.ones(3, np.float32), "s"),
         _init(np.zeros(3, np.float32), "b")),
        [(A - A.mean(-1, keepdims=True))
         / np.sqrt(A.var(-1, keepdims=True) + 1e-5)]),
    "If": lambda: (
        # cond=True selects the then-branch (x+1); both branches CAPTURE
        # the outer graph input "x" (ONNX outer-scope visibility)
        {"cond": np.asarray(True), "x": A},
        {"then_branch": _branch_graph("Add", "x", 1.0, "tb"),
         "else_branch": _branch_graph("Sub", "x", 1.0, "eb")},
        (), [A + 1.0]),
    "Loop": lambda: (
        # 3 iterations of v = v + v0, where "v0" inside the body is the
        # OUTER graph input (outer-scope capture) and also the initial
        # carried value; v is emitted per-iteration as a scan output
        {"M": np.asarray(3, np.int64), "keepgoing": np.asarray(True),
         "v0": A},
        {"body": _loop_body_graph()},
        (), [4.0 * A, np.stack([2.0 * A, 3.0 * A, 4.0 * A])]),
    "Scan": lambda: (
        # cumulative sum: state' = state + x_t, scan output = state'
        {"s0": np.zeros(3, np.float32), "xs": A},
        {"body": _scan_body_graph(), "num_scan_inputs": 1},
        (), [A.sum(axis=0), np.cumsum(A, axis=0)]),
    "ConvTranspose": lambda: _conv_transpose_case(),
    "ArgMax": lambda: ({"x": A}, {"axis": 1, "keepdims": 0}, (),
                       [np.argmax(A, axis=1).astype(np.int64)]),
    "TopK": lambda: (
        {"x": A}, {"axis": -1},
        (_init(np.asarray([2], np.int64), "k"),),
        [np.sort(A, axis=-1)[:, ::-1][:, :2],
         np.argsort(-A, axis=-1, kind="stable")[:, :2]
         .astype(np.int64)]),
    "Einsum": lambda: ({"a": A, "b": B}, {"equation": "ij,kj->ik"}, (),
                       [np.einsum("ij,kj->ik", A, B)]),
    "LSTM": lambda: _rnn_case("LSTM"),
    "GRU": lambda: _rnn_case("GRU"),
    "RNN": lambda: _rnn_case("RNN"),
    "Resize": lambda: _resize_case(),
    "GlobalMaxPool": lambda: ({"x": X4}, {}, (),
                              [X4.max(axis=(2, 3), keepdims=True)]),
    "Upsample": lambda: (
        {"x": rng.randn(1, 2, 3, 4).astype(np.float32)},
        {"mode": "nearest", "scales": [1.0, 1.0, 2.0, 2.0]}, (), None),
    "InstanceNormalization": lambda: _instancenorm_case(),
    "PRelu": lambda: (
        {"x": A}, {}, (_init(np.asarray([0.1, 0.2, 0.3], np.float32),
                             "slope"),),
        [np.where(A >= 0, A, A * np.asarray([0.1, 0.2, 0.3]))]),
    "CumSum": lambda: (
        {"x": A}, {}, (_init(np.asarray([1], np.int64), "ax"),),
        [np.cumsum(A, axis=1)]),
    "DepthToSpace": lambda: (
        # CRD mode == torch pixel_shuffle (DCR default covered by the
        # element-indexed loop golden in test_depth_space_modes)
        {"x": rng.randn(1, 8, 2, 3).astype(np.float32)},
        {"blocksize": 2, "mode": "CRD"}, (), None),
    "SpaceToDepth": lambda: (
        {"x": rng.randn(1, 2, 4, 6).astype(np.float32)},
        {"blocksize": 2}, (), None),
    "GatherElements": lambda: (
        {"x": A}, {"axis": 1},
        (_init(np.asarray([[0, 2], [1, 0]], np.int64), "idx"),),
        [np.take_along_axis(A, np.asarray([[0, 2], [1, 0]]), axis=1)]),
    "Trilu": lambda: (
        {"x": rng.randn(2, 4, 4).astype(np.float32)}, {"upper": 1},
        (_init(np.asarray([1], np.int64), "k"),), None),
    "ScatterND": lambda: (
        {"x": rng.randn(4, 3).astype(np.float32)}, {},
        (_init(np.asarray([[0], [2]], np.int64), "idx"),
         _init(rng.randn(2, 3).astype(np.float32), "upd")), None),
    "ScatterElements": lambda: (
        {"x": A.copy()}, {"axis": 1, "reduction": "add"},
        (_init(np.asarray([[0, 2], [1, 0]], np.int64), "idx"),
         _init(rng.randn(2, 2).astype(np.float32), "upd")), None),
    "GatherND": lambda: (
        {"x": rng.randn(2, 3, 4).astype(np.float32)},
        {"batch_dims": 1},
        (_init(np.asarray([[1], [2]], np.int64), "idx"),), None),
    "NonZero": lambda: (
        {"x": (A > 0).astype(np.float32)}, {}, (),
        [np.stack(np.nonzero(A > 0)).astype(np.int32)]),
    "GroupNormalization": lambda: (
        {"x": rng.randn(2, 6, 3, 3).astype(np.float32)},
        {"num_groups": 2, "epsilon": 1e-5},
        (_init(rng.randn(6).astype(np.float32), "s"),
         _init(rng.randn(6).astype(np.float32), "b")), None),
    "And": lambda: ({"a": A > 0, "b": B > 0}, {}, (),
                    [(A > 0) & (B > 0)]),
    "Or": lambda: ({"a": A > 0, "b": B > 0}, {}, (),
                   [(A > 0) | (B > 0)]),
    "Xor": lambda: ({"a": A > 0, "b": B > 0}, {}, (),
                    [(A > 0) ^ (B > 0)]),
    "Not": lambda: ({"x": A > 0}, {}, (), [~(A > 0)]),
    "GreaterOrEqual": lambda: ({"a": A, "b": B}, {}, (), [A >= B]),
    "LessOrEqual": lambda: ({"a": A, "b": B}, {}, (), [A <= B]),
    "Mod": lambda: ({"a": np.abs(A) + 1, "b": np.full_like(A, 0.7)},
                    {"fmod": 1}, (),
                    [np.fmod(np.abs(A) + 1, 0.7)]),
    "Sign": lambda: ({"x": A}, {}, (), [np.sign(A)]),
    "Round": lambda: ({"x": 3 * A}, {}, (), [np.round(3 * A)]),
    "Sin": lambda: ({"x": A}, {}, (), [np.sin(A)]),
    "Cos": lambda: ({"x": A}, {}, (), [np.cos(A)]),
    "Softsign": lambda: ({"x": A}, {}, (), [A / (1 + np.abs(A))]),
    "HardSigmoid": lambda: ({"x": A}, {"alpha": 0.25, "beta": 0.4}, (),
                            [np.clip(0.25 * A + 0.4, 0, 1)]),
    "HardSwish": lambda: ({"x": 4 * A}, {}, (),
                          [4 * A * np.clip(4 * A / 6 + 0.5, 0, 1)]),
    "LogSoftmax": lambda: ({"x": A}, {"axis": 1}, (),
                           [np.log(_softmax(A, 1))]),
    "Celu": lambda: ({"x": A}, {"alpha": 0.5}, (),
                     [np.maximum(A, 0)
                      + np.minimum(0, 0.5 * (np.exp(A / 0.5) - 1))]),
    "Mish": lambda: ({"x": A}, {}, (),
                     [A * np.tanh(np.log1p(np.exp(A)))]),
    "ThresholdedRelu": lambda: ({"x": A}, {"alpha": 0.3}, (),
                                [np.where(A > 0.3, A, 0.0)]),
    "Shrink": lambda: ({"x": A}, {"lambd": 0.4, "bias": 0.1}, (),
                       [np.where(A > 0.4, A - 0.1,
                                 np.where(A < -0.4, A + 0.1, 0.0))]),
    "ReduceSumSquare": lambda: ({"x": A}, {"axes": [1]}, (),
                                [(A * A).sum(axis=1, keepdims=True)]),
    "ReduceProd": lambda: ({"x": np.abs(A) + 0.5}, {"axes": [1]}, (),
                           [np.prod(np.abs(A) + 0.5, axis=1,
                                    keepdims=True)]),
    "ReduceL1": lambda: ({"x": A}, {"axes": [0]}, (),
                         [np.abs(A).sum(axis=0, keepdims=True)]),
    "ReduceL2": lambda: ({"x": A}, {"axes": [1]}, (),
                         [np.sqrt((A * A).sum(axis=1, keepdims=True))]),
    "ReduceLogSumExp": lambda: (
        {"x": A}, {"axes": [1]}, (),
        [np.log(np.exp(A).sum(axis=1, keepdims=True))]),
    "ArgMin": lambda: ({"x": A}, {"axis": 1, "keepdims": 0}, (),
                       [np.argmin(A, axis=1).astype(np.int32)]),
}




def _conv_transpose_case():
    import torch

    x = rng.randn(1, 3, 5, 5).astype(np.float32)
    w = rng.randn(3, 4, 3, 3).astype(np.float32)  # (C_in, C_out, k, k)
    golden = torch.nn.functional.conv_transpose2d(
        torch.from_numpy(x), torch.from_numpy(w), stride=2,
        padding=1).numpy()
    return ({"x": x}, {"strides": [2, 2], "pads": [1, 1, 1, 1]},
            (_init(w, "w"),), [golden])


def _rnn_case(kind, direction="forward", bidirectional=False,
              with_bias=True, with_h0=True):
    """Build ONNX-format weights, compute the golden with torch (whose
    gate orders differ from ONNX: LSTM iofc->ifgo perm [0,2,3,1], GRU
    zrh->rzn perm [1,0,2] — independent derivation of the importer's
    mapping)."""
    import torch

    T, Bz, I, H = 4, 3, 5, 6
    G = {"LSTM": 4, "GRU": 3, "RNN": 1}[kind]
    perm = {"LSTM": [0, 2, 3, 1], "GRU": [1, 0, 2], "RNN": [0]}[kind]
    D = 2 if bidirectional else 1
    x = rng.randn(T, Bz, I).astype(np.float32)
    W = rng.randn(D, G * H, I).astype(np.float32) * 0.4
    R = rng.randn(D, G * H, H).astype(np.float32) * 0.4
    Bb = rng.randn(D, 2 * G * H).astype(np.float32) * 0.4 if with_bias \
        else np.zeros((D, 2 * G * H), np.float32)
    h0 = rng.randn(D, Bz, H).astype(np.float32) if with_h0 else \
        np.zeros((D, Bz, H), np.float32)
    c0 = rng.randn(D, Bz, H).astype(np.float32)

    mod = {"LSTM": torch.nn.LSTM, "GRU": torch.nn.GRU,
           "RNN": torch.nn.RNN}[kind](I, H, 1,
                                      bidirectional=bidirectional)
    ridx = np.concatenate([np.arange(p * H, (p + 1) * H) for p in perm])
    with torch.no_grad():
        for d in range(D):
            sfx = "_reverse" if d == 1 else ""
            getattr(mod, f"weight_ih_l0{sfx}").copy_(
                torch.from_numpy(W[d][ridx]))
            getattr(mod, f"weight_hh_l0{sfx}").copy_(
                torch.from_numpy(R[d][ridx]))
            getattr(mod, f"bias_ih_l0{sfx}").copy_(
                torch.from_numpy(Bb[d, :G * H][ridx]))
            getattr(mod, f"bias_hh_l0{sfx}").copy_(
                torch.from_numpy(Bb[d, G * H:][ridx]))
        tx = torch.from_numpy(x)
        th0 = torch.from_numpy(h0)
        if kind == "LSTM":
            y, (hT, cT) = mod(tx, (th0, torch.from_numpy(c0)))
        else:
            y, hT = mod(tx, th0)
    Y = y.numpy().reshape(T, Bz, D, H).transpose(0, 2, 1, 3)
    attrs = {"hidden_size": H}
    if bidirectional:
        attrs["direction"] = "bidirectional"
    if kind == "GRU":
        attrs["linear_before_reset"] = 1  # torch's GRU form
    inputs = {"x": x}
    inits = [_init(W, "W"), _init(R, "R"), _init(Bb, "B"),
             _init(np.full(Bz, T, np.int32), "seq"), _init(h0, "h0")]
    golden = [Y, hT.numpy()]
    if kind == "LSTM":
        inits.append(_init(c0, "c0"))
        golden.append(cT.numpy())
    return (inputs, attrs, tuple(inits), golden)




def _resize_case():
    import torch

    x = rng.randn(1, 2, 4, 5).astype(np.float32)
    # nearest, asymmetric+floor, scales (2, 2) — exactly torch's
    # interpolate(mode="nearest")
    golden = torch.nn.functional.interpolate(
        torch.from_numpy(x), scale_factor=2, mode="nearest").numpy()
    return ({"x": x}, {"mode": "nearest",
                       "coordinate_transformation_mode": "asymmetric",
                       "nearest_mode": "floor"},
            (_init(np.asarray([], np.float32), "roi"),
             _init(np.asarray([1, 1, 2, 2], np.float32), "scales")),
            [golden])


def _instancenorm_case():
    import torch

    x = rng.randn(2, 3, 4, 5).astype(np.float32)
    s = rng.rand(3).astype(np.float32) + 0.5
    b = rng.randn(3).astype(np.float32)
    golden = torch.nn.functional.instance_norm(
        torch.from_numpy(x), weight=torch.from_numpy(s),
        bias=torch.from_numpy(b), eps=1e-5).numpy()
    return ({"x": x}, {"epsilon": 1e-5},
            (_init(s, "s"), _init(b, "b")), [golden])


def _scan_body_graph():
    """Scan body (s_in, x_t) -> (s_out, y_t): s_out = s_in + x_t,
    y_t = s_out."""
    return GraphProto(
        name="scan_body",
        input=[ValueInfoProto(name="s_in"), ValueInfoProto(name="x_t")],
        node=[NodeProto(op_type="Add", name="sb_add",
                        input=["s_in", "x_t"], output=["s_out"]),
              NodeProto(op_type="Identity", name="sb_id",
                        input=["s_out"], output=["y_t"])],
        output=[ValueInfoProto(name="s_out"), ValueInfoProto(name="y_t")])


def _branch_graph(op, captured, const, tag):
    """Subgraph: out = op(captured_outer_name, const) — no formal
    inputs, exercising outer-scope capture."""
    return GraphProto(
        name=tag,
        node=[NodeProto(op_type=op, name=f"{tag}_n",
                        input=[captured, f"{tag}_c"],
                        output=[f"{tag}_out"])],
        initializer=[_init(np.full((2, 3), const, np.float32),
                           f"{tag}_c")],
        output=[ValueInfoProto(name=f"{tag}_out",
                               elem_type=onnx_pb.FLOAT, shape=[2, 3])])


def _loop_body_graph():
    """Loop body (iter, cond_in, v_in) -> (cond_out, v_out, scan_out):
    v_out = v_in + v0 ("v0" captured from the outer scope); scan_out =
    v_out; cond passes through."""
    return GraphProto(
        name="body",
        node=[
            NodeProto(op_type="Add", name="b_add", input=["v_in", "v0"],
                      output=["v_out"]),
            NodeProto(op_type="Identity", name="b_id_c",
                      input=["cond_in"], output=["cond_out"]),
            NodeProto(op_type="Identity", name="b_id_s",
                      input=["v_out"], output=["scan_out"]),
        ],
        input=[ValueInfoProto(name="iter", elem_type=onnx_pb.INT64,
                              shape=[]),
               ValueInfoProto(name="cond_in", elem_type=onnx_pb.BOOL,
                              shape=[]),
               ValueInfoProto(name="v_in", elem_type=onnx_pb.FLOAT,
                              shape=[2, 3])],
        output=[ValueInfoProto(name="cond_out", elem_type=onnx_pb.BOOL,
                               shape=[]),
                ValueInfoProto(name="v_out", elem_type=onnx_pb.FLOAT,
                               shape=[2, 3]),
                ValueInfoProto(name="scan_out", elem_type=onnx_pb.FLOAT,
                               shape=[2, 3])])

def test_sweep_covers_every_supported_op():
    supported = set(sonnx._ONNX_OPS) | set(sonnx._CONTROL_FLOW_OPS)
    missing = supported - set(CASES)
    assert not missing, f"ops without a conformance case: {sorted(missing)}"


def test_gelu_tanh_attribute_and_export_roundtrip():
    """Both Gelu flavors import per the attribute, and export carries
    the flavor (ONNX default is exact erf; ours is tanh unless asked)."""
    import math

    exact = _run_node("Gelu", {"x": A}, {"approximate": "none"})[0]
    tanh = _run_node("Gelu", {"x": A}, {"approximate": "tanh"})[0]
    erf_golden = (A * 0.5 * (1 + np.vectorize(math.erf)(A / np.sqrt(2)))
                  ).astype(np.float32)
    np.testing.assert_allclose(exact, erf_golden, rtol=2e-4, atol=1e-5)
    assert np.abs(tanh - exact).max() > 1e-6  # genuinely different paths

    # export writes the attribute and declares opset 20
    from singa_tpu import autograd, layer, model

    class G(model.Model):
        def forward(self, x):
            return autograd.gelu(x)

        def train_one_batch(self, x):  # pragma: no cover
            raise NotImplementedError

    m = G()
    x = tensor.from_numpy(A)
    m.compile([x], is_train=False, use_graph=False)
    proto = sonnx.to_onnx(m, [x])
    assert any(o.version == 20 for o in proto.opset_import
               if not o.domain)
    gelu_nodes = [n for n in proto.graph.node if n.op_type == "Gelu"]
    assert len(gelu_nodes) == 1
    attrs = gelu_nodes[0].attrs()
    assert attrs["approximate"] == "tanh"  # autograd.gelu default
    rep = sonnx.prepare(proto)
    out = tensor.to_numpy(rep.run([A])[0])
    ref = tensor.to_numpy(m.forward(x))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=1e-5)


@pytest.mark.parametrize("op", sorted(CASES))
def test_onnx_node_conformance(op):
    inputs, attrs, inits, golden = CASES[op]()
    n_out = {"Split": 2, "Loop": 2, "Scan": 2, "TopK": 2,
             "LSTM": 3, "GRU": 2, "RNN": 2}.get(op, 1)
    outs = _run_node(op, inputs, attrs, n_out=n_out, initializers=inits)

    if golden is None and op == "Split":
        golden = [np.asarray(A[:, :1]), np.asarray(A[:, 1:])]
    elif golden is None and op == "Gemm":
        golden = [2.0 * (A @ B.T) + 0.5 * np.asarray(inputs["c"])]
    elif golden is None and op == "Trilu":
        x = np.asarray(inputs["x"])
        golden = [np.stack([np.triu(x[i], 1)
                            for i in range(x.shape[0])])]
    elif golden is None and op == "ScatterND":
        y = np.asarray(inputs["x"]).copy()
        idx = inits[0].to_numpy()
        upd = inits[1].to_numpy()
        for r in range(idx.shape[0]):
            y[tuple(idx[r])] = upd[r]
        golden = [y]
    elif golden is None and op == "ScatterElements":
        y = np.asarray(inputs["x"]).copy()
        idx = inits[0].to_numpy()
        upd = inits[1].to_numpy()
        for i in range(idx.shape[0]):
            for j in range(idx.shape[1]):
                y[i, idx[i, j]] += upd[i, j]  # reduction="add"
        golden = [y]
    elif golden is None and op == "GatherND":
        x = np.asarray(inputs["x"])
        idx = inits[0].to_numpy()
        golden = [np.stack([x[b][tuple(idx[b])]
                            for b in range(x.shape[0])])]
    elif golden is None:
        torch = pytest.importorskip("torch")
        tx = {k: torch.from_numpy(np.asarray(v).copy())
              for k, v in inputs.items()}
        if op == "Conv":
            w = torch.from_numpy(inits[0].to_numpy())
            golden = [torch.nn.functional.conv2d(tx["x"], w,
                                                 padding=1).numpy()]
        elif op == "MaxPool":
            golden = [torch.nn.functional.max_pool2d(tx["x"], 2).numpy()]
        elif op == "AveragePool":
            golden = [torch.nn.functional.avg_pool2d(tx["x"], 2).numpy()]
        elif op == "DepthToSpace":
            golden = [torch.nn.functional.pixel_shuffle(tx["x"], 2).numpy()]
        elif op == "SpaceToDepth":
            golden = [_s2d_loop(np.asarray(inputs["x"]), 2)]
        elif op == "Upsample":
            golden = [torch.nn.functional.interpolate(
                tx["x"], scale_factor=2, mode="nearest").numpy()]
        elif op == "GroupNormalization":
            golden = [torch.nn.functional.group_norm(
                tx["x"], 2,
                weight=torch.from_numpy(inits[0].to_numpy()),
                bias=torch.from_numpy(inits[1].to_numpy()),
                eps=1e-5).numpy()]
    for got, want in zip(outs, golden):
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(want, np.float32),
                                   rtol=2e-4, atol=1e-5, err_msg=op)


def _s2d_loop(x, bs):
    """ONNX SpaceToDepth per the spec's element mapping, written as
    loops so it is independent of any reshape/transpose recipe."""
    n, c, h, w = x.shape
    y = np.zeros((n, c * bs * bs, h // bs, w // bs), x.dtype)
    for bi in range(bs):
        for bj in range(bs):
            for ci in range(c):
                y[:, (bi * bs + bj) * c + ci] = \
                    x[:, ci, bi::bs, bj::bs]
    return y


def test_depth_space_modes():
    """DCR (default) and CRD DepthToSpace against element-indexed loop
    goldens; SpaceToDepth(DepthToSpace(x, DCR)) is the identity."""
    x = rng.randn(2, 8, 3, 4).astype(np.float32)
    bs, c2 = 2, 2

    def d2s_loop(x, mode):
        n, c, h, w = x.shape
        y = np.zeros((n, c2, h * bs, w * bs), x.dtype)
        for bi in range(bs):
            for bj in range(bs):
                for ci in range(c2):
                    src = ((bi * bs + bj) * c2 + ci if mode == "DCR"
                           else ci * bs * bs + bi * bs + bj)
                    y[:, ci, bi::bs, bj::bs] = x[:, src]
        return y

    for mode in ("DCR", "CRD"):
        got = _run_node("DepthToSpace", {"x": x},
                        {"blocksize": bs, "mode": mode})[0]
        np.testing.assert_allclose(got, d2s_loop(x, mode), err_msg=mode)
    d2s = _run_node("DepthToSpace", {"x": x}, {"blocksize": bs})[0]
    back = _run_node("SpaceToDepth", {"x": d2s}, {"blocksize": bs})[0]
    np.testing.assert_allclose(back, x)


def test_resize_spec_defaults_and_floor_shape():
    """The ONNX-default nearest combo (half_pixel + round_prefer_floor)
    and the spec's floor(d*scale) output shape — the two divergences a
    review repro caught against onnxruntime semantics."""
    x = np.arange(5, dtype=np.float32).reshape(1, 1, 1, 5)
    # defaults (no ctm/nearest_mode attrs), scale 0.4 -> out dim 2,
    # half_pixel+round_prefer_floor picks elements [1, 3]
    got = _run_node("Resize", {"x": x}, {"mode": "nearest"},
                    initializers=(
                        _init(np.asarray([], np.float32), "roi"),
                        _init(np.asarray([1, 1, 1, 0.4], np.float32),
                              "scales")))[0]
    np.testing.assert_allclose(got.reshape(-1), [1.0, 3.0])
    # scale 1.5 on dim 5: floor(7.5) = 7, not round's 8
    got = _run_node("Resize", {"x": x}, {"mode": "nearest"},
                    initializers=(
                        _init(np.asarray([], np.float32), "roi"),
                        _init(np.asarray([1, 1, 1, 1.5], np.float32),
                              "scales")))[0]
    assert got.shape == (1, 1, 1, 7), got.shape


def test_prelu_trailing_broadcast_wins_ambiguity():
    """ONNX unidirectional broadcast: slope (3,) on x (2,3,4,3) applies
    along the LAST axis even though it also matches the channel dim."""
    x = rng.randn(2, 3, 4, 3).astype(np.float32)
    slope = np.asarray([0.1, 0.2, 0.3], np.float32)
    got = _run_node("PRelu", {"x": x}, {},
                    initializers=(_init(slope, "slope"),))[0]
    want = np.where(x >= 0, x, x * slope)  # numpy trailing broadcast
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_reduce_logsumexp_stable():
    x = np.full((2, 3), 100.0, np.float32)
    got = _run_node("ReduceLogSumExp", {"x": x}, {"axes": [1]})[0]
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got, 100.0 + np.log(3.0), rtol=1e-5)


def test_upsample_linear_asymmetric_coordinates():
    """Legacy Upsample linear must use ASYMMETRIC source coordinates
    (src = dst/scale), not half-pixel centers (advisor r04): golden is
    a hand-rolled numpy lerp of the spec's arithmetic."""
    x = rng.randn(1, 1, 3, 4).astype(np.float32)
    scales = np.asarray([1.0, 1.0, 2.0, 2.0], np.float32)

    def lerp_axis(v, ax, scale):
        n_in = v.shape[ax]
        n_out = int(np.floor(n_in * scale))
        src = np.arange(n_out) / scale
        i0 = np.clip(np.floor(src).astype(int), 0, n_in - 1)
        i1 = np.minimum(i0 + 1, n_in - 1)
        w = (src - i0).astype(np.float32)
        shape = [1] * v.ndim
        shape[ax] = n_out
        w = w.reshape(shape)
        return (np.take(v, i0, axis=ax) * (1 - w)
                + np.take(v, i1, axis=ax) * w)

    want = lerp_axis(lerp_axis(x, 2, 2.0), 3, 2.0)
    (got,) = _run_node("Upsample", {"x": x}, {"mode": "linear"},
                       initializers=(_init(scales, "scales"),))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_isinf_flag_combinations():
    """All four detect_negative/positive combinations — notably BOTH
    zero, which must return all-False (a nested-conditional bug once
    detected +inf there; caught in review, pinned here)."""
    x = np.asarray([np.inf, -np.inf, 1.0], np.float32)
    for neg, pos, want in (
            (1, 1, [True, True, False]),
            (0, 1, [True, False, False]),
            (1, 0, [False, True, False]),
            (0, 0, [False, False, False])):
        outs = _run_node("IsInf", {"x": x},
                         {"detect_negative": neg,
                          "detect_positive": pos})
        np.testing.assert_array_equal(
            np.asarray(tensor.to_numpy(outs[0]), bool), want,
            err_msg=f"neg={neg} pos={pos}")
