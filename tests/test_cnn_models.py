"""CNN model zoo smoke + learning tests (reference: examples/cnn models,
unverified)."""

import numpy as np
import pytest

pytestmark = pytest.mark.slow

from singa_tpu import layer, opt, tensor
from singa_tpu import device as device_module
from singa_tpu.models.cnn import CNN
from singa_tpu.models.resnet import resnet18, resnet50


@pytest.fixture
def dev():
    d = device_module.get_default_device()
    d.SetRandSeed(0)
    return d


def _data(dev, n=4, c=1, hw=28, classes=10, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, c, hw, hw).astype(np.float32)
    y = rng.randint(0, classes, (n,)).astype(np.int32)
    return tensor.from_numpy(x, dev), tensor.from_numpy(y, dev)


def test_cnn_trains_eager(dev):
    m = CNN(num_classes=10, num_channels=1)
    m.set_optimizer(opt.SGD(lr=0.02, momentum=0.9))
    x, y = _data(dev, n=8)
    m.compile([x], is_train=True, use_graph=False)
    losses = [float(m(x, y)[1].data) for _ in range(8)]
    assert losses[-1] < losses[0], losses


def test_cnn_graph_equals_eager(dev):
    x, y = _data(dev, n=4)

    def make():
        dev.SetRandSeed(3)
        m = CNN(num_classes=10, num_channels=1)
        m.set_optimizer(opt.SGD(lr=0.01))
        m.compile([x], is_train=True, use_graph=False)
        return m

    m1 = make()
    m2 = make()
    m2.graph_mode = True
    from singa_tpu import model as model_mod

    m2._graph_runner = model_mod._GraphRunner(m2)
    m2.set_params({k: v.clone() for k, v in m1.get_params().items()})
    for i in range(4):
        _, l1 = m1(x, y)
        _, l2 = m2(x, y)
        np.testing.assert_allclose(float(l1.data), float(l2.data), rtol=5e-4,
                                   err_msg=f"step {i}")


def test_resnet18_forward_shape_and_step(dev):
    m = resnet18(num_classes=10)
    m.set_optimizer(opt.SGD(lr=0.01))
    x, y = _data(dev, n=2, c=3, hw=32)
    m.compile([x], is_train=True, use_graph=False)
    out, loss = m(x, y)
    assert out.shape == (2, 10)
    assert np.isfinite(float(loss.data))
    # BN running stats moved off their init during training
    rm = [v for k, v in m.get_states().items() if k.endswith("running_mean")]
    assert any(np.abs(tensor.to_numpy(t)).max() > 0 for t in rm)


def test_resnet50_param_count(dev):
    m = resnet50(num_classes=1000)
    x, _ = _data(dev, n=1, c=3, hw=64, classes=1000)
    m.compile([x], is_train=False, use_graph=False)
    n_params = sum(int(np.prod(v.shape)) for v in m.get_params().values())
    # torchvision resnet50: 25.557M params
    assert abs(n_params - 25_557_032) / 25_557_032 < 0.01, n_params


def test_resnet_eval_mode(dev):
    m = resnet18(num_classes=10)
    x, y = _data(dev, n=2, c=3, hw=32)
    m.compile([x], is_train=True, use_graph=False)
    m.set_optimizer(opt.SGD(lr=0.01))
    m(x, y)
    m.eval()
    out = m(x)
    assert out.shape == (2, 10)


def test_xception_param_count(dev):
    from singa_tpu.models.xceptionnet import Xception

    m = Xception(num_classes=1000)
    x, _ = _data(dev, n=1, c=3, hw=96, classes=1000)
    m.compile([x], is_train=False, use_graph=False)
    n_params = sum(int(np.prod(v.shape)) for v in m.get_params().values())
    # reference Xception: 22,855,952 params
    assert abs(n_params - 22_855_952) / 22_855_952 < 0.01, n_params


def test_mobilenet_v2_param_count_and_step(dev):
    from singa_tpu.models.mobilenet import mobilenet_v2

    m = mobilenet_v2(num_classes=1000)
    x, y = _data(dev, n=2, c=3, hw=64, classes=1000)
    m.compile([x], is_train=True, use_graph=False)
    n_params = sum(int(np.prod(v.shape)) for v in m.get_params().values())
    # torchvision mobilenet_v2: 3,504,872 params
    assert abs(n_params - 3_504_872) / 3_504_872 < 0.01, n_params
    m.set_optimizer(opt.SGD(lr=0.01))
    out, loss = m(x, y)
    assert out.shape == (2, 1000)
    assert np.isfinite(float(loss.data))


def test_vgg16_param_count(dev):
    from singa_tpu.models.vgg import vgg16

    m = vgg16(num_classes=1000)
    x, _ = _data(dev, n=1, c=3, hw=224, classes=1000)
    m.compile([x], is_train=False, use_graph=False)
    n_params = sum(int(np.prod(v.shape)) for v in m.get_params().values())
    # torchvision vgg16: 138,357,544 params
    assert abs(n_params - 138_357_544) / 138_357_544 < 0.01, n_params


def test_vgg11_bn_trains_small_input(dev):
    from singa_tpu.models.vgg import vgg11

    m = vgg11(num_classes=10, batch_norm=True, hidden=64)
    m.set_optimizer(opt.SGD(lr=0.01, momentum=0.9))
    x, y = _data(dev, n=4, c=3, hw=32)
    m.compile([x], is_train=True, use_graph=False)
    out, loss = m(x, y)
    assert out.shape == (4, 10)
    assert np.isfinite(float(loss.data))


def test_mobilenet_onnx_roundtrip(dev):
    from singa_tpu import sonnx
    from singa_tpu.models.mobilenet import mobilenet_v2

    m = mobilenet_v2(num_classes=10, width_mult=0.25)
    x, _ = _data(dev, n=1, c=3, hw=32)
    m.compile([x], is_train=False, use_graph=False)
    m.eval()
    native = tensor.to_numpy(m.forward(x))
    rep = sonnx.prepare(sonnx.to_onnx(m, [x]), dev)
    (out,) = rep.run([x])
    np.testing.assert_allclose(tensor.to_numpy(out), native, rtol=1e-4,
                               atol=1e-4)


def test_resnet18_onnx_roundtrip_with_bn_stats(dev):
    """BN exports as the 5-input BatchNormalization node with the
    PRE-forward running stats (export taping is pure); the imported
    graph must match native eval output after some training moved the
    stats off init."""
    from singa_tpu import sonnx
    from singa_tpu.models.resnet import resnet18

    m = resnet18(num_classes=10)
    m.set_optimizer(opt.SGD(lr=0.01))
    x, y = _data(dev, n=2, c=3, hw=32)
    m.compile([x], is_train=True, use_graph=False)
    m(x, y)  # one step so running stats are non-trivial
    m.eval()
    native = tensor.to_numpy(m.forward(x))
    rm_before = {k: tensor.to_numpy(v).copy()
                 for k, v in m.get_states().items()
                 if k.endswith("running_mean")}
    rep = sonnx.prepare(sonnx.to_onnx(m, [x]), dev)
    # export must not perturb model state
    for k, v in m.get_states().items():
        if k in rm_before:
            np.testing.assert_array_equal(tensor.to_numpy(v), rm_before[k])
    (out,) = rep.run([x])
    np.testing.assert_allclose(tensor.to_numpy(out), native, rtol=1e-3,
                               atol=1e-3)


def test_unet_trains_and_roundtrips(dev):
    """Segmentation family (round 4): ConvTranspose decoder + skip
    concats train under graph mode and survive the ONNX round trip
    (which caught a real exporter bug: Concat's REQUIRED axis
    attribute was never written — channel concat imported as batch
    concat)."""
    from singa_tpu import sonnx
    from singa_tpu.models.unet import unet

    m = unet(num_classes=3, base_channels=8, depth=2)
    m.set_optimizer(opt.Adam(lr=1e-3))
    rng = np.random.RandomState(0)
    x = tensor.from_numpy(rng.randn(2, 3, 32, 32).astype(np.float32), dev)
    y = tensor.from_numpy(rng.randint(0, 3, (2, 32, 32)).astype(np.int32),
                          dev)
    m.compile([x], is_train=True, use_graph=True)
    losses = [float(tensor.to_numpy(m(x, y)[1])) for _ in range(6)]
    assert losses[-1] < losses[0], losses

    m.eval()
    proto = sonnx.to_onnx(m, [x])
    assert any(n.op_type == "ConvTranspose" for n in proto.graph.node)
    cc = [n for n in proto.graph.node if n.op_type == "Concat"]
    assert cc and all(n.attrs().get("axis") == 1 for n in cc)
    rep = sonnx.prepare(proto, dev)
    native = tensor.to_numpy(m.forward(x))
    got = tensor.to_numpy(rep.run([x])[0])
    np.testing.assert_allclose(got, native, rtol=2e-3, atol=2e-4)


def test_conv_transpose_layer_shapes_and_grad(dev):
    from singa_tpu import autograd as ag

    ct = layer.ConvTranspose2d(6, 3, stride=2, padding=1,
                               output_padding=1)
    x = tensor.from_numpy(
        np.random.RandomState(0).randn(2, 4, 8, 8).astype(np.float32),
        dev)
    ag.set_training(True)
    try:
        y = ct(x)
        assert y.shape == (2, 6, 16, 16), y.shape  # exact 2x upsample
        loss = ag.reduce_sum(ag.mul(y, y))
        grads = dict(ag.backward(loss))
        assert ct.W in grads and np.isfinite(
            tensor.to_numpy(grads[ct.W])).all()
    finally:
        ag.set_training(False)
