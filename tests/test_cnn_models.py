"""CNN model zoo smoke + learning tests (reference: examples/cnn models,
unverified)."""

import numpy as np
import pytest

from singa_tpu import opt, tensor
from singa_tpu import device as device_module
from singa_tpu.models.cnn import CNN
from singa_tpu.models.resnet import resnet18, resnet50


@pytest.fixture
def dev():
    d = device_module.get_default_device()
    d.SetRandSeed(0)
    return d


def _data(dev, n=4, c=1, hw=28, classes=10, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, c, hw, hw).astype(np.float32)
    y = rng.randint(0, classes, (n,)).astype(np.int32)
    return tensor.from_numpy(x, dev), tensor.from_numpy(y, dev)


def test_cnn_trains_eager(dev):
    m = CNN(num_classes=10, num_channels=1)
    m.set_optimizer(opt.SGD(lr=0.02, momentum=0.9))
    x, y = _data(dev, n=8)
    m.compile([x], is_train=True, use_graph=False)
    losses = [float(m(x, y)[1].data) for _ in range(8)]
    assert losses[-1] < losses[0], losses


def test_cnn_graph_equals_eager(dev):
    x, y = _data(dev, n=4)

    def make():
        dev.SetRandSeed(3)
        m = CNN(num_classes=10, num_channels=1)
        m.set_optimizer(opt.SGD(lr=0.01))
        m.compile([x], is_train=True, use_graph=False)
        return m

    m1 = make()
    m2 = make()
    m2.graph_mode = True
    from singa_tpu import model as model_mod

    m2._graph_runner = model_mod._GraphRunner(m2)
    m2.set_params({k: v.clone() for k, v in m1.get_params().items()})
    for i in range(4):
        _, l1 = m1(x, y)
        _, l2 = m2(x, y)
        np.testing.assert_allclose(float(l1.data), float(l2.data), rtol=5e-4,
                                   err_msg=f"step {i}")


def test_resnet18_forward_shape_and_step(dev):
    m = resnet18(num_classes=10)
    m.set_optimizer(opt.SGD(lr=0.01))
    x, y = _data(dev, n=2, c=3, hw=32)
    m.compile([x], is_train=True, use_graph=False)
    out, loss = m(x, y)
    assert out.shape == (2, 10)
    assert np.isfinite(float(loss.data))
    # BN running stats moved off their init during training
    rm = [v for k, v in m.get_states().items() if k.endswith("running_mean")]
    assert any(np.abs(tensor.to_numpy(t)).max() > 0 for t in rm)


def test_resnet50_param_count(dev):
    m = resnet50(num_classes=1000)
    x, _ = _data(dev, n=1, c=3, hw=64, classes=1000)
    m.compile([x], is_train=False, use_graph=False)
    n_params = sum(int(np.prod(v.shape)) for v in m.get_params().values())
    # torchvision resnet50: 25.557M params
    assert abs(n_params - 25_557_032) / 25_557_032 < 0.01, n_params


def test_resnet_eval_mode(dev):
    m = resnet18(num_classes=10)
    x, y = _data(dev, n=2, c=3, hw=32)
    m.compile([x], is_train=True, use_graph=False)
    m.set_optimizer(opt.SGD(lr=0.01))
    m(x, y)
    m.eval()
    out = m(x)
    assert out.shape == (2, 10)


def test_xception_param_count(dev):
    from singa_tpu.models.xceptionnet import Xception

    m = Xception(num_classes=1000)
    x, _ = _data(dev, n=1, c=3, hw=96, classes=1000)
    m.compile([x], is_train=False, use_graph=False)
    n_params = sum(int(np.prod(v.shape)) for v in m.get_params().values())
    # reference Xception: 22,855,952 params
    assert abs(n_params - 22_855_952) / 22_855_952 < 0.01, n_params
