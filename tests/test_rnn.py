"""RNN ops vs torch goldens + packed-weight handle semantics (reference:
test/singa/test_operation_rnn.cc, unverified)."""

import numpy as np
import pytest

from singa_tpu import autograd, layer, opt, tensor
from singa_tpu import device as device_module
from singa_tpu.ops.rnn import RNNHandle, rnn_forward

torch = pytest.importorskip("torch")


@pytest.fixture
def dev():
    d = device_module.get_default_device()
    d.SetRandSeed(0)
    return d


@pytest.fixture(autouse=True)
def _training():
    autograd.set_training(True)
    yield
    autograd.set_training(False)


def _pack_from_torch(handle, t_lstm):
    """Pack torch nn.LSTM weights into our flat layout."""
    flat = np.zeros(handle.weights_size, np.float32)
    for l in range(handle.num_layers):
        for d in range(handle.num_directions):
            sfx = f"_l{l}" + ("_reverse" if d else "")
            for name, tname in (("w_ih", f"weight_ih{sfx}"),
                                ("w_hh", f"weight_hh{sfx}"),
                                ("b_ih", f"bias_ih{sfx}"),
                                ("b_hh", f"bias_hh{sfx}")):
                a, b, shape = handle.slices[(l, d, name)]
                flat[a:b] = getattr(t_lstm, tname).detach().numpy().ravel()
    return flat


@pytest.mark.parametrize("bidirectional", [False, True])
@pytest.mark.parametrize("num_layers", [1, 2])
@pytest.mark.slow
def test_lstm_forward_backward_vs_torch(dev, num_layers, bidirectional):
    T, B, I, H = 5, 3, 4, 6
    rng = np.random.RandomState(0)
    x_np = rng.randn(T, B, I).astype(np.float32)

    t_lstm = torch.nn.LSTM(I, H, num_layers=num_layers,
                           bidirectional=bidirectional)
    handle = RNNHandle(I, H, num_layers, "lstm", bidirectional)
    flat = _pack_from_torch(handle, t_lstm)

    x = tensor.from_numpy(x_np, dev)
    D = handle.num_directions
    hx = tensor.from_numpy(np.zeros((num_layers * D, B, H), np.float32), dev)
    cx = tensor.from_numpy(np.zeros((num_layers * D, B, H), np.float32), dev)
    W = tensor.from_numpy(flat, dev)
    W.requires_grad = True
    W.stores_grad = True

    y, hy, cy = rnn_forward(x, hx, cx, W, handle)
    tx = torch.tensor(x_np, requires_grad=True)
    ty, (thy, tcy) = t_lstm(tx)

    np.testing.assert_allclose(tensor.to_numpy(y), ty.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(tensor.to_numpy(hy), thy.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(tensor.to_numpy(cy), tcy.detach().numpy(),
                               rtol=1e-4, atol=1e-5)

    # gradient wrt packed weights
    loss = autograd.reduce_sum(autograd.mul(y, y))
    grads = dict(autograd.backward(loss))
    (ty * ty).sum().backward()
    tgrad = np.zeros_like(flat)
    for l in range(num_layers):
        for d in range(D):
            sfx = f"_l{l}" + ("_reverse" if d else "")
            for name, tname in (("w_ih", f"weight_ih{sfx}"),
                                ("w_hh", f"weight_hh{sfx}"),
                                ("b_ih", f"bias_ih{sfx}"),
                                ("b_hh", f"bias_hh{sfx}")):
                a, b, _ = handle.slices[(l, d, name)]
                tgrad[a:b] = getattr(t_lstm, tname).grad.numpy().ravel()
    np.testing.assert_allclose(tensor.to_numpy(grads[W]), tgrad,
                               rtol=1e-3, atol=1e-4)


def test_gru_forward_vs_torch(dev):
    T, B, I, H = 4, 2, 3, 5
    rng = np.random.RandomState(1)
    x_np = rng.randn(T, B, I).astype(np.float32)
    t_gru = torch.nn.GRU(I, H)
    handle = RNNHandle(I, H, 1, "gru")
    flat = _pack_from_torch(handle, t_gru)

    x = tensor.from_numpy(x_np, dev)
    hx = tensor.from_numpy(np.zeros((1, B, H), np.float32), dev)
    cx = tensor.from_numpy(np.zeros((1, B, H), np.float32), dev)
    W = tensor.from_numpy(flat, dev)
    y, hy, _ = rnn_forward(x, hx, cx, W, handle)
    ty, thy = t_gru(torch.tensor(x_np))
    np.testing.assert_allclose(tensor.to_numpy(y), ty.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def test_vanilla_rnn_relu(dev):
    T, B, I, H = 3, 2, 3, 4
    rng = np.random.RandomState(2)
    x_np = rng.randn(T, B, I).astype(np.float32)
    t_rnn = torch.nn.RNN(I, H, nonlinearity="relu")
    handle = RNNHandle(I, H, 1, "vanilla_relu")
    flat = _pack_from_torch(handle, t_rnn)
    x = tensor.from_numpy(x_np, dev)
    z = tensor.from_numpy(np.zeros((1, B, H), np.float32), dev)
    W = tensor.from_numpy(flat, dev)
    y, _, _ = rnn_forward(x, z, z, W, handle)
    ty, _ = t_rnn(torch.tensor(x_np))
    np.testing.assert_allclose(tensor.to_numpy(y), ty.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_lstm_layer_learns(dev):
    """Tiny copy task: predict class from last LSTM state."""
    rng = np.random.RandomState(3)
    B, T, I = 8, 6, 4
    x_np = rng.randn(B, T, I).astype(np.float32)
    y_np = (x_np[:, 0, 0] > 0).astype(np.int32)

    from singa_tpu.models.char_rnn import CharRNN  # noqa: F401  (smoke import)

    class M(__import__("singa_tpu.model", fromlist=["Model"]).Model):
        def __init__(self):
            super().__init__()
            self.lstm = layer.LSTM(8, batch_first=True)
            self.fc = layer.Linear(2)
            self.ce = layer.SoftMaxCrossEntropy()

        def forward(self, x):
            y, _ = self.lstm(x)
            last = autograd.squeeze(
                autograd.split(y, axis=1, parts=[T - 1, 1])[1], 1)
            return self.fc(last)

        def train_one_batch(self, x, y):
            out = self.forward(x)
            loss = self.ce(out, y)
            self.optimizer(loss)
            return out, loss

    m = M()
    m.set_optimizer(opt.Adam(lr=0.05))
    x = tensor.from_numpy(x_np, dev)
    y = tensor.from_numpy(y_np, dev)
    m.compile([x], is_train=True, use_graph=False)
    losses = [float(m(x, y)[1].data) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_char_rnn_graph_mode_step(dev):
    from singa_tpu.models.char_rnn import CharRNN, one_hot

    vocab, B, T = 12, 4, 10
    rng = np.random.RandomState(4)
    idx = rng.randint(0, vocab, (B, T + 1))
    x = tensor.from_numpy(one_hot(idx[:, :-1], vocab), dev)
    y = tensor.from_numpy(idx[:, 1:].astype(np.int32), dev)
    m = CharRNN(vocab, hidden_size=16, num_layers=2, seq_length=T)
    m.set_optimizer(opt.SGD(lr=0.1))
    m.compile([x], is_train=True, use_graph=True)
    l0 = float(m(x, y)[1].data)
    for _ in range(4):
        _, loss = m(x, y)
    assert float(loss.data) < l0


def test_lstm_layer_use_pallas_flag_ignored(dev):
    """use_pallas is accepted and ignored (the fused kernel was deleted
    in round 4 after the decisive sweep — ops/rnn.py RNNHandle
    docstring records the numbers)."""
    lstm = layer.LSTM(8, use_pallas=True, batch_first=True)
    x = tensor.from_numpy(np.random.RandomState(1).randn(2, 5, 3).astype(np.float32), dev)
    y, _ = lstm(x)
    assert y.shape == (2, 5, 8)


@pytest.mark.slow
def test_charrnn_gru_and_vanilla_cells(dev):
    """The char-RNN model accepts every reference cuDNN RNN mode."""
    from singa_tpu.models.char_rnn import CharRNN, one_hot

    for cell in ("gru", "vanilla_tanh", "vanilla_relu"):
        dev.SetRandSeed(0)
        m = CharRNN(20, hidden_size=16, num_layers=1, seq_length=8,
                    cell=cell)
        m.set_optimizer(opt.SGD(lr=0.1))
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 20, (4, 8))
        x = tensor.from_numpy(one_hot(ids, 20), dev)
        y = tensor.from_numpy(np.roll(ids, -1, 1).astype(np.int32), dev)
        m.compile([x], is_train=True, use_graph=False)
        losses = [float(m(x, y)[1].data) for _ in range(5)]
        assert losses[-1] < losses[0], (cell, losses)
