"""observe.stepprof: step-anatomy host/device attribution.

The profiler's three contracts, each tested directly:

* **exactness** — exclusive-time segments sum to the step wall (one
  denominator, the ledger's seal-time idiom), host_s + device_s ==
  wall_s, and device windows sit inside the step span.
* **invisibility when off** — no registry series, no ring, and ZERO
  extra clock calls at the engine seams (the Watchdog's two
  ``perf_counter`` calls per step are the whole budget, counted by
  monkeypatching the clock).
* **invisibility when on** — byte parity with the unprofiled engine
  and zero runtime recompiles (``block_until_ready`` on materialized
  outputs never enters jitted code).

Plus the publication surfaces: dedicated-ladder registry series that
die with their engine (the retire-unregisters contract, supervisor
restarts included), the dual-lane Chrome trace, health/why_slow
sections, the Watchdog culprit feed, prefix-build quanta on a
shipless engine, and FleetTelemetry's per-host lanes."""

import time

import numpy as np
import pytest

from singa_tpu import observe, tensor
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from singa_tpu.observe import export, monitor, stepprof
from singa_tpu.observe.federate import FleetTelemetry
from singa_tpu.observe.health import health_report
from singa_tpu.observe.registry import MetricsRegistry, registry
from singa_tpu.serve import GenerationRequest, PagedConfig, \
    PrefixCacheConfig
from singa_tpu.serve.jitpin import jit_cache_size


@pytest.fixture(scope="module")
def model():
    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    return m


@pytest.fixture(autouse=True)
def _clean():
    """Profiler off, monitor off, tracing off around each test — all
    three are process-global module state."""
    stepprof.disable()
    monitor.stop()
    observe.disable()
    observe.clear()
    yield
    stepprof.disable()
    monitor.stop()
    observe.disable()
    observe.clear()


_PROMPTS = [np.arange(9) % 256, (np.arange(4) + 3) % 256,
            np.asarray([5, 1, 200])]
_NEWS = [6, 4, 5]


def _drain(eng, prompts=_PROMPTS, news=_NEWS):
    hs = [eng.submit(GenerationRequest(p, max_new_tokens=n,
                                       temperature=0.0))
          for p, n in zip(prompts, news)]
    for _ in range(200):
        if not eng.pending:
            break
        eng.step()
    return [[int(t) for t in h.result().tokens] for h in hs]


# ---------------------------------------------------------------------------
# invisibility when off
# ---------------------------------------------------------------------------

def test_disabled_mode_leaves_no_trace_in_registry_or_ring(model):
    eng = model.serve(max_slots=2)
    try:
        _drain(eng)
    finally:
        eng.close()
    assert stepprof.active() is False
    assert stepprof.profiler() is None
    assert stepprof.records() == []
    assert not [k for k in registry().snapshot()["histograms"]
                if k.startswith("serve.step.")]
    assert stepprof.section() == {"enabled": False}
    assert stepprof.why_slow_summary() is None
    assert stepprof.culprit("serve.e0") is None


def test_disabled_mode_adds_zero_clock_calls(model, monkeypatch):
    """The whole per-step clock budget with the profiler OFF is the
    Watchdog's two ``perf_counter`` calls — and zero with monitoring
    off too.  Counted by swapping the clock itself."""
    eng = model.serve(max_slots=2)
    h = eng.submit(GenerationRequest(_PROMPTS[0], max_new_tokens=20,
                                     temperature=0.0))
    eng.step()  # admission + first decode: compiles out of the way
    eng.step()
    real = time.perf_counter
    calls = [0]

    def counting():
        calls[0] += 1
        return real()

    try:
        monkeypatch.setattr(time, "perf_counter", counting)
        calls[0] = 0
        eng.step()
        assert calls[0] == 0
        monitor.start(thread=False, dump_on_hang=False)
        calls[0] = 0
        eng.step()
        assert calls[0] == 2
        monkeypatch.setattr(time, "perf_counter", real)
    finally:
        monitor.stop()
        while eng.pending:
            eng.step()
        h.result()
        eng.close()


# ---------------------------------------------------------------------------
# exactness
# ---------------------------------------------------------------------------

def test_fractions_sum_to_one_and_ring_invariants(model):
    stepprof.enable()
    eng = model.serve(max_slots=2)
    try:
        _drain(eng)
        recs = stepprof.records()
        assert recs
        for r in recs:
            # host/device split is exact by construction
            assert r["host_s"] + r["device_s"] == \
                pytest.approx(r["wall_s"], abs=1e-12)
            # exclusive segments seal to the wall ("other" absorbs
            # unfenced time; "device" is a segment key too)
            assert sum(r["segments"].values()) == \
                pytest.approx(r["wall_s"], abs=1e-9)
            assert r["device_s"] > 0 and 0.0 < r["bubble_frac"] < 1.0
            for t0, dur in r["device_windows"]:
                assert r["t0"] <= t0
                assert t0 + dur <= r["t0"] + r["wall_s"] + 1e-9
        sec = stepprof.section()
        assert sec["enabled"] is True and sec["steps"] == len(recs)
        for e in sec["engines"].values():
            fr = e["fractions"]
            assert abs(sum(fr.values()) - 1.0) < 1e-9, fr
            assert "device" in fr and "schedule" in fr
        ws = sec["why_slow"]
        assert ws["culprit"] in ("host", "device")
        assert ws["bubble_frac"] + ws["device_frac"] == \
            pytest.approx(1.0, abs=1e-9)
        assert ws["top_host_segment"] not in (None, "device")
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# invisibility when on: parity + the recompile pin
# ---------------------------------------------------------------------------

def test_profiler_on_keeps_parity_and_compiles_nothing(model):
    eng = model.serve(max_slots=2)
    try:
        want = _drain(eng)
    finally:
        eng.close()
    jit0 = jit_cache_size()
    stepprof.enable()
    eng = model.serve(max_slots=2)
    try:
        got = _drain(eng)
    finally:
        eng.close()
    assert got == want, "profiler changed tokens"
    assert jit_cache_size() == jit0, "profiler entered jitted code"


# ---------------------------------------------------------------------------
# registry series: dedicated ladder, retire-unregisters
# ---------------------------------------------------------------------------

def test_series_use_dedicated_ladder_and_die_with_engine(model):
    stepprof.enable()
    eng = model.serve(max_slots=2)
    lbl = eng.stats.engine_label
    try:
        _drain(eng)
        snap = registry().snapshot()["histograms"]
        for fam in ("wall_s", "host_s", "device_s", "bubble_frac"):
            assert f"serve.step.{fam}{{engine={lbl}}}" in snap
        assert any(k.startswith("serve.step.segment_s{")
                   and f"engine={lbl}" in k for k in snap)
        # dedicated ladder: the 100us bucket exists and the running
        # dump satisfies the +Inf == _count cumulative invariant
        for m in registry().dump()["metrics"]:
            if not m["name"].startswith("serve.step."):
                continue
            assert m["kind"] == "histogram"
            if m["name"] != "serve.step.bubble_frac":
                assert m["buckets"][0][0] == pytest.approx(1e-4)
            assert m["buckets"][-1][0] == float("inf")
            assert m["buckets"][-1][1] == m["count"]
    finally:
        eng.close()
    # the engine's close forgot its series...
    assert not [k for k in registry().snapshot()["histograms"]
                if k.startswith("serve.step.")
                and f"engine={lbl}" in k]
    # ...and a fresh engine gets fresh ones under its own label
    eng2 = model.serve(max_slots=2)
    try:
        _drain(eng2)
        lbl2 = eng2.stats.engine_label
        assert lbl2 != lbl
        assert f"serve.step.wall_s{{engine={lbl2}}}" \
            in registry().snapshot()["histograms"]
    finally:
        eng2.close()


def test_disable_without_unregister_keeps_series_readable(model):
    stepprof.enable()
    eng = model.serve(max_slots=2)
    try:
        _drain(eng)
        stepprof.disable(unregister=False)
        # profiler off, series still in the exposition (the bench's
        # --prom-out ordering: disable BEFORE close, so the close's
        # forget_engine is a no-op on a dead profiler)
        assert stepprof.active() is False
        assert [k for k in registry().snapshot()["histograms"]
                if k.startswith("serve.step.")]
    finally:
        eng.close()
    assert [k for k in registry().snapshot()["histograms"]
            if k.startswith("serve.step.")]


def test_supervisor_restart_forgets_dead_label_and_holds_jit_pin(
        model):
    """A supervisor rebuild retires the dead engine's series, the
    fresh engine's steps register under its new label, and the
    rebuild recompiles nothing (executables are cached)."""
    from singa_tpu.resilience import FailAfterN, faults
    from singa_tpu.serve import EngineSupervisor

    stepprof.enable()
    sup = EngineSupervisor(model, max_slots=2, restart_budget=2)
    lbl0 = sup.engine.stats.engine_label
    try:
        hs = [sup.submit(GenerationRequest(p, max_new_tokens=n,
                                           temperature=0.0))
              for p, n in zip(_PROMPTS, _NEWS)]
        faults.inject("serve.decode_step", FailAfterN(2, times=1))
        jit0 = jit_cache_size()
        sup.run_until_complete(max_steps=500)
        faults.clear()
        assert sup.restarts == 1
        assert jit_cache_size() == jit0
        for h in hs:
            assert h.done()
        lbl1 = sup.engine.stats.engine_label
        assert lbl1 != lbl0
        snap = registry().snapshot()["histograms"]
        assert not [k for k in snap if k.startswith("serve.step.")
                    and f"engine={lbl0}" in k]
        assert f"serve.step.wall_s{{engine={lbl1}}}" in snap
    finally:
        faults.clear()
        sup.close()


# ---------------------------------------------------------------------------
# dual-lane Chrome trace
# ---------------------------------------------------------------------------

def test_dual_lane_export_shows_bubble_gaps(model):
    stepprof.enable()
    eng = model.serve(max_slots=2)
    lbl = eng.stats.engine_label
    try:
        _drain(eng)
        recs = stepprof.records()
    finally:
        eng.close()
    doc = export.chrome_trace([], steps=recs)
    ev = doc["traceEvents"]
    names = {e["args"]["name"] for e in ev if e.get("ph") == "M"
             and e["name"] == "thread_name" and e["pid"] == 2}
    assert f"e{lbl} host" in names and f"e{lbl} device" in names
    host = [e for e in ev if e.get("ph") == "X" and e["pid"] == 2
            and e["name"].startswith("step ")]
    segs = [e for e in ev if e.get("ph") == "X" and e["pid"] == 2
            and not e["name"].startswith(("step ", "device"))]
    dev = [e for e in ev if e.get("ph") == "X" and e["pid"] == 2
          and e["name"] == "device"]
    assert len(host) == len(recs) and segs and dev
    # the bubble is VISIBLE: device slices cover strictly less of the
    # lane than the step spans (gaps = the device sitting idle)
    assert sum(e["dur"] for e in dev) < sum(e["dur"] for e in host)
    # segment sub-slices never include the device pseudo-segment
    assert all(e["name"] != "device" for e in segs)
    assert doc["otherData"]["step_records"] == len(recs)


# ---------------------------------------------------------------------------
# health + Watchdog integration
# ---------------------------------------------------------------------------

def test_health_report_carries_step_anatomy(model):
    stepprof.enable()
    eng = model.serve(max_slots=2)
    try:
        _drain(eng)
        sa = health_report()["serve"]["step_anatomy"]
        assert sa["enabled"] is True and sa["steps"] > 0
        assert sa["why_slow"]["culprit"] in ("host", "device")
    finally:
        eng.close()
    assert health_report()["serve"]["step_anatomy"]["enabled"] is True


def test_watchdog_anomaly_names_host_vs_device_culprit(model):
    """A step-time anomaly's trace event carries the profiler's
    verdict for THAT engine: host-vs-device plus the dominant host
    segment — the 'why did this step spike' answer inline."""
    stepprof.enable()
    eng = model.serve(max_slots=2)
    src = "serve.e" + eng.stats.engine_label
    try:
        _drain(eng)
    finally:
        eng.close()

    class _Clk:
        t = 0.0

        def __call__(self):
            return self.t

    clk = _Clk()
    reg = MetricsRegistry()
    wd = monitor.Watchdog(timeout_s=100.0, clock=clk, reg=reg,
                          dump_on_hang=False, warmup=8)
    observe.enable(clock=clk)
    for i in range(20):
        wd.beat(src, step_time=0.10 + 0.01 * (i % 2))
        clk.t += 0.1
    wd.beat(src, step_time=5.0)
    ev = next(e for e in observe.events()
              if e["name"] == "monitor/step_time_anomaly")
    assert ev["args"]["culprit"] in ("host", "device")
    assert 0.0 < ev["args"]["bubble_frac"] < 1.0
    assert ev["args"]["top_host_segment"] is not None


# ---------------------------------------------------------------------------
# prefix-build quanta (the disaggregated prefill specialist)
# ---------------------------------------------------------------------------

def test_prefix_build_quanta_profile_without_a_step_loop(model):
    """A prefill specialist never runs ``step()`` — its anatomy comes
    from ``advance_prefix_build`` opening a quantum per budgeted
    advance, with the chunk dispatches timed through the same
    executor seam."""
    stepprof.enable()
    eng = model.serve(
        max_slots=2, paged=PagedConfig(block_size=8, num_blocks=64),
        prefix_cache=PrefixCacheConfig(block_size=8))
    try:
        doc = (np.arange(40) * 3 % 256).astype(np.int32)
        job = eng.start_prefix_build(doc)
        assert job is not None and not job.hit
        while not eng.advance_prefix_build(job, max_tokens=8):
            pass
        eng.export_prefix_image(job)
        recs = stepprof.records()
        assert recs, "build quanta produced no step records"
        lbl = eng.stats.engine_label
        assert all(r["engine"] == lbl for r in recs)
        assert sum(len(r["device_windows"]) for r in recs) >= 4
        assert all(r["device_s"] > 0 for r in recs)
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# federation: per-host lanes + per-host anatomy
# ---------------------------------------------------------------------------

def _step_host_rec(ts, wall, dev):
    return {"name": "step/e0", "cat": "step.host", "ph": "X",
            "ts": ts, "dur": wall, "tid": "MainThread", "depth": 0,
            "parent": None,
            "args": {"engine": "0", "step": 1,
                     "bubble_frac": round(1 - dev / wall, 4),
                     "device_s": dev, "segments": {}}}


def _step_dev_rec(ts, dur):
    return {"name": "device/e0", "cat": "step.device", "ph": "X",
            "ts": ts, "dur": dur, "tid": "MainThread", "depth": 0,
            "parent": None, "args": {"engine": "0", "step": 1}}


def _host_dump(bub_sum, n):
    return {"metrics": [
        {"name": "serve.step.bubble_frac", "kind": "histogram",
         "labels": {"engine": "0"}, "sum": bub_sum, "count": n},
        {"name": "serve.step.wall_s", "kind": "histogram",
         "labels": {"engine": "0"}, "sum": 0.5, "count": n},
    ]}


def test_fleet_telemetry_builds_per_host_step_lanes():
    class _Clk:
        def __call__(self):
            return 1000.0

    ft = FleetTelemetry(clock=_Clk())
    ft.host_online("w0")
    ft.host_online("w1")
    for i, host in enumerate(("w0", "w1")):
        ft.ingest(host, {
            "trace": [_step_host_rec(10.0 + i, 0.02, 0.008),
                      _step_dev_rec(10.001 + i, 0.008)],
            "registry": _host_dump(0.6 * (i + 1), 2 + i),
        })
    doc = ft.chrome_trace(events=[], requests=[])
    by_cat = {}
    for e in doc["traceEvents"]:
        if e.get("cat") in ("step.host", "step.device") \
                and e["pid"] >= 10:
            by_cat.setdefault(e["cat"], set()).add(e["pid"])
    assert by_cat["step.host"] == by_cat["step.device"] == {10, 11}
    sec = ft.section()
    for i, host in enumerate(("w0", "w1")):
        a = sec["hosts"][host]["step_anatomy"]
        assert a["steps"] == 2 + i
        assert a["bubble_frac"] == pytest.approx(0.6 * (i + 1)
                                                 / (2 + i))
    # a host that never shipped the families answers None, not zero
    ft.host_online("w2")
    ft.ingest("w2", {"registry": {"metrics": []}})
    assert ft.section()["hosts"]["w2"]["step_anatomy"] is None
