"""MoE expert parallelism: routing math, dense equivalence, EP-sharded
vs serial equivalence, capacity drops, aux-loss gradient flow."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from singa_tpu import autograd, layer, model, opt, tensor
from singa_tpu.parallel import sharding as shd
from singa_tpu.parallel.moe import MoEFFN, _top1_dispatch, _top2_dispatch

B, S, D, E, F = 2, 8, 16, 4, 32


def test_top2_dispatch_shapes_and_gates():
    rng = np.random.RandomState(0)
    probs = jax.nn.softmax(jnp.asarray(rng.randn(10, E)), -1)
    cap = 8
    dispatch, combine, aux = _top2_dispatch(probs, cap)
    assert dispatch.shape == (10, E, cap)
    # every token dispatched to exactly 2 slots, combine weights sum to 1
    np.testing.assert_allclose(np.asarray(dispatch.sum((1, 2))), 2.0)
    np.testing.assert_allclose(np.asarray(combine.sum((1, 2))), 1.0,
                               rtol=1e-5)
    # each (expert, slot) used at most once
    assert float(dispatch.sum(0).max()) <= 1.0 + 1e-6
    assert float(aux) > 0


def test_top1_capacity_drops():
    # all tokens prefer expert 0; capacity 2 → only 2 survive
    probs = jnp.tile(jnp.asarray([[0.9, 0.1]]), (6, 1))
    dispatch, combine, aux = _top1_dispatch(probs, 2)
    assert float(dispatch.sum()) == 2.0
    # dropped tokens have zero combine weight
    np.testing.assert_allclose(np.asarray(combine.sum((1, 2))),
                               [0.9, 0.9, 0, 0, 0, 0], rtol=1e-6)


def _dense_ffn(x, w1, b1, w2, b2):
    h = jax.nn.gelu(x @ w1 + b1)
    return h @ w2 + b2


@pytest.mark.slow
def test_top2_identical_experts_equals_dense():
    """With identical experts and ample capacity, renormalized top-2
    gates sum to 1, so the MoE output equals the shared expert's FFN."""
    rng = np.random.RandomState(1)
    x = tensor.from_numpy(rng.randn(B, S, D).astype(np.float32))
    m = MoEFFN(E, F, plan=None, top_k=2, capacity_factor=4.0)
    y = m(x)
    # overwrite with identical experts
    w1 = rng.randn(D, F).astype(np.float32) * 0.1
    b1 = rng.randn(F).astype(np.float32) * 0.1
    w2 = rng.randn(F, D).astype(np.float32) * 0.1
    b2 = rng.randn(D).astype(np.float32) * 0.1
    m.W1.copy_from_numpy(np.tile(w1, (E, 1, 1)))
    m.b1.copy_from_numpy(np.tile(b1, (E, 1)))
    m.W2.copy_from_numpy(np.tile(w2, (E, 1, 1)))
    m.b2.copy_from_numpy(np.tile(b2, (E, 1)))
    y = m(x)
    ref = _dense_ffn(tensor.to_numpy(x), w1, b1, w2, b2)
    np.testing.assert_allclose(tensor.to_numpy(y), ref, rtol=1e-4,
                               atol=1e-5)


class MoEModel(model.Model):
    def __init__(self, plan=None, aux_weight=0.01, groups=None):
        super().__init__()
        self.proj = layer.Linear(D)
        self.moe = MoEFFN(E, F, plan=plan, top_k=2, capacity_factor=4.0,
                          groups=groups)
        self.head = layer.Linear(4)
        self.loss_fn = layer.SoftMaxCrossEntropy()
        self.aux_weight = aux_weight

    def forward(self, x):
        h = self.moe(self.proj(x))
        return self.head(autograd.reduce_mean(h, axes=(1,), keepdims=False))

    def train_one_batch(self, x, y):
        logits = self.forward(x)
        loss = self.loss_fn(logits, y)
        aux = self.moe.last_aux_loss
        total = autograd.add(loss,
                             autograd.mul_scalar(aux, self.aux_weight))
        self.optimizer(total)
        return logits, total


def _data(seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(B, S, D).astype(np.float32)
    y = rng.randint(0, 4, size=(B,)).astype(np.int32)
    return x, y


@pytest.mark.slow
def test_ep_sharded_matches_serial():
    mesh = shd.create_mesh(dp=2, ep=4)
    plan = shd.ShardingPlan(mesh)

    # serial oracle pins groups=2 to reproduce the plan's grouped
    # (GShard groups-on-data) routing math exactly
    serial = MoEModel(plan=None, groups=2)
    par = MoEModel(plan=plan)
    par.set_sharding_plan(plan)
    for m in (serial, par):
        x, y = _data()
        m.set_optimizer(opt.SGD(lr=0.1))
        m.compile([tensor.from_numpy(x)], is_train=True, use_graph=True)
    par.set_states({k: tensor.to_numpy(v)
                    for k, v in serial.get_states().items()})

    for i in range(2):
        x, y = _data(seed=i)
        _, ls = serial(tensor.from_numpy(x), tensor.from_numpy(y))
        _, lp = par(tensor.from_numpy(x), tensor.from_numpy(y))
        np.testing.assert_allclose(
            float(tensor.to_numpy(lp)), float(tensor.to_numpy(ls)),
            rtol=2e-4)
    for k, vs in serial.get_states().items():
        np.testing.assert_allclose(
            tensor.to_numpy(par.get_states()[k]), tensor.to_numpy(vs),
            rtol=2e-3, atol=2e-4, err_msg=k)


@pytest.mark.slow
def test_aux_loss_trains_router():
    """The aux loss must flow gradients into the router weights."""
    m = MoEModel(plan=None, aux_weight=0.1)
    x, y = _data()
    m.set_optimizer(opt.SGD(lr=0.5))
    m.compile([tensor.from_numpy(x)], is_train=True, use_graph=False)
    wg0 = tensor.to_numpy(m.moe.Wg).copy()
    m(tensor.from_numpy(x), tensor.from_numpy(y))
    assert not np.allclose(tensor.to_numpy(m.moe.Wg), wg0), \
        "router weights unchanged — aux/main loss not reaching Wg"
