"""Pipeline parallelism: GPipe over the pipe axis equals the serial
layer stack (losses + trained params), dp x pp composition."""

import numpy as np
import pytest

import jax

from singa_tpu import autograd, layer, model, opt, tensor
from singa_tpu.parallel import sharding as shd
from singa_tpu.parallel.pipeline import PipelinedTransformer

VOCAB, HIDDEN, HEADS, INTER, LAYERS = 32, 16, 2, 32, 4
B, S = 8, 6


class PipeLM(model.Model):
    def __init__(self, plan=None, num_microbatches=4):
        super().__init__()
        self.embed = layer.Embedding(VOCAB, HIDDEN)
        self.trunk = PipelinedTransformer(
            LAYERS, HEADS, INTER, plan=plan,
            num_microbatches=num_microbatches)
        self.head = layer.Linear(VOCAB)
        self.loss_fn = layer.SoftMaxCrossEntropy()

    def forward(self, ids):
        return self.head(self.trunk(self.embed(ids)))

    def train_one_batch(self, ids, labels):
        logits = self.forward(ids)
        b, s, v = logits.shape
        loss = self.loss_fn(
            autograd.reshape(logits, (b * s, v)),
            autograd.reshape(labels, (b * s,)))
        self.optimizer(loss)
        return logits, loss


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randint(0, VOCAB, size=(B, S)).astype(np.int32),
            rng.randint(0, VOCAB, size=(B, S)).astype(np.int32))


def _compile(m):
    ids, _ = _batch()
    m.set_optimizer(opt.SGD(lr=0.1))
    m.compile([tensor.from_numpy(ids)], is_train=True, use_graph=True)
    return m


@pytest.mark.slow
def test_gpipe_matches_serial():
    mesh = shd.create_mesh(dp=2, pp=4)
    plan = shd.ShardingPlan(mesh)

    serial = _compile(PipeLM(plan=None))
    par = PipeLM(plan=plan)
    par.set_sharding_plan(plan)
    _compile(par)
    par.set_states({k: tensor.to_numpy(v)
                    for k, v in serial.get_states().items()})

    for i in range(2):
        ids, labels = _batch(seed=i)
        _, ls = serial(tensor.from_numpy(ids), tensor.from_numpy(labels))
        _, lp = par(tensor.from_numpy(ids), tensor.from_numpy(labels))
        np.testing.assert_allclose(float(tensor.to_numpy(lp)),
                                   float(tensor.to_numpy(ls)), rtol=2e-4)

    ps, pp_ = serial.get_states(), par.get_states()
    for k in ps:
        np.testing.assert_allclose(
            tensor.to_numpy(pp_[k]), tensor.to_numpy(ps[k]),
            rtol=2e-3, atol=2e-4, err_msg=k)


def test_pipeline_validation():
    import pytest

    mesh = shd.create_mesh(pp=4)
    plan = shd.ShardingPlan(mesh)
    with pytest.raises(ValueError):
        PipelinedTransformer(3, HEADS, INTER, plan=plan)  # 3 % 4 != 0


@pytest.mark.slow
def test_serial_stack_trains():
    m = _compile(PipeLM(plan=None))
    losses = []
    for i in range(10):
        ids, labels = _batch(seed=0)
        _, loss = m(tensor.from_numpy(ids), tensor.from_numpy(labels))
        losses.append(float(tensor.to_numpy(loss)))
    assert losses[-1] < losses[0]
