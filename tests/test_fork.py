"""Copy-on-write KV forking + constrained structured decoding (the
fork round: serve/fork.py, serve/structured.py, the engine's
``n>1``/``fork()``/``prune()``/``structured=`` surface).

Everything deterministic on CPU.  Parity oracles: branch 0 of an
``n>1`` group must be BYTE-identical to the plain ``n=1`` stream
(greedy, seeded sampling, GQA, int8, warm prefix), and a forked
parent's stream must be unchanged by its children's divergent writes
(CoW isolation).  The leak invariant is asserted through
``InferenceEngine.check_block_accounting`` after every drain."""

import json

import numpy as np
import pytest

from singa_tpu import tensor
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from singa_tpu.observe import requests as reqtrace
from singa_tpu.observe.registry import registry
from singa_tpu.resilience import FailAfterN, FaultInjected, faults
from singa_tpu.serve import (ForkHandle, GenerationRequest,
                             JsonSchemaAutomaton, PagedConfig,
                             PrefixCacheConfig, PriorityScheduler)


def _build(cfg):
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    return m


@pytest.fixture(scope="module")
def model():
    return _build(GPT2Config.tiny(dropout=0.0))


@pytest.fixture(scope="module")
def model256():
    # byte-sized vocab so token ids ARE characters for the
    # structured-decoding tests
    return _build(GPT2Config.tiny(dropout=0.0, vocab_size=256))


_VOCAB = [chr(c) for c in range(256)]


def _paged(**kw):
    base = dict(block_size=8, num_blocks=32)
    base.update(kw)
    return PagedConfig(**base)


def _drained_ok(eng):
    """The leak invariant: after a drain every used block is
    cache-owned (check_block_accounting raises on any leak)."""
    used = eng.check_block_accounting()
    cached = (eng.prefix_cache.cached_blocks
              if eng.prefix_cache is not None else 0)
    assert used == cached
    return used


def _plain_stream(model, prompt, n_new, temperature, seed, **serve_kw):
    eng = model.serve(max_slots=4, paged=_paged(), **serve_kw)
    h = eng.submit(GenerationRequest(prompt, max_new_tokens=n_new,
                                     temperature=temperature,
                                     seed=seed))
    eng.run_until_complete()
    out = h.result().tokens
    eng.close()
    return out


# -- best-of-n -----------------------------------------------------------

@pytest.mark.parametrize("temperature,seed", [(0.0, 0), (0.9, 7)])
def test_branch0_byte_parity(model, temperature, seed):
    """Branch 0 of an n=3 group is the EXACT stream n=1 produces —
    greedy and seeded sampling — and every sibling completes with a
    score.  Greedy siblings are identical (same argmax); sampled ones
    diverge after the shared first token."""
    prompt = (np.arange(6, dtype=np.int32) + 11)
    base = _plain_stream(model, prompt, 8, temperature, seed)
    eng = model.serve(max_slots=4, paged=_paged())
    fh = eng.submit(GenerationRequest(
        prompt, max_new_tokens=8, temperature=temperature, seed=seed,
        n=3))
    assert isinstance(fh, ForkHandle)
    eng.run_until_complete()
    assert fh.done()
    res = fh.results()
    assert len(res) == 3
    assert np.array_equal(res[0].tokens, base)
    for k, r in enumerate(res):
        assert r.branch == k
        assert r.score is not None
        assert len(r.tokens) == len(prompt) + 8
        # the first sampled token is shared (fork happens after it)
        assert r.tokens[len(prompt)] == base[len(prompt)]
    if temperature == 0.0:
        assert all(np.array_equal(r.tokens, base) for r in res)
    else:
        assert any(not np.array_equal(r.tokens, base)
                   for r in res[1:]), "siblings never diverged"
    ranked = fh.ranked()
    assert [r.score for r in ranked] == sorted(
        (r.score for r in ranked), reverse=True)
    assert fh.best() is ranked[0]
    _drained_ok(eng)
    snap = eng.stats.snapshot()
    assert snap["paged"]["blocks_used"] == 0
    eng.close()


def test_gqa_branch0_parity():
    """GQA models (narrow H_kv cache leaves) fork identically."""
    m = _build(GPT2Config.tiny(dropout=0.0, n_kv_head=2))
    prompt = (np.arange(5, dtype=np.int32) + 3)
    base = _plain_stream(m, prompt, 6, 0.8, 3)
    eng = m.serve(max_slots=3, paged=_paged())
    fh = eng.submit(GenerationRequest(prompt, max_new_tokens=6,
                                      temperature=0.8, seed=3, n=2))
    eng.run_until_complete()
    assert np.array_equal(fh.results()[0].tokens, base)
    _drained_ok(eng)
    eng.close()


def test_int8_branch0_parity(model):
    """Quantized arena: branch 0 equals the plain int8 stream."""
    prompt = (np.arange(7, dtype=np.int32) + 5)
    base = _plain_stream(model, prompt, 6, 0.7, 11,
                         cache_dtype="int8")
    eng = model.serve(max_slots=3, paged=_paged(),
                      cache_dtype="int8")
    fh = eng.submit(GenerationRequest(prompt, max_new_tokens=6,
                                      temperature=0.7, seed=11, n=2))
    eng.run_until_complete()
    assert np.array_equal(fh.results()[0].tokens, base)
    _drained_ok(eng)
    eng.close()


def test_shared_prompt_blocks_accounted(model):
    """While branches decode, the shared prompt blocks are counted
    ONCE by the accounting invariant (ownership is the block id) and
    the fork gauge reports them shared."""
    prompt = (np.arange(17, dtype=np.int32) + 2)  # 2 full blocks at B=8
    eng = model.serve(max_slots=4, paged=_paged())
    fh = eng.submit(GenerationRequest(prompt, max_new_tokens=10,
                                      temperature=0.9, seed=5, n=3))
    eng.step()  # admits the parent and forks the siblings
    assert len(fh.branches) == 3
    arena = eng.paged_arena
    assert arena.shared_blocks >= 2, "prompt blocks not shared"
    # n branches over one prompt use FEWER blocks than n independent
    # admissions would (the whole point): shared prefix counted once
    independent = 3 * (len(prompt) // 8 + 1)
    assert arena.blocks_used < independent
    eng.check_block_accounting()  # shared != leaked, mid-flight
    eng.run_until_complete()
    _drained_ok(eng)
    eng.close()


# -- tree search: fork() / prune() ---------------------------------------

def test_midstream_fork_cow_isolation(model):
    """Forking a live stream mid-generation leaves the PARENT's
    remaining tokens byte-identical to the unforked run (the child's
    divergent writes land in CoW copies, never in shared blocks), and
    the child's stream shares exactly the pre-fork tokens."""
    prompt = (np.arange(6, dtype=np.int32) + 21)
    base = _plain_stream(model, prompt, 12, 0.85, 13)
    eng = model.serve(max_slots=4, paged=_paged())
    h = eng.submit(GenerationRequest(prompt, max_new_tokens=12,
                                     temperature=0.85, seed=13))
    rid = h.request.request_id
    # run the parent a few tokens in, then split
    for _ in range(4):
        eng.step()
    bh = eng.fork(rid)
    assert bh.branch == 1
    eng.run_until_complete()
    got = h.result().tokens
    assert np.array_equal(got, base), "fork perturbed the parent"
    child = bh.result()
    assert child.request_id == f"{rid}#1"
    # shared history: prompt + pre-fork tokens identical, then the
    # child's re-keyed chain takes over
    pre = len(prompt) + 4
    assert np.array_equal(child.tokens[:pre], base[:pre])
    assert not np.array_equal(child.tokens, base)
    lbl = eng.stats.engine_label
    assert registry().snapshot()["counters"][
        f"serve.fork.cow_copies{{engine={lbl}}}"] >= 1
    _drained_ok(eng)
    eng.close()


def test_prune_frees_private_blocks(model):
    """prune() seals a complete finish_reason="pruned" result and
    returns the branch's PRIVATE blocks to the pool immediately;
    shared prompt blocks stay until the last sibling drops them."""
    prompt = (np.arange(9, dtype=np.int32) + 4)
    eng = model.serve(max_slots=4, paged=_paged())
    fh = eng.submit(GenerationRequest(prompt, max_new_tokens=16,
                                      temperature=0.9, seed=2, n=3))
    for _ in range(10):
        eng.step()
    arena = eng.paged_arena
    used_before = arena.blocks_used
    victim = fh.branches[2]
    victim.prune()
    r = victim.result()
    assert r.finish_reason == "pruned"
    assert r.branch == 2 and r.score is not None
    assert len(r.tokens) > len(prompt)  # everything emitted so far
    assert arena.blocks_used < used_before, "prune freed nothing"
    victim.prune()  # idempotent no-op once done
    eng.run_until_complete()
    assert fh.done()
    # pruned branches are excluded from the ranking
    assert all(rr.finish_reason != "pruned" for rr in fh.ranked())
    assert len(fh.results()) == 3
    lbl = eng.stats.engine_label
    assert registry().snapshot()["counters"][
        f"serve.fork.pruned{{engine={lbl}}}"] == 1
    _drained_ok(eng)
    eng.close()


def test_fork_with_prefix_cache(model):
    """Fork over a warm radix-tree admission: cache-owned prefix
    blocks are referenced (never CoW-copied), branch 0 keeps byte
    parity, and after the drain every used block is cache-owned —
    the last retiring sibling donates the prompt."""
    rng = np.random.RandomState(8)
    system = rng.randint(0, 256, 24).astype(np.int32)
    prompt = np.concatenate(
        [system, rng.randint(0, 256, 6).astype(np.int32)])
    kw = dict(prefix_cache=PrefixCacheConfig(block_size=8))
    eng = model.serve(max_slots=4, paged=_paged(num_blocks=48), **kw)
    # first pass populates the tree; second forks off a warm hit
    eng.submit(GenerationRequest(prompt, max_new_tokens=4))
    eng.run_until_complete()
    base = _plain_stream(model, prompt, 8, 0.9, 17, **kw)
    fh = eng.submit(GenerationRequest(prompt, max_new_tokens=8,
                                      temperature=0.9, seed=17, n=3))
    eng.run_until_complete()
    assert np.array_equal(fh.results()[0].tokens, base)
    snap = eng.stats.snapshot()
    assert snap["prefix"]["hit_tokens"] > 0
    used = _drained_ok(eng)
    assert used == snap["prefix"]["cached_blocks"]
    eng.close()


def test_fork_under_priority_preemption(model):
    """Composition with priority preemption: a higher-priority
    arrival preempts forked branches (byte-copied swap), they resume
    and finish with the SAME streams a roomy pool produces, and
    nothing leaks."""
    prompt = (np.arange(10, dtype=np.int32) + 6)
    req = dict(max_new_tokens=20, temperature=0.9, seed=9, n=3)
    hi_prompt = (np.arange(12, dtype=np.int32) + 40)

    def run(num_blocks):
        eng = model.serve(max_slots=4, scheduler=PriorityScheduler(),
                          paged=_paged(num_blocks=num_blocks))
        fh = eng.submit(GenerationRequest(prompt, **req))
        for _ in range(4):
            eng.step()
        hi = eng.submit(GenerationRequest(
            hi_prompt, max_new_tokens=26, priority=5))
        eng.run_until_complete()
        outs = [r.tokens for r in fh.results()] \
            + [hi.result().tokens]
        preempts = eng.stats.snapshot()["paged"]["preemptions"]
        _drained_ok(eng)
        eng.close()
        return outs, preempts

    roomy, _ = run(64)
    tight, preempts = run(10)
    assert preempts > 0, "pool never over-committed"
    assert all(np.array_equal(a, b) for a, b in zip(roomy, tight))


def test_cow_copy_fault_rejects_one_branch(model):
    """A fault at the serve.fork_copy site (the CoW block copy)
    rejects ONLY the writing branch, typed; siblings and the parent
    finish with parity, the engine never fails, nothing leaks."""
    prompt = (np.arange(6, dtype=np.int32) + 31)
    eng = model.serve(max_slots=4, paged=_paged())
    fh = eng.submit(GenerationRequest(prompt, max_new_tokens=12,
                                      temperature=0.9, seed=21, n=3))
    base = None  # parity oracle: same group, no fault
    pol = faults.inject("serve.fork_copy", FailAfterN(0, times=1))
    try:
        eng.run_until_complete()
    finally:
        faults.clear()
    assert pol.fired == 1
    done = rejected = 0
    for b in fh.branches:
        try:
            b.result()
            done += 1
        except FaultInjected as e:
            assert e.site == "serve.fork_copy"
            rejected += 1
    assert rejected == 1 and done == 2
    _drained_ok(eng)
    # fresh-pool parity: the unfaulted group on a new engine matches
    # the survivors' streams (the fault never corrupted shared KV)
    eng2 = model.serve(max_slots=4, paged=_paged())
    fh2 = eng2.submit(GenerationRequest(prompt, max_new_tokens=12,
                                        temperature=0.9, seed=21,
                                        n=3))
    eng2.run_until_complete()
    clean = {r.branch: r.tokens for r in fh2.results()}
    for b in fh.branches:
        if b.done():
            try:
                r = b.result()
            except FaultInjected:
                continue
            assert np.array_equal(r.tokens, clean[r.branch])
    _drained_ok(eng2)
    eng.close()
    eng2.close()


# -- structured decoding -------------------------------------------------

_SCHEMA = {"type": "object", "properties": {
    "verdict": {"enum": ["yes", "no", "maybe"]},
    "count": {"type": "integer"},
    "flag": {"type": "boolean"},
}}


def _decode_txt(tokens, plen):
    return "".join(_VOCAB[t] for t in tokens[plen:])


@pytest.mark.parametrize("temperature,seed",
                         [(0.0, 0), (0.9, 1), (1.2, 42)])
def test_structured_always_schema_valid(model256, temperature, seed):
    """Every constrained stream — greedy or sampled, any seed —
    json.loads-parses and matches the schema's keys and types, and
    the request retires "stop" when the automaton completes."""
    a = JsonSchemaAutomaton(_SCHEMA, _VOCAB, max_digits=4)
    prompt = (np.arange(5, dtype=np.int32) + 60)
    eng = model256.serve(max_slots=2, paged=_paged())
    h = eng.submit(GenerationRequest(
        prompt, max_new_tokens=64, temperature=temperature, seed=seed,
        structured=a))
    eng.run_until_complete()
    r = h.result()
    assert r.finish_reason == "stop"
    obj = json.loads(_decode_txt(r.tokens, len(prompt)))
    assert set(obj) == {"verdict", "count", "flag"}
    assert obj["verdict"] in ("yes", "no", "maybe")
    assert isinstance(obj["count"], int)
    assert isinstance(obj["flag"], bool)
    _drained_ok(eng)
    eng.close()


def test_structured_composes_with_fork(model256):
    """n>1 x structured: every branch independently satisfies the
    grammar (branches share the automaton but advance private state
    snapshots)."""
    a = JsonSchemaAutomaton(_SCHEMA, _VOCAB, max_digits=3)
    prompt = (np.arange(4, dtype=np.int32) + 90)
    eng = model256.serve(max_slots=4, paged=_paged())
    fh = eng.submit(GenerationRequest(
        prompt, max_new_tokens=64, temperature=1.0, seed=6, n=3,
        structured=a))
    eng.run_until_complete()
    texts = set()
    for r in fh.results():
        assert r.finish_reason == "stop"
        txt = _decode_txt(r.tokens, len(prompt))
        json.loads(txt)
        texts.add(txt)
    assert len(texts) > 1, "constrained branches never diverged"
    _drained_ok(eng)
    eng.close()


def test_automaton_compile_validation():
    """Ambiguous or unsupported schemas fail typed at CONSTRUCTION,
    never inside the serve loop."""
    with pytest.raises(ValueError, match="at least one property"):
        JsonSchemaAutomaton({"type": "array"}, _VOCAB)
    with pytest.raises(ValueError, match="unsupported value schema"):
        JsonSchemaAutomaton(
            {"type": "object",
             "properties": {"x": {"type": "number"}}}, _VOCAB)
    with pytest.raises(ValueError, match="first char"):
        JsonSchemaAutomaton(
            {"type": "object",
             "properties": {"x": {"enum": ["yes", "yellow"]}}},
            _VOCAB)
    with pytest.raises(ValueError, match="enum must be non-empty"):
        JsonSchemaAutomaton(
            {"type": "object", "properties": {"x": {"enum": []}}},
            _VOCAB)


# -- typed configuration errors ------------------------------------------

def test_request_validation_typed(model, model256):
    prompt = (np.arange(5, dtype=np.int32) + 1)
    with pytest.raises(ValueError, match="pin_session"):
        GenerationRequest(prompt, n=2, pin_session=True,
                          max_new_tokens=4)
    with pytest.raises(ValueError, match="nothing to diverge"):
        GenerationRequest(prompt, n=2, max_new_tokens=1)
    with pytest.raises(ValueError, match="n must be >= 1"):
        GenerationRequest(prompt, n=0)
    with pytest.raises(ValueError, match="callable"):
        GenerationRequest(prompt, structured=object())

    # n>1 / structured need a paged engine
    eng = model.serve(max_slots=2)
    with pytest.raises(ValueError, match="paged engine"):
        eng.submit(GenerationRequest(prompt, n=2, max_new_tokens=4))
    with pytest.raises(ValueError, match="paged engine"):
        eng.submit(GenerationRequest(
            prompt, structured=JsonSchemaAutomaton(
                _SCHEMA, _VOCAB), max_new_tokens=4))
    with pytest.raises(ValueError):
        eng.fork("nope")
    eng.close()

    # family over the block budget fails at submit, typed
    eng = model.serve(max_slots=4, paged=_paged(num_blocks=8))
    with pytest.raises(ValueError, match="per-branch"):
        eng.submit(GenerationRequest(prompt, n=4, max_new_tokens=30))
    # vocab mismatch between automaton and model
    with pytest.raises(ValueError, match="vocab"):
        eng.submit(GenerationRequest(
            prompt, max_new_tokens=8,
            structured=JsonSchemaAutomaton(_SCHEMA, _VOCAB[:100])))
    # fork verbs on unknown / non-live requests
    with pytest.raises(ValueError, match="unknown or already"):
        eng.fork("req-does-not-exist")
    with pytest.raises(ValueError, match="not a live or swapped"):
        eng.prune("req-does-not-exist")
    h = eng.submit(GenerationRequest(prompt, max_new_tokens=4))
    with pytest.raises(ValueError, match="still queued"):
        eng.fork(h.request.request_id)
    eng.run_until_complete()
    _drained_ok(eng)
    eng.close()


# -- ledger: branch-aware timelines --------------------------------------

def test_ledger_branch_hops_and_pruned_seal(model):
    """Forked branches record their branch id on the admission hop
    with zero queue/prefill phases; a pruned branch seals as a
    COMPLETED outcome (never a wedged or rejected entry)."""
    reqtrace.enable(capacity=64)
    try:
        prompt = (np.arange(6, dtype=np.int32) + 2)
        eng = model.serve(max_slots=4, paged=_paged())
        fh = eng.submit(GenerationRequest(
            prompt, max_new_tokens=10, temperature=0.9, seed=4, n=2))
        for _ in range(4):
            eng.step()
        fh.branches[1].prune()
        eng.run_until_complete()
        led = reqtrace.ledger()
        parent = led.entry(fh.request_id)
        child = led.entry(f"{fh.request_id}#1")
        assert parent["outcome"] in ("length", "stop")
        assert parent["hops"][0]["branch"] is None
        assert child["outcome"] == "pruned"
        hop = child["hops"][0]
        assert hop["branch"] == 1
        # branch admissions skip queue and prefill by construction
        assert child["phases"]["queue"] == 0.0
        assert child["phases"]["prefill"] == 0.0
        _drained_ok(eng)
        eng.close()
    finally:
        reqtrace.disable()
