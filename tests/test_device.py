"""Device/platform tests (reference: test/singa/test_platform.cc, unverified)."""

import numpy as np

from singa_tpu import device as device_module
from singa_tpu import tensor


def test_default_device():
    dev = device_module.get_default_device()
    assert dev.lang() == "kCpp"
    assert device_module.get_default_device() is dev  # singleton


def test_create_tpu_device():
    dev = device_module.create_tpu_device(0)
    assert dev.lang() == "kTpu"
    # cached per id (Platform caches devices in the reference too)
    assert device_module.create_tpu_device(0) is dev


def test_cuda_aliases_map_to_accelerator():
    dev = device_module.create_cuda_gpu()
    assert dev is device_module.create_tpu_device(0)
    devs = device_module.create_cuda_gpus_on([0, 1])
    assert len(devs) == 2


def test_tensor_on_tpu_device_roundtrip():
    dev = device_module.create_tpu_device(0)
    x = np.arange(8, dtype=np.float32)
    t = tensor.from_numpy(x, dev)
    t2 = (t * 2.0) + 1.0
    np.testing.assert_allclose(tensor.to_numpy(t2), 2 * x + 1)
    t.to_host()
    assert t.device.lang() == "kCpp"


def test_graph_flag():
    dev = device_module.create_tpu_device(0)
    assert not dev.graph_enabled()
    dev.EnableGraph(True)
    assert dev.graph_enabled()
    dev.EnableGraph(False)


def test_sync_and_query():
    dev = device_module.get_default_device()
    dev.Sync()  # must not raise
    info = device_module.device_query()
    assert info["num_devices"] >= 1
