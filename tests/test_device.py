"""Device/platform tests (reference: test/singa/test_platform.cc, unverified)."""

import numpy as np

from singa_tpu import device as device_module
from singa_tpu import tensor


def test_default_device():
    dev = device_module.get_default_device()
    assert dev.lang() == "kCpp"
    assert device_module.get_default_device() is dev  # singleton


def test_create_tpu_device():
    dev = device_module.create_tpu_device(0)
    assert dev.lang() == "kTpu"
    # cached per id (Platform caches devices in the reference too)
    assert device_module.create_tpu_device(0) is dev


def test_cuda_aliases_map_to_accelerator():
    dev = device_module.create_cuda_gpu()
    assert dev is device_module.create_tpu_device(0)
    devs = device_module.create_cuda_gpus_on([0, 1])
    assert len(devs) == 2


def test_tensor_on_tpu_device_roundtrip():
    dev = device_module.create_tpu_device(0)
    x = np.arange(8, dtype=np.float32)
    t = tensor.from_numpy(x, dev)
    t2 = (t * 2.0) + 1.0
    np.testing.assert_allclose(tensor.to_numpy(t2), 2 * x + 1)
    t.to_host()
    assert t.device.lang() == "kCpp"


def test_graph_flag():
    dev = device_module.create_tpu_device(0)
    assert not dev.graph_enabled()
    dev.EnableGraph(True)
    assert dev.graph_enabled()
    dev.EnableGraph(False)


def test_sync_and_query():
    dev = device_module.get_default_device()
    dev.Sync()  # must not raise
    info = device_module.device_query()
    assert info["num_devices"] >= 1


def test_print_time_profiling_measured_durations(tmp_path):
    """Trace-backed PrintTimeProfiling (VERDICT weak #6): capture a
    jax.profiler trace of K compiled steps of a jitted MLP, and the
    parsed table must carry NONZERO measured durations for real
    XLA-op events (not just host Python frames, which are filtered)."""
    import jax
    import jax.numpy as jnp

    dev = device_module.get_default_device()

    @jax.jit
    def mlp(x, w1, w2):
        return jax.nn.gelu(x @ w1) @ w2

    x = jnp.ones((8, 64), jnp.float32)
    w1 = jnp.ones((64, 128), jnp.float32)
    w2 = jnp.ones((128, 64), jnp.float32)
    mlp(x, w1, w2).block_until_ready()  # compile outside the capture

    logdir = str(tmp_path / "prof")
    dev.enable_profiling(logdir)
    try:
        for _ in range(4):
            mlp(x, w1, w2).block_until_ready()
    finally:
        dev.disable_profiling()

    measured = dev.PrintTimeProfiling()
    assert measured, "no measured events parsed from the trace"
    assert all(rec["total_us"] > 0 and rec["count"] >= 1
               for rec in measured.values())
    # at least one event is a real XLA op/dispatch, not host overhead
    assert any(("dot" in n or "fusion" in n or "Execute" in n
                or "gelu" in n)
               for n in measured), sorted(measured)
    # python frame events are filtered out of the table
    assert not any(n.startswith("$") or ".py:" in n for n in measured)
