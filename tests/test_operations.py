"""Conv/BN/Pool fwd+bwd vs torch CPU goldens (reference strategy:
test/python/test_operation.py compares against numpy/cudnn goldens,
unverified; torch is an independent implementation available here)."""

import numpy as np
import pytest

from singa_tpu import autograd, tensor
from singa_tpu import device as device_module

torch = pytest.importorskip("torch")


@pytest.fixture
def dev():
    return device_module.get_default_device()


@pytest.fixture(autouse=True)
def _training():
    autograd.set_training(True)
    yield
    autograd.set_training(False)


def _param(arr, dev):
    t = tensor.from_numpy(arr, dev)
    t.requires_grad = True
    t.stores_grad = True
    return t


def _t(arr):
    return torch.tensor(arr, requires_grad=True)


def test_conv2d_forward_backward_vs_torch(dev):
    rng = np.random.RandomState(0)
    x_np = rng.randn(2, 3, 8, 8).astype(np.float32)
    w_np = rng.randn(4, 3, 3, 3).astype(np.float32)
    b_np = rng.randn(4).astype(np.float32)

    from singa_tpu.ops import conv as conv_ops

    x, w, b = _param(x_np, dev), _param(w_np, dev), _param(b_np, dev)
    y = conv_ops.conv2d(x, w, b, stride=(2, 2), padding=(1, 1))
    loss = autograd.reduce_sum(autograd.mul(y, y))
    grads = dict(autograd.backward(loss))

    tx, tw, tb = _t(x_np), _t(w_np), _t(b_np)
    ty = torch.nn.functional.conv2d(tx, tw, tb, stride=2, padding=1)
    (ty * ty).sum().backward()

    np.testing.assert_allclose(tensor.to_numpy(y), ty.detach().numpy(),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(tensor.to_numpy(grads[x]), tx.grad.numpy(),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(tensor.to_numpy(grads[w]), tw.grad.numpy(),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(tensor.to_numpy(grads[b]), tb.grad.numpy(),
                               rtol=1e-3, atol=1e-3)


def test_conv2d_grouped_vs_torch(dev):
    rng = np.random.RandomState(1)
    x_np = rng.randn(2, 4, 6, 6).astype(np.float32)
    w_np = rng.randn(8, 2, 3, 3).astype(np.float32)  # groups=2

    from singa_tpu.ops import conv as conv_ops

    x, w = _param(x_np, dev), _param(w_np, dev)
    y = conv_ops.conv2d(x, w, None, stride=(1, 1), padding=(1, 1), group=2)
    tx, tw = _t(x_np), _t(w_np)
    ty = torch.nn.functional.conv2d(tx, tw, None, padding=1, groups=2)
    np.testing.assert_allclose(tensor.to_numpy(y), ty.detach().numpy(),
                               rtol=1e-4, atol=1e-4)


def test_maxpool_vs_torch(dev):
    rng = np.random.RandomState(2)
    x_np = rng.randn(2, 3, 8, 8).astype(np.float32)

    from singa_tpu.ops import pooling as pool_ops

    x = _param(x_np, dev)
    y = pool_ops.pooling2d(x, kernel=(2, 2), stride=(2, 2), is_max=True)
    loss = autograd.reduce_sum(y)
    grads = dict(autograd.backward(loss))

    tx = _t(x_np)
    ty = torch.nn.functional.max_pool2d(tx, 2, 2)
    ty.sum().backward()
    np.testing.assert_allclose(tensor.to_numpy(y), ty.detach().numpy(), rtol=1e-5)
    np.testing.assert_allclose(tensor.to_numpy(grads[x]), tx.grad.numpy(),
                               rtol=1e-5)


def test_avgpool_vs_torch(dev):
    rng = np.random.RandomState(3)
    x_np = rng.randn(2, 3, 8, 8).astype(np.float32)

    from singa_tpu.ops import pooling as pool_ops

    x = _param(x_np, dev)
    y = pool_ops.pooling2d(x, kernel=(2, 2), stride=(2, 2), is_max=False)
    tx = _t(x_np)
    ty = torch.nn.functional.avg_pool2d(tx, 2, 2)
    np.testing.assert_allclose(tensor.to_numpy(y), ty.detach().numpy(), rtol=1e-5)


def test_batchnorm_train_vs_torch(dev):
    rng = np.random.RandomState(4)
    x_np = rng.randn(4, 3, 5, 5).astype(np.float32)
    s_np = rng.rand(3).astype(np.float32) + 0.5
    b_np = rng.randn(3).astype(np.float32)

    from singa_tpu.ops import batchnorm as bn_ops

    x, s, b = _param(x_np, dev), _param(s_np, dev), _param(b_np, dev)
    rmean = tensor.from_numpy(np.zeros(3, np.float32), dev)
    rvar = tensor.from_numpy(np.ones(3, np.float32), dev)
    y = bn_ops.batchnorm2d(x, s, b, rmean, rvar, momentum=0.9, eps=1e-5)
    loss = autograd.reduce_sum(autograd.mul(y, y))
    grads = dict(autograd.backward(loss))

    tx, ts, tb = _t(x_np), _t(s_np), _t(b_np)
    ty = torch.nn.functional.batch_norm(
        tx, torch.zeros(3), torch.ones(3), ts, tb, training=True, eps=1e-5)
    (ty * ty).sum().backward()

    np.testing.assert_allclose(tensor.to_numpy(y), ty.detach().numpy(),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(tensor.to_numpy(grads[x]), tx.grad.numpy(),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(tensor.to_numpy(grads[s]), ts.grad.numpy(),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(tensor.to_numpy(grads[b]), tb.grad.numpy(),
                               rtol=1e-3, atol=1e-3)
    # running stats updated: r = 0.9*r + 0.1*batch
    np.testing.assert_allclose(
        tensor.to_numpy(rmean), 0.1 * x_np.mean((0, 2, 3)), rtol=1e-4, atol=1e-5)


def test_batchnorm_eval_uses_running_stats(dev):
    rng = np.random.RandomState(5)
    x_np = rng.randn(2, 3, 4, 4).astype(np.float32)

    from singa_tpu.ops import batchnorm as bn_ops

    autograd.set_training(False)
    x = tensor.from_numpy(x_np, dev)
    s = tensor.from_numpy(np.ones(3, np.float32), dev)
    b = tensor.from_numpy(np.zeros(3, np.float32), dev)
    rmean = tensor.from_numpy(np.full(3, 0.5, np.float32), dev)
    rvar = tensor.from_numpy(np.full(3, 2.0, np.float32), dev)
    y = bn_ops.batchnorm2d(x, s, b, rmean, rvar)
    expect = (x_np - 0.5) / np.sqrt(2.0 + 1e-5)
    np.testing.assert_allclose(tensor.to_numpy(y), expect, rtol=1e-4, atol=1e-5)


def test_conv_same_padding(dev):
    rng = np.random.RandomState(6)
    x_np = rng.randn(1, 2, 7, 7).astype(np.float32)
    w_np = rng.randn(3, 2, 3, 3).astype(np.float32)

    from singa_tpu.ops import conv as conv_ops

    x, w = _param(x_np, dev), _param(w_np, dev)
    y = conv_ops.conv2d(x, w, None, stride=(1, 1), pad_mode="SAME_UPPER")
    assert y.shape == (1, 3, 7, 7)
