"""Pipeline-parallel serving (serve/pp.py + the engine's ``pp=``
mode): token-stream parity against the single-device paged engine on
the virtual CPU mesh (cold / warm / int8 / preempt-resume /
chunked-prefill budget, greedy AND seeded sampling mixed in one pool,
microbatch widths against the compacted dispatch buckets), supervisor
restart under an injected ``serve.pp_boundary`` fault, typed config
validation (fired BEFORE any registration — the leaked-gauge audit),
and the metrics/health/unregister surface.

The single-device paged engine is the oracle (itself parity-pinned
against the slot engine and offline ``generate`` in
tests/test_paged.py), so PP parity here is transitively
offline-oracle parity.  The pipeline reorders NO arithmetic — layers
run in the same order with the same per-layer block-native kernels,
and the stage-boundary ``ppermute`` moves bytes, not partial sums —
so the parity pin is strictly tighter than TP's psum caveat; every
workload below is seed-pinned deterministic."""

import numpy as np
import pytest

from singa_tpu import tensor
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from singa_tpu.observe import health_report
from singa_tpu.observe.registry import registry
from singa_tpu.resilience import FailAfterN, faults
from singa_tpu.serve import (EngineFailedError, EngineSupervisor,
                             GenerationRequest, PagedConfig, PPConfig,
                             PrefixCacheConfig, ServeFleet)


def _build(cfg):
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    return m


@pytest.fixture(scope="module")
def model():
    return _build(GPT2Config.tiny(dropout=0.0))


_PCFG = PagedConfig(block_size=8, num_blocks=32)


def _workload(seed, n, p_lo=3, p_hi=14, n_lo=2, n_hi=9, sampled=True):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        out.append(dict(
            prompt=rng.randint(0, 256, rng.randint(p_lo, p_hi))
            .astype(np.int32),
            n_new=int(rng.randint(n_lo, n_hi)),
            temperature=(float(rng.choice([0.0, 0.9]))
                         if sampled else 0.0),
            seed=int(rng.randint(0, 1000))))
    return out


def _run(m, work, max_slots=4, max_steps=4000, **kw):
    kw.setdefault("paged", _PCFG)
    eng = m.serve(max_slots=max_slots, **kw)
    hs = [eng.submit(GenerationRequest(
        w["prompt"], max_new_tokens=w["n_new"],
        temperature=w["temperature"], seed=w["seed"]))
        for w in work]
    eng.run_until_complete(max_steps=max_steps)
    outs = [h.result().tokens for h in hs]
    snap = eng.stats.snapshot()
    eng.close()
    return outs, snap


def _parity(a, b):
    return all(np.array_equal(x, y) for x, y in zip(a, b))


def test_cold_parity_pp2(model):
    """2 stages x 2 microbatches: streams token-identical to the
    single-device paged engine, the stats snapshot carries the pp
    section, no blocks leak."""
    work = _workload(0, 7, sampled=True)
    base, _ = _run(model, work)
    outs, snap = _run(model, work, pp=PPConfig(stages=2,
                                               microbatches=2))
    assert _parity(outs, base)
    pp = snap["pp"]
    assert pp["stages"] == 2
    assert pp["layers_per_stage"] == model.cfg.n_layer // 2
    assert pp["microbatches"] == 2
    assert pp["sharded_dispatches"] > 0
    assert pp["kv_bytes_per_stage"] > 0
    assert pp["boundary_hops"] > 0
    assert snap["paged"]["blocks_used"] == 0


@pytest.mark.slow  # variant: test_cold_parity_pp2 is the fast rep
def test_deep_model_stage_per_layer():
    """The scenario the subsystem exists for — a model DEEPER than one
    device: 4 layers across 4 stages, one layer per device, parity
    preserved."""
    m = _build(GPT2Config.tiny(dropout=0.0, n_layer=4))
    work = _workload(1, 5, sampled=True)
    base, _ = _run(m, work)
    outs, snap = _run(m, work, pp=4)
    assert _parity(outs, base)
    assert snap["pp"]["stages"] == 4
    assert snap["pp"]["layers_per_stage"] == 1


@pytest.mark.slow
def test_microbatch_widths_and_compaction(model):
    """The GPipe microbatch count clamps (gcd) to the compacted
    dispatch width: a pool whose live width collapses below the
    microbatch count still decodes correctly (slots drain raggedly,
    buckets halve), and an odd microbatch request works."""
    work = _workload(2, 6, n_lo=2, n_hi=14, sampled=True)
    base, _ = _run(model, work, max_slots=8)
    outs, _ = _run(model, work, max_slots=8,
                   pp=PPConfig(stages=2, microbatches=4))
    assert _parity(outs, base)
    outs3, _ = _run(model, work, max_slots=8,
                    pp=PPConfig(stages=2, microbatches=3))
    assert _parity(outs3, base)


def test_gqa_parity_pp2():
    """GQA models: the narrow H_kv cache slices per stage on the
    LAYER axis (the head axis stays whole per stage)."""
    m = _build(GPT2Config.tiny(dropout=0.0, n_kv_head=2))
    work = _workload(3, 5, n_lo=6, n_hi=14, p_lo=4, p_hi=16)
    base, _ = _run(m, work, max_slots=3)
    outs, _ = _run(m, work, max_slots=3, pp=2)
    assert _parity(outs, base)


@pytest.mark.slow
def test_int8_parity_pp2(model):
    """int8 pools under PP: the (values, scales) leaves both slice on
    the layer axis; token parity vs the single-device int8 paged
    engine."""
    work = _workload(4, 5, sampled=True)
    base, _ = _run(model, work, cache_dtype="int8")
    eng = model.serve(max_slots=4, paged=_PCFG, cache_dtype="int8",
                      pp=2)
    try:
        vals, scales = eng.paged_arena.pool_k
        L = model.cfg.n_layer
        assert vals.shape[0] == L and scales.shape[0] == L
        assert vals.addressable_shards[0].data.shape[0] == L // 2
        assert scales.addressable_shards[0].data.shape[0] == L // 2
        hs = [eng.submit(GenerationRequest(
            w["prompt"], max_new_tokens=w["n_new"],
            temperature=w["temperature"], seed=w["seed"]))
            for w in work]
        eng.run_until_complete(max_steps=4000)
        outs = [h.result().tokens for h in hs]
    finally:
        eng.close(force=True)
    assert _parity(outs, base)


@pytest.mark.slow
def test_warm_prefix_parity_pp2(model):
    """Prefix cache on a PP engine: warm chunks flow stage-to-stage
    through the chunk twin against layer-sharded cache rows; streams
    stay byte-identical to the single-device engine."""
    rng = np.random.RandomState(6)
    system = rng.randint(0, 256, 40).astype(np.int32)
    work = [dict(prompt=np.concatenate(
        [system, rng.randint(0, 256, rng.randint(3, 8))
         .astype(np.int32)]),
        n_new=6, temperature=0.0, seed=int(rng.randint(0, 1000)))
        for _ in range(5)]
    cache = PrefixCacheConfig(block_size=8)
    base, _ = _run(model, work, max_slots=2, prefix_cache=cache,
                   paged=PagedConfig(block_size=8, num_blocks=64))
    outs, snap = _run(model, work, max_slots=2, prefix_cache=cache,
                      paged=PagedConfig(block_size=8, num_blocks=64),
                      pp=2)
    assert _parity(outs, base)
    assert snap["prefix"]["hits"] > 0, "workload never went warm"


@pytest.mark.slow
def test_preempt_resume_parity_pp2(model):
    """Preemption/swap against stage-sliced pools: the pool<->row
    copy twins run with layer-axis specs and the host image
    reassembles the full layer axis, so resumed PP streams equal the
    uninterrupted single-device run's and no block leaks."""
    work = _workload(5, 6, n_lo=12, n_hi=30, p_lo=4, p_hi=20,
                     sampled=True)
    small = PagedConfig(block_size=8, num_blocks=10)
    base, _ = _run(model, work, paged=small)
    outs, snap = _run(model, work, paged=small, pp=2)
    assert _parity(outs, base)
    pg = snap["paged"]
    assert pg["preemptions"] > 0 and pg["swap_in"] > 0
    assert pg["blocks_used"] == 0, "leaked blocks after drain"


def test_budget_parity_pp2(model):
    """The chunked-prefill token budget composes: a long admission
    splits across steps in chunk twins that flow the pipeline, and
    budgeted streams stay byte-identical to unbudgeted PP streams."""
    work = _workload(6, 4, p_lo=20, p_hi=40, n_lo=3, n_hi=7,
                     sampled=True)
    base, _ = _run(model, work,
                   paged=PagedConfig(block_size=8, num_blocks=48),
                   pp=2)
    outs, snap = _run(model, work,
                      paged=PagedConfig(block_size=8, num_blocks=48,
                                        prefill_token_budget=16),
                      pp=2)
    assert _parity(outs, base)


def test_stage_boundary_fault_supervisor_restart(model):
    """An injected ``serve.pp_boundary`` fault fails the pipelined
    engine TYPED; the supervisor rebuilds (same stage group,
    twin-cache hit) and requeued never-started streams keep parity.
    Zero wedged handles."""
    work = _workload(7, 6, n_lo=4, n_hi=10, sampled=True)
    base, _ = _run(model, work, max_slots=2)
    restarts0 = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0)
    sup = EngineSupervisor(model, max_slots=2, restart_budget=2,
                           pp=2, paged=_PCFG)
    hs = [sup.submit(GenerationRequest(
        w["prompt"], max_new_tokens=w["n_new"],
        temperature=w["temperature"], seed=w["seed"]))
        for w in work]
    pol = faults.inject("serve.pp_boundary", FailAfterN(3, times=1))
    try:
        sup.run_until_complete(max_steps=4000)
    finally:
        faults.clear()
    assert pol.fired == 1
    restarts = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0) - restarts0
    assert restarts == 1
    completed = typed = 0
    for i, h in enumerate(hs):
        assert h.done(), "wedged handle after PP restart"
        try:
            got = h.result().tokens
            assert np.array_equal(got, base[i])
            completed += 1
        except EngineFailedError as e:
            assert e.started is True
            typed += 1
    assert completed + typed == len(work)
    assert completed > 0
    sup.close()


def test_fleet_of_pp_replicas(model):
    """serve_fleet(pp=2, replicas=2) partitions the mesh into
    disjoint stage-wide groups; streams keep parity and both
    replicas carry traffic."""
    work = _workload(8, 8, sampled=True)
    base, _ = _run(model, work)
    fleet = ServeFleet(model, replicas=2, max_slots=2, pp=2,
                       paged=_PCFG)
    try:
        d0 = fleet.supervisor(0).engine.pp_exec.mesh.devices.flat
        d1 = fleet.supervisor(1).engine.pp_exec.mesh.devices.flat
        assert {d.id for d in d0}.isdisjoint({d.id for d in d1})
        hs = [fleet.submit(GenerationRequest(
            w["prompt"], max_new_tokens=w["n_new"],
            temperature=w["temperature"], seed=w["seed"]))
            for w in work]
        fleet.run_until_complete(max_steps=4000)
        outs = [h.result().tokens for h in hs]
        snap = fleet.snapshot()
    finally:
        fleet.close()
    assert _parity(outs, base)
    assert all(v > 0 for v in snap["routed"].values())


def test_config_validation(model):
    """Every incompatible pp configuration is a typed construction
    error fired BEFORE any registration (no serve.pp gauge may leak
    from a refused construction)."""

    def pp_gauges():
        return {k for k in registry().snapshot()["gauges"]
                if k.startswith("serve.pp.")}

    before = pp_gauges()
    # pp without paged: the memory model IS the stage-sliced pool
    with pytest.raises(ValueError, match="requires paged="):
        model.serve(max_slots=2, pp=2)
    # pp with the gather oracle kernel
    with pytest.raises(ValueError, match="kernel='block'"):
        model.serve(max_slots=2, pp=2,
                    paged=PagedConfig(block_size=8, kernel="gather"))
    # stages not dividing n_layer
    m3 = _build(GPT2Config.tiny(dropout=0.0, n_layer=3))
    with pytest.raises(ValueError, match="does not divide n_layer"):
        m3.serve(max_slots=2, pp=2, paged=_PCFG)
    # speculative draft: the proposal scan would serialize the
    # pipeline, and a mismatched-depth draft cannot take the split
    d = _build(GPT2Config.tiny(dropout=0.0, n_layer=1))
    with pytest.raises(ValueError, match="mismatched depth"):
        model.serve(max_slots=2, pp=2, paged=_PCFG, draft_model=d,
                    spec_k=3)
    # sliding-window models
    mw = _build(GPT2Config.tiny(dropout=0.0, attn_window=16))
    with pytest.raises(NotImplementedError, match="sliding-window"):
        mw.serve(max_slots=2, pp=2,
                 paged=PagedConfig(block_size=8, num_blocks=32))
    # MoE models take ep=, not pp=
    mm = _build(GPT2Config.tiny(dropout=0.0, moe_every=2,
                                moe_experts=4))
    with pytest.raises(ValueError, match=r"ep=EPConfig"):
        mm.serve(max_slots=2, pp=2, paged=_PCFG)
    # pp together with tp
    with pytest.raises(ValueError, match="one sharded executor"):
        model.serve(max_slots=2, pp=2, tp=2, paged=_PCFG)
    # stages wider than the mesh (8-device conftest topology)
    with pytest.raises(ValueError, match="devices"):
        model.serve(max_slots=2, pp=16, paged=_PCFG)
    # stages x replicas exceeding the mesh
    with pytest.raises(ValueError, match="exceeds"):
        ServeFleet(model, replicas=5, max_slots=2, pp=2, paged=_PCFG)
    # bad knob type
    with pytest.raises(ValueError, match="PPConfig"):
        model.serve(max_slots=2, pp="deep", paged=_PCFG)
    assert pp_gauges() == before, \
        "a refused construction leaked serve.pp gauges"
    # pp=1 is simply off (and then needs no paged=)
    eng = model.serve(max_slots=2, pp=1)
    assert eng.pp_exec is None
    eng.close()
    # explicit PPConfig passes through
    eng = model.serve(max_slots=2, pp=PPConfig(stages=2), paged=_PCFG)
    assert eng.pp_exec is not None and eng.pp_exec.stages == 2
    eng.close()


def test_metrics_and_health_unregister(model):
    """serve.pp.* metrics register per engine, surface in
    health_report()["serve"]["pp"], and unregister at close; the
    health section stays present (zeroed) with no live PP engine."""
    eng = model.serve(max_slots=2, pp=2, paged=_PCFG)
    lbl = eng.stats.engine_label
    try:
        h = eng.submit(GenerationRequest(
            np.arange(5, dtype=np.int32), max_new_tokens=3))
        eng.run_until_complete(max_steps=200)
        h.result()
        rep = health_report(include_registry=False)
        pp = rep["serve"]["pp"]
        assert pp["stages"] == 2
        assert pp["kv_bytes_per_stage"] > 0
        assert pp["sharded_dispatches"] > 0
        assert pp["boundary_hops"] > 0
    finally:
        eng.close()
    snap = registry().snapshot()["gauges"]
    assert f"serve.pp.stages{{engine={lbl}}}" not in snap, \
        "pp gauges leaked past close()"
    rep = health_report(include_registry=False)
    assert "pp" in rep["serve"]
