"""Model API + graph-vs-eager consistency (reference: test/python/test_model.py,
unverified; the jit≡eager test is SURVEY.md §4's 'implication for TPU build')."""

import numpy as np
import pytest

from singa_tpu import layer, model, opt, tensor
from singa_tpu import device as device_module
from singa_tpu.models.mlp import MLP
from singa_tpu.tensor import Tensor


@pytest.fixture
def dev():
    d = device_module.create_tpu_device(0)
    d.SetRandSeed(0)
    return d


def _data(dev, n=32, d_in=10, classes=10, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d_in).astype(np.float32)
    y = rng.randint(0, classes, size=(n,)).astype(np.int32)
    return tensor.from_numpy(x, dev), tensor.from_numpy(y, dev)


def _make(dev, use_graph, seed=0):
    dev.SetRandSeed(seed)
    m = MLP(data_size=10, perceptron_size=16, num_classes=10)
    m.set_optimizer(opt.SGD(lr=0.05, momentum=0.9))
    x, _ = _data(dev)
    m.compile([x], is_train=True, use_graph=use_graph, sequential=False)
    return m


def test_mlp_eager_loss_decreases(dev):
    m = _make(dev, use_graph=False)
    x, y = _data(dev)
    losses = []
    for _ in range(20):
        _, loss = m(x, y)
        losses.append(float(loss.data))
    assert losses[-1] < losses[0] * 0.7, losses


def test_mlp_graph_loss_decreases(dev):
    m = _make(dev, use_graph=True)
    x, y = _data(dev)
    losses = []
    for _ in range(20):
        _, loss = m(x, y)
        losses.append(float(loss.data))
    assert losses[-1] < losses[0] * 0.7, losses


def test_graph_equals_eager(dev):
    """use_graph=True must be numerically ≡ use_graph=False."""
    m1 = _make(dev, use_graph=False, seed=7)
    m2 = _make(dev, use_graph=True, seed=7)
    # identical initial params
    p1 = {k: tensor.to_numpy(v) for k, v in m1.get_params().items()}
    m2.set_params({k: tensor.from_numpy(v) for k, v in p1.items()})
    x, y = _data(dev, seed=3)
    for i in range(6):
        _, l1 = m1(x, y)
        _, l2 = m2(x, y)
        np.testing.assert_allclose(
            float(l1.data), float(l2.data), rtol=2e-4,
            err_msg=f"diverged at step {i}")
    for k in p1:
        np.testing.assert_allclose(
            tensor.to_numpy(m1.get_params()[k]),
            tensor.to_numpy(m2.get_params()[k]), rtol=2e-3, atol=2e-5)


def test_graph_recompiles_on_new_batch_size(dev):
    m = _make(dev, use_graph=True)
    x, y = _data(dev, n=32)
    m(x, y)
    m(x, y)
    x2, y2 = _data(dev, n=16)
    _, loss = m(x2, y2)  # different shape key -> new compile, not crash
    assert np.isfinite(float(loss.data))


def test_eval_mode_forward(dev):
    m = _make(dev, use_graph=False)
    x, y = _data(dev)
    m.eval()
    out = m(x)
    assert out.shape == (32, 10)
    m.train()


def test_save_load_states_roundtrip(tmp_path, dev):
    m = _make(dev, use_graph=False)
    x, y = _data(dev)
    for _ in range(3):
        m(x, y)
    fpath = str(tmp_path / "ckpt.zip")
    m.save_states(fpath, aux_states={"epoch": np.int64(3)})
    params_before = {k: tensor.to_numpy(v) for k, v in m.get_params().items()}

    m2 = _make(dev, use_graph=False, seed=99)
    aux = m2.load_states(fpath)
    assert int(aux["epoch"]) == 3
    for k, v in m2.get_params().items():
        np.testing.assert_array_equal(tensor.to_numpy(v), params_before[k])
    # optimizer momentum restored too
    assert float(m2.optimizer.step_counter.data) == float(m.optimizer.step_counter.data)
    # training continues from the checkpoint without error
    _, loss = m2(x, y)
    assert np.isfinite(float(loss.data))


def test_optimizer_swap_after_compile_recompiles(dev):
    """Swapping the optimizer after graph compile must clear the cached
    executable (lr is a trace-time constant): a stale replay would keep
    applying the OLD lr."""
    m = _make(dev, use_graph=True)
    x, y = _data(dev)
    m(x, y)
    m(x, y)  # compiled, lr=0.05 baked in
    m.set_optimizer(opt.SGD(lr=0.0))  # freeze: zero lr
    before = {k: tensor.to_numpy(v).copy()
              for k, v in m.get_params().items()}
    m(x, y)
    for k, v in m.get_params().items():
        np.testing.assert_array_equal(
            tensor.to_numpy(v), before[k],
            err_msg=f"{k} changed under lr=0 — stale executable replay")


def test_param_naming_hierarchical(dev):
    m = _make(dev, use_graph=False)
    names = set(m.get_params().keys())
    assert any("linear1" in n and n.endswith(".W") for n in names), names
    assert any("linear2" in n and n.endswith(".b") for n in names), names


def test_layer_get_set_params(dev):
    lin = layer.Linear(4)
    x = tensor.from_numpy(np.ones((2, 3), np.float32), dev)
    lin(x)
    params = lin.get_params()
    assert len(params) == 2
    newp = {k: tensor.from_numpy(np.zeros_like(tensor.to_numpy(v)))
            for k, v in params.items()}
    lin.set_params(newp)
    y = lin(x)
    np.testing.assert_array_equal(tensor.to_numpy(y), np.zeros((2, 4), np.float32))


@pytest.mark.parametrize("use_graph", [False, True])
def test_async_save_states_consistent_under_training(tmp_path, dev,
                                                     use_graph):
    """async_save snapshots device copies at call time: steps taken
    while the background write is in flight must not leak into the
    checkpoint, and the file must equal a synchronous save made at the
    same point.  The graph-mode case is the sharp one — the compiled
    step DONATES state buffers, so capturing raw .data references
    instead of copies crashes the background write."""
    m = _make(dev, use_graph=use_graph)
    x, y = _data(dev)
    for _ in range(2):
        m(x, y)
    sync_path = str(tmp_path / "sync.zip")
    async_path = str(tmp_path / "async.zip")
    m.save_states(sync_path)
    handle = m.save_states(async_path, async_save=True)
    for _ in range(3):  # mutate/donate state while the write is in flight
        m(x, y)
    handle.wait()
    assert handle.done()

    m_sync = _make(dev, use_graph=False, seed=7)
    m_sync.load_states(sync_path)
    m_async = _make(dev, use_graph=False, seed=8)
    m_async.load_states(async_path)
    for k, v in m_async.get_params().items():
        np.testing.assert_array_equal(
            tensor.to_numpy(v), tensor.to_numpy(m_sync.get_params()[k]))


# -- multi-step dispatch (train_n_batches: K steps in ONE executable) ------

def test_train_n_batches_equals_k_single_steps(dev):
    """lax.scan over the step ≡ K separate graph-mode dispatches: same
    params, same per-step losses (round-5 verdict item #1)."""
    k = 4
    m1 = _make(dev, use_graph=True, seed=11)
    m2 = _make(dev, use_graph=True, seed=11)
    rng = np.random.RandomState(3)
    xs = rng.randn(k, 32, 10).astype(np.float32)
    ys = rng.randint(0, 10, size=(k, 32)).astype(np.int32)

    single_losses = []
    for i in range(k):
        _, loss = m1(tensor.from_numpy(xs[i], dev),
                     tensor.from_numpy(ys[i], dev))
        single_losses.append(float(loss.data))

    _, losses = m2.train_n_batches(tensor.from_numpy(xs, dev),
                                   tensor.from_numpy(ys, dev))
    multi_losses = np.asarray(losses.data)
    assert multi_losses.shape == (k,)
    np.testing.assert_allclose(multi_losses, single_losses, rtol=2e-5)
    for (n1, p1), (n2, p2) in zip(sorted(m1.get_params().items()),
                                  sorted(m2.get_params().items())):
        assert n1 == n2
        np.testing.assert_allclose(tensor.to_numpy(p1),
                                   tensor.to_numpy(p2), rtol=2e-5,
                                   atol=1e-6)


def test_train_n_batches_output_stacking(dev):
    """Every output leaf gains a leading K axis (logits included)."""
    m = _make(dev, use_graph=True)
    rng = np.random.RandomState(0)
    xs = tensor.from_numpy(rng.randn(3, 32, 10).astype(np.float32), dev)
    ys = tensor.from_numpy(
        rng.randint(0, 10, size=(3, 32)).astype(np.int32), dev)
    out, losses = m.train_n_batches(xs, ys)
    assert tuple(out.shape) == (3, 32, 10)
    assert tuple(losses.shape) == (3,)


def test_train_n_batches_requires_graph_mode(dev):
    m = _make(dev, use_graph=False)
    rng = np.random.RandomState(0)
    xs = tensor.from_numpy(rng.randn(2, 32, 10).astype(np.float32), dev)
    ys = tensor.from_numpy(
        rng.randint(0, 10, size=(2, 32)).astype(np.int32), dev)
    with pytest.raises(ValueError, match="use_graph"):
        m.train_n_batches(xs, ys)


def test_train_n_batches_mismatched_lead_dim(dev):
    m = _make(dev, use_graph=True)
    rng = np.random.RandomState(0)
    xs = tensor.from_numpy(rng.randn(2, 32, 10).astype(np.float32), dev)
    ys = tensor.from_numpy(
        rng.randint(0, 10, size=(3, 32)).astype(np.int32), dev)
    with pytest.raises(ValueError, match="leading steps dim"):
        m.train_n_batches(xs, ys)


def test_train_n_batches_repeat_mode(dev):
    """repeat mode (n_steps=K, per-step-shaped inputs) ≡ K single graph
    steps on the same batch."""
    k = 4
    m1 = _make(dev, use_graph=True, seed=13)
    m2 = _make(dev, use_graph=True, seed=13)
    x, y = _data(dev, seed=2)
    singles = []
    for _ in range(k):
        _, loss = m1(x, y)
        singles.append(float(loss.data))
    _, losses = m2.train_n_batches(x, y, n_steps=k)
    np.testing.assert_allclose(np.asarray(losses.data), singles,
                               rtol=2e-5)
    for (n1, p1), (n2, p2) in zip(sorted(m1.get_params().items()),
                                  sorted(m2.get_params().items())):
        np.testing.assert_allclose(tensor.to_numpy(p1),
                                   tensor.to_numpy(p2), rtol=2e-5,
                                   atol=1e-6, err_msg=n1)
