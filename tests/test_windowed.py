"""Windowed telemetry (observe.timeseries + registry.windowed) and
multi-window SLO burn-rate alerting (observe.slo).

Everything here is THREADLESS and fake-clocked: ring arithmetic,
fire/clear hysteresis, and the export/health surfaces are all
deterministic functions of (samples, clock)."""

import json
import math

import pytest

from singa_tpu.observe import health_report
from singa_tpu.observe.export import prometheus_text
from singa_tpu.observe.registry import MetricsRegistry
from singa_tpu.observe.slo import BurnRule, SLOPolicy, alerts_section
from singa_tpu.observe.timeseries import WindowRing
from singa_tpu.utils.metrics import LatencySeries


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


# ---------------------------------------------------------------------------
# WindowRing arithmetic
# ---------------------------------------------------------------------------

def test_counter_ring_rate_basic_and_empty_window():
    clk = FakeClock()
    r = WindowRing("counter", clock=clk, baseline=0.0)
    # empty window: no samples, no growth — 0.0, never nan/raise
    assert r.rate(60.0) == 0.0
    v = 0
    for _ in range(6):
        clk.advance(10.0)
        v += 2
        r.append(v)
    # all 6 samples in the last 60s against a baseline of 0
    assert r.rate(60.0) == pytest.approx(12 / 60.0)
    # a 30s window sees only the growth of the last 30s (2 samples
    # strictly inside + the boundary one): baseline = value at the
    # last sample at/before the cutoff
    assert r.rate(30.0) == pytest.approx((12 - 6) / 30.0)
    # idle counter: window slides past every sample -> rate decays to 0
    clk.advance(120.0)
    assert r.rate(60.0) == 0.0


def test_counter_ring_single_sample_and_attach_baseline():
    clk = FakeClock()
    # attached to a counter already at 100: history is NOT credited
    r = WindowRing("counter", clock=clk, baseline=100.0)
    clk.advance(5.0)
    r.append(103)
    assert r.rate(60.0) == pytest.approx(3 / 60.0)


def test_counter_ring_wraparound_keeps_floor_baseline():
    clk = FakeClock()
    r = WindowRing("counter", capacity=4, clock=clk, baseline=0.0)
    for v in (1, 2, 3, 4, 5, 6):  # evicts samples 1, 2
        clk.advance(1.0)
        r.append(v)
    assert len(r) == 4
    # window covering everything: baseline is the FLOOR (last evicted
    # value), not zero — growth since the oldest retained knowledge
    assert r.rate(100.0) == pytest.approx((6 - 2) / 100.0)


def test_ring_clock_going_backwards_is_safe():
    clk = FakeClock()
    r = WindowRing("event", clock=clk)
    r.append(1.0)
    clk.advance(-50.0)  # clock steps BACK
    r.append(2.0)
    # reads never raise; the future-stamped sample counts in-window
    vals = r.values(10.0)
    assert 2.0 in vals
    assert r.rate(10.0) >= 0.0
    r2 = WindowRing("counter", clock=clk, baseline=0.0)
    r2.append(5)
    clk.advance(-50.0)
    r2.append(3)  # counter "reset" under a backwards clock
    assert r2.rate(10.0) >= 0.0  # clamped, never negative


def test_event_ring_quantile_and_mean():
    clk = FakeClock()
    r = WindowRing("event", clock=clk)
    assert math.isnan(r.quantile(0.5, 60.0))  # empty -> nan
    r.append(0.3)
    assert r.quantile(0.99, 60.0) == 0.3  # single sample
    for v in (0.1, 0.2, 0.4):
        clk.advance(1.0)
        r.append(v)
    assert r.quantile(0.5, 60.0) == 0.2
    assert r.mean(60.0) == pytest.approx(0.25)
    assert r.rate(60.0) == pytest.approx(4 / 60.0)
    clk.advance(60.0)  # ages out all but the last sample
    assert r.quantile(0.5, 60.0) == 0.4
    with pytest.raises(ValueError):
        r.quantile(1.5, 60.0)
    with pytest.raises(ValueError):
        r.rate(0.0)


# ---------------------------------------------------------------------------
# registry.windowed plumbing
# ---------------------------------------------------------------------------

def test_registry_windowed_counter_attaches_current_and_future():
    reg = MetricsRegistry()
    clk = FakeClock()
    c0 = reg.counter("x.total", engine="0")
    wf = reg.windowed("x.total", windows=(60,), clock=clk)
    c1 = reg.counter("x.total", engine="1")  # created AFTER windowing
    clk.advance(10.0)
    c0.inc(6)
    c1.inc(12)
    assert wf.rate(60) == pytest.approx(18 / 60.0)
    # label filter
    assert wf.rate(60, match={"engine": "1"}) == pytest.approx(
        12 / 60.0)
    # get-or-create: same family back
    assert reg.windowed("x.total") is wf


def test_registry_windowed_histogram_sees_direct_series_records():
    """EngineStats records into the adopted LatencySeries directly,
    bypassing Histogram.observe — the ring must still see it."""
    reg = MetricsRegistry()
    clk = FakeClock()
    h = reg.histogram("lat.s", engine="0")
    wf = reg.windowed("lat.s", windows=(60,), clock=clk)
    h.series.record(0.5)  # the EngineStats idiom
    h.observe(0.1)
    assert sorted(wf.values(60)) == [0.1, 0.5]
    assert wf.quantile(0.99, 60) == 0.5


def test_registry_remove_detaches_windowed_ring():
    """A retired engine's windowed series must disappear with its
    all-time series, not freeze at its last value."""
    reg = MetricsRegistry()
    clk = FakeClock()
    wf = reg.windowed("x.total", windows=(60,), clock=clk)
    c0 = reg.counter("x.total", engine="0")
    c1 = reg.counter("x.total", engine="1")
    c0.inc(5)
    c1.inc(7)
    assert len(wf.rings) == 2
    reg.remove(c1)
    assert len(wf.rings) == 1
    assert wf.rate(60) == pytest.approx(5 / 60.0)
    # further writes to the removed metric no longer reach a ring
    assert c1._rings == ()


def test_registry_remove_detaches_histogram_series_hook():
    """The histogram path detaches by the EXACT hook object (a fresh
    ``ring.append`` bound method would never match): after removal
    the series stops feeding the ring and drops the hook."""
    reg = MetricsRegistry()
    clk = FakeClock()
    h = reg.histogram("lat.s", engine="0")
    wf = reg.windowed("lat.s", windows=(60,), clock=clk)
    h.series.record(0.5)
    assert wf.values(60) == [0.5]
    hooks_with_ring = len(h.series._hooks)
    reg.remove(h)
    assert len(h.series._hooks) == hooks_with_ring - 1
    assert wf.rings == {} and wf._series_hooks == {}
    h.series.record(0.7)  # no ring left to receive it
    assert wf.values(60) == []


def test_registry_windowed_gauge_mean_and_section():
    reg = MetricsRegistry()
    clk = FakeClock()
    g = reg.gauge("depth", engine="0")
    wf = reg.windowed("depth", windows=(60,), clock=clk)
    g.set(4)
    g.inc(2)
    assert wf.kind == "gauge"
    assert wf.mean(60) == pytest.approx(5.0)
    sec = wf.section()
    assert sec["windows"]["60"]["mean"] == pytest.approx(5.0)
    reg.unwindow("depth")
    assert reg.windowed_families() == {}
    assert g._rings == ()


# ---------------------------------------------------------------------------
# bounded LatencySeries (satellite: flat RSS over multi-hour soaks)
# ---------------------------------------------------------------------------

def test_latency_series_ring_bounds_samples_keeps_totals_exact():
    s = LatencySeries(max_samples=4)
    for i in range(10):
        s.record(float(i))
    assert len(s.values) == 4            # ring: newest 4 retained
    assert s.count == 10                 # exact all-time count
    assert s.total_sum == pytest.approx(45.0)  # exact all-time sum
    # percentiles describe the retained window (documented
    # approximation) — still real observed values
    assert s.percentile(50) in (6.0, 7.0, 8.0, 9.0)
    with pytest.raises(ValueError):
        LatencySeries(max_samples=0)


def test_histogram_buckets_stay_exact_after_series_wrap():
    """Record-time binning: cumulative bucket counts cover EVERY
    recorded value even after the retained ring evicted most of
    them, and le=+Inf always equals _count."""
    reg = MetricsRegistry()
    h = reg.histogram("lat.s", buckets=(0.1, 1.0),
                      series=LatencySeries(max_samples=3))
    for _ in range(50):
        h.observe(0.05)   # below 0.1
    for _ in range(5):
        h.observe(0.5)    # in (0.1, 1.0]
    counts = dict(h.bucket_counts())
    assert counts[0.1] == 50
    assert counts[1.0] == 55
    assert counts[float("inf")] == h.count == 55
    assert len(h.series.values) == 3


# ---------------------------------------------------------------------------
# SLO burn-rate policy
# ---------------------------------------------------------------------------

def _policy(reg, clk, threshold=3.0, clear_ratio=0.5,
            budget=0.1, **kw):
    return SLOPolicy(
        None, budget_frac=budget, kinds=("ttft",),
        rules=(BurnRule("page", long_s=10.0, short_s=3.0,
                        threshold=threshold,
                        clear_ratio=clear_ratio),),
        reg=reg, clock=clk, install=False, **kw)


def test_burn_requires_both_windows(monkeypatch):
    """A short blip exceeds the SHORT window's burn but not the long
    one — no page (the multi-window point)."""
    reg = MetricsRegistry()
    clk = FakeClock()
    pol = _policy(reg, clk)
    viol = reg.counter("serve.slo_violations", engine="0", kind="ttft")
    done = reg.counter("serve.completed", engine="0")
    # 8s of clean traffic, then 2s of pure violations: short window
    # (3s) burns hot, long window (10s) stays below threshold
    for _ in range(16):
        clk.advance(0.5)
        done.inc()
    for _ in range(4):
        clk.advance(0.5)
        done.inc()
        viol.inc()
    pol.poll()
    st = pol.alerts["page"]
    assert st["burn_short"] >= 3.0
    assert st["burn_long"] < 3.0
    assert not pol.firing()


def test_burn_fires_and_clears_hysteretically_with_callback():
    reg = MetricsRegistry()
    clk = FakeClock()
    transitions = []
    pol = _policy(reg, clk,
                  on_alert=lambda name, firing, info:
                  transitions.append((name, firing)))
    viol = reg.counter("serve.slo_violations", engine="0", kind="ttft")
    done = reg.counter("serve.completed", engine="0")
    # sustained 100% violation ratio across BOTH windows -> fire
    for _ in range(24):
        clk.advance(0.5)
        done.inc()
        viol.inc()
        pol.poll()
    assert pol.firing("page")
    assert pol.alerts["page"]["fired"] == 1
    assert transitions == [("page", True)]
    g = reg.gauge("serve.slo.alert_firing", rule="page")
    assert g.value == 1
    # hovering JUST below threshold but above the clear line: the
    # alert holds (hysteresis) — 25% violations at budget 0.1 is
    # burn 2.5, between clear (1.5) and threshold (3.0)
    for i in range(40):
        clk.advance(0.5)
        done.inc()
        if i % 4 == 0:
            viol.inc()
        pol.poll()
    assert pol.firing("page"), pol.alerts["page"]
    # clean traffic: both windows fall below threshold*clear_ratio
    for _ in range(30):
        clk.advance(0.5)
        done.inc()
        pol.poll()
    assert not pol.firing("page")
    assert pol.alerts["page"]["cleared"] == 1
    assert transitions == [("page", True), ("page", False)]
    assert reg.counter("serve.slo.alerts_cleared", rule="page").value \
        == 1


def test_burn_zero_traffic_and_violations_without_completions():
    reg = MetricsRegistry()
    clk = FakeClock()
    pol = _policy(reg, clk)
    assert pol.burn_rate(3.0) == 0.0  # silence is not a burn
    viol = reg.counter("serve.slo_violations", engine="0", kind="ttft")
    clk.advance(1.0)
    viol.inc()
    assert pol.burn_rate(3.0) == float("inf")  # burning, not idle
    # the queue kind is excluded by default (different denominator)
    q = reg.counter("serve.slo_violations", engine="0", kind="queue")
    q.inc(100)
    done = reg.counter("serve.completed", engine="0")
    for _ in range(10):
        clk.advance(0.2)
        done.inc()
    assert pol.burn_rate(3.0) < float("inf")


def test_policy_validates_config():
    reg = MetricsRegistry()
    with pytest.raises(ValueError):
        SLOPolicy(None, budget_frac=0.0, reg=reg, install=False)
    with pytest.raises(ValueError):
        SLOPolicy(None, rules=(), reg=reg, install=False)
    with pytest.raises(ValueError):
        SLOPolicy(None, rules=(
            BurnRule("x", long_s=1.0, short_s=2.0, threshold=1.0),),
            reg=reg, install=False)
    with pytest.raises(ValueError):
        SLOPolicy(None, rules=(
            BurnRule("x", long_s=2.0, short_s=1.0, threshold=0.0),),
            reg=reg, install=False)
    with pytest.raises(ValueError):
        SLOPolicy(None, rules=(
            BurnRule("x", long_s=2.0, short_s=1.0, threshold=1.0,
                     clear_ratio=0.0),),
            reg=reg, install=False)
    with pytest.raises(ValueError):
        SLOPolicy(None, rules=(
            BurnRule("a", long_s=2.0, short_s=1.0, threshold=1.0),
            BurnRule("a", long_s=4.0, short_s=2.0, threshold=1.0),),
            reg=reg, install=False)


def test_install_uninstall_and_health_section():
    reg = MetricsRegistry()
    clk = FakeClock()
    assert alerts_section() == {"enabled": False}
    pol = _policy(reg, clk)
    try:
        from singa_tpu.observe import slo as slo_mod
        slo_mod.install(pol)
        sec = alerts_section()
        assert sec["enabled"] is True
        assert "page" in sec["rules"]
        # the health report carries it (and the windowed section)
        rep = health_report(reg=reg, include_registry=False)
        assert rep["serve"]["slo_alerts"]["enabled"] is True
        assert rep["windowed"]["enabled"] is True
        assert rep["serve"]["autoscale"] == {"enabled": False}
        json.dumps(rep, default=str)
    finally:
        pol.close()
    assert alerts_section() == {"enabled": False}


# ---------------------------------------------------------------------------
# export surface
# ---------------------------------------------------------------------------

def test_prometheus_windowed_siblings_build_info_uptime():
    reg = MetricsRegistry()
    clk = FakeClock()
    c = reg.counter("serve.tokens_out", engine="0",
                    help="tokens emitted")
    reg.windowed("serve.tokens_out", windows=(60,), clock=clk)
    h = reg.histogram("serve.ttft", engine="0")
    reg.windowed("serve.ttft", windows=(60,), clock=clk)
    clk.advance(30.0)
    c.inc(60)
    h.observe(0.2)
    txt = prometheus_text(reg)
    lines = txt.splitlines()
    # windowed sibling gauges, each family with HELP + TYPE
    assert any(ln.startswith(
        "singa_tpu_serve_tokens_out_rate_60s{engine=\"0\"} 1")
        for ln in lines), txt
    assert "# HELP singa_tpu_serve_tokens_out_rate_60s" in txt
    assert "# TYPE singa_tpu_serve_tokens_out_rate_60s gauge" in txt
    assert "singa_tpu_serve_ttft_p99_60s" in txt
    # the all-time families are still there, unchanged
    assert "singa_tpu_serve_tokens_out_total" in txt
    assert "singa_tpu_serve_ttft_bucket" in txt
    # scrape-target hygiene
    assert "# TYPE singa_tpu_build_info gauge" in txt
    bi = next(ln for ln in lines
              if ln.startswith("singa_tpu_build_info"))
    assert 'version="' in bi and 'jax="' in bi and 'backend="' in bi
    up = next(ln for ln in lines
              if ln.startswith("singa_tpu_process_uptime_seconds "))
    assert float(up.split()[-1]) >= 0.0
