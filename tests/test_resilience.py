"""singa_tpu.resilience: fault-injection policies, retry/backoff with
transient/fatal classification, the CheckpointManager's corruption
fallback, the async-save failure telemetry, and the typed BinFile
corruption surface.

Everything runs on CPU with seeded policies and injectable sleeps, so
the chaos is deterministic."""

import json
import os

import numpy as np
import pytest

from singa_tpu import device, opt, tensor
from singa_tpu.io import binfile
from singa_tpu.io.binfile import BinFileReader, BinFileWriter, \
    CorruptRecordError
from singa_tpu.models.mlp import MLP
from singa_tpu.observe.health import health_report
from singa_tpu.observe.registry import MetricsRegistry, registry
from singa_tpu.resilience import (CheckpointManager, FailAfterN,
                                  FailOnce, FailRate, FaultInjected,
                                  Latency, NoValidCheckpointError,
                                  RetryBudgetExceededError, RetryPolicy,
                                  faults, retry_call)
from singa_tpu.resilience.checkpoint import (MANIFEST_NAME, STATES_NAME,
                                             CheckpointCorruptError)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _counter(name, **labels):
    snap = registry().snapshot()["counters"]
    key = name
    if labels:
        key += "{" + ",".join(f"{k}={v}"
                              for k, v in sorted(labels.items())) + "}"
    return snap.get(key, 0)


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------

def test_fault_registry_disarmed_is_noop():
    assert not faults.armed()
    faults.check("checkpoint.write")  # nothing armed: no-op, no raise


def test_fail_once_fires_exactly_once():
    pol = faults.inject("t.once", FailOnce())
    with pytest.raises(FaultInjected) as ei:
        faults.check("t.once")
    assert ei.value.site == "t.once"
    assert ei.value.transient
    faults.check("t.once")  # second call passes
    assert pol.fired == 1 and pol.calls == 2


def test_fail_rate_is_seed_deterministic():
    def run(seed):
        faults.clear()
        pol = faults.inject("t.rate", FailRate(0.5, seed=seed))
        fired = []
        for _ in range(20):
            try:
                faults.check("t.rate")
                fired.append(0)
            except FaultInjected:
                fired.append(1)
        return fired
    a, b = run(7), run(7)
    assert a == b                      # same seed, same fault sequence
    assert 0 < sum(a) < 20             # actually probabilistic
    assert run(8) != a                 # different seed, different draw


def test_fail_after_n_passes_then_fires_times():
    faults.inject("t.after", FailAfterN(3, times=2))
    outcomes = []
    for _ in range(7):
        try:
            faults.check("t.after")
            outcomes.append("ok")
        except FaultInjected:
            outcomes.append("fault")
    assert outcomes == ["ok"] * 3 + ["fault"] * 2 + ["ok"] * 2


def test_latency_policy_sleeps_never_raises():
    faults.inject("t.lat", Latency(0.0))
    for _ in range(3):
        faults.check("t.lat")  # no raise


def test_injected_context_manager_disarms():
    with faults.injected("t.ctx", FailOnce()):
        assert faults.armed()
        with pytest.raises(FaultInjected):
            faults.check("t.ctx")
    assert not faults.armed()


def test_fired_faults_are_counted():
    before = _counter("resilience.faults_injected", site="t.count")
    faults.inject("t.count", FailOnce())
    with pytest.raises(FaultInjected):
        faults.check("t.count")
    assert _counter("resilience.faults_injected",
                    site="t.count") == before + 1


# ---------------------------------------------------------------------------
# retry / backoff
# ---------------------------------------------------------------------------

def test_retry_transient_then_success_counts_retries():
    reg = MetricsRegistry()
    sleeps = []
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient io")
        return "ok"

    out = retry_call(flaky, "t.retry",
                     policy=RetryPolicy(max_attempts=4,
                                        base_delay_s=0.01,
                                        max_delay_s=0.05, jitter=0.5,
                                        seed=3),
                     sleep=sleeps.append, reg=reg)
    assert out == "ok" and len(calls) == 3
    snap = reg.snapshot()["counters"]
    assert snap["resilience.retries{site=t.retry}"] == 2
    assert "resilience.gave_up{site=t.retry}" not in snap
    # exponential backoff with jitter in [1, 1.5): delay k in
    # [base*2^k, 1.5*base*2^k)
    assert 0.01 <= sleeps[0] < 0.015
    assert 0.02 <= sleeps[1] < 0.03


def test_retry_backoff_is_seed_deterministic():
    def delays(seed):
        sleeps = []
        calls = []

        def flaky():
            calls.append(1)
            raise OSError("x")

        with pytest.raises(RetryBudgetExceededError):
            retry_call(flaky, "t.det",
                       policy=RetryPolicy(max_attempts=3, seed=seed,
                                          base_delay_s=0.01),
                       sleep=sleeps.append, reg=MetricsRegistry())
        return sleeps
    assert delays(5) == delays(5)
    assert delays(5) != delays(6)


def test_retry_fatal_raises_immediately():
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("a bug, not weather")

    with pytest.raises(ValueError):
        retry_call(broken, "t.fatal", sleep=lambda s: None,
                   reg=MetricsRegistry())
    assert len(calls) == 1  # no retry for fatal classification


def test_retry_budget_exhausted_raises_typed_and_counts():
    reg = MetricsRegistry()

    def always():
        raise TimeoutError("never heals")

    with pytest.raises(RetryBudgetExceededError) as ei:
        retry_call(always, "t.budget",
                   policy=RetryPolicy(max_attempts=3,
                                      base_delay_s=0.001),
                   sleep=lambda s: None, reg=reg)
    assert ei.value.site == "t.budget"
    assert ei.value.attempts == 3
    assert isinstance(ei.value.__cause__, TimeoutError)
    snap = reg.snapshot()["counters"]
    assert snap["resilience.retries{site=t.budget}"] == 2
    assert snap["resilience.gave_up{site=t.budget}"] == 1


def test_injected_fault_transient_flag_drives_classification():
    # transient injected fault: retried and absorbed
    faults.inject("t.class", FailOnce(transient=True))
    out = retry_call(lambda: faults.check("t.class") or "ok", "t.class",
                     policy=RetryPolicy(max_attempts=2,
                                        base_delay_s=0.001),
                     sleep=lambda s: None, reg=MetricsRegistry())
    assert out == "ok"
    # fatal injected fault: raised on first attempt
    faults.clear()
    faults.inject("t.class", FailOnce(transient=False))
    with pytest.raises(FaultInjected):
        retry_call(lambda: faults.check("t.class"), "t.class",
                   sleep=lambda s: None, reg=MetricsRegistry())


def test_corrupt_record_error_is_fatal_to_retry():
    calls = []

    def corrupted():
        calls.append(1)
        raise CorruptRecordError("/x.bin", "CRC mismatch", key="w0")

    with pytest.raises(CorruptRecordError):
        retry_call(corrupted, "t.corrupt", sleep=lambda s: None,
                   reg=MetricsRegistry())
    assert len(calls) == 1  # corruption never heals on retry


# ---------------------------------------------------------------------------
# checkpoint manager
# ---------------------------------------------------------------------------

def _mlp(dev, seed=0):
    dev.SetRandSeed(seed)
    m = MLP(data_size=10, perceptron_size=8, num_classes=4)
    m.set_optimizer(opt.SGD(lr=0.05))
    x = tensor.from_numpy(np.zeros((4, 10), np.float32), dev)
    m.compile([x], is_train=True, use_graph=False, sequential=False)
    return m


def _train_steps(m, dev, n=2, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        x = tensor.from_numpy(rng.randn(4, 10).astype(np.float32), dev)
        y = tensor.from_numpy(rng.randint(0, 4, (4,)).astype(np.int32),
                              dev)
        m(x, y)


def test_checkpoint_save_restore_roundtrip(tmp_path):
    dev = device.get_default_device()
    m = _mlp(dev)
    _train_steps(m, dev)
    mgr = CheckpointManager(str(tmp_path), keep=3)
    mgr.save(m, 10, aux_states={"epoch": np.int64(1)})
    params = {k: tensor.to_numpy(v) for k, v in m.get_params().items()}

    m2 = _mlp(dev, seed=99)
    step, aux = mgr.restore_latest(m2)
    assert step == 10 and int(aux["epoch"]) == 1
    for k, v in m2.get_params().items():
        np.testing.assert_array_equal(tensor.to_numpy(v), params[k])


def test_checkpoint_manifest_is_strict_json_with_digest(tmp_path):
    dev = device.get_default_device()
    m = _mlp(dev)
    mgr = CheckpointManager(str(tmp_path), keep=3)
    path = mgr.save(m, 5)
    raiser = lambda c: (_ for _ in ()).throw(ValueError(c))  # noqa: E731
    man = json.load(open(os.path.join(path, MANIFEST_NAME)),
                    parse_constant=raiser)
    assert man["schema"] == "singa_tpu.checkpoint/1"
    assert man["step"] == 5
    assert man["param_count"] > 0
    meta = man["files"][STATES_NAME]
    states = os.path.join(path, STATES_NAME)
    assert meta["bytes"] == os.path.getsize(states)
    assert len(meta["sha256"]) == 64
    assert mgr.validate(5)["step"] == 5


def test_checkpoint_retention_keeps_last_k(tmp_path):
    dev = device.get_default_device()
    m = _mlp(dev)
    mgr = CheckpointManager(str(tmp_path), keep=2)
    for step in (1, 2, 3, 4):
        mgr.save(m, step)
    assert mgr.steps() == [3, 4]
    assert mgr.latest_step() == 4


@pytest.mark.parametrize("cut", ["third", "half", "minus_one_byte"])
def test_restore_falls_back_on_truncated_newest(tmp_path, cut):
    """Crash-mid-checkpoint: a states file truncated at several byte
    offsets must fall back to the previous good step, bumping the
    fallback counter (satellite + acceptance criterion)."""
    dev = device.get_default_device()
    m = _mlp(dev)
    _train_steps(m, dev, seed=1)
    mgr = CheckpointManager(str(tmp_path), keep=3)
    mgr.save(m, 1, aux_states={"tag": np.int64(11)})
    good = {k: tensor.to_numpy(v) for k, v in m.get_params().items()}
    _train_steps(m, dev, seed=2)
    mgr.save(m, 2, aux_states={"tag": np.int64(22)})

    sp = os.path.join(mgr.step_dir(2), STATES_NAME)
    data = open(sp, "rb").read()
    n = {"third": len(data) // 3, "half": len(data) // 2,
         "minus_one_byte": len(data) - 1}[cut]
    open(sp, "wb").write(data[:n])

    before = _counter("resilience.checkpoint_fallbacks")
    m2 = _mlp(dev, seed=7)
    step, aux = mgr.restore_latest(m2)
    assert step == 1 and int(aux["tag"]) == 11
    for k, v in m2.get_params().items():
        np.testing.assert_array_equal(tensor.to_numpy(v), good[k])
    assert _counter("resilience.checkpoint_fallbacks") == before + 1
    # and the health report surfaces it
    assert health_report()["resilience"]["checkpoint_fallbacks"] \
        >= before + 1


def test_restore_falls_back_on_bitflip_digest_mismatch(tmp_path):
    dev = device.get_default_device()
    m = _mlp(dev)
    mgr = CheckpointManager(str(tmp_path), keep=3)
    mgr.save(m, 1)
    mgr.save(m, 2)
    sp = os.path.join(mgr.step_dir(2), STATES_NAME)
    b = bytearray(open(sp, "rb").read())
    b[len(b) // 2] ^= 0xFF  # flipped bit, same length
    open(sp, "wb").write(bytes(b))
    with pytest.raises(CheckpointCorruptError) as ei:
        mgr.validate(2)
    assert "digest mismatch" in str(ei.value)
    step, _ = mgr.restore_latest(_mlp(dev, seed=3))
    assert step == 1


def test_restore_raises_when_nothing_valid(tmp_path):
    dev = device.get_default_device()
    m = _mlp(dev)
    mgr = CheckpointManager(str(tmp_path), keep=3)
    with pytest.raises(NoValidCheckpointError):
        mgr.restore_latest(m)
    mgr.save(m, 1)
    os.unlink(os.path.join(mgr.step_dir(1), MANIFEST_NAME))
    with pytest.raises(NoValidCheckpointError):
        mgr.restore_latest(m)


def test_checkpoint_write_fault_is_retried(tmp_path):
    dev = device.get_default_device()
    m = _mlp(dev)
    mgr = CheckpointManager(
        str(tmp_path), keep=3,
        retry_policy=RetryPolicy(max_attempts=3, base_delay_s=0.001,
                                 max_delay_s=0.002))
    before = _counter("resilience.retries", site="checkpoint.write")
    faults.inject("checkpoint.write", FailOnce())
    mgr.save(m, 1)  # transient injected fault absorbed by retry
    assert _counter("resilience.retries",
                    site="checkpoint.write") == before + 1
    assert mgr.validate(1)["step"] == 1


def test_model_manager_entry_points(tmp_path):
    dev = device.get_default_device()
    m = _mlp(dev)
    m.save_checkpoint(str(tmp_path), 3, aux_states={"e": np.int64(9)})
    m2 = _mlp(dev, seed=5)
    step, aux = m2.restore_latest_checkpoint(str(tmp_path))
    assert step == 3 and int(aux["e"]) == 9


# ---------------------------------------------------------------------------
# async save failure telemetry (satellite)
# ---------------------------------------------------------------------------

def test_async_save_failure_logged_and_counted(tmp_path):
    """A fire-and-forget async save that fails must bump
    checkpoint.async_failures and log at thread exit; wait() still
    re-raises (test-pinned)."""
    dev = device.get_default_device()
    m = _mlp(dev)
    before = _counter("checkpoint.async_failures")
    faults.inject("checkpoint.write", FailOnce(transient=False))
    handle = m.save_states(str(tmp_path / "a.zip"), async_save=True)
    handle._thread.join(10.0)
    assert _counter("checkpoint.async_failures") == before + 1
    with pytest.raises(FaultInjected):  # wait() re-raises, unchanged
        handle.wait(10.0)
    assert health_report()["resilience"][
        "checkpoint_async_failures"] >= before + 1


def test_sync_save_retry_kwarg_absorbs_transient_fault(tmp_path):
    dev = device.get_default_device()
    m = _mlp(dev)
    faults.inject("checkpoint.write", FailOnce())
    m.save_states(str(tmp_path / "s.zip"),
                  retry=RetryPolicy(max_attempts=2, base_delay_s=0.001))
    m2 = _mlp(dev, seed=4)
    m2.load_states(str(tmp_path / "s.zip"))  # file is whole


# ---------------------------------------------------------------------------
# BinFile typed corruption (satellite)
# ---------------------------------------------------------------------------

@pytest.fixture
def _py_binfile(monkeypatch):
    """Force the pure-Python BinFile fallback: the typed truncation
    surface lives in its parse loop (the native reader rejects a
    truncated file at open)."""
    monkeypatch.setattr(binfile, "_lib", None)
    monkeypatch.setattr(binfile, "_lib_err", RuntimeError("forced"))
    yield


def _write_bin(path):
    w = BinFileWriter(str(path))
    w.put("alpha", b"A" * 100)
    w.put("beta", b"B" * 50)
    w.close()


def test_truncated_tail_raises_typed(tmp_path, _py_binfile):
    p = tmp_path / "t.bin"
    _write_bin(p)
    size = os.path.getsize(p)
    # truncate at several offsets inside the SECOND record
    for cut in (size - 2, size - 20, size - 54):
        data = open(p, "rb").read()
        open(p, "wb").write(data[:cut])
        with pytest.raises(CorruptRecordError) as ei:
            BinFileReader(str(p))
        assert "truncated tail" in str(ei.value)
        assert ei.value.offset is not None
        open(p, "wb").write(data)  # restore for the next cut


def test_corrupt_length_header_raises_typed_not_memoryerror(
        tmp_path, _py_binfile):
    """A bit-flipped value-length field must surface as typed
    corruption, not a multi-GB allocation attempt."""
    import struct as _struct

    p = tmp_path / "l.bin"
    _write_bin(p)
    data = bytearray(open(p, "rb").read())
    # the first record's 8-byte vlen header sits after magic+klen+key
    off = 8 + 4 + 5
    data[off:off + 8] = _struct.pack("<Q", 1 << 62)
    open(p, "wb").write(bytes(data))
    with pytest.raises(CorruptRecordError) as ei:
        BinFileReader(str(p))
    assert "exceeds remaining file" in str(ei.value)


def test_crc_mismatch_names_key_and_checksums(tmp_path, _py_binfile):
    p = tmp_path / "c.bin"
    _write_bin(p)
    data = bytearray(open(p, "rb").read())
    # corrupt one payload byte of the FIRST record (value starts after
    # magic + klen + key + vlen headers = 8 + 4 + 5 + 8)
    data[8 + 4 + 5 + 8 + 10] ^= 0xFF
    open(p, "wb").write(bytes(data))
    with pytest.raises(CorruptRecordError) as ei:
        BinFileReader(str(p))
    err = ei.value
    assert err.key == "alpha"
    assert err.expected is not None and err.actual is not None
    assert err.expected != err.actual
    assert "alpha" in str(err) and "crc expected" in str(err)


def test_binfile_fault_site(tmp_path, _py_binfile):
    p = tmp_path / "f.bin"
    faults.inject("io.binfile", FailOnce())
    with pytest.raises(FaultInjected):
        BinFileWriter(str(p)).put("k", b"v")
    faults.clear()
    _write_bin(p)
    assert BinFileReader(str(p)).read_all()["alpha"] == b"A" * 100


# ---------------------------------------------------------------------------
# collective dispatch site
# ---------------------------------------------------------------------------

def test_collective_fault_retried_at_trace_time():
    from singa_tpu.parallel.communicator import _record_collective

    before = _counter("resilience.retries", site="comm.collective")
    faults.inject("comm.collective",
                  FailOnce(latency_s=0.0, transient=True))
    _record_collective("all_reduce", [np.zeros((4,), np.float32)])
    assert _counter("resilience.retries",
                    site="comm.collective") == before + 1
