"""Test configuration: force an 8-device virtual CPU mesh.

The reference (apache/singa) could only test its NCCL Communicator with >=2
physical GPUs (SURVEY.md §4); here every distributed code path runs in CI on
a virtual 8-device CPU topology.

Note: this environment's sitecustomize registers the `axon` TPU backend and
pins ``jax_platforms`` at interpreter boot, so setting JAX_PLATFORMS in the
environment is not enough — we must override the jax config after import
(but before any backend initializes, i.e. before singa_tpu or test modules
touch jax.devices()).
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running tests (subprocess multihost, CNN-zoo "
        "training, >15s parity sweeps); `-m 'not slow'` is the fast "
        "inner loop for builders")


@pytest.fixture(autouse=True, scope="module")
def _bound_jax_executable_maps():
    """Release compiled executables between test MODULES.

    One pytest process compiles thousands of XLA:CPU executables (each
    eager `_op` primitive application of a new shape caches one);
    their code mappings accumulate against the kernel's
    ``vm.max_map_count`` (65530 default) until, near the end of the
    full suite, an mmap fails inside ``backend_compile_and_load`` and
    XLA SEGFAULTS (observed twice at the same 88% mark, in whichever
    test compiled next — reproduced and measured: the map count grows
    ~4k/min through the ONNX-conformance module).  Clearing jax's
    caches per module returns the maps to baseline; within-module
    compilation reuse — where nearly all the cache hits are — is
    unaffected."""
    yield
    jax.clear_caches()
