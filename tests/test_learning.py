"""Accuracy-target end-to-end tests: "trains" must mean "LEARNS a
known-learnable task to a threshold", not "loss moved" (round-2
verdict; SURVEY.md §4 golden-value philosophy).  One deterministic
synthetic task per model family, thresholds far above chance, runtimes
kept modest (CPU-mesh CI)."""

import numpy as np
import pytest

from singa_tpu import autograd, device as device_module, layer, model, \
    opt, tensor


@pytest.fixture
def dev():
    d = device_module.get_default_device()
    d.SetRandSeed(0)
    return d


def _accuracy(m, x, y):
    m.eval()
    try:
        logits = m(x)
        pred = np.argmax(tensor.to_numpy(logits), axis=-1)
        return float(np.mean(pred == tensor.to_numpy(y)))
    finally:
        m.train()


def _two_spirals(n_per_class=250, noise=0.06, seed=0):
    """The classic non-linearly-separable 2-class benchmark: two
    interleaved spirals.  A linear model caps at ~50%; an MLP that
    actually learns exceeds 95%."""
    rng = np.random.RandomState(seed)
    t = np.sqrt(rng.rand(n_per_class)) * 3 * np.pi
    xs, ys = [], []
    for cls, phase in ((0, 0.0), (1, np.pi)):
        r = t
        x = np.stack([r * np.cos(t + phase), r * np.sin(t + phase)],
                     axis=1) / (3 * np.pi)
        x += rng.randn(*x.shape) * noise
        xs.append(x)
        ys.append(np.full(n_per_class, cls))
    x = np.concatenate(xs).astype(np.float32)
    y = np.concatenate(ys).astype(np.int32)
    idx = rng.permutation(len(x))
    return x[idx], y[idx]


def test_mlp_two_spirals_over_95(dev):
    from singa_tpu.models.mlp import MLP

    x_np, y_np = _two_spirals()
    x = tensor.from_numpy(x_np, dev)
    y = tensor.from_numpy(y_np, dev)
    m = MLP(data_size=2, perceptron_size=64, num_classes=2)
    m.set_optimizer(opt.SGD(lr=0.2, momentum=0.9))
    m.compile([x], is_train=True, use_graph=True)
    for _ in range(1500):
        m(x, y)
    acc = _accuracy(m, x, y)
    assert acc > 0.95, f"two-spirals accuracy {acc:.3f} <= 0.95"


def _shape_images(n=256, hw=16, seed=0):
    """4-class synthetic vision task: which quadrant holds the bright
    blob.  Translation-invariant conv features solve it; chance is 25%."""
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 1, hw, hw).astype(np.float32) * 0.3
    y = rng.randint(0, 4, n).astype(np.int32)
    h = hw // 2
    for i, cls in enumerate(y):
        r0 = (cls // 2) * h + rng.randint(0, h - 4)
        c0 = (cls % 2) * h + rng.randint(0, h - 4)
        x[i, 0, r0:r0 + 4, c0:c0 + 4] += 2.5
    return x, y


def test_cnn_quadrant_task_over_90(dev):
    class TinyCNN(model.Model):
        def __init__(self):
            super().__init__()
            self.conv = layer.Conv2d(8, 3, stride=2, padding=1)
            self.relu = layer.ReLU()
            self.pool = layer.MaxPool2d(2, 2)
            self.flat = layer.Flatten()
            self.fc = layer.Linear(4)
            self.loss_fn = layer.SoftMaxCrossEntropy()

        def forward(self, x):
            return self.fc(self.flat(self.pool(self.relu(self.conv(x)))))

        def train_one_batch(self, x, y):
            out = self.forward(x)
            loss = self.loss_fn(out, y)
            self.optimizer(loss)
            return out, loss

    x_np, y_np = _shape_images()
    x = tensor.from_numpy(x_np, dev)
    y = tensor.from_numpy(y_np, dev)
    m = TinyCNN()
    m.set_optimizer(opt.SGD(lr=0.05, momentum=0.9))
    m.compile([x], is_train=True, use_graph=True)
    for _ in range(60):
        m(x, y)
    acc = _accuracy(m, x, y)
    assert acc > 0.90, f"quadrant-task accuracy {acc:.3f} <= 0.90"


def test_charrnn_perplexity_bound(dev):
    """char-RNN on a fixed periodic corpus: a model that learns the
    repetition drives per-char perplexity far below the uniform-vocab
    baseline (|V|); threshold 2.0 is unreachable without learning the
    sequence structure."""
    from singa_tpu.models.char_rnn import CharRNN, one_hot

    corpus = ("the quick brown fox jumps over the lazy dog. " * 8)
    chars = sorted(set(corpus))
    vocab = len(chars)
    c2i = {c: i for i, c in enumerate(chars)}
    ids = np.array([c2i[c] for c in corpus], np.int32)

    T, B = 32, 8
    starts = np.arange(B) * 37 % (len(ids) - T - 1)
    x_ids = np.stack([ids[s:s + T] for s in starts])
    y_ids = np.stack([ids[s + 1:s + T + 1] for s in starts])

    x = tensor.from_numpy(one_hot(x_ids, vocab), dev)
    y = tensor.from_numpy(y_ids, dev)
    m = CharRNN(vocab_size=vocab, hidden_size=64, num_layers=1,
                seq_length=T)
    m.set_optimizer(opt.Adam(lr=5e-3))
    m.compile([x], is_train=True, use_graph=True)
    loss = None
    for _ in range(150):
        _, loss = m(x, y)
    ppl = float(np.exp(tensor.to_numpy(loss)))
    assert ppl < 2.0, f"char-RNN perplexity {ppl:.2f} >= 2.0 (|V|={vocab})"


@pytest.mark.slow
def test_unet_segments_rectangles_over_90(dev):
    """Segmentation family learning target: binary masks of axis-
    aligned bright rectangles on noisy backgrounds.  Chance pixel
    accuracy tracks the background fraction (~72% with these sizes);
    predicting 'all background' cannot pass the foreground-IoU bar, so
    the decoder (ConvTranspose + skips) must genuinely localize."""
    from singa_tpu.models.unet import unet

    rng = np.random.RandomState(0)
    n, hw = 48, 32
    xs = rng.randn(n, 1, hw, hw).astype(np.float32) * 0.3
    ys = np.zeros((n, hw, hw), np.int32)
    for i in range(n):
        h0, w0 = rng.randint(2, hw // 2, 2)
        hh, ww = rng.randint(8, hw // 2, 2)
        xs[i, 0, h0:h0 + hh, w0:w0 + ww] += 1.5
        ys[i, h0:h0 + hh, w0:w0 + ww] = 1

    m = unet(num_classes=2, base_channels=8, depth=2)
    m.set_optimizer(opt.Adam(lr=2e-3))
    x = tensor.from_numpy(xs, dev)
    y = tensor.from_numpy(ys, dev)
    m.compile([x], is_train=True, use_graph=True)
    for _ in range(60):
        _, loss = m(x, y)
    assert np.isfinite(float(tensor.to_numpy(loss)))

    m.eval()
    pred = np.argmax(tensor.to_numpy(m.forward(x)), axis=1)
    pix_acc = float(np.mean(pred == ys))
    inter = np.logical_and(pred == 1, ys == 1).sum()
    union = np.logical_or(pred == 1, ys == 1).sum()
    iou = inter / max(union, 1)
    assert pix_acc > 0.90, pix_acc
    assert iou > 0.60, (iou, pix_acc)
