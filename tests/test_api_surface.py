"""Reference API surface contract: the names a SINGA user's script
calls must exist with callable shapes (SURVEY.md §2.2 tables; the
`singa` alias makes these the literal import lines of upstream
examples).  This is a regression fence — removing or renaming any of
these breaks source compatibility silently otherwise."""

import inspect


def _has(mod, names):
    missing = [n for n in names if not hasattr(mod, n)]
    assert not missing, f"{mod.__name__} missing: {missing}"


def test_module_to_host_is_a_copy():
    """Reference semantics: tensor.to_host(t) clones — t keeps its
    device; only the METHOD t.to_host() migrates in place."""
    import numpy as np

    from singa import tensor

    t = tensor.from_numpy(np.ones((2, 2), np.float32))
    dev_before = t.device
    h = tensor.to_host(t)
    assert h is not t
    assert t.device is dev_before
    np.testing.assert_array_equal(tensor.to_numpy(h),
                                  tensor.to_numpy(t))


def test_tensor_api():
    from singa import tensor

    _has(tensor, [
        "Tensor", "from_numpy", "to_numpy", "einsum", "reshape",
        "transpose", "add", "sub", "eltwise_mult", "div", "mult", "axpy",
        "sum", "average", "softmax", "relu", "sigmoid", "tanh", "exp",
        "log", "abs", "pow", "lt", "le", "gt", "ge",
        "add_column", "add_row", "mult_column", "mult_row",
        "sum_columns", "sum_rows", "bernoulli", "gaussian", "uniform",
        "concatenate", "copy_data_to_from", "to_host",
    ])


def test_device_api():
    from singa import device

    _has(device, [
        "create_tpu_device", "create_tpu_devices", "get_default_device",
        "set_default_device", "CppCPU", "TpuDevice", "device_query",
        # source-compat aliases for reference scripts
        "create_cuda_gpu", "create_cuda_gpu_on", "create_cuda_gpus",
    ])


def test_autograd_api():
    from singa import autograd

    _has(autograd, [
        "Operation", "Dummy", "backward", "set_training",
        "relu", "sigmoid", "tanh", "gelu", "softmax", "matmul", "gemm",
        "add", "sub", "mul", "div", "reshape", "transpose", "cat",
        "flatten", "dropout", "softmax_cross_entropy", "cross_entropy",
        "mse_loss", "mul_scalar", "checkpoint_op", "embedding",
        "layer_norm",
    ])


def test_layer_api():
    from singa import layer

    _has(layer, [
        "Layer", "Linear", "Conv2d", "ConvTranspose2d", "BatchNorm2d",
        "Pooling2d", "MaxPool2d", "AvgPool2d", "ReLU", "Flatten",
        "Dropout", "LayerNorm", "Embedding", "LSTM", "GRU", "RNN",
        "MultiHeadAttention", "SoftMaxCrossEntropy",
    ])


def test_model_api():
    from singa import model

    m = model.Model
    for meth in ("compile", "train_one_batch", "forward", "set_optimizer",
                 "save_states", "load_states", "train", "eval",
                 "set_sharding_plan"):
        assert callable(getattr(m, meth, None)), meth


def test_opt_api():
    from singa import opt

    _has(opt, ["Optimizer", "SGD", "RMSProp", "AdaGrad", "Adam",
               "AdamW", "Lion",
               "DistOpt", "Constant", "ExponentialDecay", "StepDecay"])
    sig = inspect.signature(opt.SGD.__init__)
    for p in ("lr", "momentum", "nesterov", "weight_decay", "dampening"):
        assert p in sig.parameters, p


def test_sonnx_api():
    from singa import sonnx

    _has(sonnx, ["prepare", "to_onnx", "save", "load", "SingaBackend",
                 "SingaFrontend", "SingaRep", "SONNXModel"])


def test_parallel_api():
    from singa import parallel

    _has(parallel, ["create_mesh", "ShardingPlan", "DATA", "MODEL",
                    "SEQ", "PIPE", "EXPERT", "constrain"])
    from singa.parallel import communicator, dist_opt, moe, pipeline
    from singa.parallel import ring_attention, tensor_parallel

    _has(communicator, ["Communicator", "initialize_distributed",
                        "get_mesh"])
    _has(dist_opt, ["DistOpt"])
    _has(moe, ["MoEFFN"])
    _has(pipeline, ["PipelinedTransformer", "gpipe_spmd"])
    _has(ring_attention, ["ring_self_attention", "ring_attention_sharded"])
    _has(tensor_parallel, [
        "ColumnParallelLinear", "RowParallelLinear",
        "VocabParallelEmbedding", "ParallelMHA", "ParallelMLP",
        "ParallelTransformerBlock"])


def test_models_zoo():
    from singa_tpu.models import (alexnet, bert, char_rnn, cnn, gpt2,  # noqa
                                  mlp, mobilenet, resnet, unet, vgg,
                                  xceptionnet)

    from singa_tpu.models.resnet import (resnet18, resnet34, resnet50,
                                         resnet101, resnet152)
    from singa_tpu.models.bert import BertForMaskedLM, BertModel
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead


def test_snapshot_and_io():
    from singa import snapshot

    _has(snapshot, ["Snapshot"])
    from singa.io import binfile, image, loader, onnx_pb, textfile

    _has(binfile, ["BinFileReader", "BinFileWriter"])
    _has(textfile, ["TextFileReader", "TextFileWriter"])
    _has(loader, ["DataLoader", "write_dataset"])
