"""Autograd ops vs jax.grad goldens and tape-walk semantics (reference test
strategy: test/python/test_autograd.py & test_operation.py, unverified)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from singa_tpu import autograd, tensor
from singa_tpu import device as device_module
from singa_tpu.tensor import Tensor


@pytest.fixture
def dev():
    d = device_module.get_default_device()
    d.SetRandSeed(0)
    return d


@pytest.fixture(autouse=True)
def _training():
    autograd.set_training(True)
    yield
    autograd.set_training(False)


def _param(arr, dev):
    t = tensor.from_numpy(arr, dev)
    t.requires_grad = True
    t.stores_grad = True
    return t


def test_backward_simple_chain(dev):
    # loss = sum(relu(x W)) ; check dW against jax.grad
    rng = np.random.RandomState(0)
    x_np = rng.randn(4, 3).astype(np.float32)
    w_np = rng.randn(3, 5).astype(np.float32)
    x = tensor.from_numpy(x_np, dev)
    w = _param(w_np, dev)

    y = autograd.matmul(x, w)
    z = autograd.relu(y)
    loss = autograd.reduce_sum(z)
    grads = dict(autograd.backward(loss))
    assert w in grads
    ref = jax.grad(lambda W: jnp.sum(jax.nn.relu(x_np @ W)))(w_np)
    np.testing.assert_allclose(tensor.to_numpy(grads[w]), ref, rtol=1e-5)


def test_backward_shared_param_accumulates(dev):
    # w used twice: grads must accumulate at the Dummy before yielding
    w_np = np.array([1.0, 2.0], np.float32)
    w = _param(w_np, dev)
    a = autograd.mul(w, w)           # w^2
    b = autograd.add(a, w)           # w^2 + w
    loss = autograd.reduce_sum(b)
    grads = dict(autograd.backward(loss))
    np.testing.assert_allclose(tensor.to_numpy(grads[w]), 2 * w_np + 1)


def test_softmax_cross_entropy_grad(dev):
    rng = np.random.RandomState(1)
    x_np = rng.randn(6, 4).astype(np.float32)
    t_np = rng.randint(0, 4, size=(6,))
    x = _param(x_np, dev)
    t = tensor.from_numpy(t_np.astype(np.int32), dev)
    loss = autograd.softmax_cross_entropy(x, t)

    def ref_loss(xv):
        lp = jax.nn.log_softmax(xv, -1)
        oh = jax.nn.one_hot(t_np, 4)
        return -jnp.sum(oh * lp) / xv.shape[0]

    np.testing.assert_allclose(float(loss.data), float(ref_loss(x_np)), rtol=1e-5)
    grads = dict(autograd.backward(loss))
    ref = jax.grad(ref_loss)(x_np)
    np.testing.assert_allclose(tensor.to_numpy(grads[x]), ref, rtol=1e-5, atol=1e-6)


def test_cross_entropy_matches_softmax_path(dev):
    rng = np.random.RandomState(2)
    x_np = rng.randn(3, 5).astype(np.float32)
    t_np = np.eye(5, dtype=np.float32)[[0, 2, 4]]
    x = _param(x_np, dev)
    t = tensor.from_numpy(t_np, dev)
    p = autograd.softmax(x, axis=1)
    loss = autograd.cross_entropy(p, t)
    l2 = autograd.softmax_cross_entropy(_param(x_np, dev), tensor.from_numpy(t_np, dev))
    np.testing.assert_allclose(float(loss.data), float(l2.data), rtol=1e-5)
    grads = dict(autograd.backward(loss))
    ref = jax.grad(
        lambda xv: -jnp.sum(t_np * jax.nn.log_softmax(xv, -1)) / 3.0
    )(x_np)
    np.testing.assert_allclose(tensor.to_numpy(grads[x]), ref, rtol=1e-4, atol=1e-6)


def test_mse_and_elementwise_ops(dev):
    rng = np.random.RandomState(3)
    a_np = rng.rand(4).astype(np.float32) + 0.5
    b_np = rng.rand(4).astype(np.float32) + 0.5
    a, b = _param(a_np, dev), tensor.from_numpy(b_np, dev)
    loss = autograd.mse_loss(autograd.mul(autograd.exp(a), b), b)
    grads = dict(autograd.backward(loss))
    ref = jax.grad(lambda av: jnp.mean((jnp.exp(av) * b_np - b_np) ** 2))(a_np)
    np.testing.assert_allclose(tensor.to_numpy(grads[a]), ref, rtol=1e-5)


def test_reshape_flatten_transpose_grads(dev):
    x_np = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    x = _param(x_np, dev)
    y = autograd.reshape(x, (6, 4))
    z = autograd.transpose(y, (1, 0))
    f = autograd.flatten(z, axis=1)
    loss = autograd.reduce_sum(autograd.mul(f, f))
    grads = dict(autograd.backward(loss))
    np.testing.assert_allclose(tensor.to_numpy(grads[x]), 2 * x_np, rtol=1e-6)


def test_concat_and_multi_output_split(dev):
    a_np = np.ones((2, 3), np.float32)
    b_np = 2 * np.ones((2, 3), np.float32)
    a, b = _param(a_np, dev), _param(b_np, dev)
    c = autograd.cat([a, b], axis=0)
    parts = autograd.split(c, axis=0, parts=[1, 3])
    loss = autograd.reduce_sum(autograd.mul(parts[1], parts[1]))
    grads = dict(autograd.backward(loss))
    # row 0 of `a` flows into parts[0] (unused -> zero grad)
    expect_a = np.vstack([np.zeros((1, 3)), 2 * np.ones((1, 3))]).astype(np.float32)
    np.testing.assert_allclose(tensor.to_numpy(grads[a]), expect_a)
    np.testing.assert_allclose(tensor.to_numpy(grads[b]), 2 * b_np)


def test_dropout_train_eval(dev):
    x = tensor.from_numpy(np.ones((1000,), np.float32), dev)
    y = autograd.dropout(x, 0.4)
    arr = tensor.to_numpy(y)
    kept = arr != 0
    assert 0.45 < kept.mean() < 0.75
    np.testing.assert_allclose(arr[kept], 1.0 / 0.6, rtol=1e-5)
    autograd.set_training(False)
    y2 = autograd.dropout(x, 0.4)
    np.testing.assert_array_equal(tensor.to_numpy(y2), np.ones(1000))


def test_no_tape_when_eval(dev):
    autograd.set_training(False)
    x = _param(np.ones((2, 2), np.float32), dev)
    y = autograd.relu(x)
    assert y.creator is None


def test_backward_generator_yields_incrementally(dev):
    x = tensor.from_numpy(np.ones((2, 3), np.float32), dev)
    w1 = _param(np.ones((3, 4), np.float32), dev)
    w2 = _param(np.ones((4, 2), np.float32), dev)
    h = autograd.matmul(x, w1)
    out = autograd.matmul(h, w2)
    loss = autograd.reduce_sum(out)
    gen = autograd.backward(loss)
    first = next(gen)
    # grads arrive reverse-topologically: w2 first (closest to loss)
    assert first[0] is w2
    rest = list(gen)
    assert rest[0][0] is w1


def test_gemm_variants(dev):
    rng = np.random.RandomState(4)
    A = rng.randn(3, 4).astype(np.float32)
    B = rng.randn(5, 4).astype(np.float32)
    C = rng.randn(3, 5).astype(np.float32)
    ta, tb, tc = _param(A, dev), _param(B, dev), _param(C, dev)
    y = autograd.gemm(ta, tb, tc, alpha=2.0, beta=0.5, transB=True)
    np.testing.assert_allclose(
        tensor.to_numpy(y), 2 * (A @ B.T) + 0.5 * C, rtol=1e-5)
    loss = autograd.reduce_sum(y)
    grads = dict(autograd.backward(loss))
    assert set(grads) == {ta, tb, tc}
    np.testing.assert_allclose(
        tensor.to_numpy(grads[tc]), 0.5 * np.ones_like(C), rtol=1e-6)
