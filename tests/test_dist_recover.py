"""Controller survivability (the recover round): reconnect-with-resume
transport, worker request journaling, and fenced fleet adoption.

Three failure shapes, three recoveries — none of which may lose or
duplicate a token:

* a transient NETWORK BLIP severs the controller-side socket: the
  worker redials inside a bounded window with full-jitter backoff, the
  session resumes (same seq space, same fencing epoch), and the one
  unacked CALL replays exactly-once against the worker's reply cache —
  no failover, no respawn, no cold arena;
* a CONTROLLER CRASH orphans live workers: they keep stepping, journal
  per-request progress (emitted-token cursor, arrival order), PARK
  finished results under a TTL, and a successor controller ADOPTS them
  — fencing epoch bumped, journals reconciled, parked results
  re-delivered exactly once, never-started work requeued in arrival
  order;
* the DEPOSED controller comes back: every frame it sends carries its
  stale epoch and is refused typed (:class:`StaleEpochError`) before
  dispatch — split-brain dual routing is impossible by construction.

Unit tests drive the transport/worker protocol over socketpairs (no
engine); integration tests run the thread-mode fleet and pin byte
parity against the single-model oracle."""

import socket
import threading
import time

import numpy as np
import pytest

from singa_tpu import tensor
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from singa_tpu.serve import DistFleet, GenerationRequest, gpt2_spec
from singa_tpu.serve.autoscale import AutoscaleConfig, Autoscaler
from singa_tpu.serve.dist.transport import (
    IDEMPOTENT_OPS, MSG_CALL, MSG_HELLO, MSG_ONEWAY, MSG_REPLY,
    MSG_RESUME, PROTO_VERSION, Conn, Listener,
    NonIdempotentReplayError, PeerGoneError, PeerTimeoutError,
    StaleEpochError, TransportError, _full_jitter, resume_auth)
from singa_tpu.serve.dist.worker import _Worker, load_exc


@pytest.fixture(scope="module")
def model():
    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    return m


@pytest.fixture(scope="module")
def spec(model):
    return gpt2_spec(model)


def _prompts(n, seed=0, lo=4, hi=9):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 256, rng.randint(lo, hi)).astype(np.int32)
            for _ in range(n)]


def _oracle(model, prompts, new):
    return [[int(t) for t in model.generate(p, max_new_tokens=new,
                                            temperature=0.0)]
            for p in prompts]


# ---------------------------------------------------------------------------
# backoff + handshake auth (satellites a, c)
# ---------------------------------------------------------------------------

def test_full_jitter_bounds_and_cap():
    """Backoff draws are uniform in [0, min(base*2^a, cap)): bounded
    below the exponential ceiling early, clamped at the cap late, and
    never negative — the decorrelating shape N redialing workers need
    to not thunder in lockstep."""
    import random

    rng = random.Random(7)
    base, cap = 0.1, 2.0
    for attempt in range(10):
        hi = min(base * 2.0 ** attempt, cap)
        draws = [_full_jitter(rng, base, attempt, cap)
                 for _ in range(400)]
        assert all(0.0 <= d < hi for d in draws), (attempt, hi)
        # full jitter, not equal-jitter: draws span most of the range
        assert max(draws) > 0.8 * hi
        assert min(draws) < 0.2 * hi
    # deep attempts are cap-clamped, not exponential
    assert max(_full_jitter(rng, base, 30, cap)
               for _ in range(100)) < cap


def test_resume_auth_binds_every_field():
    """The RESUME HMAC commits to (nonce, idx, epoch, last_seq) under
    the fleet token: flipping any field — or the token — changes the
    digest, and str/bytes tokens agree (the wire carries both)."""
    base = resume_auth(b"tok", "n0", 3, 2, 17)
    assert base == resume_auth(b"tok", "n0", 3, 2, 17)  # deterministic
    assert base == resume_auth("tok", "n0", 3, 2, 17)   # str == bytes
    assert base != resume_auth(b"tok", "n1", 3, 2, 17)
    assert base != resume_auth(b"tok", "n0", 4, 2, 17)
    assert base != resume_auth(b"tok", "n0", 3, 3, 17)
    assert base != resume_auth(b"tok", "n0", 3, 2, 18)
    assert base != resume_auth(b"other", "n0", 3, 2, 17)


def test_hello_token_and_nonce_replay_refused():
    """HELLO hardening: a wrong token is refused (constant-time
    compare), a valid handshake is accepted once, and REPLAYING the
    same session nonce — even with the right token — is refused."""
    lst = Listener(token=b"secret")
    try:
        def dial(frame):
            s = socket.create_connection((lst.host, lst.port),
                                         timeout=5.0)
            c = Conn(s, "test")
            c.send(MSG_HELLO, frame)
            return c

        bad = dial({"token": b"wrong", "idx": 0,
                    "proto": PROTO_VERSION, "nonce": "n-bad"})
        with pytest.raises(TransportError, match="refused"):
            lst.accept_worker(timeout=5.0)
        bad.close()

        ok = dial({"token": b"secret", "idx": 0,
                   "proto": PROTO_VERSION, "nonce": "n-once"})
        idx, conn = lst.accept_worker(timeout=5.0)
        assert idx == 0
        conn.close()
        ok.close()

        replay = dial({"token": b"secret", "idx": 0,
                       "proto": PROTO_VERSION, "nonce": "n-once"})
        with pytest.raises(TransportError, match="nonce"):
            lst.accept_worker(timeout=5.0)
        replay.close()
    finally:
        lst.close()


def test_resume_auth_verified_and_nonce_single_use():
    """RESUME handshakes verify the HMAC over the listener's token:
    a forged auth is refused, a valid one lands as MSG_RESUME, and its
    nonce is burned — the same frame replayed is refused."""
    lst = Listener(token=b"tok")
    try:
        def dial(frame):
            s = socket.create_connection((lst.host, lst.port),
                                         timeout=5.0)
            c = Conn(s, "test")
            c.send(MSG_RESUME, frame)
            return c

        forged = dial({"idx": 1, "proto": PROTO_VERSION,
                       "nonce": "r0", "epoch": 1, "last_seq": 5,
                       "auth": "not-an-hmac"})
        with pytest.raises(TransportError, match="auth"):
            lst.accept_any(timeout=5.0)
        forged.close()

        frame = {"idx": 1, "proto": PROTO_VERSION, "nonce": "r1",
                 "epoch": 1, "last_seq": 5,
                 "auth": resume_auth(b"tok", "r1", 1, 1, 5)}
        good = dial(frame)
        kind, got, conn = lst.accept_any(timeout=5.0)
        assert kind == MSG_RESUME
        assert got["last_seq"] == 5 and got["epoch"] == 1
        conn.close()
        good.close()

        replayed = dial(dict(frame))
        with pytest.raises(TransportError, match="nonce"):
            lst.accept_any(timeout=5.0)
        replayed.close()
    finally:
        lst.close()


# ---------------------------------------------------------------------------
# replay protocol: finish_pending over a scripted peer
# ---------------------------------------------------------------------------

def _echo_responder(conn):
    """Replies to every CALL with ok + the op name, until peer loss."""
    try:
        while True:
            kind, msg = conn.recv(timeout=5.0)
            if kind == MSG_CALL:
                conn.send(MSG_REPLY, {"seq": msg["seq"], "ok": True,
                                      "value": {"op": msg["op"]}})
    except (PeerGoneError, PeerTimeoutError, TransportError, OSError):
        pass


def _conn_pair():
    sa, sb = socket.socketpair()
    a, b = Conn(sa, "ctl"), Conn(sb, "wrk")
    t = threading.Thread(target=_echo_responder, args=(b,),
                         daemon=True)
    t.start()
    return a, b


def test_finish_pending_resends_same_seq():
    """Reply lost (or call never arrived): the pending CALL resends
    under its ORIGINAL seq — the worker either answers from its reply
    cache or treats it as first delivery; either way exactly-once."""
    a, b = _conn_pair()
    try:
        seq = a.send_call("step")
        assert a._pending is not None
        # the reply exists but we "lost" it: replay instead of reading
        msg = a.finish_pending(peer_last_seq=seq)
        assert msg["seq"] == seq and msg["ok"]
        assert a._pending is None
    finally:
        a.close()
        b.close()


def test_finish_pending_first_delivery_case():
    a, b = _conn_pair()
    try:
        a._seq = 4
        a._pending = (4, "telemetry", None)  # sent, never arrived
        msg = a.finish_pending(peer_last_seq=3)  # seq == last+1
        assert msg["seq"] == 4 and msg["ok"]
    finally:
        a.close()
        b.close()


def test_finish_pending_divergence_idempotent_reissues():
    """Seq divergence on an idempotent op: safe to re-issue under a
    fresh seq (a double ping cannot corrupt anything)."""
    a, b = _conn_pair()
    try:
        assert "ping" in IDEMPOTENT_OPS
        a._seq = 4
        a._pending = (4, "ping", None)
        msg = a.finish_pending(peer_last_seq=1)   # 4 > 1+1: diverged
        assert msg["ok"] and msg["seq"] == 5      # fresh seq
        assert a._pending is None
    finally:
        a.close()
        b.close()


def test_finish_pending_divergence_non_idempotent_aborts_typed():
    """Seq divergence on submit/step: the worker may have executed it
    once already — re-issuing could double-admit, so the replay aborts
    typed into the existing failover path (NonIdempotentReplayError
    IS a PeerGoneError)."""
    a, b = _conn_pair()
    try:
        assert "submit" not in IDEMPOTENT_OPS
        a._seq = 4
        a._pending = (4, "submit", {"request": {}})
        with pytest.raises(NonIdempotentReplayError):
            a.finish_pending(peer_last_seq=1)
        assert a._pending is None
        assert issubclass(NonIdempotentReplayError, PeerGoneError)
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# worker loop: seq dedupe and epoch fencing (no engine needed)
# ---------------------------------------------------------------------------

def _worker_pair(epoch=0):
    """A live _Worker loop over a socketpair, engine-less: clock/ping
    ops exercise the dispatch, cache, and fence without a model."""
    sa, sb = socket.socketpair()
    ctl = Conn(sa, "r0")
    ticks = [0]

    def fake_clock():
        ticks[0] += 1
        return float(ticks[0])

    w = _Worker(Conn(sb, "fleet"), clock=fake_clock)
    w._epoch = epoch
    t = threading.Thread(target=w.run, daemon=True)
    t.start()
    return ctl, w, t


def test_worker_seq_dedupe_answers_from_cache():
    """A replayed seq (the post-resume case) answers from the reply
    cache WITHOUT re-executing: the cached clock value is returned
    verbatim, and the next fresh seq proves the worker still
    executes."""
    ctl, w, t = _worker_pair()
    try:
        ctl.send(MSG_CALL, {"seq": 1, "op": "clock"})
        _, r1 = ctl.recv(timeout=5.0)
        ctl.send(MSG_CALL, {"seq": 1, "op": "clock"})   # replay
        _, r2 = ctl.recv(timeout=5.0)
        assert r1["ok"] and r2["ok"]
        assert r2["value"]["t"] == r1["value"]["t"], \
            "replayed seq re-executed instead of hitting the cache"
        ctl.send(MSG_CALL, {"seq": 2, "op": "clock"})   # fresh seq
        _, r3 = ctl.recv(timeout=5.0)
        assert r3["value"]["t"] > r1["value"]["t"]
    finally:
        ctl.send(MSG_ONEWAY, {"op": "die"})
        t.join(timeout=5.0)
        ctl.close()


def test_worker_fences_stale_epoch_typed_before_dispatch():
    """Frames from a deposed controller (epoch below the worker's):
    CALLs are refused typed with StaleEpochError — which reconstructs
    to its own class controller-side — and stale ONE-WAYS are dropped,
    BEFORE dispatch, so even a ``die`` from the stale side is inert.
    The refusal is never cached: the same seq under the current epoch
    executes normally."""
    ctl, w, t = _worker_pair(epoch=3)
    try:
        ctl.send(MSG_CALL, {"seq": 1, "op": "ping", "epoch": 2})
        _, r = ctl.recv(timeout=5.0)
        assert not r["ok"]
        err = load_exc(r["err"])
        assert isinstance(err, StaleEpochError)
        # a stale die is DROPPED, not obeyed: the worker still answers
        ctl.send(MSG_ONEWAY, {"op": "die", "epoch": 2})
        ctl.send(MSG_CALL, {"seq": 1, "op": "ping", "epoch": 3})
        _, r2 = ctl.recv(timeout=5.0)
        assert r2["ok"], "stale refusal polluted the reply cache"
    finally:
        ctl.send(MSG_ONEWAY, {"op": "die", "epoch": 3})
        t.join(timeout=5.0)
        ctl.close()


# ---------------------------------------------------------------------------
# journal: TTL tombstones and exactly-once claims
# ---------------------------------------------------------------------------

def _journal_worker(now=(0.0,)):
    clockbox = list(now)
    w = _Worker(object(), clock=lambda: clockbox[0])
    return w, clockbox


def test_journal_ttl_expiry_leaves_typed_tombstone():
    """A parked result nobody claims within the TTL is dropped, but a
    tombstone remains: a LATE adopter gets a typed ``expired`` verdict
    (with the token cursor, so it can refuse started work) instead of
    silence."""
    w, clock = _journal_worker()
    w._park_ttl = 10.0
    w._journal["a"] = {"state": "done", "req": None, "cursor": 2,
                       "order": 1, "out": {"result": "X"}, "t": 0.0}
    clock[0] = 5.0
    w._sweep_journal()
    assert w._journal["a"]["state"] == "done"   # inside the TTL
    clock[0] = 11.0
    w._sweep_journal()
    ent = w._journal["a"]
    assert ent["state"] == "expired"
    assert ent["out"] is None                    # the result is gone
    assert ent["cursor"] == 2                    # the verdict survives
    got = w.op_claim({"rid": "a"})
    assert got == {"status": "expired", "cursor": 2}


def test_parked_claim_is_exactly_once():
    """Claiming a parked result deletes it: the first adopter gets the
    payload, a second claim gets ``gone`` — and the streamed-token
    backlog for the claimed rid is purged so it cannot ride a later
    step reply into a controller that never submitted it."""
    w, _ = _journal_worker()
    payload = {"result": {"tokens": [1, 2, 3]}}
    w._journal["b"] = {"state": "done", "req": {"request_id": "b"},
                       "cursor": 3, "order": 1, "out": payload,
                       "t": 0.0}
    w._tokens = [("b", 7), ("c", 9)]
    got = w.op_claim({"rid": "b"})
    assert got["status"] == "parked"
    assert got["out"] is payload and got["cursor"] == 3
    assert w._tokens == [("c", 9)]
    assert w.op_claim({"rid": "b"}) == {"status": "gone"}
    assert w.op_claim({"rid": "never-seen"}) == {"status": "gone"}


def test_journal_cap_evicts_oldest_non_live():
    w, _ = _journal_worker()
    w._journal_cap = 3
    for i in range(5):
        st = "live" if i == 0 else "done"
        w._journal[f"r{i}"] = {"state": st, "req": None, "cursor": 0,
                               "order": i, "out": None, "t": 0.0}
    w._trim_journal()
    assert len(w._journal) == 3
    assert "r0" in w._journal   # live entries are never evicted


# ---------------------------------------------------------------------------
# autoscaler: reconnect grace gates replace_dead (satellite b)
# ---------------------------------------------------------------------------

def test_in_reconnect_grace_predicate():
    class R:
        pass

    r = R()
    assert Autoscaler._in_reconnect_grace(r) is False   # no attr
    r.reconnect_deadline = None
    assert Autoscaler._in_reconnect_grace(r) is False
    r.reconnect_deadline = time.monotonic() + 30.0
    assert Autoscaler._in_reconnect_grace(r) is True
    r.reconnect_deadline = time.monotonic() - 1.0
    assert Autoscaler._in_reconnect_grace(r) is False


def test_replace_dead_waits_out_reconnect_grace(model, spec):
    """The replace_dead/reconnect race, pinned: a replica whose
    transport is inside its reconnect (+grace) window must NOT be
    respawned — the worker may be about to resume — and once the
    window lapses the autoscaler heals the fleet as before."""
    with DistFleet(spec, replicas=2, spawn="thread",
                   max_slots=2) as fleet:
        fleet.kill_worker(0)
        rep = fleet._replicas[0]
        fleet._mark_down(rep, PeerGoneError("test: worker lost",
                                            started=None))
        rep.needs_failover = False     # no routes to reconcile
        rep.reconnect_deadline = time.monotonic() + 30.0
        sc = Autoscaler(fleet, AutoscaleConfig(
            min_replicas=2, max_replicas=2,
            scale_up_cooldown_s=0.0, scale_down_cooldown_s=0.0))
        try:
            ev = sc.check()
            assert ev is None or ev["action"] != "replace_dead", ev
            assert fleet.healthy_replicas == 1
            # the window lapses: the same dead replica is now fair game
            rep.reconnect_deadline = time.monotonic() - 0.001
            ev = sc.check()
            assert ev is not None and ev["action"] == "replace_dead"
            assert fleet.healthy_replicas == 2
            assert rep.reconnect_deadline is None   # revive cleared it
        finally:
            sc.close()


# ---------------------------------------------------------------------------
# integration: blip-resume, fenced adoption, stale-controller refusal
# ---------------------------------------------------------------------------

def test_blip_resumes_without_failover_byte_parity(model, spec):
    """A severed controller-side socket mid-decode: the worker redials
    and the session resumes — zero failovers, zero requeues, the fleet
    stays at full width, the epoch never moves, and every stream is
    byte-identical to the single-model oracle."""
    prompts = _prompts(5, seed=0)
    want = _oracle(model, prompts, new=5)
    with DistFleet(spec, replicas=2, spawn="thread",
                   max_slots=2) as fleet:
        hs = [fleet.submit(GenerationRequest(
            p, max_new_tokens=5, request_id=f"b{i}"))
            for i, p in enumerate(prompts)]
        for _ in range(3):
            fleet.step()
        fleet.blip_worker(0)
        fleet.run_until_complete(max_steps=800)
        got = [[int(t) for t in h.result().tokens] for h in hs]
        snap = fleet.snapshot()
        assert fleet.healthy_replicas == 2
    assert got == want, (got, want)
    d = snap["dist"]
    assert d["reconnects"] >= 1
    assert d["resumed_calls"] >= 1
    assert d["epoch"] == 1            # a resume is not an adoption
    assert snap["failovers"] == 0
    assert snap["requeues"] == 0


def test_crash_adopt_reconciles_exactly_once_parity(model, spec):
    """Controller crash + fenced adoption: the successor attaches to
    the live workers, bumps the epoch to 2, reconciles every journaled
    request (resumed / delivered / requeued — nothing rejected), and
    every stream finishes byte-identical to the oracle: zero lost,
    zero duplicated tokens across the controller boundary."""
    prompts = _prompts(5, seed=3)
    want = _oracle(model, prompts, new=5)
    A = DistFleet(spec, replicas=2, spawn="thread", max_slots=2)
    port, token = A._listener.port, A._token
    hs = [A.submit(GenerationRequest(
        p, max_new_tokens=5, request_id=f"c{i}"))
        for i, p in enumerate(prompts)]
    for _ in range(2):
        A.step()
    assert not any(h.done() for h in hs), \
        "crash must land mid-flight for the test to mean anything"
    A.crash()

    B = DistFleet.adopt(spec, port=port, token=token, replicas=2,
                        spawn="thread", max_slots=2)
    try:
        rep = B.adoption
        assert rep["rejected"] == {}, rep["rejected"]
        handles = dict(rep["resumed"])
        handles.update(rep["delivered"])
        handles.update(rep["requeued"])
        assert sorted(handles) == [f"c{i}" for i in range(5)]
        B.run_until_complete(max_steps=800)
        got = [[int(t) for t in handles[f"c{i}"].result().tokens]
               for i in range(5)]
        snap = B.snapshot()
        assert B.healthy_replicas == 2
    finally:
        B.close()
    assert got == want, (got, want)
    assert snap["dist"]["epoch"] == 2


def test_stale_controller_refused_typed_on_every_op(model, spec):
    """The fence, controller-side: a conn stamping an older epoch (the
    deposed controller's view of the world) is refused typed on EVERY
    op — ping, snapshot, submit, and the overlapped step path — and
    the refusal is StaleEpochError, never a silent drop or a wrong
    answer.  Restoring the current epoch restores service: the fence
    rejected the EPOCH, not the connection."""
    prompts = _prompts(1, seed=9)
    with DistFleet(spec, replicas=2, spawn="thread",
                   max_slots=2) as fleet:
        sup = fleet.supervisor(0)
        sup.ping()                      # baseline: the conn is healthy
        sup._conn.epoch = 0             # impersonate a deposed epoch
        with pytest.raises(StaleEpochError):
            sup.ping()
        with pytest.raises(StaleEpochError):
            sup._rpc("snapshot")
        with pytest.raises(StaleEpochError):
            sup.submit(GenerationRequest(
                prompts[0], max_new_tokens=3, request_id="stale"))
        with pytest.raises(StaleEpochError):
            sup.step()
        sup._conn.epoch = fleet._epoch
        sup.ping()                      # fenced out, not condemned
        assert fleet.healthy_replicas == 2
