"""DistOpt / Communicator tests on the 8-device virtual CPU mesh — real
multi-device coverage the reference never had in CI (SURVEY.md §4: NCCL
paths needed >=2 physical GPUs)."""

import numpy as np
import pytest

import jax

from singa_tpu import opt, tensor
from singa_tpu import device as device_module
from singa_tpu.models.mlp import MLP
from singa_tpu.parallel.communicator import Communicator, get_mesh
from singa_tpu.parallel.dist_opt import DistOpt


N_DEV = len(jax.devices())
pytestmark = pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")


@pytest.fixture
def dev():
    d = device_module.get_default_device()
    d.SetRandSeed(0)
    return d


def _data(dev, n=32, d_in=8, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d_in).astype(np.float32)
    y = rng.randint(0, classes, (n,)).astype(np.int32)
    return tensor.from_numpy(x, dev), tensor.from_numpy(y, dev)


def _make(dev, optimizer, seed=5, use_graph=True, dist_option="plain",
          spars=None):
    dev.SetRandSeed(seed)
    m = _DistMLP(dist_option, spars)
    m.set_optimizer(optimizer)
    x, _ = _data(dev)
    m.compile([x], is_train=True, use_graph=use_graph)
    return m


class _DistMLP(MLP):
    def __init__(self, dist_option="plain", spars=None):
        super().__init__(data_size=8, perceptron_size=16, num_classes=4)
        self._dist_option = dist_option
        self._spars = spars

    def train_one_batch(self, x, y):
        return super().train_one_batch(x, y, dist_option=self._dist_option,
                                       spars=self._spars)


def test_mesh_world_size():
    comm = Communicator()
    assert comm.world_size == N_DEV


def test_dist_plain_equals_single_device(dev):
    """W-way data parallel with mean-reduced grads == full-batch SGD."""
    x, y = _data(dev, n=32)

    m_single = _make(dev, opt.SGD(lr=0.1), use_graph=True, seed=5)
    m_single.dist = False
    m_single._graph_runner.model = m_single

    m_dist = _make(dev, DistOpt(opt.SGD(lr=0.1)), use_graph=True, seed=5)
    m_dist.set_params({k: v.clone() for k, v in m_single.get_params().items()})

    for i in range(5):
        _, l1 = m_single(x, y)
        _, l2 = m_dist(x, y)
        np.testing.assert_allclose(float(l1.data), float(l2.data), rtol=1e-4,
                                   err_msg=f"step {i}")
    for k, v in m_single.get_params().items():
        np.testing.assert_allclose(
            tensor.to_numpy(v), tensor.to_numpy(m_dist.get_params()[k]),
            rtol=1e-3, atol=1e-5)


def test_dist_output_reassembly(dev):
    x, y = _data(dev, n=16)
    m = _make(dev, DistOpt(opt.SGD(lr=0.05)))
    out, loss = m(x, y)   # warm (eager, world-1 semantics)
    out, loss = m(x, y)   # compiled sharded step
    assert out.shape == (16, 4)
    assert loss.shape == ()
    assert np.isfinite(float(loss.data))


def test_dist_ambiguous_output_raises(dev):
    """A non-batch-leading output (e.g. an (L, B/W, H) RNN state) must
    ERROR under "auto" reassembly with a fix-it message, not silently
    merge the wrong dims (round-2 verdict); an explicit per-leaf spec
    list handles it."""
    from singa_tpu import autograd

    L, H = 3, 6

    class StatefulMLP(_DistMLP):
        def train_one_batch(self, x, y):
            out, loss = super().train_one_batch(x, y)
            b = x.shape[0]
            # fabricate a layer-major (L, b, H) state from the logits
            state = autograd.reshape(
                autograd.matmul(out, tensor.from_numpy(
                    np.ones((4, L * H), np.float32), x.device)),
                (b, L, H))
            state = autograd.transpose(state, (1, 0, 2))
            return out, loss, state

    dev.SetRandSeed(5)
    m = StatefulMLP()
    m.set_optimizer(DistOpt(opt.SGD(lr=0.05)))
    x, y = _data(dev, n=16)
    m.compile([x], is_train=True, use_graph=True)
    with pytest.raises(ValueError, match="dist_outputs"):
        m(x, y)
    # the explicit spec list reassembles it correctly
    m.dist_outputs = ["concat", "mean", "stack"]
    out, loss, state = m(x, y)
    assert out.shape == (16, 4)
    assert loss.shape == ()
    assert state.shape == (N_DEV, L, 16 // N_DEV, H)


def test_dist_bad_batch_divisibility(dev):
    m = _make(dev, DistOpt(opt.SGD(lr=0.05)))
    x, y = _data(dev, n=32)
    m(x, y)  # warm
    x2, y2 = _data(dev, n=30)  # 30 % 8 != 0
    with pytest.raises(ValueError, match="not divisible"):
        m(x2, y2)


def test_dist_fp16_mode_close_to_plain(dev):
    x, y = _data(dev, n=32)
    m_plain = _make(dev, DistOpt(opt.SGD(lr=0.1)), seed=9)
    m_half = _make(dev, DistOpt(opt.SGD(lr=0.1)), seed=9,
                   dist_option="fp16")
    m_half.set_params({k: v.clone() for k, v in m_plain.get_params().items()})
    for _ in range(4):
        _, l1 = m_plain(x, y)
        _, l2 = m_half(x, y)
    # bf16 wire format: close but not bit-equal
    np.testing.assert_allclose(float(l1.data), float(l2.data), rtol=0.05)


def test_dist_partial_update_runs_and_learns(dev):
    x, y = _data(dev, n=32)
    m = _make(dev, DistOpt(opt.SGD(lr=0.1)), dist_option="partialUpdate")
    losses = [float(m(x, y)[1].data) for _ in range(10)]
    assert losses[-1] < losses[0], losses


def test_dist_partial_update_preserves_gradients_over_cycle(dev):
    """Gradient preservation over one FULL W-step round-robin cycle:
    every accumulated gradient is applied exactly once, none scaled,
    none dropped.  For momentum-free SGD(lr) the conservation law is
    exact up to float accumulation:

        P_W - P_0 = sum_t dense_delta(P_t) + lr * (Rbar_W - Rbar_0)

    where ``dense_delta(P_t)`` is the single-device full-batch SGD
    step evaluated at the partial run's OWN parameter trajectory (the
    synced mean-of-shard-means grad IS the full-batch grad) and
    ``Rbar`` is the rank-mean accumulator — the delayed-but-never-
    dropped gradient mass still in flight at the cycle boundary.
    Strictly stronger than "loss went down" (VERDICT weak #5): a mode
    that silently rescaled or dropped off-turn gradients would pass
    the loss test and fail this identity."""
    x, y = _data(dev, n=32)
    lr = 0.1
    W = N_DEV

    m = _make(dev, DistOpt(opt.SGD(lr=lr)),
              dist_option="partialUpdate", seed=5)
    # oracle for the per-step dense full-batch delta
    m_ref = _make(dev, opt.SGD(lr=lr), use_graph=True, seed=5)
    m_ref.dist = False
    m_ref._graph_runner.model = m_ref

    m(x, y)   # warm step: eager world-1 semantics, residuals zeroed

    def params_np():
        return {k: tensor.to_numpy(v).copy()
                for k, v in m.get_params().items()}

    def residual_mean_np():
        out = {}
        for k, t in m.optimizer.state_tensors().items():
            if k.startswith("__residual__"):
                out[k[len("__residual__"):]] = \
                    tensor.to_numpy(t).mean(axis=0)
        return out

    p0 = params_np()
    r0 = residual_mean_np()
    assert set(r0) == set(p0), "residual accumulators missing params"
    dense_sum = {k: np.zeros_like(v, np.float64) for k, v in p0.items()}
    for _ in range(W):
        before = params_np()
        m_ref.set_params({k: tensor.from_numpy(v, dev)
                          for k, v in before.items()})
        m_ref(x, y)
        for k, v in m_ref.get_params().items():
            dense_sum[k] += (tensor.to_numpy(v).astype(np.float64)
                             - before[k])
        m(x, y)
    p1 = params_np()
    r1 = residual_mean_np()
    for k in sorted(p0):
        applied = p1[k].astype(np.float64) - p0[k]
        want = dense_sum[k] + lr * (r1[k].astype(np.float64) - r0[k])
        np.testing.assert_allclose(applied, want, rtol=5e-3, atol=5e-5,
                                   err_msg=k)
    # the identity must be tested with real in-flight mass: at the
    # cycle boundary at least one accumulator is non-trivial (every
    # param synced once, but off-turn grads since then accumulated)
    assert any(np.abs(r1[k]).max() > 1e-8 for k in r1), \
        "accumulators empty — the residual term tested nothing"


def test_dist_sparse_topk_full_density_equals_plain(dev):
    """spars=1.0 topK sparse sync must equal dense all-reduce."""
    x, y = _data(dev, n=32)
    m_plain = _make(dev, DistOpt(opt.SGD(lr=0.1)), seed=11)
    m_sparse = _make(dev, DistOpt(opt.SGD(lr=0.1)), seed=11,
                     dist_option="sparseTopK", spars=1.0)
    m_sparse.set_params({k: v.clone() for k, v in m_plain.get_params().items()})
    for i in range(4):
        _, l1 = m_plain(x, y)
        _, l2 = m_sparse(x, y)
        np.testing.assert_allclose(float(l1.data), float(l2.data), rtol=1e-3,
                                   err_msg=f"step {i}")


def test_dist_sparse_topk_low_density_learns(dev):
    x, y = _data(dev, n=32)
    m = _make(dev, DistOpt(opt.SGD(lr=0.2)), dist_option="sparseTopK",
              spars=0.1)
    losses = [float(m(x, y)[1].data) for _ in range(12)]
    assert losses[-1] < losses[0], losses
    # residual state exists and is threaded through the compiled step
    res = [k for k in m.optimizer.state_tensors() if k.startswith("__residual__")]
    assert res, "no residual accumulators created"


def test_dist_sparse_threshold_learns(dev):
    x, y = _data(dev, n=32)
    m = _make(dev, DistOpt(opt.SGD(lr=0.2)), dist_option="sparseThreshold",
              spars=0.001)
    losses = [float(m(x, y)[1].data) for _ in range(8)]
    assert losses[-1] < losses[0], losses


def test_communicator_eager_world1_identity(dev):
    """Outside the compiled step, collectives are world-1 identities."""
    comm = Communicator()
    import jax.numpy as jnp

    a = jnp.ones((4,))
    np.testing.assert_array_equal(np.asarray(comm.all_reduce(a)), np.ones(4))
    np.testing.assert_array_equal(
        np.asarray(comm.synch_half(a, average=True)), np.ones(4))
    s, r = comm.sparse_all_reduce(a, jnp.zeros((4,)), spars=0.5, topK=True)
    np.testing.assert_allclose(np.asarray(s) + np.asarray(r), np.ones(4))


def test_dist_sparse_residuals_stay_per_rank(dev):
    """Each rank's untransmitted gradient mass must survive in its own
    accumulator slice — a collapsed (replicated) residual would show
    identical slices across ranks."""
    x, y = _data(dev, n=32)
    m = _make(dev, DistOpt(opt.SGD(lr=0.1)), dist_option="sparseTopK",
              spars=0.05)
    for _ in range(4):
        m(x, y)
    res = {k: v for k, v in m.optimizer.state_tensors().items()
           if k.startswith("__residual__")}
    assert res
    distinct = False
    for k, t in res.items():
        arr = tensor.to_numpy(t)
        assert arr.shape[0] == N_DEV  # (world, *param_shape)
        if not all(np.allclose(arr[0], arr[r]) for r in range(1, N_DEV)):
            distinct = True
    assert distinct, "rank accumulator slices are identical — state collapsed"


def test_dist_partial_update_accumulators_differ_per_rank(dev):
    x, y = _data(dev, n=32)
    m = _make(dev, DistOpt(opt.SGD(lr=0.1)), dist_option="partialUpdate")
    for _ in range(3):
        m(x, y)
    res = {k: v for k, v in m.optimizer.state_tensors().items()
           if k.startswith("__residual__")}
    assert res
    arrs = [tensor.to_numpy(t) for t in res.values()]
    assert any(
        not all(np.allclose(a[0], a[r]) for r in range(1, N_DEV))
        for a in arrs
    ), "partial-update accumulators collapsed across ranks"


def test_dist_bn_running_stats_pmeaned(dev):
    """BN running stats under dist graph mode must be finite and move —
    and come back well-defined (pmean across ranks)."""
    from singa_tpu.models.cnn import CNN
    from singa_tpu.models.common import apply_dist_option

    dev.SetRandSeed(0)
    rng = np.random.RandomState(0)
    x = tensor.from_numpy(rng.randn(16, 1, 12, 12).astype(np.float32), dev)
    y = tensor.from_numpy(rng.randint(0, 10, (16,)).astype(np.int32), dev)

    class BNNet(CNN):
        pass

    import singa_tpu.layer as L

    class Net(__import__("singa_tpu.model", fromlist=["Model"]).Model):
        def __init__(self):
            super().__init__()
            self.conv = L.Conv2d(4, 3, padding=1)
            self.bn = L.BatchNorm2d()
            self.flat = L.Flatten()
            self.fc = L.Linear(10)
            self.ce = L.SoftMaxCrossEntropy()

        def forward(self, x):
            return self.fc(self.flat(self.bn(self.conv(x))))

        def train_one_batch(self, x, y):
            out = self.forward(x)
            loss = self.ce(out, y)
            apply_dist_option(self.optimizer, loss, "plain", None)
            return out, loss

    m = Net()
    m.set_optimizer(DistOpt(opt.SGD(lr=0.05)))
    m.compile([x], is_train=True, use_graph=True)
    for _ in range(3):
        m(x, y)
    rm = [v for k, v in m.get_states().items() if k.endswith("running_mean")]
    assert rm
    arr = tensor.to_numpy(rm[0])
    assert np.all(np.isfinite(arr)) and np.abs(arr).max() > 0


def test_dist_option_switch_after_compile(dev):
    """Switching dist-option mid-training (plain -> sparse) creates new
    optimizer state AFTER the first warm-up; that state must be
    materialized per step signature, not left holding dead tracers
    (regression: _GraphRunner warmed only once)."""
    from singa_tpu.models.common import apply_dist_option
    import singa_tpu.layer as L

    class Net(__import__("singa_tpu.model", fromlist=["Model"]).Model):
        def __init__(self):
            super().__init__()
            self.fc = L.Linear(4)
            self.ce = L.SoftMaxCrossEntropy()

        def forward(self, x):
            return self.fc(x)

        def train_one_batch(self, x, y, dist_option="plain", spars=None):
            out = self.forward(x)
            loss = self.ce(out, y)
            apply_dist_option(self.optimizer, loss, dist_option, spars)
            return out, loss

    rng = np.random.RandomState(0)
    x = tensor.from_numpy(rng.randn(8, 6).astype(np.float32), dev)
    y = tensor.from_numpy(rng.randint(0, 4, 8).astype(np.int32), dev)
    m = Net()
    m.set_optimizer(DistOpt(opt.SGD(lr=0.1)))
    m.compile([x], is_train=True, use_graph=True)
    l0 = float(tensor.to_numpy(m(x, y)[1]))
    # mode switch: creates sparse residual state post-compile
    for _ in range(2):
        _, loss = m(x, y, dist_option="sparseTopK", spars=0.2)
    l1 = float(tensor.to_numpy(loss))
    assert np.isfinite(l0) and np.isfinite(l1)
    res = [k for k in m.persistent_tensors() if "__residual__" in k]
    assert res
    for k in res:
        arr = tensor.to_numpy(m.persistent_tensors()[k])
        assert np.all(np.isfinite(arr))


def test_dist_clip_norm_equals_single_device_oracle(dev):
    """Global-norm clipping under DistOpt (dense sync): the clip runs
    between sync and apply over the synced (= full-batch) grads, so a
    W-way data-parallel clipped run must track the single-device
    clipped oracle — the round-5 clip feature finally crossing the
    distributed boundary (VERDICT weak #4).  clip_norm is tiny enough
    that the scale is ACTIVE every step (an inactive clip would pass
    this test without testing anything)."""
    x, y = _data(dev, n=32)
    clip = 0.05  # MLP grads here have norm >> 0.05: always clipping

    m_single = _make(dev, opt.SGD(lr=0.5, clip_norm=clip),
                     use_graph=True, seed=5)
    m_single.dist = False
    m_single._graph_runner.model = m_single

    m_dist = _make(dev, DistOpt(opt.SGD(lr=0.5, clip_norm=clip)),
                   use_graph=True, seed=5)
    m_dist.set_params({k: v.clone()
                       for k, v in m_single.get_params().items()})

    for i in range(5):
        _, l1 = m_single(x, y)
        _, l2 = m_dist(x, y)
        np.testing.assert_allclose(float(l1.data), float(l2.data),
                                   rtol=1e-4, err_msg=f"step {i}")
    for k, v in m_single.get_params().items():
        np.testing.assert_allclose(
            tensor.to_numpy(v), tensor.to_numpy(m_dist.get_params()[k]),
            rtol=1e-3, atol=1e-5, err_msg=k)
    # the clip really fired: an unclipped dist run diverges from this one
    m_unclipped = _make(dev, DistOpt(opt.SGD(lr=0.5)), seed=5)
    m_unclipped.set_params({k: v.clone()
                            for k, v in m_single.get_params().items()})
    m_unclipped(x, y)
    m_dist(x, y)
    diverged = any(
        not np.allclose(tensor.to_numpy(m_unclipped.get_params()[k]),
                        tensor.to_numpy(m_dist.get_params()[k]),
                        rtol=1e-5)
        for k in m_single.get_params())
    assert diverged, "clip_norm had no effect on the dist update"


def test_dist_clip_norm_fp16_mode_close_to_oracle(dev):
    """bf16-wire sync with clip_norm: the clip is computed in f32 over
    the post-sync grads, so the run tracks the single-device clipped
    oracle within wire-precision noise (same tolerance as the
    unclipped fp16 equivalence test)."""
    x, y = _data(dev, n=32)
    clip = 0.05
    m_plain = _make(dev, opt.SGD(lr=0.5, clip_norm=clip),
                    use_graph=True, seed=9)
    m_plain.dist = False
    m_plain._graph_runner.model = m_plain
    m_half = _make(dev, DistOpt(opt.SGD(lr=0.5, clip_norm=clip)),
                   seed=9, dist_option="fp16")
    m_half.set_params({k: v.clone()
                       for k, v in m_plain.get_params().items()})
    for _ in range(4):
        _, l1 = m_plain(x, y)
        _, l2 = m_half(x, y)
    np.testing.assert_allclose(float(l1.data), float(l2.data),
                               rtol=0.05)


def test_dist_clip_norm_refused_for_partial_and_sparse(dev):
    """Partial/sparse modes sync PARTIAL gradient information per step
    — no per-step global norm exists, so they refuse clip_norm with a
    pointer at the modes that support it."""
    x, y = _data(dev, n=32)
    for mode, spars in (("partialUpdate", None), ("sparseTopK", 0.1)):
        m = _make(dev, DistOpt(opt.SGD(lr=0.1, clip_norm=1.0)),
                  dist_option=mode, spars=spars)
        with pytest.raises(ValueError, match="clip_norm"):
            m(x, y)


def test_dist_train_n_batches_equals_single_steps(dev):
    """Multi-step dispatch (scan over the shard_map'd step) ≡ K
    separate dist dispatches (round-5 verdict item #1)."""
    k = 3
    m1 = _make(dev, DistOpt(opt.SGD(lr=0.1)), seed=21)
    m2 = _make(dev, DistOpt(opt.SGD(lr=0.1)), seed=21)
    m2.set_params({n: v.clone() for n, v in m1.get_params().items()})
    rng = np.random.RandomState(4)
    xs = rng.randn(k, 32, 8).astype(np.float32)
    ys = rng.randint(0, 4, (k, 32)).astype(np.int32)

    singles = []
    for i in range(k):
        _, loss = m1(tensor.from_numpy(xs[i], dev),
                     tensor.from_numpy(ys[i], dev))
        singles.append(float(loss.data))

    out, losses = m2.train_n_batches(tensor.from_numpy(xs, dev),
                                     tensor.from_numpy(ys, dev))
    assert tuple(out.shape) == (k, 32, 4)  # auto-merged per-rank batches
    np.testing.assert_allclose(np.asarray(losses.data), singles, rtol=2e-5)
    for n, v in m1.get_params().items():
        np.testing.assert_allclose(
            tensor.to_numpy(v), tensor.to_numpy(m2.get_params()[n]),
            rtol=1e-4, atol=1e-6, err_msg=n)
