"""sonnx ONNX import/export tests (reference: test/python/test_onnx.py +
test_onnx_backend.py, unverified)."""

import numpy as np
import pytest

from singa_tpu import autograd, opt, sonnx, tensor
from singa_tpu import device as device_module
from singa_tpu.io import onnx_pb
from singa_tpu.models.mlp import MLP
from singa_tpu.models.cnn import CNN


@pytest.fixture
def dev():
    d = device_module.get_default_device()
    d.SetRandSeed(0)
    return d


def test_onnx_pb_roundtrip():
    """Wire-format serialize -> parse identity for every message type."""
    w = onnx_pb.TensorProto.from_numpy(
        np.arange(12, dtype=np.float32).reshape(3, 4), "w")
    node = onnx_pb.NodeProto(
        op_type="Gemm", name="g0", input=["x", "w"], output=["y"],
        attribute=[onnx_pb.AttributeProto.make("alpha", 2.0),
                   onnx_pb.AttributeProto.make("transB", 1),
                   onnx_pb.AttributeProto.make("pads", [1, 2, 1, 2]),
                   onnx_pb.AttributeProto.make("mode", "test")])
    g = onnx_pb.GraphProto(
        name="g", node=[node], initializer=[w],
        input=[onnx_pb.ValueInfoProto("x", onnx_pb.FLOAT, [2, 3])],
        output=[onnx_pb.ValueInfoProto("y", onnx_pb.FLOAT, [2, 4])])
    m = onnx_pb.ModelProto(graph=g)
    blob = m.serialize()
    m2 = onnx_pb.ModelProto.parse(blob)
    assert m2.producer_name == "singa_tpu"
    n2 = m2.graph.node[0]
    assert n2.op_type == "Gemm" and n2.input == ["x", "w"]
    a = n2.attrs()
    assert a["alpha"] == pytest.approx(2.0)
    assert a["transB"] == 1
    assert a["pads"] == [1, 2, 1, 2]
    assert a["mode"] == "test"
    np.testing.assert_array_equal(
        m2.graph.initializer[0].to_numpy(),
        np.arange(12, dtype=np.float32).reshape(3, 4))
    assert m2.graph.input[0].shape == [2, 3]


def test_mlp_export_import_roundtrip(dev, tmp_path):
    m = MLP(data_size=6, perceptron_size=8, num_classes=3)
    x = tensor.from_numpy(np.random.RandomState(0).randn(4, 6).astype(np.float32), dev)
    m.compile([x], is_train=False, use_graph=False)
    native = tensor.to_numpy(m.forward(x))

    proto = sonnx.to_onnx(m, [x])
    path = str(tmp_path / "mlp.onnx")
    sonnx.save(proto, path)

    rep = sonnx.prepare(path, dev)
    (out,) = rep.run([x])
    np.testing.assert_allclose(tensor.to_numpy(out), native, rtol=1e-5,
                               atol=1e-6)


def test_cnn_export_import_roundtrip(dev, tmp_path):
    m = CNN(num_classes=10, num_channels=1)
    x = tensor.from_numpy(
        np.random.RandomState(1).randn(2, 1, 28, 28).astype(np.float32), dev)
    m.compile([x], is_train=False, use_graph=False)
    native = tensor.to_numpy(m.forward(x))

    proto = sonnx.to_onnx(m, [x])
    path = str(tmp_path / "cnn.onnx")
    sonnx.save(proto, path)
    rep = sonnx.prepare(path, dev)
    (out,) = rep.run([x])
    np.testing.assert_allclose(tensor.to_numpy(out), native, rtol=1e-4,
                               atol=1e-5)


def test_imported_model_is_trainable(dev, tmp_path):
    """SONNXModel: import an exported MLP and train it (reference
    SONNXModel semantics — imported graphs are differentiable)."""
    m = MLP(data_size=6, perceptron_size=8, num_classes=3)
    x = tensor.from_numpy(np.random.RandomState(0).randn(16, 6).astype(np.float32), dev)
    y = tensor.from_numpy(np.random.RandomState(0).randint(0, 3, (16,)).astype(np.int32), dev)
    m.compile([x], is_train=False, use_graph=False)
    proto = sonnx.to_onnx(m, [x])

    class Trainable(sonnx.SONNXModel):
        def train_one_batch(self, x, y):
            out = self.forward(x)
            loss = autograd.softmax_cross_entropy(out, y)
            self.optimizer(loss)
            return out, loss

    tm = Trainable(proto, dev)
    tm.set_optimizer(opt.SGD(lr=0.1))
    tm.train(True)
    losses = [float(tm.train_one_batch(x, y)[1].data) for _ in range(10)]
    assert losses[-1] < losses[0], losses


def test_unsupported_op_reports_name(dev):
    g = onnx_pb.GraphProto(
        name="g",
        node=[onnx_pb.NodeProto(op_type="FancyOp", input=["x"], output=["y"])],
        input=[onnx_pb.ValueInfoProto("x", onnx_pb.FLOAT, [1])],
        output=[onnx_pb.ValueInfoProto("y", onnx_pb.FLOAT, [1])])
    rep = sonnx.prepare(onnx_pb.ModelProto(graph=g), dev)
    with pytest.raises(NotImplementedError, match="FancyOp"):
        rep.run([np.zeros((1,), np.float32)])


def test_handlers_cover_bert_oplist():
    """Ops appearing in a standard BERT-base ONNX graph must all have
    handlers."""
    bert_ops = ["Add", "Cast", "Concat", "Constant", "ConstantOfShape",
                "Div", "Erf", "Gather", "Identity", "MatMul", "Mul",
                "Pow", "ReduceMean", "Reshape", "Shape", "Slice",
                "Softmax", "Sqrt", "Sub", "Tanh", "Transpose",
                "Unsqueeze", "Where", "Expand", "Equal",
                "LayerNormalization", "Gemm"]
    missing = [o for o in bert_ops if o not in sonnx._ONNX_OPS]
    assert not missing, missing


def test_layernorm_export_preserves_eps(dev, tmp_path):
    """Exported LayerNormalization must carry epsilon/axis attributes and
    import back with the same numerics."""
    from singa_tpu import layer, model

    class M(model.Model):
        def __init__(self):
            super().__init__()
            self.ln = layer.LayerNorm(eps=1e-12)

        def forward(self, x):
            return self.ln(x)

    m = M()
    x = tensor.from_numpy(
        np.random.RandomState(0).randn(2, 8).astype(np.float32) * 100, dev)
    m.compile([x], is_train=False, use_graph=False)
    native = tensor.to_numpy(m.forward(x))
    proto = sonnx.to_onnx(m, [x])
    ln_nodes = [n for n in proto.graph.node
                if n.op_type == "LayerNormalization"]
    assert ln_nodes and ln_nodes[0].attrs()["epsilon"] == pytest.approx(1e-12)
    rep = sonnx.prepare(proto, dev)
    (out,) = rep.run([x])
    np.testing.assert_allclose(tensor.to_numpy(out), native, rtol=1e-5,
                               atol=1e-6)


def test_same_pool_export_roundtrip(dev, tmp_path):
    """SAME pooling with asymmetric effective pads must round-trip."""
    from singa_tpu import autograd as ag
    from singa_tpu.ops import pooling as pool_ops

    x_np = np.random.RandomState(2).randn(1, 1, 5, 5).astype(np.float32)
    x = tensor.from_numpy(x_np, dev)
    ag.set_training(True)
    try:
        y = pool_ops.pooling2d(x, kernel=(2, 2), stride=(2, 2),
                               is_max=True, pad_mode="SAME_UPPER")
        assert y.shape == (1, 1, 3, 3)
        op = y.creator
        pairs = op.params["pads_pairs"]
        assert pairs == ((0, 1), (0, 1))
    finally:
        ag.set_training(False)


def _graph_model(nodes, initializers, inputs, outputs):
    g = onnx_pb.GraphProto(name="t", node=nodes, initializer=initializers,
                           input=inputs, output=outputs)
    return onnx_pb.ModelProto(graph=g)


def test_foreign_onnx_bytes_fixture(dev):
    """Parse + run an ONNX file whose bytes were written by an
    independent encoder (tests/fixtures/make_foreign_onnx.py), i.e. NOT
    the vendored codec — simulating a file produced by another tool."""
    import os
    fdir = os.path.join(os.path.dirname(__file__), "fixtures")
    with open(os.path.join(fdir, "foreign_gemm.onnx"), "rb") as f:
        blob = f.read()
    model = onnx_pb.load_model(blob)
    assert model.producer_name == "foreign_tool"
    assert model.graph.node[0].op_type == "Gemm"

    io = np.load(os.path.join(fdir, "foreign_gemm_io.npz"))
    rep = sonnx.prepare(blob, dev)
    (out,) = rep.run([tensor.from_numpy(io["x"], dev)])
    np.testing.assert_allclose(tensor.to_numpy(out), io["y"], rtol=1e-5,
                               atol=1e-6)


def test_asymmetric_conv_pads_import(dev):
    """ONNX Conv with asymmetric pads [0,0,1,1] must import exactly."""
    from jax import lax

    rng = np.random.RandomState(3)
    x = rng.randn(1, 2, 6, 6).astype(np.float32)
    W = rng.randn(3, 2, 3, 3).astype(np.float32)
    node = onnx_pb.NodeProto(
        op_type="Conv", name="c", input=["x", "W"], output=["y"],
        attribute=[onnx_pb.AttributeProto.make("kernel_shape", [3, 3]),
                   onnx_pb.AttributeProto.make("pads", [0, 0, 1, 1]),
                   onnx_pb.AttributeProto.make("strides", [1, 1])])
    model = _graph_model(
        [node], [onnx_pb.TensorProto.from_numpy(W, "W")],
        [onnx_pb.ValueInfoProto("x", onnx_pb.FLOAT, [1, 2, 6, 6])],
        [onnx_pb.ValueInfoProto("y", onnx_pb.FLOAT, [1, 3, 5, 5])])
    rep = sonnx.prepare(model, dev)
    (out,) = rep.run([tensor.from_numpy(x, dev)])
    ref = lax.conv_general_dilated(
        x, W, (1, 1), ((0, 1), (0, 1)),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    np.testing.assert_allclose(tensor.to_numpy(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_strided_same_pads_onnx_semantics(dev):
    """SAME_UPPER with stride: in=8,k=3,s=2 -> ONNX pads (0,1), not (1,1)
    (ADVICE r01: stride/input-size were ignored)."""
    from singa_tpu.ops.padding import same_pads
    assert same_pads((8, 8), (3, 3), (2, 2)) == ((0, 1), (0, 1))
    assert same_pads((8, 8), (3, 3), (2, 2), lower=True) == ((1, 0), (1, 0))
    assert same_pads((5, 5), (2, 2), (2, 2)) == ((0, 1), (0, 1))
    # and the conv output really uses them: out spatial = ceil(8/2) = 4
    from singa_tpu.ops.conv import conv2d
    rng = np.random.RandomState(4)
    x = tensor.from_numpy(rng.randn(1, 1, 8, 8).astype(np.float32), dev)
    W = tensor.from_numpy(rng.randn(1, 1, 3, 3).astype(np.float32), dev)
    y = conv2d(x, W, stride=(2, 2), pad_mode="SAME_UPPER")
    assert y.shape == (1, 1, 4, 4)


def test_pad_mode_and_constant_value_input(dev):
    """ONNX Pad: opset>=11 pad value rides input #3; reflect mode works;
    unknown modes raise (ADVICE r01: both were silently wrong)."""
    x_np = np.arange(6, dtype=np.float32).reshape(2, 3)

    def pad_model(attrs, n_inputs):
        names = ["x", "pads", "cval"][:n_inputs]
        node = onnx_pb.NodeProto(op_type="Pad", name="p", input=names,
                                 output=["y"], attribute=attrs)
        return _graph_model(
            [node], [],
            [onnx_pb.ValueInfoProto(n, onnx_pb.FLOAT, []) for n in names],
            [onnx_pb.ValueInfoProto("y", onnx_pb.FLOAT, [])])

    pads = tensor.from_numpy(np.array([0, 1, 0, 1], np.int64), dev)
    cval = tensor.from_numpy(np.array([7.5], np.float32), dev)
    x = tensor.from_numpy(x_np, dev)

    rep = sonnx.prepare(pad_model([], 3), dev)
    (out,) = rep.run({"x": x, "pads": pads, "cval": cval})
    np.testing.assert_array_equal(
        tensor.to_numpy(out), np.pad(x_np, ((0, 0), (1, 1)),
                                     constant_values=7.5))

    rep = sonnx.prepare(
        pad_model([onnx_pb.AttributeProto.make("mode", "reflect")], 2), dev)
    (out,) = rep.run({"x": x, "pads": pads})
    np.testing.assert_array_equal(
        tensor.to_numpy(out), np.pad(x_np, ((0, 0), (1, 1)), mode="reflect"))

    rep = sonnx.prepare(
        pad_model([onnx_pb.AttributeProto.make("mode", "wrap")], 2), dev)
    with pytest.raises(NotImplementedError):
        rep.run({"x": x, "pads": pads})


def test_constant_handlers_use_rep_device():
    """Constant/Shape/Range outputs must land on the rep's device, not
    the default device (ADVICE r01 medium)."""
    import jax
    cpus = jax.devices("cpu")
    if len(cpus) < 2:
        pytest.skip("needs >=2 devices to distinguish placement")
    dev1 = device_module.CppCPU(1)
    cval = onnx_pb.TensorProto.from_numpy(np.float32(3.0).reshape(()), "c")
    nodes = [
        onnx_pb.NodeProto(op_type="Constant", name="k", input=[],
                          output=["c"],
                          attribute=[onnx_pb.AttributeProto.make("value",
                                                                 cval)]),
        onnx_pb.NodeProto(op_type="Shape", name="s", input=["x"],
                          output=["shp"]),
    ]
    model = _graph_model(
        nodes, [],
        [onnx_pb.ValueInfoProto("x", onnx_pb.FLOAT, [2, 3])],
        [onnx_pb.ValueInfoProto("c", onnx_pb.FLOAT, []),
         onnx_pb.ValueInfoProto("shp", onnx_pb.INT64, [2])])
    rep = sonnx.prepare(model, dev1)
    x = tensor.from_numpy(np.zeros((2, 3), np.float32), dev1)
    c, shp = rep.run([x])
    for out in (c, shp):
        (d,) = out.data.devices()
        assert d == dev1.jax_device, (d, dev1.jax_device)


def test_negative_pads_crop(dev):
    """Negative ONNX pads crop that edge (legal per spec)."""
    x_np = np.arange(16, dtype=np.float32).reshape(4, 4)
    node = onnx_pb.NodeProto(op_type="Pad", name="p", input=["x", "pads"],
                             output=["y"])
    model = _graph_model(
        [node], [],
        [onnx_pb.ValueInfoProto("x", onnx_pb.FLOAT, []),
         onnx_pb.ValueInfoProto("pads", onnx_pb.INT64, [])],
        [onnx_pb.ValueInfoProto("y", onnx_pb.FLOAT, [])])
    rep = sonnx.prepare(model, dev)
    pads = tensor.from_numpy(np.array([0, -1, 0, -1], np.int64), dev)
    (out,) = rep.run({"x": tensor.from_numpy(x_np, dev), "pads": pads})
    np.testing.assert_array_equal(tensor.to_numpy(out), x_np[:, 1:3])


def test_export_grad_free_graph(dev, tmp_path):
    """Export must work when no tensor requires grad (frozen model):
    the tape records creator edges for no-grad inputs too."""
    m = MLP(data_size=6, perceptron_size=8, num_classes=3)
    x = tensor.from_numpy(
        np.random.RandomState(5).randn(4, 6).astype(np.float32), dev)
    m.compile([x], is_train=False, use_graph=False)
    for p in m.get_params().values():
        p.requires_grad = False
        p.stores_grad = False
    native = tensor.to_numpy(m.forward(x))

    proto = sonnx.to_onnx(m, [x])
    rep = sonnx.prepare(proto, dev)
    (out,) = rep.run([x])
    np.testing.assert_allclose(tensor.to_numpy(out), native, rtol=1e-5,
                               atol=1e-6)


def _if_model(then_delta=1.0, else_delta=-1.0):
    """If node whose branches capture the outer input x: x+d or x-d."""
    def branch(tag, op, d):
        return onnx_pb.GraphProto(
            name=tag,
            node=[onnx_pb.NodeProto(op_type=op, name=f"{tag}_n",
                                    input=["x", f"{tag}_c"],
                                    output=[f"{tag}_y"])],
            initializer=[onnx_pb.TensorProto.from_numpy(
                np.full((2, 3), d, np.float32), f"{tag}_c")],
            output=[onnx_pb.ValueInfoProto(f"{tag}_y", onnx_pb.FLOAT,
                                           [2, 3])])

    node = onnx_pb.NodeProto(
        op_type="If", name="if0", input=["cond"], output=["y"],
        attribute=[
            onnx_pb.AttributeProto.make(
                "then_branch", branch("t", "Add", then_delta)),
            onnx_pb.AttributeProto.make(
                "else_branch", branch("e", "Add", else_delta))])
    return _graph_model(
        [node], [],
        [onnx_pb.ValueInfoProto("cond", onnx_pb.BOOL, []),
         onnx_pb.ValueInfoProto("x", onnx_pb.FLOAT, [2, 3])],
        [onnx_pb.ValueInfoProto("y", onnx_pb.FLOAT, [2, 3])])


def test_if_traced_condition_lowers_to_lax_cond(dev):
    """A data-dependent If condition under jit cannot Python-branch;
    the handler must lower to lax.cond — both branches traced, the
    runtime value selecting between them, gradients flowing."""
    import jax
    import jax.numpy as jnp

    rep = sonnx.prepare(_if_model(), dev)
    x_np = np.random.RandomState(0).randn(2, 3).astype(np.float32)

    def f(c_arr, x_arr):
        c = tensor._wrap(c_arr, dev)
        x = tensor._wrap(x_arr, dev)
        (y,) = rep.run({"cond": c, "x": x})
        return y.data

    jf = jax.jit(f)
    y_true = np.asarray(jf(jnp.asarray(True), jnp.asarray(x_np)))
    y_false = np.asarray(jf(jnp.asarray(False), jnp.asarray(x_np)))
    np.testing.assert_allclose(y_true, x_np + 1.0, rtol=1e-6)
    np.testing.assert_allclose(y_false, x_np - 1.0, rtol=1e-6)
    # the SAME jitted executable serves both conditions (it would have
    # been a retrace/assert error if the handler Python-branched)
    g = jax.grad(lambda c, x: jnp.sum(f(c, x)), argnums=1)(
        jnp.asarray(True), jnp.asarray(x_np))
    np.testing.assert_allclose(np.asarray(g), np.ones_like(x_np),
                               rtol=1e-6)


def test_if_subgraph_wire_roundtrip(dev):
    """GraphProto attributes (field 6) survive serialize -> parse, and
    the reloaded model still executes both branches."""
    blob = _if_model().serialize()
    rep = sonnx.prepare(bytes(blob), dev)
    x_np = np.ones((2, 3), np.float32)
    (y,) = rep.run({"cond": tensor.from_numpy(np.asarray(True), dev),
                    "x": tensor.from_numpy(x_np, dev)})
    np.testing.assert_allclose(tensor.to_numpy(y), x_np + 1.0)
    (y,) = rep.run({"cond": tensor.from_numpy(np.asarray(False), dev),
                    "x": tensor.from_numpy(x_np, dev)})
    np.testing.assert_allclose(tensor.to_numpy(y), x_np - 1.0)


def test_loop_gradient_flows(dev):
    """Backward through an unrolled Loop: y = v0 + 3*x ->
    dy/dx = 3 (per element)."""
    body = onnx_pb.GraphProto(
        name="body",
        node=[onnx_pb.NodeProto(op_type="Add", name="b",
                                input=["v_in", "x"], output=["v_out"]),
              onnx_pb.NodeProto(op_type="Identity", name="c",
                                input=["cond_in"], output=["cond_out"])],
        input=[onnx_pb.ValueInfoProto("it", onnx_pb.INT64, []),
               onnx_pb.ValueInfoProto("cond_in", onnx_pb.BOOL, []),
               onnx_pb.ValueInfoProto("v_in", onnx_pb.FLOAT, [2, 3])],
        output=[onnx_pb.ValueInfoProto("cond_out", onnx_pb.BOOL, []),
                onnx_pb.ValueInfoProto("v_out", onnx_pb.FLOAT, [2, 3])])
    node = onnx_pb.NodeProto(
        op_type="Loop", name="loop0", input=["M", "keep", "v0"],
        output=["vf"],
        attribute=[onnx_pb.AttributeProto.make("body", body)])
    model = _graph_model(
        [node], [],
        [onnx_pb.ValueInfoProto("M", onnx_pb.INT64, []),
         onnx_pb.ValueInfoProto("keep", onnx_pb.BOOL, []),
         onnx_pb.ValueInfoProto("v0", onnx_pb.FLOAT, [2, 3]),
         onnx_pb.ValueInfoProto("x", onnx_pb.FLOAT, [2, 3])],
        [onnx_pb.ValueInfoProto("vf", onnx_pb.FLOAT, [2, 3])])
    rep = sonnx.prepare(model, dev)
    x = tensor.from_numpy(np.ones((2, 3), np.float32), dev)
    x.requires_grad = True
    x.stores_grad = True
    v0 = tensor.from_numpy(np.zeros((2, 3), np.float32), dev)
    autograd.set_training(True)
    try:
        (vf,) = rep.run({"M": tensor.from_numpy(np.asarray(3, np.int64),
                                                dev),
                         "keep": tensor.from_numpy(np.asarray(True), dev),
                         "v0": v0, "x": x})
        np.testing.assert_allclose(tensor.to_numpy(vf),
                                   3.0 * np.ones((2, 3)))
        loss = autograd.reduce_mean(vf)
        grads = {t: g for t, g in autograd.backward(loss)}
        (gx,) = [g for t, g in grads.items() if t is x]
        np.testing.assert_allclose(tensor.to_numpy(gx),
                                   np.full((2, 3), 3.0 / 6.0))
    finally:
        autograd.set_training(False)


def test_if_branch_initializer_shadows_outer_name(dev):
    """ONNX scoping: a subgraph's OWN initializer shadows an outer value
    of the same name — the branch must use its local constant, not the
    enclosing graph's tensor."""
    def branch(tag, d):
        return onnx_pb.GraphProto(
            name=tag,
            node=[onnx_pb.NodeProto(op_type="Add", name=f"{tag}_n",
                                    input=["x", "c"],  # "c" is LOCAL
                                    output=[f"{tag}_y"])],
            initializer=[onnx_pb.TensorProto.from_numpy(
                np.full((2, 3), d, np.float32), "c")],
            output=[onnx_pb.ValueInfoProto(f"{tag}_y", onnx_pb.FLOAT,
                                           [2, 3])])

    node = onnx_pb.NodeProto(
        op_type="If", name="if0", input=["cond"], output=["y"],
        attribute=[onnx_pb.AttributeProto.make("then_branch",
                                               branch("t", 5.0)),
                   onnx_pb.AttributeProto.make("else_branch",
                                               branch("e", -5.0))])
    model = _graph_model(
        [node], [],
        [onnx_pb.ValueInfoProto("cond", onnx_pb.BOOL, []),
         onnx_pb.ValueInfoProto("x", onnx_pb.FLOAT, [2, 3]),
         onnx_pb.ValueInfoProto("c", onnx_pb.FLOAT, [2, 3])],  # outer "c"
        [onnx_pb.ValueInfoProto("y", onnx_pb.FLOAT, [2, 3])])
    rep = sonnx.prepare(model, dev)
    x_np = np.zeros((2, 3), np.float32)
    outer_c = np.full((2, 3), 100.0, np.float32)  # must NOT be used
    (y,) = rep.run({"cond": tensor.from_numpy(np.asarray(True), dev),
                    "x": tensor.from_numpy(x_np, dev),
                    "c": tensor.from_numpy(outer_c, dev)})
    np.testing.assert_allclose(tensor.to_numpy(y), np.full((2, 3), 5.0))


@pytest.mark.slow
def test_imported_bn_model_trains_in_graph_mode(dev):
    """Imported BatchNormalization mean/var are mutable training state:
    they must ride rep.weights (tracked by persistent_tensors) or graph
    mode compiles a step whose arity disagrees with the replay call
    (regression: 'Computation compiled for N inputs but called with M')."""
    from singa_tpu import layer as layer_mod
    from singa_tpu.models.mobilenet import mobilenet_v2

    m = mobilenet_v2(num_classes=10, width_mult=0.25)
    x = tensor.from_numpy(
        np.random.RandomState(0).randn(2, 3, 32, 32).astype(np.float32),
        dev)
    m.compile([x], is_train=False, use_graph=False)
    m.eval()
    proto = sonnx.to_onnx(m, [x])

    class Trainable(sonnx.SONNXModel):
        def train_one_batch(self, x, y):
            out = self.forward(x)
            loss = self.loss_fn(out, y)
            self.optimizer(loss)
            return out, loss

    tm = Trainable(proto, dev)
    tm.loss_fn = layer_mod.SoftMaxCrossEntropy()
    tm.set_optimizer(opt.SGD(lr=1e-2, momentum=0.9))
    y = tensor.from_numpy(
        np.random.RandomState(1).randint(0, 10, (2,)).astype(np.int32),
        dev)
    tm.compile([x], is_train=True, use_graph=True)
    bn_states = [k for k in tm.get_states() if k not in tm.get_params()]
    assert bn_states, "imported BN running stats missing from states"
    before = {k: tensor.to_numpy(tm.get_states()[k]).copy()
              for k in bn_states}
    losses = [float(tm(x, y)[1].data) for _ in range(4)]
    assert losses[-1] < losses[0], losses
    # training must MOVE the promoted running stats (they are live
    # state, not shadowed by re-executed Constant nodes)...
    moved = [k for k in bn_states
             if not np.array_equal(tensor.to_numpy(tm.get_states()[k]),
                                   before[k])]
    assert moved, "promoted BN stats never updated by training"
    # ...and eval must READ them: perturbing them changes the output
    tm.train(False)
    out0 = tensor.to_numpy(tm.forward(x))
    for k in bn_states:
        t = tm.get_states()[k]
        layer_mod.Layer._load_into(t, tensor.to_numpy(t) + 5.0)
    out1 = tensor.to_numpy(tm.forward(x))
    assert not np.allclose(out0, out1), \
        "eval ignores promoted BN running stats"


def _scan_cumsum_model(reverse=False):
    """Scan with one state and one sequence input: state' = state + x_t,
    scan output = state' (i.e. cumulative sum along axis 0)."""
    body = onnx_pb.GraphProto(
        name="body",
        input=[onnx_pb.ValueInfoProto(name="s_in"),
               onnx_pb.ValueInfoProto(name="x_t")],
        node=[onnx_pb.NodeProto(op_type="Add", input=["s_in", "x_t"],
                                output=["s_out"]),
              onnx_pb.NodeProto(op_type="Identity", input=["s_out"],
                                output=["y_t"])],
        output=[onnx_pb.ValueInfoProto(name="s_out"),
                onnx_pb.ValueInfoProto(name="y_t")])
    attrs = [onnx_pb.AttributeProto.make("body", body),
             onnx_pb.AttributeProto.make("num_scan_inputs", 1)]
    if reverse:
        attrs.append(onnx_pb.AttributeProto.make(
            "scan_input_directions", [1]))
        attrs.append(onnx_pb.AttributeProto.make(
            "scan_output_directions", [1]))
    scan = onnx_pb.NodeProto(op_type="Scan", input=["s0", "x"],
                             output=["s_final", "ys"],
                             attribute=attrs)
    g = onnx_pb.GraphProto(
        name="g",
        input=[onnx_pb.ValueInfoProto(name="s0"),
               onnx_pb.ValueInfoProto(name="x")],
        node=[scan],
        output=[onnx_pb.ValueInfoProto(name="s_final"),
                onnx_pb.ValueInfoProto(name="ys")])
    return onnx_pb.ModelProto(graph=g)


def test_scan_cumsum(dev):
    rep = sonnx.prepare(_scan_cumsum_model(), dev)
    x = np.random.RandomState(0).randn(5, 3).astype(np.float32)
    s0 = np.zeros((3,), np.float32)
    s_final, ys = rep.run([s0, x])
    np.testing.assert_allclose(tensor.to_numpy(ys), np.cumsum(x, axis=0),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(tensor.to_numpy(s_final), x.sum(axis=0),
                               rtol=1e-5, atol=1e-6)


def test_scan_reverse_direction(dev):
    rep = sonnx.prepare(_scan_cumsum_model(reverse=True), dev)
    x = np.random.RandomState(1).randn(4, 2).astype(np.float32)
    s0 = np.zeros((2,), np.float32)
    s_final, ys = rep.run([s0, x])
    # reverse scan: iterate from the end; outputs re-reversed
    expect = np.cumsum(x[::-1], axis=0)[::-1]
    np.testing.assert_allclose(tensor.to_numpy(ys), expect,
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(tensor.to_numpy(s_final), x.sum(axis=0),
                               rtol=1e-5, atol=1e-6)


def test_scan_differentiable(dev):
    """Imported Scan recurrences must train like everything else."""
    autograd.set_training(True)
    try:
        rep = sonnx.prepare(_scan_cumsum_model(), dev)
        x_t = tensor.from_numpy(
            np.random.RandomState(2).randn(5, 3).astype(np.float32), dev)
        x_t.requires_grad = x_t.stores_grad = True
        s0 = tensor.from_numpy(np.zeros((3,), np.float32), dev)
        _, ys = rep.run([s0, x_t])
        loss = autograd.reduce_sum(autograd.mul(ys, ys))
        grads = dict(autograd.backward(loss))
        assert x_t in grads and grads[x_t].shape == x_t.shape
    finally:
        autograd.set_training(False)


def test_foreign_convtranspose_lstm_fixture(dev):
    """Round-3 verdict item 5's foreign fixture: ConvTranspose ->
    Reshape -> LSTM bytes written by the independent encoder, goldens
    from torch (which also cross-checks the iofc->ifgo gate
    reordering)."""
    import os
    fdir = os.path.join(os.path.dirname(__file__), "fixtures")
    with open(os.path.join(fdir, "foreign_ct_lstm.onnx"), "rb") as f:
        blob = f.read()
    model = onnx_pb.load_model(blob)
    assert [n.op_type for n in model.graph.node] == \
        ["ConvTranspose", "Reshape", "LSTM"]
    io = np.load(os.path.join(fdir, "foreign_ct_lstm_io.npz"))
    rep = sonnx.prepare(blob, dev)
    (out,) = rep.run([tensor.from_numpy(io["x"], dev)])
    np.testing.assert_allclose(tensor.to_numpy(out), io["y"], rtol=2e-4,
                               atol=1e-5)


def test_onnx_lstm_bidirectional_and_gru_lbr0(dev):
    """RNN-family variants beyond the conformance sweep's single case:
    bidirectional LSTM (both packed slots) and the ONNX-default GRU
    linear_before_reset=0 form (its own scan — torch has no lbr=0, so
    the golden is a hand-rolled numpy recurrence)."""
    from tests.test_onnx_conformance import _rnn_case, _run_node

    inputs, attrs, inits, golden = _rnn_case("LSTM", bidirectional=True)
    outs = _run_node("LSTM", inputs, attrs, n_out=3, initializers=inits)
    for got, want in zip(outs, golden):
        np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4,
                                   atol=1e-5)

    # GRU lbr=0: numpy oracle
    rng = np.random.RandomState(11)
    T, B, I, H = 3, 2, 4, 5
    x = rng.randn(T, B, I).astype(np.float32)
    W = rng.randn(1, 3 * H, I).astype(np.float32) * 0.4   # z,r,h
    R = rng.randn(1, 3 * H, H).astype(np.float32) * 0.4
    Bb = rng.randn(1, 6 * H).astype(np.float32) * 0.4

    def sig(v):
        return 1 / (1 + np.exp(-v))

    wz, wr, wn = W[0][:H], W[0][H:2 * H], W[0][2 * H:]
    rz, rr, rn = R[0][:H], R[0][H:2 * H], R[0][2 * H:]
    wbz, wbr, wbn, rbz, rbr, rbn = np.split(Bb[0], 6)
    h = np.zeros((B, H), np.float32)
    ys = []
    for t in range(T):
        z = sig(x[t] @ wz.T + wbz + h @ rz.T + rbz)
        r = sig(x[t] @ wr.T + wbr + h @ rr.T + rbr)
        n = np.tanh(x[t] @ wn.T + wbn + (r * h) @ rn.T + rbn)
        h = (1 - z) * n + z * h
        ys.append(h.copy())
    Y = np.stack(ys)[:, None]  # (T, 1, B, H)

    from singa_tpu.io.onnx_pb import TensorProto
    outs = _run_node(
        "GRU", {"x": x}, {"hidden_size": H, "linear_before_reset": 0},
        n_out=2,
        initializers=(TensorProto.from_numpy(W, "W"),
                      TensorProto.from_numpy(R, "R"),
                      TensorProto.from_numpy(Bb, "B")))
    np.testing.assert_allclose(np.asarray(outs[0]), Y, rtol=2e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(outs[1]), Y[-1], rtol=2e-4,
                               atol=1e-5)


def test_onnx_rnn_reverse_direction(dev):
    """direction='reverse' scans backwards; numpy oracle."""
    from tests.test_onnx_conformance import _run_node
    from singa_tpu.io.onnx_pb import TensorProto

    rng = np.random.RandomState(5)
    T, B, I, H = 4, 2, 3, 4
    x = rng.randn(T, B, I).astype(np.float32)
    W = rng.randn(1, H, I).astype(np.float32) * 0.5
    R = rng.randn(1, H, H).astype(np.float32) * 0.5
    h = np.zeros((B, H), np.float32)
    ys = [None] * T
    for t in reversed(range(T)):
        h = np.tanh(x[t] @ W[0].T + h @ R[0].T)
        ys[t] = h.copy()
    Y = np.stack(ys)[:, None]
    outs = _run_node(
        "RNN", {"x": x}, {"hidden_size": H, "direction": "reverse"},
        n_out=2,
        initializers=(TensorProto.from_numpy(W, "W"),
                      TensorProto.from_numpy(R, "R")))
    np.testing.assert_allclose(np.asarray(outs[0]), Y, rtol=2e-4,
                               atol=1e-5)
    # reverse scan: the final hidden state is the one after processing
    # t=0, i.e. the loop-end h — NOT Y[-1]
    np.testing.assert_allclose(np.asarray(outs[1]), h[None], rtol=2e-4,
                               atol=1e-5)


@pytest.mark.slow
def test_rnn_family_export_import_roundtrip(dev):
    """Native RNN layers export as ONNX LSTM/GRU/RNN nodes (round 4:
    the importer gained the family earlier in the round; export closes
    the asymmetry).  Each taped layer-direction scan becomes one node
    whose W/R/B constants are unpacked from the flat packed weight with
    the inverse gate reorder — all modes x both directions, 2 layers."""
    from singa_tpu import layer, model

    class Net(model.Model):
        def __init__(self, cls, bidir):
            super().__init__()
            self.rnn = cls(8, bidirectional=bidir, num_layers=2)
            self.fc = layer.Linear(5)

        def forward(self, x):
            y, _ = self.rnn(x)
            return self.fc(y)

    rng = np.random.RandomState(0)
    x_np = rng.randn(6, 3, 4).astype(np.float32)
    for cls, node_type in ((layer.LSTM, "LSTM"), (layer.GRU, "GRU"),
                           (layer.RNN, "RNN")):
        for bidir in (False, True):
            m = Net(cls, bidir)
            x = tensor.from_numpy(x_np, dev)
            m.compile([x], is_train=False, use_graph=False)
            m.eval()
            native = tensor.to_numpy(m.forward(x))
            proto = sonnx.to_onnx(m, [x])
            n_nodes = sum(1 for n in proto.graph.node
                          if n.op_type == node_type)
            assert n_nodes == 2 * (2 if bidir else 1), \
                (node_type, bidir, n_nodes)
            rep = sonnx.prepare(proto, dev)
            got = tensor.to_numpy(rep.run([x])[0])
            np.testing.assert_allclose(got, native, rtol=2e-4,
                                       atol=1e-5,
                                       err_msg=f"{node_type} {bidir}")


@pytest.mark.slow
def test_char_rnn_model_exports(dev):
    """The config-#3 model family round-trips through ONNX end to end
    (embedding-free one-hot input -> LSTM stack -> head)."""
    from singa_tpu.models.char_rnn import CharRNN, one_hot

    m = CharRNN(20, hidden_size=12, num_layers=2, seq_length=7)
    ids = np.random.RandomState(0).randint(0, 20, (3, 7))
    x = tensor.from_numpy(one_hot(ids, 20), dev)
    m.compile([x], is_train=False, use_graph=False)
    m.eval()
    native = tensor.to_numpy(m.forward(x))
    proto = sonnx.to_onnx(m, [x])
    assert any(n.op_type == "LSTM" for n in proto.graph.node)
    rep = sonnx.prepare(proto, dev)
    got = tensor.to_numpy(rep.run([x])[0])
    np.testing.assert_allclose(got, native, rtol=2e-4, atol=1e-5)


def test_rnn_export_wires_user_initial_state(dev):
    """A user-supplied h0/c0 passed as MODEL INPUTS must be wired into
    the exported LSTM node (Slice of the graph input), not baked as an
    export-time constant — running the imported model with a different
    h0 must track the native model.  Also: the flat packed weight must
    NOT appear among the initializers (the node carries unpacked W/R/B
    constants; storing both would double the parameter bytes)."""
    from singa_tpu import layer, model

    class Net(model.Model):
        def __init__(self):
            super().__init__()
            self.rnn = layer.LSTM(8, num_layers=1)

        def forward(self, x, h0, c0):
            y, _ = self.rnn(x, h0, c0)
            return y

    m = Net()
    rng = np.random.RandomState(0)
    x = tensor.from_numpy(rng.randn(5, 2, 4).astype(np.float32), dev)
    h0 = tensor.from_numpy(rng.randn(1, 2, 8).astype(np.float32), dev)
    c0 = tensor.from_numpy(rng.randn(1, 2, 8).astype(np.float32), dev)
    m.compile([x, h0, c0], is_train=False, use_graph=False)
    m.eval()
    native = tensor.to_numpy(m.forward(x, h0, c0))
    proto = sonnx.to_onnx(m, [x, h0, c0])
    assert not any(
        len(i.dims) == 1
        and int(np.prod(i.dims)) == m.rnn.handle.weights_size
        for i in proto.graph.initializer)
    rep = sonnx.prepare(proto, dev)
    got = tensor.to_numpy(rep.run([x, h0, c0])[0])
    np.testing.assert_allclose(got, native, rtol=2e-4, atol=1e-5)
    # a DIFFERENT initial state at run time must flow through
    h2 = tensor.from_numpy(np.zeros((1, 2, 8), np.float32), dev)
    native2 = tensor.to_numpy(m.forward(x, h2, c0))
    got2 = tensor.to_numpy(rep.run([x, h2, c0])[0])
    np.testing.assert_allclose(got2, native2, rtol=2e-4, atol=1e-5)
    assert np.abs(native - native2).max() > 1e-4  # h0 genuinely matters


def test_imported_lstm_reexports(dev):
    """Full circle: an externally-shaped ONNX LSTM imports (gate
    reorder onto the packed stack), wraps in SONNXModel, RE-exports
    (the packed weight unpacks back to ONNX W/R/B — no dangling
    weight-packing subgraph, no double-stored parameters), and
    re-imports with parity against the original torch golden."""
    from tests.test_onnx_conformance import _rnn_case

    inputs, attrs, inits, golden = _rnn_case("LSTM")
    node = onnx_pb.NodeProto(
        op_type="LSTM", name="n0",
        input=list(inputs) + [t.name for t in inits],
        output=["Y", "Yh", "Yc"])
    for k, v in attrs.items():
        node.attribute.append(onnx_pb.AttributeProto.make(k, v))
    g = onnx_pb.GraphProto(
        name="g", node=[node], initializer=list(inits),
        input=[onnx_pb.ValueInfoProto(name=k, elem_type=onnx_pb.FLOAT,
                                      shape=list(np.asarray(v).shape))
               for k, v in inputs.items()],
        output=[onnx_pb.ValueInfoProto(name="Y",
                                       elem_type=onnx_pb.FLOAT,
                                       shape=[])])
    proto = onnx_pb.ModelProto(graph=g)
    x = tensor.from_numpy(np.asarray(inputs["x"]), dev)

    m2 = sonnx.SONNXModel(proto, dev)
    m2.compile([x], is_train=False, use_graph=False)
    m2.eval()
    native = tensor.to_numpy(m2.forward(x))
    proto2 = sonnx.to_onnx(m2, [x])
    assert any(n.op_type == "LSTM" for n in proto2.graph.node)
    # no Gather/Concat pack subgraph dragged into the re-export
    assert not any(n.op_type in ("Gather", "Concat")
                   for n in proto2.graph.node)
    rep2 = sonnx.prepare(proto2, dev)
    (y2,) = rep2.run([x])
    np.testing.assert_allclose(tensor.to_numpy(y2), native, rtol=2e-4,
                               atol=1e-5)
    np.testing.assert_allclose(tensor.to_numpy(y2), golden[0],
                               rtol=2e-4, atol=1e-5)


def test_foreign_trilu_scatternd_fixture(dev):
    """Round-5 verdict item 7's foreign fixture: Trilu -> ScatterND
    bytes written by the independent encoder, numpy goldens."""
    import os
    fdir = os.path.join(os.path.dirname(__file__), "fixtures")
    with open(os.path.join(fdir, "foreign_trilu_scatternd.onnx"),
              "rb") as f:
        blob = f.read()
    model = onnx_pb.load_model(blob)
    assert [n.op_type for n in model.graph.node] == ["Trilu", "ScatterND"]
    io = np.load(os.path.join(fdir, "foreign_trilu_scatternd_io.npz"))
    rep = sonnx.prepare(blob, dev)
    (out,) = rep.run([tensor.from_numpy(io["x"], dev)])
    np.testing.assert_allclose(tensor.to_numpy(out), io["y"], rtol=2e-5,
                               atol=1e-6)


def test_trilu_runtime_diagonal_k(dev):
    """Trilu whose diagonal offset k is a graph INPUT, not a constant
    initializer: under jit the handler cannot fold k at build time
    (_np dies on the tracer) and must trace the mask through jnp
    (round-6 fix).  The same executable serves different k values."""
    import jax

    node = onnx_pb.NodeProto(op_type="Trilu", name="tri",
                             input=["x", "k"], output=["y"],
                             attribute=[onnx_pb.AttributeProto.make(
                                 "upper", 1)])
    model = _graph_model(
        [node], [],
        [onnx_pb.ValueInfoProto("x", onnx_pb.FLOAT, [4, 4]),
         onnx_pb.ValueInfoProto("k", onnx_pb.INT64, [1])],
        [onnx_pb.ValueInfoProto("y", onnx_pb.FLOAT, [4, 4])])
    rep = sonnx.prepare(model, dev)
    x_np = np.random.RandomState(0).randn(4, 4).astype(np.float32)

    # eager runtime k still works (concrete value, static fold)
    (y,) = rep.run({"x": tensor.from_numpy(x_np, dev),
                    "k": tensor.from_numpy(
                        np.asarray([1], np.int64), dev)})
    np.testing.assert_allclose(tensor.to_numpy(y), np.triu(x_np, 1),
                               rtol=1e-6)

    def f(x_arr, k_arr):
        xt = tensor._wrap(x_arr, dev)
        kt = tensor._wrap(k_arr, dev)
        (out,) = rep.run({"x": xt, "k": kt})
        return out.data

    jf = jax.jit(f)
    import jax.numpy as jnp
    for k in (0, 1, -1, 2):
        got = np.asarray(jf(jnp.asarray(x_np),
                            jnp.asarray([k], jnp.int32)))
        np.testing.assert_allclose(got, np.triu(x_np, k), rtol=1e-6,
                                   err_msg=f"k={k}")
