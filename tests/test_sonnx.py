"""sonnx ONNX import/export tests (reference: test/python/test_onnx.py +
test_onnx_backend.py, unverified)."""

import numpy as np
import pytest

from singa_tpu import autograd, opt, sonnx, tensor
from singa_tpu import device as device_module
from singa_tpu.io import onnx_pb
from singa_tpu.models.mlp import MLP
from singa_tpu.models.cnn import CNN


@pytest.fixture
def dev():
    d = device_module.get_default_device()
    d.SetRandSeed(0)
    return d


def test_onnx_pb_roundtrip():
    """Wire-format serialize -> parse identity for every message type."""
    w = onnx_pb.TensorProto.from_numpy(
        np.arange(12, dtype=np.float32).reshape(3, 4), "w")
    node = onnx_pb.NodeProto(
        op_type="Gemm", name="g0", input=["x", "w"], output=["y"],
        attribute=[onnx_pb.AttributeProto.make("alpha", 2.0),
                   onnx_pb.AttributeProto.make("transB", 1),
                   onnx_pb.AttributeProto.make("pads", [1, 2, 1, 2]),
                   onnx_pb.AttributeProto.make("mode", "test")])
    g = onnx_pb.GraphProto(
        name="g", node=[node], initializer=[w],
        input=[onnx_pb.ValueInfoProto("x", onnx_pb.FLOAT, [2, 3])],
        output=[onnx_pb.ValueInfoProto("y", onnx_pb.FLOAT, [2, 4])])
    m = onnx_pb.ModelProto(graph=g)
    blob = m.serialize()
    m2 = onnx_pb.ModelProto.parse(blob)
    assert m2.producer_name == "singa_tpu"
    n2 = m2.graph.node[0]
    assert n2.op_type == "Gemm" and n2.input == ["x", "w"]
    a = n2.attrs()
    assert a["alpha"] == pytest.approx(2.0)
    assert a["transB"] == 1
    assert a["pads"] == [1, 2, 1, 2]
    assert a["mode"] == "test"
    np.testing.assert_array_equal(
        m2.graph.initializer[0].to_numpy(),
        np.arange(12, dtype=np.float32).reshape(3, 4))
    assert m2.graph.input[0].shape == [2, 3]


def test_mlp_export_import_roundtrip(dev, tmp_path):
    m = MLP(data_size=6, perceptron_size=8, num_classes=3)
    x = tensor.from_numpy(np.random.RandomState(0).randn(4, 6).astype(np.float32), dev)
    m.compile([x], is_train=False, use_graph=False)
    native = tensor.to_numpy(m.forward(x))

    proto = sonnx.to_onnx(m, [x])
    path = str(tmp_path / "mlp.onnx")
    sonnx.save(proto, path)

    rep = sonnx.prepare(path, dev)
    (out,) = rep.run([x])
    np.testing.assert_allclose(tensor.to_numpy(out), native, rtol=1e-5,
                               atol=1e-6)


def test_cnn_export_import_roundtrip(dev, tmp_path):
    m = CNN(num_classes=10, num_channels=1)
    x = tensor.from_numpy(
        np.random.RandomState(1).randn(2, 1, 28, 28).astype(np.float32), dev)
    m.compile([x], is_train=False, use_graph=False)
    native = tensor.to_numpy(m.forward(x))

    proto = sonnx.to_onnx(m, [x])
    path = str(tmp_path / "cnn.onnx")
    sonnx.save(proto, path)
    rep = sonnx.prepare(path, dev)
    (out,) = rep.run([x])
    np.testing.assert_allclose(tensor.to_numpy(out), native, rtol=1e-4,
                               atol=1e-5)


def test_imported_model_is_trainable(dev, tmp_path):
    """SONNXModel: import an exported MLP and train it (reference
    SONNXModel semantics — imported graphs are differentiable)."""
    m = MLP(data_size=6, perceptron_size=8, num_classes=3)
    x = tensor.from_numpy(np.random.RandomState(0).randn(16, 6).astype(np.float32), dev)
    y = tensor.from_numpy(np.random.RandomState(0).randint(0, 3, (16,)).astype(np.int32), dev)
    m.compile([x], is_train=False, use_graph=False)
    proto = sonnx.to_onnx(m, [x])

    class Trainable(sonnx.SONNXModel):
        def train_one_batch(self, x, y):
            out = self.forward(x)
            loss = autograd.softmax_cross_entropy(out, y)
            self.optimizer(loss)
            return out, loss

    tm = Trainable(proto, dev)
    tm.set_optimizer(opt.SGD(lr=0.1))
    tm.train(True)
    losses = [float(tm.train_one_batch(x, y)[1].data) for _ in range(10)]
    assert losses[-1] < losses[0], losses


def test_unsupported_op_reports_name(dev):
    g = onnx_pb.GraphProto(
        name="g",
        node=[onnx_pb.NodeProto(op_type="FancyOp", input=["x"], output=["y"])],
        input=[onnx_pb.ValueInfoProto("x", onnx_pb.FLOAT, [1])],
        output=[onnx_pb.ValueInfoProto("y", onnx_pb.FLOAT, [1])])
    rep = sonnx.prepare(onnx_pb.ModelProto(graph=g), dev)
    with pytest.raises(NotImplementedError, match="FancyOp"):
        rep.run([np.zeros((1,), np.float32)])


def test_handlers_cover_bert_oplist():
    """Ops appearing in a standard BERT-base ONNX graph must all have
    handlers."""
    bert_ops = ["Add", "Cast", "Concat", "Constant", "ConstantOfShape",
                "Div", "Erf", "Gather", "Identity", "MatMul", "Mul",
                "Pow", "ReduceMean", "Reshape", "Shape", "Slice",
                "Softmax", "Sqrt", "Sub", "Tanh", "Transpose",
                "Unsqueeze", "Where", "Expand", "Equal",
                "LayerNormalization", "Gemm"]
    missing = [o for o in bert_ops if o not in sonnx._ONNX_OPS]
    assert not missing, missing


def test_layernorm_export_preserves_eps(dev, tmp_path):
    """Exported LayerNormalization must carry epsilon/axis attributes and
    import back with the same numerics."""
    from singa_tpu import layer, model

    class M(model.Model):
        def __init__(self):
            super().__init__()
            self.ln = layer.LayerNorm(eps=1e-12)

        def forward(self, x):
            return self.ln(x)

    m = M()
    x = tensor.from_numpy(
        np.random.RandomState(0).randn(2, 8).astype(np.float32) * 100, dev)
    m.compile([x], is_train=False, use_graph=False)
    native = tensor.to_numpy(m.forward(x))
    proto = sonnx.to_onnx(m, [x])
    ln_nodes = [n for n in proto.graph.node
                if n.op_type == "LayerNormalization"]
    assert ln_nodes and ln_nodes[0].attrs()["epsilon"] == pytest.approx(1e-12)
    rep = sonnx.prepare(proto, dev)
    (out,) = rep.run([x])
    np.testing.assert_allclose(tensor.to_numpy(out), native, rtol=1e-5,
                               atol=1e-6)


def test_same_pool_export_roundtrip(dev, tmp_path):
    """SAME pooling with asymmetric effective pads must round-trip."""
    from singa_tpu import autograd as ag
    from singa_tpu.ops import pooling as pool_ops

    x_np = np.random.RandomState(2).randn(1, 1, 5, 5).astype(np.float32)
    x = tensor.from_numpy(x_np, dev)
    ag.set_training(True)
    try:
        y = pool_ops.pooling2d(x, kernel=(2, 2), stride=(2, 2),
                               is_max=True, pad_mode="SAME_UPPER")
        assert y.shape == (1, 1, 3, 3)
        op = y.creator
        pairs = op.params["pads_pairs"]
        assert pairs == ((0, 1), (0, 1))
    finally:
        ag.set_training(False)
