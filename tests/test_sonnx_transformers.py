"""Transformer ONNX roundtrips: the fused TPU-native ops (attention,
embedding, mask builders) export as decomposed standard-op subgraphs and
re-import bit-comparably (fp32 tolerance).

This closes SURVEY.md §2.4's ONNX-zoo row beyond MLP/CNN: BERT and
GPT-2 export -> bytes -> import -> same logits.
"""

import numpy as np
import pytest

from singa_tpu import device, sonnx, tensor
from singa_tpu.models.bert import BertConfig, BertForMaskedLM, BertModel
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead

B, S = 2, 12


@pytest.fixture
def dev():
    return device.get_default_device()


def _roundtrip(m, inputs, tmp_path, extra_feeds=()):
    proto = sonnx.to_onnx(m, list(inputs))
    path = str(tmp_path / "model.onnx")
    sonnx.save(proto, path)
    rep = sonnx.prepare(path, inputs[0].device)
    feeds = [tensor.to_numpy(t) for t in inputs]
    return rep.run(feeds)


@pytest.mark.slow
def test_bert_trunk_roundtrip(dev, tmp_path):
    cfg = BertConfig.tiny(hidden_dropout=0.0, attn_dropout=0.0)
    m = BertModel(cfg)
    rng = np.random.RandomState(0)
    ids = tensor.from_numpy(
        rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32), dev)
    tt = tensor.from_numpy(np.zeros((B, S), np.int32), dev)
    m.compile([ids], is_train=False, use_graph=False)
    m.eval()
    seq, pooled = m.forward(ids, tt)

    outs = _roundtrip(m, [ids, tt], tmp_path)
    np.testing.assert_allclose(tensor.to_numpy(outs[0]),
                               tensor.to_numpy(seq), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(tensor.to_numpy(outs[1]),
                               tensor.to_numpy(pooled), rtol=1e-4,
                               atol=1e-5)


def test_bert_with_attention_mask_roundtrip(dev, tmp_path):
    """Exercises the AttnMask decomposition (Sub/Mul/Unsqueeze)."""
    cfg = BertConfig.tiny(hidden_dropout=0.0, attn_dropout=0.0)
    m = BertModel(cfg)
    rng = np.random.RandomState(1)
    ids = tensor.from_numpy(
        rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32), dev)
    tt = tensor.from_numpy(np.zeros((B, S), np.int32), dev)
    am = np.ones((B, S), np.float32)
    am[:, -4:] = 0.0  # padded tail
    amt = tensor.from_numpy(am, dev)
    m.compile([ids], is_train=False, use_graph=False)
    m.eval()
    seq, _ = m.forward(ids, tt, amt)

    outs = _roundtrip(m, [ids, tt, amt], tmp_path)
    np.testing.assert_allclose(tensor.to_numpy(outs[0]),
                               tensor.to_numpy(seq), rtol=1e-4, atol=1e-5)


def test_bert_mlm_with_dropout_roundtrip(dev, tmp_path):
    """Dropout ops export as ONNX Dropout (identity at inference)."""
    cfg = BertConfig.tiny()  # default dropout 0.1
    m = BertForMaskedLM(cfg)
    rng = np.random.RandomState(2)
    ids = tensor.from_numpy(
        rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32), dev)
    m.compile([ids], is_train=False, use_graph=False)
    m.eval()
    logits = m.forward(ids)

    outs = _roundtrip(m, [ids], tmp_path)
    np.testing.assert_allclose(tensor.to_numpy(outs[0]),
                               tensor.to_numpy(logits), rtol=1e-4,
                               atol=1e-5)


@pytest.mark.slow
def test_imported_gpt2_is_trainable(dev):
    """SONNXModel over an imported GPT-2: the decomposed graph (Gather
    embeddings, MatMul/Softmax attention with a frozen causal mask)
    trains — gradients flow through every imported op back to the
    initializer weights."""
    from singa_tpu import autograd, layer, opt

    cfg = GPT2Config.tiny(dropout=0.0)
    native = GPT2LMHead(cfg)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int32)
    x0 = tensor.from_numpy(ids, dev)
    native.compile([x0], is_train=False, use_graph=False)
    native.eval()
    proto = sonnx.to_onnx(native, [x0])

    class TrainableImport(sonnx.SONNXModel):
        def __init__(self, proto, device):
            super().__init__(proto, device)
            self.loss_fn = layer.SoftMaxCrossEntropy()

        def train_one_batch(self, x, y):
            logits = self.forward(x)
            b, s, v = logits.shape
            loss = self.loss_fn(
                autograd.reshape(logits, (b * s, v)),
                autograd.reshape(y, (b * s,)))
            self.optimizer(loss)
            return logits, loss

    m = TrainableImport(proto, dev)
    m.set_optimizer(opt.Adam(lr=2e-3))
    m.train(True)
    losses = []
    for _ in range(8):
        _, loss = m(tensor.from_numpy(ids, dev),
                    tensor.from_numpy(labels, dev))
        losses.append(float(tensor.to_numpy(loss)))
    assert losses[-1] < losses[0] - 0.3, losses
    # the frozen constants were NOT updated
    assert not any(n.startswith("const_") for n in m.get_params())


def test_exported_constants_frozen_and_shared(dev):
    """Decomposer constants (causal mask, scales) export as Constant
    NODES: never trainable on re-import, and shape-keyed so all layers
    share one mask."""
    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    rng = np.random.RandomState(0)
    ids = tensor.from_numpy(
        rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32), dev)
    m.compile([ids], is_train=False, use_graph=False)
    m.eval()
    proto = sonnx.to_onnx(m, [ids])

    consts = [n.output[0] for n in proto.graph.node
              if n.op_type == "Constant"]
    causal = [c for c in consts if c.startswith("const_causal")]
    assert len(causal) == 1, causal  # 2 layers, one shared mask
    # emit.const values (causal/scale/shape/...) are Constant nodes,
    # not initializers (initializers import as trainable weights); the
    # untracked-leaf path (e.g. baked position ids, named const_<id>)
    # legitimately stays an initializer and is int-typed -> untrainable
    for prefix in ("const_causal", "const_scale", "const_shape",
                   "const_one", "const_neg", "const_idx", "const_axes"):
        assert not any(i.name.startswith(prefix)
                       for i in proto.graph.initializer), prefix

    sm = sonnx.SONNXModel(proto, dev)
    trainable = set(sm.get_params())
    assert trainable, "imported model must keep real weights trainable"
    assert not any(n.startswith("const_") for n in trainable), trainable


def test_gqa_gpt2_roundtrip(dev, tmp_path):
    """Grouped-query attention exports: the RepeatKV head broadcast
    decomposes to Reshape/Tile/Reshape (element-interleaved, NOT a
    plain Tile, which would cycle whole-head blocks) and the imported
    graph reproduces the native logits."""
    cfg = GPT2Config.tiny(dropout=0.0, n_kv_head=2)
    m = GPT2LMHead(cfg)
    rng = np.random.RandomState(7)
    ids = tensor.from_numpy(
        rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32), dev)
    m.compile([ids], is_train=False, use_graph=False)
    m.eval()
    logits = m.forward(ids)
    proto = sonnx.to_onnx(m, [ids])
    types = [n.op_type for n in proto.graph.node]
    assert "Tile" in types, types  # the RepeatKV decomposition ran
    outs = _roundtrip(m, [ids], tmp_path)
    np.testing.assert_allclose(tensor.to_numpy(outs[0]),
                               tensor.to_numpy(logits), rtol=1e-4,
                               atol=1e-5)


def test_gpt2_roundtrip(dev, tmp_path):
    """Causal attention exports with a baked additive tril mask; tied
    lm_head exports as Transpose(wte)+MatMul."""
    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    rng = np.random.RandomState(3)
    ids = tensor.from_numpy(
        rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32), dev)
    m.compile([ids], is_train=False, use_graph=False)
    m.eval()
    logits = m.forward(ids)

    outs = _roundtrip(m, [ids], tmp_path)
    np.testing.assert_allclose(tensor.to_numpy(outs[0]),
                               tensor.to_numpy(logits), rtol=1e-4,
                               atol=1e-5)
    # causality survives the roundtrip: perturbing a late token must not
    # change the imported model's logits at earlier positions
    ids2 = tensor.to_numpy(ids).copy()
    ids2[:, -1] = (ids2[:, -1] + 1) % cfg.vocab_size
    rep = sonnx.prepare(sonnx.to_onnx(m, [ids]), dev)
    a = tensor.to_numpy(rep.run([tensor.to_numpy(ids)])[0])
    b = tensor.to_numpy(rep.run([ids2])[0])
    np.testing.assert_allclose(a[:, :-1], b[:, :-1], rtol=1e-5, atol=1e-6)
    assert not np.allclose(a[:, -1], b[:, -1])
