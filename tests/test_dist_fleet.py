"""Multi-host fleet (the dist round): serving across the process
boundary.  ``DistFleet`` presents the exact ``ServeFleet`` surface —
router, autoscaler and soak harness run unmodified — while every
replica lives behind a framed socket, KV images ship as wire frames,
and the fleet prefix index becomes a CROSS-HOST residency directory.

The parity chain under test: a request submitted to a DistFleet must
stream and resolve byte-identically to the same request on an
in-process ServeFleet (the wire moves pickled prompts and integer
tokens, never float state), and a streamed cross-host ship must land
the same image the one-shot export would have packed.  Every distance
failure (severed peer, partitioned RPC, a frame lost mid-ship) maps
onto the failover machinery the fleet already has: typed errors,
cold-but-correct requeues, zero leaked blocks on the survivors.

Tier-1 tests run workers as in-process THREADS (same wire protocol,
no spawn cost); the single true multi-process parity test is marked
``slow``.  Named to sort after test_serve_disagg (same paged
cost-table collection-order hazard test_serve_longctx documents)."""

import os
import socket

import numpy as np
import pytest

from singa_tpu import tensor
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from singa_tpu.resilience import FailOnce, faults
from singa_tpu.serve import (DistFleet, GenerationRequest, KVImage,
                             KVImageError, PagedConfig,
                             PrefixCacheConfig, ServeFleet, gpt2_spec)
from singa_tpu.serve.autoscale import AutoscaleConfig, Autoscaler
from singa_tpu.serve.dist import DistSession
from singa_tpu.serve.dist.transport import (MSG_ONEWAY, Conn,
                                            PeerGoneError,
                                            PeerTimeoutError,
                                            TransportError)
from singa_tpu.serve.kvimage import KVIMAGE_VERSION, pack_image

BLOCK = 8


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def model():
    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    return m


@pytest.fixture(scope="module")
def spec(model):
    return gpt2_spec(model)


def _disagg_kw(roles=("prefill", "decode"), num_blocks=48):
    return dict(roles=roles, max_slots=2,
                paged=PagedConfig(block_size=BLOCK,
                                  num_blocks=num_blocks),
                prefix_cache=PrefixCacheConfig(block_size=BLOCK))


def _prompts(n, seed=0, lo=4, hi=9):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 256, rng.randint(lo, hi)).astype(np.int32)
            for _ in range(n)]


def _long(seed, n=40):
    return np.random.RandomState(seed).randint(
        0, 256, n).astype(np.int32)


def _run(fleet, prompts, new=5, prefix="q", max_steps=800):
    hs = [fleet.submit(GenerationRequest(
        p, max_new_tokens=new, request_id=f"{prefix}{i}"))
        for i, p in enumerate(prompts)]
    fleet.run_until_complete(max_steps=max_steps)
    return [[int(t) for t in h.result().tokens] for h in hs]


def _leaks(fleet):
    """Wire-level leak check: the step reply carries both
    ``blocks_used`` and ``cached_blocks``, so used minus tree-cached
    on each healthy replica must be zero after a drain."""
    out = []
    for i in range(fleet.replicas):
        eng = fleet.supervisor(i).engine
        if eng._closed or eng.paged_arena is None:
            continue
        out.append(eng.paged_arena.blocks_used
                   - eng.prefix_cache.cached_blocks)
    return out


# ---------------------------------------------------------------------------
# kvimage wire codec: the bytes that cross the host boundary
# ---------------------------------------------------------------------------

def _fake_rows(width=16):
    kc = np.arange(2 * 4 * width * 8, dtype=np.float32).reshape(
        (2, 1, 4, width, 8))
    return kc, np.copy(kc)


def test_kvimage_wire_roundtrip():
    kc, vc = _fake_rows()
    img = pack_image(kc, vc, block_size=BLOCK, n_data=2, quant=False)
    back = KVImage.from_bytes(img.to_bytes())
    assert back.version == KVIMAGE_VERSION
    assert back.checksum == img.checksum
    assert back.header == img.header
    assert back.n_data == 2 and back.block_size == BLOCK
    back.validate(BLOCK, False)
    np.testing.assert_array_equal(np.asarray(back.kc),
                                  np.asarray(img.kc))


def test_kvimage_wire_rejects_corruption_typed():
    """Every way a socket can mangle a frame is a typed KVImageError,
    never a crash or a silently-wrong image: bit-flip (crc), mid-leaf
    truncation (mid-stream EOF), short framing, foreign magic,
    version skew, and a length-lying frame with trailing bytes."""
    kc, vc = _fake_rows()
    img = pack_image(kc, vc, block_size=BLOCK, n_data=2, quant=False)
    buf = img.to_bytes()

    flip = bytearray(buf)
    flip[len(flip) // 2] ^= 0xFF                 # deep in leaf bytes
    with pytest.raises(KVImageError, match="crc32"):
        KVImage.from_bytes(bytes(flip))

    with pytest.raises(KVImageError, match="mid-leaf"):
        KVImage.from_bytes(buf[: len(buf) // 2])

    with pytest.raises(KVImageError, match="truncated"):
        KVImage.from_bytes(b"KVIM")

    with pytest.raises(KVImageError, match="magic"):
        KVImage.from_bytes(b"NOPE" + buf[4:])

    skew = bytearray(buf)
    skew[4:6] = (KVIMAGE_VERSION + 1).to_bytes(2, "big")
    with pytest.raises(KVImageError, match="version"):
        KVImage.from_bytes(bytes(skew))

    with pytest.raises(KVImageError, match="trailing"):
        KVImage.from_bytes(buf + b"\x00")


# ---------------------------------------------------------------------------
# transport: framing and typed peer failures
# ---------------------------------------------------------------------------

def test_transport_frames_and_typed_failures():
    sa, sb = socket.socketpair()
    a, b = Conn(sa, "a"), Conn(sb, "b")
    try:
        a.send(MSG_ONEWAY, {"op": "ping", "payload": 7})
        kind, obj = b.recv(timeout=5.0)
        assert kind == MSG_ONEWAY and obj["payload"] == 7
        with pytest.raises(PeerTimeoutError):
            b.recv(timeout=0.05)
        # garbage on the wire is a framing loss, not a bad message
        sa.sendall(b"XXXX" + b"\x00" * 14)
        with pytest.raises(TransportError):
            b.recv(timeout=5.0)
    finally:
        a.close()
        b.close()


def test_transport_peer_close_is_peer_gone():
    sa, sb = socket.socketpair()
    a, b = Conn(sa, "a"), Conn(sb, "b")
    a.close()
    with pytest.raises(PeerGoneError):
        b.recv(timeout=5.0)
    b.close()


# ---------------------------------------------------------------------------
# parity: the wire must be invisible
# ---------------------------------------------------------------------------

def test_dist_thread_parity_and_token_streams(model, spec):
    """Greedy decode through worker threads is byte-identical to the
    in-process fleet, and on_token delivers exactly the generated
    tail parent-side, in order, per request."""
    prompts = _prompts(6, seed=0)
    with ServeFleet(model, replicas=2, max_slots=2) as f1:
        want = _run(f1, prompts, new=6)

    seen = []
    with DistFleet(spec, replicas=2, spawn="thread",
                   max_slots=2) as f2:
        hs = [f2.submit(GenerationRequest(
            p, max_new_tokens=6, request_id=f"q{i}",
            on_token=lambda req, tok: seen.append(
                (req.request_id, int(tok)))))
            for i, p in enumerate(prompts)]
        f2.run_until_complete(max_steps=500)
        got = [[int(t) for t in h.result().tokens] for h in hs]
        snap = f2.snapshot()
    assert got == want, (got, want)
    for i, toks in enumerate(got):
        tail = toks[len(prompts[i]):]
        assert [t for rid, t in seen if rid == f"q{i}"] == tail
    d = snap["dist"]
    assert d["spawn"] == "thread"
    assert d["rpcs"] > 0 and d["rpc_errors"] == 0


def test_dist_disagg_streamed_ship_parity_no_leaks(model, spec):
    """Disaggregated serving across the wire: prefill builds stream
    layer-wise frames to the decode peer, the landed image admits
    warm, and the stream is byte-identical to the single-host disagg
    fleet — with zero leaked blocks on either side after the drain."""
    prompts = [_long(s) for s in (3, 4, 5)]
    kw = _disagg_kw()
    with ServeFleet(model, replicas=2, **kw) as f1:
        want = _run(f1, prompts, new=5)
    with DistFleet(spec, replicas=2, spawn="thread", **kw) as f2:
        got = _run(f2, prompts, new=5)
        snap = f2.snapshot()
        leaks = _leaks(f2)
    assert got == want, (got, want)
    assert snap["ships"] >= 1
    assert snap["ship_fallbacks"] == 0
    assert snap["dist"]["frames"] > 0
    assert snap["dist"]["frame_bytes"] > 0
    assert all(l == 0 for l in leaks), leaks


def test_dist_sticky_session_parity(model, spec):
    """A pinned session's continuation round-trips the wire: the
    handle lands parent-side as a DistSession over host tokens, the
    next turn routes sticky, and both turns match the in-process
    fleet byte for byte."""
    p = (np.arange(40) % 256).astype(np.int32)
    extra = np.asarray([7, 3, 11, 2], np.int32)
    cache = dict(max_slots=2,
                 prefix_cache=PrefixCacheConfig(block_size=BLOCK))

    def turns(fleet):
        h = fleet.submit(GenerationRequest(p, max_new_tokens=4,
                                           pin_session=True))
        fleet.run_until_complete(max_steps=300)
        sess = h.result().session
        assert sess is not None
        h2 = fleet.submit(sess.request(extra, max_new_tokens=3))
        fleet.run_until_complete(max_steps=300)
        out = ([int(t) for t in h.result().tokens],
               [int(t) for t in h2.result().tokens])
        return sess, out

    with ServeFleet(model, replicas=2, **cache) as f1:
        _, want = turns(f1)
    with DistFleet(spec, replicas=2, spawn="thread", **cache) as f2:
        sess, got = turns(f2)
        assert isinstance(sess, DistSession)
        np.testing.assert_array_equal(sess.tokens, got[0])
        sess.release()                           # idempotent unpin
        sess.release()
    assert got == want, (got, want)


# ---------------------------------------------------------------------------
# distance failures: severed, partitioned, half-shipped
# ---------------------------------------------------------------------------

def test_dist_kill_worker_failover_requeue_parity(model, spec):
    """A worker severed mid-flight: its requests requeue onto the
    survivor and finish byte-identical to an undisturbed run (no
    tokens had streamed, so the requeue is invisible)."""
    prompts = _prompts(4, seed=2)
    with ServeFleet(model, replicas=2, max_slots=2) as f1:
        want = _run(f1, prompts, new=6, prefix="k")
    with DistFleet(spec, replicas=2, spawn="thread",
                   max_slots=2) as f2:
        hs = [f2.submit(GenerationRequest(
            p, max_new_tokens=6, request_id=f"k{i}"))
            for i, p in enumerate(prompts)]
        f2.step()
        f2.kill_worker(0)
        f2.run_until_complete(max_steps=800)
        got = [[int(t) for t in h.result().tokens] for h in hs]
        snap = f2.snapshot()
        assert f2.healthy_replicas == 1
    assert got == want, (got, want)
    assert snap["failovers"] >= 1


def test_dist_partition_then_autoscaler_replaces(model, spec):
    """An injected RPC partition (serve.dist.rpc) marks the peer down
    through the same PeerGone -> failover path a real network split
    takes; in-flight work drains on the survivor, and the role-aware
    autoscaler's replace_dead heals the fleet back to width by
    spawning a FRESH worker that then serves traffic."""
    prompts = _prompts(3, seed=4)
    with DistFleet(spec, replicas=2, spawn="thread",
                   max_slots=2) as fleet:
        hs = [fleet.submit(GenerationRequest(
            p, max_new_tokens=4, request_id=f"p{i}"))
            for i, p in enumerate(prompts)]
        faults.inject("serve.dist.rpc", FailOnce())
        fleet.run_until_complete(max_steps=800)
        for h in hs:
            assert len(h.result().tokens) > 0
        assert fleet.healthy_replicas == 1

        sc = Autoscaler(fleet, AutoscaleConfig(
            min_replicas=2, max_replicas=2,
            scale_up_cooldown_s=0.0, scale_down_cooldown_s=0.0))
        try:
            ev = sc.check()
            assert ev is not None and ev["action"] == "replace_dead"
            assert "role" in ev
            assert fleet.healthy_replicas == 2

            h = fleet.submit(GenerationRequest(
                prompts[0], max_new_tokens=3, request_id="post"))
            fleet.run_until_complete(max_steps=300)
            assert len(h.result().tokens) > 0
        finally:
            sc.close()


def test_dist_halfship_falls_back_cold(model, spec):
    """A frame lost mid-relay (serve.dist.frame): a HALF-SHIPPED
    image.  Neither peer is condemned — the destination's staging
    buffer is aborted, the build falls back to a cold serve, and the
    stream stays byte-identical with zero leaked blocks."""
    prompts = [_long(s) for s in (6, 7)]
    kw = _disagg_kw()
    with ServeFleet(model, replicas=2, **kw) as f1:
        want = _run(f1, prompts, new=4, prefix="h")
    with DistFleet(spec, replicas=2, spawn="thread", **kw) as f2:
        faults.inject("serve.dist.frame", FailOnce())
        got = _run(f2, prompts, new=4, prefix="h")
        snap = f2.snapshot()
        leaks = _leaks(f2)
        assert f2.healthy_replicas == 2
    assert got == want, (got, want)
    assert snap["ship_fallbacks"] >= 1
    assert all(l == 0 for l in leaks), leaks


def test_dist_stale_hint_prunes_and_serves_cold(model, spec):
    """The residency directory lies (hint for blocks the remote tree
    never held): the verify hook asks the LIVE tree over the wire,
    the hint is pruned, and the request serves cold-but-correct."""
    p = _long(11)
    toks = [int(t) for t in p]
    n_blocks = len(toks) // BLOCK
    kw = _disagg_kw()
    with ServeFleet(model, replicas=2, **kw) as f1:
        want = _run(f1, [p], new=4, prefix="s")
    with DistFleet(spec, replicas=2, spawn="thread", **kw) as f2:
        f2._prefix_index.register(toks, n_blocks, 1)
        assert f2._prefix_index.holders(toks, n_blocks) == [1]
        got = _run(f2, [p], new=4, prefix="s")
        # the failed verify pruned replica 1 from the span (the ship
        # that served the request may have re-registered real
        # residency at landing — a lying FULL-span hint never stays)
        assert 1 not in f2._prefix_index.holders(toks, n_blocks) \
            or f2.snapshot()["ships"] >= 1
        leaks = _leaks(f2)
    assert got == want, (got, want)
    assert all(l == 0 for l in leaks), leaks


# ---------------------------------------------------------------------------
# true multi-process parity (spawn cost: marked slow)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_dist_process_mode_parity(model, spec):
    prompts = _prompts(3, seed=1)
    with ServeFleet(model, replicas=2, max_slots=2) as f1:
        want = _run(f1, prompts, new=4)
    with DistFleet(spec, replicas=2, spawn="process",
                   max_slots=2) as f2:
        got = _run(f2, prompts, new=4)
        pids = [f2.supervisor(i).pid for i in range(2)]
    assert got == want, (got, want)
    assert all(p and p != os.getpid() for p in pids), pids


# ---------------------------------------------------------------------------
# telemetry federation (the federation round, fleet half; unit half in
# test_federate.py): clock-aligned merge, typed stale degradation,
# rejection observability, retire unregistration
# ---------------------------------------------------------------------------

@pytest.fixture()
def _observing():
    from singa_tpu import observe

    observe.clear()
    observe.enable()
    led = observe.requests.enable(capacity=1024)
    yield led
    observe.requests.disable()
    observe.disable()
    observe.clear()


def test_dist_federation_hosts_health_and_peer_metrics(
        model, spec, _observing):
    """The federated surface over a live thread fleet: every sealed
    hop carries a host id, health_report()["serve"]["dist"] names the
    straggler host and decomposes latency with the exact ``ship``
    phase (fractions summing to 1), and the transport's per-peer
    self-observability (frames/bytes counters + RTT histogram) is
    registered while the fleet lives and gone when it closes."""
    from singa_tpu.observe import health_report, registry

    prompts = _prompts(4, seed=3)
    with DistFleet(spec, replicas=2, spawn="thread", max_slots=2,
                   telemetry_interval_s=0.0) as fleet:
        _run(fleet, prompts, new=4, prefix="f")
        fleet._maybe_pull_telemetry(force=True)
        entries = _observing.entries()
        assert entries
        for e in entries:
            assert e["hops"][-1]["host"] in ("w0", "w1"), e
        ds = health_report()["serve"]["dist"]
        assert ds["enabled"] is True
        assert sorted(ds["hosts"]) == ["w0", "w1"]
        assert ds["stale_hosts"] == []
        assert all(h["pulls"] >= 1 for h in ds["hosts"].values())
        ws = ds["why_slow"]
        lat = ws["latency_p99_attribution"]
        assert set(lat) == {"queue", "prefill", "ship", "decode",
                            "stall", "preempted", "hops"}
        assert sum(p["frac"] for p in lat.values()) \
            == pytest.approx(1.0)
        assert "ship" in ws["ttft_p99_attribution"]
        assert ws["straggler_host"]["host"] in ("w0", "w1")
        assert set(ws["per_host"]) <= {"w0", "w1", "local"}
        # satellite: per-peer transport metrics live in the registry
        snap = registry().snapshot()
        for peer in ("w0", "w1"):
            assert snap["counters"][
                f"serve.dist.frames{{peer={peer}}}"] > 0
            assert snap["counters"][
                f"serve.dist.bytes{{peer={peer}}}"] > 0
            assert f"serve.dist.rtt_s{{peer={peer}}}" \
                in snap["histograms"]
        dist = fleet.snapshot()["dist"]
        assert dist["retries"] == 0
        assert "ship_overlap_efficiency" in dist
        assert dist["telemetry"]["w0"]["pulls"] >= 1
    # close(): peer series unregister, health section detaches
    snap = registry().snapshot()
    assert not any("peer=" in k for k in snap["counters"])
    assert health_report()["serve"]["dist"] == {"enabled": False}


def test_dist_telemetry_death_degrades_stale_serving_unaffected(
        model, spec, _observing):
    """Kill the telemetry channel mid-run: the host degrades to a
    typed ``stale`` marker, serving continues untouched (every request
    completes — 0 wedged, 0 lost), and the next successful pull clears
    the marker.  Conversely a pull must never CONSUME a fault injected
    on the RPC site — the partition lands on real control traffic."""
    from singa_tpu.observe import health_report

    prompts = _prompts(4, seed=5)
    with DistFleet(spec, replicas=2, spawn="thread", max_slots=2,
                   telemetry_interval_s=0.0) as fleet:
        hs = [fleet.submit(GenerationRequest(
            p, max_new_tokens=5, request_id=f"t{i}"))
            for i, p in enumerate(prompts)]
        fleet.step()
        faults.inject("serve.dist.telemetry", FailOnce())
        fleet._maybe_pull_telemetry(force=True)
        ds = health_report()["serve"]["dist"]
        assert ds["stale_hosts"] == ["w0"]
        assert ds["hosts"]["w0"]["stale_reason"]
        # serving is unaffected by the lost pull
        fleet.run_until_complete(max_steps=800)
        for h in hs:
            assert h.result().finish_reason == "length"
        assert fleet.healthy_replicas == 2
        led = _observing
        assert led.snapshot()["open"] == 0  # nothing wedged
        # recovery: the next pull clears the typed marker
        fleet._maybe_pull_telemetry(force=True)
        assert health_report()["serve"]["dist"]["stale_hosts"] == []
        prom = fleet.telemetry.prometheus_text()
        assert 'singa_tpu_federation_stale{host="w0"} 0' in prom
        # fault-site isolation: an armed RPC partition survives any
        # number of telemetry pulls and fires on real control traffic
        faults.inject("serve.dist.rpc", FailOnce())
        fleet._maybe_pull_telemetry(force=True)
        assert health_report()["serve"]["dist"]["stale_hosts"] == []
        with pytest.raises(PeerGoneError):
            fleet.supervisor(0).ping()


def test_dist_peer_loss_rejections_are_observable(
        model, spec, _observing):
    """Satellite: a worker lost mid-flight must leave evidence — a
    ``serve/request_rejected`` instant on the dist path and a ledger
    hop reject carrying reason ``peer_lost`` and the delivery-started
    verdict (False here: no token had streamed, so the requeue serves
    the caller byte-identically)."""
    from singa_tpu import observe

    prompts = _prompts(4, seed=2)
    with DistFleet(spec, replicas=2, spawn="thread",
                   max_slots=2) as fleet:
        hs = [fleet.submit(GenerationRequest(
            p, max_new_tokens=4, request_id=f"x{i}"))
            for i, p in enumerate(prompts)]
        fleet.step()
        fleet.kill_worker(0)
        fleet.run_until_complete(max_steps=800)
        for h in hs:
            assert h.result().finish_reason == "length"
    inst = [e for e in observe.events()
            if e["name"] == "serve/request_rejected"
            and (e["args"] or {}).get("reason") == "peer_lost"]
    assert inst, "no serve/request_rejected instant for the lost peer"
    assert inst[0]["args"]["started"] is False
    rejects = [
        (e["request_id"], h["reject"])
        for e in _observing.entries() for h in e["hops"]
        if h["reject"] is not None
        and h["reject"]["reason"] == "peer_lost"]
    assert rejects, "peer_lost never landed in the ledger"
    assert all(r["started"] is False for _, r in rejects)
    # the requeued requests still COMPLETED: reject evidence is on the
    # lost hop, the final outcome on the survivor's
    done = {e["request_id"]: e["outcome"]
            for e in _observing.entries()}
    for rid, _ in rejects:
        assert done[rid] == "length"


def test_dist_retire_and_revive_federation_lifecycle(
        model, spec, _observing):
    """Satellite: retire unregisters the worker's federated series
    (telemetry host slot AND per-peer transport metrics); revive
    re-registers both fresh."""
    from singa_tpu.observe import registry

    with DistFleet(spec, replicas=2, spawn="thread", max_slots=2,
                   telemetry_interval_s=0.0) as fleet:
        _run(fleet, _prompts(2, seed=7), new=3, prefix="r")
        fleet._maybe_pull_telemetry(force=True)
        assert sorted(fleet.telemetry.hosts) == ["w0", "w1"]
        fleet.start_drain(1)
        for _ in range(50):
            if fleet.drained(1):
                break
            fleet.step()
        fleet.retire_replica(1)
        assert sorted(fleet.telemetry.hosts) == ["w0"]
        snap = registry().snapshot()
        assert not any("peer=w1" in k for k in snap["counters"])
        assert any("peer=w0" in k for k in snap["counters"])
        assert 'host="w1"' not in fleet.telemetry.prometheus_text()
        # scale back up through the same slot: fresh host, fresh series
        fleet.revive(1)
        assert sorted(fleet.telemetry.hosts) == ["w0", "w1"]
        assert fleet.telemetry.hosts["w1"].pulls == 0
        snap = registry().snapshot()
        assert any("peer=w1" in k for k in snap["counters"])
        h = fleet.submit(GenerationRequest(
            _prompts(1, seed=8)[0], max_new_tokens=3,
            request_id="post-revive"))
        fleet.run_until_complete(max_steps=300)
        assert h.result().finish_reason == "length"
