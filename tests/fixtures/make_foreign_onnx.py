"""Generate ``foreign_gemm.onnx`` with an INDEPENDENT minimal protobuf
encoder (not the vendored ``singa_tpu.io.onnx_pb``), so the fixture
cross-validates the vendored codec against bytes it did not write —
simulating an ONNX file produced by another tool (VERDICT r01 item 5;
reference test strategy: sonnx is exercised against the official onnx
backend-test suite, SURVEY.md §4).

Model: y = relu(x @ W + b), x:[2,3], W:[3,4], b:[4]  (Gemm + Relu).

Run once from the repo root:  python tests/fixtures/make_foreign_onnx.py
The resulting bytes are checked into the repo.
"""

import os
import struct

import numpy as np


def varint(n):
    out = b""
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def field(num, wire, payload):
    return varint((num << 3) | wire) + payload


def msg(num, payload):          # length-delimited submessage
    return field(num, 2, varint(len(payload)) + payload)


def s(num, text):               # string field
    b = text.encode()
    return field(num, 2, varint(len(b)) + b)


def i(num, val):                # varint field
    return field(num, 0, varint(val))


def tensor_f32(name, arr):
    body = b""
    for d in arr.shape:
        body += i(1, d)                       # dims
    body += i(2, 1)                           # data_type = FLOAT
    body += s(8, name)                        # name
    raw = arr.astype("<f4").tobytes()
    body += field(9, 2, varint(len(raw)) + raw)   # raw_data
    return body


def value_info(name, shape):
    dims = b"".join(msg(1, i(1, d)) for d in shape)       # dim{dim_value}
    ttype = i(1, 1) + msg(2, dims)                        # elem_type, shape
    return s(1, name) + msg(2, msg(1, ttype))             # name, type.tensor_type


def attr_f(name, val):
    return s(1, name) + field(2, 5, struct.pack("<f", val)) + i(20, 1)


def attr_i(name, val):
    return s(1, name) + i(3, val) + i(20, 2)


def attr_is(name, vals):        # ints attribute (type INTS=7)
    return s(1, name) + b"".join(i(8, v) for v in vals) + i(20, 7)


def tensor_i64(name, arr):
    body = b""
    for d in arr.shape:
        body += i(1, d)
    body += i(2, 7)                           # data_type = INT64
    body += s(8, name)
    raw = arr.astype("<i8").tobytes()
    body += field(9, 2, varint(len(raw)) + raw)
    return body


def main():
    rng = np.random.RandomState(42)
    W = rng.randn(3, 4).astype(np.float32)
    b = rng.randn(4).astype(np.float32)

    gemm = (s(1, "x") + s(1, "W") + s(1, "b") + s(2, "h") + s(3, "gemm0")
            + s(4, "Gemm")
            + msg(5, attr_f("alpha", 1.0)) + msg(5, attr_f("beta", 1.0))
            + msg(5, attr_i("transA", 0)) + msg(5, attr_i("transB", 0)))
    relu = s(1, "h") + s(2, "y") + s(3, "relu0") + s(4, "Relu")

    graph = (msg(1, gemm) + msg(1, relu) + s(2, "foreign_graph")
             + msg(5, tensor_f32("W", W)) + msg(5, tensor_f32("b", b))
             + msg(11, value_info("x", [2, 3]))
             + msg(12, value_info("y", [2, 4])))

    model = (i(1, 7)                      # ir_version
             + s(2, "foreign_tool")       # producer_name
             + s(3, "1.0")                # producer_version
             + msg(7, graph)
             + msg(8, s(1, "") + i(2, 13)))   # opset_import {domain, version}

    out = os.path.join(os.path.dirname(__file__), "foreign_gemm.onnx")
    with open(out, "wb") as f:
        f.write(model)
    # companion goldens so the test needs no torch/onnx
    x = rng.randn(2, 3).astype(np.float32)
    y = np.maximum(x @ W + b, 0.0)
    np.savez(os.path.join(os.path.dirname(__file__), "foreign_gemm_io.npz"),
             x=x, y=y)
    print(f"wrote {out} ({os.path.getsize(out)} bytes)")
    make_convtranspose_lstm()
    make_trilu_scatternd()


def make_convtranspose_lstm():
    """Second foreign fixture (round-3 verdict item 5): a
    ConvTranspose -> Reshape -> LSTM chain, goldens from torch (whose
    LSTM gate order ifgo differs from ONNX's iofc — the npz golden
    therefore independently cross-checks the importer's gate
    reordering)."""
    import torch

    rng = np.random.RandomState(7)
    x = rng.randn(1, 2, 4, 4).astype(np.float32)
    w_ct = rng.randn(2, 3, 3, 3).astype(np.float32) * 0.5   # (Cin,Cout,k,k)
    T, Bz, I, H = 3, 1, 49, 5
    W = rng.randn(1, 4 * H, I).astype(np.float32) * 0.3     # ONNX iofc
    R = rng.randn(1, 4 * H, H).astype(np.float32) * 0.3
    Bb = rng.randn(1, 8 * H).astype(np.float32) * 0.3

    # torch golden (reorder iofc -> ifgo)
    y_ct = torch.nn.functional.conv_transpose2d(
        torch.from_numpy(x), torch.from_numpy(w_ct), stride=2,
        padding=1)                                           # (1,3,7,7)
    xl = y_ct.reshape(T, Bz, I)
    perm = [0, 2, 3, 1]
    ridx = np.concatenate([np.arange(p * H, (p + 1) * H) for p in perm])
    mod = torch.nn.LSTM(I, H, 1)
    with torch.no_grad():
        mod.weight_ih_l0.copy_(torch.from_numpy(W[0][ridx]))
        mod.weight_hh_l0.copy_(torch.from_numpy(R[0][ridx]))
        mod.bias_ih_l0.copy_(torch.from_numpy(Bb[0, :4 * H][ridx]))
        mod.bias_hh_l0.copy_(torch.from_numpy(Bb[0, 4 * H:][ridx]))
        y, (hT, cT) = mod(xl)
    Y = y.numpy().reshape(T, Bz, 1, H).transpose(0, 2, 1, 3)

    ct = (s(1, "x") + s(1, "w_ct") + s(2, "h_ct") + s(3, "ct0")
          + s(4, "ConvTranspose")
          + msg(5, attr_is("strides", [2, 2]))
          + msg(5, attr_is("pads", [1, 1, 1, 1])))
    rs = (s(1, "h_ct") + s(1, "shape") + s(2, "xl") + s(3, "rs0")
          + s(4, "Reshape"))
    lstm = (s(1, "xl") + s(1, "W") + s(1, "R") + s(1, "B")
            + s(2, "Y") + s(3, "lstm0") + s(4, "LSTM")
            + msg(5, attr_i("hidden_size", H)))

    graph = (msg(1, ct) + msg(1, rs) + msg(1, lstm)
             + s(2, "foreign_ct_lstm")
             + msg(5, tensor_f32("w_ct", w_ct))
             + msg(5, tensor_i64("shape", np.asarray([T, Bz, I])))
             + msg(5, tensor_f32("W", W)) + msg(5, tensor_f32("R", R))
             + msg(5, tensor_f32("B", Bb))
             + msg(11, value_info("x", [1, 2, 4, 4]))
             + msg(12, value_info("Y", [T, 1, Bz, H])))

    model = (i(1, 7) + s(2, "foreign_tool") + s(3, "1.0")
             + msg(7, graph) + msg(8, s(1, "") + i(2, 14)))

    out = os.path.join(os.path.dirname(__file__),
                       "foreign_ct_lstm.onnx")
    with open(out, "wb") as f:
        f.write(model)
    np.savez(os.path.join(os.path.dirname(__file__),
                          "foreign_ct_lstm_io.npz"), x=x, y=Y)
    print(f"wrote {out} ({os.path.getsize(out)} bytes)")


def make_trilu_scatternd():
    """Third foreign fixture (round-5 verdict item 7): the ops modern
    HF decoder / detection exports hit first — a causal-mask-style
    Trilu feeding a ScatterND row overwrite.  Goldens in plain numpy;
    bytes from the independent encoder, as above."""
    rng = np.random.RandomState(9)
    x = rng.randn(4, 4).astype(np.float32)
    idx = np.asarray([[0], [3]], np.int64)
    upd = rng.randn(2, 4).astype(np.float32)

    trilu = (s(1, "x") + s(2, "t") + s(3, "tri0") + s(4, "Trilu")
             + msg(5, attr_i("upper", 0)))
    scat = (s(1, "t") + s(1, "idx") + s(1, "upd") + s(2, "y")
            + s(3, "scat0") + s(4, "ScatterND"))

    graph = (msg(1, trilu) + msg(1, scat) + s(2, "foreign_trilu_scat")
             + msg(5, tensor_i64("idx", idx))
             + msg(5, tensor_f32("upd", upd))
             + msg(11, value_info("x", [4, 4]))
             + msg(12, value_info("y", [4, 4])))

    model = (i(1, 7) + s(2, "foreign_tool") + s(3, "1.0")
             + msg(7, graph) + msg(8, s(1, "") + i(2, 16)))

    out = os.path.join(os.path.dirname(__file__),
                       "foreign_trilu_scatternd.onnx")
    with open(out, "wb") as f:
        f.write(model)
    y = np.tril(x).copy()
    for r in range(idx.shape[0]):
        y[tuple(idx[r])] = upd[r]
    np.savez(os.path.join(os.path.dirname(__file__),
                          "foreign_trilu_scatternd_io.npz"), x=x, y=y)
    print(f"wrote {out} ({os.path.getsize(out)} bytes)")


if __name__ == "__main__":
    main()
