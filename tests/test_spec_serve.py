"""Fast-path serving: speculative decoding + int8 KV inside the serve
engine (perf round).

The contract under test, in order of importance:

* **greedy byte-parity** — a speculative engine's greedy token streams
  are byte-identical to the plain engine's (and therefore to
  single-prompt ``generate``), whatever the draft proposes and however
  requests arrive.  Trained model pairs throughout: speculative parity
  must not ride argmax near-ties between the chunked and sequential
  einsum orders (~1e-7 on random weights — the same discipline as
  tests/test_gpt2.py's offline speculative tests);
* **sampled distributional correctness** — rejection sampling (accept
  with min(1, p/q), resample the residual) makes every emitted token
  marginally distributed EXACTLY as direct target sampling.  Gated by
  a two-sample χ² over a tiny vocab at a fixed seed schedule
  (deterministic: the statistic is a constant, the gate can never
  flake);
* **int8 arenas** — engine streams equal offline
  ``generate(cache_dtype="int8")`` bit for bit (greedy, seeded
  sampling, GQA), because both run the identical quantized math;
* **composition** — speculation × prefix cache (multi-token retire
  donation, sessions), speculation × int8, stop-token mid-chunk
  retire, supervisor restart pass-through;
* **typed config validation** — every incompatible knob combination
  fails at construction with a message naming the conflict, never
  inside a jitted dispatch.
"""

import numpy as np
import pytest

from singa_tpu import device, opt, tensor
from singa_tpu.models import gpt2_decode
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from singa_tpu.serve import (FIFOScheduler, GenerationRequest,
                             PrefixCacheConfig)


def _train(cfg, seed, steps=12):
    """Train a tiny model on highly-learnable motif data (the
    examples/gpt2/speculative.py recipe): decisive logits and real
    draft/target agreement without a checkpoint dependency."""
    device.get_default_device().SetRandSeed(seed)
    m = GPT2LMHead(cfg)
    rng = np.random.RandomState(0)
    motif = rng.randint(0, cfg.vocab_size, 8)
    ids = np.tile(motif, (4, 4)).astype(np.int32)[:, :32]
    noise = rng.randint(0, cfg.vocab_size, ids.shape)
    mask = rng.rand(*ids.shape) < 0.05
    ids[mask] = noise[mask]
    labels = np.roll(ids, -1, axis=1).astype(np.int32)
    m.set_optimizer(opt.Adam(lr=1e-3))
    m.compile([tensor.from_numpy(ids)], is_train=True, use_graph=True)
    for _ in range(steps):
        m(tensor.from_numpy(ids), tensor.from_numpy(labels))
    m.eval()
    return m, ids


_pairs = {}


def _trained_pair(**cfgkw):
    """Cached (target, draft, train ids): a 2-layer target and a
    1-layer draft trained on the same motif data."""
    key = tuple(sorted(cfgkw.items()))
    if key not in _pairs:
        cfg_t = GPT2Config.tiny(dropout=0.0, **cfgkw)
        cfg_d = GPT2Config.tiny(dropout=0.0, n_layer=1, **cfgkw)
        target, ids = _train(cfg_t, seed=0)
        draft, _ = _train(cfg_d, seed=1, steps=8)
        _pairs[key] = (target, draft, ids)
    return _pairs[key]


def _drive(eng, reqs, max_steps=4000):
    handles = [eng.submit(r) for r in reqs]
    eng.run_until_complete(max_steps=max_steps)
    return [h.result() for h in handles]


# ---------------------------------------------------------------------------
# greedy byte-parity

def test_spec_greedy_streams_byte_identical():
    """The acceptance bar: greedy speculative serve streams equal the
    plain engine's (and the offline oracle's) byte for byte, with a
    positive realized acceptance, and a multi-token step count — a
    12-token request must finish in fewer engine steps than tokens."""
    target, draft, ids = _trained_pair()
    prompts = [ids[0, :9], ids[1, :5], ids[2, :13], ids[0, 3:7]]
    news = [12, 6, 9, 4]

    def reqs():
        return [GenerationRequest(p, max_new_tokens=n)
                for p, n in zip(prompts, news)]

    eng_plain = target.serve(max_slots=2)
    plain = _drive(eng_plain, reqs())
    eng_plain.close()

    eng = target.serve(max_slots=2, draft_model=draft, spec_k=4)
    spec = _drive(eng, reqs())
    snap = eng.stats.snapshot()
    eng.close()

    for p, n, a, b in zip(prompts, news, plain, spec):
        want = target.generate(np.asarray(p), max_new_tokens=n,
                               temperature=0)
        np.testing.assert_array_equal(a.tokens, want)
        np.testing.assert_array_equal(b.tokens, a.tokens)
    assert snap["spec"]["acceptance_rate"] > 0
    assert snap["spec"]["tokens_per_chunk"] > 1.0
    # multi-token steps: the 12-token request retired in fewer engine
    # steps than it emitted tokens (the whole point of the fast path)
    big = spec[0]
    assert big.finished_step - big.admitted_step < news[0] - 1
    assert big.tpot is not None


@pytest.mark.slow
def test_spec_gqa_parity():
    """GQA target+draft (narrow H_kv caches in BOTH arenas): greedy
    spec streams still equal the oracle token for token."""
    target, draft, ids = _trained_pair(n_kv_head=2)
    prompts = [ids[0, :8], ids[1, :6]]
    eng = target.serve(max_slots=2, draft_model=draft, spec_k=3)
    res = _drive(eng, [GenerationRequest(p, max_new_tokens=7)
                       for p in prompts])
    eng.close()
    for p, r in zip(prompts, res):
        want = target.generate(np.asarray(p), max_new_tokens=7,
                               temperature=0)
        np.testing.assert_array_equal(r.tokens, want)


def test_spec_mixed_greedy_and_sampled_pool():
    """One executable serves greedy and sampled requests side by side
    (temp is traced): the greedy stream stays byte-identical to the
    oracle while a sampled neighbor rides rejection sampling."""
    target, draft, ids = _trained_pair()
    eng = target.serve(max_slots=2, draft_model=draft, spec_k=3)
    hg = eng.submit(GenerationRequest(ids[0, :9], max_new_tokens=8))
    hs = eng.submit(GenerationRequest(ids[1, :6], max_new_tokens=8,
                                      temperature=1.0, seed=5))
    eng.run_until_complete(max_steps=500)
    eng.close()
    want = target.generate(np.asarray(ids[0, :9]), max_new_tokens=8,
                           temperature=0)
    np.testing.assert_array_equal(hg.result().tokens, want)
    samp = hs.result()
    assert len(samp.tokens) == 6 + 8
    assert samp.finish_reason == "length"


# ---------------------------------------------------------------------------
# sampled distributional correctness (the χ² gate, VERDICT missing #4)

def test_spec_sampled_chi2_matches_direct_sampling():
    """Rejection sampling's whole claim: speculative sampled tokens are
    distributed exactly as direct target sampling.  Two-sample χ² over
    a 16-token vocab at a fixed seed schedule, on the two
    verify-produced positions of a 3-token generation, against the
    α=0.001 critical value for df=15 (37.70).  Everything is seeded,
    so the statistic is deterministic — this can never flake, only
    regress.  The trained 2-vs-1-layer pair keeps acceptance interior
    (≈0.8): both the accept and the residual-resample branches carry
    real probability mass, so a bug in either moves the statistic."""
    target, draft, ids = _trained_pair(vocab_size=16)
    prompt = ids[0, :8]
    N = 400

    def collect(spec):
        kw = dict(draft_model=draft, spec_k=3) if spec else {}
        eng = target.serve(
            max_slots=8,
            scheduler=FIFOScheduler(max_queue_depth=N + 1), **kw)
        res = _drive(eng, [GenerationRequest(
            prompt, max_new_tokens=3, temperature=1.0, seed=1000 + i)
            for i in range(N)], max_steps=20000)
        snap = eng.stats.snapshot()
        eng.close()
        return (np.stack([r.tokens[len(prompt):] for r in res]), snap)

    t_spec, snap = collect(True)
    t_plain, _ = collect(False)
    rate = snap["spec"]["acceptance_rate"]
    assert 0.05 < rate < 0.999, \
        f"acceptance {rate} degenerate — the χ² gate needs both " \
        "branches exercised"
    for pos in (1, 2):
        o1 = np.bincount(t_spec[:, pos], minlength=16)
        o2 = np.bincount(t_plain[:, pos], minlength=16)
        live = (o1 + o2) > 0
        chi2 = float((((o1 - o2) ** 2)
                      / np.maximum(o1 + o2, 1))[live].sum())
        # df <= 15; the df=15 critical value upper-bounds smaller dfs
        assert chi2 < 37.70, \
            (f"position {pos}: chi2={chi2:.1f} over df<={live.sum() - 1}"
             f" — speculative sampling diverges from direct sampling")


# ---------------------------------------------------------------------------
# int8 KV arenas

@pytest.mark.slow  # variant: spec_greedy_streams is the fast rep
def test_int8_engine_parity():
    """int8 arena streams equal offline generate(cache_dtype='int8')
    bit for bit — greedy, seeded sampling, and GQA (the engine and the
    offline path run the identical quantized decode math)."""
    for cfgkw in ({}, {"n_kv_head": 2}):
        cfg = GPT2Config.tiny(dropout=0.0, **cfgkw)
        m = GPT2LMHead(cfg)
        m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
                  is_train=False, use_graph=False)
        prompts = [np.arange(9) % 256, (np.arange(4) + 3) % 256]
        eng = m.serve(max_slots=2, cache_dtype="int8")
        hg = eng.submit(GenerationRequest(prompts[0], max_new_tokens=6))
        s = int(np.random.RandomState(3).randint(0, 2 ** 31 - 1))
        hs = eng.submit(GenerationRequest(prompts[1], max_new_tokens=5,
                                          temperature=0.9, seed=s))
        eng.run_until_complete(max_steps=200)
        eng.close()
        want_g = gpt2_decode.generate(m, np.asarray(prompts[0]),
                                      max_new_tokens=6, temperature=0,
                                      cache_dtype="int8")
        np.testing.assert_array_equal(hg.result().tokens, want_g)
        want_s = gpt2_decode.generate(
            m, np.asarray(prompts[1]), max_new_tokens=5,
            temperature=0.9, rng=np.random.RandomState(3),
            cache_dtype="int8")
        np.testing.assert_array_equal(hs.result().tokens, want_s)


def test_int8_spec_compose():
    """int8 arenas × speculation: greedy spec streams equal offline
    int8 sequential decode (the comparison point when the cache is
    quantized, as generate_speculative documents)."""
    target, draft, ids = _trained_pair()
    p = ids[0, :9]
    eng = target.serve(max_slots=2, draft_model=draft, spec_k=3,
                       cache_dtype="int8")
    res = _drive(eng, [GenerationRequest(p, max_new_tokens=8)])
    eng.close()
    want = gpt2_decode.generate(target, np.asarray(p),
                                max_new_tokens=8, temperature=0,
                                cache_dtype="int8")
    np.testing.assert_array_equal(res[0].tokens, want)


# ---------------------------------------------------------------------------
# composition: prefix cache, stop tokens, supervisor pass-through

def test_spec_prefix_compose():
    """Speculation × radix prefix cache: warm (shared system prompt)
    spec streams are byte-identical to cold spec streams and to the
    oracle, multi-token retires donate canonical prompt blocks, and a
    pinned session's next turn is a warm hit that still matches."""
    target, draft, ids = _trained_pair()
    system = np.asarray(ids[0, :16])
    tails = [ids[1, :5], ids[2, 2:8], ids[0, 7:12]]
    prompts = [np.concatenate([system, t]) for t in tails]
    cfg = PrefixCacheConfig(block_size=8, num_blocks=32)

    eng = target.serve(max_slots=2, draft_model=draft, spec_k=3,
                       prefix_cache=cfg)
    res = _drive(eng, [GenerationRequest(p, max_new_tokens=6,
                                         pin_session=True)
                       for p in prompts])
    for p, r in zip(prompts, res):
        want = target.generate(np.asarray(p), max_new_tokens=6,
                               temperature=0)
        np.testing.assert_array_equal(r.tokens, want)
    # the shared system prompt hits once a retire has donated it (the
    # first two requests admit in the same pass, before any donation)
    snap = eng.stats.snapshot()
    assert snap["prefix"]["hits"] >= 1, snap["prefix"]
    # session continuation: near-full prefix hit, still oracle-exact
    sess = res[0].session
    req2 = sess.request(ids[1, :4], max_new_tokens=5)
    r2 = _drive(eng, [req2])[0]
    want2 = target.generate(np.asarray(req2.prompt_ids),
                            max_new_tokens=5, temperature=0)
    np.testing.assert_array_equal(r2.tokens, want2)
    snap2 = eng.stats.snapshot()
    assert snap2["prefix"]["hit_tokens"] > snap["prefix"]["hit_tokens"]
    for r in res:
        if r.session is not None:
            r.session.release()
    eng.close()


def test_stop_token_retires_mid_chunk():
    """A stop token lands mid-speculative-chunk: the request retires
    with finish_reason='stop' truncated at the stop position, surplus
    accepted tokens never emitted — and the plain engine agrees."""
    target, draft, ids = _trained_pair()
    p = ids[0, :9]
    base = np.asarray(target.generate(np.asarray(p), max_new_tokens=10,
                                      temperature=0))
    # stop on the 3rd generated token: with spec_k=4 chunks, that is
    # mid-chunk for any acceptance >= 2
    stop = int(base[len(p) + 2])
    outs = []
    for kw in ({}, dict(draft_model=draft, spec_k=4)):
        eng = target.serve(max_slots=1, **kw)
        r = _drive(eng, [GenerationRequest(p, max_new_tokens=10,
                                           stop_token=stop)])[0]
        eng.close()
        assert r.finish_reason == "stop"
        outs.append(r.tokens)
    np.testing.assert_array_equal(outs[0], base[:len(p) + 3])
    np.testing.assert_array_equal(outs[1], outs[0])


def test_supervisor_restart_rebuilds_spec_engine():
    """EngineSupervisor forwards the fast-decode knobs verbatim: a
    decode fault mid-spec-run rebuilds a SPECULATIVE engine (fresh
    target AND draft arenas, jit cache hit) and requeued never-started
    requests stream byte-identically to an uninterrupted run."""
    from singa_tpu.resilience import FailAfterN, faults
    from singa_tpu.serve import (EngineFailedError, EngineSupervisor)

    target, draft, ids = _trained_pair()
    prompts = [ids[i % 3, :7 + i % 4] for i in range(6)]
    base = [np.asarray(target.generate(np.asarray(p), max_new_tokens=5,
                                       temperature=0)) for p in prompts]
    sup = EngineSupervisor(target, max_slots=2, restart_budget=2,
                           draft_model=draft, spec_k=3)
    assert sup.engine.draft is draft
    handles = [sup.submit(GenerationRequest(p, max_new_tokens=5))
               for p in prompts]
    pol = faults.inject("serve.decode_step", FailAfterN(2, times=1))
    try:
        sup.run_until_complete(max_steps=2000)
    finally:
        faults.clear()
    assert pol.fired == 1
    assert sup.engine.draft is draft  # rebuilt engine kept the knobs
    completed = typed = 0
    for h, want in zip(handles, base):
        assert h.done()
        try:
            np.testing.assert_array_equal(h.result().tokens, want)
            completed += 1
        except EngineFailedError:
            typed += 1
    assert completed + typed == len(prompts) and completed > 0
    sup.close()


# ---------------------------------------------------------------------------
# stats / metrics / health

def test_spec_metrics_and_health():
    target, draft, ids = _trained_pair()
    from singa_tpu import observe

    eng = target.serve(max_slots=2, draft_model=draft, spec_k=3)
    _drive(eng, [GenerationRequest(ids[0, :9], max_new_tokens=6)])
    snap = eng.stats.snapshot()
    assert set(snap["spec"]) == {"drafted", "accepted", "chunks",
                                 "acceptance_rate", "tokens_per_chunk"}
    assert snap["spec"]["drafted"] >= snap["spec"]["accepted"] >= 0
    assert snap["spec"]["chunks"] >= 1
    reg = observe.registry().snapshot()["counters"]
    lbl = "{engine=" + eng.stats.engine_label + "}"
    assert reg["serve.spec.drafted" + lbl] == snap["spec"]["drafted"]
    health = observe.health_report(include_registry=False)
    assert health["serve"]["spec"]["drafted"] > 0
    assert 0.0 <= health["serve"]["spec"]["acceptance_rate"] <= 1.0
    eng.close()
    reg2 = observe.registry().snapshot()["counters"]
    assert ("serve.spec.drafted" + lbl) not in reg2  # unregistered


# ---------------------------------------------------------------------------
# typed config validation (the guard-fix satellite)

def test_config_validation_typed_errors():
    target, draft, ids = _trained_pair()

    with pytest.raises(ValueError, match="without draft_model"):
        target.serve(spec_k=4)
    with pytest.raises(ValueError, match="spec_k must be >= 2"):
        target.serve(draft_model=draft, spec_k=1)

    small_vocab = GPT2LMHead(GPT2Config.tiny(dropout=0.0,
                                             vocab_size=128))
    small_vocab.compile(
        [tensor.from_numpy(np.zeros((1, 16), np.int32))],
        is_train=False, use_graph=False)
    with pytest.raises(ValueError, match="vocab mismatch"):
        target.serve(draft_model=small_vocab)

    short = GPT2LMHead(GPT2Config.tiny(dropout=0.0, n_positions=32))
    short.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
                  is_train=False, use_graph=False)
    with pytest.raises(ValueError, match="n_positions"):
        target.serve(draft_model=short)

    win = GPT2LMHead(GPT2Config.tiny(dropout=0.0, attn_window=8))
    with pytest.raises(NotImplementedError, match="sliding-window"):
        target.serve(draft_model=win)

    # int8 + prefix cache is SUPPORTED since the paged round (the
    # block pool is pytree-leaf-generic): construction succeeds and
    # admissions route through the chunked canonical form
    eng8 = target.serve(cache_dtype="int8",
                        prefix_cache=PrefixCacheConfig(block_size=8,
                                                       num_blocks=16))
    assert eng8.prefix_cache is not None
    eng8.close()
    with pytest.raises(ValueError, match="cache_dtype"):
        target.serve(cache_dtype="int4")

    # speculative headroom: spec_k - 1 positions reserved at submit
    eng = target.serve(max_slots=1, draft_model=draft, spec_k=4)
    with pytest.raises(ValueError, match="spec_k-1"):
        eng.submit(GenerationRequest(np.zeros(120, np.int32),
                                     max_new_tokens=6))
    eng.close()
