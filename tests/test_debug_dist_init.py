"""Debug/NaN mode (SURVEY.md §5.2) + multi-host control-plane smoke
(SURVEY.md §5.8) + memory-pool shim (SURVEY.md §2.1)."""

import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from singa_tpu import autograd, config, device as device_module, tensor


@pytest.fixture
def dev():
    d = device_module.get_default_device()
    d.SetRandSeed(0)
    return d


def test_debug_mode_raises_on_nan(dev):
    """config.debug(True) -> a NaN-producing op raises at the op
    (jax_debug_nans), instead of poisoning training silently."""
    config.debug(True)
    try:
        x = tensor.from_numpy(np.array([-1.0], np.float32), dev)
        with pytest.raises(FloatingPointError):
            y = autograd.log(x)
            float(y.data)
    finally:
        config.debug(False)
    # off again: same op quietly yields nan (reference behavior)
    y = autograd.log(tensor.from_numpy(np.array([-1.0], np.float32), dev))
    assert np.isnan(tensor.to_numpy(y)).all()
    assert not config.debug_enabled()


def test_mem_pool_shim():
    pool = device_module.CnMemPool(init_size_mb=128)
    pool.Malloc(1024)
    free, total = pool.GetMemUsage()
    assert free >= 0 and total >= 0
    pool.Free(0, 1024)
    assert pool._outstanding == 0
    assert isinstance(device_module.CudaMemPool(), device_module.DeviceMemPool)


def test_initialize_distributed_single_process_smoke():
    """The DCN bootstrap line is live code: initialize_distributed with a
    1-process world starts the coordinator and serves process_count=1.
    Runs in a subprocess because jax.distributed.initialize must precede
    backend init (this pytest process already initialized its backend)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    code = textwrap.dedent(f"""
        import jax
        jax.config.update("jax_platforms", "cpu")
        from singa_tpu.parallel.communicator import initialize_distributed
        initialize_distributed("127.0.0.1:{port}", num_processes=1,
                               process_id=0)
        assert jax.process_count() == 1, jax.process_count()
        assert jax.process_index() == 0
        import jax.numpy as jnp
        assert float(jnp.sum(jnp.ones(4))) == 4.0
        jax.distributed.shutdown()
        print("dist-smoke-ok")
    """)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))) + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dist-smoke-ok" in proc.stdout
