"""Fleet autoscaling (serve/autoscale.py + the ServeFleet elastic
surface): the decision table threadless under a fake clock and fake
fleet, plus live-fleet integration (spawn = compile-cache reuse,
drain/retire, the scale-down leaked-gauge audit, the serve.autoscale
fault site)."""

import numpy as np
import pytest

from singa_tpu import tensor
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from singa_tpu.observe import health_report
from singa_tpu.observe.registry import MetricsRegistry, registry
from singa_tpu.resilience import FailOnce, faults
from singa_tpu.serve import (AutoscaleConfig, Autoscaler,
                             GenerationRequest, ServeFleet)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def model():
    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    return m


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class _FakeReplica:
    def __init__(self, idx):
        self.idx = idx
        self.healthy = True
        self.draining = False
        self.retired = False


class _FakePolicy:
    """Just the .alerts surface the autoscaler reads."""

    def __init__(self):
        self.alerts = {"page": {"firing": False}}

    def fire(self, on=True):
        self.alerts["page"]["firing"] = on


class FakeFleet:
    """Duck-typed ServeFleet surface the Autoscaler consumes — the
    decision table runs with zero engines."""

    def __init__(self, n=1, load=None):
        self.fleet_label = "t"
        self._clock = None
        self._replicas = [_FakeReplica(i) for i in range(n)]
        self.load = load if load is not None else {}
        self.log = []
        self.drained_set = set()

    @property
    def replicas(self):
        return len(self._replicas)

    def load_views(self):
        out = []
        for r in self._replicas:
            if not (r.healthy and not r.retired):
                continue
            v = {"replica": r.idx, "role": "mixed",
                 "draining": r.draining, "queue_depth": 0,
                 "occupancy": 0.0, "tpot_ewma": None,
                 "queue_headroom": None, "blocks_used_frac": None}
            v.update(self.load.get(r.idx, {}))
            out.append(v)
        return out

    def add_replica(self, role="mixed"):
        idx = len(self._replicas)
        self._replicas.append(_FakeReplica(idx))
        self.log.append(("add", idx))
        return idx

    def revive(self, idx):
        r = self._replicas[idx]
        r.healthy, r.retired, r.draining = True, False, False
        self.log.append(("revive", idx))

    def start_drain(self, idx):
        self._replicas[idx].draining = True
        self.log.append(("drain", idx))

    def cancel_drain(self, idx):
        self._replicas[idx].draining = False
        self.log.append(("cancel", idx))

    def drained(self, idx):
        return idx in self.drained_set

    def retire_replica(self, idx):
        r = self._replicas[idx]
        r.retired, r.healthy, r.draining = True, False, False
        self.log.append(("retire", idx))


def _scaler(fleet, clk, reg=None, policy=None, **kw):
    cfg = dict(min_replicas=1, max_replicas=3,
               scale_up_cooldown_s=10.0, scale_down_cooldown_s=30.0,
               queue_high=4.0, queue_low=0.5, occupancy_high=0.85,
               occupancy_low=0.35, blocks_high=0.85)
    cfg.update(kw)
    return Autoscaler(fleet, AutoscaleConfig(**cfg),
                      slo_policy=policy, clock=clk,
                      reg=reg if reg is not None else MetricsRegistry())


# ---------------------------------------------------------------------------
# decision table (threadless, fake fleet)
# ---------------------------------------------------------------------------

def test_scale_up_on_burn_alert():
    clk, pol = FakeClock(), _FakePolicy()
    fleet = FakeFleet(1)
    sc = _scaler(fleet, clk, policy=pol)
    assert sc.check() is None           # quiet + at min: hold
    pol.fire()
    ev = sc.check()
    assert ev["action"] == "scale_up"
    assert ev["reason"].startswith("slo_burn:page")
    assert fleet.log == [("add", 1)]
    # the ledger carries the signal snapshot that justified it
    assert ev["signals"]["alerts_firing"] == ["page"]


def test_scale_up_on_load_signals_and_cooldown_no_flap():
    clk = FakeClock()
    fleet = FakeFleet(1, load={0: {"queue_depth": 9}})
    sc = _scaler(fleet, clk)
    assert sc.check()["action"] == "scale_up"
    fleet.load[1] = {"queue_depth": 9}
    # still hot, but inside the up-cooldown: no flapping
    clk.advance(5.0)
    assert sc.check() is None
    clk.advance(5.0)
    assert sc.check()["action"] == "scale_up"
    # at max_replicas: never scales past the ceiling
    fleet.load[2] = {"queue_depth": 9}
    clk.advance(20.0)
    assert sc.check() is None
    assert fleet.replicas == 3


def test_scale_up_prefers_reviving_a_retired_slot():
    clk = FakeClock()
    fleet = FakeFleet(2)
    fleet._replicas[1].retired = True
    fleet._replicas[1].healthy = False
    fleet.load = {0: {"queue_depth": 9}}
    sc = _scaler(fleet, clk)
    ev = sc.check()
    assert ev["action"] == "scale_up" and "via=revive" in ev["reason"]
    assert fleet.log == [("revive", 1)]


def test_scale_up_on_kv_block_pressure():
    clk = FakeClock()
    fleet = FakeFleet(1, load={0: {"blocks_used_frac": 0.95}})
    sc = _scaler(fleet, clk)
    ev = sc.check()
    assert ev["action"] == "scale_up" and "kv_blocks" in ev["reason"]


def test_scale_down_only_when_quiet_and_drained():
    clk, pol = FakeClock(), _FakePolicy()
    fleet = FakeFleet(2)
    sc = _scaler(fleet, clk, policy=pol, max_replicas=2)
    # a firing alert blocks scale-down (and at max_replicas there is
    # no up to take either — the fleet holds)
    pol.fire()
    assert sc.check() is None
    pol.fire(False)
    ev = sc.check()
    assert ev["action"] == "drain_begin"
    idx = ev["replica"]
    assert fleet._replicas[idx].draining
    # NOT retired until the replica actually drains
    assert sc.check() is None
    assert not any(r.retired for r in fleet._replicas)
    fleet.drained_set.add(idx)
    ev = sc.check()
    assert ev["action"] == "drain_done"
    assert fleet._replicas[idx].retired
    # one drain at a time + down-cooldown: the second replica holds
    assert sc.check() is None


def test_scale_down_blocked_by_cooldowns_and_min():
    clk = FakeClock()
    fleet = FakeFleet(2, load={0: {"queue_depth": 9}})
    sc = _scaler(fleet, clk, min_replicas=2)
    sc.check()  # scale_up at t=0 -> 3 serving
    fleet.load = {}
    # quiet immediately after a scale-up: the down-embargo holds
    clk.advance(10.0)
    assert sc.check() is None
    clk.advance(30.0)
    ev = sc.check()
    assert ev is not None and ev["action"] == "drain_begin"
    fleet.drained_set.add(ev["replica"])
    sc.check()  # drain_done -> back to 2 serving
    # min_replicas floor: 2 serving == min, no further drain however
    # long it stays quiet
    clk.advance(100.0)
    assert sc.check() is None
    assert sum(1 for r in fleet._replicas
               if r.healthy and not r.retired) == 2  # 3 - 1 retired


def test_burst_during_drain_cancels_it():
    clk = FakeClock()
    fleet = FakeFleet(2)
    sc = _scaler(fleet, clk)
    ev = sc.check()
    assert ev["action"] == "drain_begin"
    idx = ev["replica"]
    fleet.load = {i: {"queue_depth": 9} for i in (0, 1)}
    ev = sc.check()
    assert ev["action"] == "drain_cancelled" and ev["replica"] == idx
    assert not fleet._replicas[idx].draining
    # the cancel counted as the scale-up (cooldown armed)
    assert sc.check() is None


def test_autoscale_fault_site_abandons_decision_typed():
    clk = FakeClock()
    reg = MetricsRegistry()
    fleet = FakeFleet(1, load={0: {"queue_depth": 9}})
    sc = _scaler(fleet, clk, reg=reg)
    faults.inject("serve.autoscale", FailOnce())
    ev = sc.check()
    assert ev["action"] == "scale_up_failed" and "error" in ev
    assert fleet.replicas == 1 and fleet.log == []
    assert reg.counter("serve.autoscale.decisions_failed",
                       fleet="t").value == 1
    # no cooldown was spent: the next check retries and succeeds
    ev = sc.check()
    assert ev["action"] == "scale_up"
    assert fleet.replicas == 2


def test_config_validation():
    fleet = FakeFleet(1)
    with pytest.raises(ValueError):
        _scaler(fleet, FakeClock(), min_replicas=0)
    with pytest.raises(ValueError):
        _scaler(fleet, FakeClock(), min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        _scaler(fleet, FakeClock(), queue_low=5.0, queue_high=4.0)
    with pytest.raises(ValueError):
        _scaler(fleet, FakeClock(), blocks_high=0.0)
    with pytest.raises(ValueError):
        _scaler(fleet, FakeClock(), scale_up_cooldown_s=-1.0)
    with pytest.raises(ValueError):
        # fleet narrower than the floor
        _scaler(FakeFleet(1), FakeClock(), min_replicas=2,
                max_replicas=3)


# ---------------------------------------------------------------------------
# live fleet integration
# ---------------------------------------------------------------------------

def _work(n, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randint(0, 256, rng.randint(3, 10)).astype(np.int32),
             int(rng.randint(2, 6))) for _ in range(n)]


def test_live_scale_up_serves_with_parity_and_drains_down(model):
    """The full loop on a real fleet: queue pressure spawns a replica
    (token parity held), all-quiet drains it back, the retired
    engine's metrics leave the registry (the leaked-gauge audit) and
    the health report drops its per-replica row."""
    work = _work(12, seed=3)
    base = [np.asarray(model.generate(p, max_new_tokens=n,
                                      temperature=0.0))
            for p, n in work]
    clk = FakeClock()
    fleet = ServeFleet(model, replicas=1, max_slots=2,
                       clock=clk)
    sc = Autoscaler(fleet, AutoscaleConfig(
        min_replicas=1, max_replicas=2, scale_up_cooldown_s=1.0,
        scale_down_cooldown_s=2.0, queue_high=2.0, queue_low=0.5,
        occupancy_high=1.5, occupancy_low=0.6), clock=clk)
    hs = [fleet.submit(GenerationRequest(
        p, max_new_tokens=n, temperature=0.0)) for p, n in work]
    ev = sc.check()
    assert ev is not None and ev["action"] == "scale_up"
    assert fleet.replicas == 2
    while fleet.pending:
        fleet.step()
        clk.advance(0.5)
        sc.check()
    for h, want in zip(hs, base):
        assert np.array_equal(h.result().tokens, want)
    # all-quiet: drain + retire
    for _ in range(12):
        if any(e["action"] == "drain_done"
               for e in sc.scaling_events):
            break
        clk.advance(1.0)
        sc.check()
    assert any(e["action"] == "drain_done"
               for e in sc.scaling_events)
    retired = [r for r in fleet._replicas if r.retired]
    assert len(retired) == 1
    # leaked-gauge audit: nothing keyed to the retired engine's label
    lbl = f"engine={retired[0].sup.engine.stats.engine_label}"
    snap = registry().snapshot()
    leaked = [k for sec in snap.values() for k in sec if lbl in k]
    assert leaked == [], leaked
    # health: the per-replica row is gone, the autoscale section live
    assert retired[0].idx not in fleet.health()
    rep = health_report(include_registry=False)
    assert rep["serve"]["autoscale"]["enabled"] is True
    assert rep["serve"]["autoscale"]["scale_ups"] >= 1
    assert rep["serve"]["autoscale"]["scale_downs"] >= 1
    snap_f = fleet.snapshot()
    assert snap_f["replicas"] == 1 and snap_f["replicas_retired"] == 1
    sc.close()
    fleet.close()
    # close released the autoscale gauges too
    assert health_report(include_registry=False)["serve"][
        "autoscale"] == {"enabled": False}


def test_live_draining_replica_finishes_then_retires(model):
    """start_drain stops NEW routing but the replica completes its
    live work first; retire_replica refuses while work remains."""
    clk = FakeClock()
    fleet = ServeFleet(model, replicas=2, max_slots=2, clock=clk)
    work = _work(6, seed=4)
    hs = [fleet.submit(GenerationRequest(
        p, max_new_tokens=n, temperature=0.0)) for p, n in work]
    busy = next(i for i in range(2)
                if fleet.supervisor(i).pending)
    fleet.start_drain(busy)
    with pytest.raises(RuntimeError):
        fleet.retire_replica(busy)
    # new submissions route AWAY from the draining replica
    before = fleet.snapshot()["routed"][str(busy)]
    extra = _work(3, seed=5)
    hs += [fleet.submit(GenerationRequest(
        p, max_new_tokens=n, temperature=0.0)) for p, n in extra]
    assert fleet.snapshot()["routed"][str(busy)] == before
    fleet.run_until_complete(max_steps=500)
    for h in hs:
        h.result()
    assert fleet.drained(busy)
    fleet.retire_replica(busy)
    assert fleet.snapshot()["replicas"] == 1
    # retire without drain refuses typed
    with pytest.raises(ValueError):
        fleet.retire_replica(1 - busy)
    fleet.close()


def test_live_revive_reuses_retired_slot_and_add_replica_grows(model):
    clk = FakeClock()
    fleet = ServeFleet(model, replicas=1, max_slots=2, clock=clk)
    idx = fleet.add_replica()
    assert idx == 1 and fleet.replicas == 2
    fleet.start_drain(idx)
    assert fleet.drained(idx)
    fleet.retire_replica(idx)
    assert fleet.routable_replicas == 1
    fleet.revive(idx)
    assert fleet.routable_replicas == 2
    work = _work(4, seed=6)
    hs = [fleet.submit(GenerationRequest(
        p, max_new_tokens=n, temperature=0.0)) for p, n in work]
    fleet.run_until_complete(max_steps=500)
    for h in hs:
        h.result()
    # a symmetric fleet refuses role-typed growth
    with pytest.raises(ValueError):
        fleet.add_replica(role="prefill")
    with pytest.raises(ValueError):
        fleet.add_replica(role="nonsense")
    fleet.close()
    with pytest.raises(RuntimeError):
        fleet.add_replica()


def test_live_sharded_fleet_refuses_add_replica(model):
    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs a 4-device mesh")
    from singa_tpu.serve import PagedConfig

    fleet = ServeFleet(model, replicas=2, max_slots=2, tp=2,
                       paged=PagedConfig(block_size=8, num_blocks=32))
    with pytest.raises(ValueError, match="sharded"):
        fleet.add_replica()
    fleet.run_until_complete(max_steps=50)
    fleet.close()
