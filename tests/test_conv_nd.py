"""N-D convolution: 1-D/3-D forward vs torch golden, gradients, and
ONNX Conv import at non-2-D ranks (VERDICT r01 missing #6: conv import
hardcoded 2-D)."""

import numpy as np
import pytest

from singa_tpu import autograd, tensor
from singa_tpu.ops import conv as conv_ops
from singa_tpu.io.onnx_pb import (AttributeProto, GraphProto, ModelProto,
                                  NodeProto, TensorProto, ValueInfoProto)
from singa_tpu.io import onnx_pb
from singa_tpu import sonnx

torch = pytest.importorskip("torch")


def _t(a):
    return tensor.from_numpy(a)


@pytest.mark.parametrize("ndim,stride,pad,dil", [
    (1, 1, 0, 1), (1, 2, 1, 1), (1, 1, 2, 2),
    (3, 1, 0, 1), (3, 2, 1, 1),
])
def test_convnd_matches_torch(ndim, stride, pad, dil):
    rng = np.random.RandomState(0)
    spatial_x = {1: (16,), 3: (6, 7, 8)}[ndim]
    spatial_k = {1: (4,), 3: (3, 2, 3)}[ndim]
    x = rng.randn(2, 3, *spatial_x).astype(np.float32)
    w = rng.randn(5, 3, *spatial_k).astype(np.float32)
    b = rng.randn(5).astype(np.float32)

    y = conv_ops.conv2d(_t(x), _t(w), _t(b), stride=(stride,) * ndim,
                        padding=(pad,) * ndim, dilation=(dil,) * ndim)
    fn = {1: torch.nn.functional.conv1d,
          3: torch.nn.functional.conv3d}[ndim]
    ref = fn(torch.from_numpy(x), torch.from_numpy(w),
             torch.from_numpy(b), stride=stride, padding=pad,
             dilation=dil).numpy()
    np.testing.assert_allclose(tensor.to_numpy(y), ref, rtol=1e-4,
                               atol=1e-4)


def test_conv1d_gradients_flow():
    rng = np.random.RandomState(1)
    x = _t(rng.randn(2, 3, 12).astype(np.float32))
    w = tensor.Tensor((4, 3, 5))
    w.gaussian(0, 0.1)
    w.requires_grad = w.stores_grad = True
    autograd.set_training(True)
    try:
        y = conv_ops.conv2d(x, w, None, stride=(1,), padding=(2,),
                            dilation=(1,))
        loss = autograd.reduce_sum(autograd.mul(y, y), axes=None)
        grads = {id(p): g for p, g in autograd.backward(loss)}
        assert id(w) in grads
        assert grads[id(w)].shape == w.shape
    finally:
        autograd.set_training(False)


@pytest.mark.parametrize("ndim", [1, 3])
def test_onnx_conv_import_nd(ndim):
    """Hand-built ONNX Conv node at rank != 2 imports and matches."""
    rng = np.random.RandomState(2)
    spatial_x = {1: (10,), 3: (5, 6, 4)}[ndim]
    spatial_k = {1: (3,), 3: (2, 3, 2)}[ndim]
    x = rng.randn(1, 2, *spatial_x).astype(np.float32)
    w = rng.randn(3, 2, *spatial_k).astype(np.float32)

    node = NodeProto(op_type="Conv", name="c", input=["x", "w"],
                     output=["y"])
    node.attribute.append(AttributeProto.make(
        "kernel_shape", list(spatial_k)))
    node.attribute.append(AttributeProto.make(
        "pads", [1] * ndim + [1] * ndim))
    node.attribute.append(AttributeProto.make("strides", [1] * ndim))
    g = GraphProto(
        name="g", node=[node],
        initializer=[TensorProto.from_numpy(w, "w")],
        input=[ValueInfoProto(name="x", elem_type=onnx_pb.FLOAT,
                              shape=list(x.shape)),
               ValueInfoProto(name="w", elem_type=onnx_pb.FLOAT,
                              shape=list(w.shape))],
        output=[ValueInfoProto(name="y", elem_type=onnx_pb.FLOAT,
                               shape=[])])
    rep = sonnx.prepare(ModelProto(graph=g))
    out = tensor.to_numpy(rep.run([x])[0])

    fn = {1: torch.nn.functional.conv1d,
          3: torch.nn.functional.conv3d}[ndim]
    ref = fn(torch.from_numpy(x), torch.from_numpy(w), padding=1).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)
