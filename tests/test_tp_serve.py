"""Tensor-parallel serving (serve/tp.py + the engine's ``tp=`` mode):
token-stream parity against the single-device engine on the virtual
CPU mesh (cold / warm / int8 / GQA / speculative / preempt-resume,
greedy AND seeded sampling mixed in one pool), supervisor restart of a
sharded engine under an injected ``serve.tp_collective`` fault, typed
config validation, sharded-placement checks, and the observability
surface (``serve.tp.*`` metrics, stats/health sections).

The single-device engine is the oracle (itself parity-pinned against
single-prompt ``generate`` in tests/test_serve.py), so TP parity here
is transitively offline-oracle parity.  The TP twins' one arithmetic
difference is the per-shard psum (the row-parallel contraction is
summed per shard then reduced), so logits agree to float addition
order — on TOKEN streams that is identity away from exact ties, and
every workload below is seed-pinned deterministic."""

import numpy as np
import pytest

from singa_tpu import tensor
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from singa_tpu.observe import health_report
from singa_tpu.observe.registry import registry
from singa_tpu.resilience import FailAfterN, faults
from singa_tpu.serve import (EngineFailedError, EngineSupervisor,
                             GenerationRequest, PagedConfig,
                             PrefixCacheConfig, ServeFleet, TPConfig)


def _build(cfg):
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    return m


@pytest.fixture(scope="module")
def model():
    return _build(GPT2Config.tiny(dropout=0.0))


@pytest.fixture(scope="module")
def draft():
    return _build(GPT2Config.tiny(dropout=0.0, n_layer=1))


def _workload(seed, n, p_lo=3, p_hi=14, n_lo=2, n_hi=9, sampled=True):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        out.append(dict(
            prompt=rng.randint(0, 256, rng.randint(p_lo, p_hi))
            .astype(np.int32),
            n_new=int(rng.randint(n_lo, n_hi)),
            temperature=(float(rng.choice([0.0, 0.9]))
                         if sampled else 0.0),
            seed=int(rng.randint(0, 1000))))
    return out


def _run(m, work, max_slots=2, max_steps=4000, **kw):
    eng = m.serve(max_slots=max_slots, **kw)
    hs = [eng.submit(GenerationRequest(
        w["prompt"], max_new_tokens=w["n_new"],
        temperature=w["temperature"], seed=w["seed"]))
        for w in work]
    eng.run_until_complete(max_steps=max_steps)
    outs = [h.result().tokens for h in hs]
    snap = eng.stats.snapshot()
    eng.close()
    return outs, snap


def _parity(a, b):
    return all(np.array_equal(x, y) for x, y in zip(a, b))


def test_cold_parity_tp2(model):
    """TP=2 slot-arena streams (greedy and seeded sampling mixed in
    one pool) are token-identical to the single-device engine's, and
    the stats snapshot carries the tp section."""
    work = _workload(0, 7, sampled=True)
    base, _ = _run(model, work)
    outs, snap = _run(model, work, tp=2)
    assert _parity(outs, base)
    tp = snap["tp"]
    assert tp["shards"] == 2
    assert tp["sharded_dispatches"] > 0
    assert tp["kv_bytes_per_shard"] > 0
    assert tp["collectives_per_step"] == 2 * model.cfg.n_layer


def test_cold_parity_tp4(model):
    """The same engine at tp=4 on the 8-device virtual mesh."""
    work = _workload(1, 4, sampled=True)
    base, _ = _run(model, work)
    outs, snap = _run(model, work, tp=4)
    assert _parity(outs, base)
    assert snap["tp"]["shards"] == 4


def test_gqa_parity_tp2():
    """GQA models shard the NARROW H_kv cache: each shard owns
    H_kv/tp = 1 kv head serving its full query group."""
    m = _build(GPT2Config.tiny(dropout=0.0, n_kv_head=2))
    work = _workload(2, 5, n_lo=6, n_hi=14, p_lo=4, p_hi=16)
    base, _ = _run(m, work, max_slots=3)
    outs, _ = _run(m, work, max_slots=3, tp=2)
    assert _parity(outs, base)


def test_int8_parity_and_scales_sharding(model):
    """int8 arenas under TP: token parity vs the single-device int8
    engine, and the (values, scales) leaves are BOTH actually sharded
    on the H_kv axis (the scales leaf lacks the trailing D axis — the
    rank-generic cache spec must still land on axis 2)."""
    work = _workload(3, 5, sampled=True)
    base, _ = _run(model, work, cache_dtype="int8")

    eng = model.serve(max_slots=2, tp=2, cache_dtype="int8")
    try:
        vals, scales = eng._kc
        H = model.cfg.n_kv_head
        # global shapes keep the full head axis; each shard's
        # addressable piece holds H/2 heads of values AND scales
        assert vals.shape[2] == H and scales.shape[2] == H
        assert vals.addressable_shards[0].data.shape[2] == H // 2
        assert scales.addressable_shards[0].data.shape[2] == H // 2
        hs = [eng.submit(GenerationRequest(
            w["prompt"], max_new_tokens=w["n_new"],
            temperature=w["temperature"], seed=w["seed"]))
            for w in work]
        eng.run_until_complete(max_steps=4000)
        outs = [h.result().tokens for h in hs]
    finally:
        eng.close(force=True)
    assert _parity(outs, base)


def test_spec_parity_tp2(model, draft):
    """Speculative decoding on a sharded TARGET with a fully
    REPLICATED draft: streams equal the single-device engine's (the
    draft proposes identically on every shard; the verify chunk is
    the sharded dispatch)."""
    work = _workload(4, 5, n_lo=4, n_hi=12, sampled=False)
    base, _ = _run(model, work, max_slots=3)
    outs, snap = _run(model, work, max_slots=3, tp=2,
                      draft_model=draft, spec_k=3)
    assert _parity(outs, base)
    assert snap["spec"]["chunks"] > 0


def test_paged_preempt_resume_parity_tp2(model):
    """The paged pool sharded per shard on H_kv: an over-committed
    pool forces preemption/swap mid-decode, the host copy carries the
    FULL head axis (np.asarray assembles the global row), and resumed
    TP streams equal the uninterrupted single-device run's."""
    work = _workload(5, 6, n_lo=12, n_hi=30, p_lo=4, p_hi=20,
                     sampled=True)
    base, _ = _run(model, work, max_slots=4)
    outs, snap = _run(model, work, max_slots=4, tp=2,
                      paged=PagedConfig(block_size=8, num_blocks=10))
    assert _parity(outs, base)
    pg = snap["paged"]
    assert pg["preemptions"] > 0 and pg["swap_in"] > 0
    assert pg["blocks_used"] == 0, "leaked blocks after drain"


def test_warm_prefix_parity_tp2(model):
    """Prefix-cache rows as sharded pytrees: a shared system prompt
    makes later admissions warm (sharded gather + sharded chunk
    prefill), streams byte-identical to the single-device engine."""
    rng = np.random.RandomState(6)
    system = rng.randint(0, 256, 40).astype(np.int32)
    work = [dict(prompt=np.concatenate(
        [system, rng.randint(0, 256, rng.randint(3, 8))
         .astype(np.int32)]),
        n_new=6, temperature=0.0, seed=int(rng.randint(0, 1000)))
        for _ in range(5)]
    base, _ = _run(model, work)
    outs, snap = _run(model, work, tp=2,
                      prefix_cache=PrefixCacheConfig(block_size=8,
                                                     num_blocks=64))
    assert _parity(outs, base)
    assert snap["prefix"]["hits"] > 0, "workload never went warm"


def test_supervisor_restart_tp2(model):
    """An injected ``serve.tp_collective`` fault fails the sharded
    engine TYPED mid-decode; the supervisor rebuilds it (same device
    group, twin-cache hit) and requeued never-started streams keep
    parity.  Zero wedged handles."""
    work = _workload(7, 6, n_lo=4, n_hi=10, sampled=True)
    base, _ = _run(model, work)
    restarts0 = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0)
    sup = EngineSupervisor(model, max_slots=2, restart_budget=2, tp=2)
    hs = [sup.submit(GenerationRequest(
        w["prompt"], max_new_tokens=w["n_new"],
        temperature=w["temperature"], seed=w["seed"]))
        for w in work]
    pol = faults.inject("serve.tp_collective", FailAfterN(3, times=1))
    try:
        sup.run_until_complete(max_steps=4000)
    finally:
        faults.clear()
    assert pol.fired == 1
    restarts = registry().snapshot()["counters"].get(
        "resilience.engine_restarts", 0) - restarts0
    assert restarts == 1
    completed = typed = 0
    for i, h in enumerate(hs):
        assert h.done(), "wedged handle after TP restart"
        try:
            got = h.result().tokens
            assert np.array_equal(got, base[i])
            completed += 1
        except EngineFailedError as e:
            assert e.started is True
            typed += 1
    assert completed + typed == len(work)
    assert completed > 0
    sup.close()


def test_fleet_of_tp_replicas(model):
    """serve_fleet(tp=2, replicas=2) partitions the 8-device mesh into
    disjoint 2-wide groups; streams keep parity with the single-device
    engine and both replicas carry traffic."""
    work = _workload(8, 8, sampled=True)
    base, _ = _run(model, work, max_slots=4)
    fleet = ServeFleet(model, replicas=2, max_slots=2, tp=2)
    try:
        d0 = fleet.supervisor(0).engine.tp_exec.mesh.devices.flat
        d1 = fleet.supervisor(1).engine.tp_exec.mesh.devices.flat
        assert {d.id for d in d0}.isdisjoint({d.id for d in d1})
        hs = [fleet.submit(GenerationRequest(
            w["prompt"], max_new_tokens=w["n_new"],
            temperature=w["temperature"], seed=w["seed"]))
            for w in work]
        fleet.run_until_complete(max_steps=4000)
        outs = [h.result().tokens for h in hs]
        snap = fleet.snapshot()
    finally:
        fleet.close()
    assert _parity(outs, base)
    assert all(v > 0 for v in snap["routed"].values())


def test_config_validation(model):
    """Every incompatible tp configuration is a typed construction
    error, never a shape blow-up inside a shard_map trace."""
    # tp not dividing n_head (tiny: n_head=4)
    with pytest.raises(ValueError, match="does not divide n_head"):
        model.serve(max_slots=2, tp=3)
    # tp not dividing H_kv (GQA narrow cache)
    mg = _build(GPT2Config.tiny(dropout=0.0, n_kv_head=2))
    with pytest.raises(ValueError, match="H_kv"):
        mg.serve(max_slots=2, tp=4)
    # tp wider than the mesh
    with pytest.raises(ValueError, match="devices"):
        model.serve(max_slots=2, tp=16)
    # tp x replicas exceeding the mesh (8-device conftest topology)
    with pytest.raises(ValueError, match="exceeds"):
        ServeFleet(model, replicas=5, max_slots=2, tp=2)
    # bad knob type
    with pytest.raises(ValueError, match="TPConfig"):
        model.serve(max_slots=2, tp="wide")
    # tp=1 is simply off
    eng = model.serve(max_slots=2, tp=1)
    assert eng.tp_exec is None
    eng.close()
    # explicit TPConfig passes through
    eng = model.serve(max_slots=2, tp=TPConfig(tp=2))
    assert eng.tp_exec is not None and eng.tp_exec.tp == 2
    eng.close()


def test_twin_cache_keyed_on_model_structure(model, draft):
    """Two TP engines for DIFFERENT-depth models with identical
    statics on the same device group must not share a sharded twin:
    the twin's in_specs closure bakes the params spec tree in, and the
    first model's 2-layer blocks list is not a valid prefix for the
    1-layer draft's pytree (review finding — the module-wide cache key
    now includes the param treedef)."""
    work = _workload(9, 3)
    base2, _ = _run(model, work)
    outs2, _ = _run(model, work, tp=2)       # 2-layer twins cached
    base1, _ = _run(draft, work)
    outs1, _ = _run(draft, work, tp=2)       # 1-layer: same statics
    assert _parity(outs2, base2)
    assert _parity(outs1, base1)


def test_moe_model_refused():
    """MoE blocks shard over the expert axis, not tp: typed refusal
    at construction, and the message is the CONTRACT — it must name
    the ``serve(ep=)`` path that does serve this model (the EP/PP
    round's rewritten refusal; serve/ep.py)."""
    m = _build(GPT2Config.tiny(dropout=0.0, moe_every=2,
                               moe_experts=2))
    from singa_tpu.observe.registry import registry

    def tp_gauges():
        return {k for k in registry().snapshot()["gauges"]
                if k.startswith("serve.tp.")}

    before = tp_gauges()
    with pytest.raises(NotImplementedError,
                       match=r"serve\(ep=EPConfig"):
        m.serve(max_slots=2, tp=2)
    # the refusal fired BEFORE the executor registered anything: a
    # failed construction must leak no serve.tp gauges (the PR-12
    # leaked-gauge hazard, audited for the rewritten refusal)
    assert tp_gauges() == before


def test_metrics_and_health_surface(model):
    """serve.tp.* metrics register per engine, surface in
    health_report()["serve"]["tp"], and unregister at close."""
    eng = model.serve(max_slots=2, tp=2)
    try:
        h = eng.submit(GenerationRequest(
            np.arange(5, dtype=np.int32), max_new_tokens=3))
        eng.run_until_complete(max_steps=200)
        h.result()
        rep = health_report(include_registry=False)
        tp = rep["serve"]["tp"]
        assert tp["shards"] == 2
        assert tp["kv_bytes_per_shard"] > 0
        assert tp["sharded_dispatches"] > 0
        assert tp["collectives_per_step"] == 2 * model.cfg.n_layer
    finally:
        eng.close()
    snap = registry().snapshot()["gauges"]
    lbl = f"serve.tp.shards{{engine={eng.stats.engine_label}}}"
    assert lbl not in snap, "tp metrics leaked past close()"
    # the section stays present (zeroed) with no live TP engine
    rep = health_report(include_registry=False)
    assert "tp" in rep["serve"]
