"""GPT-2: shapes, weight tying, training convergence, parallel plan
(tp/sp + MoE blocks), generation."""

import numpy as np
import pytest

from singa_tpu import tensor, opt
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from singa_tpu.parallel import sharding as shd

B, S = 4, 16


def _cfg(**kw):
    kw.setdefault("dropout", 0.0)
    return GPT2Config.tiny(**kw)


def _batch(cfg, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    # next-token labels: shift left, last position predicts ids[0]
    labels = np.roll(ids, -1, axis=1).astype(np.int32)
    return ids, labels


def test_forward_shapes_and_param_count():
    cfg = _cfg()
    m = GPT2LMHead(cfg)
    ids, _ = _batch(cfg)
    x = tensor.from_numpy(ids)
    m.compile([x], is_train=False, use_graph=False)
    logits = m.forward(x)
    assert logits.shape == (B, S, cfg.vocab_size)
    # weight tying: no separate lm_head param; untied adds vocab*embd
    tied_n = sum(np.prod(t.shape) for t in m.get_params().values())
    m2 = GPT2LMHead(_cfg(tie_weights=False))
    m2.compile([x], is_train=False, use_graph=False)
    untied_n = sum(np.prod(t.shape) for t in m2.get_params().values())
    assert untied_n - tied_n == cfg.vocab_size * cfg.n_embd


def test_trains_graph_mode():
    cfg = _cfg()
    m = GPT2LMHead(cfg)
    m.set_optimizer(opt.Adam(lr=1e-3))
    ids, labels = _batch(cfg)
    x = tensor.from_numpy(ids)
    m.compile([x], is_train=True, use_graph=True)
    losses = []
    for i in range(15):
        _, loss = m(tensor.from_numpy(ids), tensor.from_numpy(labels))
        losses.append(float(tensor.to_numpy(loss)))
    assert losses[-1] < losses[0] - 0.5, losses


@pytest.mark.slow
def test_ignore_index_mean_over_valid_positions():
    """label -1 positions contribute zero loss AND the mean divides by
    the valid count (standard ignore_index semantics) — a half-ignored
    batch must NOT report half the loss."""
    cfg = _cfg()
    ids, labels = _batch(cfg)
    x = tensor.from_numpy(ids)

    def loss_with(lab):
        from singa_tpu import device as device_module
        device_module.get_default_device().SetRandSeed(0)
        m = GPT2LMHead(cfg)
        m.set_optimizer(opt.SGD(lr=0.0))
        m.compile([x], is_train=True, use_graph=False)
        _, loss = m(tensor.from_numpy(ids), tensor.from_numpy(lab))
        return float(tensor.to_numpy(loss))

    full = loss_with(labels)
    half = labels.copy()
    half[:, S // 2:] = -1  # ignore the second half of every row
    got = loss_with(half)
    # at init the per-position CE is ~uniform (~log V), so the mean over
    # the valid half must track the full mean, not half of it
    assert abs(got - full) < 0.35 * full, (got, full)
    assert got > 0.6 * full, (got, full)


def test_tied_head_gradient_reaches_embedding():
    cfg = _cfg()
    m = GPT2LMHead(cfg)
    m.set_optimizer(opt.SGD(lr=0.5))
    ids, labels = _batch(cfg)
    x = tensor.from_numpy(ids)
    m.compile([x], is_train=True, use_graph=False)
    w0 = tensor.to_numpy(m.transformer.wte.W).copy()
    m(tensor.from_numpy(ids), tensor.from_numpy(labels))
    assert not np.allclose(tensor.to_numpy(m.transformer.wte.W), w0)


@pytest.mark.slow
def test_parallel_gpt_moe_matches_serial():
    """dp2 x tp2 x sp2 GPT with a MoE block == serial twin (the serial
    oracle pins moe_groups=2 to reproduce the plan's grouped routing)."""
    cfg = _cfg(moe_every=2, moe_experts=4, moe_groups=2)
    mesh = shd.create_mesh(dp=2, tp=2, sp=2)
    plan = shd.ShardingPlan(mesh)

    serial = GPT2LMHead(cfg)
    par = GPT2LMHead(cfg, plan=plan)
    par.set_sharding_plan(plan)
    ids, labels = _batch(cfg)
    for m in (serial, par):
        m.set_optimizer(opt.SGD(lr=0.05))
        m.compile([tensor.from_numpy(ids)], is_train=True, use_graph=True)
    par.set_states({k: tensor.to_numpy(v)
                    for k, v in serial.get_states().items()})
    for i in range(2):
        ids, labels = _batch(cfg, seed=i)
        _, ls = serial(tensor.from_numpy(ids), tensor.from_numpy(labels))
        _, lp = par(tensor.from_numpy(ids), tensor.from_numpy(labels))
        np.testing.assert_allclose(float(tensor.to_numpy(lp)),
                                   float(tensor.to_numpy(ls)), rtol=3e-4)


@pytest.mark.slow
def test_flash_attn_impl_matches_fused():
    """attn_impl="flash" (Pallas online softmax; interpret mode on CPU)
    must reproduce the fused S x S path's logits and one training
    step."""
    from singa_tpu import device as device_module

    ids, labels = _batch(_cfg())
    losses = {}
    for impl in ("fused", "flash"):
        device_module.get_default_device().SetRandSeed(0)
        cfg = _cfg(attn_impl=impl)
        m = GPT2LMHead(cfg)
        m.set_optimizer(opt.SGD(lr=0.1))
        m.compile([tensor.from_numpy(ids)], is_train=True, use_graph=True)
        _, loss = m(tensor.from_numpy(ids), tensor.from_numpy(labels))
        _, loss = m(tensor.from_numpy(ids), tensor.from_numpy(labels))
        losses[impl] = float(tensor.to_numpy(loss))
    np.testing.assert_allclose(losses["flash"], losses["fused"],
                               rtol=2e-4)
    # the flash op records the same TPAttention name+params as fused,
    # so ONNX export covers flash-built models too
    from singa_tpu import sonnx

    m.eval()
    x = tensor.from_numpy(ids)
    proto = sonnx.to_onnx(m, [x])
    rep = sonnx.prepare(proto)
    native = tensor.to_numpy(m.forward(x))
    np.testing.assert_allclose(tensor.to_numpy(rep.run([x])[0]), native,
                               rtol=2e-3, atol=2e-4)


def test_generate():
    cfg = _cfg()
    m = GPT2LMHead(cfg)
    ids, _ = _batch(cfg)
    m.compile([tensor.from_numpy(ids)], is_train=False, use_graph=False)
    out = m.generate(np.asarray([1, 2, 3]), max_new_tokens=5,
                     temperature=0.0)
    assert out.shape == (8,)
    assert (out[:3] == [1, 2, 3]).all()
    assert ((0 <= out) & (out < cfg.vocab_size)).all()


def test_kv_cache_generate_matches_windowed_greedy():
    """The KV-cached incremental decoder (models/gpt2_decode.py) must
    reproduce the windowed full-forward sampler token for token under
    greedy decoding."""
    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    x = tensor.from_numpy(np.zeros((1, 16), np.int32))
    m.compile([x], is_train=False, use_graph=False)
    prompt = np.arange(9) % cfg.vocab_size
    g_win = m.generate(prompt, max_new_tokens=12, temperature=0,
                       use_cache=False)
    g_kv = m.generate(prompt, max_new_tokens=12, temperature=0,
                      use_cache=True)
    np.testing.assert_array_equal(g_win, g_kv)
    assert g_kv[:9].tolist() == prompt.tolist()


def test_gqa_trains_and_kv_decode_matches_windowed():
    """Grouped-query attention (n_kv_head < n_head): k/v project to
    n_kv_head heads, training converges, and the KV-cached decoder —
    whose cache stays at n_kv_head heads, the whole point of GQA at
    decode — reproduces the windowed full-forward sampler token for
    token under greedy decoding."""
    import jax.numpy as jnp

    from singa_tpu.models import gpt2_decode

    cfg = _cfg(n_kv_head=2)  # tiny: n_head=4 -> query groups of 2
    m = GPT2LMHead(cfg)
    m.set_optimizer(opt.Adam(lr=1e-3))
    ids, labels = _batch(cfg)
    x = tensor.from_numpy(ids)
    m.compile([x], is_train=True, use_graph=True)
    losses = []
    for _ in range(15):
        _, loss = m(tensor.from_numpy(ids), tensor.from_numpy(labels))
        losses.append(float(tensor.to_numpy(loss)))
    assert losses[-1] < losses[0] - 0.5, losses
    # the K/V projections really are narrower (E -> E/2 here)
    attn = m.transformer.blocks[0].attn
    assert attn.k_proj.W.shape[1] * 2 == attn.q_proj.W.shape[1]
    assert attn.v_proj.W.shape[1] * 2 == attn.q_proj.W.shape[1]

    m.eval()
    prompt = np.arange(9) % cfg.vocab_size
    g_win = m.generate(prompt, max_new_tokens=12, temperature=0,
                       use_cache=False)
    g_kv = m.generate(prompt, max_new_tokens=12, temperature=0,
                      use_cache=True)
    np.testing.assert_array_equal(g_win, g_kv)
    # the decode cache holds n_kv_head heads, not n_head
    params = gpt2_decode.extract_params(m)
    _, kc, vc = gpt2_decode.prefill(
        params, jnp.asarray(ids[:1]), cfg.n_head, cfg.layer_norm_eps)
    assert kc.shape[2] == cfg.n_kv_head, kc.shape
    assert vc.shape[2] == cfg.n_kv_head, vc.shape


def test_gqa_batched_and_beam_paths_match_oracle():
    """The uniform fast path, ragged left-padded path, and batched beam
    search all run the grouped cache math; each must agree with its
    per-row/windowed oracle on a GQA model."""
    from singa_tpu.models import gpt2_decode

    cfg = _cfg(n_kv_head=1)  # extreme grouping: MQA (4 Q : 1 KV)
    m = GPT2LMHead(cfg)
    x = tensor.from_numpy(np.zeros((1, 16), np.int32))
    m.compile([x], is_train=False, use_graph=False)
    m.eval()
    prompts = [np.arange(5) % cfg.vocab_size,
               np.arange(9) % cfg.vocab_size]  # ragged pair
    batched = m.generate(prompts, max_new_tokens=8, temperature=0)
    singles = [m.generate(p, max_new_tokens=8, temperature=0)
               for p in prompts]
    for row, single, p in zip(batched, singles, prompts):
        np.testing.assert_array_equal(row[len(p):len(p) + 8],
                                      single[len(p):])
    # beam search runs the same grouped cache math; num_beams=1 is
    # contractually greedy
    beam1 = gpt2_decode.generate_beam(m, prompts[1], max_new_tokens=8,
                                      num_beams=1)
    np.testing.assert_array_equal(beam1, singles[1])
    beam4 = gpt2_decode.generate_beam(m, prompts[1], max_new_tokens=8,
                                      num_beams=4)
    assert beam4.shape == singles[1].shape


def test_gqa_config_validates_group():
    with pytest.raises(ValueError):
        GPT2Config.tiny(n_kv_head=3)  # 4 % 3 != 0


def test_sliding_window_decode_matches_windowed_sampler():
    """attn_window: the KV decoder keeps an O(window) ROLLING cache
    (position pos lives in slot pos % window) and must match the
    full-forward sampler, whose band mask comes from the training
    stack (_sdpa window) — token for token under greedy decoding."""
    import jax.numpy as jnp

    from singa_tpu.models import gpt2_decode

    cfg = _cfg(attn_window=6, n_positions=64)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    m.eval()
    p = (np.arange(11) * 7) % cfg.vocab_size
    kv = m.generate(p, max_new_tokens=14, temperature=0)
    win = m.generate(p, max_new_tokens=14, temperature=0,
                     use_cache=False)
    np.testing.assert_array_equal(kv, win)
    # the cache really is rolling: window slots, not n_positions
    params = gpt2_decode.extract_params(m)
    ids = np.zeros((1, 16), np.int32)
    ids[0, :11] = p
    _, kc, _ = gpt2_decode.prefill(
        params, jnp.asarray(ids), cfg.n_head, cfg.layer_norm_eps,
        window=6, prompt_end=11)
    assert kc.shape[3] == 6, kc.shape
    # a window covering the whole position space is normalized away
    big = _cfg(attn_window=128, n_positions=64)
    m2 = GPT2LMHead(big)
    m2.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
               is_train=False, use_graph=False)
    m2.eval()
    assert gpt2_decode._norm_window(big) is None
    g2 = m2.generate(p, max_new_tokens=8, temperature=0)
    assert g2.shape == (19,)


def test_sliding_window_band_semantics():
    """Receptive-field check: with L layers and window W, a query at
    distance > L·(W−1) from a changed token must be invariant (the
    band composes across layers); a dense model is the positive
    control."""
    import jax.numpy as jnp

    from singa_tpu.models import gpt2_decode

    def probe(attn_window):
        cfg = _cfg(n_positions=64, **({} if attn_window is None
                                      else {"attn_window": attn_window}))
        m = GPT2LMHead(cfg)
        m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
                  is_train=False, use_graph=False)
        m.eval()
        params = gpt2_decode.extract_params(m)
        ids = np.zeros((1, 16), np.int32)
        ids[0, :12] = (np.arange(12) * 5) % cfg.vocab_size
        kw = ({} if attn_window is None
              else dict(window=attn_window, prompt_end=12))
        h1, *_ = gpt2_decode.prefill(
            params, jnp.asarray(ids), cfg.n_head, cfg.layer_norm_eps,
            **kw)
        ids2 = ids.copy()
        ids2[0, 0] = (ids2[0, 0] + 3) % cfg.vocab_size
        h2, *_ = gpt2_decode.prefill(
            params, jnp.asarray(ids2), cfg.n_head, cfg.layer_norm_eps,
            **kw)
        return np.allclose(np.asarray(h1)[0, 11],
                           np.asarray(h2)[0, 11], atol=1e-6)

    # tiny = 2 layers: distance 11 > 2·(6−1) = 10 ⇒ invariant
    assert probe(6)
    assert not probe(None)  # dense: token 0 reaches position 11


def test_sliding_window_composes_and_validates():
    """window x GQA x int8 cache x ragged batch x beams in one model;
    invalid windows and the unimplemented ring composition fail
    loudly."""
    from singa_tpu.models import gpt2_decode
    from singa_tpu.parallel.tensor_parallel import ParallelMHA

    cfg = _cfg(attn_window=6, n_positions=64, n_kv_head=2)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    m.eval()
    p = (np.arange(11) * 7) % cfg.vocab_size
    outs = gpt2_decode.generate(m, [p[:5], p], max_new_tokens=6,
                                temperature=0, cache_dtype="int8")
    assert [len(o) for o in outs] == [11, 17]
    # ragged window decode equals per-row singles (greedy determinism)
    plain = gpt2_decode.generate(m, [p[:5], p], max_new_tokens=6,
                                 temperature=0)
    for row, pr in zip(plain, [p[:5], p]):
        single = m.generate(pr, max_new_tokens=6, temperature=0)
        np.testing.assert_array_equal(row, single)
    beam = gpt2_decode.generate_beam(m, p, max_new_tokens=5,
                                     num_beams=2)
    assert beam.shape == (16,)
    with pytest.raises(ValueError):
        GPT2Config.tiny(attn_window=0)
    with pytest.raises(ValueError):
        ParallelMHA(4, causal=False, window=8)  # window needs causal


def test_sliding_window_trains_and_exports():
    """The training stack's band mask: a windowed model trains in
    graph mode, and ONNX export bakes the BAND (tril ∧ i−j<W) mask —
    the imported graph reproduces the native logits."""
    from singa_tpu import sonnx

    cfg = _cfg(attn_window=5, n_positions=64)
    m = GPT2LMHead(cfg)
    m.set_optimizer(opt.Adam(lr=1e-3))
    ids, labels = _batch(cfg)
    x = tensor.from_numpy(ids)
    m.compile([x], is_train=True, use_graph=True)
    losses = []
    for _ in range(10):
        _, loss = m(tensor.from_numpy(ids), tensor.from_numpy(labels))
        losses.append(float(tensor.to_numpy(loss)))
    assert losses[-1] < losses[0], losses
    m.eval()
    logits = m.forward(x)
    rep = sonnx.prepare(sonnx.to_onnx(m, [x]), x.device)
    out = rep.run([ids])[0]
    np.testing.assert_allclose(tensor.to_numpy(out),
                               tensor.to_numpy(logits), rtol=1e-4,
                               atol=1e-5)


def test_repetition_penalty_breaks_loops_and_paths_match():
    """repetition_penalty (CTRL semantics: seen tokens divided when
    positive, multiplied when negative — applied before greedy argmax)
    must act identically on the KV-cached and windowed paths (greedy ⇒
    deterministic), change the output of a looping greedy generation,
    and work for ragged batches (the presence mask must ignore the
    left-pad zeros)."""
    cfg = _cfg()
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    m.eval()
    prompt = np.arange(9) % cfg.vocab_size
    plain = m.generate(prompt, max_new_tokens=10, temperature=0)
    kv = m.generate(prompt, max_new_tokens=10, temperature=0,
                    repetition_penalty=1.5)
    win = m.generate(prompt, max_new_tokens=10, temperature=0,
                     repetition_penalty=1.5, use_cache=False)
    np.testing.assert_array_equal(kv, win)
    assert not np.array_equal(plain, kv)
    # ragged batch: each row must equal its single-prompt generation
    # (start-aware presence init — pad zeros are NOT marked seen)
    prompts = [prompt[:5], prompt]
    outs = m.generate(prompts, max_new_tokens=8, temperature=0,
                      repetition_penalty=1.5)
    for row, p in zip(outs, prompts):
        single = m.generate(p, max_new_tokens=8, temperature=0,
                            repetition_penalty=1.5)
        np.testing.assert_array_equal(row, single)


def test_min_p_one_equals_greedy():
    """min_p=1.0 keeps only tokens tied with the max-probability token,
    so sampling at any temperature reduces to greedy."""
    cfg = _cfg()
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    m.eval()
    prompt = np.arange(9) % cfg.vocab_size
    greedy = m.generate(prompt, max_new_tokens=10, temperature=0)
    sampled = m.generate(prompt, max_new_tokens=10, temperature=1.0,
                         min_p=1.0, rng=np.random.RandomState(0))
    np.testing.assert_array_equal(greedy, sampled)


def test_sampling_extras_validate():
    cfg = _cfg()
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    m.eval()
    p = np.arange(5)
    for kw in ({"min_p": 0.0}, {"min_p": 1.5},
               {"repetition_penalty": 0.0},
               {"repetition_penalty": -2.0}):
        with pytest.raises(ValueError):
            m.generate(p, max_new_tokens=2, **kw)


def test_int8_cache_decode_matches_dense_on_trained_model():
    """cache_dtype="int8" stores the KV cache as (int8, per-row f32
    scale).  On a TRAINED model (decisive logits — quantization noise
    in the scores must not flip the argmax) greedy decoding matches
    the dense-cache path token for token, and the cache arrays really
    are int8."""
    import jax.numpy as jnp

    from singa_tpu import device as device_module
    from singa_tpu.models import gpt2_decode

    # seed the init: with urandom weights the trained logit margins are
    # occasionally thin enough for int8 noise to flip a greedy argmax
    device_module.get_default_device().SetRandSeed(0)
    cfg = _cfg()
    m = GPT2LMHead(cfg)
    m.set_optimizer(opt.Adam(lr=1e-3))
    ids, labels = _batch(cfg)
    x = tensor.from_numpy(ids)
    m.compile([x], is_train=True, use_graph=True)
    for _ in range(15):
        m(tensor.from_numpy(ids), tensor.from_numpy(labels))
    m.eval()
    prompt = ids[0, :9]
    g_dense = gpt2_decode.generate(m, prompt, max_new_tokens=12,
                                   temperature=0)
    g_int8 = gpt2_decode.generate(m, prompt, max_new_tokens=12,
                                  temperature=0, cache_dtype="int8")
    np.testing.assert_array_equal(g_dense, g_int8)

    params = gpt2_decode.extract_params(m)
    _, kc, vc = gpt2_decode.prefill(
        params, jnp.asarray(ids[:1]), cfg.n_head, cfg.layer_norm_eps,
        quant_cache=True)
    assert isinstance(kc, tuple) and kc[0].dtype == jnp.int8
    assert kc[1].dtype == jnp.float32 and kc[1].shape == kc[0].shape[:-1]
    assert isinstance(vc, tuple) and vc[0].dtype == jnp.int8


def test_int8_cache_prefill_logits_close():
    """Teacher-forced bound on the quantization error: int8-cache
    prefill hidden states equal the dense ones (quantization only
    touches what DECODE reads back; prefill attention uses the
    unquantized k/v), and a quantize/dequantize round trip of the
    cache itself stays within the symmetric-int8 error bound."""
    import jax.numpy as jnp

    from singa_tpu.models import gpt2_decode

    cfg = _cfg()
    m = GPT2LMHead(cfg)
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (1, 16)).astype(np.int32)
    m.compile([tensor.from_numpy(ids)], is_train=False, use_graph=False)
    m.eval()
    params = gpt2_decode.extract_params(m)
    h_dense, kc, _ = gpt2_decode.prefill(
        params, jnp.asarray(ids), cfg.n_head, cfg.layer_norm_eps)
    h_quant, kcq, _ = gpt2_decode.prefill(
        params, jnp.asarray(ids), cfg.n_head, cfg.layer_norm_eps,
        quant_cache=True)
    np.testing.assert_allclose(np.asarray(h_quant), np.asarray(h_dense),
                               rtol=1e-6, atol=1e-6)
    deq = gpt2_decode._dequantize_kv(kcq[0], kcq[1], jnp.float32)
    err = np.abs(np.asarray(deq) - np.asarray(kc))
    bound = np.asarray(kcq[1])[..., None] * 0.5 + 1e-6
    assert (err <= bound).all(), err.max()


def test_int8_cache_composes_with_gqa_ragged_and_beams():
    """int8 cache x GQA x ragged batch x beam search all in one: the
    quantized grouped cache decodes a ragged batch and a beam search
    without shape errors, and num_beams=1 equals greedy under the SAME
    cache_dtype (both paths see identical quantization noise)."""
    from singa_tpu.models import gpt2_decode

    cfg = _cfg(n_kv_head=2)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    m.eval()
    prompts = [np.arange(5) % cfg.vocab_size,
               np.arange(9) % cfg.vocab_size]
    outs = gpt2_decode.generate(m, prompts, max_new_tokens=6,
                                temperature=0, cache_dtype="int8")
    assert [len(o) for o in outs] == [11, 15]
    g = gpt2_decode.generate(m, prompts[1], max_new_tokens=6,
                             temperature=0, cache_dtype="int8")
    b1 = gpt2_decode.generate_beam(m, prompts[1], max_new_tokens=6,
                                   num_beams=1, cache_dtype="int8")
    np.testing.assert_array_equal(b1, g)
    with pytest.raises(ValueError, match="cache_dtype"):
        gpt2_decode.generate(m, prompts[1], max_new_tokens=2,
                             cache_dtype="int4")


def test_parallel_gqa_matches_serial():
    """GQA under an active ShardingPlan (dp2 x tp2 x sp2): the
    RepeatKV-then-constrain resharding and the KV-head/model-axis split
    must reproduce the serial GQA twin's losses — both K/V heads land
    on different model shards (n_kv_head=2 == tp axis size)."""
    cfg = _cfg(n_kv_head=2)
    mesh = shd.create_mesh(dp=2, tp=2, sp=2)
    plan = shd.ShardingPlan(mesh)

    serial = GPT2LMHead(cfg)
    par = GPT2LMHead(cfg, plan=plan)
    par.set_sharding_plan(plan)
    ids, labels = _batch(cfg)
    for m in (serial, par):
        m.set_optimizer(opt.SGD(lr=0.05))
        m.compile([tensor.from_numpy(ids)], is_train=True, use_graph=True)
    par.set_states({k: tensor.to_numpy(v)
                    for k, v in serial.get_states().items()})
    for i in range(2):
        ids, labels = _batch(cfg, seed=i)
        _, ls = serial(tensor.from_numpy(ids), tensor.from_numpy(labels))
        _, lp = par(tensor.from_numpy(ids), tensor.from_numpy(labels))
        np.testing.assert_allclose(float(tensor.to_numpy(lp)),
                                   float(tensor.to_numpy(ls)), rtol=3e-4)


def test_gqa_kv_heads_must_divide_model_axis():
    """n_kv_head not divisible by the model-axis size must fail loudly
    at construction, not mis-shard."""
    from singa_tpu.parallel.tensor_parallel import ParallelMHA

    mesh = shd.create_mesh(dp=2, tp=4)
    plan = shd.ShardingPlan(mesh)
    with pytest.raises(ValueError, match="num_kv_heads"):
        ParallelMHA(8, plan, num_kv_heads=2)  # 2 % 4 != 0


def test_kv_cache_prefill_logits_match_forward():
    """Teacher-forced check with no argmax involved: the pure-jnp
    prefill logits must match the layer-stack forward at every
    position."""
    import jax.numpy as jnp

    from singa_tpu.models import gpt2_decode

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (1, 16)).astype(np.int32)
    x = tensor.from_numpy(ids)
    m.compile([x], is_train=False, use_graph=False)
    m.eval()
    ref = tensor.to_numpy(m.forward(x))
    params = gpt2_decode.extract_params(m)
    hidden, _, _ = gpt2_decode.prefill(params, jnp.asarray(ids),
                                       cfg.n_head, cfg.layer_norm_eps)
    got = gpt2_decode._logits(hidden, params)
    np.testing.assert_allclose(np.asarray(got), ref, atol=2e-3, rtol=1e-3)


def test_kv_cache_rejects_over_length_and_falls_back():
    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    x = tensor.from_numpy(np.zeros((1, 16), np.int32))
    m.compile([x], is_train=False, use_graph=False)
    long_prompt = np.zeros(cfg.n_positions - 2, np.int32)
    # auto mode falls back to the windowed sampler instead of raising
    out = m.generate(long_prompt, max_new_tokens=5, temperature=0)
    assert len(out) == len(long_prompt) + 5
    import pytest as _pytest

    from singa_tpu.models import gpt2_decode
    with _pytest.raises(ValueError):
        gpt2_decode.generate(m, long_prompt, max_new_tokens=5)


def test_generate_zero_tokens_returns_prompt():
    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    x = tensor.from_numpy(np.zeros((1, 16), np.int32))
    m.compile([x], is_train=False, use_graph=False)
    prompt = np.arange(5) % cfg.vocab_size
    out = m.generate(prompt, max_new_tokens=0, temperature=0)
    np.testing.assert_array_equal(out, prompt)


def test_generate_default_rng_not_deterministic():
    """rng=None temperature sampling must differ across calls (parity
    with the windowed sampler's np.random fallback)."""
    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    x = tensor.from_numpy(np.zeros((1, 16), np.int32))
    m.compile([x], is_train=False, use_graph=False)
    prompt = np.arange(5) % cfg.vocab_size
    outs = {tuple(m.generate(prompt, max_new_tokens=8,
                             temperature=1.0).tolist())
            for _ in range(4)}
    assert len(outs) > 1, "identical samples across calls"


def test_batched_decode_matches_single_rows():
    """Ragged batched KV-cache decoding (one vmapped executable) must
    reproduce each prompt's single-row greedy decode token for token."""
    from singa_tpu.models import gpt2_decode

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    x = tensor.from_numpy(np.zeros((1, 16), np.int32))
    m.compile([x], is_train=False, use_graph=False)
    prompts = [np.arange(9) % cfg.vocab_size,
               (np.arange(4) + 3) % cfg.vocab_size,
               (np.arange(13) * 2 + 1) % cfg.vocab_size]
    batched = gpt2_decode.generate(m, prompts, max_new_tokens=6,
                                   temperature=0)
    assert isinstance(batched, list) and len(batched) == 3
    for p, got in zip(prompts, batched):
        single = gpt2_decode.generate(m, p, max_new_tokens=6,
                                      temperature=0)
        np.testing.assert_array_equal(got, single)
        assert got[:len(p)].tolist() == p.tolist()


@pytest.mark.slow
def test_topk_decode_restricts_support():
    """top_k=1 must equal greedy; top_k=k must only ever emit tokens
    whose teacher-forced logit ranks in the top k at that step."""
    from singa_tpu.models import gpt2_decode

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    x = tensor.from_numpy(np.zeros((1, 16), np.int32))
    m.compile([x], is_train=False, use_graph=False)
    prompt = np.arange(7) % cfg.vocab_size

    g_greedy = m.generate(prompt, max_new_tokens=8, temperature=0)
    g_k1 = m.generate(prompt, max_new_tokens=8, temperature=1.0,
                      top_k=1, rng=np.random.RandomState(0))
    np.testing.assert_array_equal(g_greedy, g_k1)

    k = 3
    out = gpt2_decode.generate(m, prompt, max_new_tokens=8,
                               temperature=1.0, top_k=k,
                               rng=np.random.RandomState(1))
    # teacher-force the sampled sequence: every emitted token's logit
    # must reach the k-th largest, within a margin covering the ~2e-3
    # fp difference between the decode stack and m.forward (a hard
    # membership check would flake on boundary ties)
    m.eval()
    window = np.zeros((1, cfg.n_positions), np.int32)
    window[0, :len(out)] = out
    logits = tensor.to_numpy(m.forward(tensor.from_numpy(window)))[0]
    for t in range(len(prompt), len(out)):
        step_logits = logits[t - 1]
        kth = np.sort(step_logits)[-k]
        assert step_logits[out[t]] >= kth - 5e-3, \
            (t, out[t], float(step_logits[out[t]]), float(kth))


@pytest.mark.slow
def test_topp_decode_restricts_support():
    """Tiny top_p must equal greedy; top_p=p must only emit tokens in
    the smallest nucleus with mass >= p at each step."""
    from singa_tpu.models import gpt2_decode

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    x = tensor.from_numpy(np.zeros((1, 16), np.int32))
    m.compile([x], is_train=False, use_graph=False)
    prompt = (np.arange(6) + 2) % cfg.vocab_size

    g_greedy = m.generate(prompt, max_new_tokens=8, temperature=0)
    g_p = m.generate(prompt, max_new_tokens=8, temperature=1.0,
                     top_p=1e-6, rng=np.random.RandomState(0))
    np.testing.assert_array_equal(g_greedy, g_p)

    p_thresh = 0.6
    out = gpt2_decode.generate(m, prompt, max_new_tokens=8,
                               temperature=1.0, top_p=p_thresh,
                               rng=np.random.RandomState(2))
    m.eval()
    window = np.zeros((1, cfg.n_positions), np.int32)
    window[0, :len(out)] = out
    logits = tensor.to_numpy(m.forward(tensor.from_numpy(window)))[0]
    for t in range(len(prompt), len(out)):
        lg = logits[t - 1].astype(np.float64)
        probs = np.exp(lg - lg.max())
        probs /= probs.sum()
        # nucleus rule: kept iff the cumulative mass BEFORE the token
        # (in prob-descending order) is < p.  Allow a small mass margin
        # for the ~2e-3 logit difference between the decode stack and
        # m.forward (hard membership would flake on boundary ties).
        tok = int(out[t])
        mass_before = float(probs[probs > probs[tok]].sum())
        assert mass_before < p_thresh + 5e-3, \
            (t, tok, mass_before, p_thresh)


def test_decode_rejects_bad_sampling_params():
    from singa_tpu.models import gpt2_decode

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    x = tensor.from_numpy(np.zeros((1, 16), np.int32))
    m.compile([x], is_train=False, use_graph=False)
    prompt = np.arange(4) % cfg.vocab_size
    with pytest.raises(ValueError):
        gpt2_decode.generate(m, prompt, max_new_tokens=2, top_p=0.0)
    with pytest.raises(ValueError):
        gpt2_decode.generate(m, prompt, max_new_tokens=2, top_p=1.5)


def test_windowed_path_rejects_bad_sampling_params():
    """The public generate() must raise the same ValueError on the
    windowed fallback path as on the KV-cached path (the windowed math
    would otherwise NaN on top_p=0)."""
    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    x = tensor.from_numpy(np.zeros((1, 16), np.int32))
    m.compile([x], is_train=False, use_graph=False)
    prompt = np.arange(4) % cfg.vocab_size
    for kw in ({"top_p": 0.0}, {"top_p": 1.5}, {"top_k": -2}):
        with pytest.raises(ValueError):
            m.generate(prompt, max_new_tokens=2, temperature=1.0,
                       use_cache=False, **kw)
        with pytest.raises(ValueError):
            m.generate(prompt, max_new_tokens=2, temperature=1.0,
                       use_cache=True, **kw)


@pytest.mark.slow
def test_tp_sharded_kv_decode_matches_serial():
    """Plan-sharded (tp=4) dense GPT-2 decodes through the KV cache:
    extract_params lays the weights out per the Megatron plan (asserted
    sharded, not single-device), the jitted generation runs SPMD, and
    greedy tokens equal the serial model's."""
    import jax
    from jax.sharding import NamedSharding

    from singa_tpu import device as device_module
    from singa_tpu.models import gpt2_decode

    cfg = GPT2Config.tiny(dropout=0.0)
    device_module.get_default_device().SetRandSeed(0)
    serial = GPT2LMHead(cfg)
    x = tensor.from_numpy(np.zeros((1, 16), np.int32))
    serial.compile([x], is_train=False, use_graph=False)

    mesh = shd.create_mesh(tp=4)
    plan = shd.ShardingPlan(mesh)
    par = GPT2LMHead(cfg, plan=plan)
    par.set_sharding_plan(plan)
    par.compile([x], is_train=False, use_graph=False)
    par.set_states({k: tensor.to_numpy(v)
                    for k, v in serial.get_states().items()})

    params = gpt2_decode.extract_params(par)
    shardings = {getattr(p["wq"].sharding, "spec", None)
                 for p in params["blocks"]}
    assert all(isinstance(p["wq"].sharding, NamedSharding)
               for p in params["blocks"]), shardings
    # the Megatron column layout shards the q projection's output dim
    assert any(s is not None and "model" in str(s) for s in shardings), \
        shardings

    prompt = np.arange(9) % cfg.vocab_size
    ref = serial.generate(prompt, max_new_tokens=8, temperature=0,
                          use_cache=True)
    got = gpt2_decode.generate(par, prompt, max_new_tokens=8,
                               temperature=0)
    np.testing.assert_array_equal(got, ref)
    # and the public wrapper auto-selects the cached path for the plan
    got2 = par.generate(prompt, max_new_tokens=8, temperature=0)
    np.testing.assert_array_equal(got2, ref)


@pytest.mark.slow
def test_beam_search_matches_exhaustive_and_greedy():
    """num_beams=1 == greedy; a beam wide enough to cover the frontier
    (num_beams = V^2 >= every level's node count for T=3) must find the
    EXACT argmax continuation, verified by scoring all V^T candidate
    continuations with teacher-forced forwards."""
    import itertools

    from singa_tpu.models import gpt2_decode

    from singa_tpu import device as device_module

    cfg = GPT2Config(vocab_size=6, n_positions=16, n_embd=32,
                     n_layer=2, n_head=4, n_inner=64, dropout=0.0)
    device_module.get_default_device().SetRandSeed(3)  # deterministic
    m = GPT2LMHead(cfg)
    x = tensor.from_numpy(np.zeros((1, 8), np.int32))
    m.compile([x], is_train=False, use_graph=False)
    m.eval()
    prompt = np.asarray([1, 4, 2], np.int32)
    T = 3

    g_greedy = m.generate(prompt, max_new_tokens=T, temperature=0)
    g_beam1 = gpt2_decode.generate_beam(m, prompt, max_new_tokens=T,
                                        num_beams=1)
    np.testing.assert_array_equal(g_greedy, g_beam1)

    # exhaustive oracle: total log-prob of every continuation
    def score(cont):
        seq = np.concatenate([prompt, np.asarray(cont, np.int32)])
        window = np.zeros((1, cfg.n_positions), np.int32)
        window[0, :len(seq)] = seq
        logits = tensor.to_numpy(
            m.forward(tensor.from_numpy(window)))[0].astype(np.float64)
        total = 0.0
        for t in range(T):
            row = logits[len(prompt) - 1 + t]
            row = row - row.max()
            total += row[cont[t]] - np.log(np.exp(row).sum())
        return total

    scored = sorted(
        itertools.product(range(cfg.vocab_size), repeat=T), key=score,
        reverse=True)
    g_wide = gpt2_decode.generate_beam(m, prompt, max_new_tokens=T,
                                       num_beams=cfg.vocab_size ** 2)
    got = tuple(int(v) for v in g_wide[len(prompt):])
    # fp32-beam vs float64-oracle near-ties: accept any candidate
    # within 1e-4 nats of the exhaustive best
    assert score(got) >= score(scored[0]) - 1e-4, \
        (got, scored[0], score(got), score(scored[0]))

    # a modest beam must never score below greedy
    g4 = gpt2_decode.generate_beam(m, prompt, max_new_tokens=T,
                                   num_beams=4)
    assert score(tuple(g4[len(prompt):])) >= \
        score(tuple(g_greedy[len(prompt):])) - 1e-9
    with pytest.raises(ValueError):
        gpt2_decode.generate_beam(m, prompt, max_new_tokens=2,
                                  num_beams=0)
    # 2-D batches are supported since round 5 (batched beam search):
    # one executable, list of per-row results
    outs = gpt2_decode.generate_beam(m, np.zeros((2, 3), np.int32),
                                     max_new_tokens=2)
    assert isinstance(outs, list) and len(outs) == 2
    assert all(len(o) == 5 for o in outs)


@pytest.mark.slow
def test_uniform_decode_path_matches_ragged_and_windowed():
    """The equal-length fast path (one shared position, batched cache
    writes) must be token-exact (f32) against BOTH the ragged vmap path
    and the windowed oracle — greedy and temperature sampling (the two
    paths consume identical per-row key chains)."""
    import jax
    import jax.numpy as jnp

    from singa_tpu.models import gpt2_decode as gd

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    x = tensor.from_numpy(np.zeros((1, 16), np.int32))
    m.compile([x], is_train=False, use_graph=False)
    params = gd.extract_params(m)
    B, PL, T = 3, 7, 10
    rng = np.random.RandomState(1)
    window = np.zeros((B, cfg.n_positions), np.int32)
    window[:, :PL] = rng.randint(0, cfg.vocab_size, (B, PL))
    ids = jnp.asarray(window)
    lens = jnp.full((B,), PL, jnp.int32)
    keys = jax.random.split(jax.random.PRNGKey(5), B)

    for greedy, temp in ((True, 1.0), (False, 0.8)):
        o_ragged = np.asarray(gd.generate_cached(
            params, ids, lens, cfg.n_head, float(cfg.layer_norm_eps),
            T, cfg.n_positions, greedy, jnp.float32(temp), keys))
        o_uni = np.asarray(gd.generate_cached_uniform(
            params, ids, PL, cfg.n_head, float(cfg.layer_norm_eps),
            T, cfg.n_positions, greedy, jnp.float32(temp), keys))
        np.testing.assert_array_equal(o_uni, o_ragged,
                                      err_msg=f"greedy={greedy}")

    m.eval()
    for i in range(B):
        w = m.generate(window[i, :PL], max_new_tokens=T, temperature=0,
                       use_cache=False)
        u = m.generate(window[i, :PL], max_new_tokens=T, temperature=0,
                       use_cache=True)  # routes to the uniform path
        np.testing.assert_array_equal(u, w)


@pytest.mark.slow
def test_tp_sharded_beam_search_matches_serial():
    """Beam search composes with plan-sharded params the same way
    sampling does (pure-jnp SPMD): tp=4 beam tokens equal serial."""
    from singa_tpu import device as device_module
    from singa_tpu.models import gpt2_decode

    cfg = GPT2Config.tiny(dropout=0.0)
    device_module.get_default_device().SetRandSeed(0)
    serial = GPT2LMHead(cfg)
    x = tensor.from_numpy(np.zeros((1, 16), np.int32))
    serial.compile([x], is_train=False, use_graph=False)
    plan = shd.ShardingPlan(shd.create_mesh(tp=4))
    par = GPT2LMHead(cfg, plan=plan)
    par.set_sharding_plan(plan)
    par.compile([x], is_train=False, use_graph=False)
    par.set_states({k: tensor.to_numpy(v)
                    for k, v in serial.get_states().items()})
    prompt = np.arange(7) % cfg.vocab_size
    b_ser = gpt2_decode.generate_beam(serial, prompt, max_new_tokens=6,
                                      num_beams=4)
    b_par = gpt2_decode.generate_beam(par, prompt, max_new_tokens=6,
                                      num_beams=4)
    np.testing.assert_array_equal(b_ser, b_par)


@pytest.mark.slow
def test_left_padded_ragged_decode_matches_scatter_oracle():
    """Round-5 fast path: a ragged batch routed through left-padding +
    the shared-position executable must be token-exact (f32) against
    the per-row scatter oracle — greedy AND sampled (same seed), and
    with top-k/top-p filters on."""
    from singa_tpu.models import gpt2_decode

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    x = tensor.from_numpy(np.zeros((1, 16), np.int32))
    m.compile([x], is_train=False, use_graph=False)
    prompts = [np.arange(9) % cfg.vocab_size,
               (np.arange(4) + 3) % cfg.vocab_size,
               (np.arange(13) * 2 + 1) % cfg.vocab_size,
               np.asarray([5])]

    for seed, kw in ((None, dict(temperature=0)),
                     (7, dict(temperature=1.0)),
                     (8, dict(temperature=0.8, top_k=5)),
                     (9, dict(temperature=1.0, top_p=0.7))):
        if seed is not None:
            kw = dict(kw, rng=np.random.RandomState(seed))
        left = gpt2_decode.generate(m, prompts, max_new_tokens=6, **kw)
        if seed is not None:
            kw = dict(kw, rng=np.random.RandomState(seed))
        oracle = gpt2_decode.generate(m, prompts, max_new_tokens=6,
                                      _ragged_impl="scatter", **kw)
        for li, oi in zip(left, oracle):
            np.testing.assert_array_equal(li, oi)


# -- MoE KV-cached decode (round 5) ----------------------------------------

def _moe_model(top_k=2):
    # capacity_factor high enough that the windowed/training forward
    # drops NOTHING (cap >= token count): the KV decode path is
    # capacity-free by design, so token parity is only defined in the
    # no-drop regime (gpt2_decode.extract_params docstring)
    cfg = GPT2Config.tiny(dropout=0.0, moe_every=2, moe_experts=4,
                          moe_top_k=top_k, moe_capacity_factor=4.0)
    m = GPT2LMHead(cfg)
    x = tensor.from_numpy(np.zeros((1, 16), np.int32))
    m.compile([x], is_train=False, use_graph=False)
    return cfg, m


@pytest.mark.parametrize("top_k", [1, 2])
@pytest.mark.slow
def test_moe_kv_decode_matches_windowed_greedy(top_k):
    """MoE KV-cached decode (capacity-free top-k routing) must equal
    the windowed full-forward sampler token for token when the windowed
    path's capacity drops nothing (tiny batch, near-uniform random
    router — nothing approaches capacity)."""
    cfg, m = _moe_model(top_k)
    prompt = np.arange(9) % cfg.vocab_size
    g_win = m.generate(prompt, max_new_tokens=10, temperature=0,
                       use_cache=False)
    g_kv = m.generate(prompt, max_new_tokens=10, temperature=0,
                      use_cache=True)
    np.testing.assert_array_equal(g_win, g_kv)
    assert g_kv[:9].tolist() == prompt.tolist()


@pytest.mark.slow
def test_moe_kv_prefill_logits_match_forward():
    """Teacher-forced: MoE prefill logits == layer-stack forward at
    every position (routing decisions included)."""
    import jax.numpy as jnp
    from singa_tpu.models import gpt2_decode

    cfg, m = _moe_model()
    ids = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (2, 16)).astype(np.int32)
    x = tensor.from_numpy(ids)
    m.eval()
    ref = tensor.to_numpy(m.forward(x))
    params = gpt2_decode.extract_params(m)
    hidden, _, _ = gpt2_decode.prefill(
        params, jnp.asarray(ids), cfg.n_head, cfg.layer_norm_eps,
        moe_top_k=cfg.moe_top_k)
    got = gpt2_decode._logits(hidden, params)
    np.testing.assert_allclose(np.asarray(got), ref, atol=2e-3,
                               rtol=1e-3)


@pytest.mark.slow
def test_moe_ragged_batch_and_beam_decode():
    """MoE rides the full round-5 decode surface: ragged left-padded
    batches and beam search (beam=1 ≡ greedy)."""
    from singa_tpu.models import gpt2_decode

    cfg, m = _moe_model()
    prompts = [np.arange(7) % cfg.vocab_size, np.asarray([3, 1, 4, 1]),
               (np.arange(11) + 2) % cfg.vocab_size]
    batched = gpt2_decode.generate(m, prompts, max_new_tokens=5,
                                   temperature=0)
    for p, got in zip(prompts, batched):
        single = gpt2_decode.generate(m, p, max_new_tokens=5,
                                      temperature=0)
        np.testing.assert_array_equal(got, single)
    beam1 = gpt2_decode.generate_beam(m, prompts[0], max_new_tokens=5,
                                      num_beams=1)
    greedy = gpt2_decode.generate(m, prompts[0], max_new_tokens=5,
                                  temperature=0)
    np.testing.assert_array_equal(beam1, greedy)


def test_batched_beam_search_matches_per_row_loop():
    """Round-5 batched beam search: a (possibly ragged) batch of
    prompts in ONE executable must equal looping generate_beam over
    rows — the block-diagonal parent gather cannot mix prompts."""
    from singa_tpu.models import gpt2_decode

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    x = tensor.from_numpy(np.zeros((1, 16), np.int32))
    m.compile([x], is_train=False, use_graph=False)
    prompts = [np.arange(8) % cfg.vocab_size,
               np.asarray([3, 1, 4]),
               (np.arange(11) + 5) % cfg.vocab_size]
    batched = gpt2_decode.generate_beam(m, prompts, max_new_tokens=6,
                                        num_beams=3)
    assert isinstance(batched, list) and len(batched) == 3
    for p, got in zip(prompts, batched):
        single = gpt2_decode.generate_beam(m, np.asarray(p),
                                           max_new_tokens=6,
                                           num_beams=3)
        np.testing.assert_array_equal(got, single)
        assert got[:len(p)].tolist() == list(p)


def test_decode_param_session_cache():
    """Repeated generate calls reuse the extracted weight pytree (no
    re-cast/re-upload); any state mutation invalidates it."""
    import jax.numpy as jnp
    from singa_tpu.models import gpt2_decode

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    x = tensor.from_numpy(np.zeros((1, 16), np.int32))
    m.compile([x], is_train=False, use_graph=False)
    p1 = gpt2_decode.extract_params(m, dtype=jnp.bfloat16)
    p2 = gpt2_decode.extract_params(m, dtype=jnp.bfloat16)
    assert p1 is p2, "unchanged model must hit the session cache"
    # different dtype = different cache entry, not a stale hit
    p3 = gpt2_decode.extract_params(m)
    assert p3 is not p1
    # re-populate the (single-slot) cache with the bf16 entry, THEN
    # mutate state: the final assertion must test the id-signature
    # miss, not the dtype eviction above
    p1b = gpt2_decode.extract_params(m, dtype=jnp.bfloat16)
    assert gpt2_decode.extract_params(m, dtype=jnp.bfloat16) is p1b
    m.set_states({k: tensor.to_numpy(v)
                  for k, v in m.get_states().items()})
    p4 = gpt2_decode.extract_params(m, dtype=jnp.bfloat16)
    assert p4 is not p1b


def test_model_generate_accepts_prompt_batches():
    """GPT2LMHead.generate (the model method) takes ragged batches
    since round 5, delegating to the KV-cached batch path."""
    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    x = tensor.from_numpy(np.zeros((1, 16), np.int32))
    m.compile([x], is_train=False, use_graph=False)
    prompts = [np.arange(6) % cfg.vocab_size, np.asarray([2, 7, 1, 8])]
    outs = m.generate(prompts, max_new_tokens=5, temperature=0)
    assert isinstance(outs, list) and len(outs) == 2
    for p, o in zip(prompts, outs):
        single = m.generate(np.asarray(p), max_new_tokens=5,
                            temperature=0)
        np.testing.assert_array_equal(o, single)
    with pytest.raises(ValueError, match="single-prompt"):
        m.generate(prompts, max_new_tokens=5, use_cache=False)


def _trained_pair(seed=0, draft_layers=1, steps=15, **cfgkw):
    """A trained tiny target and a draft trained on the same batches
    (decisive logits — speculative tests must not ride argmax
    near-ties, which flip between the chunked and sequential einsum
    orders at ~1e-7 on random models)."""
    from singa_tpu import device as device_module

    device_module.get_default_device().SetRandSeed(seed)
    cfg_t = _cfg(**cfgkw)
    target = GPT2LMHead(cfg_t)
    cfg_d = _cfg(n_layer=draft_layers, **cfgkw)
    draft = GPT2LMHead(cfg_d)
    ids, labels = _batch(cfg_t)
    for m in (target, draft):
        m.set_optimizer(opt.Adam(lr=1e-3))
        m.compile([tensor.from_numpy(ids)], is_train=True,
                  use_graph=True)
        for _ in range(steps):
            m(tensor.from_numpy(ids), tensor.from_numpy(labels))
        m.eval()
    return target, draft, ids


def test_speculative_decode_matches_target_greedy():
    """generate_speculative emits EXACTLY target-greedy tokens — the
    draft only changes speed.  Trained pair: acceptance must be
    meaningfully positive (both models learned the same loops)."""
    from singa_tpu.models import gpt2_decode

    target, draft, ids = _trained_pair()
    p = ids[0, :9]
    ref = target.generate(p, max_new_tokens=16, temperature=0)
    spec, stats = gpt2_decode.generate_speculative(
        target, draft, p, max_new_tokens=16, spec_k=4)
    np.testing.assert_array_equal(ref, spec)
    assert stats["chunks"] >= 1
    assert 0.0 <= stats["acceptance_rate"] <= 1.0


@pytest.mark.slow
def test_speculative_self_draft_accepts_everything():
    """Draft == target on a trained model: every proposal verifies, so
    acceptance is 1.0 and each chunk emits spec_k tokens (spec_k - 1
    proposals + the bonus candidate)."""
    from singa_tpu.models import gpt2_decode

    target, _, ids = _trained_pair()
    p = ids[0, :9]
    ref = target.generate(p, max_new_tokens=15, temperature=0)
    spec, stats = gpt2_decode.generate_speculative(
        target, target, p, max_new_tokens=15, spec_k=4)
    np.testing.assert_array_equal(ref, spec)
    assert stats["acceptance_rate"] == 1.0, stats
    assert stats["tokens_per_chunk"] >= 3.0, stats


@pytest.mark.slow  # variant: decode_matches_target_greedy is fast rep
def test_speculative_validates_and_composes():
    from singa_tpu.models import gpt2_decode

    target, draft, ids = _trained_pair()
    p = ids[0, :9]
    with pytest.raises(ValueError, match="spec_k"):
        gpt2_decode.generate_speculative(target, draft, p, spec_k=1)
    small_vocab = GPT2LMHead(_cfg(vocab_size=128))
    with pytest.raises(ValueError, match="vocab"):
        gpt2_decode.generate_speculative(target, small_vocab, p)
    win = GPT2LMHead(_cfg(attn_window=6, n_positions=64))
    with pytest.raises(NotImplementedError, match="sliding-window"):
        gpt2_decode.generate_speculative(win, draft, p)
    with pytest.raises(ValueError, match="exceeds"):
        gpt2_decode.generate_speculative(
            target, draft, p, max_new_tokens=10_000)
    # int8 cache composes; parity still exact on the trained pair
    ref = target.generate(p, max_new_tokens=10, temperature=0)
    spec, _ = gpt2_decode.generate_speculative(
        target, draft, p, max_new_tokens=10, spec_k=3,
        cache_dtype="int8")
    np.testing.assert_array_equal(ref, spec)
    # MoE target: _block_chunk routes through the same capacity-free
    # expert MLP as single-token decode — parity must hold with a
    # dense draft
    moe_t, _, moe_ids = _trained_pair(
        seed=2, moe_every=2, moe_experts=4,
        moe_capacity_factor=4.0)
    pm = moe_ids[0, :9]
    ref_m = moe_t.generate(pm, max_new_tokens=10, temperature=0)
    spec_m, _ = gpt2_decode.generate_speculative(
        moe_t, draft, pm, max_new_tokens=10, spec_k=3)
    np.testing.assert_array_equal(ref_m, spec_m)


def test_speculative_batched_matches_per_row():
    """A ragged prompt BATCH through speculative decoding: every row
    equals its single-prompt run (greedy determinism), and the
    aggregate stats cover all rows."""
    from singa_tpu.models import gpt2_decode

    target, draft, ids = _trained_pair()
    prompts = [ids[0, :9], ids[1, :5], ids[2, :12]]
    outs, stats = gpt2_decode.generate_speculative(
        target, draft, prompts, max_new_tokens=12, spec_k=3)
    assert len(outs) == 3
    assert len(stats["per_row_chunks"]) == 3
    assert stats["chunks"] == sum(stats["per_row_chunks"])
    for row, p in zip(outs, prompts):
        single, _ = gpt2_decode.generate_speculative(
            target, draft, p, max_new_tokens=12, spec_k=3)
        np.testing.assert_array_equal(row, single)
        # and still exactly target-greedy
        ref = target.generate(p, max_new_tokens=12, temperature=0)
        np.testing.assert_array_equal(row, ref)


def test_over_length_batched_generate_falls_back_windowed():
    """A prompt BATCH whose prompt+max_new exceeds n_positions used to
    raise with a hint pointing at the very function the caller was in;
    it must instead loop every row through the windowed fallback
    (round-6 fix).  Explicitly forcing the cache keeps the error."""
    cfg = _cfg()
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    long_p = np.zeros(cfg.n_positions - 2, np.int32)
    short_p = np.arange(5) % cfg.vocab_size
    outs = m.generate([long_p, short_p], max_new_tokens=5,
                      temperature=0)
    assert isinstance(outs, list) and len(outs) == 2
    for o, p in zip(outs, (long_p, short_p)):
        assert len(o) == len(p) + 5
        # row-for-row equal to the single-prompt windowed sampler
        single = m.generate(p, max_new_tokens=5, temperature=0,
                            use_cache=False)
        np.testing.assert_array_equal(o, single)
    with pytest.raises(ValueError, match="n_positions"):
        m.generate([long_p, short_p], max_new_tokens=5,
                   temperature=0, use_cache=True)
