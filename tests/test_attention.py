"""Attention tests: fused path, flash kernel (interpreter on CPU), ring
attention on the 8-device mesh vs single-device reference."""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from singa_tpu import autograd, opt, tensor
from singa_tpu import device as device_module
from singa_tpu.ops.attention import scaled_dot_product_attention
from singa_tpu.ops.pallas.flash_attention import flash_attention
from singa_tpu.parallel.ring_attention import ring_attention_sharded

N_DEV = len(jax.devices())


@pytest.fixture
def dev():
    d = device_module.get_default_device()
    d.SetRandSeed(0)
    return d


def _ref(q, k, v, mask=None):
    d = q.shape[-1]
    sc = jnp.einsum("bhsd,bhtd->bhst", q, k) / math.sqrt(d)
    if mask is not None:
        sc = sc + mask
    return jnp.einsum("bhst,bhtd->bhsd", jax.nn.softmax(sc, -1), v)


def _qkv(b=2, h=2, s=256, d=64, seed=0):
    rng = np.random.RandomState(seed)
    return tuple(jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))
                 for _ in range(3))


def test_flash_forward_matches_reference():
    q, k, v = _qkv()
    mask = np.zeros((2, 1, 1, 256), np.float32)
    mask[:, :, :, 200:] = -1e9
    o = flash_attention(q, k, v, jnp.asarray(mask))
    r = _ref(q, k, v, jnp.asarray(mask))
    np.testing.assert_allclose(np.asarray(o), np.asarray(r), atol=2e-3)


def test_flash_causal_matches_reference():
    q, k, v = _qkv(s=128)
    o = flash_attention(q, k, v, causal=True)
    cm = jnp.where(jnp.arange(128)[:, None] >= jnp.arange(128)[None, :],
                   0.0, -1e30)[None, None]
    r = _ref(q, k, v, cm)
    np.testing.assert_allclose(np.asarray(o), np.asarray(r), atol=2e-3)


def test_flash_gradients_match_reference():
    q, k, v = _qkv(s=128)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref(q, k, v) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-3)


@pytest.mark.slow
def test_flash_multiblock_grads_mask_and_causal():
    """Exercise the REAL kernel grids (init/flush across the sequential
    block dim, causal block skipping, unequal block_q != block_k) — with
    the 1024-default blocks a short-S test clamps to a single block and
    never hits the accumulator paths."""
    q, k, v = _qkv(s=512)
    mask = np.zeros((2, 1, 1, 512), np.float32)
    mask[:, :, :, 480:] = -1e9
    mask = jnp.asarray(mask)
    for causal in (False, True):
        def loss_flash(q, k, v):
            o = flash_attention(q, k, v, mask, causal=causal,
                                block_q=128, block_k=256)
            return jnp.sum(o ** 2)

        def loss_ref(q, k, v):
            sc_mask = mask
            if causal:
                cm = jnp.where(jnp.arange(512)[:, None]
                               >= jnp.arange(512)[None, :],
                               0.0, -1e30)[None, None]
                sc_mask = mask + cm
            return jnp.sum(_ref(q, k, v, sc_mask) ** 2)

        o = flash_attention(q, k, v, mask, causal=causal,
                            block_q=128, block_k=256)
        sc_mask = mask
        if causal:
            cm = jnp.where(jnp.arange(512)[:, None]
                           >= jnp.arange(512)[None, :],
                           0.0, -1e30)[None, None]
            sc_mask = mask + cm
        np.testing.assert_allclose(np.asarray(o),
                                   np.asarray(_ref(q, k, v, sc_mask)),
                                   atol=2e-3)
        g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-2, rtol=5e-2)


def test_flash_block_fit_nonpow2_seqlen():
    """S not divisible by the 1024-default blocks (e.g. 384) must shrink
    the block to a 128-multiple divisor and STAY on the kernel — not
    fall back to the O(S²)-backward scan path."""
    from singa_tpu.ops.pallas import flash_attention as fa

    q, k, v = _qkv(b=1, h=2, s=384)
    called = []
    orig = fa._flash
    fa._flash = lambda *a: called.append(a[7:9]) or orig(*a)
    try:
        o = flash_attention(q, k, v, causal=True)
    finally:
        fa._flash = orig
    assert called and called[0] == (384, 384), called  # kernel path, fit blocks
    cm = jnp.where(jnp.arange(384)[:, None] >= jnp.arange(384)[None, :],
                   0.0, -1e30)[None, None]
    np.testing.assert_allclose(np.asarray(o), np.asarray(_ref(q, k, v, cm)),
                               atol=2e-3)
    g = jax.grad(lambda q: jnp.sum(
        flash_attention(q, k, v, causal=True) ** 2))(q)
    assert np.isfinite(np.asarray(g)).all()


def test_flash_unaligned_seqlen_stays_on_kernel():
    """Arbitrary S (1537 — not a 128-multiple) pads to the next block
    multiple inside the wrapper and keeps the O(S·D)-backward kernel:
    fwd + grads must match the dense reference exactly on real rows."""
    from singa_tpu.ops.pallas import flash_attention as fa

    q, k, v = _qkv(b=1, h=2, s=1537 if N_DEV == 1 else 257)
    s = q.shape[2]
    called = []
    orig = fa._flash
    fa._flash = lambda *a: called.append(a[0].shape) or orig(*a)
    try:
        o = flash_attention(q, k, v, causal=True)
    finally:
        fa._flash = orig
    assert called and called[0][1] % 128 == 0, called  # padded, on-kernel
    cm = jnp.where(jnp.arange(s)[:, None] >= jnp.arange(s)[None, :],
                   0.0, -1e30)[None, None]
    np.testing.assert_allclose(np.asarray(o), np.asarray(_ref(q, k, v, cm)),
                               atol=2e-3)
    g = jax.grad(lambda q: jnp.sum(
        flash_attention(q, k, v, causal=True) ** 2))(q)
    g_ref = jax.grad(lambda q: jnp.sum(_ref(q, k, v, cm) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               atol=5e-2, rtol=5e-2)


@pytest.mark.slow
def test_flash_general_mask_through_kernel():
    """A per-query (B, 1, S, S) additive mask streams through the kernel
    as (block_q, block_k) tiles instead of forcing the O(S²) fused
    fallback; (B, H, S, S) takes the flattened layout."""
    from singa_tpu.ops.pallas import flash_attention as fa

    for mask_shape in [(2, 1, 256, 256), (2, 2, 256, 256),
                       (1, 1, 256, 256), (1, 2, 256, 256)]:
        q, k, v = _qkv(s=256)
        rng = np.random.RandomState(7)
        mask = jnp.asarray(
            np.where(rng.rand(*mask_shape) > 0.2, 0.0, -1e9)
            .astype(np.float32))
        called = []
        orig = fa._flash
        fa._flash = lambda *a: called.append(a[4] is not None) or orig(*a)
        try:
            o = flash_attention(q, k, v, mask)
        finally:
            fa._flash = orig
        assert called and called[0], (mask_shape, called)  # qmask path
        np.testing.assert_allclose(np.asarray(o),
                                   np.asarray(_ref(q, k, v, mask)),
                                   atol=2e-3)
        g = jax.grad(lambda q: jnp.sum(
            flash_attention(q, k, v, mask) ** 2))(q)
        g_ref = jax.grad(lambda q: jnp.sum(_ref(q, k, v, mask) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   atol=5e-2, rtol=5e-2)


def test_flash_wide_head_dim_padded():
    """D = 192 (not a 128-multiple, > 128) pads to 256 with zero columns
    — scores and softmax scale are unchanged, so output matches the
    dense reference."""
    q, k, v = _qkv(b=1, h=2, s=256, d=192)
    o = flash_attention(q, k, v, causal=True)
    cm = jnp.where(jnp.arange(256)[:, None] >= jnp.arange(256)[None, :],
                   0.0, -1e30)[None, None]
    np.testing.assert_allclose(np.asarray(o), np.asarray(_ref(q, k, v, cm)),
                               atol=2e-3)
    g = jax.grad(lambda k: jnp.sum(
        flash_attention(q, k, v, causal=True) ** 2))(k)
    g_ref = jax.grad(lambda k: jnp.sum(_ref(q, k, v, cm) ** 2))(k)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               atol=5e-2, rtol=5e-2)


def test_flash_unaligned_lse_matches():
    """flash_attention_lse on an unaligned S: padded tail must not
    perturb the real rows' logsumexp."""
    from singa_tpu.ops.pallas.flash_attention import flash_attention_lse

    q, k, v = _qkv(b=1, h=2, s=200)
    o, lse = flash_attention_lse(q, k, v)
    sc = jnp.einsum("bhsd,bhtd->bhst", q, k) / math.sqrt(64)
    lse_ref = jax.scipy.special.logsumexp(sc, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_ref),
                               atol=1e-3)
    np.testing.assert_allclose(np.asarray(o), np.asarray(_ref(q, k, v)),
                               atol=2e-3)


def test_flash_logsumexp_residual():
    """The fwd kernel's second output (logsumexp) is what the backward
    recomputes probabilities from — it must match scipy's logsumexp."""
    from singa_tpu.ops.pallas.flash_attention import _flash_fwd_pallas

    q, k, v = _qkv(b=1, h=2, s=512)
    qf, kf, vf = (x.reshape(2, 512, 64) for x in (q, k, v))
    mask = jnp.zeros((2, 512), jnp.float32)
    _, lse = _flash_fwd_pallas(qf, kf, vf, mask, None, 1 / math.sqrt(64),
                               False, 128, 128, 1)
    sc = jnp.einsum("bsd,btd->bst", qf, kf) / math.sqrt(64)
    lse_ref = jax.scipy.special.logsumexp(sc, axis=-1)
    np.testing.assert_allclose(np.asarray(lse[:, 0, :]),
                               np.asarray(lse_ref), atol=1e-3)


def test_sdpa_op_taped(dev):
    autograd.set_training(True)
    try:
        rng = np.random.RandomState(0)
        mk = lambda: tensor.from_numpy(  # noqa: E731
            rng.randn(1, 2, 8, 4).astype(np.float32), dev)
        q, k, v = mk(), mk(), mk()
        q.requires_grad = q.stores_grad = True
        out = scaled_dot_product_attention(q, k, v)
        loss = autograd.reduce_sum(autograd.mul(out, out))
        grads = dict(autograd.backward(loss))
        assert q in grads
        assert grads[q].shape == q.shape
    finally:
        autograd.set_training(False)


@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
def test_ring_attention_matches_single_device():
    s = 16 * N_DEV
    q, k, v = _qkv(b=1, h=2, s=s, d=16, seed=3)
    o_ring = ring_attention_sharded(q, k, v)
    o_ref = _ref(q, k, v)
    np.testing.assert_allclose(np.asarray(o_ring), np.asarray(o_ref),
                               atol=2e-4)


@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
def test_ring_attention_causal_matches():
    s = 8 * N_DEV
    q, k, v = _qkv(b=1, h=1, s=s, d=8, seed=4)
    o_ring = ring_attention_sharded(q, k, v, causal=True)
    cm = jnp.where(jnp.arange(s)[:, None] >= jnp.arange(s)[None, :],
                   0.0, -1e30)[None, None]
    np.testing.assert_allclose(np.asarray(o_ring), np.asarray(_ref(q, k, v, cm)),
                               atol=2e-4)


@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
@pytest.mark.slow
def test_ring_attention_differentiable():
    s = 8 * N_DEV
    q, k, v = _qkv(b=1, h=1, s=s, d=8, seed=5)
    g_ring = jax.grad(lambda q: jnp.sum(ring_attention_sharded(q, k, v) ** 2))(q)
    g_ref = jax.grad(lambda q: jnp.sum(_ref(q, k, v) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g_ring), np.asarray(g_ref),
                               atol=5e-4)


@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
@pytest.mark.slow
def test_ring_attention_flash_matches_single_device():
    """Ring attention with per-shard flash partials (merged via each
    step's logsumexp) must equal the plain reference — forward and
    gradient, causal and not."""
    from singa_tpu.parallel.ring_attention import ring_self_attention
    from jax.sharding import Mesh

    s = 16 * N_DEV
    q, k, v = _qkv(b=1, h=2, s=s, d=16, seed=11)
    devices = np.asarray(jax.devices())
    mesh = Mesh(devices, ("seq",))
    spec = jax.sharding.PartitionSpec(None, None, "seq", None)
    for causal in (False, True):
        f = jax.shard_map(
            lambda q_, k_, v_: ring_self_attention(
                q_, k_, v_, "seq", causal=causal, use_flash=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
        o = f(q, k, v)
        cm = None
        if causal:
            cm = jnp.where(jnp.arange(s)[:, None] >= jnp.arange(s)[None, :],
                           0.0, -1e30)[None, None]
        np.testing.assert_allclose(np.asarray(o), np.asarray(_ref(q, k, v, cm)),
                                   atol=2e-4, err_msg=f"causal={causal}")
        g1 = jax.grad(lambda q: jnp.sum(f(q, k, v) ** 2))(q)
        g2 = jax.grad(lambda q: jnp.sum(_ref(q, k, v, cm) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   atol=5e-4, err_msg=f"causal={causal}")


@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
def test_ring_attention_flash_with_padding_mask():
    from singa_tpu.parallel.ring_attention import ring_self_attention
    from jax.sharding import Mesh

    s = 16 * N_DEV
    q, k, v = _qkv(b=2, h=2, s=s, d=16, seed=12)
    maskn = np.zeros((2, 1, 1, s), np.float32)
    maskn[:, :, :, s - 10:] = -1e9
    mask = jnp.asarray(maskn)
    devices = np.asarray(jax.devices())
    mesh = Mesh(devices, ("seq",))
    spec = jax.sharding.PartitionSpec(None, None, "seq", None)
    mspec = jax.sharding.PartitionSpec(None, None, None, "seq")
    f = jax.shard_map(
        lambda q_, k_, v_, m_: ring_self_attention(
            q_, k_, v_, "seq", kv_mask=m_, use_flash=True),
        mesh=mesh, in_specs=(spec, spec, spec, mspec), out_specs=spec,
        check_vma=False)
    o = f(q, k, v, mask)
    np.testing.assert_allclose(np.asarray(o), np.asarray(_ref(q, k, v, mask)),
                               atol=2e-4)


def test_flash_attention_lse_grad_through_lse():
    """The lse output's cotangent must flow into dq/dk correctly (it
    enters the softmax Jacobian as δ' = δ − dlse) — checked against
    jax autodiff of the fallback implementation."""
    from singa_tpu.ops.pallas.flash_attention import flash_attention_lse

    q, k, v = _qkv(b=1, h=1, s=256, d=64, seed=13)

    def loss_kernel(q, k, v):
        o, lse = flash_attention_lse(q, k, v, block_q=128, block_k=128)
        return jnp.sum(o ** 2) + jnp.sum(jnp.sin(lse))

    def loss_ref(q, k, v):
        d = q.shape[-1]
        sc = jnp.einsum("bhsd,bhtd->bhst", q, k) / math.sqrt(d)
        lse = jax.scipy.special.logsumexp(sc, axis=-1)
        o = jnp.einsum("bhst,bhtd->bhsd", jax.nn.softmax(sc, -1), v)
        return jnp.sum(o ** 2) + jnp.sum(jnp.sin(lse))

    g1 = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g1, g2, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-2, rtol=5e-2, err_msg=n)


@pytest.mark.skipif(N_DEV < 2, reason="needs multi-device mesh")
@pytest.mark.slow
def test_ring_attention_flash_kernel_path():
    """S_local = 128 puts each ring step on the REAL Pallas kernel
    (interpret mode on CPU) rather than the jnp fallback — exercising
    _flash_core inside shard_map end to end, fwd + grad."""
    from singa_tpu.parallel.ring_attention import ring_self_attention
    from jax.sharding import Mesh

    s = 128 * N_DEV
    q, k, v = _qkv(b=1, h=1, s=s, d=64, seed=21)
    devices = np.asarray(jax.devices())
    mesh = Mesh(devices, ("seq",))
    spec = jax.sharding.PartitionSpec(None, None, "seq", None)
    f = jax.shard_map(
        lambda q_, k_, v_: ring_self_attention(
            q_, k_, v_, "seq", causal=True, use_flash=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    o = f(q, k, v)
    cm = jnp.where(jnp.arange(s)[:, None] >= jnp.arange(s)[None, :],
                   0.0, -1e30)[None, None]
    np.testing.assert_allclose(np.asarray(o), np.asarray(_ref(q, k, v, cm)),
                               atol=2e-3)
    g1 = jax.grad(lambda q: jnp.sum(f(q, k, v) ** 2))(q)
    g2 = jax.grad(lambda q: jnp.sum(_ref(q, k, v, cm) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               atol=5e-2, rtol=5e-2)


def test_gqa_mha_equals_full_mha_with_repeated_kv_weights(dev):
    """Functional identity: a GQA MultiHeadAttention (num_kv_heads <
    num_heads) computes exactly what a full MHA computes when the full
    model's K/V projection weights are the GQA weights repeated per
    query group — so grouping is pure weight sharing, no new math."""
    from singa_tpu.ops.attention import MultiHeadAttention

    b, s, e, h, h_kv = 2, 8, 32, 4, 2
    g, d = h // h_kv, e // h
    rng = np.random.RandomState(0)
    x = tensor.from_numpy(rng.randn(b, s, e).astype(np.float32), dev)

    gqa = MultiHeadAttention(h, num_kv_heads=h_kv)
    y_gqa = tensor.to_numpy(gqa(x))
    assert gqa.k_proj.W.shape == (e, h_kv * d)

    full = MultiHeadAttention(h)
    full(x)  # deferred init
    for name in ("q_proj", "out_proj"):
        for p in ("W", "b"):
            getattr(getattr(full, name), p).copy_from_numpy(
                tensor.to_numpy(getattr(getattr(gqa, name), p)))
    for name in ("k_proj", "v_proj"):
        wn = tensor.to_numpy(getattr(gqa, name).W)      # (E, h_kv*d)
        bn = tensor.to_numpy(getattr(gqa, name).b)      # (h_kv*d,)
        w_full = np.repeat(wn.reshape(e, h_kv, d), g, axis=1)
        b_full = np.repeat(bn.reshape(h_kv, d), g, axis=0)
        getattr(full, name).W.copy_from_numpy(w_full.reshape(e, e))
        getattr(full, name).b.copy_from_numpy(b_full.reshape(e))
    y_full = tensor.to_numpy(full(x))
    np.testing.assert_allclose(y_gqa, y_full, rtol=1e-6, atol=1e-6)


def test_gqa_mha_validates_group():
    from singa_tpu.ops.attention import MultiHeadAttention

    with pytest.raises(ValueError):
        MultiHeadAttention(4, num_kv_heads=3)


def _band_mask(s, window):
    i = jnp.arange(s)[:, None]
    j = jnp.arange(s)[None, :]
    keep = (i >= j) & (i - j < window)
    return jnp.where(keep, 0.0, -1e30)[None, None]


def test_flash_window_matches_banded_reference():
    """causal+window on the kernel path: blocks entirely below the band
    are skipped and in-block band masking matches an explicit banded
    reference — exercised across multiple blocks (S=512 > block 128
    via the fit logic, window straddles block boundaries)."""
    q, k, v = _qkv(s=512)
    o = flash_attention(q, k, v, causal=True, window=96,
                        block_q=128, block_k=128)
    r = _ref(q, k, v, _band_mask(512, 96))
    np.testing.assert_allclose(np.asarray(o), np.asarray(r), atol=2e-3)
    # window >= S degenerates to plain causal
    o2 = flash_attention(q, k, v, causal=True, window=512,
                         block_q=128, block_k=128)
    r2 = flash_attention(q, k, v, causal=True,
                         block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(o2), np.asarray(r2),
                               atol=1e-5)


def test_flash_window_gradients_match_banded_reference():
    q, k, v = _qkv(s=256)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, window=80,
                                       block_q=128, block_k=128) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref(q, k, v, _band_mask(256, 80)) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-3)


def test_flash_window_requires_causal():
    q, k, v = _qkv(s=128)
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, k, v, window=32)
    # window < 1 would mask every in-band score to the finite NEG_INF
    # floor and silently return uniform attention — must raise
    with pytest.raises(ValueError, match="window"):
        flash_attention(q, k, v, causal=True, window=0)


def test_windowed_model_uses_flash_kernel():
    """GPT2Config(attn_impl='flash', attn_window=W) trains through the
    banded kernel and matches the fused banded twin."""
    from singa_tpu import opt as opt_mod, tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead

    ids = np.random.RandomState(0).randint(0, 256, (2, 64)).astype(
        np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)
    losses = {}
    for impl in ("fused", "flash"):
        device_module.get_default_device().SetRandSeed(0)
        cfg = GPT2Config.tiny(dropout=0.0, attn_impl=impl,
                              attn_window=24, n_positions=64)
        m = GPT2LMHead(cfg)
        m.set_optimizer(opt_mod.SGD(lr=0.1))
        m.compile([tensor.from_numpy(ids)], is_train=True,
                  use_graph=True)
        for _ in range(2):
            _, loss = m(tensor.from_numpy(ids),
                        tensor.from_numpy(labels))
        losses[impl] = float(tensor.to_numpy(loss))
    np.testing.assert_allclose(losses["flash"], losses["fused"],
                               rtol=2e-4)
