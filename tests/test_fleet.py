"""Replicated serve fleet: health-checked routing, sticky sessions,
cross-replica failover with requeue parity, fleet-wide load shedding,
hedged re-dispatch, and the fleet observability surface.

Deterministic on CPU: faults come from the seeded injection registry,
routing ties break on least-recently-routed logical ticks (replica
index on a fresh router — tests/test_serve_disagg.py pins the
tie-break), and every parity check compares against the single-prompt
``generate`` oracle (requeued/hedged requests re-derive the SAME
private sampling chain from their seed)."""

import numpy as np
import pytest

from singa_tpu import tensor
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from singa_tpu.observe.health import SLO, health_report
from singa_tpu.observe.registry import registry
from singa_tpu.resilience import FailAfterN, FailOnce, faults
from singa_tpu.serve import (EngineFailedError, FleetDownError,
                             GenerationRequest, LoadShedError,
                             PrefixCacheConfig, Router, ServeFleet)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def model():
    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    return m


def _workload(n, seed=0, lo=3, hi=10, new_lo=2, new_hi=7):
    rng = np.random.RandomState(seed)
    return [(rng.randint(0, 256, rng.randint(lo, hi)).astype(np.int32),
             int(rng.randint(new_lo, new_hi))) for _ in range(n)]


def _oracle(m, work):
    return [np.asarray(m.generate(p, max_new_tokens=n, temperature=0.0))
            for p, n in work]


def _counter(name, **labels):
    snap = registry().snapshot()["counters"]
    key = name
    if labels:
        key += "{" + ",".join(f"{k}={v}"
                              for k, v in sorted(labels.items())) + "}"
    return snap.get(key, 0)


# ---------------------------------------------------------------------------
# routing
# ---------------------------------------------------------------------------

def test_fleet_balances_and_streams_match_oracle(model):
    """Least-loaded routing spreads a burst over both replicas and
    every stream is token-identical to single-prompt generate."""
    work = _workload(8, seed=0)
    base = _oracle(model, work)
    with model.serve_fleet(replicas=2, max_slots=2) as fleet:
        hs = [fleet.submit(GenerationRequest(p, max_new_tokens=n))
              for p, n in work]
        fleet.run_until_complete(max_steps=500)
        for h, want in zip(hs, base):
            assert np.array_equal(h.result().tokens, want)
        snap = fleet.snapshot()
        assert snap["replicas"] == 2
        assert snap["replicas_healthy"] == 2
        assert snap["failovers"] == 0
        # queue depth moves at submit time, so a burst alternates
        assert snap["routed"]["0"] > 0 and snap["routed"]["1"] > 0
        assert snap["routed"]["0"] + snap["routed"]["1"] == len(work)


def test_router_scores_pressure_and_tpot():
    """Unit-level router policy: queue/occupancy dominate, a slower
    TPOT EWMA prices a replica out, and a replica past its SLO
    queue-depth headroom ranks behind every unpressured one."""
    r = Router()
    views = [
        {"replica": 0, "queue_depth": 0, "occupancy": 0.0,
         "tpot_ewma": 0.3, "queue_headroom": 4},
        {"replica": 1, "queue_depth": 0, "occupancy": 0.0,
         "tpot_ewma": 0.1, "queue_headroom": 4},
    ]
    assert r.rank(views)[0] == 1  # 3x slower decode loses the tie
    views[1]["queue_depth"] = 5
    assert r.rank(views)[0] == 0  # queue depth dominates
    views[0]["queue_headroom"] = 0  # at SLO pressure: heavy penalty
    assert r.rank(views)[0] == 1
    assert r.rank([]) == []


def test_sticky_session_stays_replica_local(model):
    """A pinned session's continuation routes to the replica whose
    radix tree holds the blocks — the warm hit shows up in that
    engine's prefix counters."""
    p = (np.arange(40) % 256).astype(np.int32)
    cachecfg = PrefixCacheConfig(block_size=8, num_blocks=32)
    with model.serve_fleet(replicas=2, max_slots=2,
                           prefix_cache=cachecfg) as fleet:
        h = fleet.submit(GenerationRequest(p, max_new_tokens=4,
                                           pin_session=True))
        fleet.run_until_complete(max_steps=300)
        sess = h.result().session
        assert sess is not None
        idx = fleet._sessions[sess]
        eng = fleet.supervisor(idx).engine
        hits0 = eng.prefix_cache._c_hits.value
        # spread some background load so the sticky target is NOT the
        # least-loaded choice — stickiness must win anyway
        extra = [fleet.submit(GenerationRequest(q, max_new_tokens=n))
                 for q, n in _workload(2, seed=3)]
        req2 = sess.request(np.asarray([7, 8, 9], np.int32),
                            max_new_tokens=3)
        assert req2.session_of is sess
        h2 = fleet.submit(req2)
        fleet.run_until_complete(max_steps=300)
        want = np.asarray(model.generate(req2.prompt_ids,
                                         max_new_tokens=3,
                                         temperature=0.0))
        assert np.array_equal(h2.result().tokens, want)
        # the continuation ran on the session's replica, warm
        assert eng.prefix_cache._c_hits.value > hits0
        for e in extra:
            e.result()
        sess.release()


# ---------------------------------------------------------------------------
# failover
# ---------------------------------------------------------------------------

def test_failover_requeues_never_started_with_parity(model):
    """A replica dying past its restart budget mid-decode: started
    requests fail typed, never-started ones requeue onto the survivor
    and complete token-identical to an uninterrupted run, and the
    fleet keeps serving."""
    work = _workload(8, seed=1, new_lo=3)
    base = _oracle(model, work)
    fleet = model.serve_fleet(replicas=2, max_slots=1,
                              restart_budget=0)
    hs = [fleet.submit(GenerationRequest(p, max_new_tokens=n))
          for p, n in work]
    pol = faults.inject("serve.decode_step", FailAfterN(2, times=1))
    fleet.run_until_complete(max_steps=1000)
    faults.clear()
    assert pol.fired == 1
    completed = typed = 0
    for h, want in zip(hs, base):
        assert h.done(), "wedged handle after failover"
        try:
            assert np.array_equal(h.result().tokens, want)
            completed += 1
        except EngineFailedError:
            typed += 1
    snap = fleet.snapshot()
    assert completed + typed == len(work)
    assert typed >= 1           # the in-flight request at the fault
    assert snap["failovers"] == 1
    assert snap["requeues"] >= 1
    assert snap["replicas_healthy"] == 1
    assert fleet.healthy_replicas == 1
    # service-level availability: the survivor keeps admitting
    h2 = fleet.submit(GenerationRequest(work[0][0], max_new_tokens=4))
    fleet.run_until_complete(max_steps=300)
    want = np.asarray(model.generate(work[0][0], max_new_tokens=4,
                                     temperature=0.0))
    assert np.array_equal(h2.result().tokens, want)
    fleet.close()


def test_all_replicas_down_is_typed_not_wedged(model):
    """Both replicas crash-loop past their budget: every handle
    resolves typed (zero wedged), pending drains, and new submissions
    raise FleetDownError."""
    work = _workload(6, seed=2, new_lo=3)
    fleet = model.serve_fleet(replicas=2, max_slots=1,
                              restart_budget=0)
    hs = [fleet.submit(GenerationRequest(p, max_new_tokens=n))
          for p, n in work]
    faults.inject("serve.decode_step", FailAfterN(1, times=2))
    fleet.run_until_complete(max_steps=1000)
    faults.clear()
    assert fleet.healthy_replicas == 0
    assert not fleet.pending
    for h in hs:
        assert h.done()
        with pytest.raises(EngineFailedError):
            h.result()
    with pytest.raises(FleetDownError):
        fleet.submit(GenerationRequest(work[0][0], max_new_tokens=2))
    fleet.close()


def test_revive_reenters_routing_set(model):
    """revive() rebuilds a failed replica (fresh budget, empty cache)
    and the router admits to it again."""
    fleet = model.serve_fleet(replicas=2, max_slots=1,
                              restart_budget=0)
    h0 = fleet.submit(GenerationRequest(
        np.asarray([1, 2, 3], np.int32), max_new_tokens=6))
    faults.inject("serve.decode_step", FailAfterN(0, times=1))
    fleet.run_until_complete(max_steps=500)
    faults.clear()
    dead = [r.idx for r in fleet._replicas if not r.healthy]
    assert len(dead) == 1
    with pytest.raises(ValueError):
        fleet.revive(1 - dead[0])   # healthy replica: refuse
    fleet.revive(dead[0])
    assert fleet.healthy_replicas == 2
    del h0
    routed0 = fleet.snapshot()["routed"][str(dead[0])]
    # saturate the sibling so the router must pick the revived replica
    work = _workload(4, seed=4)
    base = _oracle(model, work)
    hs = [fleet.submit(GenerationRequest(p, max_new_tokens=n))
          for p, n in work]
    fleet.run_until_complete(max_steps=500)
    for h, want in zip(hs, base):
        assert np.array_equal(h.result().tokens, want)
    assert fleet.snapshot()["routed"][str(dead[0])] > routed0
    fleet.close()


def test_watchdog_hang_failover(model, monkeypatch):
    """A replica whose heartbeat source latched a hang is failed over
    even though its supervisor never raised: queued work moves to the
    sibling and completes with parity."""
    from singa_tpu.serve import fleet as fleet_mod

    work = _workload(4, seed=5)
    base = _oracle(model, work)
    fleet = model.serve_fleet(replicas=2, max_slots=1)
    hs = [fleet.submit(GenerationRequest(p, max_new_tokens=n))
          for p, n in work]
    hung_src = fleet.supervisor(0).engine._hb_source

    class _FakeWd:
        def beat(self, *a, **kw):
            pass

        def hang_latched(self, source):
            return source == hung_src

    monkeypatch.setattr(fleet_mod._monitor, "active", lambda: True)
    monkeypatch.setattr(fleet_mod._monitor, "watchdog",
                        lambda: _FakeWd())
    monkeypatch.setattr(fleet_mod._monitor, "heartbeat",
                        lambda *a, **kw: None)
    fleet.run_until_complete(max_steps=500)
    monkeypatch.undo()
    assert fleet.healthy_replicas == 1
    assert not fleet._replicas[0].healthy
    completed = typed = 0
    for h, want in zip(hs, base):
        assert h.done()
        try:
            assert np.array_equal(h.result().tokens, want)
            completed += 1
        except EngineFailedError as e:
            # only requests that had started may fail typed here
            assert e.started is True
            typed += 1
    assert completed >= 1
    assert fleet.snapshot()["failovers"] == 1
    fleet.close()


# ---------------------------------------------------------------------------
# degradation + hedging
# ---------------------------------------------------------------------------

def test_fleet_wide_shed_lowest_priority(model):
    """SLO-pressure shedding applied fleet-wide: an arrival is only
    refused when NO healthy replica holds lower-priority work; a
    higher-priority arrival evicts the globally cheapest victim."""
    slo = SLO(queue_depth_max=1)
    fleet = model.serve_fleet(replicas=2, max_slots=1, slo=slo,
                              shed_on_slo_pressure=True)
    p = np.asarray([1, 2, 3], np.int32)
    # fill both queues to the SLO bound with priority-0 work
    h_a = fleet.submit(GenerationRequest(p, max_new_tokens=2,
                                         priority=0))
    h_b = fleet.submit(GenerationRequest(p, max_new_tokens=2,
                                         priority=0))
    # equal priority, every replica at pressure: refused fleet-wide
    with pytest.raises(LoadShedError):
        fleet.submit(GenerationRequest(p, max_new_tokens=2, priority=0))
    # higher priority: sheds a queued priority-0 victim somewhere
    h_hi = fleet.submit(GenerationRequest(p, max_new_tokens=2,
                                          priority=5))
    fleet.run_until_complete(max_steps=300)
    want = np.asarray(model.generate(p, max_new_tokens=2,
                                     temperature=0.0))
    assert np.array_equal(h_hi.result().tokens, want)
    outcomes = []
    for h in (h_a, h_b):
        try:
            assert np.array_equal(h.result().tokens, want)
            outcomes.append("ok")
        except LoadShedError:
            outcomes.append("shed")
    assert sorted(outcomes) == ["ok", "shed"]
    fleet.close()


def test_hedge_redispatches_stuck_admission(model):
    """A request stuck un-started behind one replica's queue for
    hedge_after_steps re-dispatches to the idle sibling; first
    completion wins with oracle parity."""
    work = _workload(3, seed=6, new_lo=4, new_hi=8)
    base = _oracle(model, work)
    fleet = model.serve_fleet(replicas=2, max_slots=1,
                              hedge_after_steps=2)
    # pin routing to replica 0 so its queue backs up
    fleet.router.rank = lambda views: sorted(
        v["replica"] for v in views)
    hs = [fleet.submit(GenerationRequest(p, max_new_tokens=n))
          for p, n in work]
    # admission happens at step time: all three sit in replica 0's queue
    assert fleet.supervisor(0).engine.scheduler.queue_depth == 3
    fleet.run_until_complete(max_steps=500)
    for h, want in zip(hs, base):
        assert np.array_equal(h.result().tokens, want)
    snap = fleet.snapshot()
    assert snap["hedges"] >= 1
    # hedges land on the sibling, not the loaded replica
    assert _counter("serve.fleet.hedges", fleet=fleet.fleet_label,
                    replica="1") >= 1
    fleet.close()


def test_hedge_skips_streaming_and_sessions(model):
    """on_token / pin_session requests never hedge (a duplicate stream
    would double tokens at the client; sessions are replica-local)."""
    fleet = model.serve_fleet(replicas=2, max_slots=1,
                              hedge_after_steps=1)
    fleet.router.rank = lambda views: sorted(
        v["replica"] for v in views)
    p = np.asarray([4, 5, 6], np.int32)
    tokens = []
    hs = [fleet.submit(GenerationRequest(p, max_new_tokens=3)),
          fleet.submit(GenerationRequest(
              p, max_new_tokens=3,
              on_token=lambda r, t: tokens.append(t))),
          fleet.submit(GenerationRequest(p, max_new_tokens=3,
                                         pin_session=True))]
    fleet.run_until_complete(max_steps=500)
    for h in hs:
        h.result()
    # the streaming request emitted each token exactly once
    assert len(tokens) == 3
    fleet.close()


# ---------------------------------------------------------------------------
# fault site + observability surface
# ---------------------------------------------------------------------------

def test_serve_route_fault_site_is_synchronous_and_typed(model):
    from singa_tpu.resilience.faults import SITES

    assert "serve.route" in SITES
    with model.serve_fleet(replicas=2, max_slots=1) as fleet:
        faults.inject("serve.route", FailOnce())
        p = np.asarray([1, 2], np.int32)
        with pytest.raises(Exception) as ei:
            fleet.submit(GenerationRequest(p, max_new_tokens=2))
        assert getattr(ei.value, "site", None) == "serve.route"
        faults.clear()
        # nothing was accepted: the next submit is clean
        h = fleet.submit(GenerationRequest(p, max_new_tokens=2))
        fleet.run_until_complete(max_steps=200)
        h.result()


def test_fleet_metrics_health_report_and_unregister(model):
    work = _workload(4, seed=7)
    fleet = model.serve_fleet(replicas=2, max_slots=2)
    hs = [fleet.submit(GenerationRequest(p, max_new_tokens=n))
          for p, n in work]
    fleet.run_until_complete(max_steps=500)
    for h in hs:
        h.result()
    lbl = fleet.fleet_label
    assert _counter("serve.fleet.routed", fleet=lbl, replica="0") \
        + _counter("serve.fleet.routed", fleet=lbl, replica="1") \
        == len(work)
    rep = health_report(include_registry=False)
    sec = rep["serve"]["fleet"]
    assert sec["replicas_healthy"] >= 2
    assert sec["failovers"] == 0
    assert sum(sec["routed"].values()) >= len(work)
    # fleet restart accounting rides the resilience section
    assert "fleet_failovers" in rep["resilience"]
    assert "fleet_requeues" in rep["resilience"]
    snap = fleet.snapshot()
    assert set(snap) == {"replicas", "replicas_healthy",
                         "replicas_routable", "replicas_draining",
                         "replicas_retired", "roles",
                         "failovers", "requeues", "hedges", "routed",
                         "ships", "ship_bytes", "shared_prefix_hits",
                         "ship_fallbacks", "engines"}
    assert len(snap["engines"]) == 2
    # add-only autoscale-round keys: nothing draining or retired in a
    # static fleet, every replica routable
    assert snap["replicas_routable"] == 2
    assert snap["replicas_draining"] == 0
    assert snap["replicas_retired"] == 0
    fleet.close()
    gkey = "serve.fleet.replicas_healthy{fleet=%s}" % lbl
    assert gkey not in registry().snapshot()["gauges"]
    with pytest.raises(RuntimeError, match="closed"):
        fleet.submit(GenerationRequest(work[0][0], max_new_tokens=2))


def test_fleet_validates_config(model):
    with pytest.raises(ValueError, match="replicas"):
        ServeFleet(model, replicas=0)
    with pytest.raises(ValueError, match="hedge_after_steps"):
        ServeFleet(model, replicas=1, hedge_after_steps=0)
    with pytest.raises(ValueError, match="budget_reset_after_s"):
        ServeFleet(model, replicas=1, budget_reset_after_s=-1.0)
