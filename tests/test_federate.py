"""Cross-host telemetry federation (the federation round), unit half:
clock-offset estimation against fake skewed/drifting clocks, merged
timeline shifting and graft, ingest idempotence, the federated
Prometheus exposition (``host=`` labels, per-series ``+Inf == _count``,
cross-host bucket aggregation == ``sum(rate(x_bucket)) by (le)``), and
the typed ``stale`` degradation.  Everything here is synthetic — no
engines, no sockets — so this module is collection-order-safe; the
fleet-level federation behavior (thread workers, telemetry-channel
chaos, retire unregistration) lives in test_dist_fleet.py, which sorts
after the paged cost-table hazard boundary."""

import math

import pytest

from singa_tpu.observe import requests as reqtrace
from singa_tpu.observe.federate import (ClockSync, FleetTelemetry,
                                        merge_bucket_counts,
                                        quantile_from_buckets)
from singa_tpu.observe.registry import MetricsRegistry


# ---------------------------------------------------------------------------
# clock-offset estimation
# ---------------------------------------------------------------------------

class _TwoClocks:
    """A controller clock and a peer clock offset by ``skew`` (plus
    optional drift), with a controllable per-probe RTT: the probe sees
    the peer's clock exactly halfway through the round trip."""

    def __init__(self, skew, rtt=0.002, drift=0.0):
        self.t = 100.0
        self.skew = skew
        self.rtt = rtt
        self.drift = drift

    def local(self):
        return self.t

    def probe(self):
        # request travels rtt/2, peer reads its clock, reply travels
        # rtt/2; the peer clock also drifts per probe
        self.t += self.rtt / 2.0
        self.skew += self.drift
        peer_now = self.t + self.skew
        self.t += self.rtt / 2.0
        return peer_now


def test_clock_sync_recovers_skew_within_half_rtt():
    for skew in (-3.5, 0.0, 0.25, 120.0):
        w = _TwoClocks(skew, rtt=0.004)
        cs = ClockSync(clock=w.local).sample(w.probe, samples=5)
        assert abs(cs.offset - skew) <= cs.uncertainty + 1e-12
        assert cs.uncertainty <= w.rtt / 2.0 + 1e-12
        # mapping a peer reading back lands within the error bound
        t_peer = w.probe()
        assert abs(cs.to_local(t_peer) - w.local()) \
            <= cs.uncertainty + w.rtt + 1e-9


def test_clock_sync_asymmetric_rtt_keeps_min_sample():
    """NTP filter: noisy (large-RTT) probes never override a tighter
    earlier sample, so queueing spikes cannot degrade the estimate."""
    w = _TwoClocks(1.0, rtt=0.001)
    cs = ClockSync(clock=w.local).sample(w.probe, samples=3)
    tight = cs.rtt
    w.rtt = 0.5  # the link got congested
    cs.sample(w.probe, samples=3)
    assert cs.rtt == tight
    assert abs(cs.offset - 1.0) <= tight / 2.0 + 1e-12
    assert cs.samples == 6


def test_clock_sync_drifting_peer_reestimate():
    """A drifting peer clock: re-running sample() (what the fleet does
    on reconnect/replace_dead) re-anchors the offset; the new estimate
    tracks the CURRENT skew within RTT/2."""
    w = _TwoClocks(0.5, rtt=0.002, drift=0.0)
    cs = ClockSync(clock=w.local).sample(w.probe, samples=4)
    w.skew += 2.0          # the peer restarted with a new clock base
    cs2 = ClockSync(clock=w.local).sample(w.probe, samples=4)
    assert abs(cs2.offset - w.skew) <= cs2.uncertainty + 1e-12
    assert abs(cs2.offset - cs.offset - 2.0) <= 0.004
    # summary is JSON-shaped
    s = cs2.summary()
    assert set(s) == {"offset_s", "rtt_s", "uncertainty_s", "samples"}


def test_clock_sync_rejects_zero_samples():
    with pytest.raises(ValueError):
        ClockSync().sample(lambda: 0.0, samples=0)


# ---------------------------------------------------------------------------
# bucket-ladder merge + aggregated quantiles
# ---------------------------------------------------------------------------

def test_merge_bucket_counts_is_elementwise_sum():
    a = [[0.1, 2], [1.0, 5], [float("inf"), 7]]
    b = [[0.1, 1], [1.0, 1], [float("inf"), 4]]
    merged = merge_bucket_counts([a, b])
    assert merged == [(0.1, 3), (1.0, 6), (float("inf"), 11)]
    # the prometheus identity the exposition relies on: the merged
    # +Inf bucket is the fleet-wide count
    assert merged[-1][1] == 7 + 4


def test_quantile_from_buckets_interpolates():
    buckets = [(1.0, 10), (2.0, 20), (float("inf"), 20)]
    assert quantile_from_buckets(buckets, 0.25) == pytest.approx(0.5)
    assert quantile_from_buckets(buckets, 0.75) == pytest.approx(1.5)
    # overflow-bucket quantile clamps to the highest finite bound
    buckets = [(1.0, 2), (float("inf"), 8)]
    assert quantile_from_buckets(buckets, 0.99) == 1.0
    # nothing observed under a finite bound: no honest answer
    assert math.isnan(
        quantile_from_buckets([(1.0, 0), (float("inf"), 8)], 0.5))
    assert math.isnan(
        quantile_from_buckets([(1.0, 0), (float("inf"), 0)], 0.5))


# ---------------------------------------------------------------------------
# ingest: idempotence, staleness, host lifecycle
# ---------------------------------------------------------------------------

def _entry(rid, t0, host=None, replica=0, ttft=0.5, total=1.0,
           tokens=4):
    """A minimal sealed ledger entry with one served hop."""
    steps = [[t0 + ttft + 0.1 * i, 1] for i in range(tokens - 1)]
    return {
        "request_id": rid, "prompt_len": 8, "max_new_tokens": tokens,
        "t_submit": t0, "t_retire": t0 + total, "outcome": "length",
        "reason": None, "started": True, "tokens_out": tokens,
        "ttft_s": ttft, "tpot_s": 0.1, "phases": None,
        "hops": [{
            "engine": f"r{replica}:engine-0", "replica": replica,
            "host": host, "via": "route", "t_submit": t0,
            "t_admit": t0 + 0.1, "admit_kind": "cold",
            "hit_tokens": 0, "slot": 0, "chunks": [[t0 + 0.2, 8]],
            "t_first_token": t0 + ttft, "steps": steps,
            "tokens": tokens, "preemptions": [], "reject": None,
            "ship_s": None,
        }],
    }


class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def test_ingest_is_idempotent_and_clears_stale():
    clk = _FakeClock()
    ft = FleetTelemetry(clock=clk)
    ft.host_online("w0")
    payload = {"ledger": [_entry("a", 10.0)], "pid": 111,
               "registry": {"schema": "singa_tpu.telemetry/1",
                            "metrics": []}}
    ft.ingest("w0", payload)
    ft.mark_stale("w0", "socket severed")
    assert ft.hosts["w0"].stale
    assert ft.hosts["w0"].stale_reason == "socket severed"
    # the SAME seal re-shipped (pull overlap) merges to one entry and
    # a successful pull clears the typed stale marker
    ft.ingest("w0", payload)
    assert not ft.hosts["w0"].stale
    assert ft.hosts["w0"].pulls == 2
    assert list(ft.hosts["w0"].entries) == ["a"]
    merged_once = ft.merged_entries(local_entries=[])
    merged_twice = ft.merged_entries(local_entries=[])
    assert merged_once == merged_twice  # merge never mutates state
    # a LATER seal of the same rid replaces the interim one
    late = _entry("a", 10.0, total=2.0)
    ft.ingest("w0", {"ledger": [late]})
    assert ft.hosts["w0"].entries["a"]["t_retire"] == 12.0


def test_mark_stale_on_unknown_host_never_raises():
    ft = FleetTelemetry(clock=_FakeClock())
    ft.mark_stale("w9", "first contact failed")
    assert ft.hosts["w9"].stale


def test_host_online_drops_predecessor_and_remove_host():
    ft = FleetTelemetry(clock=_FakeClock())
    ft.host_online("w0")
    ft.ingest("w0", {"ledger": [_entry("a", 1.0)]})
    # replace_dead respawns the same slot: fresh host, no frozen state
    ft.host_online("w0", pid=222)
    assert ft.hosts["w0"].entries == {}
    assert ft.hosts["w0"].pid == 222
    ft.remove_host("w0")
    assert "w0" not in ft.hosts
    assert "w0" not in ft.prometheus_text()


# ---------------------------------------------------------------------------
# merged timelines: clock shift + graft
# ---------------------------------------------------------------------------

def test_merged_entries_shift_into_controller_time():
    """Worker entries arrive on a clock 5 s ahead; after the merge
    every timestamp is in controller time and per-hop ordering
    (submit <= admit <= first token <= retire) holds."""
    clk = _FakeClock()
    ft = FleetTelemetry(clock=clk)
    cs = ClockSync()
    cs.offset, cs.rtt = 5.0, 0.001
    ft.host_online("w0", clock_sync=cs)
    ft.ingest("w0", {"ledger": [_entry("a", 105.0)]})  # worker clock
    merged = ft.merged_entries(local_entries=[])
    assert len(merged) == 1
    e = merged[0]
    assert e["t_submit"] == pytest.approx(100.0)
    assert e["t_retire"] == pytest.approx(101.0)
    hop = e["hops"][0]
    assert hop["host"] == "w0"
    assert hop["t_submit"] <= hop["t_admit"] <= hop["t_first_token"]
    assert e["t_submit"] <= hop["t_admit"] <= e["t_retire"]
    for t, _n in hop["steps"]:
        assert hop["t_first_token"] <= t <= e["t_retire"] + 1e-9


def test_merged_entries_graft_worker_detail_into_mirror():
    """Process mode: the controller mirror has the routing skeleton
    (submit/retire, replica stamp) but no engine detail; the worker's
    record fills admission/first-token/steps and the derived
    ttft/phases are recomputed — after which the merged why_slow can
    attribute the request's latency."""
    ft = FleetTelemetry(clock=_FakeClock())
    cs = ClockSync()
    cs.offset, cs.rtt = -2.0, 0.001  # worker clock 2 s BEHIND
    ft.host_online("w1", clock_sync=cs)
    mirror = {
        "request_id": "a", "prompt_len": 8, "max_new_tokens": 4,
        "t_submit": 50.0, "t_retire": 51.0, "outcome": "length",
        "reason": None, "started": True, "tokens_out": 4,
        "ttft_s": None, "tpot_s": None, "phases": None,
        "hops": [{
            "engine": "r1:engine-0", "replica": 1, "host": "w1",
            "via": "route", "t_submit": 50.0, "t_admit": None,
            "admit_kind": None, "hit_tokens": 0, "slot": None,
            "chunks": [], "t_first_token": None, "steps": [],
            "tokens": 0, "preemptions": [], "reject": None,
            "ship_s": None,
        }],
    }
    ft.ingest("w1", {"ledger": [_entry("a", 48.0, ttft=0.4)]})
    merged = ft.merged_entries(local_entries=[mirror])
    assert len(merged) == 1
    hop = merged[0]["hops"][0]
    assert hop["t_admit"] == pytest.approx(48.1 + 2.0)
    assert hop["t_first_token"] == pytest.approx(48.4 + 2.0)
    assert merged[0]["ttft_s"] == pytest.approx(0.4)
    assert merged[0]["phases"] is not None
    ws = ft.why_slow(local_entries=[mirror])
    fr = ws["ttft_p99_attribution"]
    assert "ship" in fr
    assert sum(p["frac"] for p in fr.values()) == pytest.approx(1.0)
    assert ws["straggler_host"]["host"] == "w1"
    lat = ws["latency_p99_attribution"]
    assert set(lat) == {"queue", "prefill", "ship", "decode", "stall",
                        "preempted", "hops"}
    assert sum(p["frac"] for p in lat.values()) == pytest.approx(1.0)


def test_merged_entries_never_mutate_live_ledger():
    led = reqtrace.RequestLedger(capacity=8)
    t = 0.0
    led.on_submit("a", engine="e0", t=t, prompt_len=4,
                  max_new_tokens=2)
    led.on_admit("a", engine="e0", t=0.1, slot=0)
    led.on_first_token("a", engine="e0", t=0.2)
    led.on_retire("a", engine="e0", t=0.5, finish_reason="length",
                  tokens=2)
    before = [dict(e) for e in led.entries()]
    ft = FleetTelemetry(clock=_FakeClock())
    ft.host_online("w0")
    ft.merged_entries(local_entries=led.entries())
    assert led.entries() == before


# ---------------------------------------------------------------------------
# federated exposition
# ---------------------------------------------------------------------------

def _dump_with_histogram(n_obs, scale=1.0):
    """A real registry dump with one counter and one histogram."""
    reg = MetricsRegistry()
    c = reg.counter("serve.dist.rpcs", help="calls", peer="x")
    c.inc(n_obs)
    h = reg.histogram("serve.dist.rtt_s", help="rtt",
                      buckets=(0.001, 0.01, 0.1, 1.0), peer="x")
    for i in range(n_obs):
        h.observe(scale * (i + 1) / n_obs)
    return reg.dump()


def _parse_prom(text):
    samples = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name_lbl, val = line.rsplit(" ", 1)
        samples[name_lbl] = float(val.replace("+Inf", "inf"))
    return samples


def test_prometheus_federation_host_labels_and_inf_invariant():
    ft = FleetTelemetry(clock=_FakeClock())
    ft.host_online("w0")
    ft.host_online("w1")
    ft.ingest("w0", {"registry": _dump_with_histogram(10, 0.05)})
    ft.ingest("w1", {"registry": _dump_with_histogram(6, 0.5)})
    text = ft.prometheus_text()
    samples = _parse_prom(text)
    # every series is host-labeled; counters keep the _total suffix
    assert samples[
        'singa_tpu_serve_dist_rpcs_total{host="w0",peer="x"}'] == 10
    assert samples[
        'singa_tpu_serve_dist_rpcs_total{host="w1",peer="x"}'] == 6
    # the per-series prometheus identity: +Inf bucket == _count
    for host, n in (("w0", 10), ("w1", 6)):
        inf_key = ('singa_tpu_serve_dist_rtt_s_bucket'
                   f'{{host="{host}",le="+Inf",peer="x"}}')
        cnt_key = ('singa_tpu_serve_dist_rtt_s_count'
                   f'{{host="{host}",peer="x"}}')
        assert samples[inf_key] == samples[cnt_key] == n
    # TYPE lines: declared once per family, histogram stays histogram
    assert text.count(
        "# TYPE singa_tpu_serve_dist_rtt_s histogram") == 1
    assert text.count(
        "# TYPE singa_tpu_serve_dist_rpcs_total counter") == 1


def test_fleet_quantile_equals_sum_by_le():
    """The promQL the docs teach —
    ``histogram_quantile(q, sum(rate(x_bucket)) by (le))`` — computed
    two ways must agree: merged_histogram's ladder IS the sum-by-le of
    the per-host ladders, and the aggregated p99 interpolates on it."""
    ft = FleetTelemetry(clock=_FakeClock())
    ft.host_online("w0")
    ft.host_online("w1")
    ft.ingest("w0", {"registry": _dump_with_histogram(10, 0.05)})
    ft.ingest("w1", {"registry": _dump_with_histogram(6, 0.5)})
    agg = ft.merged_histogram("serve.dist.rtt_s")
    assert agg["count"] == 16
    assert agg["per_host_counts"] == {"w0": 10, "w1": 6}
    # hand-built sum() by (le) over the exposition's bucket samples
    samples = _parse_prom(ft.prometheus_text())
    by_le = {}
    for k, v in samples.items():
        if k.startswith("singa_tpu_serve_dist_rtt_s_bucket"):
            le = k.split('le="')[1].split('"')[0]
            by_le[float(le)] = by_le.get(float(le), 0) + v
    assert {le: c for le, c in agg["buckets"]} == by_le
    p99 = quantile_from_buckets(agg["buckets"], 0.99)
    assert agg["p99"] == p99
    # 99th of 16 obs lands in w1's tail: above w0's whole range
    assert p99 > 0.05
    assert by_le[float("inf")] == agg["count"]


def test_chrome_trace_cross_host_flow_arrows():
    """A two-hop request whose hops ran on different hosts draws one
    flow arrow between the two host pids; a kv_ship hop's arrow spans
    its measured wire time."""
    ft = FleetTelemetry(clock=_FakeClock())
    ft.host_online("w0")
    ft.host_online("w1")
    e = _entry("a", 10.0, host="w0", replica=0)
    hop2 = dict(e["hops"][0], host="w1", replica=1, via="kv_ship",
                t_submit=10.6, t_admit=10.7, t_first_token=10.8,
                ship_s=0.2)
    e["hops"].append(hop2)
    doc = ft.chrome_trace(events=[], requests=[e])
    assert doc["otherData"]["cross_host_flows"] == 1
    pids = {ev["pid"] for ev in doc["traceEvents"]}
    assert {10, 11} <= pids  # one pid per host
    s = [ev for ev in doc["traceEvents"] if ev.get("ph") == "s"
         and ev.get("cat") == "fleet"]
    f = [ev for ev in doc["traceEvents"] if ev.get("ph") == "f"
         and ev.get("cat") == "fleet"]
    assert len(s) == len(f) == 1
    assert s[0]["id"] == f[0]["id"]
    assert s[0]["pid"] == 10 and f[0]["pid"] == 11
    assert s[0]["args"]["src_host"] == "w0"
    assert f[0]["args"]["dst_host"] == "w1"
    # the arrow spans the ship's wire time, ending at hop arrival
    assert f[0]["ts"] == pytest.approx(10.6 * 1e6)
    assert f[0]["ts"] - s[0]["ts"] == pytest.approx(0.2 * 1e6)
    # same-host consecutive hops draw NO arrow
    e2 = _entry("b", 11.0, host="w0")
    e2["hops"].append(dict(e2["hops"][0]))
    doc2 = ft.chrome_trace(events=[], requests=[e2])
    assert doc2["otherData"]["cross_host_flows"] == 0


def test_section_reports_stale_and_clock():
    clk = _FakeClock()
    ft = FleetTelemetry(clock=clk)
    cs = ClockSync()
    cs.offset, cs.rtt, cs.samples = 0.25, 0.002, 5
    ft.host_online("w0", clock_sync=cs)
    ft.ingest("w0", {"registry": {"schema": "singa_tpu.telemetry/1",
                                  "metrics": []}})
    ft.mark_stale("w1", "PeerGoneError('severed')")
    clk.t += 3.0
    sec = ft.section()
    assert sec["enabled"] is True
    assert sec["stale_hosts"] == ["w1"]
    assert sec["hosts"]["w0"]["clock"]["offset_s"] == 0.25
    assert sec["hosts"]["w0"]["last_pull_age_s"] == pytest.approx(3.0)
    assert sec["hosts"]["w1"]["stale_reason"].startswith("PeerGone")
    # the exposition carries the typed stale marker as a gauge
    samples = _parse_prom(ft.prometheus_text())
    assert samples['singa_tpu_federation_stale{host="w0"}'] == 0
    assert samples['singa_tpu_federation_stale{host="w1"}'] == 1
