"""Native IO tests: BinFile store, prefetch queue, Snapshot, DataLoader
(reference: test/singa/test_snapshot.cc + io tests, unverified)."""

import numpy as np
import pytest

from singa_tpu import snapshot, tensor
from singa_tpu.io import binfile, loader


def test_native_library_builds():
    """The C++ runtime must actually build in this image (g++ is baked
    in); the pure-Python fallback is for exotic environments only."""
    assert binfile.native_available(), binfile._lib_err


def test_binfile_roundtrip(tmp_path):
    path = str(tmp_path / "store.bin")
    with binfile.BinFileWriter(path) as w:
        w.put("alpha", b"hello")
        w.put("beta", b"\x00\x01\x02" * 100)
        w.put("empty", b"")
    with binfile.BinFileReader(path) as r:
        assert r.count() == 3
        assert r.key(0) == "alpha"
        assert r.value(0) == b"hello"
        assert r.value(1) == b"\x00\x01\x02" * 100
        assert r.value(2) == b""
        d = r.read_all()
        assert set(d) == {"alpha", "beta", "empty"}


def test_binfile_crc_detects_corruption(tmp_path):
    path = str(tmp_path / "store.bin")
    with binfile.BinFileWriter(path) as w:
        w.put("k", b"A" * 64)
    blob = open(path, "rb").read()
    # flip a byte inside the value region
    corrupted = bytearray(blob)
    corrupted[-10] ^= 0xFF
    open(path, "wb").write(bytes(corrupted))
    with binfile.BinFileReader(path) as r:
        with pytest.raises(OSError, match="CRC|read failed"):
            r.value(0)


def test_prefetch_queue_threaded():
    import threading

    q = binfile.PrefetchQueue(capacity=4)
    items = [(f"k{i}", bytes([i]) * (i + 1)) for i in range(20)]

    def producer():
        for k, v in items:
            q.put(k, v)
        q.close()

    t = threading.Thread(target=producer)
    t.start()
    got = []
    while True:
        item = q.get()
        if item is None:
            break
        got.append(item)
    t.join()
    assert got == items
    q.free()


def test_snapshot_roundtrip(tmp_path):
    path = str(tmp_path / "ckpt")
    w = np.random.RandomState(0).randn(4, 5).astype(np.float32)
    b = np.arange(3, dtype=np.int32)
    with snapshot.Snapshot(path, snapshot.Snapshot.kWrite) as s:
        s.write("w", tensor.from_numpy(w))
        s.write("b", b)
    with snapshot.Snapshot(path, snapshot.Snapshot.kRead) as s:
        out = s.read()
    np.testing.assert_array_equal(tensor.to_numpy(out["w"]), w)
    np.testing.assert_array_equal(tensor.to_numpy(out["b"]), b)
    assert out["b"].data.dtype == np.int32


def test_dataloader_batches(tmp_path):
    path = str(tmp_path / "data.bin")
    rng = np.random.RandomState(0)
    xs = rng.randn(50, 3, 4, 4).astype(np.float32)
    ys = rng.randint(0, 10, (50,))
    loader.write_dataset(path, xs, ys)

    dl = loader.DataLoader(path, batch_size=8, shuffle=False, num_workers=3)
    assert len(dl) == 6
    seen_x, seen_y = [], []
    for xb, yb in dl:
        assert xb.shape == (8, 3, 4, 4)
        assert yb.shape == (8,)
        seen_x.append(xb)
        seen_y.append(yb)
    assert len(seen_x) == 6
    # unshuffled loader must preserve content (order of batches may vary
    # across workers)
    all_y = np.concatenate(seen_y)
    np.testing.assert_array_equal(np.sort(all_y), np.sort(ys[:48]))


def test_dataloader_shuffles(tmp_path):
    path = str(tmp_path / "data.bin")
    xs = np.arange(40, dtype=np.float32).reshape(40, 1)
    ys = np.arange(40)
    loader.write_dataset(path, xs, ys)
    dl = loader.DataLoader(path, batch_size=10, shuffle=True, num_workers=1)
    e1 = np.concatenate([yb for _, yb in dl])
    e2 = np.concatenate([yb for _, yb in dl])
    assert not np.array_equal(e1, e2)  # reshuffled per epoch
    np.testing.assert_array_equal(np.sort(e1), np.arange(40))


def test_utils_metrics_and_timer():
    from singa_tpu.utils.metrics import StepTimer, scaling_efficiency
    from singa_tpu.utils.timer import Timer

    st = StepTimer(skip_first=1)
    for _ in range(3):
        with st:
            pass
    assert st.mean_step_seconds() >= 0
    assert abs(scaling_efficiency(7.2, 1.0, 8) - 0.9) < 1e-9
    with Timer() as t:
        pass
    assert t.seconds >= 0


def test_logging_channels(tmp_path):
    from singa_tpu.utils import logging as slog

    slog.init_channel(dir=str(tmp_path), stderr=False)
    slog._channels.clear()
    ch = slog.get_channel("train")
    ch.info("hello %d", 42)
    content = (tmp_path / "train.log").read_text()
    assert "hello 42" in content
    slog.CHECK_EQ(1, 1)
    with pytest.raises(AssertionError, match="CHECK_EQ"):
        slog.CHECK_EQ(1, 2)
