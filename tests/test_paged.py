"""Paged KV arena (serve/paged.py + the engine's ``paged=`` mode):
byte parity against the slot-arena oracle (cold / warm / int8 / GQA /
speculative / preempt-resume), block accounting and leak checks,
priority preemption ordering, config validation, and the
observability surface (``serve.paged.*`` metrics, health section,
request-ledger ``preempted`` phase).

Everything deterministic on CPU: parity is np.array_equal on token
streams, and the slot-arena engine (itself parity-pinned against
single-prompt ``generate`` in tests/test_serve.py) is the oracle, so
preemption/swap noise cannot hide behind tolerance."""

import numpy as np
import pytest

from singa_tpu import tensor
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from singa_tpu.observe import health_report
from singa_tpu.observe import requests as reqtrace
from singa_tpu.observe.registry import registry
from singa_tpu.resilience import FailAfterN, faults
from singa_tpu.serve import (EngineFailedError, EngineSupervisor,
                             GenerationRequest, PagedConfig,
                             PrefixCacheConfig, PriorityScheduler)


def _build(cfg):
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    return m


@pytest.fixture(scope="module")
def model():
    return _build(GPT2Config.tiny(dropout=0.0))


@pytest.fixture(scope="module")
def draft():
    return _build(GPT2Config.tiny(dropout=0.0, n_layer=1))


def _workload(seed, n, p_lo=3, p_hi=14, n_lo=2, n_hi=9, sampled=False):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        out.append(dict(
            prompt=rng.randint(0, 256, rng.randint(p_lo, p_hi))
            .astype(np.int32),
            n_new=int(rng.randint(n_lo, n_hi)),
            temperature=(float(rng.choice([0.0, 0.9]))
                         if sampled else 0.0),
            seed=int(rng.randint(0, 1000))))
    return out


def _run(m, work, max_slots=2, max_steps=4000, **kw):
    eng = m.serve(max_slots=max_slots, **kw)
    hs = [eng.submit(GenerationRequest(
        w["prompt"], max_new_tokens=w["n_new"],
        temperature=w["temperature"], seed=w["seed"]))
        for w in work]
    eng.run_until_complete(max_steps=max_steps)
    outs = [h.result().tokens for h in hs]
    snap = eng.stats.snapshot()
    eng.close()
    return outs, snap


def test_cold_parity_and_clean_accounting(model):
    """Cold paged streams (greedy AND seeded sampling mixed in one
    pool) are byte-identical to the slot engine's, and a drained
    engine returns every block."""
    work = _workload(0, 8, sampled=True)
    base, _ = _run(model, work)
    outs, snap = _run(model, work,
                      paged=PagedConfig(block_size=8, num_blocks=32))
    assert all(np.array_equal(a, b) for a, b in zip(outs, base))
    assert snap["paged"]["blocks_used"] == 0
    assert snap["paged"]["preemptions"] == 0


def test_preempt_resume_byte_parity(model):
    """An over-committed pool forces mid-decode swaps; the resumed
    streams (byte-copied KV + restored key chain) equal the
    uninterrupted slot-engine run exactly — greedy and sampled."""
    work = _workload(1, 6, n_lo=12, n_hi=30, p_lo=4, p_hi=20,
                     sampled=True)
    base, _ = _run(model, work, max_slots=4)
    outs, snap = _run(model, work, max_slots=4,
                      paged=PagedConfig(block_size=8, num_blocks=10))
    assert all(np.array_equal(a, b) for a, b in zip(outs, base))
    pg = snap["paged"]
    assert pg["preemptions"] > 0 and pg["swap_in"] > 0
    assert pg["blocks_used"] == 0, "leaked blocks after drain"


def test_gqa_paged_parity():
    """GQA models (narrow H_kv cache leaves) page identically."""
    m = _build(GPT2Config.tiny(dropout=0.0, n_kv_head=2))
    work = _workload(2, 5, n_lo=8, n_hi=20, p_lo=4, p_hi=16)
    base, _ = _run(m, work, max_slots=3)
    outs, snap = _run(m, work, max_slots=3,
                      paged=PagedConfig(block_size=8, num_blocks=8))
    assert all(np.array_equal(a, b) for a, b in zip(outs, base))
    assert snap["paged"]["preemptions"] > 0  # pool was over-committed


def test_spec_paged_greedy_parity(model, draft):
    """Speculative decoding over the paged target arena: greedy
    streams equal the plain engine's (verify chunks scatter one or
    two blocks back per slot per step)."""
    work = _workload(3, 5, n_lo=4, n_hi=12, p_lo=4, p_hi=12)
    base, _ = _run(model, work, max_slots=3)
    outs, snap = _run(model, work, max_slots=3, draft_model=draft,
                      spec_k=3,
                      paged=PagedConfig(block_size=8, num_blocks=32))
    assert all(np.array_equal(a, b) for a, b in zip(outs, base))
    assert snap["spec"]["chunks"] > 0


def test_warm_prefix_zero_copy(model):
    """The radix cache rides the SAME pool: warm admissions share
    matched blocks by reference, donation adopts private blocks, and
    after the drain every used block is a cached block (nothing
    leaked, nothing copied)."""
    rng = np.random.RandomState(4)
    system = rng.randint(0, 256, 24).astype(np.int32)
    work = [dict(prompt=np.concatenate(
        [system, rng.randint(0, 256, rng.randint(3, 10))
         .astype(np.int32)]),
        n_new=int(rng.randint(3, 8)), temperature=0.0, seed=0)
        for _ in range(8)]
    base, _ = _run(model, work)
    outs, snap = _run(model, work,
                      paged=PagedConfig(block_size=8, num_blocks=48),
                      prefix_cache=PrefixCacheConfig(block_size=8))
    assert all(np.array_equal(a, b) for a, b in zip(outs, base))
    assert snap["prefix"]["hit_tokens"] > 0
    assert snap["paged"]["blocks_used"] == snap["prefix"]["cached_blocks"]
    assert snap["prefix"]["donate_skipped"] == 0  # adoption never skips


def test_warm_admission_never_evicts_its_own_match(model):
    """Regression (review-confirmed): under pool pressure a warm
    admission's block allocation runs the eviction path, which spares
    only REFERENCED nodes — the matched-but-not-yet-pinned path could
    be evicted mid-allocation and its block handed back to the SAME
    request, aliasing one pool block in two table lanes (silent KV
    corruption).  The fix acquires the match before allocating; this
    pins byte parity on the exact repro: serve A, then B (pressure),
    then A again warm against a pool with nothing else to evict."""
    rng = np.random.RandomState(13)
    A = rng.randint(0, 256, 12).astype(np.int32)
    Bp = rng.randint(0, 256, 12).astype(np.int32)
    oracle = {p.tobytes(): np.asarray(model.generate(
        p, max_new_tokens=6, temperature=0.0)) for p in (A, Bp)}
    eng = model.serve(max_slots=2,
                      paged=PagedConfig(block_size=4, num_blocks=6),
                      prefix_cache=PrefixCacheConfig(block_size=4))
    for p in (A, Bp, A):
        h = eng.submit(GenerationRequest(p, max_new_tokens=6,
                                         temperature=0.0))
        eng.run_until_complete(max_steps=1000)
        np.testing.assert_array_equal(h.result().tokens,
                                      oracle[p.tobytes()])
    eng.close()


@pytest.mark.slow  # int8 variant: serve-gate cache_int8 parity is the fast rep
def test_int8_paged_parity_vs_offline_oracle(model):
    """int8 pools ((values, scales) pytree leaves) page byte-exactly:
    engine streams equal the offline int8 generate oracle."""
    work = _workload(5, 5, n_lo=3, n_hi=8)
    from singa_tpu.models import gpt2_decode
    base = [np.asarray(gpt2_decode.generate(
        model, w["prompt"], max_new_tokens=w["n_new"], temperature=0,
        cache_dtype="int8")) for w in work]
    outs, snap = _run(model, work, cache_dtype="int8",
                      paged=PagedConfig(block_size=8, num_blocks=32))
    assert all(np.array_equal(a, b) for a, b in zip(outs, base))


def test_int8_prefix_cache_lifted(model):
    """The old int8 + prefix-cache refusal is LIFTED: quantized
    engines get warm admissions through the chunked canonical form —
    warm and cold streams are byte-identical to each other (two fresh
    engines agree exactly), and the paged and slot-arena versions
    agree too."""
    rng = np.random.RandomState(6)
    system = rng.randint(0, 256, 24).astype(np.int32)
    work = [dict(prompt=np.concatenate(
        [system, rng.randint(0, 256, rng.randint(3, 10))
         .astype(np.int32)]),
        n_new=int(rng.randint(3, 8)), temperature=0.0, seed=0)
        for _ in range(6)]
    kw = dict(cache_dtype="int8",
              paged=PagedConfig(block_size=8, num_blocks=64),
              prefix_cache=PrefixCacheConfig(block_size=8))
    outs_a, snap_a = _run(model, work, **kw)   # cold tree
    outs_b, _ = _run(model, work, **kw)        # fresh engine, again
    assert all(np.array_equal(a, b) for a, b in zip(outs_a, outs_b))
    assert snap_a["prefix"]["hit_tokens"] > 0
    outs_c, snap_c = _run(
        model, work, cache_dtype="int8",
        prefix_cache=PrefixCacheConfig(block_size=8, num_blocks=64))
    assert all(np.array_equal(a, b) for a, b in zip(outs_a, outs_c))
    assert snap_c["prefix"]["hit_tokens"] > 0


def test_session_multi_turn_on_paged(model):
    """pin_session on a paged engine: the generated region is
    re-canonicalized in place, the full sequence pinned, and turn 2
    is a warm hit with oracle parity."""
    rng = np.random.RandomState(7)
    eng = model.serve(max_slots=2,
                      paged=PagedConfig(block_size=8, num_blocks=64),
                      prefix_cache=PrefixCacheConfig(block_size=8))
    p = rng.randint(0, 256, 20).astype(np.int32)
    h = eng.submit(GenerationRequest(p, max_new_tokens=6,
                                     pin_session=True, temperature=0.0))
    eng.run_until_complete(max_steps=1000)
    sess = h.result().session
    assert sess is not None and sess.pinned_blocks > 0
    extra = rng.randint(0, 256, 5).astype(np.int32)
    req2 = sess.request(extra, max_new_tokens=6, temperature=0.0)
    hits0 = eng.prefix_cache.snapshot()["hit_tokens"]
    h2 = eng.submit(req2)
    eng.run_until_complete(max_steps=1000)
    want = np.asarray(model.generate(req2.prompt_ids, max_new_tokens=6,
                                     temperature=0.0))
    np.testing.assert_array_equal(h2.result().tokens, want)
    assert eng.prefix_cache.snapshot()["hit_tokens"] > hits0
    sess.release()
    eng.close()


def test_priority_preemption_ordering(model):
    """A high-priority arrival that does not fit in blocks PREEMPTS
    the strictly-lower-priority live request (swap to host) instead of
    waiting behind it: the urgent request finishes first, the victim
    resumes byte-identically, and the ledger attributes the victim's
    pause to the ``preempted`` phase with exact sums."""
    rng = np.random.RandomState(8)
    p_lo = rng.randint(0, 256, 10).astype(np.int32)
    p_hi = rng.randint(0, 256, 12).astype(np.int32)
    base_lo = np.asarray(model.generate(p_lo, max_new_tokens=16,
                                        temperature=0.0))
    base_hi = np.asarray(model.generate(p_hi, max_new_tokens=8,
                                        temperature=0.0))
    led = reqtrace.enable(capacity=64)
    try:
        eng = model.serve(max_slots=2, scheduler="priority",
                          paged=PagedConfig(block_size=8, num_blocks=4))
        h_lo = eng.submit(GenerationRequest(
            p_lo, max_new_tokens=16, temperature=0.0, priority=0))
        for _ in range(8):
            eng.step()
        h_hi = eng.submit(GenerationRequest(
            p_hi, max_new_tokens=8, temperature=0.0, priority=5))
        eng.run_until_complete(max_steps=2000)
        np.testing.assert_array_equal(h_lo.result().tokens, base_lo)
        np.testing.assert_array_equal(h_hi.result().tokens, base_hi)
        assert eng.stats.snapshot()["paged"]["preemptions"] >= 1
        # urgency won: the high-priority request retired first
        assert (h_hi.result().finished_step
                <= h_lo.result().finished_step)
        e = led.entry(h_lo.request.request_id)
        ph = e["phases"]
        assert ph["preempted"] > 0
        total = e["t_retire"] - e["t_submit"]
        assert sum(ph.values()) == pytest.approx(total, abs=1e-9)
        assert "preempted" in led.why_slow()["tpot_p99_attribution"]
        eng.close()
    finally:
        reqtrace.disable()


def test_priority_scheduler_queue_order():
    """Host-only: PriorityScheduler pops higher priority first, FIFO
    within a class, and requeue_front lands at the head of the
    request's own class."""
    sched = PriorityScheduler()
    reqs = [GenerationRequest(np.ones(4, np.int32), priority=p)
            for p in (0, 5, 0, 5, 2)]
    for r in reqs:
        sched.enqueue(r)
    admit, _ = sched.schedule(5, now=0.0)
    assert [r.priority for r in admit] == [5, 5, 2, 0, 0]
    # FIFO within the class
    assert admit[0] is reqs[1] and admit[1] is reqs[3]
    # requeue_front: ahead of equals, behind strictly higher
    for r in admit:
        sched.enqueue(r)
    sched.requeue_front(reqs[4])            # priority 2
    admit2, _ = sched.schedule(5, now=0.0)
    assert admit2[2] is reqs[4]


def test_block_accounting_under_churn(model):
    """Fragmentation-free allocation: across admit/preempt/retire
    churn the accounting invariant ``free + used == num_blocks`` holds
    at every step, and the drained engine holds exactly the cached
    blocks."""
    work = _workload(9, 12, n_lo=6, n_hi=24, p_lo=3, p_hi=20)
    eng = model.serve(max_slots=4,
                      paged=PagedConfig(block_size=8, num_blocks=12))
    arena = eng.paged_arena
    pending = list(work)
    hs = []
    while pending or eng.pending:
        if pending:
            w = pending.pop(0)
            hs.append(eng.submit(GenerationRequest(
                w["prompt"], max_new_tokens=w["n_new"],
                temperature=0.0)))
        eng.step()
        assert arena.blocks_free + arena.blocks_used \
            == arena.num_blocks
        held = sum(len(s.blocks) - s.n_shared
                   for s in eng._slots if s is not None)
        assert arena.blocks_used == held  # no cache: used == slot-held
    assert all(h.done() for h in hs)
    assert arena.blocks_used == 0
    assert eng.stats.snapshot()["paged"]["preemptions"] > 0
    eng.close()


def test_fail_rejects_swapped_started_true(model):
    """Engine failure with swapped-out work: swapped requests are
    STARTED (tokens streamed) — rejected typed started=True, never
    requeue-safe, and live_request_ids includes them (the fleet's
    failover verdict)."""
    eng = model.serve(max_slots=2,
                      paged=PagedConfig(block_size=8, num_blocks=6))
    rng = np.random.RandomState(10)
    hs = [eng.submit(GenerationRequest(
        rng.randint(0, 256, 10).astype(np.int32), max_new_tokens=20,
        temperature=0.0)) for _ in range(4)]
    steps = 0
    while not eng._swapped and steps < 60:
        eng.step()
        steps += 1
    assert eng._swapped, "pool never over-committed"
    swapped_ids = {sw.request.request_id for sw in eng._swapped}
    assert swapped_ids <= eng.live_request_ids
    faults.inject("serve.decode_step", FailAfterN(0, times=1))
    try:
        with pytest.raises(EngineFailedError):
            while eng.pending:
                eng.step()
    finally:
        faults.clear()
    for h in hs:
        assert h.done()
        if h.request.request_id in swapped_ids:
            with pytest.raises(EngineFailedError) as ei:
                h.result()
            assert ei.value.started is True
    eng.close(force=True)


def test_supervisor_restart_paged_parity(model):
    """A decode fault against a paged engine: supervisor rebuild gets
    a FRESH arena, never-started requests requeue with byte parity."""
    work = _workload(11, 8, n_lo=3, n_hi=8)
    base, _ = _run(model, work)
    sup = EngineSupervisor(model, max_slots=2, restart_budget=2,
                           paged=PagedConfig(block_size=8,
                                             num_blocks=32))
    arena0 = sup.engine.paged_arena
    hs = [sup.submit(GenerationRequest(
        w["prompt"], max_new_tokens=w["n_new"], temperature=0.0,
        seed=w["seed"])) for w in work]
    pol = faults.inject("serve.decode_step", FailAfterN(3, times=1))
    try:
        sup.run_until_complete(max_steps=4000)
    finally:
        faults.clear()
    assert pol.fired == 1
    assert sup.engine.paged_arena is not arena0
    assert sup.engine.paged_arena.blocks_used == 0
    done = typed = 0
    for w, h, want in zip(work, hs, base):
        try:
            got = h.result().tokens
            assert np.array_equal(
                got, np.asarray(model.generate(
                    w["prompt"], max_new_tokens=w["n_new"],
                    temperature=0)))
            done += 1
        except EngineFailedError:
            typed += 1
    assert done + typed == len(work) and done > 0
    sup.close()


def test_config_validation_typed_errors(model, draft):
    """Every impossible paged configuration fails typed at
    construction or submit, never inside a jitted dispatch."""
    with pytest.raises(ValueError, match="block_size"):
        PagedConfig(block_size=0)
    with pytest.raises(ValueError, match="num_blocks"):
        PagedConfig(num_blocks=0)
    with pytest.raises(ValueError, match="multiple"):
        model.serve(paged=PagedConfig(block_size=7))  # 128 % 7 != 0
    with pytest.raises(ValueError, match="paged must be"):
        model.serve(paged="yes")
    with pytest.raises(ValueError, match="spec_k"):
        model.serve(draft_model=draft, spec_k=16,
                    paged=PagedConfig(block_size=8))
    with pytest.raises(ValueError, match="granularity|block_size"):
        model.serve(paged=PagedConfig(block_size=8),
                    prefix_cache=PrefixCacheConfig(block_size=16))
    with pytest.raises(ValueError, match="unknown scheduler"):
        model.serve(scheduler="lifo")
    eng = model.serve(max_slots=1,
                      paged=PagedConfig(block_size=8, num_blocks=4))
    with pytest.raises(ValueError, match="KV blocks"):
        # needs (20 + 40 - 1)//8 + 1 = 8 blocks > 4: could never fit
        eng.submit(GenerationRequest(np.zeros(20, np.int32),
                                     max_new_tokens=40))
    eng.close()


def test_kernel_vs_gather_token_identity(model, draft):
    """The block-native kernel (PagedConfig default) and the
    materialized-row gather path (``kernel="gather"``) stream
    TOKEN-IDENTICAL — greedy and seeded sampling mixed in one pool,
    plain and speculative.  Online softmax reorders the float
    reduction, so this (plus the logits oracle below) is the parity
    pin; bitwise logit equality is impossible by construction
    (docs/SERVING.md "Paged KV and preemption")."""
    assert PagedConfig().kernel == "block"  # the kernel IS the default
    work = _workload(20, 8, sampled=True)
    outs_g, _ = _run(model, work,
                     paged=PagedConfig(block_size=8, num_blocks=32,
                                       kernel="gather"))
    outs_k, _ = _run(model, work,
                     paged=PagedConfig(block_size=8, num_blocks=32))
    assert all(np.array_equal(a, b) for a, b in zip(outs_k, outs_g))
    # speculative chunks too: the chunk-query accumulator against the
    # same draft proposal chain
    work2 = _workload(21, 4, n_lo=4, n_hi=10, p_lo=4, p_hi=12)
    sg, _ = _run(model, work2, max_slots=3, draft_model=draft,
                 spec_k=3, paged=PagedConfig(block_size=8,
                                             num_blocks=32,
                                             kernel="gather"))
    sk, _ = _run(model, work2, max_slots=3, draft_model=draft,
                 spec_k=3, paged=PagedConfig(block_size=8,
                                             num_blocks=32))
    assert all(np.array_equal(a, b) for a, b in zip(sk, sg))


def test_kernel_logits_allclose_gather_oracle(model):
    """Unit-level oracle for the online-softmax accumulator: one
    decode step through ``decode_step_paged`` against a random pool
    vs the row-math ``decode_step`` on the SAME KV materialized into
    a row — logits allclose (reduction order is the only difference),
    the written block's untouched lanes BYTE-equal to the pool (the
    read-modify-write round-trips bytes), and layer 0's written K row
    bitwise equal to the row path's (identical input, identical
    projection)."""
    from singa_tpu.models import gpt2_decode as gd
    import jax.numpy as jnp

    params = gd.extract_params(model)
    cfg = model.cfg
    L, H = cfg.n_layer, cfg.n_kv_head
    D = cfg.n_embd // cfg.n_head
    B, N = 8, 6
    rng = np.random.RandomState(0)
    pool_k = rng.randn(L, N + 1, H, B, D).astype(np.float32)
    pool_v = rng.randn(L, N + 1, H, B, D).astype(np.float32)
    pos, tok = 13, 7              # mid-block: block 1, offset 5
    tbl = np.full(4, N, np.int32)
    tbl[:2] = [3, 1]              # non-contiguous blocks, trash-padded
    x = (params["wte"][tok] + params["wpe"][pos])[None, None, :]
    n_blk = (pos + B - 1) // B
    eps = float(cfg.layer_norm_eps)
    logits_k, kb, vb = gd.decode_step_paged(
        params, x, jnp.asarray(pool_k), jnp.asarray(pool_v),
        jnp.asarray(tbl), jnp.int32(pos), jnp.int32(n_blk),
        cfg.n_head, eps, block=B, trash=N)
    # oracle: the same KV materialized into a (max_len) row
    W = len(tbl) * B
    row_k = np.zeros((L, 1, H, W, D), np.float32)
    row_v = np.zeros((L, 1, H, W, D), np.float32)
    for j, b in enumerate(tbl[:2]):
        row_k[:, 0, :, j * B:(j + 1) * B] = pool_k[:, b]
        row_v[:, 0, :, j * B:(j + 1) * B] = pool_v[:, b]
    logits_r, kc2, vc2 = gd.decode_step(
        params, x, jnp.asarray(row_k), jnp.asarray(row_v),
        jnp.int32(pos), cfg.n_head, eps)
    np.testing.assert_allclose(np.asarray(logits_k)[0],
                               np.asarray(logits_r)[0],
                               rtol=2e-5, atol=2e-5)
    # written block = pool block tbl[1], lane pos % B replaced
    kb = np.asarray(kb)           # (L, H, B, D)
    off = pos % B
    untouched = [i for i in range(B) if i != off]
    np.testing.assert_array_equal(kb[:, :, untouched],
                                  pool_k[:, 1][:, :, untouched])
    # layer 0's K row: same x, same projection — bitwise
    np.testing.assert_array_equal(
        kb[0][:, off], np.asarray(kc2)[0, 0][:, pos])
    np.testing.assert_allclose(
        kb[:, :, off], np.asarray(kc2)[:, 0][:, :, pos],
        rtol=2e-5, atol=2e-5)


def test_prefill_width_bitwise_invariance(model):
    """The paged cold-admission fast path prefills at the smallest
    block-multiple width covering the prompt instead of max_len
    (engine._admit).  The claim it leans on, pinned here empirically:
    prefill rows (K/V at positions < plen AND the sampled first
    token) are BITWISE invariant to the padded width — every op in
    the prefill stack is row-independent over the position axis, so
    right-pad lanes cannot reach live rows."""
    import jax
    import jax.numpy as jnp
    from singa_tpu.serve.engine import _prefill_one
    from singa_tpu.models.gpt2_decode import extract_params

    cfg = model.cfg
    params = extract_params(model)
    statics = dict(n_head=cfg.n_head,
                   eps=float(cfg.layer_norm_eps),
                   moe_top_k=2, top_k=0, use_top_p=False)
    rng = np.random.RandomState(23)
    plen = 20
    prompt = rng.randint(0, 256, plen).astype(np.int32)
    key0 = jax.random.PRNGKey(0)
    outs = {}
    for W in (32, cfg.n_positions):
        ids = np.zeros((1, W), np.int32)
        ids[0, :plen] = prompt
        tok0, _, kc, vc = _prefill_one(
            params, jnp.asarray(ids), jnp.int32(plen), key0,
            np.float32(0.0), jnp.float32(1.0), **statics)
        outs[W] = (int(tok0), np.asarray(kc)[:, :, :, :plen],
                   np.asarray(vc)[:, :, :, :plen])
    assert outs[32][0] == outs[cfg.n_positions][0]
    np.testing.assert_array_equal(outs[32][1],
                                  outs[cfg.n_positions][1])
    np.testing.assert_array_equal(outs[32][2],
                                  outs[cfg.n_positions][2])


def test_prefill_batch_bitwise_equals_single(model):
    """The batched pass prefill (engine._prefill_batch — one dispatch
    for a scheduling pass's cold paged admissions) produces each
    row's (first token, carried key, cache rows) BITWISE equal to
    the per-request ``_prefill_one`` call, key chain included."""
    import jax
    import jax.numpy as jnp
    from singa_tpu.serve.engine import _prefill_batch, _prefill_one
    from singa_tpu.models.gpt2_decode import extract_params

    cfg = model.cfg
    params = extract_params(model)
    statics = dict(n_head=cfg.n_head,
                   eps=float(cfg.layer_norm_eps),
                   moe_top_k=2, top_k=0, use_top_p=False)
    rng = np.random.RandomState(24)
    R, W = 3, 32
    ids = np.zeros((R, W), np.int32)
    plens = np.array([20, 7, 13], np.int32)
    for r, p in enumerate(plens):
        ids[r, :p] = rng.randint(0, 256, p)
    seeds = np.array([5, 99, 0], np.int32)
    temps = np.array([0.0, 0.9, 0.9], np.float32)
    top_p = jnp.float32(1.0)
    t_b, k_b, kc_b, vc_b = _prefill_batch(
        params, jnp.asarray(ids), jnp.asarray(plens),
        jnp.asarray(seeds), jnp.asarray(temps), top_p, **statics)
    for r in range(R):
        key0 = jax.random.split(
            jax.random.PRNGKey(int(seeds[r])), 1)[0]
        t1, k1, kc1, vc1 = _prefill_one(
            params, jnp.asarray(ids[r:r + 1]), jnp.int32(int(plens[r])),
            key0, np.float32(temps[r]), top_p, **statics)
        assert int(t1) == int(t_b[r])
        np.testing.assert_array_equal(np.asarray(k1),
                                      np.asarray(k_b[r]))
        np.testing.assert_array_equal(
            np.asarray(kc1)[:, 0, :, :plens[r]],
            np.asarray(kc_b)[:, r, :, :plens[r]])
        np.testing.assert_array_equal(
            np.asarray(vc1)[:, 0, :, :plens[r]],
            np.asarray(vc_b)[:, r, :, :plens[r]])


def test_kernel_edge_geometry(model):
    """The kernel's edge cases, each pinned token-identical to the
    slot engine: block_size ∈ {1, 8, 16} (block_size=1 was a prior
    bug site — session donation clamp, round 14), a partially-filled
    final block, prompts landing ``pos`` EXACTLY on a block boundary
    at admission, and a slot whose block list is length 1."""
    rng = np.random.RandomState(22)
    for B, N in ((1, 64), (8, 16), (16, 16)):
        work = []
        # plen % B == 0: admission's first decode write lands on a
        # block boundary (a fresh block's lane 0)
        for plen, n_new in ((max(B, 4), 5), (2 * max(B, 2), 3),
                            (3, 4), (5, 2)):
            work.append(dict(
                prompt=rng.randint(0, 256, plen).astype(np.int32),
                n_new=n_new,
                temperature=float(rng.choice([0.0, 0.9])),
                seed=int(rng.randint(0, 1000))))
        base, _ = _run(model, work)
        outs, snap = _run(model, work,
                          paged=PagedConfig(block_size=B,
                                            num_blocks=N))
        assert all(np.array_equal(a, b)
                   for a, b in zip(outs, base)), f"B={B}"
        assert snap["paged"]["blocks_used"] == 0
    # single-block list + trash-lane masking: ONE live request in a
    # 4-slot pool (three dead slots carry all-trash tables through
    # the same executable) whose whole lifetime fits block 0
    p = rng.randint(0, 256, 4).astype(np.int32)
    want = np.asarray(model.generate(p, max_new_tokens=4,
                                     temperature=0.0))
    eng = model.serve(max_slots=4,
                      paged=PagedConfig(block_size=16, num_blocks=8))
    h = eng.submit(GenerationRequest(p, max_new_tokens=4,
                                     temperature=0.0))
    peak_blocks = 0
    steps = 0
    while eng.pending and steps < 200:
        eng.step()
        steps += 1
        peak_blocks = max([peak_blocks] + [len(s.blocks)
                                           for s in eng._slots
                                           if s is not None])
    np.testing.assert_array_equal(h.result().tokens, want)
    assert peak_blocks == 1   # the whole lifetime fit ONE block
    eng.close()


def test_metrics_and_health_surface(model):
    """serve.paged.* metrics ride the process registry while the
    engine lives (and unregister at close); health_report carries the
    always-present serve.paged section."""
    eng = model.serve(max_slots=2,
                      paged=PagedConfig(block_size=8, num_blocks=6))
    rng = np.random.RandomState(12)
    hs = [eng.submit(GenerationRequest(
        rng.randint(0, 256, 10).astype(np.int32), max_new_tokens=18,
        temperature=0.0)) for _ in range(3)]
    eng.run_until_complete(max_steps=2000)
    assert all(h.done() for h in hs)
    lbl = eng.stats.engine_label
    snap = registry().snapshot()
    assert snap["gauges"][
        f"serve.paged.blocks_free{{engine={lbl}}}"] == 6
    assert f"serve.paged.preemptions{{engine={lbl}}}" \
        in snap["counters"]
    hp = health_report(include_registry=False)["serve"]["paged"]
    assert set(hp) == {"blocks_free", "blocks_used", "preemptions",
                       "swap_out", "swap_in"}
    assert hp["preemptions"] == eng.stats.snapshot()["paged"][
        "preemptions"]
    # cost-table capture (VERDICT weak #6): the paged steps' AOT
    # compiles are visible to crash bundles
    from singa_tpu.observe.monitor import _cost_tables
    keys = [t["key"] for t in _cost_tables()]
    assert any(k.startswith("serve.paged/") for k in keys), keys
    eng.close()
    snap2 = registry().snapshot()
    assert f"serve.paged.blocks_free{{engine={lbl}}}" \
        not in snap2["gauges"]
