"""REAL multi-host distributed training: two OS processes, each with 2
virtual CPU devices, bootstrap over jax.distributed (the DCN control
plane — the rebuild of the reference's MPI rank discovery + NCCL-id
broadcast) and run DistOpt data-parallel steps over the global 4-device
mesh with cross-process Gloo collectives.

The equivalence oracle: the same global batch trained on ONE process
with 4 virtual devices must produce the same losses.  The reference
could never test this path without >= 2 physical GPUs (SURVEY.md §4).
"""

import json
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

pytestmark = pytest.mark.slow

_WORKER = textwrap.dedent("""
    import json, os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")

    pid = int(sys.argv[1]); port = sys.argv[2]
    from singa_tpu.parallel.communicator import initialize_distributed
    initialize_distributed(f"127.0.0.1:{port}", num_processes=2,
                           process_id=pid)
    assert jax.process_count() == 2
    assert len(jax.devices()) == 4  # global mesh

    import numpy as np
    from singa_tpu import layer, model, opt, tensor
    from singa_tpu.parallel.communicator import Communicator
    from singa_tpu.parallel.dist_opt import DistOpt

    class Net(model.Model):
        def __init__(self):
            super().__init__()
            self.fc1 = layer.Linear(16)
            self.relu = layer.ReLU()
            self.fc2 = layer.Linear(4)
            self.loss_fn = layer.SoftMaxCrossEntropy()

        def forward(self, x):
            return self.fc2(self.relu(self.fc1(x)))

        def train_one_batch(self, x, y, opt_mode="plain"):
            out = self.forward(x)
            loss = self.loss_fn(out, y)
            if opt_mode == "sparse":
                self.optimizer.backward_and_sparse_update(loss,
                                                          spars=0.1)
            else:
                self.optimizer(loss)
            return out, loss

    # per-process LOCAL batch: process p takes rows [8p, 8p+8) of the
    # deterministic global batch (each process feeds its own shard,
    # like the reference's per-rank data loading)
    rng = np.random.RandomState(0)
    gx = rng.randn(16, 8).astype(np.float32)
    gy = rng.randint(0, 4, 16).astype(np.int32)
    lx, ly = gx[8 * pid:8 * pid + 8], gy[8 * pid:8 * pid + 8]

    from singa_tpu import device as device_mod
    # DELIBERATELY divergent init on process 1: the first globalized
    # step must broadcast process 0's params (reference MPI-bcast
    # semantics), so training still matches the single-process oracle
    device_mod.get_default_device().SetRandSeed(0 if pid == 0 else 7)
    m = Net()
    m.set_optimizer(DistOpt(opt.SGD(lr=0.1),
                            communicator=Communicator()))
    x0 = tensor.from_numpy(lx)
    m.compile([x0], is_train=True, use_graph=True)
    losses = []
    for _ in range(4):
        _, loss = m(tensor.from_numpy(lx), tensor.from_numpy(ly))
        losses.append(float(tensor.to_numpy(loss)))
    # sparse top-K steps create cross-process sharded residual state;
    # get_states() must fetch it (collective to_numpy) without crashing
    for _ in range(2):
        _, loss = m(tensor.from_numpy(lx), tensor.from_numpy(ly),
                    opt_mode="sparse")
        losses.append(float(tensor.to_numpy(loss)))
    states = m.persistent_tensors()
    fetched = {k: tensor.to_numpy(v).shape for k, v in states.items()}
    n_residual = sum(1 for k in fetched if "__residual__" in k)
    print("RESULT " + json.dumps({"pid": pid, "losses": losses,
                                  "n_state": len(fetched),
                                  "n_residual": n_residual}),
          flush=True)
""")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _single_process_reference():
    """Same training on one process, 4 devices, global batch."""
    import jax

    from singa_tpu import layer, model, opt, tensor
    from singa_tpu.parallel.communicator import Communicator
    from singa_tpu.parallel.dist_opt import DistOpt

    class Net(model.Model):
        def __init__(self):
            super().__init__()
            self.fc1 = layer.Linear(16)
            self.relu = layer.ReLU()
            self.fc2 = layer.Linear(4)
            self.loss_fn = layer.SoftMaxCrossEntropy()

        def forward(self, x):
            return self.fc2(self.relu(self.fc1(x)))

        def train_one_batch(self, x, y):
            out = self.forward(x)
            loss = self.loss_fn(out, y)
            self.optimizer(loss)
            return out, loss

    from singa_tpu import device as device_mod
    device_mod.get_default_device().SetRandSeed(0)
    rng = np.random.RandomState(0)
    gx = rng.randn(16, 8).astype(np.float32)
    gy = rng.randint(0, 4, 16).astype(np.int32)
    m = Net()
    m.set_optimizer(DistOpt(opt.SGD(lr=0.1),
                            communicator=Communicator(num_devices=4)))
    m.compile([tensor.from_numpy(gx)], is_train=True, use_graph=True)
    losses = []
    for _ in range(4):
        _, loss = m(tensor.from_numpy(gx), tensor.from_numpy(gy))
        losses.append(float(tensor.to_numpy(loss)))
    return losses


_WORKER_RESUME = textwrap.dedent("""
    import json, os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")

    pid = int(sys.argv[1]); port = sys.argv[2]; ckpt = sys.argv[3]
    from singa_tpu.parallel.communicator import initialize_distributed
    initialize_distributed(f"127.0.0.1:{port}", num_processes=2,
                           process_id=pid)

    import numpy as np
    from singa_tpu import layer, model, opt, tensor
    from singa_tpu import device as device_mod
    from singa_tpu.parallel.communicator import Communicator
    from singa_tpu.parallel.dist_opt import DistOpt

    class Net(model.Model):
        def __init__(self):
            super().__init__()
            self.fc1 = layer.Linear(16)
            self.relu = layer.ReLU()
            self.fc2 = layer.Linear(4)
            self.loss_fn = layer.SoftMaxCrossEntropy()

        def forward(self, x):
            return self.fc2(self.relu(self.fc1(x)))

        def train_one_batch(self, x, y):
            out = self.forward(x)
            loss = self.loss_fn(out, y)
            self.optimizer(loss)
            return out, loss

    def make(seed):
        device_mod.get_default_device().SetRandSeed(seed)
        m = Net()
        m.set_optimizer(DistOpt(opt.SGD(lr=0.1),
                                communicator=Communicator()))
        m.compile([tensor.from_numpy(lx)], is_train=True,
                  use_graph=True)
        return m

    rng = np.random.RandomState(0)
    gx = rng.randn(16, 8).astype(np.float32)
    gy = rng.randint(0, 4, 16).astype(np.int32)
    lx, ly = gx[8 * pid:8 * pid + 8], gy[8 * pid:8 * pid + 8]

    m = make(seed=0)
    losses = []
    for _ in range(2):
        _, loss = m(tensor.from_numpy(lx), tensor.from_numpy(ly))
        losses.append(float(tensor.to_numpy(loss)))
    # rank 0 writes the checkpoint (save_states gathers global state
    # with collective to_numpy on BOTH ranks; only rank 0 persists)
    states = {k: tensor.to_numpy(v) for k, v in m.get_states().items()}
    if pid == 0:
        m.save_states(ckpt)
    # barrier so rank 1 can't read a half-written file
    from jax.experimental import multihost_utils as mh
    mh.sync_global_devices("ckpt_written")

    # resume: FRESH divergently-seeded model on both ranks; load must
    # restore exact training state before continuing
    m2 = make(seed=100 + pid)
    m2.load_states(ckpt)
    for _ in range(2):
        _, loss = m2(tensor.from_numpy(lx), tensor.from_numpy(ly))
        losses.append(float(tensor.to_numpy(loss)))
    print("RESULT " + json.dumps({"pid": pid, "losses": losses}),
          flush=True)
""")


_WORKER_NO_COORD = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")
    from singa_tpu.parallel.communicator import initialize_distributed
    try:
        initialize_distributed(f"127.0.0.1:{sys.argv[1]}",
                               num_processes=2, process_id=1,
                               initialization_timeout=6)
    except ConnectionError as e:
        assert "unreachable" in str(e)
        print("CLEAN_ERROR " + type(e).__name__, flush=True)
        sys.exit(17)
    sys.exit(0)
""")


def test_coordinator_unreachable_times_out_cleanly():
    """A worker whose coordinator never comes up must fail with a clean
    timeout error, not hang forever (reference failure-detection
    parity, SURVEY.md §5.3/§5.8)."""
    port = _free_port()  # nothing listens here
    p = subprocess.Popen(
        [sys.executable, "-c", _WORKER_NO_COORD, str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    out, _ = p.communicate(timeout=120)
    assert p.returncode == 17, f"expected clean timeout exit:\n{out[-2000:]}"
    assert "CLEAN_ERROR" in out


def test_two_process_checkpoint_resume_matches_oracle(tmp_path):
    """Rank 0 checkpoints mid-training; both ranks resume into FRESH
    divergently-seeded models; the continued losses must match the
    single-process oracle's save/load cycle exactly."""
    port = _free_port()
    ckpt = str(tmp_path / "mh.ckpt")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER_RESUME, str(i), str(port),
             ckpt],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT "):
                r = json.loads(line[len("RESULT "):])
                results[r["pid"]] = r
    assert set(results) == {0, 1}, results
    np.testing.assert_allclose(results[0]["losses"],
                               results[1]["losses"], rtol=1e-6)

    # oracle: one process, 4 devices, same save/load cycle
    ref = _single_process_resume_reference(str(tmp_path / "sp.ckpt"))
    np.testing.assert_allclose(results[0]["losses"], ref,
                               rtol=1e-4, atol=1e-5)


def _single_process_resume_reference(ckpt):
    from singa_tpu import layer, model, opt, tensor
    from singa_tpu import device as device_mod
    from singa_tpu.parallel.communicator import Communicator
    from singa_tpu.parallel.dist_opt import DistOpt

    class Net(model.Model):
        def __init__(self):
            super().__init__()
            self.fc1 = layer.Linear(16)
            self.relu = layer.ReLU()
            self.fc2 = layer.Linear(4)
            self.loss_fn = layer.SoftMaxCrossEntropy()

        def forward(self, x):
            return self.fc2(self.relu(self.fc1(x)))

        def train_one_batch(self, x, y):
            out = self.forward(x)
            loss = self.loss_fn(out, y)
            self.optimizer(loss)
            return out, loss

    rng = np.random.RandomState(0)
    gx = rng.randn(16, 8).astype(np.float32)
    gy = rng.randint(0, 4, 16).astype(np.int32)

    def make(seed):
        device_mod.get_default_device().SetRandSeed(seed)
        m = Net()
        m.set_optimizer(DistOpt(opt.SGD(lr=0.1),
                                communicator=Communicator(num_devices=4)))
        m.compile([tensor.from_numpy(gx)], is_train=True, use_graph=True)
        return m

    m = make(seed=0)
    losses = []
    for _ in range(2):
        _, loss = m(tensor.from_numpy(gx), tensor.from_numpy(gy))
        losses.append(float(tensor.to_numpy(loss)))
    m.save_states(ckpt)
    m2 = make(seed=55)
    m2.load_states(ckpt)
    for _ in range(2):
        _, loss = m2(tensor.from_numpy(gx), tensor.from_numpy(gy))
        losses.append(float(tensor.to_numpy(loss)))
    return losses


def test_two_process_distopt_matches_single_process(tmp_path):
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER, str(i), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT "):
                r = json.loads(line[len("RESULT "):])
                results[r["pid"]] = r
    assert set(results) == {0, 1}, results
    # lockstep SPMD: both processes see the identical global loss —
    # despite process 1 starting from a DIFFERENT seed (rank-0
    # broadcast made the init consistent)
    np.testing.assert_allclose(results[0]["losses"],
                               results[1]["losses"], rtol=1e-6)

    ref = _single_process_reference()
    # the first 4 (plain) multi-host losses equal the single-process
    # global-batch run seeded like process 0
    np.testing.assert_allclose(results[0]["losses"][:4], ref,
                               rtol=1e-4, atol=1e-5)
    # training moved, and sparse steps fetched residual state
    losses = results[0]["losses"]
    assert losses[-1] < losses[0]
    assert results[0]["n_residual"] > 0


_WORKER_KILL = textwrap.dedent("""
    import json, os, sys, time
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")

    pid = int(sys.argv[1]); port = sys.argv[2]; ckpt = sys.argv[3]
    from singa_tpu.parallel.communicator import initialize_distributed
    initialize_distributed(f"127.0.0.1:{port}", num_processes=2,
                           process_id=pid)

    import numpy as np
    from singa_tpu import layer, model, opt, tensor
    from singa_tpu import device as device_mod
    from singa_tpu.parallel.communicator import Communicator
    from singa_tpu.parallel.dist_opt import DistOpt

    class Net(model.Model):
        def __init__(self):
            super().__init__()
            self.fc1 = layer.Linear(16)
            self.relu = layer.ReLU()
            self.fc2 = layer.Linear(4)
            self.loss_fn = layer.SoftMaxCrossEntropy()

        def forward(self, x):
            return self.fc2(self.relu(self.fc1(x)))

        def train_one_batch(self, x, y):
            out = self.forward(x)
            loss = self.loss_fn(out, y)
            self.optimizer(loss)
            return out, loss

    rng = np.random.RandomState(0)
    gx = rng.randn(16, 8).astype(np.float32)
    gy = rng.randint(0, 4, 16).astype(np.int32)
    lx, ly = gx[8 * pid:8 * pid + 8], gy[8 * pid:8 * pid + 8]

    device_mod.get_default_device().SetRandSeed(0)
    m = Net()
    m.set_optimizer(DistOpt(opt.SGD(lr=0.1),
                            communicator=Communicator()))
    m.compile([tensor.from_numpy(lx)], is_train=True, use_graph=True)
    for _ in range(2):
        _, loss = m(tensor.from_numpy(lx), tensor.from_numpy(ly))
        float(tensor.to_numpy(loss))
    if pid == 0:
        m.save_states(ckpt)
    from jax.experimental import multihost_utils as mh
    mh.sync_global_devices("ckpt_written")
    print("CKPT_DONE", flush=True)

    # steady stepping; the parent SIGKILLs rank 1 somewhere in here.
    # Every step ends in a blocking readback, so rank 0's next
    # cross-process all-reduce after the kill MUST surface an error.
    try:
        for i in range(2000):
            _, loss = m(tensor.from_numpy(lx), tensor.from_numpy(ly))
            float(tensor.to_numpy(loss))
        print("NO_ERROR", flush=True)
        sys.exit(1)
    except BaseException as e:
        print("SURVIVOR_ERROR " + type(e).__name__, flush=True)
        sys.exit(23)
""")


_WORKER_RESTART = textwrap.dedent("""
    import json, os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")

    pid = int(sys.argv[1]); port = sys.argv[2]; ckpt = sys.argv[3]
    from singa_tpu.parallel.communicator import initialize_distributed
    initialize_distributed(f"127.0.0.1:{port}", num_processes=2,
                           process_id=pid)

    import numpy as np
    from singa_tpu import layer, model, opt, tensor
    from singa_tpu import device as device_mod
    from singa_tpu.parallel.communicator import Communicator
    from singa_tpu.parallel.dist_opt import DistOpt

    class Net(model.Model):
        def __init__(self):
            super().__init__()
            self.fc1 = layer.Linear(16)
            self.relu = layer.ReLU()
            self.fc2 = layer.Linear(4)
            self.loss_fn = layer.SoftMaxCrossEntropy()

        def forward(self, x):
            return self.fc2(self.relu(self.fc1(x)))

        def train_one_batch(self, x, y):
            out = self.forward(x)
            loss = self.loss_fn(out, y)
            self.optimizer(loss)
            return out, loss

    rng = np.random.RandomState(0)
    gx = rng.randn(16, 8).astype(np.float32)
    gy = rng.randint(0, 4, 16).astype(np.int32)
    lx, ly = gx[8 * pid:8 * pid + 8], gy[8 * pid:8 * pid + 8]

    # fresh job, divergent seeds: load must restore the pre-crash state
    device_mod.get_default_device().SetRandSeed(200 + pid)
    m = Net()
    m.set_optimizer(DistOpt(opt.SGD(lr=0.1),
                            communicator=Communicator()))
    m.compile([tensor.from_numpy(lx)], is_train=True, use_graph=True)
    m.load_states(ckpt)
    losses = []
    for _ in range(2):
        _, loss = m(tensor.from_numpy(lx), tensor.from_numpy(ly))
        losses.append(float(tensor.to_numpy(loss)))
    print("RESULT " + json.dumps({"pid": pid, "losses": losses}),
          flush=True)
""")


def test_worker_death_clean_error_and_restart_matches_oracle(tmp_path):
    """SURVEY §5.3 failure story, completed (round-3 verdict item 7):
    SIGKILL one rank mid-training; the SURVIVING rank's next collective
    must error within a bound (no hang — the reference's NCCL behavior
    is job death, restart-from-snapshot is the recovery story); a fresh
    2-process job restarted from the pre-crash checkpoint must continue
    exactly like the single-process oracle."""
    import time as _time

    port = _free_port()
    ckpt = str(tmp_path / "crash.ckpt")
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER_KILL, str(i), str(port),
             ckpt],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    # wait for rank 0 to report the checkpoint barrier passed
    t0 = _time.time()
    # a reader thread enforces the 180s bound even if rank 0 produces
    # NO output at all — a bare `for line in stdout` would block in
    # readline() forever and hang the test instead of failing
    # (advisor r04).  The pump OWNS the pipe until EOF (a later
    # communicate() reading the same file object from this thread
    # would race it), collecting every line; the post-mortem
    # diagnostics below read from the collected buffer.
    import queue as _queue
    import threading as _threading

    lines = _queue.Queue()
    all_lines = []

    def _pump():
        for line in procs[0].stdout:
            all_lines.append(line)
            lines.put(line)
        lines.put(None)

    pump_thread = _threading.Thread(target=_pump, daemon=True)
    pump_thread.start()
    while True:
        try:
            line = lines.get(timeout=max(0.1, 180 - (_time.time() - t0)))
        except _queue.Empty:
            line = None
        assert line is not None and _time.time() - t0 < 180,             "never reached CKPT_DONE"
        if "CKPT_DONE" in line:
            break
    _time.sleep(1.0)          # let both ranks get into steady stepping
    procs[1].kill()           # SIGKILL the victim mid-collective
    procs[1].wait(timeout=30)

    # the survivor must DIE within the bound, not hang.  Two clean
    # paths exist: (a) the in-flight collective raises (our except
    # prints SURVIVOR_ERROR, exit 23), or (b) jax.distributed's
    # coordination-service heartbeat detector notices the dead task
    # first and terminates the process with a fatal diagnostic naming
    # it ("tasks are unhealthy (stopped sending heartbeats)") — the
    # TPU-native rebuild of the reference's NCCL semantics, where a
    # dead rank kills the job and restart-from-snapshot is the
    # recovery story (SURVEY.md §5.3).
    procs[0].wait(timeout=120)
    pump_thread.join(timeout=30)   # pump exits at pipe EOF
    assert not pump_thread.is_alive(), \
        "stdout pump still draining after 30s — output incomplete"
    out_rest = "".join(all_lines)
    assert procs[0].returncode != 0, \
        f"survivor kept running after peer death:\n{out_rest[-2000:]}"
    assert ("SURVIVOR_ERROR" in out_rest
            or "unhealthy" in out_rest
            or "another task died" in out_rest), out_rest[-2000:]

    # restart a fresh 2-process job from the checkpoint
    port2 = _free_port()
    procs2 = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER_RESTART, str(i), str(port2),
             ckpt],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    outs = [p.communicate(timeout=240)[0] for p in procs2]
    for i, (p, out) in enumerate(zip(procs2, outs)):
        assert p.returncode == 0, f"restart worker {i} failed:\n{out[-3000:]}"
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT "):
                r = json.loads(line[len("RESULT "):])
                results[r["pid"]] = r
    assert set(results) == {0, 1}, results
    np.testing.assert_allclose(results[0]["losses"],
                               results[1]["losses"], rtol=1e-6)

    # oracle: single process, 4 devices — 2 steps, then continue 2 more
    # from the SAME checkpoint file the crashed job wrote
    ref = _oracle_continue_from_ckpt(ckpt)
    np.testing.assert_allclose(results[0]["losses"], ref,
                               rtol=1e-4, atol=1e-5)


def _oracle_continue_from_ckpt(ckpt):
    from singa_tpu import layer, model, opt, tensor
    from singa_tpu import device as device_mod
    from singa_tpu.parallel.communicator import Communicator
    from singa_tpu.parallel.dist_opt import DistOpt

    class Net(model.Model):
        def __init__(self):
            super().__init__()
            self.fc1 = layer.Linear(16)
            self.relu = layer.ReLU()
            self.fc2 = layer.Linear(4)
            self.loss_fn = layer.SoftMaxCrossEntropy()

        def forward(self, x):
            return self.fc2(self.relu(self.fc1(x)))

        def train_one_batch(self, x, y):
            out = self.forward(x)
            loss = self.loss_fn(out, y)
            self.optimizer(loss)
            return out, loss

    rng = np.random.RandomState(0)
    gx = rng.randn(16, 8).astype(np.float32)
    gy = rng.randint(0, 4, 16).astype(np.int32)
    device_mod.get_default_device().SetRandSeed(77)
    m = Net()
    m.set_optimizer(DistOpt(opt.SGD(lr=0.1),
                            communicator=Communicator(num_devices=4)))
    m.compile([tensor.from_numpy(gx)], is_train=True, use_graph=True)
    m.load_states(ckpt)
    losses = []
    for _ in range(2):
        _, loss = m(tensor.from_numpy(gx), tensor.from_numpy(gy))
        losses.append(float(tensor.to_numpy(loss)))
    return losses


_WORKER_RING = textwrap.dedent("""
    import json, os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")

    pid = int(sys.argv[1]); port = sys.argv[2]
    from singa_tpu.parallel.communicator import initialize_distributed
    initialize_distributed(f"127.0.0.1:{port}", num_processes=2,
                           process_id=pid)
    assert len(jax.devices()) == 4

    import numpy as np
    import jax.numpy as jnp
    import math
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from singa_tpu.parallel.ring_attention import ring_self_attention

    B, H, S, D = 1, 2, 32, 8
    rng = np.random.RandomState(0)  # same data on both processes
    q, k, v = (rng.randn(B, H, S, D).astype(np.float32)
               for _ in range(3))

    mesh = Mesh(np.asarray(jax.devices()), ("seq",))
    spec = P(None, None, "seq", None)

    def mk(arr):
        # global array from per-process local shards: the seq axis
        # spans BOTH processes' devices (true multi-host sharding)
        return jax.make_array_from_callback(
            arr.shape, NamedSharding(mesh, spec),
            lambda idx: arr[idx])

    qg, kg, vg = mk(q), mk(k), mk(v)
    f = jax.jit(jax.shard_map(
        lambda q_, k_, v_: ring_self_attention(
            q_, k_, v_, "seq", causal=True, use_flash=False),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False))
    o = f(qg, kg, vg)
    from jax.experimental import multihost_utils as mh
    o_full = np.asarray(mh.process_allgather(o, tiled=True))

    # dense causal oracle (both processes hold the full inputs)
    sc = np.einsum("bhsd,bhtd->bhst", q, k) / math.sqrt(D)
    cm = np.tril(np.ones((S, S), bool))
    sc = np.where(cm[None, None], sc, -1e30)
    p = np.exp(sc - sc.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhst,bhtd->bhsd", p, v)
    err = float(np.max(np.abs(o_full - ref)))
    print("RESULT " + json.dumps({"pid": pid, "max_err": err}),
          flush=True)
    assert err < 2e-4, err
""")


def test_ring_attention_spans_process_boundary():
    """SURVEY §5.7 multi-host: ring attention's ppermute ring crosses
    the PROCESS boundary (2 processes x 2 devices, seq sharded over the
    global 4-device mesh, K/V hops riding the cross-process Gloo
    transport) and matches the dense causal oracle."""
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER_RING, str(i), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    outs = [p.communicate(timeout=240)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"
    errs = [json.loads(line[len("RESULT "):])["max_err"]
            for out in outs for line in out.splitlines()
            if line.startswith("RESULT ")]
    assert len(errs) == 2 and all(e < 2e-4 for e in errs), errs


_WORKER_MULTISTEP = textwrap.dedent("""
    import json, os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import jax
    jax.config.update("jax_platforms", "cpu")

    pid = int(sys.argv[1]); port = sys.argv[2]
    from singa_tpu.parallel.communicator import initialize_distributed
    initialize_distributed(f"127.0.0.1:{port}", num_processes=2,
                           process_id=pid)

    import numpy as np
    from singa_tpu import layer, model, opt, tensor
    from singa_tpu.parallel.communicator import Communicator
    from singa_tpu.parallel.dist_opt import DistOpt

    class Net(model.Model):
        def __init__(self):
            super().__init__()
            self.fc1 = layer.Linear(16)
            self.relu = layer.ReLU()
            self.fc2 = layer.Linear(4)
            self.loss_fn = layer.SoftMaxCrossEntropy()

        def forward(self, x):
            return self.fc2(self.relu(self.fc1(x)))

        def train_one_batch(self, x, y):
            out = self.forward(x)
            loss = self.loss_fn(out, y)
            self.optimizer(loss)
            return out, loss

    K = 3
    rng = np.random.RandomState(0)
    gxs = rng.randn(K, 16, 8).astype(np.float32)
    gys = rng.randint(0, 4, (K, 16)).astype(np.int32)
    # local stacked shard: (K, 8, ...) rows of each step's global batch
    lxs = gxs[:, 8 * pid:8 * pid + 8]
    lys = gys[:, 8 * pid:8 * pid + 8]

    from singa_tpu import device as device_mod
    device_mod.get_default_device().SetRandSeed(0 if pid == 0 else 7)
    m = Net()
    m.set_optimizer(DistOpt(opt.SGD(lr=0.1),
                            communicator=Communicator()))
    m.compile([tensor.from_numpy(lxs[0])], is_train=True, use_graph=True)
    _, losses = m.train_n_batches(tensor.from_numpy(lxs),
                                  tensor.from_numpy(lys))
    hist = [float(v) for v in np.asarray(tensor.to_numpy(losses))]
    print("RESULT " + json.dumps({"pid": pid, "losses": hist}),
          flush=True)
""")


def test_two_process_train_n_batches_matches_single_process():
    """Round-5 multi-step dispatch across the PROCESS boundary: each
    host feeds its (K, local_batch, ...) stacked shard; the scan over
    the shard_map'd step must reproduce K single-process global steps
    (rank-0 broadcast still applies — process 1 starts misseeded)."""
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", _WORKER_MULTISTEP, str(i), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    outs = [p.communicate(timeout=240)[0] for p in procs]
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out[-3000:]}"
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("RESULT "):
                r = json.loads(line[len("RESULT "):])
                results[r["pid"]] = r
    assert set(results) == {0, 1}, results
    np.testing.assert_allclose(results[0]["losses"],
                               results[1]["losses"], rtol=1e-6)

    # single-process oracle: same K global batches, K separate steps
    import jax  # noqa: F401  (virtual 4-device mesh from conftest)

    from singa_tpu import layer, model, opt, tensor
    from singa_tpu import device as device_mod
    from singa_tpu.parallel.communicator import Communicator
    from singa_tpu.parallel.dist_opt import DistOpt

    class Net(model.Model):
        def __init__(self):
            super().__init__()
            self.fc1 = layer.Linear(16)
            self.relu = layer.ReLU()
            self.fc2 = layer.Linear(4)
            self.loss_fn = layer.SoftMaxCrossEntropy()

        def forward(self, x):
            return self.fc2(self.relu(self.fc1(x)))

        def train_one_batch(self, x, y):
            out = self.forward(x)
            loss = self.loss_fn(out, y)
            self.optimizer(loss)
            return out, loss

    device_mod.get_default_device().SetRandSeed(0)
    K = 3
    rng = np.random.RandomState(0)
    gxs = rng.randn(K, 16, 8).astype(np.float32)
    gys = rng.randint(0, 4, (K, 16)).astype(np.int32)
    m = Net()
    m.set_optimizer(DistOpt(opt.SGD(lr=0.1),
                            communicator=Communicator(num_devices=4)))
    m.compile([tensor.from_numpy(gxs[0])], is_train=True, use_graph=True)
    ref = []
    for i in range(K):
        _, loss = m(tensor.from_numpy(gxs[i]), tensor.from_numpy(gys[i]))
        ref.append(float(tensor.to_numpy(loss)))
    np.testing.assert_allclose(results[0]["losses"], ref, rtol=1e-4,
                               atol=1e-5)
