"""Serve-engine failure semantics + supervised recovery: a raising
decode fails every request TYPED instead of wedging, the supervisor
rebuilds the engine and requeues never-started requests with
token-stream parity against an uninterrupted run, the restart budget
bounds flapping, and SLO-pressure load shedding drops the
lowest-priority queued work first.

Deterministic on CPU: faults come from the seeded injection registry
and scheduling tests run on a fake clock."""

import numpy as np
import pytest

from singa_tpu import tensor
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from singa_tpu.observe.health import SLO, health_report
from singa_tpu.observe.registry import registry
from singa_tpu.resilience import FailAfterN, FailRate, faults
from singa_tpu.serve import (EngineFailedError, EngineSupervisor,
                             FIFOScheduler, GenerationRequest,
                             LoadShedError, QueueFullError,
                             RestartBudgetExceededError)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def model():
    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    return m


_PROMPTS = [np.arange(9) % 256, (np.arange(4) + 3) % 256,
            np.asarray([5, 1, 200]), (np.arange(7) + 40) % 256]
_NEWS = [6, 3, 5, 4]


def _counter(name, **labels):
    snap = registry().snapshot()["counters"]
    key = name
    if labels:
        key += "{" + ",".join(f"{k}={v}"
                              for k, v in sorted(labels.items())) + "}"
    return snap.get(key, 0)


# ---------------------------------------------------------------------------
# typed engine failure (no wedging, no dangling handles)
# ---------------------------------------------------------------------------

def test_decode_fault_fails_all_requests_typed(model):
    """One raising decode step: in-flight requests reject with
    started=True, queued ones with started=False, the engine marks
    itself failed, and close() still releases its resources."""
    eng = model.serve(max_slots=2)
    hs = [eng.submit(GenerationRequest(p, max_new_tokens=n))
          for p, n in zip(_PROMPTS[:3], _NEWS[:3])]
    eng.step()  # admit two rows (third stays queued)
    faults.inject("serve.decode_step", FailAfterN(0, times=1))
    with pytest.raises(EngineFailedError):
        eng.step()
    faults.clear()
    assert not eng.pending  # nothing wedged, nothing dangling
    started = []
    for h in hs:
        assert h.done()
        with pytest.raises(EngineFailedError) as ei:
            h.result()
        assert ei.value.request_id == h.request.request_id
        started.append(ei.value.started)
    assert started == [True, True, False]
    # failed engine: step/submit raise typed, close still works
    with pytest.raises(EngineFailedError):
        eng.step()
    with pytest.raises(EngineFailedError):
        eng.submit(GenerationRequest(_PROMPTS[0]))
    eng.close()
    assert _counter("resilience.engine_failures") >= 1


# ---------------------------------------------------------------------------
# supervised recovery
# ---------------------------------------------------------------------------

def test_supervisor_restart_requeue_parity(model):
    """Mid-stream injected fault + restart: requeued (never-started)
    requests complete with token streams identical to an uninterrupted
    run; in-flight ones fail typed; restarts match injected faults."""
    base = [np.asarray(model.generate(p, max_new_tokens=n,
                                      temperature=0.0))
            for p, n in zip(_PROMPTS, _NEWS)]
    restarts0 = _counter("resilience.engine_restarts")

    sup = EngineSupervisor(model, max_slots=2, restart_budget=2)
    hs = [sup.submit(GenerationRequest(p, max_new_tokens=n,
                                       temperature=0.0))
          for p, n in zip(_PROMPTS, _NEWS)]
    faults.inject("serve.decode_step", FailAfterN(2, times=1))
    sup.run_until_complete(max_steps=500)
    faults.clear()

    completed, failed = [], []
    for i, h in enumerate(hs):
        assert h.done(), f"handle {i} left dangling"
        try:
            toks = h.result().tokens
            np.testing.assert_array_equal(toks, base[i])
            completed.append(i)
        except EngineFailedError as e:
            assert e.started is True  # only in-flight work fails
            failed.append(i)
    assert completed and failed  # the fault actually bit mid-stream
    assert sup.restarts == 1
    assert _counter("resilience.engine_restarts") == restarts0 + 1
    report = health_report()
    assert report["resilience"]["engine_restarts"] >= restarts0 + 1
    sup.close()


def test_supervisor_restart_budget_exhausts_typed(model):
    """An engine that fails on EVERY decode burns the budget; every
    outstanding handle resolves typed and the supervisor refuses new
    work — zero wedged, zero lost."""
    sup = EngineSupervisor(model, max_slots=2, restart_budget=1)
    hs = [sup.submit(GenerationRequest(p, max_new_tokens=n))
          for p, n in zip(_PROMPTS, _NEWS)]
    faults.inject("serve.decode_step", FailRate(1.0, seed=0))
    with pytest.raises(RestartBudgetExceededError):
        sup.run_until_complete(max_steps=500)
    faults.clear()
    assert sup.restarts == 2  # budget 1 allowed, the 2nd death killed it
    for h in hs:
        assert h.done()
        with pytest.raises(EngineFailedError):
            h.result()
    with pytest.raises(RestartBudgetExceededError):
        sup.submit(GenerationRequest(_PROMPTS[0]))
    assert not sup.pending


def test_restart_budget_resets_after_healthy_uptime(model):
    """``budget_reset_after_s``: a long-lived replica is only
    condemned by crash-LOOPING.  Failures separated by more healthy
    uptime than the window forgive the spent budget; failures inside
    the window still exhaust it (and the default — None — keeps the
    original consecutive-lifetime accounting)."""
    class FakeClock:
        def __init__(self):
            self.t = 0.0

        def __call__(self):
            return self.t

        def advance(self, dt):
            self.t += dt

    clk = FakeClock()
    sup = EngineSupervisor(model, max_slots=2, restart_budget=1,
                           budget_reset_after_s=10.0, clock=clk)

    def crash_once_and_drain():
        h = sup.submit(GenerationRequest(_PROMPTS[0], max_new_tokens=3))
        faults.inject("serve.decode_step", FailAfterN(0, times=1))
        sup.run_until_complete(max_steps=200)
        faults.clear()
        assert h.done()  # typed (started) or requeued-complete

    # three separate incidents, each past the healthy-uptime window:
    # budget 1 would die on the second without the reset
    for _ in range(3):
        crash_once_and_drain()
        assert sup.restarts == 1  # reset keeps it at one per incident
        clk.advance(11.0)
    # now two failures INSIDE the window: that IS a crash loop
    crash_once_and_drain()
    h = sup.submit(GenerationRequest(_PROMPTS[1], max_new_tokens=3))
    faults.inject("serve.decode_step", FailAfterN(0, times=1))
    with pytest.raises(RestartBudgetExceededError):
        sup.run_until_complete(max_steps=200)
    faults.clear()
    assert h.done()
    with pytest.raises(EngineFailedError):
        h.result()

    # default (None): ancient restarts still count — original contract
    clk2 = FakeClock()
    sup2 = EngineSupervisor(model, max_slots=2, restart_budget=1,
                            clock=clk2)
    for i in range(2):
        hi = sup2.submit(GenerationRequest(_PROMPTS[0],
                                           max_new_tokens=3))
        faults.inject("serve.decode_step", FailAfterN(0, times=1))
        if i == 0:
            sup2.run_until_complete(max_steps=200)
        else:
            with pytest.raises(RestartBudgetExceededError):
                sup2.run_until_complete(max_steps=200)
        faults.clear()
        clk2.advance(100.0)  # uptime is irrelevant without the window
        assert hi.done()
    with pytest.raises(ValueError, match="budget_reset_after_s"):
        EngineSupervisor(model, budget_reset_after_s=0)


def test_supervisor_clean_run_has_no_restarts(model):
    base = [np.asarray(model.generate(p, max_new_tokens=n,
                                      temperature=0.0))
            for p, n in zip(_PROMPTS[:2], _NEWS[:2])]
    with EngineSupervisor(model, max_slots=2) as sup:
        hs = [sup.submit(GenerationRequest(p, max_new_tokens=n,
                                           temperature=0.0))
              for p, n in zip(_PROMPTS[:2], _NEWS[:2])]
        sup.run_until_complete(max_steps=200)
        assert sup.restarts == 0
        for h, b in zip(hs, base):
            np.testing.assert_array_equal(h.result().tokens, b)


def test_requeued_streaming_has_no_duplicate_tokens(model):
    """A requeued request's on_token stream must match a clean run —
    queued work never streamed, so the restart emits each token once."""
    streams = {}

    def on_token(req, tok):
        streams.setdefault(req.request_id, []).append(tok)

    sup = EngineSupervisor(model, max_slots=1, restart_budget=1)
    reqs = [GenerationRequest(p, max_new_tokens=n, temperature=0.0,
                              on_token=on_token)
            for p, n in zip(_PROMPTS[:3], _NEWS[:3])]
    hs = [sup.submit(r) for r in reqs]
    faults.inject("serve.decode_step", FailAfterN(1, times=1))
    sup.run_until_complete(max_steps=500)
    faults.clear()
    for r, h in zip(reqs, hs):
        if h._error is not None:
            continue  # in-flight at fault: typed failure, no requeue
        toks = h.result().tokens
        # streamed tokens == continuation exactly once each
        np.testing.assert_array_equal(
            np.asarray(streams[r.request_id]),
            toks[len(r.prompt_ids):])
    sup.close()


def test_raising_on_token_callback_fails_only_that_request(model):
    """One client's broken streaming callback must not kill the other
    tenants' requests (or burn a supervisor restart)."""
    def bad_cb(req, tok):
        raise KeyError("client bug")

    eng = model.serve(max_slots=2)
    h_bad = eng.submit(GenerationRequest(_PROMPTS[0], max_new_tokens=4,
                                         on_token=bad_cb))
    h_ok = eng.submit(GenerationRequest(_PROMPTS[1], max_new_tokens=3,
                                        temperature=0.0))
    eng.run_until_complete(max_steps=100)
    with pytest.raises(KeyError):
        h_bad.result()
    want = np.asarray(model.generate(_PROMPTS[1], max_new_tokens=3,
                                     temperature=0.0))
    np.testing.assert_array_equal(h_ok.result().tokens, want)
    assert not eng._failed  # engine healthy, no restart consumed
    eng.close()


# ---------------------------------------------------------------------------
# load shedding (satellite + SLO-pressure admission mode)
# ---------------------------------------------------------------------------

def test_queue_full_error_names_depth_and_max():
    sched = FIFOScheduler(max_queue_depth=2)
    sched.enqueue(GenerationRequest(np.asarray([1])))
    sched.enqueue(GenerationRequest(np.asarray([2])))
    with pytest.raises(QueueFullError) as ei:
        sched.enqueue(GenerationRequest(np.asarray([3])))
    assert "depth 2" in str(ei.value)
    assert "max 2" in str(ei.value)


def test_scheduler_shed_lowest_priority_and_counter():
    before = _counter("serve.shed_requests", reason="test")
    sched = FIFOScheduler()
    lo = GenerationRequest(np.asarray([1]), priority=0)
    hi = GenerationRequest(np.asarray([2]), priority=5)
    lo2 = GenerationRequest(np.asarray([3]), priority=0)
    for r in (lo, hi, lo2):
        sched.enqueue(r)
    victim = sched.shed_lowest("test")
    assert victim is lo2  # lowest priority, newest arrival sheds first
    assert sched.queue_depth == 2
    assert _counter("serve.shed_requests", reason="test") == before + 1
    # below_priority guard: nothing ranks below 0
    assert sched.shed_lowest("test", below_priority=0) is None
    assert sched.shed_lowest("test", below_priority=99) is lo


def test_slo_pressure_sheds_lowest_priority_queued(model):
    """Admission under SLO queue pressure: a high-priority arrival
    evicts the lowest-priority queued request (typed LoadShedError);
    a low-priority arrival is refused itself."""
    slo = SLO(queue_depth_max=2)
    sup = EngineSupervisor(model, max_slots=1, shed_on_slo_pressure=True,
                           slo=slo)
    # fill: one in flight + two queued (at queue_depth_max)
    hs = [sup.submit(GenerationRequest(_PROMPTS[0], max_new_tokens=2,
                                       priority=0))]
    sup.step()  # admit it into the single slot
    hs += [sup.submit(GenerationRequest(p, max_new_tokens=2, priority=0))
           for p in _PROMPTS[1:3]]  # queue depth now 2 == max
    shed_before = _counter("serve.shed_requests", reason="slo_pressure")
    h_hi = sup.submit(GenerationRequest(_PROMPTS[3], max_new_tokens=2,
                                        priority=9))
    assert _counter("serve.shed_requests",
                    reason="slo_pressure") == shed_before + 1
    # one of the queued low-priority handles was shed typed
    shed = [h for h in hs if h.done()]
    assert len(shed) == 1
    with pytest.raises(LoadShedError):
        shed[0].result()
    # a second low-priority arrival is refused (it IS the lowest)
    with pytest.raises(LoadShedError):
        sup.submit(GenerationRequest(_PROMPTS[0], max_new_tokens=2,
                                     priority=0))
    assert _counter("serve.shed_requests", reason="slo_admission") >= 1
    sup.run_until_complete(max_steps=300)
    assert h_hi.result().finish_reason == "length"
    # health report aggregates the shed reasons
    shed_section = health_report()["resilience"]["shed_requests"]
    assert shed_section.get("slo_pressure", 0) >= 1
    sup.close()
