"""Continuous-batching serving engine (singa_tpu/serve): token parity
against the offline generate paths, iteration-level scheduling
semantics (retire + same-step backfill, prefill/decode interleave),
admission control (queue depth, deadlines), and the stats schema.

All deterministic on CPU: token streams come from fixed seeds and the
scheduling tests run on a fake clock."""

import numpy as np
import pytest

from singa_tpu import tensor
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from singa_tpu.serve import (DeadlineExceededError, FIFOScheduler,
                             GenerationRequest, QueueFullError)


def _model(**kw):
    kw.setdefault("dropout", 0.0)
    cfg = GPT2Config.tiny(**kw)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    return m


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


_PROMPTS = [np.arange(9) % 256,
            (np.arange(4) + 3) % 256,
            (np.arange(13) * 2 + 1) % 256,
            np.asarray([5, 1, 200]),
            (np.arange(7) + 40) % 256]


def test_engine_matches_single_prompt_generate():
    """Ragged arrivals through the slot pool produce per-request token
    streams identical to the same prompts run one-at-a-time through
    generate — the core exactness contract (acceptance criterion)."""
    m = _model()
    news = [6, 3, 9, 1, 5]
    eng = m.serve(max_slots=2)
    handles = []
    arrivals = {0: [0, 1], 2: [2, 3], 4: [4]}  # ragged arrival steps
    submitted = 0
    for step in range(200):
        for i in arrivals.get(step, []):
            handles.append(eng.submit(GenerationRequest(
                _PROMPTS[i], max_new_tokens=news[i])))
            submitted += 1
        if submitted == len(_PROMPTS) and not eng.pending:
            break
        eng.step()
    assert not eng.pending
    for h, p, n in zip(handles, _PROMPTS, news):
        res = h.result()
        assert res.finish_reason == "length"
        want = m.generate(np.asarray(p), max_new_tokens=n,
                          temperature=0)
        np.testing.assert_array_equal(res.tokens, want)


def test_sampled_request_matches_seeded_generate():
    """A temperature request with an explicit seed reproduces the
    offline sampled stream: the engine splits the request's key chain
    exactly as generate does."""
    m = _model()
    seed_rs = 11
    s = int(np.random.RandomState(seed_rs).randint(0, 2 ** 31 - 1))
    eng = m.serve(max_slots=2)
    h = eng.submit(GenerationRequest(_PROMPTS[0], max_new_tokens=8,
                                     temperature=0.8, seed=s))
    eng.run_until_complete(max_steps=100)
    want = m.generate(np.asarray(_PROMPTS[0]), max_new_tokens=8,
                      temperature=0.8,
                      rng=np.random.RandomState(seed_rs))
    np.testing.assert_array_equal(h.result().tokens, want)


def test_top_p_engine_matches_generate():
    """Engine-level nucleus filtering matches the offline top-p path
    for a seeded request (mixed with a greedy request in the same
    pool — one executable serves both)."""
    m = _model()
    s = int(np.random.RandomState(3).randint(0, 2 ** 31 - 1))
    eng = m.serve(max_slots=2, top_p=0.9)
    h1 = eng.submit(GenerationRequest(_PROMPTS[1], max_new_tokens=7,
                                      temperature=1.0, seed=s))
    h2 = eng.submit(GenerationRequest(_PROMPTS[2], max_new_tokens=4))
    eng.run_until_complete(max_steps=100)
    from singa_tpu.models import gpt2_decode
    want1 = gpt2_decode.generate(
        m, np.asarray(_PROMPTS[1]), max_new_tokens=7, temperature=1.0,
        top_p=0.9, rng=np.random.RandomState(3))
    np.testing.assert_array_equal(h1.result().tokens, want1)
    want2 = m.generate(np.asarray(_PROMPTS[2]), max_new_tokens=4,
                       temperature=0)
    np.testing.assert_array_equal(h2.result().tokens, want2)


def test_backfill_lands_on_the_retirement_step():
    """When a row hits its token budget, the queued request enters the
    freed slot in the SAME engine step (retire -> backfill), not a
    step later — the iteration-level scheduling contract."""
    m = _model()
    eng = m.serve(max_slots=2)
    ha = eng.submit(GenerationRequest(_PROMPTS[0], max_new_tokens=2))
    hb = eng.submit(GenerationRequest(_PROMPTS[1], max_new_tokens=6))
    hc = eng.submit(GenerationRequest(_PROMPTS[2], max_new_tokens=3))
    eng.run_until_complete(max_steps=100)
    ra, rc = ha.result(), hc.result()
    # A emits token 1 at admission (step 0) and token 2 on the next
    # decode; C must be admitted within that same step
    assert rc.admitted_step == ra.finished_step
    # and every stream still matches the offline oracle
    for h, p, n in ((ha, _PROMPTS[0], 2), (hb, _PROMPTS[1], 6),
                    (hc, _PROMPTS[2], 3)):
        want = m.generate(np.asarray(p), max_new_tokens=n,
                          temperature=0)
        np.testing.assert_array_equal(h.result().tokens, want)


def test_prefill_interleave_caps_admissions_per_step():
    """max_prefills_per_step bounds admissions per scheduling pass so
    an arrival burst cannot starve the decode loop."""
    m = _model()
    eng = m.serve(max_slots=4,
                  scheduler=FIFOScheduler(max_prefills_per_step=1))
    hs = [eng.submit(GenerationRequest(_PROMPTS[i], max_new_tokens=4))
          for i in range(3)]
    eng.run_until_complete(max_steps=100)
    steps = [h.result().admitted_step for h in hs]
    assert steps == sorted(steps) and len(set(steps)) == 3, steps


def test_deadline_expired_requests_rejected_distinctly():
    """A request whose deadline passes while queued is rejected with
    DeadlineExceededError (distinct from QueueFullError); rows already
    in a slot are unaffected."""
    m = _model()
    clock = _FakeClock()
    eng = m.serve(max_slots=1, clock=clock)
    h1 = eng.submit(GenerationRequest(_PROMPTS[0], max_new_tokens=6))
    h2 = eng.submit(GenerationRequest(_PROMPTS[1], max_new_tokens=2,
                                      deadline=5.0))
    eng.step()          # admits h1 (single slot); h2 queued
    clock.advance(10.0)  # h2's deadline passes while queued
    eng.run_until_complete(max_steps=100)
    assert h1.result().finish_reason == "length"
    assert h2.done()
    with pytest.raises(DeadlineExceededError):
        h2.result()
    snap = eng.stats.snapshot()
    assert snap["requests"]["rejected_deadline"] == 1
    assert snap["requests"]["completed"] == 1


def test_queue_depth_rejection_is_synchronous():
    m = _model()
    eng = m.serve(max_slots=1,
                  scheduler=FIFOScheduler(max_queue_depth=2))
    eng.submit(GenerationRequest(_PROMPTS[0], max_new_tokens=2))
    eng.submit(GenerationRequest(_PROMPTS[1], max_new_tokens=2))
    with pytest.raises(QueueFullError):
        eng.submit(GenerationRequest(_PROMPTS[2], max_new_tokens=2))
    assert eng.stats.snapshot()["requests"]["rejected_queue_full"] == 1


def test_streaming_callback_sees_every_token_in_order():
    m = _model()
    streamed = []
    eng = m.serve(max_slots=1)
    h = eng.submit(GenerationRequest(
        _PROMPTS[0], max_new_tokens=5,
        on_token=lambda req, tok: streamed.append(tok)))
    eng.run_until_complete(max_steps=50)
    res = h.result()
    np.testing.assert_array_equal(
        np.asarray(streamed, np.int32),
        res.tokens[len(_PROMPTS[0]):])


def test_stats_schema_stable():
    """BENCH_SERVE.json and dashboards key on this schema; extend by
    adding keys, never renaming."""
    m = _model()
    eng = m.serve(max_slots=2)
    eng.submit(GenerationRequest(_PROMPTS[0], max_new_tokens=3))
    eng.submit(GenerationRequest(_PROMPTS[1], max_new_tokens=1))
    eng.run_until_complete(max_steps=50)
    snap = eng.stats.snapshot()
    assert set(snap) == {"requests", "throughput", "latency", "queue",
                         "slots", "slo", "prefix", "spec", "paged",
                         "tp", "ep", "pp"}
    # no prefix cache / draft model / paged arena / tp-ep-pp mesh
    # configured: present but None
    assert snap["prefix"] is None
    assert snap["spec"] is None
    assert snap["paged"] is None
    assert snap["tp"] is None
    assert snap["ep"] is None
    assert snap["pp"] is None
    assert set(snap["requests"]) == {
        "submitted", "completed", "rejected_deadline",
        "rejected_queue_full"}
    assert set(snap["throughput"]) == {
        "tokens_out", "wall_s", "uptime_s", "tokens_per_s",
        "goodput_tokens_per_s", "prefills", "decode_steps"}
    assert set(snap["latency"]) == {"ttft", "tpot", "tpot_ewma_s"}
    for series in (snap["latency"]["ttft"], snap["latency"]["tpot"]):
        assert set(series) == {"count", "mean", "p50", "p99", "max"}
    # the router's headroom signal: set once a multi-token retire exists
    assert snap["latency"]["tpot_ewma_s"] == pytest.approx(
        snap["latency"]["tpot"]["mean"])
    assert set(snap["queue"]) == {"mean_depth", "max_depth"}
    assert set(snap["slots"]) == {"max_slots", "occupancy_mean"}
    assert snap["requests"]["completed"] == 2
    assert snap["throughput"]["tokens_out"] == 4
    assert snap["latency"]["ttft"]["count"] == 2
    # the 1-token request contributes no TPOT sample
    assert snap["latency"]["tpot"]["count"] == 1
    assert 0.0 < snap["slots"]["occupancy_mean"] <= 1.0


def test_engine_validates_requests_and_models():
    m = _model()
    eng = m.serve(max_slots=1)
    with pytest.raises(ValueError, match="max_len"):
        eng.submit(GenerationRequest(
            np.zeros(120, np.int32), max_new_tokens=20))
    with pytest.raises(ValueError, match="max_new_tokens"):
        GenerationRequest(_PROMPTS[0], max_new_tokens=0)
    with pytest.raises(ValueError, match="prompt_ids"):
        GenerationRequest(np.zeros(0, np.int32))
    mw = _model(attn_window=8)
    with pytest.raises(NotImplementedError, match="sliding-window"):
        mw.serve()
    with pytest.raises(ValueError, match="max_queue_depth"):
        FIFOScheduler(max_queue_depth=0)


def test_duplicate_request_id_rejected_and_handles_evicted():
    """An in-flight duplicate request_id would orphan the earlier
    handle (the id routes completion) — rejected at submit.  Resolved
    requests are evicted from the engine's routing table, so the id
    becomes reusable and a long-lived engine stays memory-flat."""
    m = _model()
    eng = m.serve(max_slots=1)
    eng.submit(GenerationRequest(_PROMPTS[0], max_new_tokens=2,
                                 request_id="trace-1"))
    with pytest.raises(ValueError, match="in flight"):
        eng.submit(GenerationRequest(_PROMPTS[1], max_new_tokens=2,
                                     request_id="trace-1"))
    eng.run_until_complete(max_steps=50)
    assert len(eng._handles) == 0
    # id reusable once its predecessor resolved
    h = eng.submit(GenerationRequest(_PROMPTS[1], max_new_tokens=2,
                                     request_id="trace-1"))
    eng.run_until_complete(max_steps=50)
    assert h.result().finish_reason == "length"
    assert len(eng._handles) == 0


def test_gqa_model_serves_exactly():
    """GQA keeps its narrow H_kv arena in the pool and still matches
    the offline oracle token for token."""
    m = _model(n_kv_head=2)
    eng = m.serve(max_slots=2)
    hs = [eng.submit(GenerationRequest(p, max_new_tokens=4))
          for p in _PROMPTS[:3]]
    eng.run_until_complete(max_steps=100)
    for h, p in zip(hs, _PROMPTS):
        want = m.generate(np.asarray(p), max_new_tokens=4,
                          temperature=0)
        np.testing.assert_array_equal(h.result().tokens, want)
