"""Rematerialization (jax.checkpoint): remat'd models train identically
to their non-remat twins — memory is traded for FLOPs with zero
numerical drift (checkpointed VJPs recompute the same ops)."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from singa_tpu import opt, tensor
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from singa_tpu.parallel.pipeline import PipelinedTransformer

B, S = 4, 16


@pytest.mark.slow
def test_gpt2_remat_matches_plain():
    rng = np.random.RandomState(0)
    base = GPT2LMHead(GPT2Config.tiny(dropout=0.0))
    remat = GPT2LMHead(GPT2Config.tiny(dropout=0.0, remat=True))
    ids = rng.randint(0, base.cfg.vocab_size, (B, S)).astype(np.int32)
    labels = np.roll(ids, -1, 1).astype(np.int32)
    x0 = tensor.from_numpy(ids)
    for m in (base, remat):
        m.set_optimizer(opt.SGD(lr=0.1))
        m.compile([x0], is_train=True, use_graph=True)
    remat.set_states({k: tensor.to_numpy(v)
                      for k, v in base.get_states().items()})
    la, lb = [], []
    for _ in range(3):
        _, l1 = base(tensor.from_numpy(ids), tensor.from_numpy(labels))
        _, l2 = remat(tensor.from_numpy(ids), tensor.from_numpy(labels))
        la.append(float(tensor.to_numpy(l1)))
        lb.append(float(tensor.to_numpy(l2)))
    np.testing.assert_allclose(lb, la, rtol=1e-5)


@pytest.mark.slow
def test_pipeline_remat_matches_plain():
    from test_pipeline import PipeLM, _batch, _compile

    plain = _compile(PipeLM(plan=None))
    rem = PipeLM(plan=None)
    # swap in a remat trunk BEFORE compile (same class name, so state
    # names line up for the copy below)
    rem.trunk = PipelinedTransformer(4, 2, 32, plan=None, remat=True)
    _compile(rem)
    rem.set_states({k: tensor.to_numpy(v)
                    for k, v in plain.get_states().items()})
    assert {k for k in plain.get_states()} == \
        {k for k in rem.get_states()}
    for i in range(2):
        ids, labels = _batch(seed=i)
        _, lp = plain(tensor.from_numpy(ids), tensor.from_numpy(labels))
        _, lr = rem(tensor.from_numpy(ids), tensor.from_numpy(labels))
        np.testing.assert_allclose(float(tensor.to_numpy(lr)),
                                   float(tensor.to_numpy(lp)), rtol=1e-5)


@pytest.mark.slow
def test_moe_remat_matches_plain():
    from test_moe import MoEModel, _data
    from singa_tpu.parallel.moe import MoEFFN

    plain = MoEModel(plan=None)
    rem = MoEModel(plan=None)
    rem.moe = MoEFFN(4, 32, plan=None, top_k=2, capacity_factor=4.0,
                     remat=True)
    x, y = _data()
    for m in (plain, rem):
        m.set_optimizer(opt.SGD(lr=0.1))
        m.compile([tensor.from_numpy(x)], is_train=True, use_graph=True)
    rem.set_states({k: tensor.to_numpy(v)
                    for k, v in plain.get_states().items()})
    for i in range(2):
        x, y = _data(seed=i)
        _, lp = plain(tensor.from_numpy(x), tensor.from_numpy(y))
        _, lr = rem(tensor.from_numpy(x), tensor.from_numpy(y))
        np.testing.assert_allclose(float(tensor.to_numpy(lr)),
                                   float(tensor.to_numpy(lp)), rtol=1e-5)
