"""Tensor semantics vs numpy goldens (reference test strategy: SURVEY.md §4,
test/python/test_tensor.py, unverified)."""

import numpy as np
import pytest

from singa_tpu import tensor
from singa_tpu import device as device_module
from singa_tpu.tensor import Tensor


@pytest.fixture
def dev():
    return device_module.get_default_device()


def test_create_zeros(dev):
    t = Tensor((3, 4), device=dev)
    assert t.shape == (3, 4)
    assert t.size() == 12
    assert t.ndim() == 2
    np.testing.assert_array_equal(tensor.to_numpy(t), np.zeros((3, 4), np.float32))


def test_from_to_numpy_roundtrip(dev):
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    t = tensor.from_numpy(x, dev)
    np.testing.assert_array_equal(tensor.to_numpy(t), x)


def test_float64_input_downcast(dev):
    x = np.ones((2, 2), dtype=np.float64)
    t = tensor.from_numpy(x, dev)
    assert np.dtype(t.dtype) == np.float32


def test_operators(dev):
    a = tensor.from_numpy(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32), dev)
    b = tensor.from_numpy(np.array([[5.0, 6.0], [7.0, 8.0]], np.float32), dev)
    np.testing.assert_allclose(tensor.to_numpy(a + b), [[6, 8], [10, 12]])
    np.testing.assert_allclose(tensor.to_numpy(a - b), [[-4, -4], [-4, -4]])
    np.testing.assert_allclose(tensor.to_numpy(a * b), [[5, 12], [21, 32]])
    np.testing.assert_allclose(tensor.to_numpy(b / a), [[5, 3], [7 / 3, 2]], rtol=1e-6)
    np.testing.assert_allclose(tensor.to_numpy(a + 1.0), [[2, 3], [4, 5]])
    np.testing.assert_allclose(tensor.to_numpy(2.0 * a), [[2, 4], [6, 8]])
    np.testing.assert_allclose(tensor.to_numpy(-a), [[-1, -2], [-3, -4]])


def test_inplace_rebinding(dev):
    a = tensor.from_numpy(np.ones((2, 2), np.float32), dev)
    a += 2.0
    np.testing.assert_allclose(tensor.to_numpy(a), 3 * np.ones((2, 2)))
    a *= 2.0
    np.testing.assert_allclose(tensor.to_numpy(a), 6 * np.ones((2, 2)))


def test_comparison_returns_float_mask(dev):
    a = tensor.from_numpy(np.array([1.0, 5.0, 3.0], np.float32), dev)
    m = a > 2.0
    assert m.data.dtype == np.float32
    np.testing.assert_array_equal(tensor.to_numpy(m), [0.0, 1.0, 1.0])


def test_matmul_and_mult(dev):
    a = np.random.RandomState(0).randn(4, 5).astype(np.float32)
    b = np.random.RandomState(1).randn(5, 3).astype(np.float32)
    ta, tb = tensor.from_numpy(a, dev), tensor.from_numpy(b, dev)
    np.testing.assert_allclose(tensor.to_numpy(tensor.mult(ta, tb)), a @ b, rtol=1e-5)
    c = Tensor((4, 3), device=dev)
    c.set_value(1.0)
    out = tensor.mult(ta, tb, C=c, alpha=2.0, beta=0.5)
    np.testing.assert_allclose(tensor.to_numpy(out), 2 * (a @ b) + 0.5, rtol=1e-5)


def test_unary_and_reductions(dev):
    x = np.random.RandomState(2).rand(3, 4).astype(np.float32) + 0.1
    t = tensor.from_numpy(x, dev)
    np.testing.assert_allclose(tensor.to_numpy(tensor.exp(t)), np.exp(x), rtol=1e-5)
    np.testing.assert_allclose(tensor.to_numpy(tensor.log(t)), np.log(x), rtol=1e-5)
    np.testing.assert_allclose(tensor.to_numpy(tensor.sqrt(t)), np.sqrt(x), rtol=1e-5)
    np.testing.assert_allclose(tensor.to_numpy(tensor.tanh(t)), np.tanh(x), rtol=1e-5)
    np.testing.assert_allclose(tensor.to_numpy(tensor.sum(t)), x.sum(), rtol=1e-5)
    np.testing.assert_allclose(tensor.to_numpy(tensor.sum(t, axis=0)), x.sum(0), rtol=1e-5)
    np.testing.assert_allclose(tensor.to_numpy(tensor.mean(t, axis=1)), x.mean(1), rtol=1e-5)
    np.testing.assert_allclose(
        tensor.to_numpy(tensor.softmax(t)),
        np.exp(x) / np.exp(x).sum(-1, keepdims=True),
        rtol=1e-5,
    )


def test_axpy(dev):
    x = tensor.from_numpy(np.ones((3,), np.float32), dev)
    y = tensor.from_numpy(np.full((3,), 2.0, np.float32), dev)
    tensor.axpy(0.5, x, y)
    np.testing.assert_allclose(tensor.to_numpy(y), [2.5, 2.5, 2.5])


def test_row_column_ops(dev):
    M = tensor.from_numpy(np.ones((2, 3), np.float32), dev)
    v = tensor.from_numpy(np.array([1.0, 2.0], np.float32), dev)
    tensor.add_column(v, M)
    np.testing.assert_allclose(tensor.to_numpy(M), [[2, 2, 2], [3, 3, 3]])
    w = tensor.from_numpy(np.array([1.0, 2.0, 3.0], np.float32), dev)
    tensor.mult_row(w, M)
    np.testing.assert_allclose(tensor.to_numpy(M), [[2, 4, 6], [3, 6, 9]])
    np.testing.assert_allclose(tensor.to_numpy(tensor.sum_rows(M)), [5, 10, 15])
    np.testing.assert_allclose(tensor.to_numpy(tensor.sum_columns(M)), [12, 18])


def test_reshape_transpose(dev):
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    t = tensor.from_numpy(x, dev)
    np.testing.assert_array_equal(tensor.to_numpy(t.reshape((3, 2))), x.reshape(3, 2))
    np.testing.assert_array_equal(tensor.to_numpy(t.T), x.T)
    np.testing.assert_array_equal(tensor.to_numpy(tensor.transpose(t)), x.T)


def test_random_fills(dev):
    t = Tensor((1000,), device=dev)
    t.gaussian(1.0, 2.0)
    arr = tensor.to_numpy(t)
    assert np.abs(arr.mean() - 1.0) < 0.3
    assert np.abs(arr.std() - 2.0) < 0.3
    t.uniform(-1.0, 1.0)
    arr = tensor.to_numpy(t)
    assert arr.min() >= -1.0 and arr.max() <= 1.0
    t.bernoulli(0.3)
    arr = tensor.to_numpy(t)
    assert set(np.unique(arr)).issubset({0.0, 1.0})
    assert np.abs(arr.mean() - 0.3) < 0.1


def test_rng_reproducible(dev):
    dev.SetRandSeed(42)
    a = Tensor((16,), device=dev).gaussian(0, 1)
    dev.SetRandSeed(42)
    b = Tensor((16,), device=dev).gaussian(0, 1)
    np.testing.assert_array_equal(tensor.to_numpy(a), tensor.to_numpy(b))


def test_copy_semantics(dev):
    a = tensor.from_numpy(np.ones((2, 2), np.float32), dev)
    b = a.clone()
    a += 1.0
    np.testing.assert_allclose(tensor.to_numpy(b), np.ones((2, 2)))  # clone detached
    c = Tensor((2, 2), device=dev)
    c.copy_data(a)
    np.testing.assert_allclose(tensor.to_numpy(c), 2 * np.ones((2, 2)))


def test_set_value_and_norms(dev):
    t = Tensor((4,), device=dev)
    t.SetValue(3.0)
    np.testing.assert_allclose(tensor.to_numpy(t), [3, 3, 3, 3])
    assert abs(t.l1() - 3.0) < 1e-6
    assert abs(t.l2() - 3.0) < 1e-6


def test_concat_stack(dev):
    a = tensor.from_numpy(np.ones((2, 2), np.float32), dev)
    b = tensor.from_numpy(np.zeros((2, 2), np.float32), dev)
    assert tensor.concatenate([a, b], axis=0).shape == (4, 2)
    assert tensor.stack([a, b], axis=0).shape == (2, 2, 2)


def test_astype(dev):
    t = tensor.from_numpy(np.array([1.5, 2.5], np.float32), dev)
    ti = t.as_type(tensor.int32)
    assert np.dtype(ti.dtype) == np.int32
