"""Long-context serving (the long-context round): the Sarathi-style
chunked-prefill token budget (``PagedConfig(prefill_token_budget=)``),
windowed paged decode (sliding-window models in O(window) blocks), and
ring-attention prefill over the TP mesh
(``TPConfig(ring_prefill=True)``).

Parity discipline matches the rest of the serve suite: token streams
are np.array_equal-pinned against the unbudgeted engine / the offline
windowed ``generate`` oracle / the single-device engine — budgeted
chunk prefill rides the same ``_chunk_row`` executable the prefix
cache pinned bitwise against full prefill, so budgeted streams are
BYTE-identical; the windowed block kernel and the ring logsumexp merge
reorder float reductions, so those pins are token-identity (the same
caveat the kernel and TP rounds document)."""

import math

import numpy as np
import pytest

from singa_tpu import tensor
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from singa_tpu.observe import requests as reqtrace
from singa_tpu.resilience import FailAfterN, FailOnce, faults
from singa_tpu.serve import (EngineFailedError, EngineSupervisor,
                             GenerationRequest, PagedConfig,
                             PrefixCacheConfig)
from singa_tpu.serve.tp import TPConfig

B = 8  # pool block size every engine below uses


def _build(cfg):
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    return m


@pytest.fixture(scope="module")
def model():
    return _build(GPT2Config.tiny(dropout=0.0))


@pytest.fixture(scope="module")
def windowed(model):
    """Sliding-window twin of ``model`` — SAME weights, so in-window
    streams must agree byte-for-byte with the full-cache engine."""
    cfg = GPT2Config.tiny(dropout=0.0, attn_window=2 * B)
    wm = _build(cfg)
    wm.set_states(model.get_states())
    return wm


@pytest.fixture(scope="module")
def draft():
    return _build(GPT2Config.tiny(dropout=0.0, n_layer=1))


def _reqs(specs):
    return [GenerationRequest(
        np.asarray(p, np.int32), max_new_tokens=n,
        temperature=t, seed=s)
        for p, n, t, s in specs]


def _drive(m, reqs, max_slots=4, max_steps=6000, **kw):
    eng = m.serve(max_slots=max_slots, **kw)
    hs = [eng.submit(r) for r in reqs]
    eng.run_until_complete(max_steps=max_steps)
    outs = [h.result().tokens for h in hs]
    snap = eng.stats.snapshot()
    eng.close()
    return outs, snap


def _mix(seed=0):
    """One long admission (64-token prompt) among short chat traffic,
    greedy and sampled mixed."""
    rng = np.random.RandomState(seed)
    specs = [(rng.randint(0, 256, 64), 4, 0.0, 11)]
    for i in range(3):
        specs.append((rng.randint(0, 256, rng.randint(4, 12)),
                      6, float(rng.choice([0.0, 0.9])), 20 + i))
    return _reqs(specs)


# -- chunked-prefill token budget -------------------------------------------

def test_budget_streams_byte_identical(model):
    """Budgeted chunk prefill == unbudgeted whole-prompt prefill,
    byte for byte (greedy + seeded sampling): the chunks ride the
    bitwise-pinned ``_chunk_row`` path and the admission token
    samples through ``_first_from_hidden`` exactly like the warm
    path."""
    base, _ = _drive(model, _mix(),
                     paged=PagedConfig(block_size=B, num_blocks=32))
    outs, _ = _drive(model, _mix(),
                     paged=PagedConfig(block_size=B, num_blocks=32,
                                       prefill_token_budget=B))
    assert all(np.array_equal(a, b) for a, b in zip(outs, base))


def test_budget_decode_dispatches_every_step(model):
    """While the long admission's prefill spreads across steps, the
    already-live chat slots advance EVERY step (decode is dispatched
    before the budget pass) — the stall the budget exists to kill —
    and a queued follower admits only after the expensive head
    finishes (FIFO blocks, it never skips)."""
    eng = model.serve(max_slots=4, paged=PagedConfig(
        block_size=B, num_blocks=32, prefill_token_budget=B))
    chat = eng.submit(GenerationRequest(
        np.arange(6, dtype=np.int32), max_new_tokens=40,
        temperature=0.0, seed=1))
    eng.step()                      # chat admitted + decoding
    long_prompt = np.arange(64, dtype=np.int32) % 256
    h_long = eng.submit(GenerationRequest(
        long_prompt, max_new_tokens=2, temperature=0.0, seed=2))
    h_follow = eng.submit(GenerationRequest(
        np.arange(5, dtype=np.int32), max_new_tokens=2,
        temperature=0.0, seed=3))
    long_steps = 0
    while True:
        pos_before = int(eng._pos[0])
        eng.step()
        if not eng._prefilling:
            break
        long_steps += 1
        assert int(eng._pos[0]) == pos_before + 1, \
            "chat decode stalled behind the budgeted prefill"
        # the head consumes the whole budget each step, so the
        # follower must not overtake it (FIFO blocks, never skips)
        assert not h_follow.done()
        live = sum(s is not None for s in eng._slots)
        assert live == 1 and len(eng._prefilling) == 1, \
            "follower overtook the budgeted head"
        if eng.step_count > 200:
            pytest.fail("budgeted prefill never completed")
    # 64-token prompt at an 8-token budget: 8 chunks, one per step
    assert long_steps >= len(long_prompt) // B - 1
    eng.run_until_complete(max_steps=2000)
    for h in (chat, h_long, h_follow):
        assert h.result().tokens is not None
    assert eng.paged_arena.blocks_used == 0
    eng.close()


def test_budget_ledger_chunks_and_stall_attribution(model):
    """The request ledger sees every budgeted chunk (prefill phase of
    the long request spans steps) and chat requests' stall phase
    stays bounded."""
    led = reqtrace.enable(capacity=256)
    try:
        outs, _ = _drive(model, _mix(),
                         paged=PagedConfig(block_size=B,
                                           num_blocks=32,
                                           prefill_token_budget=B))
        entries = {e["request_id"]: e for e in led.entries()}
        long_e = [e for e in entries.values()
                  if e["prompt_len"] == 64][0]
        assert long_e["phases"]["prefill"] > 0
        # phase attribution stays exact arithmetic with chunked
        # prefill in the timeline
        ph = long_e["phases"]
        assert abs(ph["hops"] + ph["queue"] + ph["prefill"]
                   - long_e["ttft_s"]) <= 1e-9 + 1e-6 * long_e["ttft_s"]
    finally:
        reqtrace.disable()


def test_budget_with_prefix_cache_warm_hits(model):
    """Budget + radix prefix cache: a warm second request (admitted
    after the first retired and donated) re-admits through the
    budgeted path and stays byte-identical to the cold stream (same
    canonical chunk form)."""
    shared = (np.arange(24, dtype=np.int32) * 3) % 256
    specs = [(np.concatenate([shared, np.arange(6, dtype=np.int32)]),
              5, 0.0, 1),
             (np.concatenate([shared,
                              np.arange(9, dtype=np.int32) + 1]),
              5, 0.0, 2)]
    cold, _ = _drive(model, _reqs(specs),
                     paged=PagedConfig(block_size=B, num_blocks=32))
    eng = model.serve(max_slots=4,
                      paged=PagedConfig(block_size=B, num_blocks=32,
                                        prefill_token_budget=B),
                      prefix_cache=PrefixCacheConfig(block_size=B))
    warm = []
    for r in _reqs(specs):      # sequential: donation before reuse
        h = eng.submit(r)
        eng.run_until_complete(max_steps=500)
        warm.append(h.result().tokens)
    snap = eng.stats.snapshot()
    eng.close()
    assert all(np.array_equal(a, b) for a, b in zip(warm, cold))
    assert snap["prefix"]["hit_tokens"] > 0


def test_budget_fault_mid_prefill_frees_blocks(model):
    """A fault BETWEEN chunks (the ``serve.prefill_chunk`` site)
    fails the engine typed — the mid-prefill request rejects
    requeue-safe (started=False) and its partial blocks return to
    the free list (no leak); under a supervisor the requeued request
    completes with byte parity."""
    want = np.asarray(model.generate(
        np.arange(64, dtype=np.int32) % 256, max_new_tokens=3,
        temperature=0))
    # direct engine: typed failure, started=False, zero leak
    eng = model.serve(max_slots=2, paged=PagedConfig(
        block_size=B, num_blocks=32, prefill_token_budget=B))
    h = eng.submit(GenerationRequest(
        np.arange(64, dtype=np.int32) % 256, max_new_tokens=3,
        temperature=0.0))
    faults.inject("serve.prefill_chunk", FailAfterN(2, times=1))
    try:
        with pytest.raises(EngineFailedError):
            for _ in range(50):
                eng.step()
    finally:
        faults.clear()
    with pytest.raises(EngineFailedError) as ei:
        h.result()
    assert ei.value.started is False
    assert eng.paged_arena.blocks_used == 0, "mid-prefill leak"
    eng.close(force=True)
    # supervised: restart + requeue, parity kept
    sup = EngineSupervisor(model, max_slots=2, restart_budget=2,
                           paged=PagedConfig(
                               block_size=B, num_blocks=32,
                               prefill_token_budget=B))
    h = sup.submit(GenerationRequest(
        np.arange(64, dtype=np.int32) % 256, max_new_tokens=3,
        temperature=0.0))
    pol = faults.inject("serve.prefill_chunk", FailAfterN(2, times=1))
    try:
        sup.run_until_complete(max_steps=2000)
    finally:
        faults.clear()
    assert pol.fired == 1
    assert np.array_equal(h.result().tokens, want)
    assert sup.engine.paged_arena.blocks_used == 0
    sup.close()


def test_budget_with_spec_draft(model, draft):
    """Budget composes with speculative decoding: the target prefill
    chunks, the draft prefills whole at completion, streams equal the
    unbudgeted spec engine's."""
    kw = dict(draft_model=draft, spec_k=4)
    base, _ = _drive(model, _mix(3),
                     paged=PagedConfig(block_size=B, num_blocks=32),
                     **kw)
    outs, _ = _drive(model, _mix(3),
                     paged=PagedConfig(block_size=B, num_blocks=32,
                                       prefill_token_budget=B), **kw)
    assert all(np.array_equal(a, b) for a, b in zip(outs, base))


def test_resume_never_lands_on_prefilling_slot(model):
    """Regression (review finding): a slot reserved by an in-flight
    chunked prefill is NOT free — a swapped request resuming into it
    would be clobbered when the prefill completes and promotes the
    reservation.  The collision needs the prefilling slot BELOW the
    freed one (resume picks the lowest 'free' index): slot 0's first
    tenant retires and the queued long admission backfills it while
    slot 1's tenant is then preempted."""
    eng = model.serve(max_slots=2, paged=PagedConfig(
        block_size=B, num_blocks=32, prefill_token_budget=B))
    h_a = eng.submit(GenerationRequest(        # slot 0: retires at
        np.arange(4, dtype=np.int32), max_new_tokens=4,
        temperature=0.0, seed=3))              # step 4 (after b admits)
    eng.step()
    h_b = eng.submit(GenerationRequest(        # slot 1, long-running
        np.arange(6, dtype=np.int32), max_new_tokens=30,
        temperature=0.0, seed=2))
    eng.step()
    h_long = eng.submit(GenerationRequest(     # queued behind both
        np.arange(64, dtype=np.int32) % 256, max_new_tokens=2,
        temperature=0.0, seed=1))
    for _ in range(20):                        # until long reserves 0
        eng.step()
        if 0 in eng._prefilling:
            break
    assert 0 in eng._prefilling and eng._slots[0] is None
    assert eng._slots[1] is not None
    eng._preempt_slot(1, reason="test")        # swapped entry, slot 1
    assert eng._swapped
    eng.step()   # resume pass: must pick slot 1, NOT the reserved 0
    assert 0 in eng._prefilling or eng._slots[0] is not None
    # drain: every request must resolve (with the bug the resumed
    # request's slot was overwritten and its handle never finished)
    eng.run_until_complete(max_steps=2000)
    for h, (p, n) in ((h_a, (4, 4)), (h_b, (6, 30)), (h_long, (64, 2))):
        want = model.generate(
            (np.arange(p, dtype=np.int32) % 256) if p == 64
            else np.arange(p, dtype=np.int32),
            max_new_tokens=n, temperature=0)
        assert np.array_equal(h.result().tokens, want)
    assert eng.paged_arena.blocks_used == 0
    eng.close()


def test_start_prefilling_copy_fault_frees_blocks(model):
    """Regression (review finding): a fault in the row copy BETWEEN
    block allocation and the prefilling registration must not leak
    the freshly allocated blocks."""
    eng = model.serve(max_slots=2, paged=PagedConfig(
        block_size=B, num_blocks=32, prefill_token_budget=B))
    h = eng.submit(GenerationRequest(
        np.arange(40, dtype=np.int32) % 256, max_new_tokens=2,
        temperature=0.0))
    faults.inject("serve.paged_copy", FailOnce())
    try:
        with pytest.raises(EngineFailedError):
            for _ in range(20):
                eng.step()
    finally:
        faults.clear()
    with pytest.raises(EngineFailedError) as ei:
        h.result()
    assert ei.value.started is False
    assert eng.paged_arena.blocks_used == 0, "copy-fault block leak"
    eng.close(force=True)


# -- windowed paged decode ---------------------------------------------------

def test_windowed_in_window_byte_parity(model, windowed):
    """Sequences that never leave the window: the windowed paged
    engine streams byte-identically to the full-cache paged engine on
    the same weights (the band never binds, the masks add no float
    difference)."""
    specs = [(np.arange(5, dtype=np.int32), 6, 0.0, 1),
             (np.arange(7, dtype=np.int32) + 3, 6, 0.9, 2)]
    base, _ = _drive(model, _reqs(specs),
                     paged=PagedConfig(block_size=B, num_blocks=32))
    outs, _ = _drive(windowed, _reqs(specs),
                     paged=PagedConfig(block_size=B, num_blocks=32))
    assert all(np.array_equal(a, b) for a, b in zip(outs, base))


def test_windowed_long_generation_block_accounting(windowed):
    """A generation far beyond the window: the slot never holds more
    than ceil(window/B)+1 blocks, dropped blocks are REUSED (the
    total blocks touched exceeds the pool), the stream equals the
    offline windowed ``generate`` oracle, and the drained pool is
    leak-free."""
    prompt = np.arange(10, dtype=np.int32)
    n_new = 90   # total 100 positions = 13 blocks > 6-block pool
    eng = windowed.serve(max_slots=1, paged=PagedConfig(
        block_size=B, num_blocks=6))
    h = eng.submit(GenerationRequest(prompt, max_new_tokens=n_new,
                                     temperature=0.0))
    peak = 0
    while eng.pending:
        eng.step()
        s = eng._slots[0]
        if s is not None:
            peak = max(peak, sum(1 for b in s.blocks
                                 if b != eng.paged_arena.trash))
    window = 2 * B
    assert peak <= math.ceil(window / B) + 1, peak
    assert eng.paged_arena.window_drops > 6, "pool blocks not reused"
    assert eng.paged_arena.blocks_used == 0
    want = windowed.generate(prompt, max_new_tokens=n_new,
                             temperature=0)
    assert np.array_equal(h.result().tokens, want)
    eng.close()


def test_windowed_long_prompt_admits_in_window_blocks(windowed):
    """A prompt longer than the window admits holding only the
    in-window lanes' blocks — the below-window prefix is computed but
    never allocated."""
    prompt = (np.arange(64, dtype=np.int32) * 5) % 256
    eng = windowed.serve(max_slots=1, paged=PagedConfig(
        block_size=B, num_blocks=6))
    h = eng.submit(GenerationRequest(prompt, max_new_tokens=4,
                                     temperature=0.0))
    eng.step()
    s = eng._slots[0]
    held = sum(1 for b in s.blocks if b != eng.paged_arena.trash)
    assert held <= math.ceil(2 * B / B) + 1, held
    eng.run_until_complete(max_steps=500)
    want = windowed.generate(prompt, max_new_tokens=4, temperature=0)
    assert np.array_equal(h.result().tokens, want)
    eng.close()


def test_windowed_int8_parity(windowed):
    """Windowed x int8: token streams equal the offline windowed int8
    oracle's (per-block dequant in the kernel vs the rolling cache's
    folded scales — same quantized values, same key set)."""
    specs = [(np.arange(10, dtype=np.int32), 30, 0.0, 1)]
    from singa_tpu.models import gpt2_decode

    outs, _ = _drive(windowed, _reqs(specs), max_slots=1,
                     cache_dtype="int8",
                     paged=PagedConfig(block_size=B, num_blocks=8))
    want = gpt2_decode.generate(windowed, specs[0][0],
                                max_new_tokens=30, temperature=0,
                                cache_dtype="int8")
    assert np.array_equal(outs[0], want)


def test_windowed_spec_parity(windowed, draft):
    """Windowed x speculative: greedy spec streams equal the plain
    windowed engine's (argmax-match acceptance over the same windowed
    target logits)."""
    specs = [(np.arange(9, dtype=np.int32), 24, 0.0, 1),
             (np.arange(6, dtype=np.int32) + 2, 20, 0.0, 2)]
    base, _ = _drive(windowed, _reqs(specs),
                     paged=PagedConfig(block_size=B, num_blocks=16))
    outs, snap = _drive(windowed, _reqs(specs),
                        paged=PagedConfig(block_size=B,
                                          num_blocks=16),
                        draft_model=draft, spec_k=4)
    assert all(np.array_equal(a, b) for a, b in zip(outs, base))
    # untrained random draft/target rarely argmax-agree — acceptance
    # may legitimately be 0; the pin is that verify CHUNKS ran the
    # windowed chunk kernel and streams stayed equal
    assert snap["spec"]["chunks"] > 0


def test_windowed_preempt_resume_parity(windowed):
    """Windowed x preemption: an over-committed pool swaps a windowed
    slot out (O(window) host image) and the resumed stream equals the
    uninterrupted run's."""
    specs = [(np.arange(8, dtype=np.int32), 40, 0.0, 1),
             ((np.arange(10, dtype=np.int32) * 7) % 256, 40, 0.7, 2),
             (np.arange(5, dtype=np.int32) + 9, 40, 0.0, 3)]
    base, _ = _drive(windowed, _reqs(specs), max_slots=3,
                     paged=PagedConfig(block_size=B, num_blocks=32))
    outs, snap = _drive(windowed, _reqs(specs), max_slots=3,
                        paged=PagedConfig(block_size=B, num_blocks=8),
                        scheduler="priority")
    assert all(np.array_equal(a, b) for a, b in zip(outs, base))
    assert snap["paged"]["blocks_used"] == 0


def test_windowed_tp_parity(windowed):
    """Windowed x tensor parallelism: the sharded twins carry the
    window static; tp=2 streams are token-identical to the
    single-device windowed engine."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs a 2-device mesh")
    specs = [(np.arange(9, dtype=np.int32), 30, 0.0, 1),
             (np.arange(7, dtype=np.int32) + 1, 24, 0.9, 2)]
    base, _ = _drive(windowed, _reqs(specs),
                     paged=PagedConfig(block_size=B, num_blocks=16))
    outs, _ = _drive(windowed, _reqs(specs), tp=2,
                     paged=PagedConfig(block_size=B, num_blocks=16))
    assert all(np.array_equal(a, b) for a, b in zip(outs, base))


# -- ring-attention prefill --------------------------------------------------

def test_ring_prefill_token_identical(model):
    """Ring-sharded prefill == single-device chunk/serial prefill,
    token-identical on the virtual mesh (greedy + seeded sampling;
    the logsumexp merge reorders floats, identity away from ties),
    and the short prompt stays below the threshold on the serial
    path."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs a 2-device mesh")
    specs = [((np.arange(72, dtype=np.int32) * 3) % 256, 5, 0.0, 1),
             ((np.arange(70, dtype=np.int32) * 5) % 256, 5, 0.8, 2),
             (np.arange(8, dtype=np.int32), 5, 0.0, 3)]
    base, _ = _drive(model, _reqs(specs),
                     paged=PagedConfig(block_size=B, num_blocks=48))
    eng = model.serve(max_slots=4,
                      paged=PagedConfig(block_size=B, num_blocks=48),
                      tp=TPConfig(tp=2, ring_prefill=True,
                                  ring_min_tokens=32))
    hs = [eng.submit(r) for r in _reqs(specs)]
    eng.run_until_complete(max_steps=2000)
    outs = [h.result().tokens for h in hs]
    assert eng.tp_exec.ring_prefills == 2   # the two long prompts
    eng.close()
    assert all(np.array_equal(a, b) for a, b in zip(outs, base))


def test_ring_budget_composition(model):
    """Ring + prefill_token_budget: long admissions take the one-shot
    ring dispatch (charged against the budget), short ones chunk —
    streams stay identical to the plain engine's."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs a 2-device mesh")
    base, _ = _drive(model, _mix(5),
                     paged=PagedConfig(block_size=B, num_blocks=32))
    eng = model.serve(max_slots=4,
                      paged=PagedConfig(block_size=B, num_blocks=32,
                                        prefill_token_budget=2 * B),
                      tp=TPConfig(tp=2, ring_prefill=True,
                                  ring_min_tokens=32))
    hs = [eng.submit(r) for r in _mix(5)]
    eng.run_until_complete(max_steps=2000)
    outs = [h.result().tokens for h in hs]
    assert eng.tp_exec.ring_prefills == 1
    eng.close()
    assert all(np.array_equal(a, b) for a, b in zip(outs, base))


# -- configuration contracts -------------------------------------------------

def test_longctx_config_validation(model, windowed, draft):
    """Every refused composition is typed at construction with a
    message naming the long-context path it relates to."""
    # windowed without paged: still NotImplementedError, now naming
    # the paged path instead of only the offline fallback
    with pytest.raises(NotImplementedError, match="paged"):
        windowed.serve()
    # windowed + gather kernel: the oracle path would attend freed
    # blocks
    with pytest.raises(ValueError, match="kernel"):
        windowed.serve(paged=PagedConfig(block_size=B, num_blocks=8,
                                         kernel="gather"))
    # windowed + prefix cache: dropped blocks break the radix
    # contiguity contract
    with pytest.raises(NotImplementedError, match="prefix"):
        windowed.serve(paged=PagedConfig(block_size=B, num_blocks=8),
                       prefix_cache=PrefixCacheConfig(block_size=B))
    # budget must be a block multiple
    with pytest.raises(ValueError, match="prefill_token_budget"):
        PagedConfig(block_size=B, num_blocks=8,
                    prefill_token_budget=B + 1)
    with pytest.raises(ValueError, match="ring_min_tokens"):
        TPConfig(tp=2, ring_min_tokens=-1)
    # ring requires paged
    with pytest.raises(ValueError, match="ring_prefill"):
        model.serve(tp=TPConfig(tp=2, ring_prefill=True))
    # ring + prefix cache refused (non-canonical K/V)
    with pytest.raises(ValueError, match="ring_prefill"):
        model.serve(paged=PagedConfig(block_size=B, num_blocks=8),
                    prefix_cache=PrefixCacheConfig(block_size=B),
                    tp=TPConfig(tp=2, ring_prefill=True))
    # ring + int8 refused (byte-parity pin would not survive)
    with pytest.raises(ValueError, match="int8"):
        model.serve(paged=PagedConfig(block_size=B, num_blocks=8),
                    cache_dtype="int8",
                    tp=TPConfig(tp=2, ring_prefill=True))
    # over-length submit names the long-context path
    eng = model.serve(max_slots=1)
    with pytest.raises(ValueError, match="Long-context serving"):
        eng.submit(GenerationRequest(
            np.zeros(120, np.int32), max_new_tokens=30))
    eng.close()
