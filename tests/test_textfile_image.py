"""TextFile reader/writer + JPEG codec (reference: src/io/*.cc readers,
SURVEY.md §2.1 IO row)."""

import numpy as np
import pytest

from singa_tpu.io.textfile import TextFileReader, TextFileWriter


def test_textfile_roundtrip(tmp_path):
    p = str(tmp_path / "t.txt")
    with TextFileWriter(p) as w:
        w.put("hello world")
        w.put("line\nwith\nnewlines")
        w.put("back\\slash")
        w.Write("reference-verb")
    with TextFileReader(p) as r:
        assert r.count() == 4
        assert r.key(1) == "1"
        assert r.value(0) == "hello world"
        assert r.value(1) == "line\nwith\nnewlines"
        assert r.value(2) == "back\\slash"
        assert r.value(3) == "reference-verb"
        items = list(r.items())
        assert items[0] == ("0", "hello world")


def test_textfile_sequential_read(tmp_path):
    p = str(tmp_path / "t.txt")
    with TextFileWriter(p) as w:
        for i in range(3):
            w.put(f"v{i}")
    r = TextFileReader(p)
    got = []
    while True:
        kv = r.Read()
        if kv is None:
            break
        got.append(kv)
    assert got == [("0", "v0"), ("1", "v1"), ("2", "v2")]
    r.SeekToFirst()
    assert r.Read() == ("0", "v0")


def test_textfile_append(tmp_path):
    p = str(tmp_path / "t.txt")
    with TextFileWriter(p) as w:
        w.put("a")
    with TextFileWriter(p, append=True) as w:
        w.put("b")
    with TextFileReader(p) as r:
        assert [v for _, v in r.items()] == ["a", "b"]


def test_jpg_codec_roundtrip():
    pil = pytest.importorskip("PIL")  # noqa: F841
    from singa_tpu.io.image import decode_jpg, encode_jpg

    rng = np.random.RandomState(0)
    # smooth gradient image so JPEG loss stays small
    g = np.linspace(0, 255, 32, dtype=np.uint8)
    img = np.stack([np.tile(g, (32, 1))] * 3, axis=-1)
    blob = encode_jpg(img, quality=95)
    assert blob[:2] == b"\xff\xd8"  # JPEG SOI marker
    back = decode_jpg(blob)
    assert back.shape == img.shape and back.dtype == np.uint8
    assert np.abs(back.astype(int) - img.astype(int)).mean() < 3.0

    # grayscale path
    blob2 = encode_jpg(np.tile(g, (32, 1)))
    back2 = decode_jpg(blob2)
    assert back2.shape == (32, 32)

    with pytest.raises(ValueError):
        encode_jpg(rng.randn(8, 8, 3).astype(np.float32))


def test_augment_batch_eval_native_matches_numpy():
    """Eval mode (center crop, no flip) is deterministic, so the native
    C++ path and the numpy fallback must agree to float rounding."""
    from singa_tpu.image_tool import augment_batch
    from singa_tpu.io import binfile as bf

    rng = np.random.RandomState(0)
    imgs = rng.randint(0, 256, (8, 40, 36, 3), dtype=np.uint8)
    mean, std = [0.48, 0.45, 0.4], [0.22, 0.23, 0.24]
    out_a = augment_batch(imgs, (32, 24), mean, std, train=False)
    lib, err = bf._lib, bf._lib_err
    bf._lib, bf._lib_err = None, Exception("forced fallback")
    try:
        out_b = augment_batch(imgs, (32, 24), mean, std, train=False)
    finally:
        bf._lib, bf._lib_err = lib, err
    assert out_a.shape == (8, 3, 32, 24)
    np.testing.assert_allclose(out_a, out_b, atol=1e-5)


def test_augment_batch_train_deterministic_and_cropped():
    from singa_tpu.image_tool import augment_batch

    rng = np.random.RandomState(1)
    imgs = rng.randint(0, 256, (16, 40, 40, 3), dtype=np.uint8)
    a = augment_batch(imgs, 32, train=True, seed=5)
    b = augment_batch(imgs, 32, train=True, seed=5)
    c = augment_batch(imgs, 32, train=True, seed=6)
    assert a.shape == (16, 3, 32, 32)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, c)
    # un-normalized output stays in [0, 1]
    assert a.min() >= 0.0 and a.max() <= 1.0
