"""Disaggregated prefill/decode serving (the disagg round): role-typed
fleets, KV shipping through the versioned host image, the fleet-level
prefix index, and the router's least-recently-routed tie-break.

The parity chain under test: a prefill specialist's build is the
chunked-prefill CANONICAL form (the exact executable warm admission
rides), the ship image is a byte copy of those blocks, and the decode
replica's admission is a local warm hit — so a disaggregated stream
must be byte-identical to the same request served by one engine
(greedy AND seeded sampling, dense AND int8 pools).  Every failure
mode (mid-ship fault, specialist death, destination capacity) must
requeue cold-but-correct with zero leaked blocks on BOTH replicas.

Named to sort after test_monitor (the paged AOT compiles register
cost tables — same collection-order hazard test_serve_longctx
documents)."""

import numpy as np
import pytest

from singa_tpu import tensor
from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from singa_tpu.observe import requests as reqtrace
from singa_tpu.resilience import FailOnce, faults
from singa_tpu.serve import (GenerationRequest, KVImage, KVImageError,
                             PagedConfig, PrefixCacheConfig, Router,
                             ServeFleet)
from singa_tpu.serve.kvimage import KVIMAGE_VERSION, pack_image
from singa_tpu.serve.prefix import FleetPrefixIndex

BLOCK = 8


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture(scope="module")
def model():
    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    return m


def _disagg_kw(num_blocks=48, **extra):
    return dict(paged=PagedConfig(block_size=BLOCK,
                                  num_blocks=num_blocks),
                prefix_cache=PrefixCacheConfig(block_size=BLOCK),
                **extra)


def _long(seed, n=40):
    return np.random.RandomState(seed).randint(
        0, 256, n).astype(np.int32)


def _chats(n, seed=1):
    rng = np.random.RandomState(seed)
    return [(rng.randint(0, 256, rng.randint(3, 7)).astype(np.int32),
             int(rng.randint(2, 5))) for _ in range(n)]


def _leaks(fleet):
    """Blocks unaccounted for on each replica after a drain: used
    minus tree-cached must be zero (live slots are empty)."""
    out = []
    for i in range(fleet.replicas):
        eng = fleet.supervisor(i).engine
        if eng._closed:
            continue
        out.append(eng.paged_arena.blocks_used
                   - eng.prefix_cache.cached_blocks)
    return out


# ---------------------------------------------------------------------------
# kvimage: the shared versioned host format
# ---------------------------------------------------------------------------

def _fake_rows(width=16, quant=False):
    if quant:
        kc = (np.zeros((2, 1, 4, width, 8), np.int8),
              np.zeros((2, 1, 4, width), np.float32))
    else:
        kc = np.zeros((2, 1, 4, width, 8), np.float32)
    vc = (tuple(np.copy(a) for a in kc) if quant
          else np.copy(kc))
    return kc, vc


def test_kvimage_pack_validate_roundtrip():
    kc, vc = _fake_rows()
    img = pack_image(kc, vc, block_size=8, n_data=2, quant=False)
    assert img.version == KVIMAGE_VERSION
    assert img.width == 16 and img.nbytes > 0
    img.validate(8, False)                      # clean
    nar = img.narrowed(1)
    assert nar.width == 8 and nar.n_data == 1
    nar.validate(8, False)


def test_kvimage_mismatches_fail_typed():
    kc, vc = _fake_rows()
    img = pack_image(kc, vc, block_size=8, n_data=2, quant=False)
    with pytest.raises(KVImageError):           # wrong block size
        img.validate(16, False)
    with pytest.raises(KVImageError):           # dense into int8 pool
        img.validate(8, True)
    bad = KVImage(KVIMAGE_VERSION + 1, 8, 2, False, img.header,
                  img.kc, img.vc)
    with pytest.raises(KVImageError):           # unknown version
        bad.validate(8, False)
    lies = KVImage(KVIMAGE_VERSION, 8, 3, False, img.header,
                   img.kc, img.vc)
    with pytest.raises(KVImageError):           # n_data beyond width
        lies.validate(8, False)


def test_kvimage_truncation_detected_by_header():
    """A truncated transfer (arrays no longer match the pack-time
    header) fails typed — it can never scatter garbage."""
    kc, vc = _fake_rows()
    img = pack_image(kc, vc, block_size=8, n_data=2, quant=False)
    img.kc = img.kc[:, :, :, :8]                # 'truncated in transit'
    with pytest.raises(KVImageError):
        img.validate(8, False)


def test_swap_roundtrips_through_image_and_rejects_mismatch(model):
    """Preemption swap rides the same versioned format: out -> in is
    byte-exact, and an image from an alien geometry refuses before
    touching the pool."""
    eng = model.serve(max_slots=2, **_disagg_kw())
    arena = eng.paged_arena
    blocks = arena.alloc(2)
    img = arena.swap_out(blocks, 2)
    before_k, _ = arena.gather_row(blocks, n_used=2)
    dst = arena.alloc(2)
    arena.swap_in(img, dst)
    after_k, _ = arena.gather_row(dst, n_used=2)
    np.testing.assert_array_equal(np.asarray(before_k),
                                  np.asarray(after_k))
    alien = pack_image(img.kc, img.vc, block_size=BLOCK * 2,
                       n_data=1, quant=False)
    with pytest.raises(KVImageError):
        arena.swap_in(alien, dst)
    arena.free(blocks)
    arena.free(dst)
    eng.close()


# ---------------------------------------------------------------------------
# router: least-recently-routed tie-break + prefill scoring
# ---------------------------------------------------------------------------

def test_router_tiebreak_least_recently_routed():
    """Equal scores no longer bias onto replica 0: the tie goes to
    the replica routed to least recently (deterministic logical
    ticks — a fresh router still falls back to index order)."""
    r = Router()
    views = [{"replica": i, "queue_depth": 0, "occupancy": 0.0,
              "tpot_ewma": None, "queue_headroom": None}
             for i in range(3)]
    assert r.rank(views) == [0, 1, 2]           # fresh: index order
    r.note_routed(0)
    assert r.rank(views) == [1, 2, 0]
    r.note_routed(1)
    assert r.rank(views) == [2, 0, 1]
    r.note_routed(2)
    r.note_routed(1)
    assert r.rank(views) == [0, 2, 1]
    # real load still dominates the tie-break
    views[0]["queue_depth"] = 3
    assert r.rank(views)[-1] == 0


def test_router_prefill_scoring_by_build_depth():
    r = Router()
    views = [{"replica": 0, "prefill_depth": 2},
             {"replica": 1, "prefill_depth": 0}]
    assert r.rank_prefill(views) == [1, 0]


def test_fleet_prefix_index_register_lookup_drop():
    idx = FleetPrefixIndex(4)
    toks = np.arange(12, dtype=np.int32)
    idx.register(toks, 3, replica=0)
    idx.register(toks, 2, replica=1)
    assert idx.holders(toks, 3) == [0]
    assert idx.holders(toks, 2) == [0, 1]
    assert idx.holders(np.arange(1, 13, dtype=np.int32), 2) == []
    idx.unregister(toks, 3, replica=0)      # stale-hint pruning
    assert idx.holders(toks, 3) == []
    assert idx.holders(toks, 2) == [1]      # replica 1's record kept
    idx.drop_replica(1)
    assert idx.holders(toks, 2) == []
    assert idx.snapshot()["indexed_blocks"] == 0


def test_fleet_prefix_index_bounded():
    """The residency trie never grows past max_blocks: the stalest
    root subtree is evicted first, the freshest registration always
    survives its own insert."""
    idx = FleetPrefixIndex(4, max_blocks=6)
    prompts = [np.arange(i * 100, i * 100 + 12, dtype=np.int32)
               for i in range(4)]
    for p in prompts:
        idx.register(p, 3, replica=0)
        assert idx.snapshot()["indexed_blocks"] <= 6
    assert idx.holders(prompts[-1], 3) == [0]   # freshest survives
    assert idx.holders(prompts[0], 3) == []     # stalest evicted


# ---------------------------------------------------------------------------
# role validation
# ---------------------------------------------------------------------------

def test_roles_validation(model):
    with pytest.raises(ValueError, match="one role per replica"):
        ServeFleet(model, replicas=2, roles=("prefill",),
                   max_slots=2, **_disagg_kw())
    with pytest.raises(ValueError, match="unknown role"):
        ServeFleet(model, replicas=2, roles=("prefill", "verifier"),
                   max_slots=2, **_disagg_kw())
    with pytest.raises(ValueError, match="paged= AND prefix_cache="):
        ServeFleet(model, replicas=2, roles=("prefill", "decode"),
                   max_slots=2)


# ---------------------------------------------------------------------------
# disaggregated parity + ships
# ---------------------------------------------------------------------------

def test_disagg_greedy_parity_ships_and_no_leaks(model):
    """The service-level pin: every stream of a 1-prefill/1-decode
    fleet — long documents shipped, short chats routed direct — is
    byte-identical to single-prompt generate; ships happened; the
    prefill specialist carried NO decode traffic; zero leaked blocks
    on both replicas."""
    docs = [(_long(3), 4), (_long(4), 3)]
    chats = _chats(2)
    work = docs + chats
    base = [np.asarray(model.generate(p, max_new_tokens=n,
                                      temperature=0.0))
            for p, n in work]
    with model.serve_fleet(replicas=2, roles=("prefill", "decode"),
                           max_slots=2, **_disagg_kw()) as fleet:
        hs = [fleet.submit(GenerationRequest(
            p, max_new_tokens=n, temperature=0.0)) for p, n in work]
        fleet.run_until_complete(max_steps=800)
        for h, want in zip(hs, base):
            np.testing.assert_array_equal(h.result().tokens, want)
        snap = fleet.snapshot()
        assert snap["ships"] >= 2, snap
        assert snap["ship_bytes"] > 0
        assert snap["ship_fallbacks"] == 0
        assert snap["routed"]["0"] == 0         # specialist: no decode
        assert snap["routed"]["1"] == len(work)
        # the decode replica served the shipped admissions WARM
        dec = fleet.supervisor(1).engine.stats.snapshot()["prefix"]
        assert dec["hits"] >= 2
        assert all(l == 0 for l in _leaks(fleet)), _leaks(fleet)


def test_disagg_seeded_sampling_parity(model):
    p = _long(7, n=37)
    want = model.generate(p, max_new_tokens=6, temperature=0.8,
                          rng=np.random.RandomState(21))
    seed = int(np.random.RandomState(21).randint(0, 2 ** 31 - 1))
    with model.serve_fleet(replicas=2, roles=("prefill", "decode"),
                           max_slots=2, **_disagg_kw()) as fleet:
        h = fleet.submit(GenerationRequest(
            p, max_new_tokens=6, temperature=0.8, seed=seed))
        fleet.run_until_complete(max_steps=400)
        np.testing.assert_array_equal(h.result().tokens, want)
        assert fleet.snapshot()["ships"] == 1


def test_disagg_int8_parity(model):
    """int8 pools ship their (values, scales) image: the
    disaggregated stream equals a single int8+cache engine's (the
    chunked-quantized canonical form both sides share)."""
    p = _long(9, n=33)
    eng = model.serve(max_slots=2, cache_dtype="int8", **_disagg_kw())
    h0 = eng.submit(GenerationRequest(p, max_new_tokens=5,
                                      temperature=0.0))
    eng.run_until_complete(max_steps=300)
    want = h0.result().tokens
    eng.close()
    with model.serve_fleet(replicas=2, roles=("prefill", "decode"),
                           max_slots=2, cache_dtype="int8",
                           **_disagg_kw()) as fleet:
        h = fleet.submit(GenerationRequest(p, max_new_tokens=5,
                                           temperature=0.0))
        fleet.run_until_complete(max_steps=400)
        np.testing.assert_array_equal(h.result().tokens, want)
        assert fleet.snapshot()["ships"] == 1
        assert all(l == 0 for l in _leaks(fleet))


def test_warm_via_ship_equals_local_warm(model):
    """The three admission paths agree byte-for-byte: cold single
    engine, locally-warm single engine (prefix cache hit), and
    warm-via-ship on a disaggregated fleet."""
    p = _long(11, n=41)
    cold = np.asarray(model.generate(p, max_new_tokens=5,
                                     temperature=0.0))
    eng = model.serve(max_slots=2, **_disagg_kw())
    ha = eng.submit(GenerationRequest(p, max_new_tokens=5,
                                      temperature=0.0))
    eng.run_until_complete(max_steps=300)
    hb = eng.submit(GenerationRequest(p, max_new_tokens=5,
                                      temperature=0.0))   # local warm
    eng.run_until_complete(max_steps=300)
    assert eng.stats.snapshot()["prefix"]["hits"] >= 1
    local_warm = hb.result().tokens
    eng.close()
    with model.serve_fleet(replicas=2, roles=("prefill", "decode"),
                           max_slots=2, **_disagg_kw()) as fleet:
        hc = fleet.submit(GenerationRequest(p, max_new_tokens=5,
                                            temperature=0.0))
        fleet.run_until_complete(max_steps=400)
        shipped = hc.result().tokens
    np.testing.assert_array_equal(ha.result().tokens, cold)
    np.testing.assert_array_equal(local_warm, cold)
    np.testing.assert_array_equal(shipped, cold)


def test_shared_prefix_hits_across_replicas(model):
    """The fleet-level cache: a prompt prefilled once on the
    specialist warms LATER requests without any re-prefill — the
    second admission either routes to the resident decode replica
    (warm locally) or exports the resident blocks (no recompute).
    Either way shared_prefix_hits counts it and the specialist built
    the prefix exactly once."""
    p = _long(13, n=40)
    want = np.asarray(model.generate(p, max_new_tokens=4,
                                     temperature=0.0))
    with model.serve_fleet(replicas=2, roles=("prefill", "decode"),
                           max_slots=2, **_disagg_kw()) as fleet:
        h1 = fleet.submit(GenerationRequest(p, max_new_tokens=4,
                                            temperature=0.0))
        fleet.run_until_complete(max_steps=400)
        h2 = fleet.submit(GenerationRequest(p, max_new_tokens=4,
                                            temperature=0.0))
        fleet.run_until_complete(max_steps=400)
        np.testing.assert_array_equal(h1.result().tokens, want)
        np.testing.assert_array_equal(h2.result().tokens, want)
        snap = fleet.snapshot()
        assert snap["shared_prefix_hits"] >= 1, snap
        # residency did the work the second time: either the ship
        # count stayed at 1 (warm decode routing) or the second ship
        # exported without recompute (counted as the shared hit)
        assert snap["ships"] <= 2


def test_ship_queue_backpressure_falls_through_to_classic(model):
    """The ship queue is not exempt from back-pressure: past the
    scheduler-depth bound, long admissions route CLASSIC (the decode
    side's own queue bounds apply) instead of parking unboundedly
    behind the specialists — still byte-correct, just not shipped."""
    docs = [(_long(31), 3), (_long(32), 3)]
    base = [np.asarray(model.generate(p, max_new_tokens=n,
                                      temperature=0.0))
            for p, n in docs]
    with model.serve_fleet(replicas=2, roles=("prefill", "decode"),
                           max_slots=2, **_disagg_kw()) as fleet:
        fleet._ship_queue_max = lambda: 1
        hs = [fleet.submit(GenerationRequest(
            p, max_new_tokens=n, temperature=0.0)) for p, n in docs]
        assert len(fleet._ship_jobs) == 1      # second refused a park
        fleet.run_until_complete(max_steps=500)
        for h, want in zip(hs, base):
            np.testing.assert_array_equal(h.result().tokens, want)
        snap = fleet.snapshot()
        assert snap["ships"] == 1
        assert snap["routed"]["1"] == 2        # both decoded on dst


def test_short_prompt_routes_direct(model):
    """Nothing shippable (< 2 full blocks): classic routing to the
    decode side, zero ships."""
    p = np.arange(6, dtype=np.int32)
    want = np.asarray(model.generate(p, max_new_tokens=4,
                                     temperature=0.0))
    with model.serve_fleet(replicas=2, roles=("prefill", "decode"),
                           max_slots=2, **_disagg_kw()) as fleet:
        h = fleet.submit(GenerationRequest(p, max_new_tokens=4,
                                           temperature=0.0))
        fleet.run_until_complete(max_steps=200)
        np.testing.assert_array_equal(h.result().tokens, want)
        snap = fleet.snapshot()
        assert snap["ships"] == 0
        assert snap["routed"]["1"] == 1 and snap["routed"]["0"] == 0


def test_degenerate_fleet_mixed_fallback(model):
    """A role-typed fleet with no decode side still serves every
    request (cold, never refused) — the mixed-role fallback."""
    work = _chats(3, seed=5) + [(_long(15), 3)]
    base = [np.asarray(model.generate(p, max_new_tokens=n,
                                      temperature=0.0))
            for p, n in work]
    with ServeFleet(model, replicas=1, roles=("prefill",),
                    max_slots=2, **_disagg_kw()) as fleet:
        hs = [fleet.submit(GenerationRequest(
            p, max_new_tokens=n, temperature=0.0)) for p, n in work]
        fleet.run_until_complete(max_steps=600)
        for h, want in zip(hs, base):
            np.testing.assert_array_equal(h.result().tokens, want)
        assert fleet.snapshot()["ships"] == 0


def test_session_sticky_skips_ship(model):
    """A pinned session's continuation routes STICKY to the replica
    whose tree holds its blocks — never through a ship."""
    p = _long(17, n=40)
    with model.serve_fleet(replicas=2, roles=("prefill", "decode"),
                           max_slots=2, **_disagg_kw()) as fleet:
        h = fleet.submit(GenerationRequest(
            p, max_new_tokens=4, temperature=0.0, pin_session=True))
        fleet.run_until_complete(max_steps=400)
        sess = h.result().session
        assert sess is not None
        req2 = sess.request(np.arange(4, dtype=np.int32),
                            max_new_tokens=4, temperature=0.0)
        h2 = fleet.submit(req2)
        fleet.run_until_complete(max_steps=400)
        want = np.asarray(model.generate(
            req2.prompt_ids, max_new_tokens=4, temperature=0.0))
        np.testing.assert_array_equal(h2.result().tokens, want)
        assert fleet.snapshot()["ships"] == 1   # only the first turn
        sess.release()


# ---------------------------------------------------------------------------
# failure modes: mid-ship fault + specialist death
# ---------------------------------------------------------------------------

def test_ship_fault_requeues_cold_with_parity(model):
    """An injected serve.kv_ship fault mid-transfer: the request is
    requeued COLD (byte-identical — nothing streamed during a ship),
    the fallback is counted, and neither replica leaks a block."""
    p = _long(19, n=40)
    want = np.asarray(model.generate(p, max_new_tokens=4,
                                     temperature=0.0))
    with model.serve_fleet(replicas=2, roles=("prefill", "decode"),
                           max_slots=2, **_disagg_kw()) as fleet:
        pol = faults.inject("serve.kv_ship", FailOnce())
        h = fleet.submit(GenerationRequest(p, max_new_tokens=4,
                                           temperature=0.0))
        fleet.run_until_complete(max_steps=400)
        faults.clear()
        assert pol.fired == 1
        np.testing.assert_array_equal(h.result().tokens, want)
        snap = fleet.snapshot()
        assert snap["ships"] == 0
        assert snap["ship_fallbacks"] == 1
        assert snap["replicas_healthy"] == 2    # a ship fault is not
        #                                         an engine death
        assert all(l == 0 for l in _leaks(fleet)), _leaks(fleet)


def test_prefill_specialist_killed_mid_ship(model):
    """chaos: a chunk fault with a zero restart budget KILLS the
    prefill specialist mid-build.  The fleet fails it over, serves
    the mid-ship request (and everything else) cold on the decode
    replica with parity — zero wedged, zero lost, zero leaked on
    both the dead arena and the survivor."""
    work = [(_long(23), 3)] + _chats(3, seed=7)
    base = [np.asarray(model.generate(p, max_new_tokens=n,
                                      temperature=0.0))
            for p, n in work]
    with ServeFleet(model, replicas=2, roles=("prefill", "decode"),
                    max_slots=2, restart_budget=0,
                    **_disagg_kw()) as fleet:
        arena0 = fleet.supervisor(0).engine.paged_arena
        pol = faults.inject("serve.prefill_chunk", FailOnce())
        hs = [fleet.submit(GenerationRequest(
            p, max_new_tokens=n, temperature=0.0)) for p, n in work]
        fleet.run_until_complete(max_steps=600)
        faults.clear()
        assert pol.fired == 1
        for h, want in zip(hs, base):
            assert h.done()
            np.testing.assert_array_equal(h.result().tokens, want)
        snap = fleet.snapshot()
        assert snap["replicas_healthy"] == 1
        assert snap["failovers"] == 1
        assert snap["ships"] == 0
        assert snap["ship_fallbacks"] == 1
        # the dead specialist's pool leaked nothing behind the
        # partial build, and the survivor is clean
        assert arena0.blocks_used == 0, arena0.blocks_used
        eng1 = fleet.supervisor(1).engine
        assert eng1.paged_arena.blocks_used \
            == eng1.prefix_cache.cached_blocks


# ---------------------------------------------------------------------------
# ledger: via=kv_ship hop + exact ship-phase attribution
# ---------------------------------------------------------------------------

def test_ledger_kv_ship_hop_and_ship_phase(model):
    p = _long(29, n=40)
    reqtrace.enable(capacity=64)
    try:
        with model.serve_fleet(replicas=2,
                               roles=("prefill", "decode"),
                               max_slots=2, **_disagg_kw()) as fleet:
            h = fleet.submit(GenerationRequest(
                p, max_new_tokens=4, temperature=0.0,
                request_id="shipped"))
            fleet.run_until_complete(max_steps=400)
            h.result()
        led = reqtrace.ledger()
        e = led.entry("shipped")
        vias = [hop["via"] for hop in e["hops"]]
        assert vias == ["prefill", "kv_ship"], vias
        final = e["hops"][e["final_hop"]]
        assert final["via"] == "kv_ship"
        assert final["src_replica"] == 0 and final["replica"] == 1
        assert final["ship_bytes"] > 0 and final["ship_blocks"] >= 1
        assert final["admit_kind"] == "warm"    # the ship's point
        ph = e["phases"]
        assert ph["ship"] > 0
        # exact arithmetic: hops + ship + queue + prefill == TTFT,
        # and all seven phases sum to total latency
        assert ph["hops"] + ph["ship"] + ph["queue"] + ph["prefill"] \
            == pytest.approx(e["ttft_s"], abs=1e-9)
        assert sum(ph.values()) == pytest.approx(
            e["t_retire"] - e["t_submit"], abs=1e-9)
    finally:
        reqtrace.disable()
