"""BERT model tests (config #4 workload)."""

import numpy as np
import pytest

from singa_tpu import opt, tensor
from singa_tpu import device as device_module
from singa_tpu.models.bert import BertConfig, BertForMaskedLM, BertModel


@pytest.fixture
def dev():
    d = device_module.get_default_device()
    d.SetRandSeed(0)
    return d


def _batch(dev, cfg, b=2, s=16, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, (b, s)).astype(np.int32)
    labels = rng.randint(0, cfg.vocab_size, (b, s)).astype(np.int32)
    return (tensor.from_numpy(ids, dev), tensor.from_numpy(labels, dev))


def test_bert_tiny_forward_shapes(dev):
    cfg = BertConfig.tiny()
    m = BertModel(cfg)
    ids, _ = _batch(dev, cfg)
    m.eval()
    seq, pooled = m(ids)
    assert seq.shape == (2, 16, cfg.hidden_size)
    assert pooled.shape == (2, cfg.hidden_size)


def test_bert_attention_mask_changes_output(dev):
    cfg = BertConfig.tiny(hidden_dropout=0.0, attn_dropout=0.0)
    m = BertModel(cfg)
    ids, _ = _batch(dev, cfg)
    m.eval()
    seq_nomask, _ = m(ids)
    mask = np.ones((2, 16), np.float32)
    mask[:, 8:] = 0.0
    seq_masked, _ = m(ids, attention_mask=tensor.from_numpy(mask, dev))
    # masking the second half must change the first half's outputs
    a = tensor.to_numpy(seq_nomask)[:, :8]
    b = tensor.to_numpy(seq_masked)[:, :8]
    assert not np.allclose(a, b)


@pytest.mark.slow
def test_bert_mlm_trains_graph_mode(dev):
    cfg = BertConfig.tiny(hidden_dropout=0.0, attn_dropout=0.0)
    m = BertForMaskedLM(cfg)
    m.set_optimizer(opt.Adam(lr=1e-3))
    ids, labels = _batch(dev, cfg, b=4, s=12)
    m.compile([ids], is_train=True, use_graph=True)
    losses = [float(m(ids, labels)[1].data) for _ in range(6)]
    assert losses[-1] < losses[0], losses


@pytest.mark.slow
def test_bert_base_param_count(dev):
    cfg = BertConfig.base()
    m = BertForMaskedLM(cfg)
    ids, _ = _batch(dev, cfg, b=1, s=8)
    m.compile([ids], is_train=False, use_graph=False)
    n = sum(int(np.prod(v.shape)) for v in m.bert.get_params().values())
    # BERT-base trunk: ~109.48M params (embeddings + 12 layers + pooler)
    assert abs(n - 109_482_240) / 109_482_240 < 0.01, n


@pytest.mark.slow
def test_bert_parallel_plan_matches_serial(dev):
    """dp2 x tp2 x sp2 BERT == serial BERT (same state names, so a
    checkpoint moves between layouts)."""
    from singa_tpu.parallel import sharding as shd
    from singa_tpu import tensor as T

    cfg = BertConfig.tiny(hidden_dropout=0.0, attn_dropout=0.0)
    mesh = shd.create_mesh(dp=2, tp=2, sp=2)
    plan = shd.ShardingPlan(mesh)

    serial = BertForMaskedLM(cfg)
    par = BertForMaskedLM(cfg, plan=plan)
    par.set_sharding_plan(plan)
    ids, labels = _batch(dev, cfg, b=4, s=8)
    for m in (serial, par):
        m.set_optimizer(opt.SGD(lr=0.05))
        m.compile([ids], is_train=True, use_graph=True)
    assert set(serial.get_states()) == set(par.get_states())
    par.set_states({k: T.to_numpy(v)
                    for k, v in serial.get_states().items()})
    for _ in range(2):
        _, ls = serial(ids, labels)
        _, lp = par(ids, labels)
        np.testing.assert_allclose(float(T.to_numpy(lp)),
                                   float(T.to_numpy(ls)), rtol=3e-4)
