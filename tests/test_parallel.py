"""Model parallelism (GSPMD plan path): tensor + sequence parallel
transformer equals its serial twin bit-for-bit (to fp32 tolerance).

The reference could never test its Communicator without physical GPUs
(SURVEY.md §4); here the full dp*tp*sp mesh runs on the virtual 8-device
CPU topology from conftest.py.
"""

import numpy as np
import pytest

import jax

from singa_tpu import autograd, layer, model, opt, tensor
from singa_tpu.parallel import sharding as shd
from singa_tpu.parallel.tensor_parallel import (
    ColumnParallelLinear, ParallelTransformerBlock, VocabParallelEmbedding,
)

VOCAB, HIDDEN, HEADS, INTER, LAYERS = 64, 32, 4, 64, 2
B, S = 4, 8


class TinyLM(model.Model):
    def __init__(self, plan=None, causal=True, use_flash=False):
        super().__init__()
        self.embed = VocabParallelEmbedding(VOCAB, HIDDEN, plan)
        self.blocks = [
            ParallelTransformerBlock(HEADS, INTER, plan, causal=causal,
                                     use_flash=use_flash)
            for _ in range(LAYERS)
        ]
        self.head = ColumnParallelLinear(VOCAB, plan, gather_output=True)
        self.loss_fn = layer.SoftMaxCrossEntropy()

    def forward(self, ids):
        x = self.embed(ids)
        for blk in self.blocks:
            x = blk(x)
        return self.head(x)

    def train_one_batch(self, ids, labels):
        logits = self.forward(ids)
        b, s, v = logits.shape
        loss = self.loss_fn(
            autograd.reshape(logits, (b * s, v)),
            autograd.reshape(labels, (b * s,)))
        self.optimizer(loss)
        return logits, loss


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, VOCAB, size=(B, S)).astype(np.int32)
    labels = rng.randint(0, VOCAB, size=(B, S)).astype(np.int32)
    return ids, labels


def _compile(m, use_plan):
    ids, labels = _batch()
    x = tensor.from_numpy(ids)
    m.set_optimizer(opt.SGD(lr=0.1))
    m.compile([x], is_train=True, use_graph=True)
    return m


def _run_steps(m, nsteps=2):
    outs = []
    for i in range(nsteps):
        ids, labels = _batch(seed=i)
        logits, loss = m(tensor.from_numpy(ids), tensor.from_numpy(labels))
        outs.append(float(tensor.to_numpy(loss)))
    return outs


@pytest.mark.parametrize("dp,tp,sp", [(2, 2, 2), (1, 4, 1), (2, 1, 4)])
@pytest.mark.slow
def test_tp_sp_matches_serial(dp, tp, sp):
    mesh = shd.create_mesh(dp=dp, tp=tp, sp=sp)
    plan = shd.ShardingPlan(mesh)

    serial = _compile(TinyLM(plan=None), False)
    par = TinyLM(plan=plan)
    par.set_sharding_plan(plan)
    _compile(par, True)
    # identical weights
    par.set_states({k: tensor.to_numpy(v)
                    for k, v in serial.get_states().items()})

    loss_s = _run_steps(serial)
    loss_p = _run_steps(par)
    np.testing.assert_allclose(loss_p, loss_s, rtol=2e-4, atol=2e-5)

    # updated params still match after two optimizer steps
    ps = serial.get_states()
    pp = par.get_states()
    assert set(ps) == set(pp)
    for k in ps:
        np.testing.assert_allclose(
            tensor.to_numpy(pp[k]), tensor.to_numpy(ps[k]),
            rtol=2e-3, atol=2e-4, err_msg=k)


def test_ring_attention_padding_mask_matches_dense():
    """Key-padding mask rotates around the ring with its K/V block."""
    import jax.numpy as jnp
    from singa_tpu.parallel.ring_attention import ring_self_attention
    from jax.sharding import Mesh, PartitionSpec as P

    rng = np.random.RandomState(0)
    b, h, s, d = 2, 2, 8, 4
    q = rng.randn(b, h, s, d).astype(np.float32)
    k = rng.randn(b, h, s, d).astype(np.float32)
    v = rng.randn(b, h, s, d).astype(np.float32)
    # mask out the last 3 key positions of batch row 1
    mask = np.zeros((b, 1, 1, s), np.float32)
    mask[1, :, :, -3:] = -1e9

    # dense reference
    sc = np.einsum("bhsd,bhtd->bhst", q, k) / np.sqrt(d) + mask
    p = np.exp(sc - sc.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhst,bhtd->bhsd", p, v)

    mesh = Mesh(np.asarray(jax.devices()[:4]), ("seq",))
    spec = P(None, None, "seq", None)
    mspec = P(None, None, None, "seq")
    f = jax.shard_map(
        lambda q_, k_, v_, m_: ring_self_attention(
            q_, k_, v_, "seq", kv_mask=m_),
        mesh=mesh, in_specs=(spec, spec, spec, mspec), out_specs=spec,
        check_vma=False)
    out = np.asarray(f(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                       jnp.asarray(mask)))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_param_specs_assigned():
    mesh = shd.create_mesh(dp=2, tp=2, sp=2)
    plan = shd.ShardingPlan(mesh)
    m = TinyLM(plan=plan)
    m.set_sharding_plan(plan)
    _compile(m, True)
    specs = {n: getattr(t, "partition_spec", None)
             for n, t in m.get_params().items()}
    col = [n for n, s in specs.items()
           if s == shd.P(None, shd.MODEL)]
    row = [n for n, s in specs.items()
           if s == shd.P(shd.MODEL, None)]
    # q/k/v + fc1 + head are column-parallel; out_proj + fc2 + embed rows
    assert any("q_proj" in n for n in col)
    assert any("fc1" in n for n in col)
    assert any("out_proj" in n for n in row)
    assert any("embed" in n for n in row)
    # layernorm stays replicated
    assert all(specs[n] is None for n in specs if "ln" in n)


def test_plan_state_spec_inheritance():
    mesh = shd.create_mesh(dp=2, tp=4)
    plan = shd.ShardingPlan(mesh)
    t = tensor.Tensor((4, 8))
    t.partition_spec = shd.P(None, shd.MODEL)
    pspecs = {"w": shd.P(None, shd.MODEL)}
    assert plan.spec_for_state("w", t) == shd.P(None, shd.MODEL)
    o = tensor.Tensor((4, 8))
    assert plan.spec_for_state("__opt__w:momentum", o,
                               pspecs) == shd.P(None, shd.MODEL)
    assert plan.spec_for_state("__opt__w:momentum", o, {}) == shd.P()


@pytest.mark.slow
def test_sharded_model_checkpoint_roundtrip(tmp_path):
    """save_states on a planned (tp/sp-sharded) model gathers to host;
    load_states restores and the model resumes identically."""
    mesh = shd.create_mesh(dp=2, tp=2, sp=2)
    plan = shd.ShardingPlan(mesh)
    m = TinyLM(plan=plan)
    m.set_sharding_plan(plan)
    _compile(m, True)
    _run_steps(m, nsteps=2)  # params now live sharded on the mesh

    path = str(tmp_path / "ckpt.zip")
    m.save_states(path)
    before = {k: tensor.to_numpy(v) for k, v in m.get_states().items()}

    m2 = TinyLM(plan=plan)
    m2.set_sharding_plan(plan)
    _compile(m2, True)
    m2.load_states(path)
    for k, v in m2.get_states().items():
        np.testing.assert_array_equal(tensor.to_numpy(v), before[k],
                                      err_msg=k)
    # both resume with identical losses
    la = _run_steps(m, nsteps=2)
    lb = _run_steps(m2, nsteps=2)
    np.testing.assert_allclose(lb, la, rtol=1e-5)


def test_create_mesh_axes():
    mesh = shd.create_mesh(dp=2, tp=2, sp=2)
    assert mesh.axis_names == shd.AXES
    assert mesh.shape["data"] == 2 and mesh.shape["model"] == 2
    assert mesh.shape["pipe"] == 1 and mesh.shape["expert"] == 1
    with pytest.raises(ValueError):
        shd.create_mesh(dp=16, tp=16)


@pytest.mark.slow
def test_parallel_mha_flash_under_seq_plan_matches_serial():
    """ParallelMHA(use_flash=True) under a seq-sharded plan routes each
    ring step through the flash kernel; losses must match the serial
    fused model (the policy BertLayer now delegates here)."""
    mesh = shd.create_mesh(dp=1, tp=2, sp=4)
    plan = shd.ShardingPlan(mesh)

    serial = _compile(TinyLM(plan=None), False)
    par = TinyLM(plan=plan, use_flash=True)
    par.set_sharding_plan(plan)
    _compile(par, True)
    par.set_states({k: tensor.to_numpy(v)
                    for k, v in serial.get_states().items()})
    loss_s = _run_steps(serial)
    loss_p = _run_steps(par)
    np.testing.assert_allclose(loss_p, loss_s, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_parallel_mha_flash_without_seq_axis_warns_and_falls_back(caplog):
    """No seq axis: the flash request is dropped with a one-shot warning
    and the fused head-sharded path keeps training."""
    import logging as _logging

    mesh = shd.create_mesh(dp=2, tp=4)
    plan = shd.ShardingPlan(mesh)
    par = TinyLM(plan=plan, use_flash=True)
    par.set_sharding_plan(plan)
    with caplog.at_level(_logging.WARNING, logger="singa_tpu"):
        _compile(par, True)
        losses = _run_steps(par)
    assert all(np.isfinite(losses))
    assert any("use_flash ignored" in r.message for r in caplog.records)


def test_ring_attention_inf_mask_no_nan():
    """-inf additive masks (the jnp.where(pad, -inf, 0) idiom) must not
    NaN the merge even when a whole rank's K/V shard is masked
    (regression: the normalized-partial refactor computed
    exp(-inf - -inf) before the NEG_INF clamp)."""
    import jax.numpy as jnp
    from singa_tpu.parallel.ring_attention import ring_self_attention
    from jax.sharding import Mesh, PartitionSpec as P

    n = len(jax.devices())
    rng = np.random.RandomState(3)
    b, h, s, d = 1, 2, 8 * n, 4
    q = rng.randn(b, h, s, d).astype(np.float32)
    k = rng.randn(b, h, s, d).astype(np.float32)
    v = rng.randn(b, h, s, d).astype(np.float32)
    mask = np.zeros((b, 1, 1, s), np.float32)
    mask[:, :, :, -8:] = -np.inf  # masks the LAST rank's shard entirely
    mesh = Mesh(np.asarray(jax.devices()), ("seq",))
    spec = P(None, None, "seq", None)
    mspec = P(None, None, None, "seq")
    f = jax.shard_map(
        lambda q_, k_, v_, m_: ring_self_attention(
            q_, k_, v_, "seq", kv_mask=m_),
        mesh=mesh, in_specs=(spec, spec, spec, mspec), out_specs=spec,
        check_vma=False)
    o = np.asarray(f(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                     jnp.asarray(mask)))
    assert np.isfinite(o).all()
    # matches the dense reference with the same -inf mask
    import math as _math
    sc = np.einsum("bhsd,bhtd->bhst", q, k) / _math.sqrt(d) + mask
    sc = sc - sc.max(-1, keepdims=True)
    p = np.exp(sc); p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhst,bhtd->bhsd", p, v)
    np.testing.assert_allclose(o, ref, atol=2e-4)


def _peak_temp_bytes(m):
    """Per-device temp (activation/residual) HBM of the compiled train
    step, from XLA's static memory analysis — the quantity that bounds
    the max trainable sequence length."""
    best = 0
    for entry in m._graph_runner._compiled.values():
        fn = entry[0]
        try:
            ma = fn.memory_analysis()
        except AttributeError:
            continue
        if ma is not None:
            best = max(best, int(ma.temp_size_in_bytes))
    assert best > 0, "no compiled executable with memory analysis"
    return best


@pytest.mark.slow
def test_longctx_max_trainable_seqlen_scales_with_mesh():
    """SURVEY §5.7 / round-3 verdict item 1b: the max trainable S scales
    with the seq-mesh size.  At a fixed global S, the ring-attention
    (sp=8, flash) training step needs a FRACTION of the single-device
    fused step's per-device activation memory — so a global S whose
    serial step exceeds one rank's HBM budget still trains when
    sharded, and one sharded step runs to a finite loss here to prove
    it compiles AND executes, not just partitions."""
    S_long = 2048
    ids = np.random.RandomState(0).randint(
        0, VOCAB, size=(1, S_long)).astype(np.int32)
    labels = np.roll(ids, -1, axis=1).astype(np.int32)

    def build(plan, use_flash):
        m = TinyLM(plan=plan, use_flash=use_flash)
        if plan is not None:
            m.set_sharding_plan(plan)
        m.set_optimizer(opt.SGD(lr=0.1))
        m.compile([tensor.from_numpy(ids)], is_train=True,
                  use_graph=True)
        m(tensor.from_numpy(ids), tensor.from_numpy(labels))
        return m

    serial = build(None, use_flash=False)
    serial_temp = _peak_temp_bytes(serial)

    mesh = shd.create_mesh(sp=8)
    ring = build(shd.ShardingPlan(mesh), use_flash=True)
    ring_temp = _peak_temp_bytes(ring)

    # the serial fused step materializes O(S^2) score/prob residuals;
    # the ring step holds O(S_local * S) at worst.  Demand a >=4x
    # per-rank saving at sp=8 (the asymptotic factor is ~W, but the
    # model's S-independent weights/optimizer state dilute it at this
    # toy size)
    assert ring_temp * 4 <= serial_temp, (ring_temp, serial_temp)

    # and the sharded step actually trains: finite loss on a real step
    _, loss = ring(tensor.from_numpy(ids), tensor.from_numpy(labels))
    assert np.isfinite(float(tensor.to_numpy(loss)))


@pytest.mark.slow
def test_longctx_ring_memory_linear_not_quadratic_in_seqlen():
    """Companion growth-law check: as the global S grows with the mesh
    (S_local fixed), per-rank ring memory grows ~LINEARLY (the O(S·D)
    K/V hop residuals), while the serial fused step grows
    ~quadratically (O(S²) score residuals).  Linear growth is what
    makes S_max scale with W: W ranks buy a W-times-longer trainable
    sequence at roughly constant per-rank headroom beyond the O(S·D)
    term every attention impl pays to hold K/V at all."""
    def temp_at(s_global, sp=None):
        ids = np.random.RandomState(0).randint(
            0, VOCAB, size=(1, s_global)).astype(np.int32)
        labels = np.roll(ids, -1, axis=1).astype(np.int32)
        plan = (None if sp is None
                else shd.ShardingPlan(shd.create_mesh(sp=sp)))
        m = TinyLM(plan=plan, use_flash=sp is not None)
        if plan is not None:
            m.set_sharding_plan(plan)
        m.set_optimizer(opt.SGD(lr=0.1))
        m.compile([tensor.from_numpy(ids)], is_train=True,
                  use_graph=True)
        m(tensor.from_numpy(ids), tensor.from_numpy(labels))
        return _peak_temp_bytes(m)

    ring_ratio = temp_at(2048, sp=8) / temp_at(512, sp=2)
    serial_ratio = temp_at(2048) / temp_at(512)
    # 4x the sequence: linear growth ~4x, quadratic ~16x
    assert ring_ratio < 6, ring_ratio
    assert serial_ratio > 1.8 * ring_ratio, (serial_ratio, ring_ratio)


@pytest.mark.slow
def test_train_n_batches_under_plan_matches_serial_steps():
    """Multi-step dispatch on the GSPMD plan path: lax.scan over the
    planned step ≡ K single planned dispatches ≡ the serial model
    (round-5 verdict item #1)."""
    k = 3
    mesh = shd.create_mesh(dp=2, tp=2, sp=2)
    plan = shd.ShardingPlan(mesh)

    serial = _compile(TinyLM(plan=None), False)
    par = TinyLM(plan=plan)
    par.set_sharding_plan(plan)
    _compile(par, True)
    par.set_states({n: tensor.to_numpy(v)
                    for n, v in serial.get_states().items()})

    xs = np.stack([_batch(seed=i)[0] for i in range(k)])
    ys = np.stack([_batch(seed=i)[1] for i in range(k)])
    singles = []
    for i in range(k):
        _, loss = serial(tensor.from_numpy(xs[i]),
                         tensor.from_numpy(ys[i]))
        singles.append(float(tensor.to_numpy(loss)))

    _, losses = par.train_n_batches(tensor.from_numpy(xs),
                                    tensor.from_numpy(ys))
    np.testing.assert_allclose(np.asarray(losses.data), singles,
                               rtol=2e-4, atol=2e-5)
    ps, pp = serial.get_states(), par.get_states()
    for n in ps:
        np.testing.assert_allclose(
            tensor.to_numpy(pp[n]), tensor.to_numpy(ps[n]),
            rtol=2e-3, atol=2e-4, err_msg=n)


# -- zigzag (load-balanced) causal ring attention (round 5) ----------------

def _serial_causal(q, k, v):
    d = q.shape[-1]
    sc = np.einsum("bhsd,bhtd->bhst", q, k) / np.sqrt(d)
    s = q.shape[2]
    sc = np.where(np.tril(np.ones((s, s), bool))[None, None], sc, -1e30)
    p = np.exp(sc - sc.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhst,bhtd->bhsd", p, v)


@pytest.mark.slow
def test_zigzag_ring_causal_matches_serial():
    import jax.numpy as jnp
    from singa_tpu.parallel.ring_attention import (
        zigzag_ring_attention_sharded)
    from jax.sharding import Mesh

    rng = np.random.RandomState(0)
    b, h, s, d = 2, 2, 32, 8
    q, k, v = (rng.randn(b, h, s, d).astype(np.float32) for _ in range(3))
    ref = _serial_causal(q, k, v)
    for w in (2, 4, 8):
        mesh = Mesh(np.asarray(jax.devices()[:w]), ("seq",))
        out = np.asarray(zigzag_ring_attention_sharded(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh=mesh))
        np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5,
                                   err_msg=f"W={w}")


def test_zigzag_ring_balanced_work():
    """The analytic per-rank work is UNIFORM for zigzag (±0) while the
    contiguous causal layout is maximally skewed — the point of the
    layout (round-5 verdict item 4)."""
    from singa_tpu.parallel.ring_attention import (
        ring_causal_half_pairs_per_rank)

    for w in (2, 4, 8, 16, 64):
        zz = ring_causal_half_pairs_per_rank(w, "zigzag")
        assert len(set(zz)) == 1, zz
        cont = ring_causal_half_pairs_per_rank(w, "contiguous")
        assert max(cont) == w * min(cont)  # last rank does W x first's
        # total FLOPs identical (both compute their diagonal tiles
        # dense-masked): zigzag only redistributes them uniformly
        assert sum(zz) == sum(cont)


@pytest.mark.slow
def test_zigzag_ring_differentiable():
    """Gradients flow through scan+cond+ppermute (training path)."""
    import jax.numpy as jnp
    from singa_tpu.parallel.ring_attention import (
        zigzag_ring_attention_sharded)
    from jax.sharding import Mesh

    rng = np.random.RandomState(1)
    b, h, s, d = 1, 2, 16, 4
    q, k, v = (jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))
               for _ in range(3))
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("seq",))

    def loss(q_, k_, v_):
        return jnp.sum(zigzag_ring_attention_sharded(
            q_, k_, v_, mesh=mesh) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    # finite-difference check on one coordinate of q
    eps = 1e-3
    dq = np.zeros_like(np.asarray(q))
    dq[0, 0, 3, 1] = eps
    num = (float(loss(q + dq, k, v)) - float(loss(q - dq, k, v))) / (2 * eps)
    np.testing.assert_allclose(float(g[0][0, 0, 3, 1]), num, rtol=2e-2)
    assert all(np.isfinite(np.asarray(gi)).all() for gi in g)


def test_zigzag_order_roundtrip():
    from singa_tpu.parallel.ring_attention import zigzag_order

    order = zigzag_order(32, 4)
    assert sorted(order.tolist()) == list(range(32))
    # rank 0's block = first 8 entries: stripe 0 then stripe 7
    assert order[:8].tolist() == [0, 1, 2, 3, 28, 29, 30, 31]


@pytest.mark.slow
def test_zigzag_ring_flash_matches_serial():
    """use_flash=True: each zigzag half-pair runs through the Pallas
    kernel as a square (h, h) call; must equal serial causal."""
    import jax.numpy as jnp
    from singa_tpu.parallel.ring_attention import (
        zigzag_ring_attention_sharded)
    from jax.sharding import Mesh

    rng = np.random.RandomState(3)
    b, h, s, d = 1, 2, 32, 8
    q, k, v = (rng.randn(b, h, s, d).astype(np.float32) for _ in range(3))
    ref = _serial_causal(q, k, v)
    for w in (2, 4):
        mesh = Mesh(np.asarray(jax.devices()[:w]), ("seq",))
        out = np.asarray(zigzag_ring_attention_sharded(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh=mesh,
            use_flash=True))
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4,
                                   err_msg=f"W={w}")


def test_zigzag_repartition_roundtrip_matches_global_order():
    """The in-shard 4-ppermute repartition equals the global
    zigzag_order gather, and its inverse is exact."""
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from singa_tpu.parallel.ring_attention import (zigzag_order,
                                                   zigzag_repartition)

    for w in (2, 4, 8):
        mesh = Mesh(np.asarray(jax.devices()[:w]), ("seq",))
        s = 4 * w
        x = np.arange(2 * 1 * s * 3, dtype=np.float32).reshape(2, 1, s, 3)
        spec = P(None, None, "seq", None)
        fwd = jax.shard_map(
            lambda v: zigzag_repartition(v, "seq"), mesh=mesh,
            in_specs=(spec,), out_specs=spec, check_vma=False)
        bwd = jax.shard_map(
            lambda v: zigzag_repartition(v, "seq", inverse=True),
            mesh=mesh, in_specs=(spec,), out_specs=spec, check_vma=False)
        z = np.asarray(fwd(jnp.asarray(x)))
        np.testing.assert_array_equal(z, x[:, :, zigzag_order(s, w)])
        np.testing.assert_array_equal(np.asarray(bwd(jnp.asarray(z))), x)
