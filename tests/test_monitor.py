"""observe.monitor + observe.health: flight recorder bounds, crash
bundles, watchdog hang/anomaly firing rules (injectable clock), MFU
accounting honesty (nan, never 0, never a crash), and serve SLO
violation counters.

Everything host-side and deterministic: the watchdog is driven by
``check()`` on a fake clock (no thread), metrics live in private
registries, and crash bundles land in tmp_path."""

import glob
import json
import math
import os
import sys

import pytest

from singa_tpu import observe
from singa_tpu.observe import monitor
from singa_tpu.observe.health import SLO, health_report
from singa_tpu.observe.registry import MetricsRegistry


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def advance(self, dt):
        self.t += dt

    def __call__(self):
        return self.t


@pytest.fixture(autouse=True)
def _clean_monitor():
    """Monitoring off, recorder detached, tracing off around each
    test — the module-level monitor is process-global state."""
    monitor.stop()
    monitor.uninstall_crash_handler()
    observe.disable()
    observe.clear()
    yield
    monitor.stop()
    monitor.uninstall_crash_handler()
    observe.disable()
    observe.clear()


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

def test_flight_recorder_records_with_tracing_off_and_stays_bounded():
    rec = monitor.flight_recorder()
    rec.clear()
    rec.start(capacity=100)
    try:
        assert not observe.is_enabled()
        for i in range(1000):  # 10x capacity
            observe.event(f"e{i}", cat="x", i=i)
        assert len(rec) == 100
        # the ring keeps the TAIL (newest 100), oldest first
        evs = rec.events()
        assert evs[0]["name"] == "e900" and evs[-1]["name"] == "e999"
        # independence: the main trace buffer saw NOTHING
        assert observe.events() == []
    finally:
        rec.stop()
    # detached: emissions stop reaching the ring
    observe.event("after-stop")
    assert len(rec) == 100


def test_flight_recorder_and_tracing_compose():
    rec = monitor.flight_recorder()
    rec.clear()
    rec.start(capacity=10)
    observe.enable(clock=FakeClock())
    try:
        with observe.span("s", cat="x"):
            pass
        assert [e["name"] for e in observe.events()] == ["s"]
        assert [e["name"] for e in rec.events()] == ["s"]
    finally:
        rec.stop()


# ---------------------------------------------------------------------------
# crash bundles
# ---------------------------------------------------------------------------

def test_dump_report_roundtrips_through_json(tmp_path):
    rec = monitor.flight_recorder()
    rec.clear()
    rec.start(capacity=128)
    try:
        observe.registry().counter("monitor_test.count").inc(3)
        for i in range(60):
            observe.event(f"e{i}", cat="t")
        path = monitor.dump_report(path=str(tmp_path / "bundle.json"),
                                   reason="unit-test")
        d = json.loads(open(path).read())
    finally:
        rec.stop()
    assert d["schema"] == "singa_tpu.crash/1"
    assert d["reason"] == "unit-test"
    assert len(d["recent_events"]) >= 50
    assert d["registry"]["counters"]["monitor_test.count"] >= 3
    assert d["host"]["pid"] == os.getpid()
    assert "process_index" in d["host"]
    assert isinstance(d["cost_tables"], list)


def test_crash_handler_dumps_on_uncaught_exception(tmp_path, monkeypatch):
    """The acceptance path: a synthetic run dies mid-step on an
    injected exception; a parseable bundle with the last >= 50 events
    and a registry snapshot must be on disk afterwards."""
    monkeypatch.setenv("SINGA_TPU_CRASH_DIR", str(tmp_path))
    # chain onto a silent hook so the test log stays clean
    monkeypatch.setattr(sys, "excepthook", lambda *a: None)
    monitor.flight_recorder().clear()
    monitor.install_crash_handler(signals=())
    try:
        for i in range(75):
            observe.event(f"step{i}", cat="train", step=i)
        try:
            raise RuntimeError("injected mid-step failure")
        except RuntimeError:
            sys.excepthook(*sys.exc_info())
    finally:
        monitor.uninstall_crash_handler()
        monitor.flight_recorder().stop()
    bundles = glob.glob(str(tmp_path / "monitor-crash-*.json"))
    assert len(bundles) == 1
    d = json.loads(open(bundles[0]).read())
    assert "injected mid-step failure" in d["reason"]
    assert "RuntimeError" in d["traceback"]
    assert len(d["recent_events"]) >= 50
    assert set(d["registry"]) == {"counters", "gauges", "histograms"}
    # uninstall restored the (silenced) previous hook
    assert sys.excepthook.__name__ == "<lambda>"


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------

def test_watchdog_fires_exactly_once_per_missed_heartbeat():
    clk = FakeClock()
    reg = MetricsRegistry()
    wd = monitor.Watchdog(timeout_s=10.0, clock=clk, reg=reg,
                          dump_on_hang=False)
    wd.beat("train", step_time=0.1)
    clk.advance(5.0)
    assert wd.check() == []          # within timeout
    clk.advance(6.0)
    assert wd.check() == ["train"]   # missed -> fires
    clk.advance(100.0)
    assert wd.check() == []          # latched: ONE incident, not one/poll
    assert wd.hangs == 1
    wd.beat("train", step_time=0.1)  # recovery resets the latch
    clk.advance(11.0)
    assert wd.check() == ["train"]
    assert wd.hangs == 2
    s = wd.summary()
    assert s["sources"]["train"]["hang_latched"] is True
    assert s["hangs"] == 2


def test_watchdog_hang_emits_stacks_and_dumps_bundle(tmp_path,
                                                    monkeypatch):
    monkeypatch.setenv("SINGA_TPU_CRASH_DIR", str(tmp_path))
    clk = FakeClock()
    rec = monitor.flight_recorder()
    rec.clear()
    rec.start(capacity=64)
    observe.enable(clock=clk)
    try:
        wd = monitor.Watchdog(timeout_s=1.0, clock=clk,
                              reg=MetricsRegistry())
        wd.beat("serve", step_time=0.01)
        clk.advance(2.0)
        assert wd.check() == ["serve"]
        hang = next(e for e in observe.events()
                    if e["name"] == "monitor/hang")
        assert hang["args"]["source"] == "serve"
        assert any("MainThread" in t for t in hang["args"]["threads"])
        assert wd.last_dump is not None
        d = json.loads(open(wd.last_dump).read())
        assert d["reason"] == "hang:serve"
        assert "MainThread" in d["thread_stacks"]
    finally:
        rec.stop()


def test_watchdog_step_time_anomaly_zscore():
    clk = FakeClock()
    reg = MetricsRegistry()
    wd = monitor.Watchdog(timeout_s=100.0, clock=clk, reg=reg,
                          dump_on_hang=False, warmup=8)
    observe.enable(clock=clk)
    # steady-but-not-constant feed (constant would keep the EWMA
    # variance at exactly 0, which disables the z-test by design)
    for i in range(20):
        wd.beat("train", step_time=0.10 + 0.01 * (i % 2))
        clk.advance(0.1)
    anom = reg.counter("train.step_time_anomalies",
                       process=wd._process)
    assert anom.value == 0
    wd.beat("train", step_time=5.0)     # ~1000 sigma
    assert anom.value == 1
    ev = next(e for e in observe.events()
              if e["name"] == "monitor/step_time_anomaly")
    assert ev["args"]["source"] == "train" and ev["args"]["z"] > 6
    # fresh-compile dispatches are liveness-only: no sample, no anomaly
    wd.beat("train", step_time=50.0, fresh_compile=True)
    assert anom.value == 1
    # per-process straggler histogram got every replay sample
    h = reg.histogram("train.step_time", process=wd._process)
    assert h.count == 21


def test_watchdog_multi_step_beat_normalizes_per_step():
    reg = MetricsRegistry()
    wd = monitor.Watchdog(clock=FakeClock(), reg=reg,
                          dump_on_hang=False)
    wd.beat("train", step_time=20.0, steps=100)  # one K-step dispatch
    h = reg.histogram("train.step_time", process=wd._process)
    assert h.summary()["max"] == pytest.approx(0.2)
    assert wd.summary()["sources"]["train"]["beats"] == 100


# ---------------------------------------------------------------------------
# MFU accounting
# ---------------------------------------------------------------------------

def test_mfu_gauge_is_nan_without_cost_table_or_known_backend(
        monkeypatch):
    # no compiled graph step anywhere: step_flops has no table to read
    # (extra cost sources too — serve-side AOT compiles from earlier
    # test modules register paged cost tables process-wide, and this
    # test's contract is "no table ANYWHERE")
    monkeypatch.setattr("singa_tpu.model._graph_runners", [])
    monkeypatch.setattr(
        "singa_tpu.observe.monitor._extra_cost_sources", [])
    clk = FakeClock()
    reg = MetricsRegistry()
    meter = monitor.MfuMeter(reg=reg, clock=clk)
    # nan BEFORE any sample too (gauges initialize to nan, not 0)
    assert math.isnan(reg.gauge("train.mfu").value)
    reg.counter("train.steps").inc(50)
    clk.advance(10.0)
    s = meter.sample()                    # must not raise
    assert s["steps_per_s"] == pytest.approx(5.0)
    assert math.isnan(s["step_flops"])
    assert math.isnan(s["model_flops_per_s"])
    assert math.isnan(s["mfu"]) and not s["mfu"] == 0
    assert math.isnan(reg.gauge("train.mfu").value)
    assert math.isnan(reg.gauge("train.model_flops_per_s").value)


def test_mfu_math_against_known_peak(monkeypatch):
    monkeypatch.setattr(monitor, "step_flops", lambda: 1e12)
    monkeypatch.setattr(monitor, "peak_flops",
                        lambda device_kind=None: 275e12)
    clk = FakeClock()
    reg = MetricsRegistry()
    meter = monitor.MfuMeter(reg=reg, clock=clk)
    reg.counter("train.steps").inc(100)
    clk.advance(2.0)
    s = meter.sample()
    assert s["model_flops_per_s"] == pytest.approx(50 * 1e12)
    assert s["mfu"] == pytest.approx(50 / 275)
    assert reg.gauge("train.mfu").value == pytest.approx(50 / 275)


def test_mfu_zero_step_interval_is_nan_pair(monkeypatch):
    """An interval with ZERO train steps (a process serving, not
    training) publishes model_flops_per_s AND mfu as nan TOGETHER —
    never a hard 0.0 flops/s next to a null mfu (the committed
    BENCH_SERVE health.train inconsistency on unknown-peak backends):
    a busy process must never read as 0 flops/s, whatever the
    backend's peak table knows."""
    monkeypatch.setattr(monitor, "step_flops", lambda: 1e12)
    clk = FakeClock()
    reg = MetricsRegistry()
    meter = monitor.MfuMeter(reg=reg, clock=clk)
    for peak in (275e12, float("nan")):   # known AND unknown peak
        monkeypatch.setattr(monitor, "peak_flops",
                            lambda device_kind=None, p=peak: p)
        clk.advance(5.0)                  # a real interval, 0 steps
        s = meter.sample()
        assert math.isnan(s["model_flops_per_s"]), s
        assert math.isnan(s["mfu"]), s
        assert math.isnan(
            reg.gauge("train.model_flops_per_s").value)
        assert math.isnan(reg.gauge("train.mfu").value)
    # and a real training interval afterwards still rates normally
    monkeypatch.setattr(monitor, "peak_flops",
                        lambda device_kind=None: 100e12)
    reg.counter("train.steps").inc(10)
    clk.advance(10.0)
    assert meter.sample()["model_flops_per_s"] == pytest.approx(1e12)


def test_mfu_read_does_not_reset_the_sampling_window(monkeypatch):
    """health_report() must not shrink the watchdog thread's rate
    interval to ~0 (which would publish a misleading 0 for a process
    that just trained hard) — read() returns the last published
    sample; back-to-back sample()s inside MIN_INTERVAL_S are no-ops."""
    monkeypatch.setattr(monitor, "step_flops", lambda: 1e12)
    monkeypatch.setattr(monitor, "peak_flops",
                        lambda device_kind=None: 100e12)
    clk = FakeClock()
    reg = MetricsRegistry()
    meter = monitor.MfuMeter(reg=reg, clock=clk)
    reg.counter("train.steps").inc(100)
    clk.advance(10.0)
    s1 = meter.sample()                 # 10 steps/s
    assert s1["mfu"] == pytest.approx(0.1)
    clk.advance(0.01)                   # a report lands right after
    assert meter.sample() is s1         # short interval: unchanged
    assert meter.read() is s1           # read never mutates
    assert reg.gauge("train.mfu").value == pytest.approx(0.1)


def test_mfu_first_sample_in_tiny_interval_is_nan_not_zero(
        monkeypatch):
    """health_report() milliseconds after monitor.start() on a busy
    TPU: 0 steps over a ~0s window must report nan, never publish 0."""
    monkeypatch.setattr(monitor, "step_flops", lambda: 1e12)
    monkeypatch.setattr(monitor, "peak_flops",
                        lambda device_kind=None: 100e12)
    clk = FakeClock()
    reg = MetricsRegistry()
    meter = monitor.MfuMeter(reg=reg, clock=clk)
    reg.counter("train.steps").inc(100)
    clk.advance(0.01)
    s = meter.read()
    assert math.isnan(s["mfu"]) and math.isnan(s["model_flops_per_s"])
    assert math.isnan(reg.gauge("train.mfu").value)  # not published
    clk.advance(10.0)                   # a real interval later: real mfu
    # window runs from construction (the tiny probe did not reset it)
    assert meter.sample()["mfu"] == pytest.approx(100 / 10.01 / 100)


def test_span_clock_swap_mid_span_never_reaches_the_ring():
    """disable() mid-span restores perf_counter; the half-open span's
    mixed-clock duration must not land in the flight recorder either."""
    rec = monitor.flight_recorder()
    rec.clear()
    rec.start(capacity=16)
    try:
        observe.enable(clock=FakeClock(1_000_000.0))
        with observe.span("crossing", cat="x"):
            observe.disable()  # clock swapped back mid-span
        assert rec.events() == []
        assert observe.events() == []
    finally:
        rec.stop()


def test_crash_bundle_is_strict_json(tmp_path):
    """nan gauges (train.mfu on CPU) must serialize as null — the
    bundle is readable by jq, not just Python."""
    monitor.MfuMeter(reg=observe.registry())  # plants nan gauges
    path = monitor.dump_report(path=str(tmp_path / "b.json"),
                               reason="strictness")

    def raiser(c):
        raise ValueError(f"non-strict JSON constant {c}")

    d = json.loads(open(path).read(), parse_constant=raiser)
    assert d["registry"]["gauges"]["train.mfu"] is None


def test_idle_beat_disarms_hang_detection():
    """Idle is not hung: a drained source (busy=False) never fires,
    however long it stays silent; the next busy beat re-arms."""
    clk = FakeClock()
    wd = monitor.Watchdog(timeout_s=1.0, clock=clk,
                          reg=MetricsRegistry(), dump_on_hang=False)
    wd.beat("serve.e0", step_time=0.01)
    wd.beat("serve.e0", busy=False)      # drained
    clk.advance(1_000.0)
    assert wd.check() == []              # idle != hung
    assert wd.summary()["sources"]["serve.e0"]["armed"] is False
    wd.beat("serve.e0", step_time=0.01)  # traffic again: re-armed
    clk.advance(2.0)
    assert wd.check() == ["serve.e0"]


def test_forget_source_releases_state_and_metrics():
    clk = FakeClock()
    reg = MetricsRegistry()
    wd = monitor.Watchdog(timeout_s=1.0, clock=clk, reg=reg,
                          dump_on_hang=False)
    wd.beat("serve.e7", step_time=0.01)
    assert len(reg.metrics()) == 2  # step_time hist + anomalies
    wd.forget("serve.e7")
    assert reg.metrics() == []
    assert "serve.e7" not in wd.summary()["sources"]
    clk.advance(100.0)
    assert wd.check() == []  # forgotten sources cannot fire


def test_engine_heartbeats_per_engine_disarm_on_drain_and_forget():
    """End to end: each engine beats its own serve.e<n> source (a
    wedged engine is never masked by a healthy sibling), disarms when
    drained, and close() drops the source + its metrics."""
    import numpy as np

    from singa_tpu import tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from singa_tpu.serve import GenerationRequest

    clk_wd = FakeClock()
    wd = monitor.start(watchdog_timeout_s=30.0, clock=clk_wd,
                       thread=False, dump_on_hang=False)
    try:
        cfg = GPT2Config(vocab_size=64, n_positions=32, n_embd=16,
                         n_layer=1, n_head=2, n_inner=32, dropout=0.0,
                         attn_impl="fused")
        m = GPT2LMHead(cfg)
        m.compile([tensor.from_numpy(np.zeros((1, 4), np.int32))],
                  is_train=False, use_graph=False)
        eng = m.serve(max_slots=2)
        src = eng._hb_source
        assert src == "serve.e" + eng.stats.engine_label
        eng.submit(GenerationRequest(np.asarray([1, 2, 3]),
                                     max_new_tokens=2))
        eng.run_until_complete(max_steps=20)
        s = wd.summary()["sources"][src]
        assert s["beats"] >= 1 and s["armed"] is False  # drained
        clk_wd.advance(1_000.0)
        assert wd.check() == []  # idle engine never a false hang
        # new traffic re-arms BEFORE the dispatch (a wedged first
        # prefill/decode after idle must still be detectable)
        eng.submit(GenerationRequest(np.asarray([2, 3]),
                                     max_new_tokens=3))
        eng.step()
        assert wd.summary()["sources"][src]["armed"] is True
        eng.run_until_complete(max_steps=20)
        assert wd.summary()["sources"][src]["armed"] is False
        eng.close()
        assert src not in wd.summary()["sources"]
    finally:
        monitor.stop()


def test_sigint_handler_and_excepthook_write_one_bundle(tmp_path,
                                                        monkeypatch):
    """Ctrl-C path: the SIGINT handler dumps signal:2, then chains to
    default_int_handler whose KeyboardInterrupt reaches the chained
    excepthook — which must NOT write a second bundle."""
    import signal as _signal

    monkeypatch.setenv("SINGA_TPU_CRASH_DIR", str(tmp_path))
    monkeypatch.setattr(sys, "excepthook", lambda *a: None)
    monitor.flight_recorder().clear()
    monitor.install_crash_handler(signals=(_signal.SIGINT,))
    try:
        handler = _signal.getsignal(_signal.SIGINT)
        with pytest.raises(KeyboardInterrupt):
            handler(int(_signal.SIGINT), None)  # dumps + chains
        try:
            raise KeyboardInterrupt
        except KeyboardInterrupt:
            sys.excepthook(*sys.exc_info())  # must dedupe
    finally:
        monitor.uninstall_crash_handler()
        monitor.flight_recorder().stop()
    bundles = glob.glob(str(tmp_path / "monitor-crash-*.json"))
    assert len(bundles) == 1
    assert json.loads(open(bundles[0]).read())["reason"] == "signal:2"


def test_hangs_counter_is_labeled_per_source():
    clk = FakeClock()
    reg = MetricsRegistry()
    wd = monitor.Watchdog(timeout_s=1.0, clock=clk, reg=reg,
                          dump_on_hang=False)
    wd.beat("train")
    wd.beat("serve")
    clk.advance(2.0)
    assert sorted(wd.check()) == ["serve", "train"]
    assert reg.counter("monitor.hangs", source="train").value == 1
    assert reg.counter("monitor.hangs", source="serve").value == 1
    assert wd.hangs == 2  # cross-source total


def test_peak_flops_table_lookup():
    assert monitor.peak_flops("TPU v4") == 275e12
    assert monitor.peak_flops("TPU v5p") == 459e12
    assert monitor.peak_flops("TPU v5 lite") == 197e12
    assert math.isnan(monitor.peak_flops("cpu"))
    assert math.isnan(monitor.peak_flops("A100"))  # never a guess


# ---------------------------------------------------------------------------
# serve SLO monitor
# ---------------------------------------------------------------------------

def _result(ttft, tpot, rid="r-0"):
    class R:
        pass

    r = R()
    r.ttft, r.tpot, r.request_id = ttft, tpot, rid
    return r


def test_slo_violation_counters_on_slow_retire():
    from singa_tpu.serve.stats import EngineStats

    reg = MetricsRegistry()
    slo = SLO(ttft_p99_s=0.1, tpot_p50_s=0.05, queue_depth_max=4)
    st = EngineStats(max_slots=2, clock=FakeClock(), reg=reg, slo=slo)
    lbl = dict(engine=st.engine_label)
    st.on_complete(_result(ttft=0.02, tpot=0.01))   # within targets
    assert reg.counter("serve.slo_violations", kind="ttft",
                       **lbl).value == 0
    st.on_complete(_result(ttft=0.5, tpot=0.2))     # synthetic slow one
    assert reg.counter("serve.slo_violations", kind="ttft",
                       **lbl).value == 1
    assert reg.counter("serve.slo_violations", kind="tpot",
                       **lbl).value == 1
    st.on_complete(_result(ttft=0.5, tpot=None))    # 1-token: no tpot
    assert reg.counter("serve.slo_violations", kind="ttft",
                       **lbl).value == 2
    assert reg.counter("serve.slo_violations", kind="tpot",
                       **lbl).value == 1
    # queue pressure fires past queue_depth_max
    st.on_schedule(queue_depth=3)
    st.on_schedule(queue_depth=9)
    assert reg.counter("serve.slo_violations", kind="queue",
                       **lbl).value == 1
    snap = st.snapshot()
    assert snap["slo"]["violations"] == {"ttft": 2, "tpot": 1,
                                         "queue": 1}
    assert snap["slo"]["targets"]["ttft_p99_s"] == 0.1
    json.dumps(snap)


def test_slo_counters_unregister_with_the_engine():
    from singa_tpu.serve.stats import EngineStats

    reg = MetricsRegistry()
    st = EngineStats(2, FakeClock(), reg=reg,
                     slo=SLO(ttft_p99_s=1.0))
    assert len(reg.metrics()) == 17  # 14 base + 3 slo kinds
    st.unregister()
    assert len(reg.metrics()) == 0


def test_snapshot_gains_uptime_and_goodput():
    from singa_tpu.serve.stats import EngineStats

    clk = FakeClock()
    st = EngineStats(2, clk, reg=MetricsRegistry())
    for _ in range(30):
        st.on_token()
    clk.advance(3.0)
    snap = st.snapshot()
    assert snap["throughput"]["uptime_s"] == pytest.approx(3.0)
    assert snap["throughput"]["goodput_tokens_per_s"] == pytest.approx(
        10.0)
    assert snap["slo"] is None  # no targets configured


# ---------------------------------------------------------------------------
# health report + module lifecycle
# ---------------------------------------------------------------------------

def test_health_report_schema_and_sections():
    clk = FakeClock()
    monitor.start(watchdog_timeout_s=60.0, clock=clk, thread=False)
    try:
        monitor.heartbeat("train", step_time=0.1)
        report = health_report()
        assert set(report) == {
            "schema", "host", "train", "step_time", "serve",
            "windowed", "resilience", "watchdog", "flight_recorder",
            "registry"}
        # always-present feature sections: {"enabled": False} until
        # their layers install (windowed rings, burn-rate policy,
        # autoscaler)
        assert report["windowed"] == {"enabled": False}
        assert report["serve"]["slo_alerts"] == {"enabled": False}
        assert report["serve"]["autoscale"] == {"enabled": False}
        # the resilience section is always present, zeroed when the
        # layer never armed
        assert report["resilience"]["engine_restarts"] >= 0
        assert isinstance(report["resilience"]["retries"], dict)
        assert report["watchdog"]["active"] is True
        assert report["watchdog"]["hangs"] == 0
        assert "train" in report["watchdog"]["sources"]
        assert report["flight_recorder"]["active"] is True
        assert math.isnan(report["train"]["mfu"])  # CPU: honest nan
        assert report["serve"]["slo_violations"] == {
            "ttft": 0, "tpot": 0, "queue": 0}
        # per-process step-time summary names this process
        sec = report["step_time"]["train"]
        assert sec["straggler"]["process"] in sec["per_process"]
        json.dumps(report, default=str)
        # benches embed next to their own top-level registry key and
        # opt out of the duplicate snapshot
        slim = health_report(include_registry=False)
        assert set(report) - set(slim) == {"registry"}
    finally:
        monitor.stop()
    assert not monitor.active()
    monitor.heartbeat("train", step_time=0.1)  # no-op after stop


def test_health_report_aggregates_engine_goodput():
    from singa_tpu.serve.stats import EngineStats

    clk = FakeClock()
    reg = MetricsRegistry()
    a = EngineStats(2, clk, reg=reg)
    b = EngineStats(2, clk, reg=reg)
    for _ in range(8):
        a.on_token()
    for _ in range(4):
        b.on_token()
    clk.advance(2.0)
    report = health_report(
        engine_snapshots=[a.snapshot(), b.snapshot()])
    # summed across concurrent engines (4 + 2), same scope as the
    # cross-engine slo_violations totals beside it
    assert report["serve"]["goodput_tokens_per_s"] == pytest.approx(6.0)
    assert len(report["serve"]["engines"]) == 2


def test_graph_runner_feeds_watchdog_and_health_report():
    """End to end over the real instrumentation site: graph-mode
    training beats the watchdog (replays feed step times, the compile
    dispatch is liveness-only) and the health report carries the XLA
    step flops with an honest nan MFU on CPU."""
    import numpy as np

    from singa_tpu import device, opt, tensor
    from singa_tpu.models.mlp import MLP

    wd = monitor.start(watchdog_timeout_s=600.0, clock=FakeClock(),
                       thread=False, dump_on_hang=False)
    try:
        dev = device.create_tpu_device(0)
        dev.SetRandSeed(0)
        m = MLP(data_size=8, perceptron_size=4, num_classes=3)
        m.set_optimizer(opt.SGD(lr=0.05))
        rng = np.random.RandomState(0)
        x = tensor.from_numpy(rng.randn(4, 8).astype(np.float32), dev)
        y = tensor.from_numpy(
            rng.randint(0, 3, (4,)).astype(np.int32), dev)
        m.compile([x], is_train=True, use_graph=True)
        before = observe.registry().histogram(
            "train.step_time", process=wd._process).count
        m(x, y)  # compile: heartbeat, but no step-time sample
        m(x, y)  # replay
        m(x, y)  # replay
        assert wd.summary()["sources"]["train"]["beats"] >= 3
        after = observe.registry().histogram(
            "train.step_time", process=wd._process).count
        assert after - before == 2
        report = health_report()
        assert report["train"]["step_flops"] > 0  # XLA cost table
        assert math.isnan(report["train"]["mfu"])  # CPU backend
        assert report["watchdog"]["hangs"] == 0
    finally:
        monitor.stop()


def test_module_heartbeat_routes_to_started_watchdog():
    clk = FakeClock()
    wd = monitor.start(watchdog_timeout_s=5.0, clock=clk, thread=False,
                       dump_on_hang=False)
    try:
        assert monitor.start() is wd  # idempotent while running
        monitor.heartbeat("serve", step_time=0.02)
        clk.advance(6.0)
        assert wd.check() == ["serve"] or wd.hangs >= 1
    finally:
        monitor.stop()
