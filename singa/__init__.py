"""``singa`` — drop-in import alias for :mod:`singa_tpu`.

The reference framework is imported as ``from singa import tensor,
device, opt, autograd, layer, model, sonnx``.  This alias makes those
lines — and any-depth forms like ``import singa.io.onnx_pb`` — resolve
to the SAME module objects as ``singa_tpu`` (a meta-path finder aliases
``singa.*`` onto ``singa_tpu.*`` in sys.modules; no re-export stubs, no
second execution), so isinstance checks and module-level state behave
as one package.  A reference training script ports by changing only its
device-creation line, and even that is optional: singa_tpu.device
aliases ``create_cuda_gpu(_on)`` to the TPU device for source compat.
"""

import importlib
import importlib.abc
import importlib.util
import sys

import singa_tpu as _st

__version__ = _st.__version__


class _AliasLoader(importlib.abc.Loader):
    """Hands the already-imported singa_tpu module object to the import
    system instead of executing the file a second time."""

    def __init__(self, mod):
        self._mod = mod
        self._real_spec = mod.__spec__

    def create_module(self, spec):
        return self._mod

    def exec_module(self, module):
        # already executed under its singa_tpu.* name; the import system
        # just overwrote module.__spec__ with the singa.* alias spec —
        # restore the original so the shared module object keeps its
        # singa_tpu identity (relative imports check
        # __package__ == __spec__.parent; reload/spec-keyed tooling use
        # __spec__.name).  sys.modules keeps the alias entry regardless.
        module.__spec__ = self._real_spec


class _AliasFinder(importlib.abc.MetaPathFinder):
    def find_spec(self, fullname, path=None, target=None):
        if not fullname.startswith("singa."):
            return None
        real = "singa_tpu." + fullname[len("singa."):]
        try:
            exists = importlib.util.find_spec(real) is not None
        except ModuleNotFoundError:
            exists = False  # a parent package doesn't exist
        if not exists:
            return None
        # the module exists: a failure HERE is a real bug inside it and
        # must propagate with its own traceback, not be masked as
        # "No module named singa.X"
        mod = importlib.import_module(real)
        spec = importlib.util.spec_from_loader(fullname, _AliasLoader(mod))
        if getattr(mod, "__path__", None) is not None:
            spec.submodule_search_locations = list(mod.__path__)
        return spec


sys.meta_path.insert(0, _AliasFinder())


def __getattr__(name):
    # serves `from singa import tensor` lazily; routes through the
    # finder so sys.modules['singa.tensor'] is the singa_tpu module
    if name.startswith("_"):
        raise AttributeError(name)
    try:
        return importlib.import_module(f"singa.{name}")
    except ModuleNotFoundError as e:
        # PEP 562: missing attributes must raise AttributeError so
        # hasattr()/getattr(default) keep working — but only translate
        # "module does not exist"; real failures inside an existing
        # module propagate from the finder above
        if e.name in (f"singa.{name}", f"singa_tpu.{name}"):
            raise AttributeError(name) from None
        raise


def __dir__():
    import pkgutil

    subs = [m.name for m in pkgutil.iter_modules(_st.__path__)]
    return sorted(set(globals()) | set(subs))
