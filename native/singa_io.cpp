// Native IO runtime for singa_tpu (reference parity: src/io/ BinFile
// reader/writer + src/utils/safe_queue.h, unverified — SURVEY.md §2.1
// "IO: readers/writers" and "Utils").  The reference implements its
// record store and data-loading queue in C++; this is the TPU-stack
// equivalent, exposed to Python over a C ABI via ctypes (no pybind11 in
// this image).
//
// Components:
//   * BinFile record store: append-only [u32 keylen][key][u64 vallen]
//     [val][u32 crc32-of-val] records behind an 8-byte magic+version
//     header.  Used by snapshot.py as the checkpoint container.
//   * PrefetchQueue: a fixed-capacity MPMC blocking ring buffer with a
//     pool of loader threads pulling record indices and materializing
//     value blobs, so the Python training loop overlaps host IO with
//     device steps (the reference's safe_queue + decoder threads).
//
// Build: make -C native   (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <string>
#include <vector>
#include <mutex>
#include <condition_variable>
#include <thread>
#include <atomic>

namespace {

constexpr uint64_t kMagic = 0x314F49414754534eULL;  // "NSTGAIO1" LE

uint32_t crc32(const uint8_t* data, size_t n) {
  static uint32_t table[256];
  static bool init = false;
  if (!init) {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = c & 1 ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    init = true;
  }
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; i++) c = table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Record {
  std::string key;
  std::vector<uint8_t> val;
};

struct BinReader {
  FILE* f = nullptr;
  std::vector<std::pair<std::string, std::pair<uint64_t, uint64_t>>> index;
  // key -> (offset of value, length)
};

struct BinWriter {
  FILE* f = nullptr;
};

struct PrefetchQueue {
  std::vector<Record> ring;
  size_t cap = 0, head = 0, tail = 0, count = 0;
  std::mutex mu;
  std::condition_variable not_full, not_empty;
  std::atomic<bool> closed{false};
};

}  // namespace

extern "C" {

// ---------------------------------------------------------------- writer --
void* binfile_writer_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  if (fwrite(&kMagic, 8, 1, f) != 1) { fclose(f); return nullptr; }
  auto* w = new BinWriter();
  w->f = f;
  return w;
}

int binfile_writer_put(void* hw, const char* key, const uint8_t* val,
                       uint64_t len) {
  auto* w = static_cast<BinWriter*>(hw);
  if (!w || !w->f) return -1;
  uint32_t klen = static_cast<uint32_t>(strlen(key));
  uint32_t crc = crc32(val, len);
  if (fwrite(&klen, 4, 1, w->f) != 1) return -1;
  if (fwrite(key, 1, klen, w->f) != klen) return -1;
  if (fwrite(&len, 8, 1, w->f) != 1) return -1;
  if (len && fwrite(val, 1, len, w->f) != len) return -1;
  if (fwrite(&crc, 4, 1, w->f) != 1) return -1;
  return 0;
}

int binfile_writer_close(void* hw) {
  auto* w = static_cast<BinWriter*>(hw);
  if (!w) return -1;
  int rc = 0;
  if (w->f) rc = fclose(w->f);
  delete w;
  return rc;
}

// ---------------------------------------------------------------- reader --
void* binfile_reader_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  uint64_t magic = 0;
  if (fread(&magic, 8, 1, f) != 1 || magic != kMagic) {
    fclose(f);
    return nullptr;
  }
  auto* r = new BinReader();
  r->f = f;
  // scan the index
  while (true) {
    uint32_t klen;
    if (fread(&klen, 4, 1, f) != 1) break;
    std::string key(klen, '\0');
    if (fread(key.data(), 1, klen, f) != klen) break;
    uint64_t vlen;
    if (fread(&vlen, 8, 1, f) != 1) break;
    uint64_t off = static_cast<uint64_t>(ftell(f));
    if (fseek(f, static_cast<long>(vlen + 4), SEEK_CUR) != 0) break;
    r->index.emplace_back(key, std::make_pair(off, vlen));
  }
  return r;
}

int64_t binfile_reader_count(void* hr) {
  auto* r = static_cast<BinReader*>(hr);
  return r ? static_cast<int64_t>(r->index.size()) : -1;
}

// key of record i; returns length or -1
int64_t binfile_reader_key(void* hr, int64_t i, char* out, int64_t cap) {
  auto* r = static_cast<BinReader*>(hr);
  if (!r || i < 0 || i >= (int64_t)r->index.size()) return -1;
  const auto& k = r->index[i].first;
  if ((int64_t)k.size() + 1 > cap) return -1;
  memcpy(out, k.data(), k.size());
  out[k.size()] = '\0';
  return static_cast<int64_t>(k.size());
}

int64_t binfile_reader_val_len(void* hr, int64_t i) {
  auto* r = static_cast<BinReader*>(hr);
  if (!r || i < 0 || i >= (int64_t)r->index.size()) return -1;
  return static_cast<int64_t>(r->index[i].second.second);
}

// copy record i's value into out (cap bytes); verifies crc; returns len or -1
int64_t binfile_reader_val(void* hr, int64_t i, uint8_t* out, int64_t cap) {
  auto* r = static_cast<BinReader*>(hr);
  if (!r || i < 0 || i >= (int64_t)r->index.size()) return -1;
  auto [off, len] = r->index[i].second;
  if ((int64_t)len > cap) return -1;
  if (fseek(r->f, static_cast<long>(off), SEEK_SET) != 0) return -1;
  if (len && fread(out, 1, len, r->f) != len) return -1;
  uint32_t crc_stored;
  if (fread(&crc_stored, 4, 1, r->f) != 1) return -1;
  if (crc32(out, len) != crc_stored) return -2;  // corruption
  return static_cast<int64_t>(len);
}

int binfile_reader_close(void* hr) {
  auto* r = static_cast<BinReader*>(hr);
  if (!r) return -1;
  if (r->f) fclose(r->f);
  delete r;
  return 0;
}

// ------------------------------------------------------------- prefetch --
void* prefetch_queue_new(int64_t capacity) {
  auto* q = new PrefetchQueue();
  q->cap = static_cast<size_t>(capacity);
  q->ring.resize(q->cap);
  return q;
}

// producer: blocks while full; returns 0, or -1 if closed
int prefetch_queue_put(void* hq, const char* key, const uint8_t* val,
                       uint64_t len) {
  auto* q = static_cast<PrefetchQueue*>(hq);
  std::unique_lock<std::mutex> lk(q->mu);
  q->not_full.wait(lk, [&] { return q->count < q->cap || q->closed; });
  if (q->closed) return -1;
  Record& slot = q->ring[q->tail];
  slot.key = key;
  slot.val.assign(val, val + len);
  q->tail = (q->tail + 1) % q->cap;
  q->count++;
  q->not_empty.notify_one();
  return 0;
}

// consumer: blocks while empty; returns value length, -1 when closed+drained
int64_t prefetch_queue_get(void* hq, char* key_out, int64_t key_cap,
                           uint8_t* val_out, int64_t val_cap) {
  auto* q = static_cast<PrefetchQueue*>(hq);
  std::unique_lock<std::mutex> lk(q->mu);
  q->not_empty.wait(lk, [&] { return q->count > 0 || q->closed; });
  if (q->count == 0) return -1;
  Record& slot = q->ring[q->head];
  if ((int64_t)slot.key.size() + 1 > key_cap ||
      (int64_t)slot.val.size() > val_cap)
    return -2;
  memcpy(key_out, slot.key.data(), slot.key.size());
  key_out[slot.key.size()] = '\0';
  memcpy(val_out, slot.val.data(), slot.val.size());
  int64_t n = static_cast<int64_t>(slot.val.size());
  slot.val.clear();
  slot.val.shrink_to_fit();
  q->head = (q->head + 1) % q->cap;
  q->count--;
  q->not_full.notify_one();
  return n;
}

int64_t prefetch_queue_size(void* hq) {
  auto* q = static_cast<PrefetchQueue*>(hq);
  std::lock_guard<std::mutex> lk(q->mu);
  return static_cast<int64_t>(q->count);
}

void prefetch_queue_close(void* hq) {
  auto* q = static_cast<PrefetchQueue*>(hq);
  {
    std::lock_guard<std::mutex> lk(q->mu);
    q->closed = true;
  }
  q->not_full.notify_all();
  q->not_empty.notify_all();
}

void prefetch_queue_free(void* hq) {
  delete static_cast<PrefetchQueue*>(hq);
}

// ---------------------------------------------------------------------------
// Batch image augmentation (reference parity: src/io/transformer.cc does
// crop/flip/normalize in C++ with OpenCV, unverified — SURVEY.md §2.1
// "IO: readers/writers" image transformer row).  One fused pass per
// image: random crop to (ph, pw) + coin-flip horizontal mirror (train)
// or center crop (eval), uint8 HWC -> normalized float32 CHW, threaded
// over the batch.  Deterministic per (seed, image index).
// ---------------------------------------------------------------------------

static inline uint64_t splitmix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

int augment_batch(const uint8_t* src, int64_t n, int64_t h, int64_t w,
                  int64_t c, int64_t ph, int64_t pw, const float* mean,
                  const float* stddev, uint64_t seed, int train,
                  int64_t threads, float* dst) {
  if (ph > h || pw > w || c <= 0 || n < 0) return -1;
  std::vector<float> scale(c), bias(c);
  for (int64_t ch = 0; ch < c; ch++) {
    float s = stddev ? stddev[ch] : 1.0f;
    float m = mean ? mean[ch] : 0.0f;
    scale[ch] = 1.0f / (255.0f * s);
    bias[ch] = -m / s;
  }
  if (threads <= 0) {
    threads = static_cast<int64_t>(std::thread::hardware_concurrency());
    if (threads <= 0) threads = 4;
  }
  if (threads > n) threads = n > 0 ? n : 1;

  auto worker = [&](int64_t t0) {
    for (int64_t i = t0; i < n; i += threads) {
      uint64_t r = splitmix64(seed ^ (0xA5A5A5A5ULL + (uint64_t)i));
      int64_t y, x;
      bool mirror = false;
      if (train) {
        y = (h == ph) ? 0 : (int64_t)(r % (uint64_t)(h - ph + 1));
        r = splitmix64(r);
        x = (w == pw) ? 0 : (int64_t)(r % (uint64_t)(w - pw + 1));
        r = splitmix64(r);
        mirror = (r & 1ULL) != 0;
      } else {
        y = (h - ph) / 2;
        x = (w - pw) / 2;
      }
      const uint8_t* im = src + (size_t)i * h * w * c;
      for (int64_t ch = 0; ch < c; ch++) {
        float sc = scale[ch], bi = bias[ch];
        float* out = dst + (((size_t)i * c + ch) * ph) * pw;
        for (int64_t yy = 0; yy < ph; yy++) {
          const uint8_t* row = im + ((y + yy) * w + x) * c + ch;
          float* orow = out + yy * pw;
          if (mirror) {
            for (int64_t xx = 0; xx < pw; xx++)
              orow[xx] = (float)row[(pw - 1 - xx) * c] * sc + bi;
          } else {
            for (int64_t xx = 0; xx < pw; xx++)
              orow[xx] = (float)row[xx * c] * sc + bi;
          }
        }
      }
    }
  };
  std::vector<std::thread> pool;
  for (int64_t t = 1; t < threads; t++) pool.emplace_back(worker, t);
  worker(0);
  for (auto& th : pool) th.join();
  return 0;
}

}  // extern "C"
