"""Perf-trend guard: diff a FRESH bench_serve report against the
committed baseline (BENCH_SERVE.json) so a PR that quietly regresses
the serving engine fails loudly in CI instead of surfacing three PRs
later as "when did decode get slow?".

Two classes of check, deliberately different in temperament:

* **strict** (exit 1): correctness invariants that must never drift —
  byte parity with the static-batch reference (core run AND the
  profiled ``--step-anatomy`` run), and ZERO runtime recompiles in
  every section of the fresh report that carries a ``recompiles``
  census (the jit-cache pin: observability and new features must not
  push anything into jitted code).
* **advisory** (exit 0, loud warning): throughput and latency trends —
  ``engine.tokens_per_s`` and ``engine.ttft_p50_s`` vs the committed
  numbers.  CI runners are noisy shared CPU boxes, so the tolerances
  are generous (default: flag < 0.5x throughput or > 2.0x TTFT) and a
  trip is a WARNING in the verdict JSON, not a failure — the committed
  baseline is re-recorded by the same PR that legitimately moves it.

Usage::

    python bench_trend.py --fresh /tmp/bench_serve_ci.json
    python bench_trend.py            # runs bench_serve itself

``--fresh`` reuses a report another CI step already produced (the
serve gate's), so the trend check costs one JSON diff, not a second
multi-minute bench run — the fast-lane budget discipline.
"""
from __future__ import annotations

import argparse
import json
import subprocess
import sys
import tempfile


def _walk_recompiles(node, path=""):
    """Every ``recompiles`` census in the report tree, with its
    section path — new sections are gated automatically."""
    if isinstance(node, dict):
        for k, v in node.items():
            sub = f"{path}.{k}" if path else k
            if k == "recompiles":
                yield path or "<root>", v
            else:
                yield from _walk_recompiles(v, sub)
    elif isinstance(node, list):
        for i, v in enumerate(node):
            yield from _walk_recompiles(v, f"{path}[{i}]")


def _run_fresh(out_path):
    cmd = [sys.executable, "bench_serve.py", "--step-anatomy"]
    with open(out_path, "w") as fh:
        subprocess.run(cmd, stdout=fh, check=True)


def trend(baseline, fresh, tput_floor=0.5, ttft_ceil=2.0):
    """The diff.  Returns the verdict dict; ``verdict["passed"]`` is
    the strict gate (advisory trips never clear it)."""
    strict, advisory = [], []

    # -- strict: parity ----------------------------------------------
    if fresh.get("parity") is not True:
        strict.append("core parity is not True in the fresh report")
    sa = fresh.get("step_anatomy")
    if sa is not None and sa.get("parity") is not True:
        strict.append("step-anatomy parity is not True (profiler ON"
                      " changed tokens)")

    # -- strict: the recompile pin, every census in the report -------
    for where, n in _walk_recompiles(fresh):
        if n not in (None, 0):
            strict.append(f"recompiles={n} in section {where!r}"
                          " (jit-cache pin broken)")

    # -- advisory: throughput / latency trend ------------------------
    comp = {}
    be, fe = baseline.get("engine", {}), fresh.get("engine", {})
    b_tps, f_tps = be.get("tokens_per_s"), fe.get("tokens_per_s")
    if b_tps and f_tps:
        ratio = f_tps / b_tps
        comp["tokens_per_s"] = {"baseline": round(b_tps, 1),
                                "fresh": round(f_tps, 1),
                                "ratio": round(ratio, 3)}
        if ratio < tput_floor:
            advisory.append(
                f"throughput {f_tps:.0f} tok/s is {ratio:.2f}x the"
                f" committed {b_tps:.0f} (floor {tput_floor}x)")
    b_tt, f_tt = be.get("ttft_p50_s"), fe.get("ttft_p50_s")
    if b_tt and f_tt:
        ratio = f_tt / b_tt
        comp["ttft_p50_s"] = {"baseline": round(b_tt, 4),
                              "fresh": round(f_tt, 4),
                              "ratio": round(ratio, 3)}
        if ratio > ttft_ceil:
            advisory.append(
                f"TTFT p50 {f_tt * 1e3:.1f}ms is {ratio:.2f}x the"
                f" committed {b_tt * 1e3:.1f}ms (ceiling"
                f" {ttft_ceil}x)")
    bsa = baseline.get("step_anatomy")
    if bsa and sa and bsa.get("bubble_frac") and sa.get("bubble_frac"):
        comp["bubble_frac"] = {"baseline": round(bsa["bubble_frac"], 4),
                               "fresh": round(sa["bubble_frac"], 4)}

    return {"bench": "serve_trend", "schema": "singa_tpu.trend/1",
            "strict_failures": strict, "advisory_warnings": advisory,
            "comparison": comp, "passed": not strict}


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", default="BENCH_SERVE.json",
                    help="committed reference report")
    ap.add_argument("--fresh", default=None,
                    help="existing fresh report to diff (skips the"
                         " bench run)")
    ap.add_argument("--tput-floor", type=float, default=0.5,
                    help="advisory: flag fresh/baseline tokens/s"
                         " below this ratio")
    ap.add_argument("--ttft-ceil", type=float, default=2.0,
                    help="advisory: flag fresh/baseline TTFT p50"
                         " above this ratio")
    args = ap.parse_args(argv)

    with open(args.baseline) as fh:
        baseline = json.load(fh)
    if args.fresh is None:
        tmp = tempfile.NamedTemporaryFile(
            suffix=".json", prefix="bench_trend_", delete=False)
        tmp.close()
        _run_fresh(tmp.name)
        args.fresh = tmp.name
    with open(args.fresh) as fh:
        fresh = json.load(fh)

    verdict = trend(baseline, fresh, tput_floor=args.tput_floor,
                    ttft_ceil=args.ttft_ceil)
    print(json.dumps(verdict, indent=1))
    for w in verdict["advisory_warnings"]:
        print(f"bench_trend ADVISORY: {w}", file=sys.stderr)
    for f in verdict["strict_failures"]:
        print(f"bench_trend STRICT FAILURE: {f}", file=sys.stderr)
    return 0 if verdict["passed"] else 1


if __name__ == "__main__":
    sys.exit(main())
