"""Real-chip Mosaic smoke for the flash-attention kernel paths.

The pytest suite runs on the forced CPU backend (tests/conftest.py)
where Pallas executes in interpret mode — so a kernel that passes CI
can still fail Mosaic compilation on hardware (this environment has
produced Mosaic-only failures before: oversized tiles surface as
HTTP 500 tpu_compile_helper errors).  This script exercises every
kernel entry the wrapper can select ON THE REAL CHIP and records the
result in TPU_SMOKE.json (round-3 verdict, weak #5 / item 1c):

  1. pad-to-block wrapper: unaligned S=1537, causal, fwd + grad
  2. general (B,1,S,S) mask streamed as kernel tiles, fwd + grad
  3. padded head dim D=192 (shrunken block budget)
  4. the flash kernel INSIDE shard_map on a real 1-device ('seq') mesh
     (manual-mode Mosaic, the ring-attention composition), fwd + grad
  5. per-head (1,H,S,S) ALiBi-layout mask (modulo index map)

    python tpu_smoke.py            # writes TPU_SMOKE.json
"""

import json
import math
import os
import time

import numpy as np

NEG_INF = -1e30
# f32 matmuls ride the MXU as bf16 passes at DEFAULT precision, so the
# oracle comparison tolerance is bf16-scale, not f32-scale
ATOL = 1e-2


def _ref(q, k, v, mask=None, causal=False):
    import jax
    import jax.numpy as jnp

    d = q.shape[-1]
    sc = jnp.einsum("bhsd,bhtd->bhst", q.astype(jnp.float32),
                    k.astype(jnp.float32)) / math.sqrt(d)
    if mask is not None:
        sc = sc + mask
    if causal:
        s = q.shape[2]
        cm = jnp.tril(jnp.ones((s, s), bool))
        sc = jnp.where(cm[None, None], sc, NEG_INF)
    return jnp.einsum("bhst,bhtd->bhsd", jax.nn.softmax(sc, -1),
                      v.astype(jnp.float32)).astype(q.dtype)


def main():
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from singa_tpu.ops.pallas.flash_attention import flash_attention
    from singa_tpu.parallel.ring_attention import ring_self_attention

    backend = jax.default_backend()
    assert backend != "cpu", (
        "tpu_smoke must run on the TPU backend (CPU runs interpret "
        "mode, which is what this script exists to go beyond)")

    rng = np.random.RandomState(0)

    def qkv(b, h, s, d):
        return tuple(jnp.asarray(rng.randn(b, h, s, d).astype(np.float32))
                     for _ in range(3))

    checks = []

    def check(name, fn):
        t0 = time.time()
        try:
            fn()
            checks.append({"name": name, "ok": True,
                           "seconds": round(time.time() - t0, 1)})
        except Exception as e:  # record, keep sweeping
            checks.append({"name": name, "ok": False,
                           "error": f"{type(e).__name__}: {e}"[:300]})

    def c1():
        q, k, v = qkv(1, 2, 1537, 64)
        o = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal=True))(q, k, v)
        np.testing.assert_allclose(
            np.asarray(o), np.asarray(_ref(q, k, v, causal=True)),
            atol=ATOL)
        g = jax.jit(jax.grad(lambda q: jnp.sum(
            flash_attention(q, k, v, causal=True) ** 2)))(q)
        gr = jax.grad(lambda q: jnp.sum(_ref(q, k, v, causal=True) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                                   atol=5e-2, rtol=5e-2)

    def c2():
        q, k, v = qkv(2, 2, 1024, 64)
        mask = jnp.asarray(np.where(
            rng.rand(2, 1, 1024, 1024) > 0.2, 0.0, -1e9)
            .astype(np.float32))
        o = jax.jit(flash_attention)(q, k, v, mask)
        np.testing.assert_allclose(
            np.asarray(o), np.asarray(_ref(q, k, v, mask)), atol=ATOL)
        g = jax.jit(jax.grad(lambda q: jnp.sum(
            flash_attention(q, k, v, mask) ** 2)))(q)
        gr = jax.grad(lambda q: jnp.sum(_ref(q, k, v, mask) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                                   atol=5e-2, rtol=5e-2)

    def c3():
        q, k, v = qkv(1, 2, 512, 192)
        o = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal=True))(q, k, v)
        np.testing.assert_allclose(
            np.asarray(o), np.asarray(_ref(q, k, v, causal=True)),
            atol=ATOL)

    def c4():
        q, k, v = qkv(1, 2, 2048, 64)
        mesh = Mesh(np.asarray(jax.devices()[:1]), ("seq",))
        spec = P(None, None, "seq", None)
        f = jax.jit(jax.shard_map(
            lambda q_, k_, v_: ring_self_attention(
                q_, k_, v_, "seq", causal=True, use_flash=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False))
        o = f(q, k, v)
        np.testing.assert_allclose(
            np.asarray(o), np.asarray(_ref(q, k, v, causal=True)),
            atol=ATOL)
        g = jax.jit(jax.grad(lambda q: jnp.sum(f(q, k, v) ** 2)))(q)
        gr = jax.grad(lambda q: jnp.sum(_ref(q, k, v, causal=True) ** 2))(q)
        np.testing.assert_allclose(np.asarray(g), np.asarray(gr),
                                   atol=5e-2, rtol=5e-2)

    def c5():
        q, k, v = qkv(2, 4, 512, 64)
        alibi = jnp.asarray(
            rng.randn(1, 4, 512, 512).astype(np.float32) * 0.1)
        o = jax.jit(flash_attention)(q, k, v, alibi)
        np.testing.assert_allclose(
            np.asarray(o), np.asarray(_ref(q, k, v, alibi)), atol=ATOL)

    check("pad_to_block_unaligned_S1537_causal_fwd_grad", c1)
    check("general_mask_B1SS_kernel_tiles_fwd_grad", c2)
    check("wide_head_D192_padded", c3)
    check("shard_map_1dev_mesh_ring_flash_fwd_grad", c4)
    check("per_head_alibi_mask_1HSS", c5)

    out = {
        "backend": backend,
        "device_kind": jax.devices()[0].device_kind,
        "ok": all(c["ok"] for c in checks),
        "checks": checks,
        "note": ("Mosaic-compiled kernel paths validated on the real "
                 "chip; the pytest suite covers the same paths in "
                 "interpret mode on the CPU mesh"),
    }
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "TPU_SMOKE.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    raise SystemExit(0 if out["ok"] else 1)


if __name__ == "__main__":
    main()
