"""Trace-driven soak harness: bursty Poisson traffic against an
AUTOSCALED fleet, judged by the closed telemetry→action loop.

Every other bench replays a fixed request palette; production claims
need a workload GENERATOR (ROADMAP item 5a).  This harness drives:

* **arrivals** — a non-homogeneous Poisson process (thinning over a
  warm / burst / cool rate profile — the compressed diurnal-plus-
  incident shape), with a long-tail prompt-length mix, a priority
  mix, and session CONTINUATIONS: a fraction of completed requests
  re-arrive after an exponential think-time with the whole prior
  conversation as the next prompt — the multi-turn traffic shape;
* **the fleet under test** — paged engine replicas behind
  ``ServeFleet``, scaled between min/max replicas by
  ``serve.autoscale.Autoscaler`` off the Router signals plus the
  multi-window burn-rate state of an installed
  ``observe.slo.SLOPolicy`` (windows scaled to the soak duration so
  a CI-minutes run exercises the same machinery an ``--hours`` run
  does);
* **the verdict** — SOAK.json, gated IN the harness: the burst must
  fire a burn-rate alert, the autoscaler must scale up, the alert
  must clear after the burst, the fleet must drain back down
  (``scaling_events`` carries every decision with its signal
  snapshot), NO request may wedge or vanish (typed rejections are
  counted, never lost), zero KV blocks may leak, replica spawns must
  cost ZERO runtime recompiles (module-wide twin caches), and the
  request ledger's why_slow attribution must be present with phase
  fractions summing to 1.

Calibration first: a throwaway engine measures unloaded TTFT and
service rate on THIS box, then the SLO target, arrival rates, and
alert windows are derived from the measurements — the same harness
is honest on a laptop, a CI runner, or a chip host.

Usage::

    python bench_soak.py --seconds 60          # CI scale
    python bench_soak.py --hours 4             # soak scale
"""

import argparse
import heapq
import itertools
import json
import time

import numpy as np

# long-tail prompt lengths: mostly chat-short, a document tail
_PLEN_PALETTE = [4, 6, 8, 12, 16, 24, 48, 64]
_PLEN_WEIGHTS = [0.20, 0.20, 0.15, 0.15, 0.10, 0.08, 0.07, 0.05]
_NEW_PALETTE = [2, 3, 4, 6, 8, 12]
_NEW_WEIGHTS = [0.22, 0.22, 0.22, 0.14, 0.10, 0.10]
_PRIORITIES = [0, 1, 2]
_PRIO_WEIGHTS = [0.7, 0.2, 0.1]


class SoakTrace:
    """Seeded arrival generator: warm/burst/cool Poisson thinning plus
    follow-up (continuation) scheduling."""

    def __init__(self, seconds, base_rate, burst_rate, seed=0,
                 vocab=256, burst_frac=(0.25, 0.60),
                 continue_prob=0.25, think_mean_s=None):
        self.T = float(seconds)
        self.base_rate = float(base_rate)
        self.burst_rate = float(burst_rate)
        self.burst = (burst_frac[0] * self.T, burst_frac[1] * self.T)
        self.continue_prob = continue_prob
        self.think_mean_s = (think_mean_s if think_mean_s is not None
                             else max(1.0, self.T / 30.0))
        self.vocab = vocab
        self.rng = np.random.RandomState(seed)

    def rate(self, t) -> float:
        lo, hi = self.burst
        return self.burst_rate if lo <= t < hi else self.base_rate

    def arrivals(self):
        """[(t, kind_dict)] for the whole run — Poisson thinning
        against the max rate, so the burst edge is exact."""
        out, t, rmax = [], 0.0, max(self.base_rate, self.burst_rate)
        while True:
            t += float(self.rng.exponential(1.0 / rmax))
            if t >= self.T:
                return out
            if self.rng.rand() <= self.rate(t) / rmax:
                out.append((t, self.fresh_request()))

    def fresh_request(self) -> dict:
        plen = int(self.rng.choice(_PLEN_PALETTE, p=_PLEN_WEIGHTS))
        return {
            "prompt": self.rng.randint(
                0, self.vocab, plen).astype(np.int32),
            "n_new": int(self.rng.choice(_NEW_PALETTE,
                                         p=_NEW_WEIGHTS)),
            "priority": int(self.rng.choice(_PRIORITIES,
                                            p=_PRIO_WEIGHTS)),
            "turn": 1,
        }

    def maybe_continue(self, spec, result, now_t, max_prompt=96):
        """Session continuation: with probability ``continue_prob``,
        the caller "reads the answer" for an exponential think-time
        and re-sends the WHOLE conversation plus a new user tail as
        the next turn's prompt (cold-but-realistic multi-turn
        traffic; prefix caching is a separate bench's subject)."""
        if spec["turn"] >= 3 or len(result.tokens) >= max_prompt:
            return None
        if self.rng.rand() >= self.continue_prob:
            return None
        tail = self.rng.randint(
            0, self.vocab, int(self.rng.randint(2, 7))).astype(np.int32)
        prompt = np.concatenate(
            [np.asarray(result.tokens, np.int32), tail])[-max_prompt:]
        due = now_t + float(self.rng.exponential(self.think_mean_s))
        return due, {
            "prompt": prompt,
            "n_new": int(self.rng.choice(_NEW_PALETTE,
                                         p=_NEW_WEIGHTS)),
            "priority": spec["priority"],
            "turn": spec["turn"] + 1,
        }


def _calibrate(m, max_slots, paged_cfg, max_prompt=96):
    """Measure unloaded TTFT p50 and service rate on a throwaway
    engine with the SAME statics the fleet replicas will use.  This
    doubles as the compile warmup — one admission per block-multiple
    prefill width the soak can ever produce, so the spawn-scoped
    recompile pin is never confused by a first-seen workload shape."""
    from singa_tpu.serve import GenerationRequest

    rng = np.random.RandomState(99)
    eng = m.serve(max_slots=max_slots, paged=paged_cfg)
    bs = paged_cfg.block_size
    # width sweep: plen = k*bs + 1 covers every admission width in
    # [bs, max_prompt+bs].  Each width runs once as a PAIRED
    # admission and once alone, so the batched-prefill executables
    # compile for every (rows, width) shape the soak can schedule —
    # a mid-run compile would otherwise masquerade as a 1s+ prefill
    # in the latency record
    plens = [k * bs + 1 for k in range(0, max_prompt // bs + 1)]
    for p in plens:
        hs = [eng.submit(GenerationRequest(
            rng.randint(0, 256, p).astype(np.int32),
            max_new_tokens=2)) for _ in range(min(2, max_slots))]
        while eng.pending:
            eng.step()
        for h in hs:
            h.result()
    for p in plens:
        h = eng.submit(GenerationRequest(
            rng.randint(0, 256, p).astype(np.int32), max_new_tokens=2))
        while eng.pending:
            eng.step()
        h.result()
    # sequential: unloaded TTFT (no queue wait) — measured from the
    # probe results themselves, NOT the engine-lifetime stats (those
    # include the width sweep's compile-stalled admissions)
    probe_ttfts = []
    for _ in range(6):
        p = rng.randint(0, 256, 12).astype(np.int32)
        h = eng.submit(GenerationRequest(p, max_new_tokens=4))
        while not h.done():
            eng.step()
        probe_ttfts.append(h.result().ttft)
    probe_ttfts.sort()
    ttft_p50 = probe_ttfts[len(probe_ttfts) // 2]
    # saturated: service rate per replica
    t0 = time.perf_counter()
    hs = []
    for _ in range(16):
        plen = int(rng.choice(_PLEN_PALETTE, p=_PLEN_WEIGHTS))
        p = rng.randint(0, 256, plen).astype(np.int32)
        n = int(rng.choice(_NEW_PALETTE, p=_NEW_WEIGHTS))
        hs.append(eng.submit(GenerationRequest(p, max_new_tokens=n)))
    while eng.pending:
        eng.step()
    wall = time.perf_counter() - t0
    for h in hs:
        h.result()
    eng.close()
    return ttft_p50, 16.0 / wall


def run_soak(seconds, seed=0, min_replicas=1, max_replicas=3,
             max_slots=2):
    from bench_serve import _serve_jit_cache_size
    from singa_tpu import observe
    from singa_tpu.observe.slo import BurnRule, SLOPolicy
    from singa_tpu.serve import (AutoscaleConfig, Autoscaler,
                                 GenerationRequest, LoadShedError,
                                 PagedConfig, QueueFullError,
                                 ServeFleet)
    from singa_tpu.utils.metrics import percentile

    from singa_tpu import tensor
    from singa_tpu.models.gpt2 import GPT2Config, GPT2LMHead

    cfg = GPT2Config.tiny(dropout=0.0)
    m = GPT2LMHead(cfg)
    m.compile([tensor.from_numpy(np.zeros((1, 16), np.int32))],
              is_train=False, use_graph=False)
    paged_cfg = PagedConfig(block_size=8, num_blocks=64)

    ttft_p50, svc_rate = _calibrate(m, max_slots, paged_cfg)
    jit0 = _serve_jit_cache_size()

    # derived knobs.  Open-loop rates are CAPPED at absolute values —
    # on a fast box the backlog top-up (below) supplies the burst
    # pressure instead of a raw arrival flood, so request counts stay
    # bounded and back-pressure rejections stay incidental.  The SLO
    # target is placed where a held backlog of ``burst_depth``
    # requests must violate it (wait ≈ depth / service rate) but the
    # unloaded warm phase comfortably meets it — the same derivation
    # is honest at any box speed.  The BURN ALERT is deliberately the
    # leading scale-up signal: the queue-depth threshold is a deep
    # safety valve, so the soak proves the telemetry→alert→action
    # chain rather than the raw queue heuristic racing ahead of it.
    burst_depth = 12  # held queue depth per routable replica
    base_rate = min(0.5 * svc_rate * min_replicas, 6.0)
    burst_rate = min(3.0 * svc_rate * min_replicas, 20.0)
    slo_target = max(3.0 * ttft_p50,
                     min(0.15, burst_depth / (4.0 * svc_rate)))
    short_w = max(2.0, round(seconds / 30.0))
    long_w = max(2.0 * short_w, round(seconds / 12.0))
    budget_frac = 0.2
    threshold = 3.0  # fires when >60% of completions violate

    trace = SoakTrace(seconds, base_rate, burst_rate, seed=seed)
    arrivals = trace.arrivals()

    slo = observe.SLO(ttft_p99_s=slo_target)
    observe.requests.enable(capacity=8192)
    fleet = ServeFleet(m, replicas=min_replicas, max_slots=max_slots,
                       slo=slo, paged=paged_cfg)
    policy = SLOPolicy(
        slo, budget_frac=budget_frac, kinds=("ttft",),
        rules=(BurnRule("page", long_s=long_w, short_s=short_w,
                        threshold=threshold, clear_ratio=0.5),))
    scaler = Autoscaler(fleet, AutoscaleConfig(
        min_replicas=min_replicas, max_replicas=max_replicas,
        scale_up_cooldown_s=short_w,
        scale_down_cooldown_s=max(3.0, seconds / 15.0),
        # the queue threshold is a deep safety valve (the burn alert
        # should lead); occupancy is effectively off — a 2-slot
        # replica reads 1.0 whenever it is merely busy, so the
        # instantaneous sample carries no scale signal at this width
        queue_high=25.0, queue_low=0.75,
        occupancy_high=1.5, occupancy_low=0.6,
        blocks_high=0.85), slo_policy=policy)

    # burst realism vs box variance: open-loop Poisson alone cannot
    # guarantee overload on an arbitrarily fast box (and would bury a
    # slow one), so the burst ALSO holds a sustained backlog — the
    # retry-storm shape of a real incident: whenever the fleet's
    # queues dip below ``burst_depth`` per routable replica inside
    # the burst window, extra arrivals top them back up.  Every
    # top-up is a normal request, counted separately.
    burst_topups = 0

    arr_i = 0                   # cursor into arrivals (time-sorted)
    followups = []              # continuation min-heap keyed on due t
    fu_seq = itertools.count()  # heap tie-break (specs don't compare)
    live = []                   # (spec, handle)
    finished = 0
    typed_failed = 0            # accepted, then rejected typed mid-run
    rejected = {"queue_full": 0, "shed": 0}
    continuations = 0
    submitted = 0

    def submit(spec):
        nonlocal submitted
        req = GenerationRequest(np.asarray(spec["prompt"], np.int32),
                                max_new_tokens=spec["n_new"],
                                priority=spec["priority"])
        try:
            h = fleet.submit(req)
        except QueueFullError:
            rejected["queue_full"] += 1
            return
        except LoadShedError:
            rejected["shed"] += 1
            return
        submitted += 1
        live.append((spec, h))

    t0 = time.monotonic()
    deadline = seconds * 2.0 + 60.0  # hard stop: a wedged soak fails
    peak_replicas = min_replicas
    next_poll = 0.0
    spawn_recompiles = 0 if jit0 is not None else None
    while True:
        el = time.monotonic() - t0
        while arr_i < len(arrivals) and arrivals[arr_i][0] <= el:
            submit(arrivals[arr_i][1])
            arr_i += 1
        while followups and followups[0][0] <= el:
            continuations += 1
            submit(heapq.heappop(followups)[2])
        if trace.burst[0] <= el < trace.burst[1]:
            views = fleet.load_views()
            routable = [v for v in views if not v["draining"]]
            depth = sum(v["queue_depth"] for v in routable)
            want = burst_depth * max(1, len(routable))
            while depth < want and burst_topups < 4000:
                burst_topups += 1
                depth += 1
                submit(trace.fresh_request())
        if fleet.pending:
            fleet.step()
        else:
            time.sleep(0.002)
        if el >= next_poll:
            # throttled control plane: the burn windows are seconds
            # wide, polling at 10 Hz loses nothing
            next_poll = el + 0.1
            policy.poll()
            j_pre = (_serve_jit_cache_size()
                     if spawn_recompiles is not None else None)
            ev = scaler.check()
            if ev is not None and ev["action"] == "scale_up" \
                    and j_pre is not None:
                # THE pin: a replica spawned mid-run must be a
                # compile-cache hit (module-wide twin/jit caches) —
                # any compile inside the scale-up action shows here
                spawn_recompiles += _serve_jit_cache_size() - j_pre
            peak_replicas = max(peak_replicas,
                                fleet.routable_replicas)
        # harvest completions; schedule think-time continuations
        still = []
        for spec, h in live:
            if not h.done():
                still.append((spec, h))
                continue
            try:
                r = h.result()
            except Exception:
                typed_failed += 1  # typed rejection, never lost
                continue
            finished += 1
            if el < seconds:
                fu = trace.maybe_continue(spec, r, el)
                if fu is not None and fu[0] < seconds:
                    heapq.heappush(
                        followups, (fu[0], next(fu_seq), fu[1]))
        live[:] = still
        if el >= seconds and arr_i >= len(arrivals) and not followups \
                and not fleet.pending and not live:
            # traffic is over: keep polling until the alert clears
            # and the fleet drains back down (or give up at deadline)
            policy.poll()
            scaler.check()
            done_down = (scaler.section()["scale_downs"] >= 1
                         or scaler.section()["scale_ups"] == 0)
            cleared = not policy.firing()
            if (cleared and done_down) or el >= deadline:
                break
            time.sleep(0.05)
        if el >= deadline:
            break
    wall = time.monotonic() - t0

    # final harvest: anything resolved after the last in-loop pass
    wedged = 0
    for spec, h in live:
        if not h.done():
            wedged += 1
            continue
        try:
            h.result()
            finished += 1
        except Exception:
            typed_failed += 1
    jit1 = _serve_jit_cache_size()
    leaked = 0
    for rep in fleet._replicas:
        eng = rep.sup.engine
        if not eng._closed and eng.paged_arena is not None:
            leaked += eng.paged_arena.blocks_used

    health = observe.health_report(include_registry=False)
    why = health["serve"]["why_slow"]
    alerts = policy.section()
    autoscale = scaler.section()
    snap = fleet.snapshot()

    report = {
        "bench": "soak",
        "schema": "singa_tpu.soak/1",
        "config": {
            "seconds": seconds,
            "seed": seed,
            "min_replicas": min_replicas,
            "max_replicas": max_replicas,
            "max_slots": max_slots,
            "calibrated": {"ttft_p50_unloaded_s": ttft_p50,
                           "service_rate_per_replica": svc_rate},
            "base_rate": base_rate,
            "burst_rate": burst_rate,
            "burst_window_s": list(trace.burst),
            "slo_ttft_p99_s": slo_target,
            "burn_windows_s": [short_w, long_w],
            "burn_threshold": threshold,
            "budget_frac": budget_frac,
        },
        "workload": {
            "arrivals": len(arrivals),
            "burst_topups": burst_topups,
            "burst_depth_target": burst_depth,
            "continuations": continuations,
            "prompt_len_p50": percentile(
                [len(s["prompt"]) for _, s in arrivals], 50),
            "prompt_len_p99": percentile(
                [len(s["prompt"]) for _, s in arrivals], 99),
        },
        "wall_s": wall,
        "requests": {
            "submitted": submitted,
            "completed": finished,
            "typed_failures": typed_failed,
            "rejected_at_submit": dict(rejected),
            "wedged": wedged,
            "lost": submitted - finished - typed_failed - wedged,
        },
        "slo_alerts": alerts,
        "autoscale": autoscale,
        "fleet": {
            "replicas_peak": peak_replicas,
            "replicas_final": snap["replicas_routable"],
            "replicas_retired": snap["replicas_retired"],
            "failovers": snap["failovers"],
        },
        "blocks_leaked": leaked,
        # the gated pin: jit-cache growth INSIDE scale-up actions —
        # a spawned replica must be a compile-cache hit
        "recompiles": spawn_recompiles,
        # honest context, not gated: total cache growth over the run
        # (workload widths the calibration sweep may have missed)
        "jit_entries_added_total": (None if jit0 is None
                                    else jit1 - jit0),
        "why_slow": why,
        "health": health,
    }

    # -- the pass/fail criteria (also asserted by the CI gate) ----------
    page = alerts["rules"]["page"]
    checks = {
        "alert_fired": page["fired"] >= 1,
        "alert_cleared": page["cleared"] >= 1,
        "scaled_up": autoscale["scale_ups"] >= 1,
        "drained_down": autoscale["scale_downs"] >= 1,
        "events_match": (
            sum(1 for e in autoscale["events"]
                if e["action"] == "scale_up") >= 1
            and sum(1 for e in autoscale["events"]
                    if e["action"] == "drain_done") >= 1),
        "no_wedged": wedged == 0,
        "no_lost": report["requests"]["lost"] == 0,
        "no_leaked_blocks": leaked == 0,
        "no_recompiles": report["recompiles"] in (0, None),
        "why_slow_sums_to_1": (
            why.get("enabled") is True
            and abs(sum(v["frac"] for v in
                        why["ttft_p99_attribution"].values()) - 1.0)
            < 1e-6),
    }
    report["pass"] = checks
    report["passed"] = all(checks.values())

    policy.close()
    scaler.close()
    try:
        fleet.run_until_complete(max_steps=5000)
        fleet.close()
    except RuntimeError:
        pass  # a wedged soak already failed its gates; report anyway
    observe.requests.disable()
    return report


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seconds", type=float, default=60.0,
                    help="soak duration (traffic window; the run adds "
                         "calibration + drain-down time)")
    ap.add_argument("--hours", type=float, default=None,
                    help="long-soak mode: overrides --seconds with "
                         "hours*3600")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--max-replicas", type=int, default=3)
    ap.add_argument("--out", default="SOAK.json", metavar="PATH")
    args = ap.parse_args()
    seconds = args.hours * 3600.0 if args.hours else args.seconds

    from singa_tpu import observe
    from singa_tpu.observe.export import json_sanitize

    observe.monitor.start(watchdog_timeout_s=900.0, crash_handler=True)
    report = run_soak(seconds, seed=args.seed,
                      max_replicas=args.max_replicas)
    report["health"]["watchdog_hangs"] = \
        report["health"]["watchdog"]["hangs"]
    observe.monitor.stop()

    line = json.dumps(json_sanitize(report), default=str,
                      allow_nan=False)
    with open(args.out, "w") as f:
        f.write(line + "\n")
    print(line)
    if not report["passed"]:
        failed = [k for k, ok in report["pass"].items() if not ok]
        raise SystemExit(f"soak FAILED: {failed}")


if __name__ == "__main__":
    main()
